"""Datetime pattern formatting/parsing — date_format, from_unixtime,
unix_timestamp, to_date(fmt).

Reference: datetimeExpressions.scala (GpuFromUnixTime, GpuDateFormatClass,
GpuToUnixTimestamp — cuDF strftime backed, with the plugin gating the
pattern to a supported subset via DateUtils.tagAndGetCudfFormat; unsupported
patterns fall back). Same architecture: the Java SimpleDateFormat subset
below compiles into ONE device byte-layout kernel (digit extraction from
cast.py) or a fixed-offset parse; patterns outside the subset raise at
construction so the planner can fall back per-node. UTC session zone only,
like the reference requires.

Supported tokens: ``yyyy MM dd HH mm ss`` plus literal separators.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import numpy as np

from ..types import DataType, LONG, STRING, DateType, TimestampType
from .base import Ctx, Expression, Literal, Val
from .cast import (
    MICROS_PER_DAY,
    US_PER_SECOND,
    _digits_msd,
    _dev_trim,
    _pack,
    _parse_digits,
)
from .datetime import civil_from_days, days_from_civil

_TOKENS = {"yyyy": 4, "MM": 2, "dd": 2, "HH": 2, "mm": 2, "ss": 2}
# single-letter variants print UNPADDED (SimpleDateFormat count-1 fields);
# parsing consumes a greedy 1..k digit run behind a per-row cursor
_UNPADDED = {"y": 4, "M": 2, "d": 2, "H": 2, "m": 2, "s": 2}


def parse_pattern(fmt: str) -> Tuple[Tuple[str, str], ...]:
    """Pattern → ((kind, text)…); kind is 'tok' (zero-padded), 'unp'
    (unpadded single-letter) or 'lit'. Raises ValueError for tokens outside
    the supported subset (planner check catches it). Unpadded tokens format
    AND parse: the parser runs a per-row cursor with greedy digit runs."""
    out = []
    i = 0
    while i < len(fmt):
        ch = fmt[i]
        if ch.isalpha():
            # SimpleDateFormat groups by letter RUN: 'yy'/'MMM' are distinct
            # fields, not two of ours — consume the whole run and only
            # accept exact widths (silent mis-tokenization would format
            # wrong data instead of falling back)
            j = i
            while j < len(fmt) and fmt[j] == ch:
                j += 1
            run = fmt[i:j]
            if run in _TOKENS:
                out.append(("tok", run))
            elif len(run) == 1 and run in _UNPADDED:
                out.append(("unp", run))
            else:
                raise ValueError(
                    f"datetime pattern token {run!r} at {i} in {fmt!r} is "
                    f"outside the supported subset "
                    f"{sorted(_TOKENS) + sorted(_UNPADDED)}"
                )
            i = j
            continue
        out.append(("lit", ch))
        i += 1
    return tuple(out)


def pattern_supported(fmt: str) -> bool:
    try:
        parse_pattern(fmt)
        return True
    except ValueError:
        return False


def _fields_from_micros(xp, micros):
    micros = micros.astype(xp.int64)
    days = xp.floor_divide(micros, MICROS_PER_DAY)
    tod = micros - days * MICROS_PER_DAY
    y, mo, d = civil_from_days(xp, days.astype(xp.int32))
    secs = tod // US_PER_SECOND
    return {
        "yyyy": y.astype(xp.int64),
        "MM": mo.astype(xp.int64),
        "dd": d.astype(xp.int64),
        "HH": secs // 3600,
        "mm": (secs // 60) % 60,
        "ss": secs % 60,
    }


_UNP_FIELD = {"y": "yyyy", "M": "MM", "d": "dd", "H": "HH", "m": "mm", "s": "ss"}


def _format_device(ctx: Ctx, micros, pattern) -> tuple:
    """One fused byte-layout kernel: fixed-width digit slots per token;
    unpadded tokens drop leading zeros via the keep mask (the last digit
    always stays)."""
    xp = ctx.xp
    fields = _fields_from_micros(xp, micros)
    n = micros.shape[0]
    slots, keeps = [], []
    width = 0
    for kind, text in pattern:
        if kind == "tok":
            k = _TOKENS[text]
            d = _digits_msd(xp, fields[text], k)
            slots.append((d + 48).astype(xp.uint8))
            keeps.append(xp.ones((n, k), dtype=bool))
            width += k
        elif kind == "unp":
            k = _UNPADDED[text]
            val = fields[_UNP_FIELD[text]]
            d = _digits_msd(xp, val, k)
            # keep digit j iff some digit at position <= j is nonzero, or
            # it's the last digit
            nz = (xp.cumsum((d != 0).astype(xp.int32), axis=1) > 0) | (
                xp.arange(k)[None, :] == k - 1
            )
            slots.append((d + 48).astype(xp.uint8))
            keeps.append(nz)
            width += k
        else:
            slots.append(xp.full((n, 1), ord(text), dtype=xp.uint8))
            keeps.append(xp.ones((n, 1), dtype=bool))
            width += 1
    mat = xp.concatenate(slots, axis=1)
    keep = xp.concatenate(keeps, axis=1)
    return _pack(ctx, mat, keep, width)


def _format_cpu(micros: int, pattern) -> str:
    days, tod = divmod(int(micros), MICROS_PER_DAY)
    z = days + 719468
    era = z // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    mo = mp + (3 if mp < 10 else -9)
    y += mo <= 2
    secs = tod // US_PER_SECOND
    vals = {
        "yyyy": y,
        "MM": mo,
        "dd": d,
        "HH": secs // 3600,
        "mm": (secs // 60) % 60,
        "ss": secs % 60,
    }
    out = []
    for kind, text in pattern:
        if kind == "tok":
            out.append(f"{vals[text] % (10 ** _TOKENS[text]):0{_TOKENS[text]}d}")
        elif kind == "unp":
            out.append(str(vals[_UNP_FIELD[text]] % (10 ** _UNPADDED[text])))
        else:
            out.append(text)
    return "".join(out)


@dataclass(frozen=True)
class DateFormatClass(Expression):
    """``date_format(ts, fmt)`` — UTC."""

    child: Expression
    fmt: Expression  # literal

    @property
    def data_type(self) -> DataType:
        return STRING

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    def _micros(self, ctx, v):
        xp = ctx.xp
        data = ctx.broadcast(v.data)
        if isinstance(self.child.data_type, DateType):
            return data.astype(xp.int64) * MICROS_PER_DAY
        return data.astype(xp.int64)

    def eval(self, ctx: Ctx) -> Val:
        v = self.child.eval(ctx)
        pattern = parse_pattern(self.fmt.value)
        micros = self._micros(ctx, v)
        if ctx.is_device:
            data, lens = _format_device(ctx, micros, pattern)
            return Val(data, v.valid, lens)
        out = np.asarray(
            [_format_cpu(m, pattern) for m in micros], dtype=object
        )
        return Val(out, v.valid)

    def __str__(self):
        return f"date_format({self.child}, {self.fmt})"


@dataclass(frozen=True)
class FromUnixTime(Expression):
    """``from_unixtime(seconds, fmt)`` — UTC."""

    child: Expression
    fmt: Expression

    @property
    def data_type(self) -> DataType:
        return STRING

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    def eval(self, ctx: Ctx) -> Val:
        v = self.child.eval(ctx)
        pattern = parse_pattern(self.fmt.value)
        xp = ctx.xp
        micros = ctx.broadcast(v.data).astype(xp.int64) * US_PER_SECOND
        if ctx.is_device:
            data, lens = _format_device(ctx, micros, pattern)
            return Val(data, v.valid, lens)
        out = np.asarray(
            [_format_cpu(m, pattern) for m in micros], dtype=object
        )
        return Val(out, v.valid)


def _parse_device(ctx: Ctx, val: Val, pattern):
    """Parse the pattern → (micros, ok). Fixed-width tokens sit at static
    offsets from the trim start; unpadded single-letter tokens ('M/d/yyyy')
    consume a greedy 1..k digit run behind a per-row cursor (SimpleDateFormat
    numeric-field semantics)."""
    from .cast import _char_at
    from .strings import dev_str

    xp = ctx.xp
    ch, lengths = dev_str(ctx, val)
    start, end, has_any = _dev_trim(ctx, ch, lengths)
    has_unp = any(k == "unp" for k, _ in pattern)
    # tokens absent from the pattern default like Java: month/day 1, rest 0
    fields = {
        t: xp.full(ctx.n, 1 if t in ("MM", "dd") else 0, dtype=xp.int64)
        for t in _TOKENS
    }
    if not has_unp:
        total = sum(_TOKENS[t] if k == "tok" else 1 for k, t in pattern)
        ok = has_any & ((end - start) == total)
        off = 0
        for kind, text in pattern:
            if kind == "tok":
                k = _TOKENS[text]
                v, seg_ok = _parse_digits(
                    ctx, ch, start + off, start + off + k
                )
                fields[text] = v
                ok = ok & seg_ok
                off += k
            else:
                ok = ok & (_char_at(ctx, ch, start + off) == ord(text))
                off += 1
    else:
        cur = start
        ok = has_any
        for kind, text in pattern:
            if kind == "tok":
                k = _TOKENS[text]
                v, seg_ok = _parse_digits(ctx, ch, cur, cur + k)
                fields[text] = v
                ok = ok & seg_ok & (cur + k <= end)
                cur = cur + k
            elif kind == "unp":
                k = _UNPADDED[text]
                run = None
                acc = xp.zeros(ctx.n, dtype=xp.int64)
                width = xp.zeros(ctx.n, dtype=xp.int32)
                for j in range(k):
                    c = _char_at(ctx, ch, cur + j)
                    isd = (c >= 48) & (c <= 57) & ((cur + j) < end)
                    run = isd if run is None else (run & isd)
                    acc = xp.where(
                        run, acc * 10 + (c - 48).astype(xp.int64), acc
                    )
                    width = width + run.astype(xp.int32)
                fields[_UNP_FIELD[text]] = acc
                ok = ok & (width >= 1)
                cur = cur + width
            else:
                ok = (
                    ok
                    & (_char_at(ctx, ch, cur) == ord(text))
                    & (cur < end)
                )
                cur = cur + 1
        ok = ok & (cur == end)
    from .cast import _days_in_month

    y = fields["yyyy"].astype(xp.int32)
    mo = xp.clip(fields["MM"], 1, 12).astype(xp.int32)
    d = xp.clip(fields["dd"], 1, 31).astype(xp.int32)
    ok = (
        ok
        & (fields["MM"] >= 1)
        & (fields["MM"] <= 12)
        & (fields["dd"] >= 1)
        # per-month bound: Feb 29 of a non-leap year must NOT parse
        & (fields["dd"] <= _days_in_month(xp, y, mo))
        & (fields["HH"] < 24)
        & (fields["mm"] < 60)
        & (fields["ss"] < 60)
    )
    days = days_from_civil(xp, y, mo, d).astype(xp.int64)
    micros = days * MICROS_PER_DAY + (
        fields["HH"] * 3600 + fields["mm"] * 60 + fields["ss"]
    ) * US_PER_SECOND
    return micros, ok


def _parse_cpu(s, pattern):
    if s is None:
        return None
    s = s.strip()
    fields = {t: (1 if t in ("MM", "dd") else 0) for t in _TOKENS}
    off = 0
    for kind, text in pattern:
        if kind == "tok":
            k = _TOKENS[text]
            seg = s[off : off + k]
            if len(seg) != k or not (seg.isascii() and seg.isdigit()):
                return None
            fields[text] = int(seg)
            off += k
        elif kind == "unp":
            # greedy 1..k digit run (SimpleDateFormat numeric field)
            k = _UNPADDED[text]
            j = off
            while j < len(s) and j - off < k and s[j].isascii() and s[j].isdigit():
                j += 1
            if j == off:
                return None
            fields[_UNP_FIELD[text]] = int(s[off:j])
            off = j
        else:
            if off >= len(s) or s[off] != text:
                return None
            off += 1
    if off != len(s):
        return None
    if not (
        1 <= fields["MM"] <= 12
        and 1 <= fields["dd"] <= 31
        and fields["HH"] < 24
        and fields["mm"] < 60
        and fields["ss"] < 60
    ):
        return None
    # per-month day bound (Feb 29 of a non-leap year must not parse)
    import calendar

    if fields["dd"] > calendar.monthrange(fields["yyyy"], fields["MM"])[1]:
        return None

    def dfc(y, m, d):
        y -= m <= 2
        era = y // 400
        yoe = y - era * 400
        doy = (153 * (m + (-3 if m > 2 else 9)) + 2) // 5 + d - 1
        doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
        return era * 146097 + doe - 719468

    days = dfc(fields["yyyy"], fields["MM"], fields["dd"])
    return days * MICROS_PER_DAY + (
        fields["HH"] * 3600 + fields["mm"] * 60 + fields["ss"]
    ) * US_PER_SECOND


@dataclass(frozen=True)
class ToUnixTimestamp(Expression):
    """``unix_timestamp(str, fmt)`` → seconds (LONG), null on mismatch."""

    child: Expression
    fmt: Expression

    @property
    def data_type(self) -> DataType:
        return LONG

    def eval(self, ctx: Ctx) -> Val:
        v = self.child.eval(ctx)
        pattern = parse_pattern(self.fmt.value)
        if isinstance(self.child.data_type, (DateType, TimestampType)):
            from .cast import Cast

            tv = Cast(self.child, TimestampType()).eval(ctx)
            xp = ctx.xp
            return Val(
                xp.floor_divide(ctx.broadcast(tv.data).astype(xp.int64), US_PER_SECOND),
                tv.valid,
            )
        if ctx.is_device:
            micros, ok = _parse_device(ctx, v, pattern)
            xp = ctx.xp
            return Val(
                xp.floor_divide(micros, US_PER_SECOND),
                v.full_valid(ctx) & ok,
            )
        from .strings import _cpu_strs

        s = _cpu_strs(ctx, v)
        valid = ctx.broadcast_bool(v.valid)
        out = np.zeros(ctx.n, dtype=np.int64)
        ok = np.zeros(ctx.n, dtype=bool)
        for i in range(ctx.n):
            if not valid[i]:
                continue
            m = _parse_cpu(s[i], pattern)
            if m is not None:
                out[i] = m // US_PER_SECOND
                ok[i] = True
        return Val(out, valid & ok)


@dataclass(frozen=True)
class ParseToDate(Expression):
    """``to_date(str, fmt)`` with an explicit pattern (without one, the
    planner emits a plain Cast to DATE)."""

    child: Expression
    fmt: Expression

    @property
    def data_type(self) -> DataType:
        from ..types import DATE

        return DATE

    def eval(self, ctx: Ctx) -> Val:
        v = self.child.eval(ctx)
        pattern = parse_pattern(self.fmt.value)
        xp = ctx.xp
        if ctx.is_device:
            micros, ok = _parse_device(ctx, v, pattern)
            days = xp.floor_divide(micros, MICROS_PER_DAY).astype(xp.int32)
            return Val(days, v.full_valid(ctx) & ok)
        from .strings import _cpu_strs

        s = _cpu_strs(ctx, v)
        valid = ctx.broadcast_bool(v.valid)
        out = np.zeros(ctx.n, dtype=np.int32)
        ok = np.zeros(ctx.n, dtype=bool)
        for i in range(ctx.n):
            if not valid[i]:
                continue
            m = _parse_cpu(s[i], pattern)
            if m is not None:
                out[i] = m // MICROS_PER_DAY
                ok[i] = True
        return Val(out, valid & ok)
