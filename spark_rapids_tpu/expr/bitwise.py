"""Bitwise expressions — the analogue of bitwise.scala (~200 LoC).

Java shift semantics: the shift amount is masked to the operand width
(``n & 31`` for int, ``n & 63`` for long) — implemented explicitly since
numpy/XLA shifts are undefined/zero for out-of-range amounts.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..types import DataType, IntegralType
from .base import BinaryExpression, Ctx, Expression, UnaryExpression


@dataclass(frozen=True)
class BitwiseAnd(BinaryExpression):
    l: Expression
    r: Expression

    @property
    def data_type(self) -> DataType:
        return self.l.data_type

    def _compute(self, ctx: Ctx, l, r):
        return l & r


@dataclass(frozen=True)
class BitwiseOr(BinaryExpression):
    l: Expression
    r: Expression

    @property
    def data_type(self) -> DataType:
        return self.l.data_type

    def _compute(self, ctx: Ctx, l, r):
        return l | r


@dataclass(frozen=True)
class BitwiseXor(BinaryExpression):
    l: Expression
    r: Expression

    @property
    def data_type(self) -> DataType:
        return self.l.data_type

    def _compute(self, ctx: Ctx, l, r):
        return l ^ r


@dataclass(frozen=True)
class BitwiseNot(UnaryExpression):
    c: Expression

    @property
    def data_type(self) -> DataType:
        return self.c.data_type

    def _compute(self, ctx: Ctx, data):
        return ~data


def _width_mask(dt: DataType) -> int:
    return 63 if dt.np_dtype.itemsize == 8 else 31


class _Shift(BinaryExpression):
    """value SHIFT amount — value keeps its type, amount is int."""

    @property
    def data_type(self) -> DataType:
        return self.l.data_type

    def _compute(self, ctx: Ctx, l, r):
        xp = ctx.xp
        dt = self.l.data_type
        n = (r.astype(xp.int32) & _width_mask(dt)).astype(xp.int32)
        return self._shift(ctx, l, n, dt)


@dataclass(frozen=True)
class ShiftLeft(_Shift):
    l: Expression
    r: Expression

    def _shift(self, ctx, v, n, dt):
        return (v << n).astype(dt.np_dtype)


@dataclass(frozen=True)
class ShiftRight(_Shift):
    """Arithmetic (sign-extending) right shift — Java ``>>``."""

    l: Expression
    r: Expression

    def _shift(self, ctx, v, n, dt):
        return (v >> n).astype(dt.np_dtype)


@dataclass(frozen=True)
class ShiftRightUnsigned(_Shift):
    """Logical right shift — Java ``>>>``."""

    l: Expression
    r: Expression

    def _shift(self, ctx, v, n, dt):
        xp = ctx.xp
        udt = xp.uint64 if dt.np_dtype.itemsize == 8 else xp.uint32
        out = v.astype(udt) >> n.astype(udt)
        return out.astype(dt.np_dtype)
