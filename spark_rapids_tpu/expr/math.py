"""Math expressions — the analogue of mathExpressions.scala (443 LoC).

Spark-isms implemented on both backends:
* ``log``/``log1p`` return NULL for out-of-domain inputs (Spark's Logarithm),
  unlike IEEE -inf/NaN.
* ``floor``/``ceil`` on double return LONG (Java Math.floor + toLong with
  saturation); on integral types they are identity.
* ``round``/``bround`` (HALF_UP / HALF_EVEN) run on device for integral
  inputs (exact integer math); double rounding falls back to CPU where the
  oracle uses java.math.BigDecimal semantics via python decimal — the
  reference (branch-0.5) has no GPU Round either.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..types import (
    DOUBLE,
    LONG,
    DataType,
    DoubleType,
    FloatType,
    IntegralType,
)
from .base import BinaryExpression, Ctx, Expression, UnaryExpression, Val, and_valid


class _DoubleFn(UnaryExpression):
    """Unary double function: input coerced to double, NaN-in → NaN-out."""

    @property
    def data_type(self) -> DataType:
        return DOUBLE

    def _compute(self, ctx: Ctx, data):
        xp = ctx.xp
        return self._fn(xp, data.astype(xp.float64))


def _mk_double_fn(name: str, fn, doc: str = ""):
    cls = dataclass(frozen=True)(
        type(
            name,
            (_DoubleFn,),
            {
                "__doc__": doc or f"Spark ``{name.lower()}``.",
                "__annotations__": {"c": Expression},
                "_fn": staticmethod(fn),
            },
        )
    )
    return cls


Sqrt = _mk_double_fn("Sqrt", lambda xp, x: xp.sqrt(x))
Cbrt = _mk_double_fn("Cbrt", lambda xp, x: xp.cbrt(x))
Exp = _mk_double_fn("Exp", lambda xp, x: xp.exp(x))
Expm1 = _mk_double_fn("Expm1", lambda xp, x: xp.expm1(x))
Sin = _mk_double_fn("Sin", lambda xp, x: xp.sin(x))
Cos = _mk_double_fn("Cos", lambda xp, x: xp.cos(x))
Tan = _mk_double_fn("Tan", lambda xp, x: xp.tan(x))
Asin = _mk_double_fn("Asin", lambda xp, x: xp.arcsin(x))
Acos = _mk_double_fn("Acos", lambda xp, x: xp.arccos(x))
Atan = _mk_double_fn("Atan", lambda xp, x: xp.arctan(x))
Sinh = _mk_double_fn("Sinh", lambda xp, x: xp.sinh(x))
Cosh = _mk_double_fn("Cosh", lambda xp, x: xp.cosh(x))
Tanh = _mk_double_fn("Tanh", lambda xp, x: xp.tanh(x))
ToDegrees = _mk_double_fn("ToDegrees", lambda xp, x: xp.degrees(x))
ToRadians = _mk_double_fn("ToRadians", lambda xp, x: xp.radians(x))
# Inverse hyperbolics use Spark's literal formulas (StrictMath compositions,
# Asinh/Acosh/Atanh in mathExpressions.scala) rather than np.arcsinh etc. —
# same NaN domains AND the same rounding as the Java implementations.
Acosh = _mk_double_fn(
    "Acosh", lambda xp, x: xp.log(x + xp.sqrt(x * x - 1.0)),
    "Spark ``acosh`` — log(x + sqrt(x^2-1)), NaN below 1.",
)
Asinh = _mk_double_fn(
    "Asinh", lambda xp, x: xp.log(x + xp.sqrt(x * x + 1.0)),
    "Spark ``asinh`` — log(x + sqrt(x^2+1)) (Spark's exact formula).",
)
Atanh = _mk_double_fn(
    "Atanh", lambda xp, x: 0.5 * xp.log((1.0 + x) / (1.0 - x)),
    "Spark ``atanh`` — 0.5*log((1+x)/(1-x)), NaN outside (-1, 1).",
)
Cot = _mk_double_fn(
    "Cot", lambda xp, x: 1.0 / xp.tan(x), "Spark ``cot`` — 1/tan(x)."
)
Rint = _mk_double_fn("Rint", lambda xp, x: xp.rint(x))
Signum = _mk_double_fn(
    "Signum", lambda xp, x: xp.sign(x), "Sign as double (NaN → NaN)."
)


class _DomainLog(UnaryExpression):
    """Log-family: NULL outside the domain (Spark Logarithm.nullable)."""

    lower = 0.0  # exclusive domain lower bound

    @property
    def data_type(self) -> DataType:
        return DOUBLE

    def eval(self, ctx: Ctx) -> Val:
        c = self.child.eval(ctx)
        xp = ctx.xp
        x = ctx.broadcast(c.data).astype(xp.float64)
        # Spark's Logarithm nulls only when input <= bound; NaN input is NOT
        # <= bound in Java, so log(NaN) stays NaN (not NULL)
        ok = (x > self.lower) | xp.isnan(x)
        safe = xp.where(ok, x, 1.0)
        data = self._fn(xp, safe)
        return Val(data, and_valid(ctx, c.valid, ok))


@dataclass(frozen=True)
class Log(_DomainLog):
    c: Expression
    _fn = staticmethod(lambda xp, x: xp.log(x))


@dataclass(frozen=True)
class Log10(_DomainLog):
    c: Expression
    _fn = staticmethod(lambda xp, x: xp.log10(x))


@dataclass(frozen=True)
class Log2(_DomainLog):
    c: Expression
    _fn = staticmethod(lambda xp, x: xp.log2(x))


@dataclass(frozen=True)
class Log1p(_DomainLog):
    c: Expression
    lower = -1.0
    _fn = staticmethod(lambda xp, x: xp.log1p(x))


@dataclass(frozen=True)
class Logarithm(BinaryExpression):
    """``log(base, x)`` — NULL when base <= 0 or x <= 0 (Spark Logarithm's
    nullSafeEval; reference rule GpuOverrides.scala:1274)."""

    base: Expression
    x: Expression

    @property
    def data_type(self) -> DataType:
        return DOUBLE

    @property
    def nullable(self) -> bool:
        return True

    def _compute(self, ctx: Ctx, l, r):
        xp = ctx.xp
        b = l.astype(xp.float64)
        x = r.astype(xp.float64)
        # NaN operands are not <= 0 in Java, so they flow through as NaN
        ok = ((b > 0.0) | xp.isnan(b)) & ((x > 0.0) | xp.isnan(x))
        data = xp.log(xp.where(ok, x, 1.0)) / xp.log(xp.where(ok, b, 2.0))
        return data, ok


@dataclass(frozen=True)
class Pow(BinaryExpression):
    l: Expression
    r: Expression

    @property
    def data_type(self) -> DataType:
        return DOUBLE

    def _compute(self, ctx: Ctx, l, r):
        xp = ctx.xp
        return xp.power(l.astype(xp.float64), r.astype(xp.float64))


@dataclass(frozen=True)
class Atan2(BinaryExpression):
    l: Expression
    r: Expression

    @property
    def data_type(self) -> DataType:
        return DOUBLE

    def _compute(self, ctx: Ctx, l, r):
        xp = ctx.xp
        return xp.arctan2(l.astype(xp.float64), r.astype(xp.float64))


@dataclass(frozen=True)
class Hypot(BinaryExpression):
    l: Expression
    r: Expression

    @property
    def data_type(self) -> DataType:
        return DOUBLE

    def _compute(self, ctx: Ctx, l, r):
        xp = ctx.xp
        return xp.hypot(l.astype(xp.float64), r.astype(xp.float64))


_LONG_MIN, _LONG_MAX = -(2**63), 2**63 - 1


class _FloorCeil(UnaryExpression):
    """floor/ceil: identity on integral, double → LONG with Java-toLong
    saturation (NaN → 0)."""

    @property
    def data_type(self) -> DataType:
        if isinstance(self.child.data_type, IntegralType):
            return self.child.data_type
        return LONG

    def _compute(self, ctx: Ctx, data):
        xp = ctx.xp
        if isinstance(self.child.data_type, IntegralType):
            return data
        x = self._rnd(xp, data.astype(xp.float64))
        oob_hi = x >= float(_LONG_MAX)
        oob_lo = x <= float(_LONG_MIN)
        safe = xp.where(xp.isnan(x) | oob_hi | oob_lo, 0.0, x)
        out = safe.astype(xp.int64)
        # Java toLong saturation at the boundaries (float(_LONG_MAX) == 2^63
        # itself overflows an int64 cast, hence the masked fix-up)
        out = xp.where(oob_hi, _LONG_MAX, out)
        out = xp.where(oob_lo, _LONG_MIN, out)
        return out


@dataclass(frozen=True)
class Floor(_FloorCeil):
    c: Expression
    _rnd = staticmethod(lambda xp, x: xp.floor(x))


@dataclass(frozen=True)
class Ceil(_FloorCeil):
    c: Expression
    _rnd = staticmethod(lambda xp, x: xp.ceil(x))


class _RoundBase(Expression):
    """Spark round/bround — scale must be a literal (like the reference's
    foldable requirement for cudf round scales)."""

    half_even = False

    @property
    def data_type(self) -> DataType:
        return self.child.data_type

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    def _scale(self) -> int:
        from .base import Literal

        assert isinstance(self.scale, Literal)
        return int(self.scale.value)

    def eval(self, ctx: Ctx) -> Val:
        c = self.child.eval(ctx)
        d = self._scale()
        dt = self.child.data_type
        xp = ctx.xp
        if isinstance(dt, IntegralType):
            data = ctx.broadcast(c.data)
            if d >= 0:
                return Val(data, c.valid)
            p = 10 ** (-d)
            x = data.astype(xp.int64)
            q = xp.floor_divide(x, p)  # rem = x - q*p is in [0, p)
            rem2 = (x - q * p) * 2
            if self.half_even:
                up = (rem2 > p) | ((rem2 == p) & (xp.mod(q, 2) != 0))
            else:  # HALF_UP: ties go away from zero
                up = (rem2 > p) | ((rem2 == p) & (x >= 0))
            out = q + up.astype(xp.int64)
            return Val((out * p).astype(dt.np_dtype), c.valid)
        if ctx.is_device:
            # incompat-gated device path (reference GpuRound/GpuBRound via
            # cudf round — "may round slightly differently"): arithmetic in
            # f64 binary, not java BigDecimal's shortest-decimal-repr space,
            # so decimal-boundary ties can land one ulp differently.
            x = ctx.broadcast(c.data).astype(xp.float64)
            if d >= 309:
                # 10**d overflows float64; every double is unchanged at
                # this scale (largest exponent span is ±308)
                return Val(x.astype(dt.np_dtype), c.valid)
            if d <= -309:
                # |x|/10**309 < 1 for every finite double: rounds to zero
                out = xp.where(xp.isfinite(x), xp.zeros_like(x), x)
                return Val(out.astype(dt.np_dtype), c.valid)
            if d >= 0:
                p = float(10 ** d)
                if self.half_even:
                    out = xp.round(x * p) / p
                else:
                    out = xp.sign(x) * xp.floor(xp.abs(x) * p + 0.5) / p
                # x * p can overflow to ±inf for finite x (round(1e306, 3)):
                # the scaled space cannot represent the value, where
                # Spark's BigDecimal path returns x unchanged — such a
                # magnitude has no digits at scale d to round
                out = xp.where(xp.isfinite(x * p), out, x)
            else:
                q = float(10 ** (-d))
                if self.half_even:
                    out = xp.round(x / q) * q
                else:
                    out = xp.sign(x) * xp.floor(xp.abs(x) / q + 0.5) * q
            # NaN/±inf pass through sign*floor unscathed except sign(nan)=nan
            out = xp.where(xp.isfinite(x), out, x)
            return Val(out.astype(dt.np_dtype), c.valid)
        # CPU engine keeps exact java BigDecimal semantics
        import decimal as _dec

        data = np.asarray(ctx.broadcast(c.data), dtype=np.float64)
        mode = _dec.ROUND_HALF_EVEN if self.half_even else _dec.ROUND_HALF_UP
        out = np.empty(len(data), dtype=np.float64)
        # java BigDecimal is arbitrary-precision; python's default 28-digit
        # context raises InvalidOperation quantizing huge doubles (1e306 at
        # scale 3 needs ~310 digits) — widen to cover the full f64 range
        with _dec.localcontext() as dctx:
            dctx.prec = 400
            for i, x in enumerate(data.tolist()):
                if x != x or x in (float("inf"), float("-inf")):
                    out[i] = x
                    continue
                out[i] = float(
                    _dec.Decimal(repr(x)).quantize(
                        _dec.Decimal(1).scaleb(-d), rounding=mode
                    )
                )
        return Val(out.astype(dt.np_dtype), c.valid)


@dataclass(frozen=True)
class Round(_RoundBase):
    child: Expression
    scale: Expression
    half_even = False


@dataclass(frozen=True)
class BRound(_RoundBase):
    child: Expression
    scale: Expression
    half_even = True
