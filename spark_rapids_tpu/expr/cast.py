"""Cast — the analogue of GpuCast.scala (1319 LoC in the reference), the
single most semantics-dense expression.

Implemented pairs (both backends, Spark non-ANSI semantics):

* numeric → numeric: Java conversion semantics — int narrowing wraps
  (two's complement), floating → integral saturates at min/max with NaN → 0
  (Scala ``Double.toInt``), integral → floating rounds to nearest.
* numeric/boolean ↔ boolean: ``x != 0``; bool → numeric 0/1.
* date/timestamp widening (date → timestamp, timestamp → date floor).
* decimal ↔ integral/decimal rescale with overflow → NULL (Spark wraps in
  nullOnOverflow for non-ANSI).
* string ↔ numeric: gated behind configs like the reference
  (``spark.rapids.sql.castStringToFloat.enabled`` etc.); string→int of
  well-formed input implemented on device via the padded byte matrix.

Unsupported pairs raise at planning time so the planner can fall back per-node
(the TypeChecks gating path).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..types import (
    BooleanType,
    ByteType,
    DataType,
    DateType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegerType,
    IntegralType,
    LongType,
    NullType,
    ShortType,
    StringType,
    TimestampType,
)
from .base import Ctx, Expression, UnaryExpression, Val

_INT_BOUNDS = {
    np.dtype(np.int8): (-(2**7), 2**7 - 1),
    np.dtype(np.int16): (-(2**15), 2**15 - 1),
    np.dtype(np.int32): (-(2**31), 2**31 - 1),
    np.dtype(np.int64): (-(2**63), 2**63 - 1),
}

MICROS_PER_DAY = 86400 * 1000000


def _float_to_int(xp, data, to_np_dtype):
    """Java (int)/(long) conversion: NaN→0, saturate at bounds, truncate."""
    lo, hi = _INT_BOUNDS[to_np_dtype]
    x = xp.trunc(xp.where(xp.isnan(data), 0.0, data))
    hi_f = float(hi)  # rounds UP to 2^63 for int64 (inexact) — handled below
    above = (x >= hi_f) if int(hi_f) != hi else (x > hi_f)
    below = x < float(lo)  # lo is a power of two, exactly representable
    inner = ~above & ~below
    casted = xp.where(inner, x, 0.0).astype(to_np_dtype)
    return xp.where(above, hi, xp.where(below, lo, casted)).astype(to_np_dtype)


@dataclass(frozen=True)
class Cast(UnaryExpression):
    c: Expression
    to: DataType

    @property
    def data_type(self) -> DataType:
        return self.to

    @property
    def nullable(self) -> bool:
        # casts that can produce null from non-null (overflow/parse) handled
        # by returning extra validity in eval
        return True

    def eval(self, ctx: Ctx) -> Val:
        v = self.c.eval(ctx)
        frm, to = self.c.data_type, self.to
        xp = ctx.xp
        if frm == to:
            return v
        if isinstance(frm, NullType):
            return Val(xp.zeros((), dtype=to.np_dtype), xp.asarray(False))
        if isinstance(to, StringType):
            return self._to_string(ctx, v, frm)
        if isinstance(frm, StringType):
            return self._from_string(ctx, v, to)
        data, extra_valid = self._numeric_cast(ctx, v.data, frm, to)
        valid = v.valid
        if extra_valid is not None:
            valid = ctx.broadcast_bool(valid) & extra_valid
        return Val(data, valid)

    # ── numeric/temporal matrix ────────────────────────────────────────────
    def _numeric_cast(self, ctx: Ctx, data, frm: DataType, to: DataType):
        xp = ctx.xp
        if isinstance(to, BooleanType):
            return data != 0, None
        if isinstance(frm, BooleanType):
            return data.astype(to.np_dtype), None
        if isinstance(frm, DateType) and isinstance(to, TimestampType):
            return data.astype(np.int64) * MICROS_PER_DAY, None
        if isinstance(frm, TimestampType) and isinstance(to, DateType):
            # floor-div towards -inf (Spark: DateTimeUtils.microsToDays)
            return (data // MICROS_PER_DAY).astype(np.int32), None
        if isinstance(frm, DecimalType) or isinstance(to, DecimalType):
            return self._decimal_cast(ctx, data, frm, to)
        if isinstance(to, (FloatType, DoubleType)):
            return data.astype(to.np_dtype), None
        # target integral
        if isinstance(frm, (FloatType, DoubleType)):
            return _float_to_int(xp, data, to.np_dtype), None
        return data.astype(to.np_dtype), None  # integral narrowing wraps (Java)

    def _decimal_cast(self, ctx: Ctx, data, frm: DataType, to: DataType):
        xp = ctx.xp
        if isinstance(frm, DecimalType) and isinstance(to, DecimalType):
            ds = to.scale - frm.scale
            if ds >= 0:
                scaled = data * (10**ds)
                lo, hi = -(10**to.precision) + 1, 10**to.precision - 1
                ok = (data <= hi // (10**ds)) & (data >= lo // (10**ds))
                return scaled, ok
            # round half-up on scale reduction
            f = 10 ** (-ds)
            q = data // f
            rem = data - q * f
            adj = xp.where(2 * xp.abs(rem) >= f, xp.sign(data), 0)
            out = q + adj
            lo, hi = -(10**to.precision) + 1, 10**to.precision - 1
            return out, (out >= lo) & (out <= hi)
        if isinstance(frm, DecimalType):
            # decimal → integral/float: value = unscaled / 10^scale
            if isinstance(to, (FloatType, DoubleType)):
                return (data.astype(np.float64) / (10**frm.scale)).astype(
                    to.np_dtype
                ), None
            q = data // (10**frm.scale) if frm.scale else data
            # Spark truncates toward zero for decimal→int
            if frm.scale:
                t = data / (10**frm.scale)
                q = xp.trunc(t).astype(np.int64)
            lo, hi = _INT_BOUNDS[to.np_dtype]
            ok = (q >= lo) & (q <= hi)
            return q.astype(to.np_dtype), ok
        if isinstance(to, DecimalType):
            if isinstance(frm, (FloatType, DoubleType)):
                scaled = data * (10.0**to.scale)
                # round half-up
                unscaled = xp.where(
                    xp.isnan(scaled), 0, xp.floor(xp.abs(scaled) + 0.5) * xp.sign(scaled)
                )
                lo, hi = -(10**to.precision) + 1, 10**to.precision - 1
                ok = (~xp.isnan(data)) & (unscaled >= lo) & (unscaled <= hi)
                return unscaled.astype(np.int64), ok
            unscaled = data.astype(np.int64) * (10**to.scale)
            lo, hi = -(10**to.precision) + 1, 10**to.precision - 1
            ok = (data.astype(np.int64) <= hi // (10**to.scale)) & (
                data.astype(np.int64) >= lo // (10**to.scale)
            )
            return unscaled, ok
        raise TypeError(f"unsupported cast {frm} -> {to}")

    # ── string paths ───────────────────────────────────────────────────────
    def _to_string(self, ctx: Ctx, v: Val, frm: DataType) -> Val:
        if ctx.is_device:
            raise NotImplementedError("cast to string runs on CPU in this version")
        import numpy as np

        data = ctx.broadcast(v.data)
        if isinstance(frm, BooleanType):
            out = np.asarray([("true" if bool(x) else "false") for x in data], dtype=object)
        elif isinstance(frm, IntegralType) and not isinstance(
            frm, (DateType, TimestampType)
        ):
            out = np.asarray([str(int(x)) for x in data], dtype=object)
        else:
            raise NotImplementedError(f"cast {frm} -> string (gated)")
        return Val(out, v.valid)

    def _from_string(self, ctx: Ctx, v: Val, to: DataType) -> Val:
        if ctx.is_device:
            return self._from_string_device(ctx, v, to)
        import numpy as np

        n = ctx.n
        data = np.broadcast_to(np.asarray(v.data, dtype=object), (n,))
        valid = ctx.broadcast_bool(v.valid)
        if isinstance(to, IntegralType) and not isinstance(to, (DateType, TimestampType)):
            out = np.zeros(n, dtype=to.np_dtype)
            ok = np.zeros(n, dtype=bool)
            lo, hi = _INT_BOUNDS[to.np_dtype]
            for i in range(n):
                if not valid[i]:
                    continue
                s = data[i].strip() if data[i] is not None else None
                try:
                    val = int(s)
                    if lo <= val <= hi:
                        out[i] = val
                        ok[i] = True
                except (TypeError, ValueError):
                    pass
            return Val(out, valid & ok)
        if isinstance(to, (FloatType, DoubleType)):
            out = np.zeros(n, dtype=to.np_dtype)
            ok = np.zeros(n, dtype=bool)
            for i in range(n):
                if not valid[i]:
                    continue
                s = data[i].strip() if data[i] is not None else None
                try:
                    out[i] = to.np_dtype.type(s)
                    ok[i] = True
                except (TypeError, ValueError):
                    pass
            return Val(out, valid & ok)
        raise NotImplementedError(f"cast string -> {to}")

    def _from_string_device(self, ctx: Ctx, v: Val, to: DataType) -> Val:
        """Device string→integral parse over the padded byte matrix.

        Spark semantics: trim whitespace (<= 0x20) like UTF8String.trimAll,
        optional +/- sign, digits only, NULL on malformed input or overflow.
        """
        xp = ctx.xp
        if not (
            isinstance(to, IntegralType) and not isinstance(to, (DateType, TimestampType))
        ):
            raise NotImplementedError(f"device cast string -> {to}")
        data = v.data if v.data.ndim == 2 else v.data[None, :]
        n, w = data.shape
        lengths = xp.broadcast_to(xp.asarray(v.lengths), (n,))
        idx = xp.arange(w, dtype=xp.int32)[None, :]
        in_len = idx < lengths[:, None]
        ch = data
        nonspace = (ch > 0x20) & in_len
        has_any = nonspace.any(axis=1)
        start = xp.argmax(nonspace, axis=1).astype(xp.int32)
        last = (w - 1) - xp.argmax(nonspace[:, ::-1], axis=1).astype(xp.int32)
        effective = (idx >= start[:, None]) & (idx <= last[:, None]) & in_len
        is_digit = (ch >= ord("0")) & (ch <= ord("9"))
        is_sign = ((ch == ord("-")) | (ch == ord("+"))) & (idx == start[:, None])
        ok_chars = xp.where(effective, is_digit | is_sign, True).all(axis=1)
        has_digit = (is_digit & effective).any(axis=1)
        # Horner left-to-right with int64 overflow detection
        hi64 = xp.asarray(2**63 - 1, dtype=xp.int64)
        acc = xp.zeros(n, dtype=xp.int64)
        overflow = xp.zeros(n, dtype=bool)
        for j in range(w):
            d = (ch[:, j] - ord("0")).astype(xp.int64)
            use = effective[:, j] & is_digit[:, j]
            would_overflow = acc > (hi64 - d) // 10
            overflow = overflow | (use & would_overflow)
            acc = xp.where(use, acc * 10 + d, acc)
        neg = ((ch == ord("-")) & (idx == start[:, None])).any(axis=1)
        out = xp.where(neg, -acc, acc)
        ok = ok_chars & has_digit & has_any & ~overflow
        lo, hi = _INT_BOUNDS[to.np_dtype]
        ok = ok & (out >= lo) & (out <= hi)
        return Val(out.astype(to.np_dtype), ctx.broadcast_bool(v.valid) & ok)

    def __str__(self):
        return f"cast({self.c} as {self.to})"


def can_cast_on_device(frm: DataType, to: DataType, conf) -> bool:
    """TypeChecks-style gate used by the planner."""
    from .. import config as cfg

    if isinstance(frm, StringType) and isinstance(to, (FloatType, DoubleType)):
        return conf.is_enabled(cfg.CAST_STRING_TO_FLOAT)
    if isinstance(frm, (FloatType, DoubleType)) and isinstance(to, StringType):
        return conf.is_enabled(cfg.CAST_FLOAT_TO_STRING)
    if isinstance(to, StringType) or isinstance(frm, StringType):
        # device handles string→integral; other string paths fall back
        return isinstance(to, IntegralType) and not isinstance(
            to, (DateType, TimestampType)
        )
    return True
