"""Cast — the analogue of GpuCast.scala (1319 LoC in the reference), the
single most semantics-dense expression.

Implemented pairs (both backends):

* numeric → numeric: Java conversion semantics — int narrowing wraps
  (two's complement), floating → integral saturates at min/max with NaN → 0
  (Scala ``Double.toInt``), integral → floating rounds to nearest.
* numeric/boolean ↔ boolean: ``x != 0``; bool → numeric 0/1.
* date/timestamp widening (date → timestamp, timestamp → date floor);
  timestamp ↔ integral/fractional in seconds (Spark's micros/1e6 convention).
* decimal ↔ integral/fractional/decimal rescale with overflow → NULL
  (Spark wraps in nullOnOverflow for non-ANSI).
* X → string for bool/integral/float/double/date/timestamp/decimal — device
  kernels over the padded byte matrix; float → string follows Java
  ``Double.toString`` (jformat.py) and its device kernel is gated behind
  ``spark.rapids.sql.castFloatToString.enabled`` exactly like the reference
  (GpuCast.scala castFloatingTypeToString), because shortest-round-trip digit
  selection on device can differ in the last digit for extreme exponents.
* string → bool/integral/float/double/date/timestamp/decimal — Spark's
  UTF8String parsing semantics (trimAll of control/space chars, sign, the
  DateTimeUtils segment grammar for dates/timestamps). string→float and
  string→timestamp are config-gated like the reference
  (``castStringToFloat.enabled`` / ``castStringToTimestamp.enabled``).

ANSI mode (``spark.sql.ansi.enabled``): the same pairs raise ``AnsiError`` on
overflow / malformed input instead of producing NULL, and integral narrowing
range-checks instead of wrapping (reference: ansiEnabled branches of
GpuCast.scala, AnsiCastOpSuite). On the CPU backend the error is raised
immediately; on device the error sites are accumulated on the ``Ctx`` and the
project/filter kernels return per-site flags that the exec checks after the
kernel runs (one host sync per batch, only when ANSI casts are present).

Unsupported pairs raise at planning time so the planner can fall back per-node
(the TypeChecks gating path).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..types import (
    BooleanType,
    ByteType,
    DataType,
    DateType,
    DecimalType,
    DoubleType,
    FloatType,
    FractionalType,
    IntegerType,
    IntegralType,
    LongType,
    NullType,
    ShortType,
    StringType,
    TimestampType,
)
from .base import AnsiError, Ctx, Expression, UnaryExpression, Val

_INT_BOUNDS = {
    np.dtype(np.int8): (-(2**7), 2**7 - 1),
    np.dtype(np.int16): (-(2**15), 2**15 - 1),
    np.dtype(np.int32): (-(2**31), 2**31 - 1),
    np.dtype(np.int64): (-(2**63), 2**63 - 1),
}

_INT_DIGITS = {
    np.dtype(np.int8): 3,
    np.dtype(np.int16): 5,
    np.dtype(np.int32): 10,
    np.dtype(np.int64): 19,
}

MICROS_PER_DAY = 86400 * 1000000
US_PER_SECOND = 1_000_000

I64_MIN = -(2**63)
LONG_MIN_STR = b"-9223372036854775808"


def _float_to_int(xp, data, to_np_dtype):
    """Java (int)/(long) conversion: NaN→0, saturate at bounds, truncate."""
    lo, hi = _INT_BOUNDS[to_np_dtype]
    x = xp.trunc(xp.where(xp.isnan(data), 0.0, data))
    hi_f = float(hi)  # rounds UP to 2^63 for int64 (inexact) — handled below
    above = (x >= hi_f) if int(hi_f) != hi else (x > hi_f)
    below = x < float(lo)  # lo is a power of two, exactly representable
    inner = ~above & ~below
    casted = xp.where(inner, x, 0.0).astype(to_np_dtype)
    return xp.where(above, hi, xp.where(below, lo, casted)).astype(to_np_dtype)


def _float_int_ok(xp, data, to_np_dtype):
    """ANSI range check for float → integral: in-bounds and not NaN."""
    lo, hi = _INT_BOUNDS[to_np_dtype]
    x = xp.trunc(data)
    hi_f = float(hi)
    above = (x >= hi_f) if int(hi_f) != hi else (x > hi_f)
    return ~xp.isnan(data) & ~above & (x >= float(lo))


# ── device byte-matrix helpers (shared with strings.py idioms) ─────────────

# Double-double decimal powers: 10^s = (hi + lo) · 2^E with hi ∈ [1, 2),
# host-built exactly with Fractions. float(Fraction) is correctly rounded, so
# hi+lo carries ~106 bits of 10^s — enough for correctly-rounded decimal ↔
# binary conversion without big integers on device (Ryu/strtod-style).
def _build_dd_pow10():
    from fractions import Fraction

    lo_s, hi_s = -350, 350
    his, los, es = [], [], []
    for s in range(lo_s, hi_s + 1):
        v = Fraction(10) ** s
        e = v.numerator.bit_length() - v.denominator.bit_length() - 1
        if Fraction(2) ** (e + 1) <= v:
            e += 1
        md = v / Fraction(2) ** e
        hi = float(md)
        lo = float(md - Fraction(hi))
        his.append(hi)
        los.append(lo)
        es.append(e)
    return (
        np.asarray(his, dtype=np.float64),
        np.asarray(los, dtype=np.float64),
        np.asarray(es, dtype=np.int64),
        lo_s,
    )


_DD_HI, _DD_LO, _DD_E, _DD_MIN_S = _build_dd_pow10()
with np.errstate(over="ignore"):
    _POW2 = np.power(2.0, np.arange(-1100, 1101, dtype=np.float64))


def _pow2f(xp, k):
    """Exact 2.0**k via table (k clipped to ±1100; beyond is 0/inf)."""
    return xp.take(xp.asarray(_POW2), xp.clip(k + 1100, 0, 2200).astype(xp.int32))


def _two_prod(xp, a, b):
    """Dekker two-product: a*b = p + err exactly (|a|,|b| ≲ 1e150)."""
    split = 134217729.0  # 2^27 + 1
    p = a * b
    ca = split * a
    ah = ca - (ca - a)
    al = a - ah
    cb = split * b
    bh = cb - (cb - b)
    bl = b - bh
    err = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, err


def _int_div_pow10(xp, v, k: int):
    """v / 10^k (v: int64) correctly rounded to float64.

    Plain ``v.astype(f64) / 10**k`` is NOT bit-stable under jit: XLA
    strength-reduces division by a constant into a reciprocal multiply
    (~30% of values one ulp off vs IEEE division). Both backends route
    through the double-double decimal path instead."""
    neg = v < 0
    mag = xp.abs(v)
    out = _dec_to_float(xp, mag, xp.full(v.shape, -k, dtype=xp.int32))
    return xp.where(neg, -out, out)


def _two_sum(xp, a, b):
    """Knuth two-sum: a + b = s + err exactly."""
    s = a + b
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return s, err


def _dec_to_float(xp, d, r):
    """Correctly-rounded float64 of the decimal d · 10^r (d: int64 in
    [0, ~1e18]).

    d can exceed 2^53, so it is split into exact high/low parts before the
    double-double product with the 10^r tables; the only rounding is the
    final one (subnormal results double-round — the same corner every
    table-driven strtod shares)."""
    idx = xp.clip(r - _DD_MIN_S, 0, 700).astype(xp.int32)
    mh = xp.take(xp.asarray(_DD_HI), idx)
    ml = xp.take(xp.asarray(_DD_LO), idx)
    E = xp.take(xp.asarray(_DD_E), idx)
    d = d.astype(xp.int64)
    d_a = ((d >> 30) << 30).astype(xp.float64)  # ≤ 2^60, 30 trailing zeros
    d_b = (d & ((1 << 30) - 1)).astype(xp.float64)
    p1, e1 = _two_prod(xp, d_a, mh)
    p2, e2 = _two_prod(xp, d_b, mh)
    s, e3 = _two_sum(xp, p1, p2)
    tail = ((e1 + e2) + e3) + (d_a + d_b) * ml
    v = s + tail
    out = v * _pow2f(xp, E)
    # table range exceeded → saturate the way the true value would
    # (a zero mantissa stays zero regardless of the exponent)
    out = xp.where((r > 350) & (d != 0), xp.asarray(xp.inf), out)
    out = xp.where(r < -350, 0.0, out)
    out = xp.where(d == 0, 0.0, out)
    return out


def _signbit(xp, x):
    """Bitcast-free signbit for float64 (TPU X64 emulation cannot bitcast
    64-bit types): catches -0.0 via the sign of 1/x."""
    one_over = xp.where(x == 0, 1.0 / xp.where(x == 0, x, 1.0), 0.0)
    return (x < 0) | ((x == 0) & (one_over < 0))


def _digits_msd(xp, mag, k):
    """Non-negative int64 magnitudes → uint8 digit matrix [n, k], MSD first."""
    cols = []
    m = mag
    for _ in range(k):
        cols.append((m % 10).astype(xp.uint8))
        m = m // 10
    return xp.stack(cols[::-1], axis=1)


def _first_sig(xp, digits):
    """Index of the first significant digit per row (k-1 when all zero)."""
    nz = digits != 0
    k = digits.shape[1]
    has = nz.any(axis=1)
    return xp.where(has, xp.argmax(nz, axis=1), k - 1).astype(xp.int32)


def _pack(ctx: Ctx, slots, keep, min_width: int):
    from .strings import compact_bytes
    from ..columnar.device import bucket_width

    return compact_bytes(ctx, slots, keep, bucket_width(min_width))


def _dev_trim(ctx: Ctx, data, lengths):
    """UTF8String.trimAll bounds: indices [start, end) of the non-space
    (> 0x20) region; end == start for all-space strings."""
    xp = ctx.xp
    w = data.shape[1]
    idx = xp.arange(w, dtype=xp.int32)[None, :]
    in_len = idx < lengths[:, None]
    nonspace = (data > 0x20) & in_len
    any_ = nonspace.any(axis=1)
    start = xp.argmax(nonspace, axis=1).astype(xp.int32)
    last = (w - 1) - xp.argmax(nonspace[:, ::-1], axis=1).astype(xp.int32)
    end = xp.where(any_, last + 1, start)
    return start, end, any_


def _parse_digits(ctx: Ctx, ch, a, b, max_digits=None):
    """Parse the digit run in [a, b) per row → (int64 value, ok)."""
    xp = ctx.xp
    n, w = ch.shape
    idx = xp.arange(w, dtype=xp.int32)[None, :]
    use = (idx >= a[:, None]) & (idx < b[:, None])
    is_digit = (ch >= 48) & (ch <= 57)
    ok = xp.where(use, is_digit, True).all(axis=1) & (b > a)
    if max_digits is not None:
        ok = ok & ((b - a) <= max_digits)
    val = xp.zeros(n, dtype=xp.int64)
    for j in range(w):
        u = use[:, j] & is_digit[:, j]
        d = (ch[:, j] - 48).astype(xp.int64)
        val = xp.where(u, val * 10 + d, val)
    return val, ok


def _find_char(ctx: Ctx, ch, c, a, b):
    """First index of byte ``c`` in [a, b) per row, else ``b``; plus found."""
    xp = ctx.xp
    w = ch.shape[1]
    idx = xp.arange(w, dtype=xp.int32)[None, :]
    hit = (ch == c) & (idx >= a[:, None]) & (idx < b[:, None])
    any_ = hit.any(axis=1)
    first = xp.argmax(hit, axis=1).astype(xp.int32)
    return xp.where(any_, first, b), any_


def _char_at(ctx: Ctx, ch, i):
    """Byte at per-row index i (0 when out of the matrix)."""
    xp = ctx.xp
    w = ch.shape[1]
    i = xp.clip(i, 0, w - 1)
    return xp.take_along_axis(ch, i[:, None].astype(xp.int32), axis=1)[:, 0]


def _days_in_month(xp, y, m):
    from .datetime import days_from_civil

    ny = y + (m == 12)
    nm = xp.where(m == 12, 1, m + 1)
    return days_from_civil(xp, ny, nm, xp.ones_like(m)) - days_from_civil(
        xp, y, m, xp.ones_like(m)
    )


@dataclass(frozen=True)
class Cast(UnaryExpression):
    c: Expression
    to: DataType
    ansi: bool = False

    @property
    def data_type(self) -> DataType:
        return self.to

    @property
    def nullable(self) -> bool:
        # casts that can produce null from non-null (overflow/parse) handled
        # by returning extra validity in eval
        return True

    def _err(self, ctx: Ctx, child_valid, ok, what: str):
        """ANSI: register/raise an error for rows valid-in but failed."""
        bad = ctx.broadcast_bool(child_valid) & ~ok
        ctx.register_error(
            f"[ANSI] cast({self.c.data_type.simple_string} as "
            f"{self.to.simple_string}) {what}",
            bad,
        )

    def eval(self, ctx: Ctx) -> Val:
        v = self.c.eval(ctx)
        frm, to = self.c.data_type, self.to
        xp = ctx.xp
        if frm == to:
            return v
        if isinstance(frm, NullType):
            if isinstance(to, StringType):
                from .base import Literal

                return Literal(None, to).eval(ctx)
            return Val(xp.zeros((), dtype=to.np_dtype), xp.asarray(False))
        if isinstance(to, StringType):
            return self._to_string(ctx, v, frm)
        if isinstance(frm, StringType):
            return self._from_string(ctx, v, to)
        data, extra_valid = self._numeric_cast(ctx, v.data, frm, to)
        valid = v.valid
        if extra_valid is not None:
            if self.ansi:
                self._err(ctx, valid, extra_valid, "overflow")
            valid = ctx.broadcast_bool(valid) & extra_valid
        return Val(data, valid)

    # ── numeric/temporal matrix ────────────────────────────────────────────
    def _numeric_cast(self, ctx: Ctx, data, frm: DataType, to: DataType):
        xp = ctx.xp
        if isinstance(to, BooleanType):
            return data != 0, None
        if isinstance(frm, BooleanType):
            if isinstance(to, TimestampType):
                return data.astype(np.int64) * US_PER_SECOND, None
            if isinstance(to, DecimalType):
                # true → 1 scaled to the target (unscaled = 10^scale), not
                # the raw 0/1 bit as unscaled
                unscaled = data.astype(np.int64) * (10**to.scale)
                if to.scale >= to.precision:
                    # decimal(p,s) with s >= p cannot represent 1
                    return unscaled, data == 0
                return unscaled, None
            return data.astype(to.np_dtype), None
        if isinstance(frm, DateType) and isinstance(to, TimestampType):
            return data.astype(np.int64) * MICROS_PER_DAY, None
        if isinstance(frm, TimestampType) and isinstance(to, DateType):
            # floor-div towards -inf (Spark: DateTimeUtils.microsToDays)
            return (data // MICROS_PER_DAY).astype(np.int32), None
        if isinstance(frm, TimestampType):
            # timestamp → numeric: seconds (Spark: micros / 1e6, floor for
            # integral targets, exact fraction for fractional ones)
            if isinstance(to, (FloatType, DoubleType)):
                return _int_div_pow10(xp, data, 6).astype(to.np_dtype), None
            if isinstance(to, DecimalType):
                # seconds (incl. fraction) at to.scale, HALF_UP
                sh = to.scale - 6
                micros = data.astype(np.int64)
                if sh >= 0:
                    unscaled = micros * (10**sh)
                    lim = (2**63 - 1) // (10**sh)
                    ok = (xp.abs(micros) <= lim) if sh else xp.ones(
                        micros.shape, dtype=bool
                    )
                else:
                    # HALF_UP = away from zero: floor-div remainders are
                    # always ≥ 0, so ties round up only for non-negatives
                    f = 10 ** (-sh)
                    q = micros // f
                    r = micros - q * f
                    up = (2 * r > f) | ((2 * r == f) & (micros >= 0))
                    unscaled = q + up.astype(xp.int64)
                    ok = None
                lim2 = 10**to.precision - 1
                inb = (unscaled >= -lim2) & (unscaled <= lim2)
                return unscaled, inb if ok is None else (ok & inb)
            secs = xp.floor_divide(data, US_PER_SECOND)
            out = secs.astype(to.np_dtype)
            if self.ansi and to.np_dtype != np.dtype(np.int64):
                lo, hi = _INT_BOUNDS[to.np_dtype]
                return out, (secs >= lo) & (secs <= hi)
            return out, None
        if isinstance(to, TimestampType):
            # numeric → timestamp: value is seconds
            if isinstance(frm, (FloatType, DoubleType)):
                micros = data.astype(np.float64) * US_PER_SECOND
                out = _float_to_int(xp, micros, np.dtype(np.int64))
                ok = ~xp.isnan(data) & ~xp.isinf(data)
                return out, ok
            if isinstance(frm, DecimalType):
                secs = _int_div_pow10(xp, data, frm.scale)
                return _float_to_int(
                    xp, secs * US_PER_SECOND, np.dtype(np.int64)
                ), None
            return data.astype(np.int64) * US_PER_SECOND, None
        if isinstance(frm, DecimalType) or isinstance(to, DecimalType):
            return self._decimal_cast(ctx, data, frm, to)
        if isinstance(to, (FloatType, DoubleType)):
            return data.astype(to.np_dtype), None
        # target integral
        if isinstance(frm, (FloatType, DoubleType)):
            out = _float_to_int(xp, data, to.np_dtype)
            if self.ansi:
                return out, _float_int_ok(xp, data, to.np_dtype)
            return out, None
        # integral narrowing: wraps (Java) non-ANSI, range-checks ANSI
        out = data.astype(to.np_dtype)
        if self.ansi and to.np_dtype.itemsize < data.dtype.itemsize:
            lo, hi = _INT_BOUNDS[to.np_dtype]
            src = data.astype(np.int64)
            return out, (src >= lo) & (src <= hi)
        return out, None

    def _decimal_cast(self, ctx: Ctx, data, frm: DataType, to: DataType):
        xp = ctx.xp
        if isinstance(frm, DecimalType) and isinstance(to, DecimalType):
            ds = to.scale - frm.scale
            if ds >= 0:
                scaled = data * (10**ds)
                lo, hi = -(10**to.precision) + 1, 10**to.precision - 1
                ok = (data <= hi // (10**ds)) & (data >= lo // (10**ds))
                return scaled, ok
            # round half-up on scale reduction
            f = 10 ** (-ds)
            q = data // f
            rem = data - q * f
            adj = xp.where(2 * xp.abs(rem) >= f, xp.sign(data), 0)
            out = q + adj
            lo, hi = -(10**to.precision) + 1, 10**to.precision - 1
            return out, (out >= lo) & (out <= hi)
        if isinstance(frm, DecimalType):
            # decimal → integral/float: value = unscaled / 10^scale
            if isinstance(to, (FloatType, DoubleType)):
                return _int_div_pow10(xp, data, frm.scale).astype(
                    to.np_dtype
                ), None
            # Spark truncates toward zero for decimal→int (integer-exact:
            # the float quotient can flip trunc at integer boundaries)
            q = data
            if frm.scale:
                p = 10**frm.scale
                q0 = data // p
                r = data - q0 * p
                q = q0 + ((q0 < 0) & (r != 0)).astype(np.int64)
            lo, hi = _INT_BOUNDS[to.np_dtype]
            ok = (q >= lo) & (q <= hi)
            return q.astype(to.np_dtype), ok
        if isinstance(to, DecimalType):
            if isinstance(frm, (FloatType, DoubleType)):
                scaled = data * (10.0**to.scale)
                # round half-up
                unscaled = xp.where(
                    xp.isnan(scaled), 0, xp.floor(xp.abs(scaled) + 0.5) * xp.sign(scaled)
                )
                lo, hi = -(10**to.precision) + 1, 10**to.precision - 1
                ok = (~xp.isnan(data)) & (unscaled >= lo) & (unscaled <= hi)
                return unscaled.astype(np.int64), ok
            unscaled = data.astype(np.int64) * (10**to.scale)
            lo, hi = -(10**to.precision) + 1, 10**to.precision - 1
            ok = (data.astype(np.int64) <= hi // (10**to.scale)) & (
                data.astype(np.int64) >= lo // (10**to.scale)
            )
            return unscaled, ok
        raise TypeError(f"unsupported cast {frm} -> {to}")

    # ── X → string ─────────────────────────────────────────────────────────
    def _to_string(self, ctx: Ctx, v: Val, frm: DataType) -> Val:
        if ctx.is_device:
            return self._to_string_device(ctx, v, frm)
        data = ctx.broadcast(v.data)
        valid = ctx.broadcast_bool(v.valid)
        if isinstance(frm, BooleanType):
            out = np.asarray(
                ["true" if bool(x) else "false" for x in data], dtype=object
            )
        elif isinstance(frm, DateType):
            out = np.asarray([_cpu_date_str(int(x)) for x in data], dtype=object)
        elif isinstance(frm, TimestampType):
            out = np.asarray([_cpu_ts_str(int(x)) for x in data], dtype=object)
        elif isinstance(frm, DecimalType):
            out = np.asarray(
                [_cpu_decimal_str(int(x), frm.scale) for x in data], dtype=object
            )
        elif isinstance(frm, (FloatType, DoubleType)):
            from .jformat import java_float_str

            is32 = isinstance(frm, FloatType)
            out = np.asarray(
                [java_float_str(x, is32) for x in data], dtype=object
            )
        elif isinstance(frm, IntegralType):
            out = np.asarray([str(int(x)) for x in data], dtype=object)
        else:
            raise NotImplementedError(f"cast {frm} -> string")
        out[~valid] = None
        return Val(out, valid)

    def _to_string_device(self, ctx: Ctx, v: Val, frm: DataType) -> Val:
        xp = ctx.xp
        data = ctx.broadcast(v.data)
        if isinstance(frm, BooleanType):
            b = data.astype(bool)
            t = xp.asarray(np.frombuffer(b"true\x00", dtype=np.uint8))
            f = xp.asarray(np.frombuffer(b"false", dtype=np.uint8))
            slots = xp.where(b[:, None], t[None, :], f[None, :])
            lens = xp.where(b, 4, 5).astype(xp.int32)
            from ..columnar.device import bucket_width

            w = bucket_width(5)
            out = xp.pad(slots.astype(xp.uint8), ((0, 0), (0, w - 5)))
            return Val(out, v.valid, lens)
        if isinstance(frm, DateType):
            packed, lens = _dev_date_str(ctx, data)
            return Val(packed, v.valid, lens)
        if isinstance(frm, TimestampType):
            packed, lens = _dev_ts_str(ctx, data)
            return Val(packed, v.valid, lens)
        if isinstance(frm, DecimalType):
            packed, lens = _dev_decimal_str(ctx, data, frm.scale)
            return Val(packed, v.valid, lens)
        if isinstance(frm, (FloatType, DoubleType)):
            packed, lens = _dev_float_str(ctx, data, isinstance(frm, FloatType))
            return Val(packed, v.valid, lens)
        if isinstance(frm, IntegralType):
            packed, lens = _dev_int_str(ctx, data, frm.np_dtype)
            return Val(packed, v.valid, lens)
        raise NotImplementedError(f"device cast {frm} -> string")

    # ── string → X ─────────────────────────────────────────────────────────
    def _from_string(self, ctx: Ctx, v: Val, to: DataType) -> Val:
        if ctx.is_device:
            return self._from_string_device(ctx, v, to)

        n = ctx.n
        data = np.broadcast_to(np.asarray(v.data, dtype=object), (n,))
        valid = ctx.broadcast_bool(v.valid)
        out = np.zeros(n, dtype=to.np_dtype if not isinstance(to, BooleanType) else bool)
        ok = np.zeros(n, dtype=bool)
        for i in range(n):
            if not valid[i] or data[i] is None:
                continue
            r = _cpu_parse(data[i], to, ansi=self.ansi)
            if r is not None:
                out[i] = r
                ok[i] = True
        if self.ansi:
            self._err(ctx, valid, ok, "invalid input")
        return Val(out, valid & ok)

    def _from_string_device(self, ctx: Ctx, v: Val, to: DataType) -> Val:
        from .strings import dev_str

        ch, lengths = dev_str(ctx, v)
        start, end, has_any = _dev_trim(ctx, ch, lengths)
        if isinstance(to, BooleanType):
            out, ok = _dev_str_to_bool(ctx, ch, start, end)
        elif isinstance(to, DateType):
            out, ok = _dev_str_to_date(ctx, ch, start, end)
        elif isinstance(to, TimestampType):
            out, ok = _dev_str_to_ts(ctx, ch, start, end)
        elif isinstance(to, DecimalType):
            out, ok = _dev_str_to_decimal(ctx, ch, start, end, to)
        elif isinstance(to, (FloatType, DoubleType)):
            out, ok = _dev_str_to_float(ctx, ch, start, end, to)
        elif isinstance(to, IntegralType):
            out, ok = _dev_str_to_int(ctx, ch, start, end, to, ansi=self.ansi)
        else:
            raise NotImplementedError(f"device cast string -> {to}")
        ok = ok & has_any
        if self.ansi:
            self._err(ctx, v.valid, ok, "invalid input")
        return Val(out, ctx.broadcast_bool(v.valid) & ok)

    def __str__(self):
        return f"cast({self.c} as {self.to})"


# ═══════════════════════════════ device kernels ════════════════════════════


def _dev_int_str(ctx: Ctx, data, src_dtype):
    """Integral → string bytes: sign + significant digits."""
    xp = ctx.xp
    v = data.astype(xp.int64)
    k = _INT_DIGITS[np.dtype(src_dtype)]
    is_min = v == I64_MIN if k == 19 else xp.zeros(v.shape, dtype=bool)
    mag = xp.abs(xp.where(is_min, 0, v))
    D = _digits_msd(xp, mag, k)
    first = _first_sig(xp, D)
    neg = v < 0
    colidx = xp.arange(k, dtype=xp.int32)[None, :]
    sign_col = xp.where(neg, ord("-"), 0).astype(xp.uint8)[:, None]
    slots = xp.concatenate([sign_col, (D + 48).astype(xp.uint8)], axis=1)
    keep = xp.concatenate(
        [neg[:, None], colidx >= first[:, None]], axis=1
    )
    packed, lens = _pack(ctx, slots, keep, k + 1)
    if k == 19:
        cbytes = np.zeros(packed.shape[1], dtype=np.uint8)
        cbytes[: len(LONG_MIN_STR)] = np.frombuffer(LONG_MIN_STR, dtype=np.uint8)
        packed = xp.where(is_min[:, None], xp.asarray(cbytes)[None, :], packed)
        lens = xp.where(is_min, len(LONG_MIN_STR), lens).astype(xp.int32)
    return packed, lens


def _ymd_slots(xp, y, m, d):
    """[sign][y7][-][m2][-][d2] slot matrix + keep for a civil date."""
    neg = y < 0
    ymag = xp.abs(y.astype(xp.int64))
    Dy = _digits_msd(xp, ymag, 7)
    first = _first_sig(xp, Dy)
    first = xp.minimum(first, 3)  # at least 4 year digits (zero-padded)
    Dm = _digits_msd(xp, m.astype(xp.int64), 2)
    Dd = _digits_msd(xp, d.astype(xp.int64), 2)
    n = y.shape[0]
    dash = xp.full((n, 1), ord("-"), dtype=xp.uint8)
    sign_col = xp.where(neg, ord("-"), 0).astype(xp.uint8)[:, None]
    slots = xp.concatenate(
        [sign_col, (Dy + 48).astype(xp.uint8), dash, (Dm + 48).astype(xp.uint8),
         dash, (Dd + 48).astype(xp.uint8)],
        axis=1,
    )
    colidx = xp.arange(7, dtype=xp.int32)[None, :]
    ones = xp.ones((n, 1), dtype=bool)
    keep = xp.concatenate(
        [neg[:, None], colidx >= first[:, None], ones, xp.ones((n, 2), dtype=bool),
         ones, xp.ones((n, 2), dtype=bool)],
        axis=1,
    )
    return slots, keep


def _dev_date_str(ctx: Ctx, days):
    from .datetime import civil_from_days

    xp = ctx.xp
    y, m, d = civil_from_days(xp, days)
    slots, keep = _ymd_slots(xp, y, m, d)
    return _pack(ctx, slots, keep, slots.shape[1])


def _dev_ts_str(ctx: Ctx, micros):
    """yyyy-MM-dd HH:mm:ss[.ffffff] with the fraction's trailing zeros
    trimmed (Spark DateTimeUtils.timestampToString, UTC session zone)."""
    from .datetime import civil_from_days

    xp = ctx.xp
    micros = micros.astype(xp.int64)
    days = xp.floor_divide(micros, MICROS_PER_DAY)
    tod = micros - days * MICROS_PER_DAY
    y, m, d = civil_from_days(xp, days.astype(xp.int32))
    slots_d, keep_d = _ymd_slots(xp, y, m, d)
    secs = tod // US_PER_SECOND
    frac = (tod - secs * US_PER_SECOND).astype(xp.int64)
    hh = secs // 3600
    mi = (secs // 60) % 60
    ss = secs % 60
    n = micros.shape[0]

    def two(v):
        return (_digits_msd(xp, v.astype(xp.int64), 2) + 48).astype(xp.uint8)

    sp = xp.full((n, 1), ord(" "), dtype=xp.uint8)
    col = xp.full((n, 1), ord(":"), dtype=xp.uint8)
    dot = xp.full((n, 1), ord("."), dtype=xp.uint8)
    F = _digits_msd(xp, frac, 6)
    has_frac = frac > 0
    # keep fraction digits up to the last nonzero
    last_nz = 5 - xp.argmax((F != 0)[:, ::-1], axis=1).astype(xp.int32)
    fidx = xp.arange(6, dtype=xp.int32)[None, :]
    keep_f = has_frac[:, None] & (fidx <= last_nz[:, None])
    slots = xp.concatenate(
        [slots_d, sp, two(hh), col, two(mi), col, two(ss), dot,
         (F + 48).astype(xp.uint8)],
        axis=1,
    )
    ones2 = xp.ones((n, 2), dtype=bool)
    ones1 = xp.ones((n, 1), dtype=bool)
    keep = xp.concatenate(
        [keep_d, ones1, ones2, ones1, ones2, ones1, ones2,
         has_frac[:, None], keep_f],
        axis=1,
    )
    return _pack(ctx, slots, keep, slots.shape[1])


def _dev_decimal_str(ctx: Ctx, unscaled, scale: int):
    """BigDecimal.toPlainString shape: [-]intdigits[.frac]; device decimals
    cap scale at the plain-notation region (planner gates scale > 6 where
    Java switches to scientific notation)."""
    xp = ctx.xp
    v = unscaled.astype(xp.int64)
    neg = v < 0
    mag = xp.abs(v)
    D = _digits_msd(xp, mag, 19)
    n = v.shape[0]
    sign_col = xp.where(neg, ord("-"), 0).astype(xp.uint8)[:, None]
    if scale == 0:
        first = _first_sig(xp, D)
        colidx = xp.arange(19, dtype=xp.int32)[None, :]
        slots = xp.concatenate([sign_col, (D + 48).astype(xp.uint8)], axis=1)
        keep = xp.concatenate([neg[:, None], colidx >= first[:, None]], axis=1)
        return _pack(ctx, slots, keep, 20)
    k_int = 19 - scale
    Di, Df = D[:, :k_int], D[:, k_int:]
    first = _first_sig(xp, Di)
    colidx = xp.arange(k_int, dtype=xp.int32)[None, :]
    dot = xp.full((n, 1), ord("."), dtype=xp.uint8)
    slots = xp.concatenate(
        [sign_col, (Di + 48).astype(xp.uint8), dot, (Df + 48).astype(xp.uint8)],
        axis=1,
    )
    keep = xp.concatenate(
        [neg[:, None], colidx >= first[:, None],
         xp.ones((n, 1 + scale), dtype=bool)],
        axis=1,
    )
    return _pack(ctx, slots, keep, slots.shape[1])


def _dev_float_str(ctx: Ctx, data, is32: bool):
    """Java Double/Float.toString on device: exact binary-mantissa
    extraction, correctly-rounded decimal digits via the double-double 10^s
    tables, shortest round-tripping prefix search, Java formatting rules.

    Verified digit-exact against the CPU (Java-rule) formatter over fuzzed
    normal doubles/floats across the full exponent range. Remaining
    divergence class: XLA flushes subnormals to zero (DAZ), so subnormal
    inputs format as ``0.0`` — which is why the pair sits behind
    ``castFloatToString.enabled`` (the reference gates it for cuDF's
    analogous formatting divergences)."""
    xp = ctx.xp
    maxd = 9 if is32 else 17
    x = data.astype(xp.float64)
    mag = xp.abs(x)
    nan = xp.isnan(x)
    inf = xp.isinf(x)
    zero = mag == 0
    neg = _signbit(xp, x)
    safe = xp.where(nan | inf | zero, 1.0, mag)
    # exact binary mantissa: safe = m2 · 2^(be-52) with m2 ∈ [2^52, 2^53)
    # (power-of-two scaling is exact; log2 only seeds the integer estimate)
    be = xp.floor(xp.log2(safe)).astype(xp.int64)

    def _m2(b):
        u = 52 - b
        u1 = xp.clip(u, -1000, 1000)
        return safe * _pow2f(xp, u1) * _pow2f(xp, u - u1)

    m2f = _m2(be)
    for _ in range(2):
        be = (
            be
            + (m2f >= 2.0**53).astype(be.dtype)
            - (m2f < 2.0**52).astype(be.dtype)
        )
        m2f = _m2(be)
    t = be - 52
    # correctly rounded maxd-digit decimal mantissa via the double-double
    # 10^s tables: only the final round of (m2 · 2^t · 10^s) is inexact
    e10 = xp.floor(xp.log10(safe)).astype(xp.int64)
    m_full = xp.zeros(x.shape, dtype=xp.int64)
    for _ in range(2):
        s = (maxd - 1) - e10
        idx = xp.clip(s - _DD_MIN_S, 0, 700).astype(xp.int32)
        mh = xp.take(xp.asarray(_DD_HI), idx)
        ml = xp.take(xp.asarray(_DD_LO), idx)
        E = xp.take(xp.asarray(_DD_E), idx)
        p2 = _pow2f(xp, t + E)  # P = (mh+ml)·2^(t+E) ∈ (1.1, 22.2] — exact
        p, err = _two_prod(xp, m2f, mh * p2)
        tot_err = err + m2f * (ml * p2)
        r0 = xp.round(p)
        rem = (p - r0) + tot_err
        m_full = r0.astype(xp.int64) + xp.round(rem).astype(xp.int64)
        # signed distance (true − m_full) in digit units: breaks exact-half
        # ties when rounding to shorter digit counts below
        frac_rem = rem - xp.round(rem)
        e10 = (
            e10
            + (m_full >= 10**maxd).astype(e10.dtype)
            - (m_full < 10 ** (maxd - 1)).astype(e10.dtype)
        )
    m_full = xp.where(m_full >= 10**maxd, m_full // 10, m_full)
    m_full = xp.where(m_full < 10 ** (maxd - 1), m_full * 10, m_full)
    # shortest round-trip prefix length
    cmp_t = xp.float32 if is32 else xp.float64
    orig = xp.abs(data).astype(cmp_t)
    best_len = xp.full(x.shape, maxd, dtype=xp.int32)
    best_m = m_full
    best_e = e10
    for L in range(maxd - 1, 0, -1):
        div = 10 ** (maxd - L)
        q = m_full // div
        r = m_full - q * div
        half = div // 2
        at_half = r == half
        up_at_half = (frac_rem > 0) | ((frac_rem == 0) & (q % 2 == 1))
        q = q + ((r > half) | (at_half & up_at_half)).astype(xp.int64)
        bumped = q >= 10**L
        q2 = xp.where(bumped, q // 10, q)
        eL = e10 + bumped
        rexp = eL - (L - 1)
        recon = _dec_to_float(xp, q2, rexp)
        ok = recon.astype(cmp_t) == orig
        best_len = xp.where(ok, L, best_len)
        best_m = xp.where(ok, q2 * (10 ** (maxd - L)), best_m)
        best_e = xp.where(ok, eL, best_e)
    D = _digits_msd(xp, best_m, maxd)  # best digits, MSD first, zero-padded
    nd = best_len
    a = best_e  # adjusted exponent: value = d.ddd * 10^a
    n = x.shape[0]
    plain = (a >= -3) & (a < 7) & ~(nan | inf)
    # layout: [sign][8 int digits][.][frac digits][E][-][3 exp digits]
    # int part for plain: a+1 digits (a "0" placeholder when value < 1)
    int_cnt = xp.where(plain, xp.maximum(a + 1, 1), 1).astype(xp.int32)
    islots = []
    ikeeps = []
    for j in range(8):
        jj = xp.full((n,), j, dtype=xp.int32)
        if j < maxd:
            dig = D[:, j].astype(xp.uint8)
        else:
            dig = xp.zeros(n, dtype=xp.uint8)
        # leading "0" when |x| < 1 (int_cnt == 1 & a < 0 → digit "0")
        use_zero = plain & (a < 0) & (jj == 0)
        dig = xp.where(use_zero, 0, dig)
        islots.append((dig + 48).astype(xp.uint8))
        ikeeps.append(jj < int_cnt)
    # fraction digits: for plain: digits int_cnt.. (skip when a<0: leading
    # zeros then all nd digits); scientific: digits 1..
    zcnt = xp.where(plain & (a < 0), -a - 1, 0).astype(xp.int32)  # 0.00ddd
    fstart = xp.where(plain & (a >= 0), int_cnt, xp.where(plain, 0, 1))
    fslots = []
    fkeeps = []
    fcols = int(maxd + 3)  # frac zeros (≤2) + digits
    for j in range(fcols):
        jj = xp.full((n,), j, dtype=xp.int32)
        is_zero_pad = jj < zcnt
        didx = jj - zcnt + fstart
        dig = xp.zeros(n, dtype=xp.int64)
        for k in range(maxd):
            dig = xp.where(didx == k, D[:, k].astype(xp.int64), dig)
        dig = xp.where(is_zero_pad, 0, dig)
        in_digits = (didx >= fstart) & (didx < nd)
        keep = is_zero_pad | in_digits
        fslots.append((dig + 48).astype(xp.uint8))
        fkeeps.append(keep)
    # at least one fraction digit: when none kept, keep "0"
    any_frac = fkeeps[0]
    for kf in fkeeps[1:]:
        any_frac = any_frac | kf
    fkeeps[0] = fkeeps[0] | ~any_frac
    fslots[0] = xp.where(fkeeps[0] & ~any_frac, ord("0"), fslots[0]).astype(
        xp.uint8
    )
    # exponent slots
    aneg = a < 0
    amag = xp.abs(a)
    Ae = _digits_msd(xp, amag, 3)
    efirst = _first_sig(xp, Ae)
    sci = ~plain & ~(nan | inf)
    dotc = xp.full((n, 1), ord("."), dtype=xp.uint8)
    slots = xp.concatenate(
        [xp.where(neg, ord("-"), 0).astype(xp.uint8)[:, None]]
        + [s[:, None] for s in islots]
        + [dotc]
        + [s[:, None] for s in fslots]
        + [xp.full((n, 1), ord("E"), dtype=xp.uint8),
           xp.full((n, 1), ord("-"), dtype=xp.uint8)]
        + [(Ae[:, k] + 48).astype(xp.uint8)[:, None] for k in range(3)],
        axis=1,
    )
    keep = xp.concatenate(
        [(neg & ~nan)[:, None]]
        + [k[:, None] for k in ikeeps]
        + [xp.ones((n, 1), dtype=bool)]
        + [k[:, None] for k in fkeeps]
        + [sci[:, None], (sci & aneg)[:, None]]
        + [(sci & (xp.full((n,), k, dtype=xp.int32) >= efirst))[:, None]
           for k in range(3)],
        axis=1,
    )
    packed, lens = _pack(ctx, slots, keep, slots.shape[1])
    # specials overwrite
    for mask, txt in (
        (nan, b"NaN"),
        (inf & ~neg, b"Infinity"),
        (inf & neg, b"-Infinity"),
        (zero & ~neg, b"0.0"),
        (zero & neg, b"-0.0"),
    ):
        cb = np.zeros(packed.shape[1], dtype=np.uint8)
        cb[: len(txt)] = np.frombuffer(txt, dtype=np.uint8)
        packed = xp.where(mask[:, None], xp.asarray(cb)[None, :], packed)
        lens = xp.where(mask, len(txt), lens).astype(xp.int32)
    return packed, lens


def _dev_str_to_int(ctx: Ctx, ch, start, end, to: DataType, ansi: bool = False):
    """Spark UTF8String.toLong semantics over the trimmed region —
    Java Long.parseLong's negative accumulation, so ``-2^63`` parses.
    Non-ANSI additionally accepts a decimal tail (``'1.5' → 1``, truncation
    toward zero), matching the reference castStringToInts regex
    ``^([+\\-]?[0-9]+)(?:\\.[0-9]*)?$``; ANSI rejects it like Spark's
    toLongExact."""
    xp = ctx.xp
    n, w = ch.shape
    idx = xp.arange(w, dtype=xp.int32)[None, :]
    first_ch = _char_at(ctx, ch, start)
    has_sign = (first_ch == ord("-")) | (first_ch == ord("+"))
    neg = first_ch == ord("-")
    dstart = start + has_sign.astype(xp.int32)
    is_digit = (ch >= 48) & (ch <= 57)
    in_region = (idx >= dstart[:, None]) & (idx < end[:, None])
    dot_in = (ch == ord(".")) & in_region
    has_dot = dot_in.any(axis=1)
    first_dot = xp.argmax(dot_in, axis=1).astype(xp.int32)
    int_end = xp.where(has_dot, first_dot, end)
    digit_region = (idx >= dstart[:, None]) & (idx < int_end[:, None])
    frac_region = (idx > int_end[:, None]) & (idx < end[:, None])
    ok_chars = xp.where(digit_region | frac_region, is_digit, True).all(axis=1)
    # UTF8String.toInt: the integer part may be EMPTY when a separator is
    # present ('.5' → 0, '-.5' → 0 on CPU Spark); only sign-alone /
    # fully-empty inputs are rejected
    has_digit = (is_digit & digit_region).any(axis=1) | (
        has_dot & (dstart < end)
    )
    if ansi:
        ok_chars = ok_chars & ~has_dot
        has_digit = (is_digit & digit_region).any(axis=1)
    limit = xp.where(
        neg,
        xp.asarray(I64_MIN, dtype=xp.int64),
        xp.asarray(-(2**63 - 1), dtype=xp.int64),
    )
    # limit/10 truncated toward zero — same value for both limits
    multmin = xp.asarray(-((2**63 - 1) // 10), dtype=xp.int64)
    acc = xp.zeros(n, dtype=xp.int64)
    overflow = xp.zeros(n, dtype=bool)
    for j in range(w):
        d = (ch[:, j] - 48).astype(xp.int64)
        use = digit_region[:, j] & is_digit[:, j]
        overflow = overflow | (use & (acc < multmin))
        nxt = acc * 10
        overflow = overflow | (use & (nxt < limit + d))
        acc = xp.where(use, nxt - d, acc)
    out = xp.where(neg, acc, -acc)
    ok = ok_chars & has_digit & ~overflow
    lo, hi = _INT_BOUNDS[to.np_dtype]
    ok = ok & (out >= lo) & (out <= hi)
    return out.astype(to.np_dtype), ok


def _dev_str_to_bool(ctx: Ctx, ch, start, end):
    """Spark StringUtils.isTrueString/isFalseString (case-insensitive)."""
    xp = ctx.xp
    lower = xp.where(
        (ch >= ord("A")) & (ch <= ord("Z")), ch + 32, ch
    ).astype(xp.uint8)
    ln = end - start

    def matches(tok: bytes):
        m = ln == len(tok)
        for k, b in enumerate(tok):
            m = m & (_char_at(ctx, lower, start + k) == b)
        return m

    is_true = (
        matches(b"true") | matches(b"t") | matches(b"yes") | matches(b"y")
        | matches(b"1")
    )
    is_false = (
        matches(b"false") | matches(b"f") | matches(b"no") | matches(b"n")
        | matches(b"0")
    )
    return is_true, is_true | is_false


def _dev_parse_date_part(ctx: Ctx, ch, start, end):
    """Parse [+-]y{1,7}[-m{1,2}[-d{1,2}]] in [start, end) → (days, ok)."""
    from .datetime import days_from_civil

    xp = ctx.xp
    first_ch = _char_at(ctx, ch, start)
    has_sign = (first_ch == ord("-")) | (first_ch == ord("+"))
    neg = first_ch == ord("-")
    p = start + has_sign.astype(xp.int32)
    d1, f1 = _find_char(ctx, ch, ord("-"), p, end)
    d2, f2 = _find_char(ctx, ch, ord("-"), d1 + 1, end)
    y_end = xp.where(f1, d1, end)
    yv, y_ok = _parse_digits(ctx, ch, p, y_end, max_digits=6)
    m_end = xp.where(f2, d2, end)
    mv, m_ok = _parse_digits(ctx, ch, d1 + 1, m_end, max_digits=2)
    dv, dd_ok = _parse_digits(ctx, ch, d2 + 1, end, max_digits=2)
    mv = xp.where(f1, mv, 1)
    dv = xp.where(f2, dv, 1)
    ok = y_ok & xp.where(f1, m_ok, True) & xp.where(f2, dd_ok, True)
    y = xp.where(neg, -yv, yv).astype(xp.int32)
    m = mv.astype(xp.int32)
    d = dv.astype(xp.int32)
    ok = ok & (m >= 1) & (m <= 12) & (d >= 1)
    m_c = xp.clip(m, 1, 12)
    ok = ok & (d <= _days_in_month(xp, y, m_c))
    days = days_from_civil(xp, y, m_c, xp.clip(d, 1, 31))
    return days.astype(xp.int32), ok


def _dev_str_to_date(ctx: Ctx, ch, start, end):
    """Spark DateTimeUtils.stringToDate: the date segment grammar with
    anything from 'T' onward ignored."""
    xp = ctx.xp
    t_pos, has_t = _find_char(ctx, ch, ord("T"), start, end)
    date_end = xp.where(has_t, t_pos, end)
    return _dev_parse_date_part(ctx, ch, start, date_end)


def _dev_str_to_ts(ctx: Ctx, ch, start, end):
    """Spark DateTimeUtils.stringToTimestamp, UTC-only subset:
    date ['T'|' ' h{1,2}:m{1,2}:s{1,2}[.f{0,6}]]['Z']."""
    xp = ctx.xp
    last = _char_at(ctx, ch, end - 1)
    has_z = (last == ord("Z")) & (end > start)
    end = xp.where(has_z, end - 1, end)
    t1, f1 = _find_char(ctx, ch, ord("T"), start, end)
    t2, f2 = _find_char(ctx, ch, ord(" "), start, end)
    sep = xp.minimum(t1, t2)
    has_time = f1 | f2
    date_end = xp.where(has_time, sep, end)
    days, d_ok = _dev_parse_date_part(ctx, ch, start, date_end)
    t0 = sep + 1
    c1, g1 = _find_char(ctx, ch, ord(":"), t0, end)
    c2, g2 = _find_char(ctx, ch, ord(":"), c1 + 1, end)
    hv, h_ok = _parse_digits(ctx, ch, t0, c1, max_digits=2)
    mv, m_ok = _parse_digits(ctx, ch, c1 + 1, xp.where(g2, c2, end), max_digits=2)
    dot, has_dot = _find_char(ctx, ch, ord("."), c2 + 1, end)
    s_end = xp.where(has_dot, dot, end)
    sv, s_ok = _parse_digits(ctx, ch, c2 + 1, s_end, max_digits=2)
    fv, f_ok = _parse_digits(ctx, ch, dot + 1, end, max_digits=6)
    f_ok = f_ok | (end == dot + 1)  # trailing '.' with no digits is valid
    fdigits = xp.clip(end - (dot + 1), 0, 6)
    mult = xp.zeros(fdigits.shape, dtype=xp.int64)
    for k in range(7):
        mult = xp.where(fdigits == k, 10 ** (6 - k), mult)
    micros_frac = xp.where(has_dot, fv * mult, 0)
    time_ok = (
        g1 & g2 & h_ok & m_ok & s_ok
        & xp.where(has_dot, f_ok, True)
        & (hv < 24) & (mv < 60) & (sv < 60)
    )
    tod = xp.where(
        has_time,
        (hv * 3600 + mv * 60 + sv) * US_PER_SECOND + micros_frac,
        0,
    )
    ok = d_ok & xp.where(has_time, time_ok, True)
    micros = days.astype(xp.int64) * MICROS_PER_DAY + tod
    return micros, ok


def _dev_str_to_float(ctx: Ctx, ch, start, end, to: DataType):
    """Decimal-notation float parse: [+-]digits[.digits][eE[+-]digits] plus
    the special literals inf/infinity/nan (Spark Cast string→double).
    Gated: binary result can differ from strtod in the last ulp for extreme
    exponents (the reference gates castStringToFloat for the same class)."""
    xp = ctx.xp
    n, w = ch.shape
    lower = xp.where((ch >= 65) & (ch <= 90), ch + 32, ch).astype(xp.uint8)
    first_ch = _char_at(ctx, ch, start)
    has_sign = (first_ch == ord("-")) | (first_ch == ord("+"))
    neg = first_ch == ord("-")
    p = start + has_sign.astype(xp.int32)
    ln = end - p

    def matches(tok: bytes):
        m = ln == len(tok)
        for k, b in enumerate(tok):
            m = m & (_char_at(ctx, lower, p + k) == b)
        return m

    is_inf = matches(b"inf") | matches(b"infinity")
    is_nan = matches(b"nan")
    # exponent marker
    e_pos, has_e = _find_char(ctx, lower, ord("e"), p, end)
    mant_end = xp.where(has_e, e_pos, end)
    dot, has_dot = _find_char(ctx, ch, ord("."), p, mant_end)
    int_end = xp.where(has_dot, dot, mant_end)
    idx = xp.arange(w, dtype=xp.int32)[None, :]
    is_digit = (ch >= 48) & (ch <= 57)
    # mantissa digits: integer part then fraction; cap significance at 18
    acc = xp.zeros(n, dtype=xp.int64)
    ndig = xp.zeros(n, dtype=xp.int32)  # significant digits consumed
    extra_exp = xp.zeros(n, dtype=xp.int32)  # dropped int digits
    frac_cnt = xp.zeros(n, dtype=xp.int32)
    int_any = xp.zeros(n, dtype=bool)
    frac_any = xp.zeros(n, dtype=bool)
    bad = xp.zeros(n, dtype=bool)
    for j in range(w):
        in_int = (idx[0, j] >= p) & (idx[0, j] < int_end)
        in_frac = has_dot & (idx[0, j] > dot) & (idx[0, j] < mant_end)
        dig = is_digit[:, j]
        d = (ch[:, j] - 48).astype(xp.int64)
        bad = bad | ((in_int | in_frac) & ~dig)
        room = ndig < 18
        take_int = in_int & dig
        take_frac = in_frac & dig
        acc = xp.where((take_int | take_frac) & room, acc * 10 + d, acc)
        ndig = ndig + ((take_int | take_frac) & room).astype(xp.int32)
        extra_exp = extra_exp + (take_int & ~room).astype(xp.int32)
        frac_cnt = frac_cnt + (take_frac & room).astype(xp.int32)
        int_any = int_any | take_int
        frac_any = frac_any | take_frac
    # exponent
    e_first = _char_at(ctx, ch, e_pos + 1)
    e_sign = (e_first == ord("-")) | (e_first == ord("+"))
    e_neg = e_first == ord("-")
    # 8 exponent digits: anything past ±350 saturates to ±inf / 0 in
    # _dec_to_float exactly like strtod overflow/underflow
    ev, e_ok = _parse_digits(
        ctx, ch, e_pos + 1 + e_sign.astype(xp.int32), end, max_digits=8
    )
    ev = xp.clip(ev, 0, 100_000)
    ev = xp.where(e_neg, -ev, ev).astype(xp.int32)
    exp_total = xp.where(has_e, ev, 0) + extra_exp - frac_cnt
    # negative exponents divide by the (exactly representable for |e| ≤ 22)
    # power instead of multiplying by its inexact reciprocal — the strtod
    # fast path, so results match the CPU parse for ordinary literals
    val = _dec_to_float(xp, acc, exp_total)
    ok_num = (int_any | frac_any) & ~bad & xp.where(has_e, e_ok, True)
    out = xp.where(is_inf, xp.inf, xp.where(is_nan, xp.nan, val))
    out = xp.where(neg, -out, out)
    ok = ok_num | is_inf | is_nan
    return out.astype(to.np_dtype), ok


def _dev_str_to_decimal(ctx: Ctx, ch, start, end, to: DecimalType):
    """[+-]digits[.digits][eE[+-]digits] → unscaled int64 at to.scale,
    rounding HALF_UP (Spark Decimal.changePrecision)."""
    xp = ctx.xp
    n, w = ch.shape
    lower = xp.where((ch >= 65) & (ch <= 90), ch + 32, ch).astype(xp.uint8)
    first_ch = _char_at(ctx, ch, start)
    has_sign = (first_ch == ord("-")) | (first_ch == ord("+"))
    neg = first_ch == ord("-")
    p = start + has_sign.astype(xp.int32)
    e_pos, has_e = _find_char(ctx, lower, ord("e"), p, end)
    mant_end = xp.where(has_e, e_pos, end)
    dot, has_dot = _find_char(ctx, ch, ord("."), p, mant_end)
    int_end = xp.where(has_dot, dot, mant_end)
    idx = xp.arange(w, dtype=xp.int32)[None, :]
    is_digit = (ch >= 48) & (ch <= 57)
    acc = xp.zeros(n, dtype=xp.int64)
    frac_cnt = xp.zeros(n, dtype=xp.int32)
    any_dig = xp.zeros(n, dtype=bool)
    bad = xp.zeros(n, dtype=bool)
    overflow = xp.zeros(n, dtype=bool)
    hi = xp.asarray(2**62, dtype=xp.int64)
    for j in range(w):
        in_int = (idx[0, j] >= p) & (idx[0, j] < int_end)
        in_frac = has_dot & (idx[0, j] > dot) & (idx[0, j] < mant_end)
        dig = is_digit[:, j]
        d = (ch[:, j] - 48).astype(xp.int64)
        bad = bad | ((in_int | in_frac) & ~dig)
        take = (in_int | in_frac) & dig
        overflow = overflow | (take & (acc > hi // 10))
        acc = xp.where(take, acc * 10 + d, acc)
        frac_cnt = frac_cnt + (in_frac & dig).astype(xp.int32)
        any_dig = any_dig | take
    e_first = _char_at(ctx, ch, e_pos + 1)
    e_sign = (e_first == ord("-")) | (e_first == ord("+"))
    e_neg = e_first == ord("-")
    ev, e_ok = _parse_digits(
        ctx, ch, e_pos + 1 + e_sign.astype(xp.int32), end, max_digits=4
    )
    ev = xp.clip(ev, 0, 10_000)
    ev = xp.where(e_neg, -ev, ev).astype(xp.int32)
    shift = to.scale - frac_cnt + xp.where(has_e, ev, 0)
    # apply shift: multiply (overflow-check) or divide with HALF_UP rounding
    out = acc
    for s in range(1, 19):
        up = shift == s
        pw = 10**s
        overflow = overflow | (up & (xp.abs(out) > (2**63 - 1) // pw))
        out = xp.where(up, out * pw, out)
    for s in range(1, 19):
        dn = shift == -s
        pw = 10**s
        q = out // pw
        r = out - q * pw
        q = q + (2 * r >= pw).astype(xp.int64)
        out = xp.where(dn, q, out)
    overflow = overflow | ((shift > 18) & (acc != 0))
    out = xp.where(xp.abs(shift) > 18, 0, out)
    lim = 10**to.precision - 1
    ok = any_dig & ~bad & ~overflow & xp.where(has_e, e_ok, True)
    ok = ok & (out <= lim)
    out = xp.where(neg, -out, out)
    return out, ok


# ═══════════════════════════════ CPU oracle ════════════════════════════════


def _cpu_date_str(days: int) -> str:
    y, m, d = _civil(days)
    sign = "-" if y < 0 else ""
    return f"{sign}{abs(y):04d}-{m:02d}-{d:02d}"


def _cpu_ts_str(micros: int) -> str:
    days, tod = divmod(micros, MICROS_PER_DAY)
    y, m, d = _civil(days)
    secs, frac = divmod(tod, US_PER_SECOND)
    hh, rem = divmod(secs, 3600)
    mi, ss = divmod(rem, 60)
    sign = "-" if y < 0 else ""
    base = f"{sign}{abs(y):04d}-{m:02d}-{d:02d} {hh:02d}:{mi:02d}:{ss:02d}"
    if frac:
        base += ("." + f"{frac:06d}").rstrip("0")
    return base


def _cpu_decimal_str(unscaled: int, scale: int) -> str:
    """java.math.BigDecimal.toString (Spark Decimal.toString)."""
    import decimal as _dec

    return str(_dec.Decimal(unscaled).scaleb(-scale))


def _civil(z: int):
    z += 719468
    era = z // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + (3 if mp < 10 else -9)
    return y + (m <= 2), m, d


def _days_from_civil_py(y: int, m: int, d: int) -> int:
    y -= m <= 2
    era = y // 400
    yoe = y - era * 400
    doy = (153 * (m + (-3 if m > 2 else 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _cpu_parse_date_part(s: str):
    """Python mirror of _dev_parse_date_part (the single source of the
    grammar both backends implement)."""
    if not s:
        return None
    neg = s[0] == "-"
    if s[0] in "+-":
        s = s[1:]
    segs = s.split("-")
    if len(segs) > 3 or not segs[0]:
        return None
    try:
        vals = [int(x) for x in segs]
    except ValueError:
        return None
    if any(not x.isdigit() for x in segs):
        return None
    if len(segs[0]) > 6 or any(len(x) > 2 for x in segs[1:]):
        return None
    y = vals[0] * (-1 if neg else 1)
    m = vals[1] if len(vals) > 1 else 1
    d = vals[2] if len(vals) > 2 else 1
    if not (1 <= m <= 12 and 1 <= d):
        return None
    dim = _days_from_civil_py(y + (m == 12), 1 if m == 12 else m + 1, 1) - (
        _days_from_civil_py(y, m, 1)
    )
    if d > dim:
        return None
    return _days_from_civil_py(y, m, d)


def _cpu_parse(s: str, to: DataType, ansi: bool = False):
    """CPU string parse for one value; None on malformed (→ NULL)."""
    s = s.strip(
        "".join(chr(c) for c in range(0x21))
    )  # UTF8String.trimAll: all ctrl/space ≤ 0x20
    if not s.isascii():
        # Spark's UTF8String parsers are ASCII-only; python's int()/Decimal()
        # accept full-width Unicode digits — reject them to match
        return None
    if isinstance(to, BooleanType):
        ls = s.lower()
        if ls in ("true", "t", "yes", "y", "1"):
            return True
        if ls in ("false", "f", "no", "n", "0"):
            return False
        return None
    if isinstance(to, DateType):
        return _cpu_parse_date_part(s.split("T")[0])
    if isinstance(to, TimestampType):
        if s.endswith("Z"):
            s = s[:-1]
        sep = None
        for c in ("T", " "):
            if c in s:
                sep = c
                break
        if sep is None:
            days = _cpu_parse_date_part(s)
            return None if days is None else days * MICROS_PER_DAY
        date_s, _, time_s = s.partition(sep)
        days = _cpu_parse_date_part(date_s)
        if days is None:
            return None
        parts = time_s.split(":")
        if len(parts) != 3:
            return None
        try:
            h, mi = int(parts[0]), int(parts[1])
            sec_s, _, frac_s = parts[2].partition(".")
            sec = int(sec_s)
            if len(parts[0]) > 2 or len(parts[1]) > 2 or len(sec_s) > 2:
                return None
            frac = 0
            if frac_s:
                if len(frac_s) > 6 or not frac_s.isdigit():
                    return None
                frac = int(frac_s) * 10 ** (6 - len(frac_s))
        except ValueError:
            return None
        if not (h < 24 and mi < 60 and sec < 60):
            return None
        return days * MICROS_PER_DAY + (h * 3600 + mi * 60 + sec) * US_PER_SECOND + frac
    if isinstance(to, DecimalType):
        import decimal as _dec

        try:
            d = _dec.Decimal(s)
        except _dec.InvalidOperation:
            return None
        if not d.is_finite():
            return None
        unscaled = int(
            d.scaleb(to.scale).to_integral_value(rounding=_dec.ROUND_HALF_UP)
        )
        if abs(unscaled) > 10**to.precision - 1:
            return None
        return unscaled
    if isinstance(to, (FloatType, DoubleType)):
        ls = s.lower()
        sign = -1.0 if ls.startswith("-") else 1.0
        core = ls.lstrip("+-")
        if core in ("inf", "infinity"):
            return sign * float("inf")
        if core == "nan":
            return float("nan")
        if "_" in s or "x" in ls:  # Python literal-isms Java rejects
            return None
        try:
            return to.np_dtype.type(s)
        except (TypeError, ValueError):
            return None
    if isinstance(to, IntegralType):
        sign = s[:1] if s[:1] in "+-" else ""
        body = s[1:] if sign else s
        had_dot = False
        if not ansi and "." in body:
            # UTF8String.toLong truncation: '1.5' → 1 when the tail after
            # '.' is all digits (or empty); the integer part may itself be
            # empty ('.5' → 0); ANSI rejects like toLongExact
            intpart, _, frac = body.partition(".")
            if frac and not frac.isdigit():
                return None
            body = intpart
            had_dot = True
        if not body.isdigit():
            if not (had_dot and body == ""):
                return None
            body = "0"
        try:
            val = int(sign + body)
        except (TypeError, ValueError):
            return None
        lo, hi = _INT_BOUNDS[to.np_dtype]
        return val if lo <= val <= hi else None
    return None


# ═══════════════════════════════ planner gate ══════════════════════════════


def can_cast_on_device(frm: DataType, to: DataType, conf) -> bool:
    """TypeChecks-style gate used by the planner (GpuCast type matrix)."""
    from .. import config as cfg
    from ..types import is_complex

    if is_complex(frm) or is_complex(to):
        return False
    if isinstance(frm, StringType):
        if isinstance(to, (FloatType, DoubleType)):
            return conf.is_enabled(cfg.CAST_STRING_TO_FLOAT)
        if isinstance(to, TimestampType):
            return conf.is_enabled(cfg.CAST_STRING_TO_TIMESTAMP)
        return isinstance(
            to, (IntegralType, BooleanType, DateType, DecimalType, StringType)
        )
    if isinstance(to, StringType):
        if isinstance(frm, (FloatType, DoubleType)):
            return conf.is_enabled(cfg.CAST_FLOAT_TO_STRING)
        if isinstance(frm, DecimalType):
            # Java switches to scientific notation beyond scale 6 leading
            # zeros; the device kernel only emits plain notation
            return frm.scale <= 6
        return True
    return True
