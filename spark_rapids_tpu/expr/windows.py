"""Window expressions — reference: GpuWindowExpression.scala (831 LoC;
rows & range frames, rank/rownumber/lead/lag) and GpuWindowExec.scala.

A ``WindowExpression`` pairs a window function (ranking, lead/lag, or an
aggregate) with a ``WindowSpec`` (partition keys, ordering, frame). Spark
frame semantics implemented:

* default frame: RANGE UNBOUNDED PRECEDING..CURRENT ROW when ordered,
  ROWS UNBOUNDED..UNBOUNDED otherwise;
* ranking functions always use the whole-partition ordering and ignore the
  frame; rank/dense_rank rank *peer groups* (rows equal on the order keys);
* RANGE CURRENT ROW bounds include the full peer group.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

from ..types import DataType, IntegerType, INT, LONG
from .base import Expression, Literal, to_expr

# Spark's Window.unboundedPreceding/Following sentinels
UNBOUNDED_PRECEDING = -(1 << 62)
UNBOUNDED_FOLLOWING = 1 << 62
CURRENT_ROW = 0


@dataclass(frozen=True)
class WindowOrder:
    """Ordering inside a window spec (SortOrder twin, kept here to avoid an
    expr→plan import cycle)."""

    child: Expression
    ascending: bool = True
    nulls_first: Optional[bool] = None

    def resolved_nulls_first(self) -> bool:
        # Spark default: nulls first for ASC, nulls last for DESC
        if self.nulls_first is None:
            return self.ascending
        return self.nulls_first

    def __str__(self):
        d = "ASC" if self.ascending else "DESC"
        nf = "NULLS FIRST" if self.resolved_nulls_first() else "NULLS LAST"
        return f"{self.child} {d} {nf}"


@dataclass(frozen=True)
class WindowFrame:
    frame_type: str  # "rows" | "range"
    lower: int  # <= 0 preceding; sentinels above
    upper: int

    def scaled_for_decimal(self, order_dt) -> "WindowFrame":
        """RANGE offsets over a decimal order key compare against the
        UNSCALED int64 representation: scale the integer bounds by
        10^scale (5 PRECEDING over decimal(_,2) means 500 unscaled).
        Shared by the device and CPU window execs so the oracle cannot
        diverge from the device path."""
        from ..types import DecimalType

        if not isinstance(order_dt, DecimalType):
            return self
        import dataclasses as _dc

        pow10 = 10 ** order_dt.scale
        sent = (UNBOUNDED_PRECEDING, CURRENT_ROW, UNBOUNDED_FOLLOWING)
        return _dc.replace(
            self,
            lower=self.lower if self.lower in sent else self.lower * pow10,
            upper=self.upper if self.upper in sent else self.upper * pow10,
        )

    def _b(self, v, pre):
        if v == UNBOUNDED_PRECEDING:
            return "UNBOUNDED PRECEDING"
        if v == UNBOUNDED_FOLLOWING:
            return "UNBOUNDED FOLLOWING"
        if v == 0:
            return "CURRENT ROW"
        return f"{-v} PRECEDING" if v < 0 else f"{v} FOLLOWING"

    def __str__(self):
        return (
            f"{self.frame_type.upper()} BETWEEN {self._b(self.lower, True)} "
            f"AND {self._b(self.upper, False)}"
        )


@dataclass(frozen=True)
class WindowSpec:
    partition_by: Tuple[Expression, ...] = ()
    order_by: Tuple[WindowOrder, ...] = ()
    frame: Optional[WindowFrame] = None  # None → Spark default

    def resolved_frame(self) -> WindowFrame:
        if self.frame is not None:
            return self.frame
        if self.order_by:
            return WindowFrame("range", UNBOUNDED_PRECEDING, CURRENT_ROW)
        return WindowFrame("rows", UNBOUNDED_PRECEDING, UNBOUNDED_FOLLOWING)


# ── window functions without an aggregate analogue ─────────────────────────


@dataclass(frozen=True)
class RankingFunction(Expression):
    """Base for row_number/rank/dense_rank/ntile."""

    @property
    def data_type(self) -> DataType:
        return INT

    @property
    def nullable(self) -> bool:
        return False

    def children(self):
        return []


@dataclass(frozen=True)
class RowNumber(RankingFunction):
    def __str__(self):
        return "row_number()"


@dataclass(frozen=True)
class Rank(RankingFunction):
    def __str__(self):
        return "rank()"


@dataclass(frozen=True)
class DenseRank(RankingFunction):
    def __str__(self):
        return "dense_rank()"


@dataclass(frozen=True)
class PercentRank(RankingFunction):
    """(rank − 1) / (partition rows − 1); 0.0 for single-row partitions."""

    @property
    def data_type(self) -> DataType:
        from ..types import DOUBLE

        return DOUBLE

    def __str__(self):
        return "percent_rank()"


@dataclass(frozen=True)
class CumeDist(RankingFunction):
    """rows ≤ current peer group / partition rows."""

    @property
    def data_type(self) -> DataType:
        from ..types import DOUBLE

        return DOUBLE

    def __str__(self):
        return "cume_dist()"


@dataclass(frozen=True)
class NTile(RankingFunction):
    """Spark NTile: n rows into ``buckets`` groups; the first n % buckets
    groups get one extra row."""

    buckets: int

    def __str__(self):
        return f"ntile({self.buckets})"


@dataclass(frozen=True)
class Lead(Expression):
    child: Expression
    offset: int = 1
    default: Expression = field(default_factory=lambda: Literal(None))

    @property
    def data_type(self) -> DataType:
        return self.child.data_type

    @property
    def nullable(self) -> bool:
        return True

    def children(self):
        return [self.child, self.default]

    def __str__(self):
        return f"lead({self.child}, {self.offset})"


@dataclass(frozen=True)
class Lag(Expression):
    child: Expression
    offset: int = 1
    default: Expression = field(default_factory=lambda: Literal(None))

    @property
    def data_type(self) -> DataType:
        return self.child.data_type

    @property
    def nullable(self) -> bool:
        return True

    def children(self):
        return [self.child, self.default]

    def __str__(self):
        return f"lag({self.child}, {self.offset})"


@dataclass(frozen=True)
class WindowExpression(Expression):
    """function OVER (spec) — the planner pulls these out of projections into
    a Window node (Spark's ExtractWindowExpressions)."""

    function: Expression
    spec: WindowSpec

    @property
    def data_type(self) -> DataType:
        return self.function.data_type

    @property
    def nullable(self) -> bool:
        return getattr(self.function, "nullable", True)

    def children(self):
        return [self.function]

    def __str__(self):
        parts = []
        if self.spec.partition_by:
            parts.append(
                "PARTITION BY " + ", ".join(map(str, self.spec.partition_by))
            )
        if self.spec.order_by:
            parts.append("ORDER BY " + ", ".join(map(str, self.spec.order_by)))
        parts.append(str(self.spec.resolved_frame()))
        return f"{self.function} OVER ({' '.join(parts)})"


def contains_window(e: Expression) -> bool:
    if isinstance(e, WindowExpression):
        return True
    return any(contains_window(c) for c in e.children())
