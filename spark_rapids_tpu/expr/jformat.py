"""Java-compatible floating-point → string formatting.

Spark's ``cast(float/double as string)`` produces ``java.lang.Double.toString``
/ ``Float.toString`` output (reference: GpuCast.scala castFloatingTypeToString,
which documents cuDF's divergence and gates the pair behind
``spark.rapids.sql.castFloatToString.enabled``). Java's rules:

* NaN → ``NaN``; infinities → ``Infinity`` / ``-Infinity``; zeros keep their
  sign bit (``0.0`` / ``-0.0``).
* ``1e-3 <= |x| < 1e7``: plain decimal with the shortest digit string that
  round-trips, always keeping at least one digit after the point (``1.0``).
* otherwise: "computerized scientific" ``d.dddE±e`` with at least one digit
  after the point (``1.0E10``).

The shortest round-trip digits here come from numpy's ``unique=True``
formatter (Grisu/Ryu-exact); OpenJDK's pre-19 FloatingDecimal emits a
non-shortest string for a handful of exotic values — a documented divergence
class the reference shares.
"""
from __future__ import annotations

import numpy as np


def _digits_exp(x, is32: bool) -> tuple[str, int, bool]:
    """Shortest round-trip digits of finite nonzero ``x`` as
    (digit string, adjusted exponent a, negative) with x = d.igits × 10^a."""
    v = np.float32(x) if is32 else np.float64(x)
    s = np.format_float_scientific(abs(v), unique=True, trim="-")
    mant, _, exp = s.partition("e")
    digits = mant.replace(".", "")
    return digits, int(exp), bool(np.signbit(v))


def java_float_str(x, is32: bool) -> str:
    """Java ``Double.toString``/``Float.toString`` of ``x``."""
    if np.isnan(x):
        return "NaN"
    if np.isinf(x):
        return "-Infinity" if x < 0 else "Infinity"
    if x == 0:
        return "-0.0" if np.signbit(x) else "0.0"
    digits, a, neg = _digits_exp(x, is32)
    sign = "-" if neg else ""
    if -3 <= a < 7:
        if a >= len(digits) - 1:  # integral value: pad with zeros, add .0
            return f"{sign}{digits}{'0' * (a - len(digits) + 1)}.0"
        if a >= 0:
            return f"{sign}{digits[: a + 1]}.{digits[a + 1 :]}"
        return f"{sign}0.{'0' * (-a - 1)}{digits}"
    frac = digits[1:] or "0"
    return f"{sign}{digits[0]}.{frac}E{a}"
