"""Expression IR — the analogue of the reference's expression library plus the
Catalyst expressions it wraps (reference: GpuOverrides.scala ~260 expr rules,
GpuBoundAttribute.scala, literals.scala, namedExpressions.scala).

Design, TPU-first:

* Expressions are **frozen dataclasses**, hashable by structure. A bound
  expression tree is the compile-cache key for the jitted kernel that
  evaluates it — the analogue of cudf's pre-compiled kernel dispatch.
* One evaluation implementation serves both backends: ``Ctx.xp`` is either
  ``numpy`` (CPU fallback operators + differential-test oracle) or
  ``jax.numpy`` (device). Spark semantics (null propagation, Java wraparound,
  NaN ordering, div-by-zero→null) are implemented explicitly so both backends
  agree bit-for-bit with CPU Spark.
* Values are (data, validity) pairs with lazy scalar broadcasting; XLA fuses
  the broadcasts away on device.

Name resolution: the DataFrame/logical layer produces ``UnresolvedAttribute``;
``bind()`` resolves names against a schema into ``BoundReference`` (ordinal) —
the analogue of ``GpuBindReferences``.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Optional, Sequence, Union

import numpy as np

from ..types import (
    BOOLEAN,
    DOUBLE,
    BooleanType,
    DataType,
    DecimalType,
    FractionalType,
    IntegralType,
    LONG,
    NullType,
    Schema,
    StringType,
    TimestampType,
    DateType,
    numeric_promote,
)


class AnsiError(ArithmeticError):
    """Raised when an ANSI-mode expression (cast overflow, malformed parse)
    hits invalid input — Spark's SparkArithmeticException/DateTimeException
    family under ``spark.sql.ansi.enabled`` (reference: ansiEnabled branches
    in GpuCast.scala and AnsiCastOpSuite)."""


@dataclass
class Val:
    """An evaluation result: data + validity, each either scalar or length-n.

    Device strings carry ``lengths`` (see columnar.device); CPU strings use an
    object ndarray in ``data`` with ``lengths is None``. Device complex values
    (array/struct/map) carry ``children`` — nested DeviceColumn planes — with
    ``data`` None; the CPU engine stores python objects in ``data`` instead.
    """

    data: Any
    valid: Any
    lengths: Any = None
    children: Any = None  # tuple[DeviceColumn] for device complex values

    def full_data(self, ctx: "Ctx"):
        return ctx.broadcast(self.data)

    def full_valid(self, ctx: "Ctx"):
        return ctx.broadcast_bool(self.valid)


class Ctx:
    """Evaluation context over one batch for one backend."""

    def __init__(self, xp, n: int, is_device: bool, columns, num_rows=None, task=None):
        self.xp = xp
        self.n = n  # capacity (device) or row count (cpu)
        self.is_device = is_device
        self.columns = columns  # list of Val
        self.num_rows = num_rows  # device scalar when is_device
        self.task = task  # TaskVals (traced) for task-dependent expressions
        # ANSI error sites: (message, per-row bool mask) accumulated during
        # device tracing; the project/filter kernels return the masked
        # any-flags and the exec raises AnsiError host-side after the run
        self.errors: list = []
        # rows for which the currently-evaluating expression is actually
        # selected (vectorized eval runs ALL conditional branches; Spark
        # evaluates per-row, so errors in untaken branches must not fire)
        self._err_mask = None

    def error_scope(self, mask):
        """Context manager: AND ``mask`` into the branch-liveness mask that
        gates ANSI error sites (If/CaseWhen/Coalesce branch evaluation)."""
        from contextlib import contextmanager

        @contextmanager
        def scope():
            prev = self._err_mask
            m = self.broadcast_bool(mask)
            self._err_mask = m if prev is None else (prev & m)
            try:
                yield
            finally:
                self._err_mask = prev

        return scope()

    def register_error(self, message: str, row_mask) -> None:
        row_mask = self.broadcast_bool(row_mask)
        if self._err_mask is not None:
            row_mask = row_mask & self._err_mask
        if self.is_device:
            self.errors.append((message, row_mask))
        else:
            if bool(np.any(row_mask)):
                raise AnsiError(message)

    def broadcast(self, data):
        xp = self.xp
        arr = xp.asarray(data)
        if arr.ndim == 0:
            return xp.broadcast_to(arr, (self.n,))
        return arr

    def broadcast_bool(self, v):
        xp = self.xp
        arr = xp.asarray(v)
        if arr.ndim == 0:
            return xp.broadcast_to(arr.astype(bool), (self.n,))
        return arr.astype(bool)

    @staticmethod
    def for_device(batch, task=None) -> "Ctx":
        import jax.numpy as jnp

        cols = [
            Val(c.data, c.validity, c.lengths, c.children) for c in batch.columns
        ]
        return Ctx(jnp, batch.capacity, True, cols, batch.num_rows, task)

    @staticmethod
    def for_cpu(columns: list[tuple[np.ndarray, np.ndarray]], n: int, task=None) -> "Ctx":
        cols = [Val(d, v) for d, v in columns]
        return Ctx(np, n, False, cols, task=task)


@dataclass(frozen=True)
class Expression:
    """Base class. Subclasses are frozen dataclasses; children are fields."""

    def children(self) -> Sequence["Expression"]:
        vals = []
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, Expression):
                vals.append(v)
            elif isinstance(v, tuple):
                for x in v:
                    if isinstance(x, Expression):
                        vals.append(x)
                    elif isinstance(x, tuple):
                        vals.extend(y for y in x if isinstance(y, Expression))
        return vals

    @property
    def data_type(self) -> DataType:
        raise NotImplementedError(type(self).__name__)

    @property
    def nullable(self) -> bool:
        return True

    def eval(self, ctx: Ctx) -> Val:
        raise NotImplementedError(type(self).__name__)

    # pretty printing
    def __str__(self) -> str:
        args = ", ".join(str(c) for c in self.children())
        return f"{type(self).__name__.lower()}({args})"


@dataclass(frozen=True)
class UnresolvedAttribute(Expression):
    name: str

    @property
    def data_type(self) -> DataType:
        raise TypeError(f"unresolved attribute '{self.name}' has no type")

    def __str__(self):
        return f"'{self.name}"


@dataclass(frozen=True)
class BoundReference(Expression):
    ordinal: int
    dtype: DataType
    _nullable: bool = True

    @property
    def data_type(self) -> DataType:
        return self.dtype

    @property
    def nullable(self) -> bool:
        return self._nullable

    def eval(self, ctx: Ctx) -> Val:
        return ctx.columns[self.ordinal]

    def __str__(self):
        return f"input[{self.ordinal}, {self.dtype}]"


@dataclass(frozen=True)
class Literal(Expression):
    value: Any
    dtype: DataType

    @property
    def data_type(self) -> DataType:
        return self.dtype

    @property
    def nullable(self) -> bool:
        return self.value is None

    def eval(self, ctx: Ctx) -> Val:
        xp = ctx.xp
        if self.value is None:
            if isinstance(self.dtype, StringType):
                if ctx.is_device:
                    from ..columnar.device import MIN_STR_WIDTH

                    return Val(
                        xp.zeros(MIN_STR_WIDTH, dtype=xp.uint8),
                        xp.asarray(False),
                        xp.asarray(0, dtype=xp.int32),
                    )
                return Val(np.asarray(None, dtype=object), np.asarray(False))
            zero = xp.zeros((), dtype=self.dtype.np_dtype)
            return Val(zero, xp.asarray(False))
        if isinstance(self.dtype, StringType):
            raw = self.value.encode("utf-8")
            if ctx.is_device:
                from ..columnar.device import pad_scalar_bytes

                buf, n = pad_scalar_bytes(raw)
                data = xp.asarray(buf)  # [w] — scalar-like string
                return Val(data, xp.asarray(True), xp.asarray(n, dtype=xp.int32))
            return Val(np.asarray(self.value, dtype=object), np.asarray(True))
        if isinstance(self.dtype, DecimalType):
            import decimal as _dec

            unscaled = int(
                _dec.Decimal(self.value).scaleb(self.dtype.scale).to_integral_value()
            )
            return Val(xp.asarray(unscaled, dtype=xp.int64), xp.asarray(True))
        return Val(
            xp.asarray(self.value, dtype=self.dtype.np_dtype), xp.asarray(True)
        )

    def __str__(self):
        return f"{self.value}"


@dataclass(frozen=True)
class Alias(Expression):
    child: Expression
    name: str

    @property
    def data_type(self) -> DataType:
        return self.child.data_type

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    def eval(self, ctx: Ctx) -> Val:
        return self.child.eval(ctx)

    def __str__(self):
        return f"{self.child} AS {self.name}"


def output_name(e: Expression) -> str:
    if isinstance(e, Alias):
        return e.name
    if isinstance(e, UnresolvedAttribute):
        return e.name
    if isinstance(e, BoundReference):
        return f"col{e.ordinal}"
    return str(e)


# ── null-propagation helpers shared by concrete expressions ────────────────


def and_valid(ctx: Ctx, *vs):
    xp = ctx.xp
    out = None
    for v in vs:
        b = xp.asarray(v).astype(bool)
        out = b if out is None else out & b
    return out


class UnaryExpression(Expression):
    """Null-propagating unary op: implement ``_compute(ctx, data)``."""

    @property
    def child(self) -> Expression:  # convention: first dataclass field
        return self.children()[0]

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    def eval(self, ctx: Ctx) -> Val:
        c = self.child.eval(ctx)
        data = self._compute(ctx, c.data)
        return Val(data, c.valid)

    def _compute(self, ctx: Ctx, data):
        raise NotImplementedError


class BinaryExpression(Expression):
    """Null-propagating binary op: implement ``_compute(ctx, l, r)`` which may
    also return (data, extra_valid) to add result-dependent nullability."""

    @property
    def left(self) -> Expression:
        return self.children()[0]

    @property
    def right(self) -> Expression:
        return self.children()[1]

    def eval(self, ctx: Ctx) -> Val:
        l = self.left.eval(ctx)
        r = self.right.eval(ctx)
        out = self._compute(ctx, l.data, r.data)
        if isinstance(out, tuple):
            data, extra = out
            valid = and_valid(ctx, l.valid, r.valid, extra)
        else:
            data = out
            valid = and_valid(ctx, l.valid, r.valid)
        return Val(data, valid)

    def _compute(self, ctx: Ctx, l, r):
        raise NotImplementedError


# ── binding / coercion ──────────────────────────────────────────────────────


def map_child_exprs(e: Expression, f) -> Expression:
    """Rebuild ``e`` with ``f`` applied to each child expression, handling
    plain fields, tuples of expressions, and tuples of expression-pairs
    (CaseWhen branches)."""
    kwargs = {}
    changed = False
    for fld in dataclasses.fields(e):
        v = getattr(e, fld.name)
        if isinstance(v, Expression):
            nv = f(v)
        elif isinstance(v, tuple):
            items = []
            for x in v:
                if isinstance(x, Expression):
                    items.append(f(x))
                elif isinstance(x, tuple):
                    items.append(
                        tuple(f(y) if isinstance(y, Expression) else y for y in x)
                    )
                else:
                    items.append(x)
            nv = tuple(items)
        else:
            nv = v
        kwargs[fld.name] = nv
        if nv is not v:
            changed = True
    return dataclasses.replace(e, **kwargs) if changed else e


def bind(expr: Expression, schema: Schema) -> Expression:
    """Resolve names → ordinals and apply Spark-style type coercion.

    The analogue of ``GpuBindReferences`` + the slice of Catalyst's analyzer
    the reference relies on Spark for.
    """
    from .coercion import coerce  # late import to avoid cycle

    def rec(e: Expression) -> Expression:
        if isinstance(e, UnresolvedAttribute):
            i = schema.index_of(e.name)
            f = schema[i]
            return BoundReference(i, f.data_type, f.nullable)
        if isinstance(e, BoundReference) or isinstance(e, Literal):
            return e
        return coerce(map_child_exprs(e, rec))

    return rec(expr)


def to_expr(v: Union[Expression, int, float, str, bool, None]) -> Expression:
    """Lift python values to literals (DataFrame-API convenience)."""
    if isinstance(v, Expression):
        return v
    from ..types import BOOLEAN, DOUBLE, INT, LONG, NULL, STRING

    if v is None:
        return Literal(None, NULL)
    if isinstance(v, bool):
        return Literal(v, BOOLEAN)
    if isinstance(v, int):
        return Literal(v, INT if -(2**31) <= v < 2**31 else LONG)
    if isinstance(v, float):
        return Literal(v, DOUBLE)
    if isinstance(v, str):
        return Literal(v, STRING)
    import datetime as _dt

    if isinstance(v, _dt.datetime):
        from ..types import TIMESTAMP

        if v.tzinfo is None:  # naive timestamps are UTC in this engine
            v = v.replace(tzinfo=_dt.timezone.utc)
        epoch = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
        return Literal(
            (v - epoch) // _dt.timedelta(microseconds=1), TIMESTAMP
        )
    if isinstance(v, _dt.date):
        from ..types import DATE

        return Literal((v - _dt.date(1970, 1, 1)).days, DATE)
    raise TypeError(f"cannot lift {type(v)} to an expression")
