"""Aggregate functions — reference: org/.../rapids/AggregateFunctions.scala
(CudfAggregate mapping) + aggregate.scala's update/merge two-phase model.

Each aggregate declares, exactly like the reference's ``GpuAggregateFunction``:

* ``update_exprs``   — projections of the input evaluated before the update
* ``buffer_fields``  — the aggregation buffer schema (e.g. Average: sum, count)
* ``update_ops`` / ``merge_ops`` — per-buffer-column segment reductions
  ('sum' | 'min' | 'max' | 'count' | 'first' | 'last'), executed by the
  sort+segment-reduce device kernel (ops/aggregate.py) or the numpy fallback
* ``evaluate(ctx, buffers)`` — final projection from buffer values

Spark result-type rules implemented: sum(integral)=long (wrapping),
sum(float/double)=double, sum(decimal(p,s))=decimal(min(p+10,18),s) under the
DECIMAL64 gate; count=long never-null; avg=double (decimal later); min/max
keep the input type and are null on empty groups.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

from ..types import (
    DOUBLE,
    DataType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegralType,
    LONG,
    NullType,
    StringType,
)
from .base import Ctx, Expression, Literal, Val


@dataclass(frozen=True)
class AggregateFunction(Expression):
    """Base; concrete functions are frozen dataclasses with child exprs."""

    @property
    def update_exprs(self) -> Tuple[Expression, ...]:
        raise NotImplementedError

    @property
    def buffer_types(self) -> Tuple[DataType, ...]:
        raise NotImplementedError

    @property
    def update_ops(self) -> Tuple[str, ...]:
        raise NotImplementedError

    @property
    def merge_ops(self) -> Tuple[str, ...]:
        raise NotImplementedError

    def evaluate(self, ctx: Ctx, buffers: Sequence[Val]) -> Val:
        """Final projection; default: first buffer."""
        return buffers[0]


@dataclass(frozen=True)
class Sum(AggregateFunction):
    child: Expression
    # DISTINCT is planned away before execution (planner._rewrite_distinct:
    # group by keys+child first, then re-aggregate — AggUtils
    # planAggregateWithOneDistinct analogue), so execution never sees it
    distinct: bool = False

    @property
    def data_type(self) -> DataType:
        ct = self.child.data_type
        if isinstance(ct, DecimalType):
            return DecimalType(min(ct.precision + 10, DecimalType.MAX_PRECISION), ct.scale)
        if isinstance(ct, (FloatType, DoubleType)):
            return DOUBLE
        return LONG

    @property
    def update_exprs(self):
        from .cast import Cast

        ct = self.child.data_type
        if self.data_type == ct:
            return (self.child,)
        return (Cast(self.child, self.data_type),)

    @property
    def buffer_types(self):
        return (self.data_type,)

    @property
    def update_ops(self):
        return ("sum",)

    @property
    def merge_ops(self):
        return ("sum",)

    def __str__(self):
        return f"sum({self.child})"


@dataclass(frozen=True)
class Count(AggregateFunction):
    """count(expr) — counts non-null; count(*) via Count(Literal(1))."""

    child: Expression
    distinct: bool = False

    @property
    def data_type(self) -> DataType:
        return LONG

    @property
    def nullable(self) -> bool:
        return False

    @property
    def update_exprs(self):
        return (self.child,)

    @property
    def buffer_types(self):
        return (LONG,)

    @property
    def update_ops(self):
        return ("count",)

    @property
    def merge_ops(self):
        return ("sum",)

    def __str__(self):
        return f"count({self.child})"


@dataclass(frozen=True)
class Min(AggregateFunction):
    child: Expression

    @property
    def data_type(self) -> DataType:
        return self.child.data_type

    @property
    def update_exprs(self):
        return (self.child,)

    @property
    def buffer_types(self):
        return (self.child.data_type,)

    @property
    def update_ops(self):
        return ("min",)

    @property
    def merge_ops(self):
        return ("min",)

    def __str__(self):
        return f"min({self.child})"


@dataclass(frozen=True)
class Max(AggregateFunction):
    child: Expression

    @property
    def data_type(self) -> DataType:
        return self.child.data_type

    @property
    def update_exprs(self):
        return (self.child,)

    @property
    def buffer_types(self):
        return (self.child.data_type,)

    @property
    def update_ops(self):
        return ("max",)

    @property
    def merge_ops(self):
        return ("max",)

    def __str__(self):
        return f"max({self.child})"


@dataclass(frozen=True)
class Average(AggregateFunction):
    child: Expression
    distinct: bool = False

    @property
    def data_type(self) -> DataType:
        return DOUBLE

    @property
    def update_exprs(self):
        from .cast import Cast

        c = self.child
        if c.data_type != DOUBLE:
            c = Cast(c, DOUBLE)
        return (c, self.child)

    @property
    def buffer_types(self):
        return (DOUBLE, LONG)

    @property
    def update_ops(self):
        return ("sum", "count")

    @property
    def merge_ops(self):
        return ("sum", "sum")

    def evaluate(self, ctx: Ctx, buffers: Sequence[Val]) -> Val:
        xp = ctx.xp
        s, c = buffers
        cnt = ctx.broadcast(c.data)
        nz = cnt != 0
        safe = xp.where(nz, cnt, 1)
        data = ctx.broadcast(s.data) / safe
        valid = ctx.broadcast_bool(s.valid) & nz
        return Val(data, valid)

    def __str__(self):
        return f"avg({self.child})"


@dataclass(frozen=True)
class First(AggregateFunction):
    child: Expression
    ignore_nulls: bool = False

    @property
    def data_type(self) -> DataType:
        return self.child.data_type

    @property
    def update_exprs(self):
        return (self.child,)

    @property
    def buffer_types(self):
        return (self.child.data_type,)

    @property
    def update_ops(self):
        return ("first_ignore_nulls" if self.ignore_nulls else "first",)

    @property
    def merge_ops(self):
        return ("first_ignore_nulls" if self.ignore_nulls else "first",)


@dataclass(frozen=True)
class Last(AggregateFunction):
    child: Expression
    ignore_nulls: bool = False

    @property
    def data_type(self) -> DataType:
        return self.child.data_type

    @property
    def update_exprs(self):
        return (self.child,)

    @property
    def buffer_types(self):
        return (self.child.data_type,)

    @property
    def update_ops(self):
        return ("last_ignore_nulls" if self.ignore_nulls else "last",)

    @property
    def merge_ops(self):
        return ("last_ignore_nulls" if self.ignore_nulls else "last",)


@dataclass(frozen=True)
class _CentralMoment(AggregateFunction):
    """Variance/stddev over (count, sum, sum-of-squares) buffers — all plain
    segment reductions, so the same fused device kernel serves them.

    Reference: AggregateFunctions.scala GpuStddevSamp/GpuVariancePop family.
    Spark merges Welford M2 terms; the sum-of-squares formulation here can
    differ from Spark in low-order float bits for ill-conditioned inputs
    (both engines here share it, so the differential harness is exact).
    """

    child: Expression

    sample = False  # n-1 divisor + NaN at n == 1
    sqrt = False

    @property
    def data_type(self) -> DataType:
        return DOUBLE

    @property
    def update_exprs(self):
        from .arithmetic import Multiply
        from .cast import Cast

        c = self.child
        if not isinstance(c.data_type, DoubleType):
            c = Cast(c, DOUBLE)
        return (self.child, c, Multiply(c, c))

    @property
    def buffer_types(self):
        return (LONG, DOUBLE, DOUBLE)

    @property
    def update_ops(self):
        return ("count", "sum", "sum")

    @property
    def merge_ops(self):
        return ("sum", "sum", "sum")

    def evaluate(self, ctx: Ctx, buffers: Sequence[Val]) -> Val:
        xp = ctx.xp
        cnt = ctx.broadcast(buffers[0].data).astype(xp.float64)
        s = ctx.broadcast(buffers[1].data)
        ss = ctx.broadcast(buffers[2].data)
        nz = cnt > 0
        safe_n = xp.where(nz, cnt, 1.0)
        m = s / safe_n
        m2 = ss - s * m  # Σ(x−μ)² up to rounding
        div = (cnt - 1.0) if self.sample else cnt
        safe_div = xp.where(div > 0, div, 1.0)
        var = xp.where(div > 0, m2 / safe_div, xp.nan)
        out = xp.sqrt(xp.maximum(var, 0.0)) if self.sqrt else xp.where(
            xp.isnan(var), var, xp.maximum(var, 0.0)
        )
        return Val(out, nz)

    def __str__(self):
        return f"{type(self).__name__.lower()}({self.child})"


@dataclass(frozen=True)
class VariancePop(_CentralMoment):
    sample = False
    sqrt = False


@dataclass(frozen=True)
class VarianceSamp(_CentralMoment):
    sample = True
    sqrt = False


@dataclass(frozen=True)
class StddevPop(_CentralMoment):
    sample = False
    sqrt = True


@dataclass(frozen=True)
class StddevSamp(_CentralMoment):
    sample = True
    sqrt = True


@dataclass(frozen=True)
class _PairMoment(AggregateFunction):
    """covar_pop / covar_samp / corr over (n, Σx, Σy, Σxy [, Σx², Σy²])
    buffers — plain count/sum segment reductions, so the fused device
    aggregate kernel serves them unchanged. Spark semantics: only rows
    where BOTH operands are non-null contribute (Corr.scala /
    Covariance.scala); the masked update expressions below encode that."""

    x: Expression
    y: Expression

    sample = False
    is_corr = False

    @property
    def data_type(self) -> DataType:
        return DOUBLE

    def _masked(self):
        from .base import Literal
        from .cast import Cast
        from .conditional import If
        from .predicates import And, IsNotNull

        both = And(IsNotNull(self.x), IsNotNull(self.y))
        null = Literal(None, DOUBLE)

        def m(e):
            if not isinstance(e.data_type, DoubleType):
                e = Cast(e, DOUBLE)
            return If(both, e, null)

        return m(self.x), m(self.y)

    @property
    def update_exprs(self):
        from .arithmetic import Multiply

        mx, my = self._masked()
        base = (mx, mx, my, Multiply(mx, my))
        if self.is_corr:
            return base + (Multiply(mx, mx), Multiply(my, my))
        return base

    @property
    def buffer_types(self):
        return (LONG,) + (DOUBLE,) * (5 if self.is_corr else 3)

    @property
    def update_ops(self):
        return ("count",) + ("sum",) * (5 if self.is_corr else 3)

    @property
    def merge_ops(self):
        return ("sum",) * (6 if self.is_corr else 4)

    def evaluate(self, ctx: Ctx, buffers: Sequence[Val]) -> Val:
        xp = ctx.xp
        n = ctx.broadcast(buffers[0].data).astype(xp.float64)
        sx = ctx.broadcast(buffers[1].data)
        sy = ctx.broadcast(buffers[2].data)
        sxy = ctx.broadcast(buffers[3].data)
        nan = xp.float64(float("nan"))
        safe_n = xp.where(n > 0, n, 1.0)
        cxy = sxy / safe_n - (sx / safe_n) * (sy / safe_n)
        if self.is_corr:
            sxx = ctx.broadcast(buffers[4].data)
            syy = ctx.broadcast(buffers[5].data)
            vx = sxx / safe_n - (sx / safe_n) ** 2
            vy = syy / safe_n - (sy / safe_n) ** 2
            # Spark Corr: NaN when either side is constant — selected via
            # where over a SAFE divisor (unguarded 0/0 spews numpy
            # RuntimeWarnings on the CPU engine)
            denom = xp.sqrt(xp.maximum(vx, 0.0) * xp.maximum(vy, 0.0))
            data = xp.where(denom > 0, cxy / xp.where(denom > 0, denom, 1.0), nan)
            valid = n >= 1
        elif self.sample:
            # covar_samp: (Σxy − ΣxΣy/n)/(n−1); NaN at one pair — matching
            # the engine's var_samp/stddev_samp convention (NaN at one
            # sample, null at zero; the _CentralMoment family above)
            data = xp.where(
                n > 1,
                (sxy - sx * sy / safe_n) / xp.where(n > 1, n - 1, 1.0),
                nan,
            )
            valid = n >= 1
        else:
            data = cxy
            valid = n >= 1
        return Val(data.astype(xp.float64), valid)

    def __str__(self):
        name = (
            "corr"
            if self.is_corr
            else ("covar_samp" if self.sample else "covar_pop")
        )
        return f"{name}({self.x}, {self.y})"


@dataclass(frozen=True)
class CovarPop(_PairMoment):
    sample = False


@dataclass(frozen=True)
class CovarSamp(_PairMoment):
    sample = True


@dataclass(frozen=True)
class Corr(_PairMoment):
    is_corr = True


@dataclass(frozen=True)
class CollectList(AggregateFunction):
    """collect_list — gathers non-null values per group into an array
    (reference: AggregateFunctions.scala GpuCollectList). Runs on the CPU
    engine; the planner falls back (TypeSig-style gate in overrides)."""

    child: Expression

    @property
    def data_type(self) -> DataType:
        from ..types import ArrayType

        return ArrayType(self.child.data_type, contains_null=False)

    @property
    def nullable(self) -> bool:
        return False  # empty array, never null (Spark semantics)

    @property
    def update_exprs(self):
        return (self.child,)

    @property
    def buffer_types(self):
        return (self.data_type,)

    @property
    def update_ops(self):
        return ("collect_list",)

    @property
    def merge_ops(self):
        return ("merge_lists",)

    def __str__(self):
        return f"collect_list({self.child})"


@dataclass(frozen=True)
class CollectSet(CollectList):
    """collect_set — collect_list with duplicates removed at evaluation
    (reference: GpuCollectSet)."""

    @property
    def update_ops(self):
        return ("collect_set",)

    @property
    def merge_ops(self):
        return ("merge_sets",)

    def __str__(self):
        return f"collect_set({self.child})"


@dataclass(frozen=True)
class MergeLists(AggregateFunction):
    """Internal: merge partial collect_list arrays into one (Spark's
    Collect merge phase). Produced only by the DISTINCT rewrite when a
    collect aggregate rides along; CPU-only (the device path plans collect
    as a single complete aggregate and never merges lists)."""

    child: Expression

    @property
    def data_type(self) -> DataType:
        return self.child.data_type

    @property
    def nullable(self) -> bool:
        return False

    @property
    def update_exprs(self):
        return (self.child,)

    @property
    def buffer_types(self):
        return (self.data_type,)

    @property
    def update_ops(self):
        return ("merge_lists",)

    @property
    def merge_ops(self):
        return ("merge_lists",)

    def __str__(self):
        return f"merge_lists({self.child})"


@dataclass(frozen=True)
class MergeSets(MergeLists):
    @property
    def update_ops(self):
        return ("merge_sets",)

    @property
    def merge_ops(self):
        return ("merge_sets",)

    def __str__(self):
        return f"merge_sets({self.child})"


def is_aggregate(e: Expression) -> bool:
    if isinstance(e, AggregateFunction):
        return True
    return any(is_aggregate(c) for c in e.children())


def contains_distinct(e: Expression) -> bool:
    if isinstance(e, AggregateFunction) and getattr(e, "distinct", False):
        return True
    return any(contains_distinct(c) for c in e.children())
