"""Type coercion — the slice of Catalyst's analyzer the reference relies on
Spark to run before its planning pass. Inserts Casts so binary operators see
same-type operands, and promotes Divide operands to double (Spark's
``ImplicitTypeCasts``/``DecimalPrecision`` behavior for the supported types).
"""
from __future__ import annotations

import dataclasses

from ..types import (
    DOUBLE,
    BooleanType,
    DataType,
    DecimalType,
    DateType,
    IntegralType,
    NullType,
    NumericType,
    StringType,
    TimestampType,
    numeric_promote,
)
from .arithmetic import Add, Divide, IntegralDivide, Multiply, Pmod, Remainder, Subtract
from .base import Expression, Literal
from .bitwise import BitwiseAnd, BitwiseOr, BitwiseXor
from .cast import Cast
from .nullexprs import Greatest, Least, NaNvl
from .predicates import (
    Comparison,
    EqualNullSafe,
    EqualTo,
    GreaterThan,
    GreaterThanOrEqual,
    In,
    LessThan,
    LessThanOrEqual,
)

_ARITH = (Add, Subtract, Multiply, Remainder, Pmod)
_CMP = (
    EqualTo,
    EqualNullSafe,
    LessThan,
    LessThanOrEqual,
    GreaterThan,
    GreaterThanOrEqual,
)


def _cast_to(e: Expression, dt: DataType) -> Expression:
    if e.data_type == dt:
        return e
    if isinstance(e, Literal) and e.value is None:
        return Literal(None, dt)
    return Cast(e, dt)


def _integral_decimal(dt: DataType) -> DecimalType:
    """Exact-width Decimal(p, 0) of an integral type (Spark's
    DecimalType.forType): byte→3, short→5, int→10, long→19 (capped)."""
    widths = {1: 3, 2: 5, 4: 10, 8: 19}
    p = min(widths[dt.np_dtype.itemsize], DecimalType.MAX_PRECISION)
    return DecimalType(p, 0)


def _common_type(a: DataType, b: DataType) -> DataType:
    if a == b:
        return a
    if isinstance(a, NullType):
        return b
    if isinstance(b, NullType):
        return a
    if isinstance(a, DecimalType) and isinstance(b, DecimalType):
        # Spark DecimalPrecision.widerDecimalType: keep every integral and
        # fractional digit of both sides
        s = max(a.scale, b.scale)
        p = max(a.precision - a.scale, b.precision - b.scale) + s
        return DecimalType(min(p, DecimalType.MAX_PRECISION), s)
    if isinstance(a, DecimalType) and isinstance(b, IntegralType) and not isinstance(b, (DateType, TimestampType)):
        # Spark: integral promotes to decimal of exact width
        p = _integral_decimal(b).precision
        return DecimalType(max(a.precision, min(p + a.scale, DecimalType.MAX_PRECISION)), a.scale)
    if isinstance(b, DecimalType):
        return _common_type(b, a)
    if isinstance(a, NumericType) and isinstance(b, NumericType) and not isinstance(
        a, (DateType, TimestampType)
    ) and not isinstance(b, (DateType, TimestampType)):
        return numeric_promote(a, b)
    if isinstance(a, StringType) and isinstance(b, NumericType):
        return DOUBLE
    if isinstance(b, StringType) and isinstance(a, NumericType):
        return DOUBLE
    raise TypeError(f"cannot find common type for {a} and {b}")


def coerce(e: Expression) -> Expression:
    """Rewrite one (already child-resolved) node with the casts Spark's
    analyzer would insert."""
    from ..types import CalendarInterval, CalendarIntervalType

    if isinstance(e, (Add, Subtract)) and (
        isinstance(e.l.data_type, CalendarIntervalType)
        or isinstance(e.r.data_type, CalendarIntervalType)
    ):
        # analyzer's DateTimeOperations: date/timestamp ± INTERVAL becomes
        # DateAddInterval / TimeAdd (intervals must be literals, like the
        # reference's GpuTimeAdd gate)
        from .datetime import DateAddInterval, TimeAdd

        if isinstance(e.l.data_type, CalendarIntervalType):
            if isinstance(e, Subtract):
                raise TypeError("cannot subtract a date/timestamp from an interval")
            base, itv = e.r, e.l
        else:
            base, itv = e.l, e.r
        if isinstance(e, Subtract):
            if not isinstance(itv, Literal):
                raise TypeError("interval operand must be a literal")
            m, d, us = CalendarInterval(*itv.value)
            itv = Literal(CalendarInterval(-m, -d, -us), itv.data_type)
        if isinstance(base.data_type, DateType):
            return DateAddInterval(base, itv)
        if isinstance(base.data_type, TimestampType):
            return TimeAdd(base, itv)
        raise TypeError(
            f"cannot add an interval to a {base.data_type} operand"
        )
    if isinstance(e, _ARITH) or isinstance(e, _CMP):
        lt, rt = e.l.data_type, e.r.data_type
        if isinstance(e, Multiply) and (
            isinstance(lt, DecimalType) or isinstance(rt, DecimalType)
        ):
            # Spark multiplies decimals at their ORIGINAL types (result
            # p1+p2+1, s1+s2); widening to a common type first would
            # inflate the result precision past what Spark produces. An
            # integral operand is promoted to its exact-width Decimal(p,0)
            # only; fractional operands fall through to the double path.
            def _exact(side, dt):
                if isinstance(dt, DecimalType):
                    return side
                if isinstance(dt, IntegralType) and not isinstance(
                    dt, (DateType, TimestampType)
                ):
                    return _cast_to(side, _integral_decimal(dt))
                return None

            nl, nr = _exact(e.l, lt), _exact(e.r, rt)
            if nl is not None and nr is not None:
                return dataclasses.replace(e, l=nl, r=nr)
        if lt == rt and not isinstance(lt, NullType):
            return e
        ct = _common_type(lt, rt)
        return dataclasses.replace(e, l=_cast_to(e.l, ct), r=_cast_to(e.r, ct))
    if isinstance(e, (Divide, IntegralDivide)):
        lt, rt = e.l.data_type, e.r.data_type
        if isinstance(lt, DecimalType) or isinstance(rt, DecimalType):
            ct = _common_type(lt, rt)
            return dataclasses.replace(e, l=_cast_to(e.l, ct), r=_cast_to(e.r, ct))
        # Spark: Divide on anything non-decimal runs on double
        return dataclasses.replace(e, l=_cast_to(e.l, DOUBLE), r=_cast_to(e.r, DOUBLE))
    if isinstance(e, (BitwiseAnd, BitwiseOr, BitwiseXor)):
        lt, rt = e.l.data_type, e.r.data_type
        if lt == rt:
            return e
        ct = _common_type(lt, rt)
        return dataclasses.replace(e, l=_cast_to(e.l, ct), r=_cast_to(e.r, ct))
    if isinstance(e, (Greatest, Least)):
        ct = e.exprs[0].data_type
        for v in e.exprs[1:]:
            if not isinstance(v.data_type, NullType):
                ct = _common_type(ct, v.data_type) if not isinstance(ct, NullType) else v.data_type
        return dataclasses.replace(e, exprs=tuple(_cast_to(v, ct) for v in e.exprs))
    if isinstance(e, NaNvl):
        # Spark keeps the operands' common fractional type (float stays float)
        lt, rt = e.l.data_type, e.r.data_type
        ct = lt if lt == rt else _common_type(lt, rt)
        if not isinstance(ct, (NullType,)):
            return dataclasses.replace(e, l=_cast_to(e.l, ct), r=_cast_to(e.r, ct))
        return e
    if isinstance(e, In):
        ct = e.c.data_type
        for v in e.values:
            if not isinstance(v.data_type, NullType):
                ct = _common_type(ct, v.data_type)
        return dataclasses.replace(
            e,
            c=_cast_to(e.c, ct),
            values=tuple(_cast_to(v, ct) for v in e.values),
        )
    from .complex import CreateArray, UnresolvedExtractValue

    if isinstance(e, UnresolvedExtractValue):
        return e.resolve()  # struct field / array index / map key dispatch
    if isinstance(e, CreateArray) and e.items:
        ct = e.items[0].data_type
        for v in e.items[1:]:
            if not isinstance(v.data_type, NullType):
                ct = (
                    v.data_type
                    if isinstance(ct, NullType)
                    else _common_type(ct, v.data_type)
                )
        return dataclasses.replace(e, items=tuple(_cast_to(v, ct) for v in e.items))
    return e
