"""ctypes loader for the native host data plane (``native/srt_host.cc``).

The reference's host runtime rides native code (cuDF JCudfSerialization,
RMM, the pinned-pool sub-allocator); this package is the TPU build's
equivalent seam. The shared library is auto-built with ``g++`` on first
import (cached by source mtime) and every entry point has a pure-python
fallback, so the engine never *requires* the toolchain — ``available()``
says which plane is active, and ``spark.rapids.native.enabled`` gates it.

Exposed planes:

* :func:`murmur3_*` — Spark-exact columnar murmur3 (HashFunctions.scala
  semantics; differential-tested against ``ops/hash.py``'s numpy kernels).
* :class:`AddressSpaceAllocator` — best-fit arena sub-allocation
  (AddressSpaceAllocator.scala:22) for host staging pools.
* :func:`frame_pack` / :func:`frame_unpack` — contiguous multi-buffer
  frames, the spill/shuffle "one buffer" currency
  (GpuColumnVectorFromBuffer.java / JCudfSerialization).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "native", "srt_host.cc")
_LIB = os.path.join(_REPO, "native", "build", "libsrt_host.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    """Compile to a process-unique temp path then os.rename into place —
    atomic on the same filesystem, so concurrent first-use builds from
    multiple worker processes can never load a half-written .so."""
    try:
        os.makedirs(os.path.dirname(_LIB), exist_ok=True)
        tmp = f"{_LIB}.{os.getpid()}.tmp"
        subprocess.run(
            ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-o", tmp, _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.rename(tmp, _LIB)
        return True
    except Exception:
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64 = ctypes.c_int64
    lib.srt_version.restype = ctypes.c_int32
    for name, args in (
        ("srt_mm3_i32", [i32p, u8p, u32p, i64]),
        ("srt_mm3_i64", [ctypes.POINTER(ctypes.c_int64), u8p, u32p, i64]),
        ("srt_mm3_bool", [u8p, u8p, u32p, i64]),
        ("srt_mm3_f32", [ctypes.POINTER(ctypes.c_float), u8p, u32p, i64]),
        ("srt_mm3_f64", [ctypes.POINTER(ctypes.c_double), u8p, u32p, i64]),
        ("srt_mm3_bytes", [u8p, i32p, u8p, u32p, i64, i64]),
        ("srt_pmod_i32", [i32p, i32p, i64, ctypes.c_int32]),
    ):
        fn = getattr(lib, name)
        fn.argtypes = args
        fn.restype = None
    lib.srt_asa_create.argtypes = [ctypes.c_uint64]
    lib.srt_asa_create.restype = ctypes.c_void_p
    lib.srt_asa_destroy.argtypes = [ctypes.c_void_p]
    lib.srt_asa_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.srt_asa_alloc.restype = ctypes.c_int64
    lib.srt_asa_free.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.srt_asa_free.restype = ctypes.c_int64
    lib.srt_asa_allocated.argtypes = [ctypes.c_void_p]
    lib.srt_asa_allocated.restype = ctypes.c_uint64
    lib.srt_asa_available.argtypes = [ctypes.c_void_p]
    lib.srt_asa_available.restype = ctypes.c_uint64
    lib.srt_asa_largest_free.argtypes = [ctypes.c_void_p]
    lib.srt_asa_largest_free.restype = ctypes.c_int64
    lib.srt_frame_size.argtypes = [u64p, ctypes.c_int32]
    lib.srt_frame_size.restype = ctypes.c_int64
    lib.srt_frame_pack.argtypes = [
        ctypes.POINTER(u8p), u64p, ctypes.c_int32, u8p, ctypes.c_uint64,
    ]
    lib.srt_frame_pack.restype = ctypes.c_int64
    lib.srt_frame_count.argtypes = [u8p, ctypes.c_uint64]
    lib.srt_frame_count.restype = ctypes.c_int32
    lib.srt_frame_unpack.argtypes = [u8p, ctypes.c_uint64, u64p, u64p, ctypes.c_int32]
    lib.srt_frame_unpack.restype = ctypes.c_int32
    return lib


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("SRT_NATIVE_DISABLE"):
            return None
        stale = not os.path.exists(_LIB) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
        )
        if stale and not _build():
            return None
        try:
            _lib = _bind(ctypes.CDLL(_LIB))
        except OSError:
            _lib = None
        return _lib


_enabled = True


def set_enabled(flag: bool) -> None:
    """Session-level gate (``spark.rapids.native.enabled``)."""
    global _enabled
    _enabled = bool(flag)


def available() -> bool:
    return _enabled and _load() is not None


# ---------------------------------------------------------------------------
# murmur3
# ---------------------------------------------------------------------------

def _vp(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def _valid_ptr(valid: Optional[np.ndarray]):
    if valid is None:
        return ctypes.cast(None, ctypes.POINTER(ctypes.c_uint8)), None
    v = np.ascontiguousarray(np.asarray(valid), dtype=np.uint8)
    return v.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), v


def murmur3_update(dtype_kind: str, data: np.ndarray,
                   valid: Optional[np.ndarray], h: np.ndarray,
                   lengths: Optional[np.ndarray] = None) -> None:
    """In-place update of the running row-hash ``h`` (uint32[n], contiguous)
    with one column. ``dtype_kind`` ∈ {i32,i64,bool,f32,f64,bytes}; for
    ``bytes`` ``data`` is padded ``[n, width]`` u8 with ``lengths``."""
    lib = _load()
    assert lib is not None
    n = h.shape[0]
    vp, keep = _valid_ptr(valid)  # noqa: F841 - keep alive through call
    hp = _vp(h, ctypes.c_uint32)
    if dtype_kind == "i32":
        lib.srt_mm3_i32(_vp(data, ctypes.c_int32), vp, hp, n)
    elif dtype_kind == "i64":
        lib.srt_mm3_i64(_vp(data, ctypes.c_int64), vp, hp, n)
    elif dtype_kind == "bool":
        lib.srt_mm3_bool(_vp(data, ctypes.c_uint8), vp, hp, n)
    elif dtype_kind == "f32":
        lib.srt_mm3_f32(_vp(data, ctypes.c_float), vp, hp, n)
    elif dtype_kind == "f64":
        lib.srt_mm3_f64(_vp(data, ctypes.c_double), vp, hp, n)
    elif dtype_kind == "bytes":
        assert lengths is not None and data.ndim == 2
        lib.srt_mm3_bytes(
            _vp(data, ctypes.c_uint8), _vp(lengths, ctypes.c_int32), vp, hp,
            n, data.shape[1],
        )
    else:  # pragma: no cover
        raise ValueError(dtype_kind)


def pmod(h_i32: np.ndarray, num_partitions: int) -> np.ndarray:
    lib = _load()
    assert lib is not None
    h = np.ascontiguousarray(h_i32, dtype=np.int32)
    out = np.empty_like(h)
    lib.srt_pmod_i32(
        _vp(h, ctypes.c_int32), _vp(out, ctypes.c_int32), h.shape[0],
        num_partitions,
    )
    return out


# ---------------------------------------------------------------------------
# address-space allocator
# ---------------------------------------------------------------------------

class AddressSpaceAllocator:
    """Best-fit offset allocator over an arena of ``size`` bytes (native;
    AddressSpaceAllocator.scala:22). ``alloc`` returns an offset or None."""

    def __init__(self, size: int):
        lib = _load()
        assert lib is not None
        self._lib = lib
        self._h = lib.srt_asa_create(size)
        if not self._h:  # pragma: no cover - allocation failure
            raise MemoryError("srt_asa_create failed")
        self.size = size

    def alloc(self, size: int) -> Optional[int]:
        off = self._lib.srt_asa_alloc(self._h, size)
        return None if off < 0 else int(off)

    def free(self, offset: int) -> int:
        n = self._lib.srt_asa_free(self._h, offset)
        if n < 0:
            raise ValueError(f"free of unallocated offset {offset}")
        return int(n)

    @property
    def allocated(self) -> int:
        return int(self._lib.srt_asa_allocated(self._h))

    @property
    def available(self) -> int:
        return int(self._lib.srt_asa_available(self._h))

    @property
    def largest_free(self) -> int:
        return int(self._lib.srt_asa_largest_free(self._h))

    def close(self):
        if self._h:
            self._lib.srt_asa_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# contiguous frames
# ---------------------------------------------------------------------------

def _as_u8_arrays(buffers: Sequence) -> List[np.ndarray]:
    """Normalize bytes / memoryview / contiguous ndarray buffers to flat
    uint8 arrays — the single definition both frame_pack and frame_write
    layer on (layouts must stay byte-identical)."""
    arrs = []
    for b in buffers:
        if isinstance(b, np.ndarray):
            a = np.ascontiguousarray(b).reshape(-1)
            arrs.append(a.view(np.uint8) if a.size else np.empty(0, np.uint8))
        else:
            arrs.append(
                np.frombuffer(b, dtype=np.uint8) if len(b) else np.empty(0, np.uint8)
            )
    return arrs


def frame_pack(buffers: Sequence) -> memoryview:
    """Pack buffers (bytes / memoryview / contiguous ndarray) into one
    contiguous frame (8-byte-aligned payloads). Returns a zero-copy view
    of the frame."""
    lib = _load()
    assert lib is not None
    n = len(buffers)
    arrs = _as_u8_arrays(buffers)
    lens = np.asarray([a.shape[0] for a in arrs], dtype=np.uint64)
    lens_p = _vp(lens, ctypes.c_uint64)
    total = lib.srt_frame_size(lens_p, n)
    out = np.empty(int(total), dtype=np.uint8)
    ptrs = (ctypes.POINTER(ctypes.c_uint8) * n)(
        *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)) for a in arrs]
    )
    wrote = lib.srt_frame_pack(ptrs, lens_p, n, _vp(out, ctypes.c_uint8), total)
    assert wrote == total, (wrote, total)
    # return the backing array, NOT .tobytes(): the spill path writes the
    # frame straight to disk, and a bytes copy would transiently double host
    # memory exactly when memory is short
    return out.data


def frame_write(fobj, buffers: Sequence) -> int:
    """Stream buffers to a file in the exact ``srt_frame_pack`` layout
    WITHOUT materializing the whole frame — the spill path runs under host
    memory pressure, where a full-frame copy would transiently double the
    buffer being shed. Returns bytes written."""
    arrs = _as_u8_arrays(buffers)
    n = len(arrs)
    lens = np.asarray([a.shape[0] for a in arrs], dtype=np.uint64)
    import struct

    fobj.write(struct.pack("<IIII", 0x46545253, 1, n, 0))
    fobj.write(lens.tobytes())
    off = 16 + 8 * n
    for a in arrs:
        pad = (-off) % 8
        if pad:
            fobj.write(b"\x00" * pad)
            off += pad
        if a.shape[0]:
            fobj.write(memoryview(a))
        off += a.shape[0]
    return off


def frame_unpack(data: bytes) -> List[memoryview]:
    """Unpack a frame into zero-copy views over ``data``."""
    lib = _load()
    assert lib is not None
    arr = np.frombuffer(data, dtype=np.uint8)
    n = lib.srt_frame_count(_vp(arr, ctypes.c_uint8), arr.shape[0])
    if n < 0:
        raise ValueError("malformed srt frame")
    offs = np.empty(n, dtype=np.uint64)
    lens = np.empty(n, dtype=np.uint64)
    rc = lib.srt_frame_unpack(
        _vp(arr, ctypes.c_uint8), arr.shape[0], _vp(offs, ctypes.c_uint64),
        _vp(lens, ctypes.c_uint64), n,
    )
    if rc != 0:
        raise ValueError("malformed srt frame")
    mv = memoryview(data)
    return [mv[int(o) : int(o) + int(l)] for o, l in zip(offs, lens)]


# ---------------------------------------------------------------------------
# row materialization (native/srt_rows.cc — CudfUnsafeRow.java:399 analogue)

_ROWS_SRC = os.path.join(_REPO, "native", "srt_rows.cc")
_ROWS_LIB = os.path.join(_REPO, "native", "build", "srt_rows.so")
_rows_mod = None
_rows_tried = False


def _build_rows() -> bool:
    import sysconfig

    inc = sysconfig.get_paths()["include"]
    try:
        os.makedirs(os.path.dirname(_ROWS_LIB), exist_ok=True)
        tmp = f"{_ROWS_LIB}.{os.getpid()}.tmp"
        subprocess.run(
            ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", f"-I{inc}",
             "-o", tmp, _ROWS_SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.rename(tmp, _ROWS_LIB)
        return True
    except Exception:
        return False


def _load_rows():
    global _rows_mod, _rows_tried
    if _rows_mod is not None or _rows_tried:
        return _rows_mod
    with _lock:
        if _rows_mod is not None or _rows_tried:
            return _rows_mod
        _rows_tried = True
        if os.environ.get("SRT_NATIVE_DISABLE"):
            return None
        stale = not os.path.exists(_ROWS_LIB) or (
            os.path.exists(_ROWS_SRC)
            and os.path.getmtime(_ROWS_SRC) > os.path.getmtime(_ROWS_LIB)
        )
        if stale and not _build_rows():
            return None
        try:
            import importlib.machinery
            import importlib.util

            loader = importlib.machinery.ExtensionFileLoader(
                "srt_rows", _ROWS_LIB
            )
            spec = importlib.util.spec_from_file_location(
                "srt_rows", _ROWS_LIB, loader=loader
            )
            mod = importlib.util.module_from_spec(spec)
            loader.exec_module(mod)
            _rows_mod = mod
        except Exception:
            _rows_mod = None
        return _rows_mod


_ROWS_PRIM = {
    "int8": "i8", "int16": "i16", "int32": "i32", "int64": "i64",
    "float": "f32", "double": "f64",
}


def rows_decode(table) -> Optional[list]:
    """``collect()``'s row materialization: one C pass assembles the row
    tuples from columnar buffers (primitives/strings zero-copy; other
    types pre-converted per column). Returns None when the extension is
    unavailable so the caller keeps its pure-python path."""
    if not _enabled:
        return None
    mod = _load_rows()
    if mod is None:
        return None
    n = table.num_rows
    specs = []
    try:
        _build_specs(table, specs, n)
    except Exception:
        return None  # contract: fall back to the pure-python path
    try:
        return mod.decode(specs, n)
    except Exception:
        return None


def _build_specs(table, specs, n):
    import pyarrow as pa
    import pyarrow.compute as pc

    for col in table.columns:
        a = col.combine_chunks() if col.num_chunks != 1 else col.chunk(0)
        if isinstance(a, pa.ChunkedArray):  # zero chunks (empty table)
            a = pa.concat_arrays([c for c in a.chunks]) if a.num_chunks else (
                pa.array([], type=a.type)
            )
        t = a.type
        valid = None
        if a.null_count:
            valid = np.ascontiguousarray(
                pc.is_valid(a).to_numpy(zero_copy_only=False)
            ).view(np.uint8)
        if str(t) in _ROWS_PRIM:
            # raw data buffer, never to_numpy: a nullable int64 column
            # would round-trip through float64 there and corrupt values
            # beyond 2**53 (null slots hold garbage but sit under `valid`)
            want = {"i8": np.int8, "i16": np.int16, "i32": np.int32,
                    "i64": np.int64, "f32": np.float32, "f64": np.float64}[
                        _ROWS_PRIM[str(t)]]
            buf = a.buffers()[1]
            data = (
                np.frombuffer(buf, dtype=want, count=n + a.offset)[a.offset:]
                if buf is not None and n
                else np.zeros(n, dtype=want)
            )
            specs.append((_ROWS_PRIM[str(t)], data, valid, None, None))
        elif t == pa.bool_():
            data = np.ascontiguousarray(
                a.to_numpy(zero_copy_only=False)
            )
            if data.dtype == object:
                data = np.asarray(
                    [bool(x) if x is not None else False for x in data]
                )
            specs.append(("bool", data.view(np.uint8), valid, None, None))
        elif t in (pa.string(), pa.large_string()):
            if t == pa.large_string():
                a = a.cast(pa.string())
            bufs = a.buffers()
            offsets = np.frombuffer(
                bufs[1], dtype=np.int32,
                count=n + 1 + a.offset,
            )[a.offset:].astype(np.int64)
            data = np.frombuffer(bufs[2], dtype=np.uint8) if bufs[2] else (
                np.zeros(0, dtype=np.uint8)
            )
            specs.append(("str", data, valid, offsets, None))
        else:
            specs.append(("obj", None, None, None, a.to_pylist()))
