"""Static metric-catalog lint — the PR-9 entry-point shim.

The check itself now lives in the graft-lint framework as the ``metrics``
pass (``analysis/passes/metrics.py``): every LITERAL metric name passed
to a GLOBAL-registry accessor (``counter``/``timer``/``gauge``/
``watermark``/``histogram``/``get_or_create`` on a known GLOBAL alias —
module aliases ``GLOBAL``/``_M``/``_obs``/``_GLOBAL_METRICS``/
``obs_metrics.GLOBAL``/``metrics.GLOBAL``) must be in
``obs.metrics.CATALOG``; every f-string name must start with a declared
dynamic-family prefix; every ``dynamic_name("<prefix>", …)`` call must
use a declared prefix. Per-operator metrics (``Exec.metric``) live on
plan instances, not the process registry, and are out of scope.

This module keeps the PR-9 entry points working unchanged:
``python -m spark_rapids_tpu.metrics_lint`` / ``make metrics-lint`` /
``tests/test_metrics_lint.py`` — all thin wrappers over the framework
(which also runs the pass inside ``make lint`` and tier-1's
tests/test_analysis.py meta-test).
"""
from __future__ import annotations

import os
import sys
from typing import List


def lint(root: str) -> List[str]:
    """Run the metrics pass standalone; returns rendered findings
    (inline ``# graft: ok(metrics: …)`` suppressions are honored, like
    the full framework run)."""
    from .analysis import Project, load_baseline, run_passes
    from .analysis import default_baseline_path
    from .analysis.passes.metrics import PASS

    project = Project.load(root)
    result = run_passes(
        project, [PASS], baseline=load_baseline(default_baseline_path(root))
    )
    return [f.render() for f in result.findings]


def main(argv=None) -> int:
    root = (argv or sys.argv[1:] or ["."])[0]
    findings = lint(os.path.abspath(root))
    if findings:
        print(f"metrics-lint: {len(findings)} catalog-drift finding(s):")
        for f in findings:
            print("  " + f)
        return 1
    print("metrics-lint: every emitted metric name is catalogued")
    return 0


if __name__ == "__main__":
    sys.exit(main())
