"""Static metric-catalog lint — the docs_gen-style drift check for the
metric registry.

The process-wide registry (``obs/metrics.py GLOBAL``) is pre-registered
from ``CATALOG`` so exporters always emit the full series set and
``docs/observability.md`` can document it. Nothing enforced that, though:
a call site minting ``GLOBAL.counter("kernel.newThing")`` silently grows
an uncatalogued series that scrapes see but docs and dashboards don't —
catalog drift. This lint closes the loop statically:

- every LITERAL metric name passed to a GLOBAL-registry accessor
  (``counter``/``timer``/``gauge``/``watermark``/``histogram``/
  ``get_or_create`` on a known GLOBAL alias) must be in ``CATALOG``;
- every f-string metric name must start with a declared dynamic-family
  prefix (``metrics.DYNAMIC_PREFIXES`` — the slug-capped families);
- every ``dynamic_name("<prefix>", …)`` call must use a declared prefix.

Per-operator metrics (``Exec.metric`` — numInputRows, opTime, pipe*) live
on plan instances, not the process registry, and are out of scope here.

Run: ``python -m spark_rapids_tpu.metrics_lint`` (or ``make
metrics-lint``; the tier-1 suite runs it via tests/test_metrics_lint.py).
Exit code 1 on drift, with file:line per finding.
"""
from __future__ import annotations

import os
import re
import sys
from typing import List, Tuple

#: receiver spellings that mean "the process-wide GLOBAL registry" at the
#: project's call sites (module aliases included)
_RECEIVERS = (
    r"GLOBAL",
    r"_M",
    r"_obs",
    r"_GLOBAL_METRICS",
    r"obs_metrics\.GLOBAL",
    r"metrics\.GLOBAL",
)

_KINDS = r"(?:counter|timer|gauge|watermark|histogram|get_or_create)"

_LITERAL_CALL = re.compile(
    r"(?:^|[^\w.])(?:" + "|".join(_RECEIVERS) + r")\s*\.\s*" + _KINDS
    + r"\(\s*([frbu]{0,2})([\"'])((?:[^\"'\\]|\\.)*?)\2",
    re.MULTILINE,
)

_DYNAMIC_NAME_CALL = re.compile(
    r"\bdynamic_name\(\s*([\"'])((?:[^\"'\\]|\\.)*?)\1",
    re.MULTILINE,
)


def _iter_source_files(root: str):
    pkg = os.path.join(root, "spark_rapids_tpu")
    for base, _dirs, files in os.walk(pkg):
        for f in files:
            if f.endswith(".py"):
                yield os.path.join(base, f)
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        yield bench


def lint(root: str) -> List[str]:
    from .obs import metrics as OM

    catalog = {name for name, _kind, _doc in OM.CATALOG}
    dynamic = tuple(OM.DYNAMIC_PREFIXES)
    findings: List[str] = []
    self_path = os.path.join("spark_rapids_tpu", "obs", "metrics.py")

    def check_name(path: str, lineno: int, prefixes: Tuple[str, ...],
                   name: str, is_fstring: bool) -> None:
        if is_fstring:
            static_prefix = name.split("{", 1)[0]
            if not any(static_prefix.startswith(p) or p.startswith(static_prefix)
                       for p in prefixes):
                findings.append(
                    f"{path}:{lineno}: dynamic metric name f\"{name}\" does "
                    "not match any declared dynamic-family prefix "
                    "(obs.metrics.DYNAMIC_PREFIXES) — route it through "
                    "dynamic_name() with a declared prefix"
                )
            return
        if name not in catalog:
            findings.append(
                f"{path}:{lineno}: metric {name!r} is not pre-registered in "
                "the GLOBAL catalog (obs.metrics.CATALOG) — add it there so "
                "exports, docs, and dashboards see the series"
            )

    skip = (self_path, os.path.join("spark_rapids_tpu", "metrics_lint.py"))
    for path in _iter_source_files(root):
        rel = os.path.relpath(path, root)
        if rel.endswith(skip):
            continue  # the catalog itself + this lint's own docstring
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
        for m in _LITERAL_CALL.finditer(text):
            lineno = text.count("\n", 0, m.start()) + 1
            check_name(rel, lineno, dynamic, m.group(3),
                       is_fstring="f" in m.group(1))
        for m in _DYNAMIC_NAME_CALL.finditer(text):
            lineno = text.count("\n", 0, m.start()) + 1
            prefix = m.group(2)
            if prefix not in dynamic:
                findings.append(
                    f"{rel}:{lineno}: dynamic_name prefix {prefix!r} "
                    "is not declared in obs.metrics.DYNAMIC_PREFIXES"
                )
    return findings


def main(argv=None) -> int:
    root = (argv or sys.argv[1:] or ["."])[0]
    findings = lint(os.path.abspath(root))
    if findings:
        print(f"metrics-lint: {len(findings)} catalog-drift finding(s):")
        for f in findings:
            print("  " + f)
        return 1
    print("metrics-lint: every emitted metric name is catalogued")
    return 0


if __name__ == "__main__":
    sys.exit(main())
