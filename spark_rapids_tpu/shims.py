"""Version-compatibility seam — the SparkShims trait (L8).

Reference: SparkShims.scala (sql-plugin:73-200, ~60 methods abstracting
cross-version Spark behavior), ShimLoader.scala:26 (ServiceLoader discovery
of the provider matching the runtime version), shims/spark30X modules.

Standalone there is no host Spark, but the seam is load-bearing in the
design (SURVEY §1 L8: "keep the trait"): every Spark-version-dependent
SEMANTIC this engine implements routes through a shim method, so targeting
another Spark version is one subclass, not a code audit. The session
selects the shim from ``spark.rapids.tpu.sparkVersion``.
"""
from __future__ import annotations


class SparkShim:
    """Behavior knobs that differ across Spark versions."""

    version = "3.1"

    # Spark 3.0/3.1 default ANSI off; a 4.x shim would flip this
    def ansi_default(self) -> bool:
        return False

    # Spark 3.x: adaptive execution default off in 3.0/3.1, on in 3.2+
    def adaptive_default(self) -> bool:
        return False

    # CSV nullValue default (constant across 3.x; here for completeness)
    def csv_null_value(self) -> str:
        return ""

    # proleptic Gregorian parsing: 3.x uses the strict DateTimeFormatter
    # grammar (invalid dates → null); a 2.4 shim would be lenient
    def strict_date_parsing(self) -> bool:
        return True

    # decimal64 cap (DECIMAL128 arrives with newer plugin generations)
    def max_decimal_precision(self) -> int:
        return 18

    # spark.sql.parquet.datetimeRebaseModeInWrite default: writing dates
    # before the Gregorian cutover needs julian rebase the engine does not
    # perform — EXCEPTION refuses them loudly (Spark 3.1/3.2 default;
    # reference RebaseHelper.scala). CORRECTED writes proleptic values
    # as-is (newer defaults).
    def parquet_rebase_write(self) -> str:
        return "EXCEPTION"


class Spark311Shim(SparkShim):
    version = "3.1"


class Spark320Shim(SparkShim):
    version = "3.2"

    def adaptive_default(self) -> bool:
        return True


class Spark330Shim(Spark320Shim):
    version = "3.3"

    def parquet_rebase_write(self) -> str:
        return "CORRECTED"


_PROVIDERS = {s.version: s for s in (Spark311Shim, Spark320Shim, Spark330Shim)}


def get_shim(version: str | None) -> SparkShim:
    """ShimLoader.getSparkShims analogue: match the configured version
    prefix against registered providers; unknown versions fail loudly like
    the reference's 'no shim for version' error."""
    if not version:
        return Spark311Shim()
    for v, cls in sorted(_PROVIDERS.items(), reverse=True):
        if version.startswith(v):
            return cls()
    raise ValueError(
        f"no shim provider for Spark version {version!r} "
        f"(available: {sorted(_PROVIDERS)})"
    )
