"""Device-resident columnar data — the ``GpuColumnVector``/``ColumnarBatch``
layer re-designed for TPU/XLA.

Reference analogue: sql-plugin GpuColumnVector.java (cudf ColumnVector wrapper,
Table<->batch converters :550-582, type map :476) and the batch currency that
every GpuExec operator streams. Here a column is a pytree of JAX arrays in
Arrow layout:

* fixed-width types: ``data``: ``dtype[capacity]``, ``validity``: ``bool[capacity]``
* strings: ``data``: ``uint8[capacity, width]`` (padded bytes), ``lengths``:
  ``int32[capacity]``, ``validity`` — a fixed-width design chosen for the MXU/
  VPU's static-shape world instead of cudf's offsets+chars, with ``width``
  bucketed to a power of two to bound recompilation.

Key TPU-first departures from the reference:

* **Static shapes**: every batch has a power-of-two ``capacity``; live rows are
  prefix-compacted ``[0, num_rows)`` and ``num_rows`` is a *device* scalar so
  pipelines (filter -> project -> partial agg) run with zero host syncs.
  ``DeviceBatch.row_count()`` syncs on demand at operator boundaries only.
* **jit caching**: kernels are plain jitted functions of these pytrees; the
  (tree structure, shapes, dtypes) tuple is the compile cache key — the
  analogue of cudf's pre-compiled kernel library.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from ..types import (
    DataType,
    DecimalType,
    NullType,
    Schema,
    StringType,
    StructField,
    from_arrow,
)

MIN_CAPACITY = 8
MIN_STR_WIDTH = 8


def bucket_capacity(n: int) -> int:
    """Round a row count up to the next power of two (>= MIN_CAPACITY) so the
    number of distinct compiled shapes per schema is logarithmic."""
    cap = MIN_CAPACITY
    while cap < n:
        cap <<= 1
    return cap


def bucket_width(n: int) -> int:
    w = MIN_STR_WIDTH
    while w < n:
        w <<= 1
    return w


def pad_scalar_bytes(raw: bytes) -> tuple[np.ndarray, int]:
    """Encode one byte string into the padded scalar-string device layout:
    (uint8[bucket_width], true length). Shared by string literals and the
    TaskVals file-name channel."""
    w = bucket_width(max(len(raw), 1))
    buf = np.zeros(w, dtype=np.uint8)
    buf[: len(raw)] = np.frombuffer(raw, dtype=np.uint8)
    return buf, len(raw)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DeviceColumn:
    """One column of a device batch. ``dtype`` is static pytree metadata."""

    dtype: DataType
    data: jax.Array  # fixed-width: [cap]; string: uint8[cap, width]
    validity: jax.Array  # bool[cap]
    lengths: Optional[jax.Array] = None  # string only: int32[cap]

    def tree_flatten(self):
        children = (self.data, self.validity, self.lengths)
        return children, self.dtype

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, validity, lengths = children
        return cls(aux, data, validity, lengths)

    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    @property
    def is_string(self) -> bool:
        return isinstance(self.dtype, StringType)

    @property
    def str_width(self) -> int:
        assert self.is_string
        return int(self.data.shape[1])


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DeviceBatch:
    """A batch of columns with a device-resident live-row count.

    Rows ``[0, num_rows)`` are live; padding rows have ``validity == False``
    and zeroed data. ``schema`` is static pytree metadata.
    """

    schema: Schema
    columns: list[DeviceColumn]
    num_rows: jax.Array  # int32 scalar (device)

    def tree_flatten(self):
        return (self.columns, self.num_rows), self.schema

    @classmethod
    def tree_unflatten(cls, aux, children):
        columns, num_rows = children
        return cls(aux, list(columns), num_rows)

    @property
    def capacity(self) -> int:
        if self.columns:
            return self.columns[0].capacity
        return 0

    def row_count(self) -> int:
        """Host-sync the live-row count. Use only at operator boundaries."""
        return int(self.num_rows)

    def row_mask(self) -> jax.Array:
        """bool[capacity] — True for live rows."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.num_rows

    def column(self, i: int) -> DeviceColumn:
        return self.columns[i]

    def with_columns(self, schema: Schema, columns: list[DeviceColumn]) -> "DeviceBatch":
        return DeviceBatch(schema, columns, self.num_rows)

    def size_bytes(self) -> int:
        """Approximate device footprint (for batching goals / spill accounting)."""
        total = 0
        for c in self.columns:
            total += c.data.size * c.data.dtype.itemsize
            total += c.validity.size
            if c.lengths is not None:
                total += c.lengths.size * 4
        return total


# ── Host <-> device transfer (the H2D/D2H seam; reference: GpuColumnVector
#    from(Table)/from(ColumnarBatch) + RapidsHostColumnVector) ───────────────


def _np_from_arrow_fixed(arr: pa.Array, dt: DataType) -> tuple[np.ndarray, np.ndarray]:
    """Arrow fixed-width array → (data ndarray, validity ndarray), nulls
    zeroed. Buffer-view based (no float64 round trip) — see host.np_from_arrow."""
    from .host import np_from_arrow

    return np_from_arrow(arr, dt)


def _string_to_padded(arr: pa.Array, width: Optional[int]) -> tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Arrow string array → (bytes[n, width], lengths[n], validity[n], width)."""
    arr = arr.cast(pa.string())
    n = len(arr)
    valid = ~np.asarray(arr.is_null())
    # Offsets/values buffers give us lengths without python-object round trips.
    buf_offsets = np.frombuffer(arr.buffers()[1], dtype=np.int32)[
        arr.offset : arr.offset + n + 1
    ]
    lengths = (buf_offsets[1:] - buf_offsets[:-1]).astype(np.int32)
    lengths = np.where(valid, lengths, 0).astype(np.int32)
    maxlen = int(lengths.max()) if n else 0
    if width is None:
        width = bucket_width(max(maxlen, 1))
    if maxlen > width:
        raise ValueError(f"string length {maxlen} exceeds device width {width}")
    out = np.zeros((n, width), dtype=np.uint8)
    values = np.frombuffer(arr.buffers()[2], dtype=np.uint8) if arr.buffers()[2] else np.zeros(0, np.uint8)
    # Vectorized ragged copy: gather value bytes into the padded matrix.
    starts = buf_offsets[:-1]
    cols = np.arange(width, dtype=np.int64)[None, :]
    idx = starts.astype(np.int64)[:, None] + cols
    take_mask = cols < lengths[:, None]
    idx = np.where(take_mask, idx, 0)
    if values.size:
        gathered = values[np.clip(idx, 0, values.size - 1)]
        out = np.where(take_mask, gathered, 0).astype(np.uint8)
    return out, lengths, valid, width


def _padded_to_string(data: np.ndarray, lengths: np.ndarray, valid: np.ndarray, n: int) -> pa.Array:
    data, lengths, valid = data[:n], lengths[:n], valid[:n]
    lengths = np.where(valid, lengths, 0).astype(np.int64)
    offsets = np.zeros(n + 1, dtype=np.int32)
    np.cumsum(lengths, out=offsets[1:])
    width = data.shape[1] if data.ndim == 2 else 0
    take = np.arange(width)[None, :] < lengths[:, None]
    values = data[take].astype(np.uint8).tobytes() if n and width else b""
    null_mask = None
    if not valid.all():
        null_mask = pa.array(valid.astype(bool)).buffers()[1]
    return pa.StringArray.from_buffers(
        n, pa.py_buffer(offsets.tobytes()), pa.py_buffer(values), null_mask
    )


def host_to_device(
    rb: pa.RecordBatch,
    capacity: Optional[int] = None,
    str_widths: Optional[dict[int, int]] = None,
) -> DeviceBatch:
    """Arrow RecordBatch (host currency) → DeviceBatch, padded to a bucketed
    capacity. One H2D transfer per buffer; XLA sees static shapes."""
    n = rb.num_rows
    cap = capacity or bucket_capacity(max(n, 1))
    schema = Schema.from_arrow(rb.schema)
    cols: list[DeviceColumn] = []
    for i, field in enumerate(schema):
        arr = rb.column(i)
        if isinstance(arr, pa.ChunkedArray):  # pragma: no cover - RecordBatch cols are flat
            arr = arr.combine_chunks()
        dt = field.data_type
        if isinstance(dt, StringType):
            want = (str_widths or {}).get(i)
            data, lengths, valid, width = _string_to_padded(arr, want)
            pdata = np.zeros((cap, width), dtype=np.uint8)
            pdata[:n] = data
            plen = np.zeros(cap, dtype=np.int32)
            plen[:n] = lengths
            pval = np.zeros(cap, dtype=bool)
            pval[:n] = valid
            cols.append(
                DeviceColumn(dt, jnp.asarray(pdata), jnp.asarray(pval), jnp.asarray(plen))
            )
        elif isinstance(dt, NullType):
            cols.append(
                DeviceColumn(
                    dt,
                    jnp.zeros(cap, dtype=jnp.int8),
                    jnp.zeros(cap, dtype=bool),
                )
            )
        else:
            data, valid = _np_from_arrow_fixed(arr, dt)
            pdata = np.zeros(cap, dtype=dt.np_dtype)
            pdata[:n] = data
            pval = np.zeros(cap, dtype=bool)
            pval[:n] = valid
            cols.append(DeviceColumn(dt, jnp.asarray(pdata), jnp.asarray(pval)))
    return DeviceBatch(schema, cols, jnp.asarray(n, dtype=jnp.int32))


def device_to_host(batch: DeviceBatch) -> pa.RecordBatch:
    """DeviceBatch → Arrow RecordBatch sliced to live rows (single D2H)."""
    n = batch.row_count()
    arrays: list[pa.Array] = []
    fields: list[pa.Field] = []
    for f, col in zip(batch.schema, batch.columns):
        dt = f.data_type
        valid = np.asarray(col.validity)[: max(n, 0)].astype(bool)
        if isinstance(dt, StringType):
            data = np.asarray(col.data)
            lengths = np.asarray(col.lengths)
            arr = _padded_to_string(data, lengths, np.asarray(col.validity), n)
        elif isinstance(dt, NullType):
            arr = pa.nulls(n)
        else:
            data = np.asarray(col.data)[:n]
            if isinstance(dt, DecimalType):
                # data holds unscaled int64; rebuild decimals by value.
                import decimal as _dec

                scale = dt.scale
                py = [
                    None if not v else _dec.Decimal(int(x)).scaleb(-scale)
                    for x, v in zip(data.tolist(), valid.tolist())
                ]
                arr = pa.array(py, type=pa.decimal128(dt.precision, dt.scale))
            else:
                mask = None if valid.all() else ~valid
                arr = pa.array(data, type=dt.to_arrow(), from_pandas=False, mask=mask)
        arrays.append(arr)
        fields.append(pa.field(f.name, dt.to_arrow(), f.nullable))
    return pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields))


def empty_batch(schema: Schema, capacity: int = MIN_CAPACITY) -> DeviceBatch:
    cols = []
    for f in schema:
        dt = f.data_type
        if isinstance(dt, StringType):
            cols.append(
                DeviceColumn(
                    dt,
                    jnp.zeros((capacity, MIN_STR_WIDTH), dtype=jnp.uint8),
                    jnp.zeros(capacity, dtype=bool),
                    jnp.zeros(capacity, dtype=jnp.int32),
                )
            )
        else:
            cols.append(
                DeviceColumn(
                    dt,
                    jnp.zeros(capacity, dtype=dt.np_dtype),
                    jnp.zeros(capacity, dtype=bool),
                )
            )
    return DeviceBatch(schema, cols, jnp.asarray(0, dtype=jnp.int32))
