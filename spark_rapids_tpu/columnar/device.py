"""Device-resident columnar data — the ``GpuColumnVector``/``ColumnarBatch``
layer re-designed for TPU/XLA.

Reference analogue: sql-plugin GpuColumnVector.java (cudf ColumnVector wrapper,
Table<->batch converters :550-582, type map :476) and the batch currency that
every GpuExec operator streams. Here a column is a pytree of JAX arrays in
Arrow layout:

* fixed-width types: ``data``: ``dtype[capacity]``, ``validity``: ``bool[capacity]``
* strings: ``data``: ``uint8[capacity, width]`` (padded bytes), ``lengths``:
  ``int32[capacity]``, ``validity`` — a fixed-width design chosen for the MXU/
  VPU's static-shape world instead of cudf's offsets+chars, with ``width``
  bucketed to a power of two to bound recompilation.

Key TPU-first departures from the reference:

* **Static shapes**: every batch has a power-of-two ``capacity``; live rows are
  prefix-compacted ``[0, num_rows)`` and ``num_rows`` is a *device* scalar so
  pipelines (filter -> project -> partial agg) run with zero host syncs.
  ``DeviceBatch.row_count()`` syncs on demand at operator boundaries only.
* **jit caching**: kernels are plain jitted functions of these pytrees; the
  (tree structure, shapes, dtypes) tuple is the compile cache key — the
  analogue of cudf's pre-compiled kernel library.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from ..types import (
    DataType,
    DecimalType,
    NullType,
    Schema,
    StringType,
    StructField,
    from_arrow,
)

MIN_CAPACITY = 8
MIN_STR_WIDTH = 8


def bucket_capacity(n: int) -> int:
    """Round a row count up to the shape-bucket lattice: the next power of
    two at or above ``kernels.shape_bucket_floor()`` (>= MIN_CAPACITY), so
    the number of distinct compiled shapes per schema is logarithmic AND
    every batch below the floor shares ONE geometry — one cached executable
    serves them all (spark.rapids.tpu.shapeBuckets.*). Padding rows above
    ``num_rows`` are masked inert by the batch invariant."""
    from .. import kernels as K

    cap = K.shape_bucket_floor()
    if cap < MIN_CAPACITY:
        cap = MIN_CAPACITY
    while cap < n:
        cap <<= 1
    return cap


def tight_capacity(n: int) -> int:
    """Round a row count up to the next power of two >= MIN_CAPACITY,
    ignoring the shape-bucket lattice floor. The shrink-to-fit path
    (ops/gather.shrink_one) exists to CUT device footprint before
    non-splittable merges and D2H packing; re-bucketing it to the lattice
    floor would pin tiny batches (13-group partial-aggregate outputs) at
    the ingest geometry and re-inflate exactly the buffers it is meant to
    shrink."""
    cap = MIN_CAPACITY
    while cap < n:
        cap <<= 1
    return cap


def bucket_width(n: int) -> int:
    w = MIN_STR_WIDTH
    while w < n:
        w <<= 1
    return w


def pad_scalar_bytes(raw: bytes) -> tuple[np.ndarray, int]:
    """Encode one byte string into the padded scalar-string device layout:
    (uint8[bucket_width], true length). Shared by string literals and the
    TaskVals file-name channel."""
    w = bucket_width(max(len(raw), 1))
    buf = np.zeros(w, dtype=np.uint8)
    buf[: len(raw)] = np.frombuffer(raw, dtype=np.uint8)
    return buf, len(raw)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DeviceColumn:
    """One column of a device batch. ``dtype`` is static pytree metadata.

    Layouts by type:
    * fixed-width: ``data``: dtype[cap]; ``validity``: bool[cap]
    * string: ``data``: uint8[cap, w]; ``lengths``: int32[cap]
    * array<e>: ``data`` None; ``lengths``: int32[cap] (list sizes);
      ``children`` = (element column,) whose planes carry a second padded
      axis: element data [cap, W(, w)], element validity [cap, W]
    * struct: ``data`` None; ``children`` = per-field columns [cap]
    * map<k,v>: like array with ``children`` = (keys, values) planes
    """

    dtype: DataType
    data: Optional[jax.Array]
    validity: jax.Array  # bool[cap]
    lengths: Optional[jax.Array] = None  # string/array/map: int32[cap]
    children: Optional[tuple] = None  # nested columns (array/struct/map)

    def tree_flatten(self):
        return (self.data, self.validity, self.lengths, self.children), self.dtype

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, validity, lengths, kids = children
        if kids is not None:
            kids = tuple(kids)
        return cls(aux, data, validity, lengths, kids)

    @property
    def capacity(self) -> int:
        if self.data is not None:
            return int(self.data.shape[0])
        return int(self.validity.shape[0])

    @property
    def is_string(self) -> bool:
        return isinstance(self.dtype, StringType)

    @property
    def str_width(self) -> int:
        assert self.is_string
        return int(self.data.shape[1])

    @property
    def list_width(self) -> int:
        """Padded element count per row (array/map columns)."""
        return int(self.children[0].data.shape[1])


def dc_replace(col: DeviceColumn, **kw) -> DeviceColumn:
    """dataclasses.replace for DeviceColumn — the way to rebuild a column
    with a changed field WITHOUT dropping nested children planes."""
    return dataclasses.replace(col, **kw)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DeviceBatch:
    """A batch of columns with a device-resident live-row count.

    Rows ``[0, num_rows)`` are live; padding rows have ``validity == False``
    and zeroed data. ``schema`` is static pytree metadata.
    """

    schema: Schema
    columns: list[DeviceColumn]
    num_rows: jax.Array  # int32 scalar (device)

    def tree_flatten(self):
        return (self.columns, self.num_rows), self.schema

    @classmethod
    def tree_unflatten(cls, aux, children):
        columns, num_rows = children
        return cls(aux, list(columns), num_rows)

    @property
    def capacity(self) -> int:
        if self.columns:
            return self.columns[0].capacity
        return 0

    def row_count(self) -> int:
        """Host-sync the live-row count. Use only at operator boundaries."""
        return int(self.num_rows)

    def row_mask(self) -> jax.Array:
        """bool[capacity] — True for live rows."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.num_rows

    def column(self, i: int) -> DeviceColumn:
        return self.columns[i]

    def by_name(self, name: str) -> DeviceColumn:
        """Column lookup for the ``to_jax()`` export path."""
        return self.columns[self.schema.index_of(name)]

    def with_columns(self, schema: Schema, columns: list[DeviceColumn]) -> "DeviceBatch":
        return DeviceBatch(schema, columns, self.num_rows)

    def size_bytes(self) -> int:
        """Approximate device footprint (for batching goals / spill accounting)."""

        def col_bytes(c) -> int:
            total = 0
            if c.data is not None:
                total += c.data.size * c.data.dtype.itemsize
            total += c.validity.size
            if c.lengths is not None:
                total += c.lengths.size * 4
            if c.children is not None:
                total += sum(col_bytes(k) for k in c.children)
            return total

        return sum(col_bytes(c) for c in self.columns)


# ── Host <-> device transfer (the H2D/D2H seam; reference: GpuColumnVector
#    from(Table)/from(ColumnarBatch) + RapidsHostColumnVector) ───────────────


def _np_from_arrow_fixed(arr: pa.Array, dt: DataType) -> tuple[np.ndarray, np.ndarray]:
    """Arrow fixed-width array → (data ndarray, validity ndarray), nulls
    zeroed. Buffer-view based (no float64 round trip) — see host.np_from_arrow."""
    from .host import np_from_arrow

    return np_from_arrow(arr, dt)


def _string_to_padded(
    arr: pa.Array, width: Optional[int], max_str_bytes: Optional[int] = None
) -> tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Arrow string array → (bytes[n, width], lengths[n], validity[n], width).
    ``max_str_bytes`` (spark.rapids.tpu.string.maxBytes) caps the inferred
    width — longer values raise, surfacing the configured ceiling."""
    arr = arr.cast(pa.string())
    n = len(arr)
    valid = ~np.asarray(arr.is_null())
    # Offsets/values buffers give us lengths without python-object round trips.
    buf_offsets = np.frombuffer(arr.buffers()[1], dtype=np.int32)[
        arr.offset : arr.offset + n + 1
    ]
    lengths = (buf_offsets[1:] - buf_offsets[:-1]).astype(np.int32)
    lengths = np.where(valid, lengths, 0).astype(np.int32)
    maxlen = int(lengths.max()) if n else 0
    if width is None:
        if max_str_bytes is not None and maxlen > max_str_bytes:
            raise ValueError(
                f"string length {maxlen} exceeds "
                f"spark.rapids.tpu.string.maxBytes={max_str_bytes}"
            )
        width = bucket_width(max(maxlen, 1))
    if maxlen > width:
        raise ValueError(f"string length {maxlen} exceeds device width {width}")
    out = np.zeros((n, width), dtype=np.uint8)
    values = np.frombuffer(arr.buffers()[2], dtype=np.uint8) if arr.buffers()[2] else np.zeros(0, np.uint8)
    # Vectorized ragged copy: gather value bytes into the padded matrix.
    starts = buf_offsets[:-1]
    cols = np.arange(width, dtype=np.int64)[None, :]
    idx = starts.astype(np.int64)[:, None] + cols
    take_mask = cols < lengths[:, None]
    idx = np.where(take_mask, idx, 0)
    if values.size:
        gathered = values[np.clip(idx, 0, values.size - 1)]
        out = np.where(take_mask, gathered, 0).astype(np.uint8)
    return out, lengths, valid, width


def _padded_to_string(data: np.ndarray, lengths: np.ndarray, valid: np.ndarray, n: int) -> pa.Array:
    data, lengths, valid = data[:n], lengths[:n], valid[:n]
    lengths = np.where(valid, lengths, 0).astype(np.int64)
    offsets = np.zeros(n + 1, dtype=np.int32)
    np.cumsum(lengths, out=offsets[1:])
    width = data.shape[1] if data.ndim == 2 else 0
    take = np.arange(width)[None, :] < lengths[:, None]
    values = data[take].astype(np.uint8).tobytes() if n and width else b""
    null_mask = None
    if not valid.all():
        null_mask = pa.array(valid.astype(bool)).buffers()[1]
    return pa.StringArray.from_buffers(
        n, pa.py_buffer(offsets.tobytes()), pa.py_buffer(values), null_mask
    )


def _np_col_from_arrow(
    arr: pa.Array,
    dt: DataType,
    cap: int,
    width: Optional[int] = None,
    max_str_bytes: Optional[int] = None,
) -> DeviceColumn:
    """Arrow array → host-side DeviceColumn (numpy leaves), padded to cap.
    Recursive over array/struct/map nesting."""
    from ..types import ArrayType, MapType, StructType

    n = len(arr)
    if isinstance(dt, StringType):
        data, lengths, valid, w = _string_to_padded(arr, width, max_str_bytes)
        pdata = np.zeros((cap, w), dtype=np.uint8)
        pdata[:n] = data
        plen = np.zeros(cap, dtype=np.int32)
        plen[:n] = lengths
        pval = np.zeros(cap, dtype=bool)
        pval[:n] = valid
        return DeviceColumn(dt, pdata, pval, plen)
    if isinstance(dt, NullType):
        return DeviceColumn(dt, np.zeros(cap, np.int8), np.zeros(cap, bool))
    if isinstance(dt, StructType):
        arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
        pval = np.zeros(cap, dtype=bool)
        pval[:n] = ~np.asarray(arr.is_null())
        kids = tuple(
            _np_col_from_arrow(arr.field(i), f.data_type, cap)
            for i, f in enumerate(dt.fields)
        )
        return DeviceColumn(dt, None, pval, None, kids)
    if isinstance(dt, (ArrayType, MapType)):
        return _np_list_from_arrow(arr, dt, cap)
    data, valid = _np_from_arrow_fixed(arr, dt)
    pdata = np.zeros(cap, dtype=dt.np_dtype)
    pdata[:n] = data
    pval = np.zeros(cap, dtype=bool)
    pval[:n] = valid
    return DeviceColumn(dt, pdata, pval)


def _list_offsets(arr) -> np.ndarray:
    off_buf = arr.buffers()[1]
    off_dt = np.int64 if pa.types.is_large_list(arr.type) else np.int32
    return np.frombuffer(off_buf, dtype=off_dt)[arr.offset : arr.offset + len(arr) + 1]


def _np_list_from_arrow(arr, dt, cap: int) -> DeviceColumn:
    """List/Map arrow array → padded element-plane layout. The element plane
    is built by converting the (flat) child values, then gathering them into
    [cap, W] rows — the strings recipe generalized."""
    from ..types import ArrayType, MapType

    arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    n = len(arr)
    offsets = _list_offsets(arr)
    valid = np.zeros(cap, dtype=bool)
    valid[:n] = ~np.asarray(arr.is_null())
    lengths = np.zeros(cap, dtype=np.int32)
    lengths[:n] = np.where(valid[:n], offsets[1:] - offsets[:-1], 0)
    W = bucket_width(max(int(lengths.max()) if n else 0, 1))

    def plane(values: pa.Array, vdt) -> DeviceColumn:
        # child values carry the parent's slice offset via `offsets`
        vcap = bucket_capacity(max(len(values), 1))
        flat = _np_col_from_arrow(values, vdt, vcap)
        starts = offsets[:-1].astype(np.int64)
        cols_ix = np.arange(W, dtype=np.int64)[None, :]
        idx = np.zeros((cap, W), dtype=np.int64)
        idx[:n] = starts[:, None] + cols_ix
        mask = np.arange(W)[None, :] < lengths[:, None]
        idx = np.where(mask, np.clip(idx, 0, max(len(values) - 1, 0)), 0)
        d = flat.data[idx]  # [cap, W(, w)]
        if d.ndim == 3:
            d = np.where(mask[:, :, None], d, 0)
        else:
            d = np.where(mask, d, 0)
        v = np.where(mask, flat.validity[idx], False)
        ln = None
        if flat.lengths is not None:
            ln = np.where(mask, flat.lengths[idx], 0).astype(np.int32)
        return DeviceColumn(vdt, d, v, ln)

    if isinstance(dt, MapType):
        kids = (plane(arr.keys, dt.key_type), plane(arr.items, dt.value_type))
    else:
        kids = (plane(arr.values, dt.element_type),)
    return DeviceColumn(dt, None, valid, lengths, kids)


def host_to_device(
    rb: pa.RecordBatch,
    capacity: Optional[int] = None,
    str_widths: Optional[dict[int, int]] = None,
    max_str_bytes: Optional[int] = None,
) -> DeviceBatch:
    """Arrow RecordBatch (host currency) → DeviceBatch, padded to a bucketed
    capacity. Every buffer ships in ONE batched ``jax.device_put`` call —
    PJRT coalesces the transfers, so a slow link pays one round trip per
    batch instead of one per buffer. ``max_str_bytes``
    (spark.rapids.tpu.string.maxBytes) caps the padded string width the
    fixed-width layout will materialize."""
    import time as _time

    from ..obs import ledger as _ledger
    from ..obs import metrics as _metrics

    n = rb.num_rows
    cap = capacity or bucket_capacity(max(n, 1))
    schema = Schema.from_arrow(rb.schema)
    host_cols = []
    # padding to the bucketed capacity is host work worth attributing: the
    # shape-bucket lattice trades it for compile reuse, and the ledger's
    # exclusive `pad` phase (carved out of the enclosing h2d scope) is how
    # the trade stays measurable per query
    t0 = _time.perf_counter_ns()
    with _ledger.phase("pad"):
        for i, field in enumerate(schema):
            arr = rb.column(i)
            if isinstance(arr, pa.ChunkedArray):  # pragma: no cover - RecordBatch cols are flat
                arr = arr.combine_chunks()
            host_cols.append(
                _np_col_from_arrow(
                    arr,
                    field.data_type,
                    cap,
                    (str_widths or {}).get(i),
                    max_str_bytes,
                )
            )
    _metrics.GLOBAL.timer("batch.padTimeNs").add(
        _time.perf_counter_ns() - t0
    )
    num_rows, cols = jax.device_put((np.asarray(n, np.int32), host_cols))
    return DeviceBatch(schema, list(cols), num_rows)


def abstract_batch(
    schema: Schema, capacity: int, str_widths: Optional[dict] = None
) -> Optional[DeviceBatch]:
    """DeviceBatch pytree with ``jax.ShapeDtypeStruct`` leaves — the
    abstract input the kernel pre-compilation pass (plan/planner.py
    precompile_plan) lowers kernels against via ``GuardedJit.warm``. The
    treedef and leaf shapes match what ``host_to_device`` produces for the
    same geometry, so the warmed binary is the one the real batch hits.

    Returns None when the schema cannot be shaped statically: nested types
    (their element-plane widths are data-dependent) or a string column
    without a width hint in ``str_widths`` (column index → padded width).
    """
    from ..types import ArrayType, MapType, StructType

    S = jax.ShapeDtypeStruct
    cols = []
    for i, f in enumerate(schema):
        dt = f.data_type
        if isinstance(dt, (ArrayType, MapType, StructType)):
            return None
        if isinstance(dt, StringType):
            w = (str_widths or {}).get(i)
            if not w:
                return None
            cols.append(
                DeviceColumn(
                    dt,
                    S((capacity, int(w)), np.uint8),
                    S((capacity,), np.bool_),
                    S((capacity,), np.int32),
                )
            )
            continue
        if isinstance(dt, NullType):
            cols.append(
                DeviceColumn(dt, S((capacity,), np.int8), S((capacity,), np.bool_))
            )
            continue
        cols.append(
            DeviceColumn(
                dt, S((capacity,), dt.np_dtype), S((capacity,), np.bool_)
            )
        )
    return DeviceBatch(schema, cols, S((), np.int32))


def _pad8(nbytes: int) -> int:
    return (nbytes + 7) & ~7


def _pack_kernel(schema: Schema, cap: int, widths: tuple):
    """Cached device kernel: flatten a whole batch (row count + every data/
    validity/lengths buffer, each 8-byte aligned) into ONE uint8 vector —
    the contiguous-buffer D2H currency (reference: JCudfSerialization /
    GpuColumnVectorFromBuffer; here it buys one PJRT transfer per batch).

    float64 data buffers ride as separate raw leaves beside the flat vector:
    the TPU X64 emulation cannot bitcast 64-bit floats and recovering their
    bits arithmetically would canonicalize values the emulation flushes —
    a raw PJRT transfer is exact for whatever the device holds."""
    from .. import kernels as K

    return K.kernel(
        ("pack_d2h", schema, cap, widths), lambda: K.GuardedJit(_pack_pure)
    )


def _pack_to_bytes(flat):
    """1-D array → little-endian uint8 bytes. 64-bit ints split into
    (lo, hi) uint32 halves arithmetically (ops/bits.py): the TPU X64
    emulation can't width-change bitcast 64-bit types."""
    from ..ops.bits import i64_bytes_le

    if flat.dtype == jnp.bool_:
        return flat.astype(jnp.uint8)
    if flat.dtype in (jnp.dtype(jnp.int64), jnp.dtype(jnp.uint64)):
        return i64_bytes_le(flat)
    if flat.dtype != jnp.uint8:
        return jax.lax.bitcast_convert_type(flat, jnp.uint8).reshape(-1)
    return flat


def _pack_pure(batch: DeviceBatch):
    """The traceable pack body (shape-generic; callers cache per shape)."""
    parts = [_pack_to_bytes(batch.num_rows.astype(jnp.int64).reshape(1))]
    side: list[jax.Array] = []

    def add(arr):
        flat = _pack_to_bytes(arr.reshape(-1))
        pad = _pad8(flat.shape[0]) - flat.shape[0]
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros(pad, jnp.uint8)])
        parts.append(flat)

    for f, col in zip(batch.schema, batch.columns):
        # decode derives the layout from the SCHEMA; a drifted device dtype
        # would silently shift every later offset — fail at trace time
        assert col.data.dtype == _decode_np_dtype(f.data_type), (
            f.name,
            col.data.dtype,
            f.data_type,
        )
        assert (col.lengths is not None) == _has_lengths(f.data_type), f.name
        if col.data.dtype == jnp.dtype(jnp.float64):
            side.append(col.data.reshape(-1))
        else:
            add(col.data)
        add(col.validity.astype(jnp.uint8))
        if col.lengths is not None:
            add(col.lengths)
    # ONE f64 side leaf: each device_get leaf is a full round trip
    # on a tunneled PJRT link (~35ms), so 8 float columns as 8
    # leaves cost more than the whole data transfer
    side_cat = jnp.concatenate(side) if side else jnp.zeros(0, jnp.float64)
    return jnp.concatenate(parts), side_cat


SPEC_PULL_PREFIX = 8192


def device_to_host_speculative(batch: DeviceBatch):
    """ONE-transfer fetch for small results: pull (true row count, pack of
    the first SPEC_PULL_PREFIX rows) together; when the batch's live rows
    fit the prefix, that single round trip IS the result — the usual
    shrink-then-pull path pays two. Aggregate/TopN outputs (a handful of
    rows in a capacity-sized batch) are exactly this shape, and on the
    tunneled link every round trip is ~100ms. Returns (record_batch, None)
    on success; (None, true_row_count) when the result does not fit so the
    caller can shrink WITHOUT re-paying the row-count sync; (None, None)
    for nested/small batches it does not handle."""
    cap = batch.capacity
    if cap <= SPEC_PULL_PREFIX or not batch.columns:
        return None, None
    if any(c.children is not None for c in batch.columns):
        return None, None
    from .. import kernels as K
    from ..ops.gather import gather_column

    widths = tuple(
        c.data.shape[1] if c.data.ndim == 2 else None for c in batch.columns
    )

    def make():
        def run(b: DeviceBatch):
            idx = jnp.arange(SPEC_PULL_PREFIX, dtype=jnp.int32)
            cols = [gather_column(c, idx) for c in b.columns]
            nb = DeviceBatch(
                b.schema, cols, jnp.minimum(b.num_rows, SPEC_PULL_PREFIX)
            )
            flat, side = _pack_pure(nb)
            # the TRUE row count rides as an extra 8-byte header word in the
            # SAME flat buffer — a separate leaf would be its own round trip
            # on a tunneled PJRT link, defeating the one-transfer point
            true_hdr = _pack_to_bytes(b.num_rows.astype(jnp.int64).reshape(1))
            return jnp.concatenate([true_hdr, flat]), side

        return K.GuardedJit(run)

    kernel = K.kernel(("d2h_spec", batch.schema, cap, widths), make)
    flat, side = jax.device_get(kernel(batch))
    flat = np.asarray(flat)
    n_true = int(flat[:8].view(np.int64)[0])
    if n_true > SPEC_PULL_PREFIX:
        return None, n_true
    rb = _decode_packed(
        batch.schema,
        widths,
        SPEC_PULL_PREFIX,
        flat[8:],
        np.asarray(side),
    )
    return rb, None


def device_to_host(batch: DeviceBatch, shrink: bool = True) -> pa.RecordBatch:
    """DeviceBatch → Arrow RecordBatch sliced to live rows.

    The whole batch is packed on device into one flat buffer and fetched
    with a single transfer — a slow PJRT link pays one round trip, not one
    per buffer (per-column ``np.asarray`` was the top cost on a tunneled
    TPU). Pass ``shrink=False`` when the caller already re-bucketed the
    batch (DeviceToHostExec bulk-shrinks a window of batches with one
    row-count sync — the per-batch sync here would double-pay the RTT)."""
    cap = batch.capacity
    if cap == 0:
        return pa.RecordBatch.from_arrays(
            [pa.array([], type=f.data_type.to_arrow()) for f in batch.schema],
            schema=batch.schema.to_arrow(),
        )
    if shrink and cap > MIN_CAPACITY:
        # never ship padding over a slow link: re-bucket to the live rows
        # first (one row-count round trip buys skipping up to cap-n rows
        # of every buffer)
        from ..ops.gather import shrink_one

        batch = shrink_one(batch, batch.row_count())
        cap = batch.capacity
    if any(c.children is not None for c in batch.columns):
        # nested columns: fetch the whole pytree in one device_get and
        # rebuild arrow recursively (the flat pack layout is for the common
        # primitive/string case)
        num_rows, host_cols = jax.device_get((batch.num_rows, batch.columns))
        n = int(num_rows)
        arrays = [
            _arrow_from_np_col(c, f.data_type, n)
            for f, c in zip(batch.schema, host_cols)
        ]
        return pa.RecordBatch.from_arrays(arrays, schema=batch.schema.to_arrow())
    widths = tuple(
        c.data.shape[1] if c.data.ndim == 2 else None for c in batch.columns
    )
    flat, side = jax.device_get(_pack_kernel(batch.schema, cap, widths)(batch))
    return _decode_packed(
        batch.schema, widths, cap, np.asarray(flat), np.asarray(side)
    )


def _decode_np_dtype(dt: DataType) -> "np.dtype":
    """Device storage dtype of a flat column (strings ride as uint8 byte
    matrices; everything else stores its np_dtype)."""
    if isinstance(dt, StringType):
        return np.dtype(np.uint8)
    return np.dtype(dt.np_dtype)


def _has_lengths(dt: DataType) -> bool:
    return isinstance(dt, StringType)


def _decode_packed(
    schema: Schema, widths: tuple, cap: int, flat: "np.ndarray", side: "np.ndarray"
) -> pa.RecordBatch:
    """Host-side decode of _pack_pure's flat layout → Arrow RecordBatch."""
    n = int(flat[:8].view(np.int64)[0])
    off = 8
    side_off = 0
    host_cols: list[DeviceColumn] = []
    for f, w in zip(schema, widths):
        np_dt = _decode_np_dtype(f.data_type)
        if np_dt == np.dtype(np.float64):
            count = cap * (w or 1)
            data = side[side_off : side_off + count]
            if w:
                data = data.reshape(cap, w)
            side_off += count
        else:
            itemsize = np_dt.itemsize
            count = cap * (w or 1)
            nbytes = count * itemsize
            data = flat[off : off + nbytes].view(np_dt)
            data = data.reshape(cap, w) if w else data
            off += _pad8(nbytes)
        validity = flat[off : off + cap].view(np.bool_)
        off += _pad8(cap)
        lengths = None
        if _has_lengths(f.data_type):
            lengths = flat[off : off + cap * 4].view(np.int32)
            off += _pad8(cap * 4)
        host_cols.append(DeviceColumn(f.data_type, data, validity, lengths))
    arrays: list[pa.Array] = []
    fields: list[pa.Field] = []
    for f, col in zip(schema, host_cols):
        dt = f.data_type
        valid = np.asarray(col.validity)[: max(n, 0)].astype(bool)
        if isinstance(dt, StringType):
            data = np.asarray(col.data)
            lengths = np.asarray(col.lengths)
            arr = _padded_to_string(data, lengths, np.asarray(col.validity), n)
        elif isinstance(dt, NullType):
            arr = pa.nulls(n)
        else:
            data = np.asarray(col.data)[:n]
            if isinstance(dt, DecimalType):
                # data holds unscaled int64; rebuild decimals by value.
                import decimal as _dec

                scale = dt.scale
                py = [
                    None if not v else _dec.Decimal(int(x)).scaleb(-scale)
                    for x, v in zip(data.tolist(), valid.tolist())
                ]
                arr = pa.array(py, type=pa.decimal128(dt.precision, dt.scale))
            else:
                mask = None if valid.all() else ~valid
                arr = pa.array(data, type=dt.to_arrow(), from_pandas=False, mask=mask)
        arrays.append(arr)
        fields.append(pa.field(f.name, dt.to_arrow(), f.nullable))
    return pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields))


def _arrow_from_np_col(col: DeviceColumn, dt: DataType, n: int) -> pa.Array:
    """Host-side (numpy-leaf) DeviceColumn → arrow array of n rows.
    Recursive inverse of _np_col_from_arrow."""
    from ..types import ArrayType, MapType, StructType

    valid = np.asarray(col.validity)[:n].astype(bool)
    null_mask = None if valid.all() else ~valid
    if isinstance(dt, StringType):
        return _padded_to_string(
            np.asarray(col.data), np.asarray(col.lengths), np.asarray(col.validity), n
        )
    if isinstance(dt, NullType):
        return pa.nulls(n)
    if isinstance(dt, StructType):
        kids = [
            _arrow_from_np_col(c, f.data_type, n)
            for c, f in zip(col.children, dt.fields)
        ]
        return pa.StructArray.from_arrays(
            kids,
            fields=[pa.field(f.name, f.data_type.to_arrow(), f.nullable) for f in dt.fields],
            mask=pa.array(~valid) if null_mask is not None else None,
        )
    if isinstance(dt, (ArrayType, MapType)):
        lengths = np.where(valid, np.asarray(col.lengths)[:n], 0).astype(np.int64)
        offsets = np.zeros(n + 1, dtype=np.int32)
        offsets[1:] = np.cumsum(lengths)
        W = col.children[0].data.shape[1] if col.children[0].data is not None else 0
        take = np.arange(W)[None, :] < lengths[:, None]

        def flatten_plane(plane: DeviceColumn, vdt) -> pa.Array:
            total = int(lengths.sum())
            d = np.asarray(plane.data)[:n]
            v = np.asarray(plane.validity)[:n]
            fdata = d[take]  # [total(, w)]
            fvalid = v[take]
            flen = (
                np.asarray(plane.lengths)[:n][take]
                if plane.lengths is not None
                else None
            )
            fcol = DeviceColumn(vdt, fdata, fvalid, flen)
            return _arrow_from_np_col(fcol, vdt, total)

        # a null offset marks a null list (arrow from_arrays convention)
        offs = pa.array(
            offsets,
            type=pa.int32(),
            mask=np.append(~valid, False) if null_mask is not None else None,
        )
        if isinstance(dt, MapType):
            keys = flatten_plane(col.children[0], dt.key_type)
            items = flatten_plane(col.children[1], dt.value_type)
            return pa.MapArray.from_arrays(offs, keys, items)
        values = flatten_plane(col.children[0], dt.element_type)
        return pa.ListArray.from_arrays(offs, values)
    data = np.asarray(col.data)[:n]
    if isinstance(dt, DecimalType):
        import decimal as _dec

        scale = dt.scale
        py = [
            None if not v else _dec.Decimal(int(x)).scaleb(-scale)
            for x, v in zip(data.tolist(), valid.tolist())
        ]
        return pa.array(py, type=pa.decimal128(dt.precision, dt.scale))
    return pa.array(data, type=dt.to_arrow(), from_pandas=False, mask=null_mask)


def _empty_col(dt: DataType, capacity: int, plane_w: Optional[int] = None) -> DeviceColumn:
    from ..types import ArrayType, MapType, StructType

    shape = (capacity,) if plane_w is None else (capacity, plane_w)
    valid = jnp.zeros(shape, dtype=bool)
    if isinstance(dt, StringType):
        return DeviceColumn(
            dt,
            jnp.zeros(shape + (MIN_STR_WIDTH,), dtype=jnp.uint8),
            valid,
            jnp.zeros(shape, dtype=jnp.int32),
        )
    if isinstance(dt, StructType):
        kids = tuple(_empty_col(f.data_type, capacity, plane_w) for f in dt.fields)
        return DeviceColumn(dt, None, valid, None, kids)
    if isinstance(dt, ArrayType):
        kid = _empty_col(dt.element_type, capacity, 1)
        return DeviceColumn(dt, None, valid, jnp.zeros(shape, jnp.int32), (kid,))
    if isinstance(dt, MapType):
        kids = (
            _empty_col(dt.key_type, capacity, 1),
            _empty_col(dt.value_type, capacity, 1),
        )
        return DeviceColumn(dt, None, valid, jnp.zeros(shape, jnp.int32), kids)
    return DeviceColumn(dt, jnp.zeros(shape, dtype=dt.np_dtype), valid)


def empty_batch(schema: Schema, capacity: int = MIN_CAPACITY) -> DeviceBatch:
    cols = [_empty_col(f.data_type, capacity) for f in schema]
    return DeviceBatch(schema, cols, jnp.asarray(0, dtype=jnp.int32))
