"""Arrow IPC stream framing — the ONE wire encoding for columnar batches.

Shuffle frames (``shuffle/serializer.py``), broadcast payloads, and the
network serving front-end (``serve/``) all move record batches as
self-contained Arrow IPC streams (schema message + batch messages). The
read/write helpers live here so the framing is written once and hardened
once; the serializer keeps its codec/metric layering on top as thin shims.

Hardening the streamed-result path needs (both hit by result tails):

- **zero-row batches** — a served query's final partition is often empty;
  pyarrow round-trips a 0-row batch fine, but a stream whose table is empty
  yields NO combinable batch (``Table.to_batches() == []``), so the single-
  batch readers here rebuild an empty batch from the stream schema instead
  of indexing into a missing list;
- **all-null columns** — an all-null typed column and a ``NullType`` column
  both serialize with degenerate buffers; reads go through the stream
  reader (never raw buffer peeling), so validity-only columns survive.
"""
from __future__ import annotations

import io
from typing import List, Optional, Tuple

import pyarrow as pa


def schema_to_bytes(schema: pa.Schema) -> bytes:
    return schema.serialize().to_pybytes()


def schema_from_bytes(data: bytes) -> pa.Schema:
    return pa.ipc.read_schema(pa.py_buffer(data))


def empty_batch(schema: pa.Schema) -> pa.RecordBatch:
    """A 0-row batch of ``schema`` (the stream-tail currency)."""
    return pa.RecordBatch.from_arrays(
        [pa.array([], type=f.type) for f in schema], schema=schema
    )


def write_stream(
    batches: List[pa.RecordBatch], schema: Optional[pa.Schema] = None
) -> bytes:
    """Batches → one complete Arrow IPC stream. ``schema`` is required when
    ``batches`` may be empty (a schema-only stream is valid and decodes to
    zero batches)."""
    if schema is None:
        if not batches:
            raise ValueError("write_stream with no batches requires a schema")
        schema = batches[0].schema
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, schema) as w:
        for rb in batches:
            w.write_batch(rb)
    return sink.getvalue()


def read_stream(data: bytes) -> Tuple[pa.Schema, List[pa.RecordBatch]]:
    """IPC stream → (schema, batches). Zero-row batches are preserved; a
    schema-only stream returns an empty list."""
    with pa.ipc.open_stream(pa.py_buffer(data)) as r:
        schema = r.schema
        batches = [b for b in r]
    return schema, batches


def write_batch(rb: pa.RecordBatch) -> bytes:
    """One batch → a self-contained IPC stream frame (schema + batch), the
    unit both shuffle frames and served result batches travel as."""
    return write_stream([rb])


def read_batch(data: bytes) -> pa.RecordBatch:
    """Self-contained IPC frame → ONE batch. Multi-batch frames combine;
    empty frames (schema only, or only 0-row batches) rebuild a 0-row batch
    from the stream schema rather than failing on the empty batch list."""
    schema, batches = read_stream(data)
    if len(batches) == 1:
        return batches[0]
    if not batches:
        return empty_batch(schema)
    table = pa.Table.from_batches(batches)
    if table.num_rows == 0:
        return empty_batch(schema)
    return table.combine_chunks().to_batches()[0]
