from .device import (
    DeviceBatch,
    DeviceColumn,
    bucket_capacity,
    bucket_width,
    device_to_host,
    empty_batch,
    host_to_device,
)
from .host import arrow_from_np, batch_from_columns, concat_batches, np_from_arrow
