"""Host-side columnar helpers — the ``RapidsHostColumnVector`` analogue.

The host currency everywhere (spill, shuffle, CPU fallback operators, IO) is
``pyarrow.RecordBatch``. The CPU execution engine computes over numpy views
with explicit validity masks so Spark semantics (Java integer wraparound,
null propagation, NaN ordering) are implemented exactly rather than inherited
from pyarrow.compute.
"""
from __future__ import annotations

import decimal as _dec
from typing import Optional

import numpy as np
import pyarrow as pa

from ..types import DataType, DecimalType, NullType, Schema, StringType, is_complex


def fixed_np(arr: pa.Array, np_dtype: np.dtype) -> np.ndarray:
    """Zero-copy-ish view of a fixed-width arrow array's data buffer.

    Avoids ``to_numpy``'s nullable-int→float64 promotion, which silently
    loses precision on int64 values beyond 2^53 (null slots hold garbage —
    callers mask them)."""
    n = len(arr)
    buf = arr.buffers()[1]
    if buf is None:
        return np.zeros(n, dtype=np_dtype)
    if np_dtype == np.bool_:
        bits = np.frombuffer(buf, dtype=np.uint8)
        idx = np.arange(arr.offset, arr.offset + n)
        return ((bits[idx // 8] >> (idx % 8)) & 1).astype(bool)
    data = np.frombuffer(buf, dtype=np_dtype, count=arr.offset + n)[arr.offset :]
    return data


def np_from_arrow(arr: pa.Array, dt: DataType) -> tuple[np.ndarray, np.ndarray]:
    """Arrow array → (data, validity). For strings, data is an object ndarray
    of python str (None for null). Null slots in fixed-width data are zeroed."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    valid = ~np.asarray(arr.is_null())
    n = len(arr)
    if isinstance(dt, StringType):
        data = np.empty(n, dtype=object)
        data[:] = arr.cast(pa.string()).to_pylist()
        return data, valid
    if is_complex(dt):
        # CPU oracle representation: object ndarray of python values
        # (lists / dicts-as-lists-of-pairs / structs-as-dicts)
        data = np.empty(n, dtype=object)
        data[:] = arr.to_pylist()
        return data, valid
    if isinstance(dt, NullType):
        return np.zeros(n, dtype=np.int8), np.zeros(n, dtype=bool)
    if isinstance(dt, DecimalType):
        # decimal128 storage is 128-bit little-endian; DECIMAL64 gating means
        # the value always fits the low 64 bits (two's complement)
        buf = arr.buffers()[1]
        if buf is None:
            return np.zeros(n, dtype=np.int64), valid
        pairs = np.frombuffer(buf, dtype=np.int64, count=(arr.offset + n) * 2)
        data = pairs.reshape(-1, 2)[arr.offset :, 0]
        return np.where(valid, data, 0), valid
    if pa.types.is_date32(arr.type):
        arr = arr.cast(pa.int32())
    elif pa.types.is_timestamp(arr.type):
        arr = arr.cast(pa.int64())
    data = fixed_np(arr, dt.np_dtype)
    if not valid.all():
        data = np.where(valid, data, np.zeros((), dtype=dt.np_dtype))
    return np.ascontiguousarray(data), valid


def arrow_from_np(data: np.ndarray, valid: np.ndarray, dt: DataType) -> pa.Array:
    n = len(data)
    if isinstance(dt, NullType):
        return pa.nulls(n)
    if isinstance(dt, StringType):
        py = [data[i] if valid[i] else None for i in range(n)]
        return pa.array(py, type=pa.string())
    if isinstance(dt, DecimalType):
        py = [
            _dec.Decimal(int(data[i])).scaleb(-dt.scale) if valid[i] else None
            for i in range(n)
        ]
        return pa.array(py, type=pa.decimal128(dt.precision, dt.scale))
    if is_complex(dt):
        py = [data[i] if valid[i] else None for i in range(n)]
        return pa.array(py, type=dt.to_arrow())
    mask = None if valid.all() else ~valid
    return pa.array(data, type=dt.to_arrow(), mask=mask)


def batch_from_columns(
    schema: Schema, cols: list[tuple[np.ndarray, np.ndarray]]
) -> pa.RecordBatch:
    arrays = [
        arrow_from_np(d, v, f.data_type) for (d, v), f in zip(cols, schema)
    ]
    return pa.RecordBatch.from_arrays(arrays, schema=schema.to_arrow())


def concat_batches(schema: Schema, batches: list[pa.RecordBatch]) -> pa.RecordBatch:
    if not batches:
        return pa.RecordBatch.from_arrays(
            [pa.array([], type=f.data_type.to_arrow()) for f in schema],
            schema=schema.to_arrow(),
        )
    table = pa.Table.from_batches(batches)
    return table.combine_chunks().to_batches()[0] if table.num_rows else batches[0].slice(0, 0)
