"""spark_rapids_tpu — a TPU-native Spark-SQL-accelerator-class framework.

A standalone columnar SQL engine with the architecture of NVIDIA's RAPIDS
Accelerator for Apache Spark (the reference at /root/reference): a planning
layer that rewrites physical plans so SQL operators execute as columnar
kernels on accelerator-resident Arrow batches with per-operator CPU fallback,
a tiered HBM->host->disk spill framework, task admission control, columnar
shuffle, and Arrow/pandas interop — with the kernel layer implemented in
JAX/XLA (plus Pallas) on TPU instead of cuDF/CUDA, and multi-chip exchange
over ICI meshes instead of UCX.
"""
import jax as _jax

# Spark semantics are 64-bit (LongType, DoubleType, 64-bit decimal); JAX's
# 32-bit default would silently truncate, so the framework requires x64.
# (On TPU, f64 is emulated — the planner keeps hot paths in 32-bit/bf16 where
# Spark's types allow it.)
_jax.config.update("jax_enable_x64", True)

from . import config
from .config import TpuConf
from .types import (
    BOOLEAN,
    BYTE,
    DATE,
    DOUBLE,
    FLOAT,
    INT,
    LONG,
    NULL,
    SHORT,
    STRING,
    TIMESTAMP,
    DecimalType,
    Schema,
    StructField,
)

from .session import DataFrame, TpuSession
from . import functions

__version__ = "0.1.0"
