"""Numpy kernels for the CPU (fallback/oracle) engine.

These implement Spark-exact semantics with the same spec as the device
kernels in ops/ — the differential test harness (tests/harness.py) compares
the two engines, which is the reference's CPU-vs-GPU equality strategy
(SparkQueryCompareTestSuite.scala) turned inward.
"""
from __future__ import annotations

import numpy as np

from ..types import (
    BooleanType,
    DataType,
    DoubleType,
    FloatType,
    StringType,
)


def normalized_float_bits(data: np.ndarray) -> np.ndarray:
    """Float -> comparable int64 bits with Spark's grouping/join/sort
    normalization: -0.0 == 0.0 and one canonical NaN."""
    x = np.where(data == 0, np.zeros_like(data), data)
    x = np.where(np.isnan(x), np.full_like(x, np.nan), x)
    return x.astype(np.float64).view(np.int64)


def encode_group_key(dt: DataType, data: np.ndarray, valid: np.ndarray):
    """Encode one key column into int64 word columns such that equal words ⇔
    same Spark group (nulls one group, NaNs one group, -0.0 == 0.0).
    Returns a list of int64 arrays (validity word + value word)."""
    from ..types import is_complex

    n = len(valid)
    vw = valid.astype(np.int64)
    if isinstance(dt, StringType) or is_complex(dt):
        def canon(v):
            if isinstance(v, list):
                return tuple(canon(x) for x in v)
            if isinstance(v, dict):
                return tuple((k, canon(x)) for k, x in sorted(v.items()))
            if isinstance(v, tuple):
                return tuple(canon(x) for x in v)
            return v

        vocab: dict = {}
        codes = np.zeros(n, dtype=np.int64)
        for i in range(n):
            if not valid[i]:
                continue
            key = canon(data[i])
            code = vocab.get(key)
            if code is None:
                code = len(vocab) + 1
                vocab[key] = code
            codes[i] = code
        return [vw, codes]
    if isinstance(dt, (FloatType, DoubleType)):
        return [vw, np.where(valid, normalized_float_bits(data), 0)]
    return [vw, np.where(valid, data.astype(np.int64), 0)]


def group_inverse(encoded_cols: list[np.ndarray], n: int):
    """(inverse ids, first-occurrence row index per group). Group order is
    first-occurrence order (stable, like streaming aggregation)."""
    if not encoded_cols:
        return np.zeros(n, dtype=np.int64), np.zeros(min(n, 1), dtype=np.int64)
    mat = np.stack(encoded_cols, axis=1)
    # np.unique(axis=0) sorts; recover first-occurrence order for stability
    uniq, first_idx, inverse = np.unique(
        mat, axis=0, return_index=True, return_inverse=True
    )
    order = np.argsort(first_idx, kind="stable")
    rank = np.empty_like(order)
    rank[order] = np.arange(len(order))
    return rank[inverse], first_idx[order]


_NULL_SENTINEL_F = -(2**62)


def _canon_value(v):
    """Hashable canonical form with Spark value equality (NaN == NaN)."""
    import math

    if isinstance(v, float) and math.isnan(v):
        return "__NaN__"
    if isinstance(v, list):
        return tuple(_canon_value(x) for x in v)
    if isinstance(v, dict):
        return tuple((k, _canon_value(x)) for k, x in sorted(v.items()))
    return v


def _sorted_set(items: list) -> list:
    """Deterministic collect_set order: value-ascending with canonical
    floats (-0.0 → 0.0, NaN greatest) — mirrors the device kernel's
    value-sorted dedupe. Spark guarantees no order for collect_set, so a
    canonical order is a compatible (and testable) choice."""
    import math

    def canon(v):
        if isinstance(v, float) and v == 0.0:
            return 0.0
        return v

    def key(v):
        if isinstance(v, float):
            return (1, 0.0) if math.isnan(v) else (0, v)
        return (0, v)

    vals = [canon(v) for v in items]
    try:
        return sorted(vals, key=key)
    except TypeError:
        return vals


def _dedup_spark(items: list) -> list:
    seen = set()
    out = []
    for v in items:
        k = _canon_value(v)
        if k not in seen:
            seen.add(k)
            out.append(v)
    return out


def reduce_groups(
    op: str,
    dt: DataType,
    data: np.ndarray,
    valid: np.ndarray,
    inv: np.ndarray,
    num_groups: int,
):
    """One segment reduction; returns (data[num_groups], valid[num_groups])."""
    G = num_groups
    any_valid = np.zeros(G, dtype=bool)
    np.logical_or.at(any_valid, inv, valid)
    if op == "count":
        out = np.zeros(G, dtype=np.int64)
        np.add.at(out, inv[valid], 1)
        return out, np.ones(G, dtype=bool)
    if op == "sum":
        out = np.zeros(G, dtype=data.dtype)
        np.add.at(out, inv[valid], data[valid])
        return out, any_valid
    if isinstance(dt, StringType) and op in ("min", "max"):
        # python loop: UTF-8 byte order like Spark's UTF8String.compareTo
        out = np.empty(G, dtype=object)
        outv = np.zeros(G, dtype=bool)
        for i in range(len(inv)):
            g = inv[i]
            if not valid[i]:
                continue
            v = data[i]
            if not outv[g]:
                out[g], outv[g] = v, True
            elif op == "min" and v.encode() < out[g].encode():
                out[g] = v
            elif op == "max" and v.encode() > out[g].encode():
                out[g] = v
        return out, outv
    if op in ("min", "max"):
        if np.issubdtype(data.dtype, np.floating):
            fill = np.inf if op == "min" else -np.inf
            x = np.where(valid, data, fill)
            # Spark NaN ordering: NaN greatest
            had_nan = np.zeros(G, dtype=bool)
            np.logical_or.at(had_nan, inv, valid & np.isnan(data))
            x = np.where(np.isnan(x), np.inf, x)
            out = np.full(G, fill, dtype=data.dtype)
            (np.minimum if op == "min" else np.maximum).at(out, inv, x)
            if op == "max":
                out = np.where(had_nan, np.nan, out)
            else:
                out = np.where(had_nan & (out == np.inf), np.nan, out)
            return out, any_valid
        info = np.iinfo(data.dtype)
        fill = info.max if op == "min" else info.min
        x = np.where(valid, data, fill)
        out = np.full(G, fill, dtype=data.dtype)
        (np.minimum if op == "min" else np.maximum).at(out, inv, x)
        return out, any_valid
    if op in ("collect_list", "collect_set", "merge_lists", "merge_sets"):
        out = np.empty(G, dtype=object)
        for g in range(G):
            out[g] = []
        merging = op.startswith("merge")
        for i in range(len(inv)):
            if not valid[i]:
                continue
            if merging:
                out[inv[i]].extend(data[i])
            else:
                out[inv[i]].append(data[i])
        if op in ("collect_set", "merge_sets"):
            for g in range(G):
                out[g] = _sorted_set(_dedup_spark(out[g]))
        # collect results are never null — empty array for all-null groups
        return out, np.ones(G, dtype=bool)
    idx = np.arange(len(inv), dtype=np.int64)
    big = np.int64(2**62)
    if op == "first":
        pick = np.full(G, big)
        np.minimum.at(pick, inv, idx)
    elif op == "last":
        pick = np.full(G, -1, dtype=np.int64)
        np.maximum.at(pick, inv, idx)
    elif op == "first_ignore_nulls":
        pick = np.full(G, big)
        np.minimum.at(pick, inv, np.where(valid, idx, big))
    elif op == "last_ignore_nulls":
        pick = np.full(G, -1, dtype=np.int64)
        np.maximum.at(pick, inv, np.where(valid, idx, -1))
    else:
        raise ValueError(op)
    ok = (pick != big) & (pick >= 0)
    safe = np.clip(pick, 0, max(len(inv) - 1, 0))
    out = data[safe] if len(inv) else np.zeros(G, dtype=data.dtype)
    outv = (valid[safe] if len(inv) else np.zeros(G, dtype=bool)) & ok
    return out, outv
