"""pandas-interop execs — the L7 python-exec family.

Reference: GpuMapInPandasExec / GpuFlatMapGroupsInPandasExec (+ the shared
GpuArrowEvalPythonExec Arrow streaming, :391). There the columnar batches
stream over Arrow IPC to a separate python worker; this engine IS python,
so the "worker protocol" collapses to zero-copy ``RecordBatch →
pandas`` conversions in-process — same dataflow, no socket. These execs
run on the host side of a D2H transition (python user code cannot run on
the TPU), exactly like the reference pairs its python execs with
columnar↔row transitions.
"""
from __future__ import annotations

from typing import Iterator, List

import pyarrow as pa

from ..plan.physical import Exec, ExecContext, PartitionSet
from ..types import Schema


def _df_to_batches(df, schema: Schema, what: str) -> Iterator[pa.RecordBatch]:
    import pandas as pd

    if not isinstance(df, pd.DataFrame):
        raise TypeError(f"{what} must return pandas DataFrames, got {type(df)}")
    target = schema.to_arrow()
    tbl = pa.Table.from_pandas(df, preserve_index=False)
    cols = []
    for f in target:
        if f.name not in tbl.column_names:
            raise ValueError(
                f"{what} result is missing column {f.name!r} "
                f"(declared schema: {schema.names})"
            )
        arr = tbl.column(f.name)
        if arr.type != f.type:
            arr = arr.cast(f.type)
        cols.append(arr.combine_chunks())
    for rb in pa.Table.from_arrays(cols, schema=target).to_batches():
        if rb.num_rows:
            yield rb


class CpuMapInPandasExec(Exec):
    """fn(iterator of pd.DataFrame) → iterator of pd.DataFrame, one call
    per partition (pyspark mapInPandas contract)."""

    def __init__(self, fn, schema: Schema, child: Exec):
        super().__init__([child])
        self.fn = fn
        self._schema = schema

    @property
    def output(self) -> Schema:
        return self._schema

    def execute(self, ctx: ExecContext) -> PartitionSet:
        fn, schema = self.fn, self._schema

        def run(it: Iterator[pa.RecordBatch]):
            def dfs():
                for rb in it:
                    yield rb.to_pandas()

            for df in fn(dfs()):
                yield from _df_to_batches(df, schema, "mapInPandas fn")

        return self.children[0].execute(ctx).map_partitions(run)

    def node_string(self):
        return f"CpuMapInPandas {getattr(self.fn, '__name__', 'fn')}"


class CpuFlatMapGroupsInPandasExec(Exec):
    """fn(pd.DataFrame) → pd.DataFrame per key group. The planner exchanges
    rows by the grouping keys first, so each partition holds whole groups
    (the reference plans its python exec the same way)."""

    def __init__(self, grouping: List[str], fn, schema: Schema, child: Exec):
        super().__init__([child])
        self.grouping = list(grouping)
        self.fn = fn
        self._schema = schema

    @property
    def output(self) -> Schema:
        return self._schema

    def execute(self, ctx: ExecContext) -> PartitionSet:
        fn, schema, keys = self.fn, self._schema, self.grouping

        def run(it: Iterator[pa.RecordBatch]):
            batches = list(it)
            if not batches:
                return
            pdf = pa.Table.from_batches(batches).to_pandas()
            if not len(pdf):
                return
            if not keys:
                # groupBy().applyInPandas: the whole frame is one group
                yield from _df_to_batches(fn(pdf), schema, "applyInPandas fn")
                return
            # dropna=False: NULL keys form a group (Spark semantics)
            for _, group in pdf.groupby(keys, dropna=False, sort=False):
                out = fn(group.reset_index(drop=True))
                yield from _df_to_batches(out, schema, "applyInPandas fn")

        return self.children[0].execute(ctx).map_partitions(run)

    def node_string(self):
        return (
            f"CpuFlatMapGroupsInPandas {self.grouping} "
            f"{getattr(self.fn, '__name__', 'fn')}"
        )
