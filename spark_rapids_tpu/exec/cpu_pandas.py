"""pandas-interop execs — the L7 python-exec family.

Reference: GpuMapInPandasExec / GpuFlatMapGroupsInPandasExec (+ the shared
GpuArrowEvalPythonExec Arrow streaming, :391). There the columnar batches
stream over Arrow IPC to a separate python worker; this engine IS python,
so the "worker protocol" collapses to zero-copy ``RecordBatch →
pandas`` conversions in-process — same dataflow, no socket. These execs
run on the host side of a D2H transition (python user code cannot run on
the TPU), exactly like the reference pairs its python execs with
columnar↔row transitions.
"""
from __future__ import annotations

from typing import Iterator, List

import pyarrow as pa

from ..plan.physical import Exec, ExecContext, PartitionSet
from ..types import Schema


def prefetched(it: Iterator, depth: int) -> Iterator:
    """BatchQueue analogue (GpuArrowEvalPythonExec.scala:188): a producer
    thread drains the upstream batch pipeline into a bounded queue while
    the python function consumes — device production and python compute
    overlap instead of serializing. The WHOLE upstream iterator advances on
    the producer thread (task thread-locals re-assert per pull, so the
    stage scoping is thread-consistent); errors propagate to the consumer;
    an abandoned consumer releases the producer via the stop flag."""
    if depth <= 0:
        return it
    import queue as _q
    import threading

    buf: "_q.Queue" = _q.Queue(maxsize=depth)
    stop = threading.Event()
    DONE = object()

    class _Err:
        def __init__(self, e):
            self.e = e

    def produce():
        try:
            for x in it:
                while not stop.is_set():
                    try:
                        buf.put(x, timeout=0.1)
                        break
                    except _q.Full:
                        continue
                if stop.is_set():
                    return
            item = DONE
        except BaseException as e:  # noqa: BLE001 - relayed to the consumer
            item = _Err(e)
        while not stop.is_set():
            try:
                buf.put(item, timeout=0.1)
                return
            except _q.Full:
                continue

    def consume():
        # lazy start: a consumer generator that is never advanced never
        # runs its finally, so an eager producer would busy-poll forever
        threading.Thread(target=produce, daemon=True).start()
        try:
            while True:
                x = buf.get()
                if x is DONE:
                    return
                if isinstance(x, _Err):
                    raise x.e
                yield x
        finally:
            stop.set()

    return consume()


def _prefetch_depth(ctx: ExecContext) -> int:
    from .. import config as cfg

    return cfg.PYTHON_PREFETCH_BATCHES.get(ctx.conf)


def _df_to_batches(df, schema: Schema, what: str) -> Iterator[pa.RecordBatch]:
    import pandas as pd

    if not isinstance(df, pd.DataFrame):
        raise TypeError(f"{what} must return pandas DataFrames, got {type(df)}")
    target = schema.to_arrow()
    tbl = pa.Table.from_pandas(df, preserve_index=False)
    cols = []
    for f in target:
        if f.name not in tbl.column_names:
            raise ValueError(
                f"{what} result is missing column {f.name!r} "
                f"(declared schema: {schema.names})"
            )
        arr = tbl.column(f.name)
        if arr.type != f.type:
            arr = arr.cast(f.type)
        cols.append(arr.combine_chunks())
    for rb in pa.Table.from_arrays(cols, schema=target).to_batches():
        if rb.num_rows:
            yield rb


class CpuMapInPandasExec(Exec):
    """fn(iterator of pd.DataFrame) → iterator of pd.DataFrame, one call
    per partition (pyspark mapInPandas contract)."""

    def __init__(self, fn, schema: Schema, child: Exec):
        super().__init__([child])
        self.fn = fn
        self._schema = schema

    @property
    def output(self) -> Schema:
        return self._schema

    def execute(self, ctx: ExecContext) -> PartitionSet:
        fn, schema = self.fn, self._schema
        depth = _prefetch_depth(ctx)

        def run(it: Iterator[pa.RecordBatch]):
            src = prefetched(it, depth)

            def dfs():
                for rb in src:
                    yield rb.to_pandas()

            for df in fn(dfs()):
                yield from _df_to_batches(df, schema, "mapInPandas fn")

        return self.children[0].execute(ctx).map_partitions(run)

    def node_string(self):
        return f"CpuMapInPandas {getattr(self.fn, '__name__', 'fn')}"


class CpuFlatMapGroupsInPandasExec(Exec):
    """fn(pd.DataFrame) → pd.DataFrame per key group. The planner exchanges
    rows by the grouping keys first, so each partition holds whole groups
    (the reference plans its python exec the same way)."""

    def __init__(self, grouping: List[str], fn, schema: Schema, child: Exec):
        super().__init__([child])
        self.grouping = list(grouping)
        self.fn = fn
        self._schema = schema

    @property
    def output(self) -> Schema:
        return self._schema

    def execute(self, ctx: ExecContext) -> PartitionSet:
        fn, schema, keys = self.fn, self._schema, self.grouping

        def run(it: Iterator[pa.RecordBatch]):
            batches = list(it)
            if not batches:
                return
            pdf = pa.Table.from_batches(batches).to_pandas()
            if not len(pdf):
                return
            if not keys:
                # groupBy().applyInPandas: the whole frame is one group
                yield from _df_to_batches(fn(pdf), schema, "applyInPandas fn")
                return
            # dropna=False: NULL keys form a group (Spark semantics)
            for _, group in pdf.groupby(keys, dropna=False, sort=False):
                out = fn(group.reset_index(drop=True))
                yield from _df_to_batches(out, schema, "applyInPandas fn")

        return self.children[0].execute(ctx).map_partitions(run)

    def node_string(self):
        return (
            f"CpuFlatMapGroupsInPandas {self.grouping} "
            f"{getattr(self.fn, '__name__', 'fn')}"
        )


def _group_map(pdf, keys):
    """key tuple → group DataFrame (dropna=False: NULL keys group; insertion
    order preserved)."""
    out = {}
    if not len(pdf):
        return out
    for key, group in pdf.groupby(keys, dropna=False, sort=False):
        if not isinstance(key, tuple):
            key = (key,)
        # NaN keys are not equal to themselves; normalize for matching
        norm = tuple(None if (isinstance(k, float) and k != k) else k for k in key)
        out[norm] = group.reset_index(drop=True)
    return out


class CpuFlatMapCoGroupsInPandasExec(Exec):
    """``fn(left_pd, right_pd) -> pd.DataFrame`` once per key group present
    on either side; the planner exchanges both children by their keys with
    the same arity so co-grouped keys land in the same partition pair
    (reference GpuFlatMapCoGroupsInPandasExec)."""

    def __init__(self, left_keys, right_keys, fn, schema: Schema, left: Exec, right: Exec):
        super().__init__([left, right])
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.fn = fn
        self._schema = schema

    @property
    def output(self) -> Schema:
        return self._schema

    def execute(self, ctx: ExecContext) -> PartitionSet:
        fn, schema = self.fn, self._schema
        lk, rk = self.left_keys, self.right_keys
        lschema = self.children[0].output.to_arrow()
        rschema = self.children[1].output.to_arrow()
        lparts = self.children[0].execute(ctx)
        rparts = self.children[1].execute(ctx)
        assert lparts.num_partitions == rparts.num_partitions, (
            "cogroup sides must be co-partitioned"
        )

        def make(lt, rt):
            def run():
                lpdf = pa.Table.from_batches(list(lt()), schema=lschema).to_pandas()
                rpdf = pa.Table.from_batches(list(rt()), schema=rschema).to_pandas()
                lgroups = _group_map(lpdf, lk)
                rgroups = _group_map(rpdf, rk)
                lempty = lpdf.iloc[0:0]
                rempty = rpdf.iloc[0:0]
                keys = list(lgroups) + [k for k in rgroups if k not in lgroups]
                for key in keys:
                    out = fn(
                        lgroups.get(key, lempty), rgroups.get(key, rempty)
                    )
                    yield from _df_to_batches(out, schema, "cogroup applyInPandas fn")

            return run

        return PartitionSet(
            [make(lt, rt) for lt, rt in zip(lparts.parts, rparts.parts)]
        )

    def node_string(self):
        return (
            f"CpuFlatMapCoGroupsInPandas {self.left_keys}/{self.right_keys} "
            f"{getattr(self.fn, '__name__', 'fn')}"
        )


class CpuAggregateInPandasExec(Exec):
    """GROUPED_AGG pandas UDFs: one scalar per (group, udf); output is
    grouping columns ++ udf results (reference GpuAggregateInPandasExec).
    ``udfs``: list of (out_name, fn, return_type, arg_col_names)."""

    def __init__(self, grouping: List[str], udfs, schema: Schema, child: Exec):
        super().__init__([child])
        self.grouping = list(grouping)
        self.udfs = list(udfs)
        self._schema = schema

    @property
    def output(self) -> Schema:
        return self._schema

    def execute(self, ctx: ExecContext) -> PartitionSet:
        schema, keys, udfs = self._schema, self.grouping, self.udfs
        child_schema = self.children[0].output.to_arrow()

        def run(it: Iterator[pa.RecordBatch]):
            import pandas as pd

            batches = list(it)
            pdf = pa.Table.from_batches(batches, schema=child_schema).to_pandas()
            if keys and not len(pdf):
                return
            rows: dict = {f.name: [] for f in schema.to_arrow()}
            if keys:
                groups = pdf.groupby(keys, dropna=False, sort=False)
            else:
                # keyless global aggregate: exactly one output row even for
                # empty input (Spark emits the UDF over an empty frame)
                groups = [((), pdf)]
            for key, group in groups:
                if not isinstance(key, tuple):
                    key = (key,)
                for name, k in zip(keys, key):
                    rows[name].append(
                        None if (isinstance(k, float) and k != k) else k
                    )
                for out_name, fn, _rt, arg_names in udfs:
                    rows[out_name].append(
                        fn(*[group[a].reset_index(drop=True) for a in arg_names])
                    )
            out = pd.DataFrame(rows)
            yield from _df_to_batches(out, schema, "grouped-agg pandas UDF")

        return self.children[0].execute(ctx).map_partitions(run)

    def node_string(self):
        return (
            f"CpuAggregateInPandas {self.grouping} "
            f"[{', '.join(u[0] for u in self.udfs)}]"
        )
