"""TPU physical operators — the GpuExec family.

Reference analogues: basicPhysicalOperators.scala (GpuProjectExec,
GpuFilterExec), aggregate.scala (GpuHashAggregateExec), GpuSortExec.scala,
GpuShuffleExchangeExec + GpuPartitioning, GpuTransitionOverrides' transitions.

Each operator compiles ONE fused XLA program per (expression tree, schema,
capacity) via jax.jit over DeviceBatch pytrees; the device semaphore gates
first touch of the device per partition-task (GpuSemaphore protocol).

Kernels live in the module-level ``kernels`` cache keyed by bound expression
trees + schemas — NOT on exec instances — so re-running a query (which
rebuilds the exec tree) reuses every compiled program. See kernels.py.
"""
from __future__ import annotations

from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from ..columnar.device import (
    DeviceBatch,
    DeviceColumn,
    bucket_capacity,
    dc_replace,
    device_to_host,
    empty_batch,
    host_to_device,
)
from ..columnar.host import concat_batches
from ..expr import Expression, bind, output_name
from ..expr.aggregates import AggregateFunction
from ..expr.base import BoundReference, Ctx, Val
from ..expr.misc import contains_task_dependent
from . import task
from ..ops.aggregate import group_aggregate
from ..ops.concat import concat_device
from ..ops.gather import bulk_shrink, compact, gather_batch, gather_column
from ..ops.hash import murmur3_rows, partition_ids
from ..ops.sortkeys import batch_radix_words, sort_permutation
from ..plan.logical import SortOrder
from ..plan.physical import Exec, ExecContext, PartitionSet
from ..types import Schema, StringType, StructField
from .. import kernels as K
from ..obs import ledger as obs_ledger


_lscope = obs_ledger.scope_or_null


def val_to_column(ctx: Ctx, val: Val, dtype) -> DeviceColumn:
    """Materialize an expression result into a full DeviceColumn."""
    from ..types import ArrayType, MapType, StructType

    if isinstance(dtype, (ArrayType, MapType)):
        lengths = ctx.broadcast(val.lengths).astype(jnp.int32)
        return DeviceColumn(dtype, None, val.full_valid(ctx), lengths, val.children)
    if isinstance(dtype, StructType):
        return DeviceColumn(dtype, None, val.full_valid(ctx), None, val.children)
    if isinstance(dtype, StringType):
        data = val.data
        if data.ndim == 1:  # scalar string literal [w]
            data = jnp.broadcast_to(data[None, :], (ctx.n, data.shape[0]))
        lengths = jnp.broadcast_to(jnp.asarray(val.lengths), (ctx.n,))
        return DeviceColumn(dtype, data, val.full_valid(ctx), lengths)
    data = ctx.broadcast(val.data)
    if data.dtype != dtype.np_dtype:
        data = data.astype(dtype.np_dtype)
    return DeviceColumn(dtype, data, val.full_valid(ctx))


# ── transitions ─────────────────────────────────────────────────────────────


def _row_bytes(schema: Schema) -> int:
    """Rough per-row device footprint for batch-size targeting."""
    total = 0
    for f in schema:
        dt = f.data_type
        if isinstance(dt, StringType):
            total += 64  # padded bytes + lengths, typical bucket
        else:
            try:
                total += dt.np_dtype.itemsize
            except Exception:
                total += 16
        total += 1  # validity
    return max(total, 1)


def _expr_has_error_site(e) -> bool:
    """Fusion guard: expressions that raise through the kernel error
    channel (ANSI casts, split's maxTokens overflow) must keep their
    standalone kernel — a fused copy would silently swallow the error."""
    from ..expr.cast import Cast as _Cast
    from ..expr.strings_ext import StringSplit as _Split

    if isinstance(e, _Cast) and e.ansi:
        return True
    if isinstance(e, _Split):
        return True
    return any(_expr_has_error_site(c) for c in e.children())


def _upload_cache_budget(conf) -> int:
    """H2D upload-cache byte budget (spark.rapids.tpu.uploadCache.maxBytes):
    explicit when set; else a quarter of the device's reported byte limit;
    else the historical 4 GiB fallback."""
    from .. import config as cfg

    b = cfg.UPLOAD_CACHE_MAX_BYTES.get(conf)
    if b > 0:
        return b
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        total = stats.get("bytes_limit", 0)
        if total:
            return int(total) // 4
    except Exception:
        pass
    return 4 << 30


def _placed_partitions(ctx: "ExecContext", pset: PartitionSet) -> PartitionSet:
    """Mesh mode: commit partition p's batches to device p%n so per-partition
    kernels run data-parallel across chips from the scan onward (single-
    device mode passes through untouched)."""
    if ctx.mesh is None:
        return pset
    from ..parallel.mesh import put_batch

    mc = ctx.mesh

    def make(p, t):
        def it():
            dev = mc.device_for(p)
            # graft: ok(cancel-beat: upstream partition iterator beats per
            # batch; put_batch is one async device placement)
            for db in t():
                yield put_batch(db, dev)

        return it

    return PartitionSet([make(p, t) for p, t in enumerate(pset.parts)])


class HostToDeviceExec(Exec):
    """Host Arrow batches → device batches (HostColumnarToGpu analogue).

    Incoming batches are re-chunked to ``spark.rapids.sql.batchSizeBytes``
    (the CoalesceGoal TargetSize contract — GpuExec.scala:173-188) so one
    oversized host batch cannot blow the device working set."""

    def __init__(self, child: Exec):
        super().__init__([child])

    @property
    def output(self) -> Schema:
        return self.children[0].output

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        from .. import config as cfg

        schema = self.output
        max_rows = max(
            1, cfg.BATCH_SIZE_BYTES.get(ctx.conf) // _row_bytes(schema)
        )
        max_str = cfg.STRING_MAX_BYTES.get(ctx.conf)
        rows_m = self.metric("numInputRows", "ESSENTIAL")
        time_m = self.metric("hostToDeviceTime", "MODERATE")
        bytes_m = self.metric("hostToDeviceBytes", "MODERATE")
        timing = self.metrics_on(ctx, "MODERATE")

        led = getattr(ctx, "ledger", None)

        def fn(it):
            tok = ctx.cancel_token
            for rb in it:
                if tok is not None:
                    tok.check()  # sched/: stop uploads at batch boundaries
                if rb.num_rows == 0:
                    continue
                rows_m.add(rb.num_rows)
                bytes_m.add(rb.nbytes)
                for off in range(0, rb.num_rows, max_rows):
                    if tok is not None:
                        tok.check()  # beat per uploaded chunk, not just
                        # per host batch — one oversized source batch
                        # re-chunks into many uploads
                    chunk = (
                        rb
                        if rb.num_rows <= max_rows
                        else rb.slice(off, max_rows)
                    )
                    ctx.semaphore.acquire_if_necessary()
                    # scopes close BEFORE the yield: a ledger phase (and
                    # the transfer timer) must measure the upload, not the
                    # consumer's work while this generator is suspended
                    if timing:
                        with _lscope(led, "h2d"), time_m.timed():
                            db = host_to_device(chunk, max_str_bytes=max_str)
                    else:
                        with _lscope(led, "h2d"):
                            db = host_to_device(chunk, max_str_bytes=max_str)
                    yield db
                    if rb.num_rows <= max_rows:
                        break

        child = self.children[0]
        from .cpu import CpuScanExec

        if isinstance(child, CpuScanExec) and ctx.session is not None:
            # Session-level upload cache for in-memory relations: repeated
            # collects over the same (immutable) arrow table reuse the
            # device-resident batches instead of re-padding + re-uploading —
            # the device analogue of Spark's in-memory scan staying hot.
            # The cached entry holds a reference to the source table, so
            # id() stays valid for the session's lifetime.
            key = (
                "h2d",
                id(child.source),
                child.num_partitions,
                K.schema_key(schema),  # field names participate here
                max_rows,
                max_str,
            )
            import threading

            # concurrent queries race this LRU (get/insert vs evict-pop →
            # KeyError, double-insert): all cache BOOKKEEPING serializes
            # under one session lock; the uploads themselves stay outside it
            with ctx.session._h2d_lock:
                cache = ctx.session._h2d_cache
                entry = cache.get(key)
                if entry is None:
                    entry = {
                        # pin BOTH: the source anchors the cache key's id()
                        # across pruning passes, the pruned table backs the
                        # uploaded batches
                        "table": (child.source, child.table),
                        "parts": [None] * child.num_partitions,
                        "rows": [0] * child.num_partitions,
                        # per-partition in-flight build event (single-
                        # flight: concurrent cold queries must not each
                        # upload the partition — N transient HBM copies
                        # would defeat the scheduler's admission budget)
                        "building": [None] * child.num_partitions,
                        "lock": threading.Lock(),
                    }
                    # BYTES-bounded LRU: cached uploads are plain references
                    # (never registered with the spill catalog), so this bound
                    # is the ONLY thing standing between many-table sessions
                    # and pinned-HBM OOM. The old 4-ENTRY bound thrashed on
                    # TPC-H's 8-table star schema, re-uploading every table
                    # each run (~3.5s/query over a tunneled link at sf=0.5); a
                    # byte budget keeps whole star schemas resident while still
                    # evicting when the cached set actually grows large.
                    # Arrow nbytes underestimates the padded device footprint —
                    # ~2x covers pow2 row padding; string byte-planes can
                    # exceed it, which only makes eviction earlier (safe side).
                    new_bytes = 2 * child.table.nbytes
                    budget = _upload_cache_budget(ctx.conf)
                    held = sum(c.get("est_bytes", 0) for c in cache.values())
                    while cache and held + new_bytes > budget:
                        old = cache.pop(next(iter(cache)))  # LRU head
                        held -= old.get("est_bytes", 0)
                    entry["est_bytes"] = new_bytes
                    cache[key] = entry
                else:
                    cache[key] = cache.pop(key)  # refresh LRU order
            child_parts = child.execute(ctx)

            def make_cached(p, thunk):
                def it():
                    # single-flight per partition: one builder uploads, the
                    # rest wait on its event and replay; a failed builder
                    # clears its event so a waiter takes over (same
                    # contract as the session's df.cache() store)
                    tok = ctx.cancel_token
                    while True:
                        with entry["lock"]:
                            built = entry["parts"][p]
                            ev = entry["building"][p]
                            builder = built is None and ev is None
                            if builder:
                                ev = entry["building"][p] = threading.Event()
                        if built is not None:
                            # replay: keep the metric honest, no device sync
                            rows_m.add(entry["rows"][p])
                            for db in built:
                                if tok is not None:
                                    tok.check()
                                ctx.semaphore.acquire_if_necessary()
                                yield db
                            return
                        if builder:
                            n_before = rows_m.value
                            try:
                                out = list(fn(thunk()))
                                with entry["lock"]:
                                    entry["parts"][p] = out
                                    entry["rows"][p] = rows_m.value - n_before
                            finally:
                                with entry["lock"]:
                                    entry["building"][p] = None
                                ev.set()
                            for db in out:
                                if tok is not None:
                                    tok.check()
                                yield db
                            return
                        # another query is uploading this partition: wait
                        # for it (cancellable — this thread's own token
                        # still fires at its admission deadline/cancel)
                        while not ev.wait(0.05):
                            if tok is not None:
                                tok.check()

                return it

            return _placed_partitions(
                ctx,
                PartitionSet(
                    [make_cached(p, t) for p, t in enumerate(child_parts.parts)]
                ),
            )

        return _placed_partitions(ctx, child.execute(ctx).map_partitions(fn))


class DeviceToHostExec(Exec):
    """Device batches → host Arrow (GpuColumnarToRow/GpuBringBackToHost)."""

    def __init__(self, child: Exec):
        super().__init__([child])

    @property
    def output(self) -> Schema:
        return self.children[0].output

    def execute(self, ctx: ExecContext) -> PartitionSet:
        rows_m = self.metric("numOutputRows", "ESSENTIAL")
        time_m = self.metric("deviceToHostTime", "MODERATE")
        bytes_m = self.metric("deviceToHostBytes", "MODERATE")
        timing = self.metrics_on(ctx, "MODERATE")
        led = getattr(ctx, "ledger", None)

        # speculate only below execs whose results are usually tiny
        # relative to capacity (a big scan/filter single batch would pay a
        # guaranteed-wasted prefix round trip)
        def _result_shrinking(node) -> bool:
            while isinstance(
                node, (TpuCoalescePartitionsExec, TpuCoalesceBatchesExec)
            ):
                node = node.children[0]
            return isinstance(
                node,
                (
                    TpuHashAggregateExec,
                    TpuTakeOrderedAndProjectExec,
                    TpuLimitExec,
                ),
            )

        speculate = _result_shrinking(self.children[0])

        def fn(it):
            from itertools import islice

            from ..ops.concat import concat_device
            from ..ops.gather import bulk_shrink

            tok = ctx.cancel_token
            while True:
                if tok is not None:
                    tok.check()  # beat per D2H window: the pull below is
                    # where a collect() spends its host time
                # shrink to the live bucket before packing: the pack kernel
                # flattens the whole capacity, so a 6-row aggregate output in
                # a 512k-capacity batch would otherwise ship ~30MB over PJRT.
                # Windowed so at most 8 batches are held on device at once.
                chunk = list(islice(it, 8))
                if not chunk:
                    return
                shrunk = None
                if speculate and len(chunk) == 1:
                    # single batch below a result-shrinking exec (aggregate
                    # / TopN / limit): try the ONE-round-trip speculative
                    # pull before paying the shrink sync + pull pair
                    from ..columnar.device import device_to_host_speculative
                    from ..ops.gather import shrink_one

                    # device-completion wait separated from the copy: the
                    # block costs nothing extra (the transfer would wait
                    # anyway) and splits the ledger's 'device_execute'
                    # from 'd2h' at the only point the host truly waits
                    if led is not None:
                        with led.scope("device_execute"):
                            # graft: ok(host-sync: ledger attribution split
                            # — the D2H pull below would block here anyway)
                            jax.block_until_ready(chunk[0])
                    if timing:
                        with _lscope(led, "d2h"), time_m.timed():
                            rb, n_true = device_to_host_speculative(chunk[0])
                    else:
                        with _lscope(led, "d2h"):
                            rb, n_true = device_to_host_speculative(chunk[0])
                    if rb is not None:
                        ctx.semaphore.release_if_necessary()
                        if rb.num_rows:
                            rows_m.add(rb.num_rows)
                            bytes_m.add(rb.nbytes)
                            yield rb
                        continue
                    if n_true is not None:
                        # the count came back with the failed speculation —
                        # shrink without a second sync (and skip bulk_shrink,
                        # whose row-count fetch would re-pay that sync)
                        shrunk = [shrink_one(chunk[0], n_true, tight=False)]
                if shrunk is None:
                    # lattice-quantized (tight=False): the pack kernel keeps
                    # one stable geometry per shape bucket instead of
                    # compiling per live-row count — still cuts sparse
                    # multi-k capacities down to the floor
                    shrunk = bulk_shrink(chunk, tight=False)
                # merge SMALL shrunk batches on device: every pull is a full
                # tunnel round trip, so 8 tiny result batches as one packed
                # transfer beat 8 separate ones by ~8 RTTs
                if (
                    len(shrunk) > 1
                    and sum(b.capacity for b in shrunk) <= (1 << 16)
                ):
                    shrunk = [concat_device(shrunk)]
                for db in shrunk:
                    if tok is not None:
                        tok.check()
                    from ..mem.spill import with_oom_retry

                    pull = lambda b: device_to_host(b, shrink=False)  # noqa: E731
                    if led is not None:
                        with led.scope("device_execute"):
                            # graft: ok(host-sync: ledger attribution split
                            # — the D2H pull below would block here anyway)
                            jax.block_until_ready(db)
                    if timing:
                        with _lscope(led, "d2h"), time_m.timed():
                            rb = with_oom_retry(ctx.catalog, pull, db)
                    else:
                        with _lscope(led, "d2h"):
                            rb = with_oom_retry(ctx.catalog, pull, db)
                    ctx.semaphore.release_if_necessary()
                    if rb.num_rows:
                        rows_m.add(rb.num_rows)
                        bytes_m.add(rb.nbytes)
                        yield rb

        # Dispatch-ahead pipelining (exec/pipeline.py): the D2H pull above
        # blocks a full host round trip per window; driving the upstream
        # chain from a producer thread keeps batches i+1..k dispatching on
        # device while this sink blocks on batch i. Conf and metrics
        # resolve HERE, on the single-threaded plan walk — partition
        # thunks race on a thread pool.
        from .pipeline import pipe_metrics, pipeline_conf, pipelined_partition

        pconf = pipeline_conf(ctx)
        metrics = pipe_metrics(self, ctx) if pconf is not None else None

        def run(it):
            return pipelined_partition(pconf, ctx, it, fn, metrics)

        return self.children[0].execute(ctx).map_partitions(run)


# ── compute execs ───────────────────────────────────────────────────────────


class TpuRangeExec(Exec):
    """Device-side sequence generation (GpuRangeExec,
    basicPhysicalOperators.scala) — ids are born on device, no H2D copy."""

    def __init__(self, cpu_range):
        super().__init__([])
        self._cpu = cpu_range
        self._schema = cpu_range.output

    @property
    def output(self) -> Schema:
        return self._schema

    @property
    def is_device(self) -> bool:
        return True

    def _fn(self, cap: int):
        schema = self._schema
        step = self._cpu.step

        def make():
            def gen(first, m):
                ids = first + step * jnp.arange(cap, dtype=jnp.int64)
                valid = jnp.arange(cap, dtype=jnp.int32) < m
                from ..types import LONG

                col = DeviceColumn(LONG, jnp.where(valid, ids, 0), valid)
                return DeviceBatch(schema, [col], m.astype(jnp.int32))

            return gen

        return K.jit_kernel(("range", step, cap, schema), make)

    def execute(self, ctx: ExecContext) -> PartitionSet:
        from .. import config as cfg

        batch_rows = cfg.BATCH_SIZE_ROWS.get(ctx.conf)
        start, step = self._cpu.start, self._cpu.step
        parts = []
        for lo, cnt in self._cpu.partition_bounds():
            def make(lo=lo, cnt=cnt):
                def it():
                    ctx.semaphore.acquire_if_necessary()
                    tok = ctx.cancel_token
                    done = 0
                    while done < cnt:
                        if tok is not None:
                            tok.check()
                        m = min(batch_rows, cnt - done)
                        cap = bucket_capacity(max(m, 1))
                        first = start + (lo + done) * step
                        yield self._fn(cap)(
                            jnp.asarray(first, dtype=jnp.int64),
                            jnp.asarray(m, dtype=jnp.int32),
                        )
                        done += m

                return it()

            parts.append(make)
        return PartitionSet(parts)

    def node_string(self):
        c = self._cpu
        return f"TpuRange ({c.start}, {c.end}, step={c.step}, splits={c.num_partitions})"


class _ErrorCheckingKernel:
    """Wraps a jitted kernel returning ``(out, err_flags)``: raises
    ``AnsiError`` host-side when a flag fires (one sync per batch, and only
    for kernels whose expression tree registered error sites — non-ANSI
    queries return a statically-empty flag vector and never sync)."""

    def __init__(self, fn, sites: list):
        self._fn = fn
        self._sites = sites

    def __call__(self, batch, tvals):
        out, errs = self._fn(batch, tvals)
        if errs.shape[0]:
            import numpy as np

            from ..expr.base import AnsiError

            # graft: ok(host-sync: ANSI error-site check — kernels with
            # registered error expressions must surface the raise at THIS
            # batch; non-ANSI trees return a statically-empty flag vector
            # and never reach this sync)
            flags = np.asarray(errs)
            if flags.any():
                raise AnsiError(self._sites[int(np.argmax(flags))])
        return out

    def _cache_size(self):
        cs = getattr(self._fn, "_cache_size", None)
        return cs() if callable(cs) else 0

    def warm(self, *args) -> bool:
        """Pre-compilation passthrough (plan/planner.py precompile_plan)."""
        return self._fn.warm(*args)


def _error_flags(ctx: Ctx, live, sites: list):
    """Collect ANSI error sites registered during tracing into a flag vector
    (and capture their messages — tracing runs this Python code, so the
    closure list is filled before the first batch result is consumed)."""
    import jax.numpy as jnp

    sites[:] = [m for m, _ in ctx.errors]
    if not ctx.errors:
        return jnp.zeros((0,), dtype=bool)
    return jnp.stack([(mask & live).any() for _, mask in ctx.errors])


def project_kernel(exprs: tuple, schema: Schema):
    """Fused projection kernel, cached by (bound exprs, output schema)."""

    def make():
        import jax

        sites: list = []

        def _project(batch: DeviceBatch, tvals):
            c = Ctx.for_device(batch, task=tvals)
            cols = [val_to_column(c, e.eval(c), e.data_type) for e in exprs]
            # keep padding rows inert
            live = batch.row_mask()
            cols = [
                dc_replace(col, validity=col.validity & live)
                for col in cols
            ]
            errs = _error_flags(c, live, sites)
            return DeviceBatch(schema, cols, batch.num_rows), errs

        return _ErrorCheckingKernel(K.GuardedJit(_project), sites)

    return K.kernel(("project", exprs, schema), make)


def filter_kernel(condition: Expression):
    def make():
        import jax

        sites: list = []

        def _filter(batch: DeviceBatch, tvals):
            c = Ctx.for_device(batch, task=tvals)
            v = condition.eval(c)
            keep = c.broadcast_bool(v.data) & v.full_valid(c)
            errs = _error_flags(c, batch.row_mask(), sites)
            return compact(batch, keep), errs

        return _ErrorCheckingKernel(K.GuardedJit(_filter), sites)

    return K.kernel(("filter", condition), make)


class TpuProjectExec(Exec):
    def __init__(
        self,
        exprs: List[Expression],
        child: Exec,
        schema: Optional[Schema] = None,
    ):
        super().__init__([child])
        self.exprs = [bind(e, child.output) for e in exprs]
        # converted plans pass the CPU exec's schema: their exprs are already
        # bound, so output_name() would yield colN placeholders — and the
        # kernel bakes the schema into the DeviceBatch it emits
        self._schema = schema or Schema(
            [
                StructField(output_name(e0), e.data_type, e.nullable)
                for e0, e in zip(exprs, self.exprs)
            ]
        )
        self._needs_task = any(contains_task_dependent(e) for e in self.exprs)
        self._fn = project_kernel(tuple(self.exprs), self._schema)

    @property
    def output(self) -> Schema:
        return self._schema

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        fn = self._fn
        needs_task = self._needs_task

        def run(it):
            # splittable-operator opt-in: OOM at a launch spills, retries,
            # then recursively halves the batch (resilience/retry.py)
            return task.run_device(
                fn, it, needs_task, catalog=ctx.catalog,
                policy=ctx.retry_policy, op="ProjectExec",
                breaker=ctx.breaker, token=ctx.cancel_token,
            )

        return self.children[0].execute(ctx).map_partitions(run)

    def node_string(self):
        return f"TpuProject [{', '.join(map(str, self.exprs))}]"


class TpuFilterExec(Exec):
    def __init__(self, condition: Expression, child: Exec):
        super().__init__([child])
        self.condition = bind(condition, child.output)

        self._needs_task = contains_task_dependent(self.condition)
        self._fn = filter_kernel(self.condition)

    @property
    def output(self) -> Schema:
        return self.children[0].output

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        fn = self._fn
        needs_task = self._needs_task

        def run(it):
            # splittable: a filter over concat(a, b) is concat(filter(a),
            # filter(b)) — halves yield independently under OOM pressure
            return task.run_device(
                fn, it, needs_task, catalog=ctx.catalog,
                policy=ctx.retry_policy, op="FilterExec",
                breaker=ctx.breaker, token=ctx.cancel_token,
            )

        return self.children[0].execute(ctx).map_partitions(run)

    def node_string(self):
        return f"TpuFilter {self.condition}"


class TpuUnionExec(Exec):
    def __init__(self, children: List[Exec]):
        super().__init__(children)

    @property
    def output(self) -> Schema:
        return self.children[0].output

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        parts = []
        for c in self.children:
            parts.extend(c.execute(ctx).parts)
        return PartitionSet(parts)


class TpuCoalescePartitionsExec(Exec):
    def __init__(self, child: Exec):
        super().__init__([child])

    @property
    def output(self) -> Schema:
        return self.children[0].output

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        from .. import config as cfg

        child_parts = self.children[0].execute(ctx)
        n_workers = min(
            len(child_parts.parts), cfg.CONCURRENT_TPU_TASKS.get(ctx.conf)
        )

        def it():
            if n_workers <= 1 or len(child_parts.parts) == 1:
                # graft: ok(cancel-beat: delegates to the upstream
                # partition iterators, which beat per batch)
                for t in child_parts.parts:
                    yield from t()
                return
            # drive child partitions concurrently (each per-partition chain
            # of kernel dispatches pays tunnel RTTs; overlapping them is the
            # executor-task-slot model this node would otherwise collapse).
            # At most n_workers partitions are buffered at once (memory
            # bound), and each worker returns its semaphore permit when its
            # partition completes.
            from concurrent.futures import ThreadPoolExecutor

            # straggler speculation (sched/speculation.py): this node IS
            # the engine's executor-task-slot surface — the coalesce of a
            # collect() drives every leaf partition — so the monitor
            # watches HERE. A partition past the runtime bar gets a
            # duplicate attempt of the same pure thunk; first commit wins,
            # the loser unwinds through an attempt-scoped child token.
            spec = None
            token = getattr(ctx, "cancel_token", None)
            if cfg.SPECULATION_ENABLED.get(ctx.conf) and token is not None:
                from ..sched.speculation import SpeculationMonitor

                scheduler = getattr(ctx.session, "_scheduler", None)
                spec = SpeculationMonitor.from_conf(
                    ctx.conf, ctx=ctx, token=token,
                    pool=getattr(scheduler, "pool", None),
                    n_partitions=len(child_parts.parts),
                )

            def run_one(i, t):
                from ..resilience import faults as _faults

                if spec is None:
                    try:
                        _faults.on_task_attempt(i, 0, token)
                        return list(t())
                    finally:
                        ctx.semaphore.release_if_necessary()

                def attempt(attempt_token):
                    try:
                        # chaos straggler point: the first attempt of the
                        # configured partition crawls; a duplicate runs free
                        _faults.on_task_attempt(i, 0, attempt_token)
                        return list(t())
                    finally:
                        # primary runs on this worker thread, a duplicate
                        # on the monitor's — each returns its own permit
                        ctx.semaphore.release_if_necessary()

                return spec.run_partition(i, attempt)

            parts = child_parts.parts
            try:
                with ThreadPoolExecutor(max_workers=n_workers) as pool:
                    pending = {
                        i: pool.submit(run_one, i, parts[i])
                        for i in range(min(n_workers, len(parts)))
                    }
                    nxt = len(pending)
                    # graft: ok(cancel-beat: the worker threads drive the
                    # upstream iterators (which beat per batch); a cancel
                    # raises inside run_one and surfaces through result())
                    for i in range(len(parts)):
                        batches = pending.pop(i).result()
                        if nxt < len(parts):
                            pending[nxt] = pool.submit(run_one, nxt, parts[nxt])
                            nxt += 1
                        yield from batches
            finally:
                if spec is not None:
                    spec.close()

        return PartitionSet([it])


# Largest [capacity, W] collect element plane the device path will build
# (~1GB of int64). Beyond it (one group holding most of a huge input) the
# padded layout is the wrong tool — the query fails with the kill-switch
# hint instead of OOMing the device.
_COLLECT_PLANE_LIMIT = 1 << 27


class TpuHashAggregateExec(Exec):
    """Sort-based group-by on device; one phase (partial|final|complete).

    The reference's hot loop (aggregate.scala:406-468) is: per-batch update
    aggregate → concat partials → merge aggregate. Here both update and merge
    are the same fused kernel with different reduce ops.
    """

    def __init__(
        self,
        mode: str,
        grouping: List[Expression],
        agg_fns: List[AggregateFunction],
        result_exprs: Optional[List[Expression]],
        result_names: Optional[List[str]],
        child: Exec,
    ):
        super().__init__([child])
        self.mode = mode
        self.grouping = [bind(g, child.output) for g in grouping]
        self.agg_fns = list(agg_fns)
        self.result_exprs = None if result_exprs is None else list(result_exprs)
        self.result_names = None if result_names is None else list(result_names)
        self._schema = self._compute_schema(child)

    def _compute_schema(self, child: Exec) -> Schema:
        fields = []
        for g in self.grouping:
            fields.append(StructField(output_name(g), g.data_type, g.nullable))
        if self.mode == "partial":
            for i, f in enumerate(self.agg_fns):
                for j, bt in enumerate(f.buffer_types):
                    fields.append(StructField(f"buf{i}_{j}", bt, True))
            return Schema(fields)
        assert self.result_exprs is not None
        return Schema(
            [
                StructField(name, e.data_type, e.nullable)
                for name, e in zip(self.result_names, self.result_exprs)
            ]
        )

    @property
    def output(self) -> Schema:
        return self._schema

    @property
    def is_device(self) -> bool:
        return True

    def _buffer_ordinal(self, f: AggregateFunction, j: int) -> int:
        return _buffer_ordinal(self.grouping, self.agg_fns, f, j)

    def _make_kernel(
        self, child_schema: Schema, pre_filter=None, has_nans=True,
        collect_width: int = 0,
    ):
        return aggregate_kernel(
            self.mode,
            tuple(self.grouping),
            tuple(self.agg_fns),
            None if self.result_exprs is None else tuple(self.result_exprs),
            self._schema,
            child_schema,
            pre_filter,
            has_nans,
            collect_width,
        )

    @property
    def _has_collect(self) -> bool:
        return any(
            op in ("collect_list", "collect_set")
            for f in self.agg_fns
            for op in f.update_ops
        )

    def _width_kernel(self, child_schema: Schema, pre_filter, has_nans):
        """Max-group-size pre-pass for the collect plane width (one host
        sync per partition — the join sizes its output buckets the same
        way)."""
        grouping = tuple(self.grouping)

        def make():
            def _width(batch: DeviceBatch):
                from ..ops.aggregate import group_max_size

                c = Ctx.for_device(batch)
                live = batch.row_mask()
                if pre_filter is not None:
                    fv = pre_filter.eval(c)
                    live = live & c.broadcast_bool(fv.data) & fv.full_valid(c)
                if not grouping:
                    return live.sum().astype(jnp.int32)
                key_cols = [
                    val_to_column(c, g.eval(c), g.data_type) for g in grouping
                ]
                key_cols = [
                    dc_replace(k, validity=k.validity & live) for k in key_cols
                ]
                work = DeviceBatch(
                    Schema(
                        [
                            StructField(f"k{i}", k.dtype, True)
                            for i, k in enumerate(key_cols)
                        ]
                    ),
                    key_cols,
                    batch.num_rows,
                )
                return group_max_size(
                    work,
                    list(range(len(key_cols))),
                    live_mask=live if pre_filter is not None else None,
                    has_nans=has_nans,
                )

            return _width

        key = ("agg_width", grouping, child_schema, pre_filter, has_nans)
        return K.jit_kernel(key, make)

    def _fused_child(self) -> tuple:
        """(effective child, fused pre_filter) — the filter-fusion decision,
        shared by execute() and the kernel pre-compilation pass so both see
        the SAME kernel. Fusing folds the filter predicate into the
        aggregate as a liveness mask: a filter's schema equals its child's,
        so bindings hold, and the compaction gather of every column is
        skipped entirely. Filters with error sites (ANSI casts, split
        overflow) stay standalone — fusion would bypass their kernel error
        channel."""
        child = self.children[0]
        if (
            self.mode in ("partial", "complete")
            and isinstance(child, TpuFilterExec)
            and not child._needs_task
            and not _expr_has_error_site(child.condition)
        ):
            return child.children[0], child.condition
        return child, None

    def execute(self, ctx: ExecContext) -> PartitionSet:
        child, pre_filter = self._fused_child()
        from .. import config as cfg
        from ..resilience import retry as R

        child_schema = child.output
        has_nans = cfg.HAS_NANS.get(ctx.conf)
        kernel = self._make_kernel(child_schema, pre_filter, has_nans)
        merge_jit = self._merge_jit(has_nans)
        catalog, policy, breaker = ctx.catalog, ctx.retry_policy, ctx.breaker

        def run(it):
            if self.mode == "partial":
                # per-batch update aggregate, then concat + merge — the
                # reference's hot loop (aggregate.scala:406-468). Multi-batch
                # partitions shrink outputs to the live-group bucket before
                # the merge concat; single-batch outputs are shrunk by the
                # consumer (exchange) in one cross-partition bulk sync.
                # The update kernel is splittable (partials from the two
                # halves merge downstream exactly like two input batches),
                # so OOM escalates through the split state machine.
                partials = []
                for db in it:
                    partials.extend(
                        R.run_with_retry(
                            catalog, kernel, db, policy,
                            op="HashAggregateExec", breaker=breaker,
                        )
                    )
                if not partials:
                    if self.grouping:
                        return
                    partials = [kernel(empty_batch(child_schema))]
                if len(partials) == 1:
                    yield partials[0]
                else:
                    partials = bulk_shrink(partials)
                    yield R.run_once(
                        catalog, merge_jit, concat_device(partials), policy,
                        op="HashAggregateExec", breaker=breaker,
                    )
                return
            # final/complete: single merge+evaluate over the whole partition
            # (NOT splittable: merging halves separately would emit two
            # partial groups per key — spill-retry only)
            batches = list(it)
            if not batches:
                if self.grouping:
                    return
                batches = [empty_batch(child_schema)]
            merged = batches[0] if len(batches) == 1 else concat_device(batches)
            if self._has_collect:
                # collect plane width from the max-group-size pre-pass
                # (bucketed so recompiles stay logarithmic in group size).
                # Shrink first: the [capacity, W] element plane scales with
                # BOTH factors, and a sparse merged batch inflates capacity.
                merged = bulk_shrink([merged])[0]
                w = int(self._width_kernel(child_schema, pre_filter, has_nans)(merged))
                width = bucket_capacity(max(w, 1))
                if merged.capacity * width > _COLLECT_PLANE_LIMIT:
                    raise RuntimeError(
                        "device collect_list/collect_set needs a "
                        f"[{merged.capacity}, {width}] element plane "
                        f"(> {_COLLECT_PLANE_LIMIT} elements) — a single "
                        "group holds too many rows for the padded device "
                        "layout; disable the device path with "
                        "spark.rapids.sql.expression.CollectList=false / "
                        "spark.rapids.sql.expression.CollectSet=false"
                    )
                ck = self._make_kernel(
                    child_schema,
                    pre_filter,
                    has_nans,
                    collect_width=width,
                )
                yield R.run_once(
                    catalog, ck, merged, policy,
                    op="HashAggregateExec", breaker=breaker,
                )
                return
            yield R.run_once(
                catalog, kernel, merged, policy,
                op="HashAggregateExec", breaker=breaker,
            )

        return child.execute(ctx).map_partitions(run)

    def _merge_jit(self, has_nans=True):
        return aggregate_merge_kernel(
            tuple(self.grouping), tuple(self.agg_fns), self._schema, has_nans
        )

    def node_string(self):
        return (
            f"TpuHashAggregate({self.mode}) keys={[str(g) for g in self.grouping]} "
            f"aggs={[str(a) for a in self.agg_fns]}"
        )




def _buffer_ordinal(grouping, agg_fns, f: AggregateFunction, j: int) -> int:
    """Ordinal of buffer ``j`` of ``f`` in the keys ++ buffers layout."""
    base = len(grouping)
    for g in agg_fns:
        if g is f:
            return base + j
        base += len(g.buffer_types)
    raise KeyError


def aggregate_kernel(
    mode: str,
    grouping: tuple,
    agg_fns: tuple,
    result_exprs,
    out_schema: Schema,
    child_schema: Schema,
    pre_filter: Optional[Expression] = None,
    has_nans: bool = True,
    collect_width: int = 0,
):
    """The fused group-aggregate program (update or merge+evaluate), cached
    by the full aggregation signature. ``pre_filter`` fuses a child filter's
    predicate in as a liveness mask — no compaction (a full gather of every
    column, slow on TPU) between the filter and the aggregate."""

    def make():
        def _aggregate(batch: DeviceBatch) -> DeviceBatch:
            c = Ctx.for_device(batch)
            live = batch.row_mask()
            if pre_filter is not None:
                fv = pre_filter.eval(c)
                live = live & c.broadcast_bool(fv.data) & fv.full_valid(c)
            # materialize grouping keys + agg inputs as columns
            key_cols = [
                val_to_column(c, g.eval(c), g.data_type) for g in grouping
            ]
            key_cols = [
                dc_replace(k, validity=k.validity & live)
                for k in key_cols
            ]
            in_cols: list[DeviceColumn] = []
            ops: list[str] = []
            for f in agg_fns:
                if mode in ("partial", "complete"):
                    exprs = [bind(e, child_schema) for e in f.update_exprs]
                    for e, op in zip(exprs, f.update_ops):
                        col = val_to_column(c, e.eval(c), e.data_type)
                        in_cols.append(
                            dc_replace(col, validity=col.validity & live)
                        )
                        ops.append(op)
                else:
                    for j, op in enumerate(f.merge_ops):
                        in_cols.append(batch.columns[_buffer_ordinal(grouping, agg_fns, f, j)])
                        ops.append(op)
            tmp_schema = Schema(
                [StructField(f"k{i}", k.dtype, True) for i, k in enumerate(key_cols)]
            )
            work = DeviceBatch(
                Schema(list(tmp_schema.fields)), key_cols, batch.num_rows
            )
            # group_aggregate works on a batch containing the key columns;
            # ungrouped reductions force one output group even when empty
            out_keys, out_aggs, num_groups = group_aggregate(
                work,
                list(range(len(key_cols))),
                in_cols,
                ops,
                min_groups=0 if grouping else 1,
                live_mask=live if pre_filter is not None else None,
                has_nans=has_nans,
                collect_width=collect_width,
            )
            if mode == "partial":
                cols = out_keys + out_aggs
                return DeviceBatch(out_schema, cols, num_groups)
            # final/complete: evaluate aggregates + result projection
            cap = batch.capacity
            gctx = Ctx(jnp, cap, True, [Val(k.data, k.validity, k.lengths) for k in out_keys], num_groups)
            agg_results: list[Val] = []
            i = 0
            for f in agg_fns:
                nbuf = len(f.buffer_types)
                bufs = [
                    Val(
                        out_aggs[i + j].data,
                        out_aggs[i + j].validity,
                        out_aggs[i + j].lengths,
                        out_aggs[i + j].children,
                    )
                    for j in range(nbuf)
                ]
                agg_results.append(f.evaluate(gctx, bufs))
                i += nbuf
            rctx = Ctx(
                jnp,
                cap,
                True,
                [Val(k.data, k.validity, k.lengths) for k in out_keys] + agg_results,
                num_groups,
            )
            glive = jnp.arange(cap, dtype=jnp.int32) < num_groups
            cols = []
            for e in result_exprs:
                col = val_to_column(rctx, e.eval(rctx), e.data_type)
                cols.append(dc_replace(col, validity=col.validity & glive))
            return DeviceBatch(out_schema, cols, num_groups)

        return _aggregate

    key = (
        "agg",
        mode,
        grouping,
        agg_fns,
        result_exprs,
        out_schema,
        child_schema,
        pre_filter,
        has_nans,
        collect_width,
    )
    return K.jit_kernel(key, make)


def aggregate_merge_kernel(
    grouping: tuple, agg_fns: tuple, out_schema: Schema, has_nans: bool = True
):
    """Merge-mode aggregation kernel over (concatenated) partial batches.
    The partial-output layout is keys ++ buffers, so key ordinals and
    _buffer_ordinal line up with the final layout."""

    def make():
        def _m(batch: DeviceBatch) -> DeviceBatch:
            in_cols = []
            ops = []
            for f in agg_fns:
                for j, op in enumerate(f.merge_ops):
                    in_cols.append(batch.columns[_buffer_ordinal(grouping, agg_fns, f, j)])
                    ops.append(op)
            out_keys, out_aggs, num_groups = group_aggregate(
                batch,
                list(range(len(grouping))),
                in_cols,
                ops,
                min_groups=0 if grouping else 1,
                has_nans=has_nans,
            )
            return DeviceBatch(out_schema, out_keys + out_aggs, num_groups)

        return _m

    return K.jit_kernel(
        ("agg_merge", grouping, agg_fns, out_schema, has_nans), make
    )


class TpuSortExec(Exec):
    """Per-partition sort. Two modes (GpuSortExec.scala:36-42,212-510):

    * single-batch: coalesce the partition into one batch and sort it;
    * out-of-core: when the partition exceeds the configured threshold, sort
      each incoming batch into a *run*, park runs in the spill catalog
      (device→host→disk as memory demands), then merge runs pairwise — at
      most two runs are device-resident at any moment.
    """

    def __init__(self, order: List[SortOrder], child: Exec):
        super().__init__([child])
        self.order = [
            SortOrder(bind(o.child, child.output), o.ascending, o.nulls_first)
            for o in order
        ]

    @property
    def output(self) -> Schema:
        return self.children[0].output

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        from .. import config as cfg
        from ..mem.spill import SpillPriorities, with_oom_retry

        _sort = device_sort_fn(self.order)
        _merge = device_merge_fn(self.order)
        threshold = cfg.OUT_OF_CORE_SORT_THRESHOLD.get(ctx.conf)
        catalog = ctx.catalog

        def make_run(b):
            """Sort one input batch into a spillable run; drop the input ref."""
            from ..mem.spill import _batch_device

            catalog.ensure_headroom(2 * b.size_bytes(), _batch_device(b))
            return catalog.register(
                with_oom_retry(catalog, _sort, b), SpillPriorities.WORKING
            )

        def run(it):
            # Stream the input: buffer small partitions for the single-batch
            # fast path; past the threshold, convert each incoming batch into
            # a sorted spillable run immediately so the unsorted input never
            # accumulates on device.
            pending, pending_bytes, runs = [], 0, None
            for b in it:
                if runs is None:
                    pending.append(b)
                    pending_bytes += b.size_bytes()
                    if pending_bytes > threshold and len(pending) > 1:
                        runs = [make_run(p) for p in pending]
                        pending = []
                else:
                    runs.append(make_run(b))
            if runs is None:
                if not pending:
                    return
                merged = concat_device(pending)
                del pending
                yield with_oom_retry(catalog, _sort, merged)
                return
            # Staged binary merge of sorted runs — a TRUE merge kernel
            # (binary-search ranks, linear work per level, O(n log k) total)
            # instead of re-sorting each concatenation; operands get_batch()
            # pins so the retry-spill cannot evict what it is merging.
            while len(runs) > 1:
                nxt = []
                for i in range(0, len(runs) - 1, 2):
                    a, b = runs[i], runs[i + 1]

                    def merge_pair(a=a, b=b):
                        # pin the operands FIRST so the headroom pass (and
                        # any retry-spill) cannot evict what is being merged
                        ba, bb = a.get_batch(), b.get_batch()
                        from ..mem.spill import _batch_device

                        catalog.ensure_headroom(
                            2 * (a.size_bytes + b.size_bytes),
                            _batch_device(ba),
                        )
                        return _merge(ba, bb)

                    out = with_oom_retry(catalog, merge_pair)
                    a.close(), b.close()
                    nxt.append(catalog.register(out, SpillPriorities.WORKING))
                if len(runs) % 2:
                    nxt.append(runs[-1])
                runs = nxt
            with runs[0] as final:
                yield final.get_batch()

        return self.children[0].execute(ctx).map_partitions(run)

    def node_string(self):
        return f"TpuSort [{', '.join(map(str, self.order))}]"


def _order_key(order: List[SortOrder]) -> tuple:
    return tuple((o.child, o.ascending, o.resolved_nulls_first()) for o in order)


def device_sort_fn(order: List[SortOrder]):
    """Jitted whole-batch sort kernel shared by TpuSortExec and TopN."""
    order = list(order)

    def make():
        def _sort(batch: DeviceBatch) -> DeviceBatch:
            c = Ctx.for_device(batch)
            live = batch.row_mask()
            words = []
            for o in order:
                col = val_to_column(c, o.child.eval(c), o.child.data_type)
                col = dc_replace(col, validity=col.validity & live)
                from ..ops.sortkeys import column_radix_words

                words.extend(
                    column_radix_words(col, o.ascending, o.resolved_nulls_first())
                )
            perm = sort_permutation(words, live)
            return gather_batch(batch, perm, batch.num_rows)

        return _sort

    return K.jit_kernel(("sort", _order_key(order)), make)


def device_merge_fn(order: List[SortOrder]):
    """Two-run merge: concat the sorted runs (live segments land at [0, na)
    and [na, na+nb)), then ONE jitted kernel rebuilds radix words and
    gathers through ``merge_permutation``'s binary-search ranks — O(n log n)
    GATHERS per level instead of re-running the sort, whose TPU lowering is
    a sorting network with per-pass cost far above a gather sweep (see
    sort_permutation's compile-time notes). The reference's true
    out-of-core merge (GpuSortExec.scala:212-510). Caveat measured on the
    XLA-CPU backend: its lax.sort is a fast comparison sort, so there the
    re-sort wins — the merge is sized for TPU economics. The concat runs as
    its own cached kernel (kernels must not nest compiles)."""
    order = list(order)

    def make():
        def _merge(merged: DeviceBatch, na, nb) -> DeviceBatch:
            from ..ops.sortkeys import column_radix_words, merge_permutation

            c = Ctx.for_device(merged)
            words = []
            for o in order:
                col = val_to_column(c, o.child.eval(c), o.child.data_type)
                col = dc_replace(col, validity=col.validity & merged.row_mask())
                words.extend(
                    column_radix_words(col, o.ascending, o.resolved_nulls_first())
                )
            perm = merge_permutation(words, na, nb)
            return gather_batch(merged, perm, na + nb)

        return _merge

    kernel = K.jit_kernel(("merge_runs", _order_key(order)), make)

    def merge(ba: DeviceBatch, bb: DeviceBatch) -> DeviceBatch:
        import jax.numpy as jnp

        na, nb = ba.num_rows, bb.num_rows
        merged = concat_device([ba, bb])
        return kernel(
            merged,
            jnp.asarray(na, jnp.int32),
            jnp.asarray(nb, jnp.int32),
        )

    return merge


def _slice_head_impl(batch: DeviceBatch, take) -> DeviceBatch:
    """First min(num_rows, take) rows — shared by limit and TopN (module-
    level jit: one program per batch signature, cached for the process)."""
    take = jnp.minimum(batch.num_rows, take)
    live = jnp.arange(batch.capacity, dtype=jnp.int32) < take
    cols = [
        dc_replace(c, validity=c.validity & live)
        for c in batch.columns
    ]
    return DeviceBatch(batch.schema, cols, take.astype(jnp.int32))


slice_head = K.GuardedJit(_slice_head_impl)


def _radix_select_kth(w: "jax.Array", k: int) -> "jax.Array":
    """Exact k-th smallest of a uint64 vector, MSB→LSB radix select: fix
    one bit per step by counting how many values share the built prefix
    with the current bit 0. O(64·n) fully-vectorized elementwise work —
    no sorting network, no top_k."""
    def body(i, state):
        prefix, kk = state
        shift = jnp.uint64(63) - i.astype(jnp.uint64)
        bit = jnp.uint64(1) << shift
        # bits at/above the current position
        hi_mask = ~(bit - jnp.uint64(1))
        cnt0 = ((w & hi_mask) == prefix).sum(dtype=jnp.int64)
        take1 = kk > cnt0
        prefix = jnp.where(take1, prefix | bit, prefix)
        kk = jnp.where(take1, kk - cnt0, kk)
        return prefix, kk

    prefix, _ = jax.lax.fori_loop(
        0, 64, body, (jnp.uint64(0), jnp.asarray(k, jnp.int64))
    )
    return prefix


class TpuTakeOrderedAndProjectExec(Exec):
    """TopN on device: per-partition sort + head(n), then merged final
    sort + head(n) (reference: GpuTakeOrderedAndProjectExec, limit.scala)."""

    def __init__(self, n: int, order: List[SortOrder], child: Exec):
        super().__init__([child])
        self.n = n
        self.order = [
            SortOrder(bind(o.child, child.output), o.ascending, o.nulls_first)
            for o in order
        ]
        self.prefilter_hits = 0  # observability: candidate fast path taken

    @property
    def output(self) -> Schema:
        return self.children[0].output

    @property
    def is_device(self) -> bool:
        return True

    # below this capacity the full sort is cheap enough that the candidate
    # pass's extra host sync would dominate
    TOPK_MIN_CAPACITY = 1 << 15

    def _candidate_fn(self):
        """(mask, count) of rows whose FIRST radix word ties or beats the
        n-th best — a superset of the true top-n (ties at the boundary are
        kept; later sort keys only reorder within first-word ties). Lets
        TopN avoid the full multi-word sort of a huge padded batch: top_k
        is O(cap·log n), then only the candidates get sorted."""
        order = self.order
        k = self.n

        def make():
            def cand(batch: DeviceBatch):
                c = Ctx.for_device(batch)
                live = batch.row_mask()
                o = order[0]
                col = val_to_column(c, o.child.eval(c), o.child.data_type)
                col = dc_replace(col, validity=col.validity & live)
                from ..ops.sortkeys import column_radix_words

                # value_only: for unpacked layouts (64-bit/string/double)
                # word [0] would be the standalone VALIDITY word — a {0,1}
                # threshold that degenerates the prefilter (sortkeys.py's
                # docstring forbids slicing word 0). Nulls get explicit
                # boundary keys per the null ordering instead.
                w0 = column_radix_words(
                    col,
                    o.ascending,
                    o.resolved_nulls_first(),
                    value_only=True,
                )[0]
                dead = jnp.uint64(0xFFFFFFFFFFFFFFFF)
                null_key = (
                    jnp.uint64(0) if o.resolved_nulls_first() else dead
                )
                w0 = jnp.where(col.validity, w0, null_key)
                w0 = jnp.where(live, w0, dead)
                kk = min(k, int(w0.shape[0]))
                # k-th smallest via radix-select: 64 masked count-reductions
                # (lax.top_k at this size lowers to a pathological full
                # sort on TPU — measured minutes at 2M rows)
                kth = _radix_select_kth(w0, kk)
                mask = live & (w0 <= kth)
                return mask, mask.sum(dtype=jnp.int32)

            return K.GuardedJit(cand)

        return K.kernel(("topn_cand", _order_key(self.order), self.n), make)

    def execute(self, ctx: ExecContext) -> PartitionSet:
        n = jnp.asarray(self.n, jnp.int32)
        sort_fn = device_sort_fn(self.order)
        cand_fn = self._candidate_fn()
        limit = self.n

        def topn(batches):
            if not batches:
                return None
            merged = batches[0] if len(batches) == 1 else concat_device(batches)
            cand_cap = bucket_capacity(max(4 * limit, 4096))
            if (
                merged.capacity >= self.TOPK_MIN_CAPACITY
                # the gathered candidate batch must be meaningfully smaller
                # than the input or the pass does strictly more work
                and cand_cap <= merged.capacity // 4
            ):
                mask, cnt = cand_fn(merged)
                cnt = int(cnt)  # one host sync buys skipping the big sort
                if cnt <= cand_cap:
                    self.prefilter_hits += 1
                    # fixed-size nonzero + gather: O(cap) scan, NO sorting
                    # network over the huge padded batch (compact's argsort
                    # would be exactly the cost this path exists to skip)
                    def make_gather(cc=cand_cap):
                        def g(b: DeviceBatch, m: jax.Array):
                            idx = jnp.nonzero(
                                m, size=cc, fill_value=b.capacity - 1
                            )[0].astype(jnp.int32)
                            taken = m.sum(dtype=jnp.int32)
                            out = gather_batch(b, idx, taken)
                            live = (
                                jnp.arange(cc, dtype=jnp.int32) < taken
                            )
                            cols = [
                                dc_replace(c2, validity=c2.validity & live)
                                for c2 in out.columns
                            ]
                            return DeviceBatch(out.schema, cols, taken)

                        return K.GuardedJit(g)

                    gather_fn = K.kernel(
                        (
                            "topn_gather",
                            merged.schema,
                            merged.capacity,
                            cand_cap,
                        ),
                        make_gather,
                    )
                    return slice_head(sort_fn(gather_fn(merged, mask)), n)
            return slice_head(sort_fn(merged), n)

        child_parts = self.children[0].execute(ctx)

        def it():
            partials = []
            for t in child_parts.parts:
                out = topn(list(t()))
                if out is not None:
                    partials.append(out)
            final = topn(partials)
            if final is not None:
                yield final

        return PartitionSet([it])

    def node_string(self):
        return f"TpuTakeOrderedAndProject n={self.n} [{', '.join(map(str, self.order))}]"


class TpuExpandExec(Exec):
    """Projection-list fan-out per batch (GpuExpandExec analogue): each
    projection compiles into the same fused kernel; output batches share the
    input's row count."""

    def __init__(self, projections: List[List[Expression]], names: List[str], child: Exec):
        super().__init__([child])
        self.projections = [
            [bind(e, child.output) for e in proj] for proj in projections
        ]
        from ..types import NullType

        fields = []
        for i, name in enumerate(names):
            es = [proj[i] for proj in self.projections]
            dt = next(
                (e.data_type for e in es if not isinstance(e.data_type, NullType)),
                es[0].data_type,
            )
            fields.append(StructField(name, dt, any(e.nullable for e in es)))
        self._schema = Schema(fields)
        schema = self._schema
        projections = tuple(tuple(p) for p in self.projections)

        def make():
            def _expand(batch: DeviceBatch) -> list[DeviceBatch]:
                c = Ctx.for_device(batch)
                live = batch.row_mask()
                out = []
                for proj in projections:
                    cols = []
                    for e, f in zip(proj, schema):
                        col = val_to_column(c, e.eval(c), f.data_type)
                        cols.append(
                            dc_replace(col, dtype=f.data_type, validity=col.validity & live)
                        )
                    out.append(DeviceBatch(schema, cols, batch.num_rows))
                return out

            return _expand

        self._fn = K.jit_kernel(("expand", projections, schema), make)

    @property
    def output(self) -> Schema:
        return self._schema

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        fn = self._fn

        def run(it):
            tok = ctx.cancel_token
            for db in it:
                if tok is not None:
                    tok.check()
                yield from fn(db)

        return self.children[0].execute(ctx).map_partitions(run)

    def node_string(self):
        return f"TpuExpand x{len(self.projections)}"


class TpuGenerateExec(Exec):
    """explode/posexplode on device (GpuGenerateExec.scala analogue).

    TPU-first: instead of cudf's Table.explode, output slot j maps to
    (row r_j, element p_j) via a vectorized ``searchsorted`` over the
    cumulative element counts — log-depth, no scatters, static output
    capacity bucketed from one host sync of the total element count."""

    def __init__(self, cpu_gen, child: Exec):
        super().__init__([child])
        self.generator = cpu_gen.generator  # bound against same schema
        self.out_names = cpu_gen.out_names
        self._schema = cpu_gen.output

    @property
    def output(self) -> Schema:
        return self._schema

    @property
    def is_device(self) -> bool:
        return True

    def _lengths_kernel(self):
        g = self.generator

        def make():
            def fn(batch: DeviceBatch):
                c = Ctx.for_device(batch)
                v = g.child.eval(c)
                live = batch.row_mask() & c.broadcast_bool(v.valid)
                lengths = jnp.where(live, c.broadcast(v.lengths), 0).astype(jnp.int32)
                return lengths, lengths.sum()

            return fn

        return K.jit_kernel(("gen_lengths", g), make)

    def _explode_kernel(self, out_cap: int):
        from ..types import MapType

        g = self.generator
        out_schema = self._schema
        is_map = isinstance(g.child.data_type, MapType)
        position = g.position

        def make():
            def fn(batch: DeviceBatch, lengths, total):
                c = Ctx.for_device(batch)
                v = g.child.eval(c)
                coff = jnp.cumsum(lengths)
                j = jnp.arange(out_cap, dtype=jnp.int32)
                r = jnp.searchsorted(coff, j, side="right").astype(jnp.int32)
                live = j < total
                r = jnp.clip(r, 0, batch.capacity - 1)
                prev = jnp.where(r > 0, coff[jnp.clip(r - 1, 0, None)], 0)
                p = (j - prev).astype(jnp.int32)
                out_cols = [gather_column(col, r, live) for col in batch.columns]
                if position:
                    from ..types import INT

                    out_cols.append(
                        DeviceColumn(INT, jnp.where(live, p, 0), live)
                    )
                planes = v.children
                gctx = Ctx(jnp, out_cap, True, [], total)
                if is_map:
                    for plane, dt in (
                        (planes[0], g.child.data_type.key_type),
                        (planes[1], g.child.data_type.value_type),
                    ):
                        ev = _plane_element(plane, r, p, live)
                        out_cols.append(val_to_column(gctx, ev, dt))
                else:
                    ev = _plane_element(planes[0], r, p, live)
                    out_cols.append(
                        val_to_column(gctx, ev, g.child.data_type.element_type)
                    )
                return DeviceBatch(out_schema, out_cols, total.astype(jnp.int32))

            return fn

        return K.jit_kernel(("gen_explode", g, out_schema, out_cap), make)

    def execute(self, ctx: ExecContext) -> PartitionSet:
        lk = self._lengths_kernel()

        def run(it):
            tok = ctx.cancel_token
            for db in it:
                if tok is not None:
                    tok.check()
                lengths, total_dev = lk(db)
                # graft: ok(host-sync: the explode output CAPACITY must be
                # chosen on host (bucketed jit signature) — one scalar
                # pull per batch is inherent to row-expanding generators)
                total = int(total_dev)
                if total == 0:
                    continue
                out_cap = bucket_capacity(total)
                yield self._explode_kernel(out_cap)(
                    db, lengths, jnp.asarray(total, jnp.int32)
                )

        return self.children[0].execute(ctx).map_partitions(run)

    def node_string(self):
        return f"TpuGenerate {self.generator}"


def _plane_element(plane: DeviceColumn, r, p, live):
    """Element (r_j, p_j) of a padded element plane as a Val."""
    W = plane.data.shape[1]
    safe = jnp.clip(p, 0, W - 1)
    data = plane.data[r, safe]
    valid = plane.validity[r, safe] & live
    lengths = None
    if plane.lengths is not None:
        lengths = jnp.where(live, plane.lengths[r, safe], 0)
    if data.ndim == 2:
        data = jnp.where(live[:, None], data, 0)
    else:
        data = jnp.where(live, data, jnp.zeros_like(data))
    return Val(data, valid, lengths)


# which join side may be SPLIT under skew (the other side is replicated;
# replication must not be able to emit unmatched rows of its own side)
_SPLITTABLE_SIDES = {
    "inner": ("left", "right"),
    "left": ("left",),
    "left_semi": ("left",),
    "left_anti": ("left",),
    "right": ("right",),
    "full": (),
}


def _aqe_join_plan(sa, sb, n, advisory, sides, skew_thresh, skew_factor):
    """One shared AQE plan for both shuffle reads of a join: per output
    slot, a list of (source partition, split index, split count) for each
    side. Coalescing groups adjacent small partitions; a skewed partition
    (one side > max(threshold, factor x median), other side small) is
    split across the slots coalescing freed while the other side's
    partition replicates into each. Deterministic in (sa, sb) so both
    exchanges compute identical plans."""
    combined = [x + y for x, y in zip(sa, sb)]
    skewed: dict = {}
    if sides and skew_thresh > 0:
        med_a = sorted(sa)[n // 2]
        med_b = sorted(sb)[n // 2]
        for p in range(n):
            if (
                "left" in sides
                and sa[p] > max(skew_thresh, skew_factor * med_a)
                and sb[p] <= skew_thresh
            ):
                skewed[p] = "left"
            elif (
                "right" in sides
                and sb[p] > max(skew_thresh, skew_factor * med_b)
                and sa[p] <= skew_thresh
            ):
                skewed[p] = "right"
    groups: list = []
    cur: list = []
    by = 0
    for p in range(n):
        if p in skewed:
            if cur:
                groups.append(("g", cur))
                cur, by = [], 0
            groups.append(("s", [p]))
            continue
        if cur and by + combined[p] > advisory:
            groups.append(("g", cur))
            cur, by = [], 0
        cur.append(p)
        by += combined[p]
    if cur:
        groups.append(("g", cur))
    free = n - len(groups)
    out_a: list = [[] for _ in range(n)]
    out_b: list = [[] for _ in range(n)]
    slot = 0
    for kind, g in groups:
        if kind == "s" and free > 0:
            p = g[0]
            side = skewed[p]
            big = sa[p] if side == "left" else sb[p]
            want = max(2, int(big // max(advisory, 1)))
            k = min(free + 1, want, n)
            free -= k - 1
            for j in range(k):
                if side == "left":
                    out_a[slot].append((p, j, k))
                    out_b[slot].append((p, 0, 1))
                else:
                    out_a[slot].append((p, 0, 1))
                    out_b[slot].append((p, j, k))
                slot += 1
        else:
            for p in g:
                out_a[slot].append((p, 0, 1))
                out_b[slot].append((p, 0, 1))
            slot += 1
    return out_a, out_b


def _row_range_slice(db: DeviceBatch, j: int, k: int) -> Optional[DeviceBatch]:
    """Rows of capacity-range slice j of k, compacted (skew split unit)."""
    fn = K.jit_kernel(
        ("aqe_split", db.schema, db.capacity, j, k),
        lambda: _make_row_range_slice(j, k),
    )
    return fn(db)


def _make_row_range_slice(j: int, k: int):
    def run(db: DeviceBatch) -> DeviceBatch:
        # slice the LIVE prefix [0, num_rows), not the padded capacity —
        # rows are prefix-compacted, so capacity-based slices would leave
        # every live row in slice 0
        n = db.num_rows.astype(jnp.int32)
        lo = (n * j) // k
        hi = (n * (j + 1)) // k
        idx = jnp.arange(db.capacity, dtype=jnp.int32)
        keep = (idx >= lo) & (idx < hi) & db.row_mask()
        return compact(db, keep)

    return run


class TpuShuffleExchangeExec(Exec):
    """Partitioned exchange with on-device bucketing and device-side slicing
    (GpuShuffleExchangeExec + the four GpuPartitioning impls;
    sliceInternalOnGpu analogue). Hash = murmur3 pmod; range = radix-word
    compare against host-sampled bounds; round-robin; single. In-process:
    device batches move between partitions without leaving HBM; the
    multi-process serializer path lives in shuffle/."""

    def __init__(self, partitioning, child: Exec):
        super().__init__([child])
        from .cpu import _bind_partitioning

        self.partitioning = _bind_partitioning(partitioning, child.output)
        # AQE coalescing coordination: a co-partitioned consumer (shuffled
        # join) links its two feeding exchanges so both compute ONE shared
        # assignment from combined sizes; if only one side is an exchange,
        # coalescing is disabled to keep positional pairing intact.
        self._aqe_peer: "TpuShuffleExchangeExec | None" = None
        self._aqe_disabled = False

    @property
    def num_partitions(self) -> int:
        return self.partitioning.num_partitions

    @property
    def output(self) -> Schema:
        return self.children[0].output

    @property
    def is_device(self) -> bool:
        return True

    def _scatter_fns(self, nparts, pre_filter=None):
        """Build the jitted kernels for this exchange's partitioning; XLA's
        own compile cache dedupes retraces across execute() calls.
        ``pre_filter`` fuses a child filter's predicate in as a liveness
        mask — dead rows fall out during bucketing, skipping the filter's
        own compaction sort + full-width gather."""
        from ..ops.gather import partition_slices
        from ..plan.partitioning import (
            HashPartitioning,
            RangePartitioning,
            RoundRobinPartitioning,
            words_partition_ids,
        )

        part = self.partitioning

        def live_of(batch: DeviceBatch, c: Ctx):
            if pre_filter is None:
                return None
            fv = pre_filter.eval(c)
            return c.broadcast_bool(fv.data) & fv.full_valid(c)

        if isinstance(part, HashPartitioning) and part.keys:
            keys = tuple(part.keys)

            def make_hash():
                def hash_slice(batch: DeviceBatch) -> list[DeviceBatch]:
                    c = Ctx.for_device(batch)
                    cols = []
                    for k in keys:
                        col = val_to_column(c, k.eval(c), k.data_type)
                        cols.append((k.data_type, col.data, col.validity, col.lengths))
                    h = murmur3_rows(jnp, cols, batch.capacity)
                    pids = partition_ids(jnp, h, nparts)
                    return partition_slices(
                        batch, pids, nparts, live_of(batch, c)
                    )

                return hash_slice

            return (
                "hash",
                K.jit_kernel(
                    ("exchange_hash", keys, nparts, pre_filter), make_hash
                ),
            )

        if isinstance(part, RoundRobinPartitioning):

            def make_rr():
                def rr_slice(batch: DeviceBatch, start) -> list[DeviceBatch]:
                    pids = (start + jnp.arange(batch.capacity, dtype=jnp.int32)) % nparts
                    c = Ctx.for_device(batch)
                    return partition_slices(
                        batch, pids, nparts, live_of(batch, c)
                    )

                return rr_slice

            return (
                "roundrobin",
                K.jit_kernel(("exchange_rr", nparts, pre_filter), make_rr),
            )

        if isinstance(part, RangePartitioning):
            order = part.order

            def make_words():
                def batch_word_groups(batch: DeviceBatch):
                    """Per-order-column radix word lists (aligned later)."""
                    from ..ops.sortkeys import column_radix_words

                    c = Ctx.for_device(batch)
                    return [
                        column_radix_words(
                            val_to_column(c, o.child.eval(c), o.child.data_type),
                            o.ascending,
                            o.resolved_nulls_first(),
                        )
                        for o in order
                    ]

                return batch_word_groups

            words_jit = K.jit_kernel(
                ("exchange_range_words", _order_key(order)), make_words
            )

            def make_range():
                def range_slice(batch: DeviceBatch, words, bounds) -> list[DeviceBatch]:
                    pids = words_partition_ids(jnp, words, bounds)
                    c = Ctx.for_device(batch)
                    return partition_slices(
                        batch, pids, nparts, live_of(batch, c)
                    )

                return range_slice

            return (
                "range",
                (
                    words_jit,
                    K.jit_kernel(
                        ("exchange_range_slice", nparts, pre_filter),
                        make_range,
                    ),
                ),
            )

        return ("single", None)

    # ── mesh (SPMD) path ────────────────────────────────────────────────
    def _pid_fns(self, nparts):
        """Per-row partition-id kernels (no per-partition compact): the mesh
        exchange scatters by pid inside one fused all_to_all program, so
        hash/range/round-robin all lower to the same ICI data plane."""
        from ..plan.partitioning import (
            HashPartitioning,
            RangePartitioning,
            RoundRobinPartitioning,
            words_partition_ids,
        )

        part = self.partitioning
        if isinstance(part, HashPartitioning) and part.keys:
            keys = tuple(part.keys)

            def make_hash():
                def pids(batch: DeviceBatch):
                    c = Ctx.for_device(batch)
                    cols = []
                    for k in keys:
                        col = val_to_column(c, k.eval(c), k.data_type)
                        cols.append(
                            (k.data_type, col.data, col.validity, col.lengths)
                        )
                    h = murmur3_rows(jnp, cols, batch.capacity)
                    return partition_ids(jnp, h, nparts).astype(jnp.int32)

                return pids

            return ("hash", K.jit_kernel(("mesh_pid_hash", keys, nparts), make_hash))
        if isinstance(part, RoundRobinPartitioning):

            def make_rr():
                def pids(batch: DeviceBatch, start):
                    return (
                        (start + jnp.arange(batch.capacity, dtype=jnp.int32))
                        % nparts
                    ).astype(jnp.int32)

                return pids

            return ("roundrobin", K.jit_kernel(("mesh_pid_rr", nparts), make_rr))
        if isinstance(part, RangePartitioning):
            order = part.order

            def make_words():
                def batch_word_groups(batch: DeviceBatch):
                    from ..ops.sortkeys import column_radix_words

                    c = Ctx.for_device(batch)
                    return [
                        column_radix_words(
                            val_to_column(c, o.child.eval(c), o.child.data_type),
                            o.ascending,
                            o.resolved_nulls_first(),
                        )
                        for o in order
                    ]

                return batch_word_groups

            words_jit = K.jit_kernel(
                ("mesh_range_words", _order_key(order)), make_words
            )

            def make_range():
                def pids(words, bounds):
                    return words_partition_ids(jnp, words, bounds).astype(jnp.int32)

                return pids

            return ("range", (words_jit, K.jit_kernel(("mesh_pid_range",), make_range)))
        return ("single", None)

    def _execute_mesh(self, ctx: ExecContext, mc) -> PartitionSet:
        """SPMD exchange: chip i contributes child partitions j ≡ i (mod n)
        concatenated to one batch; one fused all_to_all re-partitions every
        chip's rows over ICI; output partition i stays committed on chip i
        so downstream per-partition kernels run on their own devices.
        (GpuShuffleExchangeExec over the UCX data plane, engine-wired —
        RapidsShuffleInternalManagerBase.scala:200-396.)"""
        import threading

        from ..parallel.mesh import mesh_exchange, put_batch
        from ..plan.partitioning import SAMPLE_PER_BATCH, compute_range_bounds

        nparts = self.num_partitions
        kind, fn = self._pid_fns(nparts)
        schema = self.output
        child_parts = self.children[0].execute(ctx)
        state: dict = {"out": None}
        lock = threading.Lock()

        def materialize():
            with lock:
                if state["out"] is not None:
                    return state["out"]
                n = mc.n
                per_chip_lists: list = [[] for _ in range(n)]
                for j, t in enumerate(child_parts.parts):
                    per_chip_lists[j % n].extend(t())
                per_chip = [
                    concat_device(l) if l else empty_batch(schema)
                    for l in per_chip_lists
                ]
                # commit each chip's input to its device so the global
                # stacked view assembles zero-copy
                per_chip = [
                    put_batch(b, mc.device_for(i)) for i, b in enumerate(per_chip)
                ]
                if kind == "hash":
                    pids = [fn(b) for b in per_chip]
                elif kind == "roundrobin":
                    pids = [
                        fn(b, jnp.asarray(i, jnp.int32))
                        for i, b in enumerate(per_chip)
                    ]
                elif kind == "range":
                    words_jit, pid_jit = fn
                    import numpy as np

                    all_words = self._mesh_range_words(
                        ctx, words_jit, per_chip
                    )
                    dev_samples, dev_valid = [], []
                    for db, words in zip(per_chip, all_words):
                        s_idx = (
                            jnp.arange(SAMPLE_PER_BATCH, dtype=jnp.int32)
                            * jnp.maximum(db.num_rows, 1)
                        ) // SAMPLE_PER_BATCH
                        dev_samples.append(jnp.stack([w[s_idx] for w in words]))
                        dev_valid.append(
                            jnp.broadcast_to(db.num_rows > 0, (SAMPLE_PER_BATCH,))
                        )
                    # graft: ok(host-sync: range bounds need the samples on
                    # host — ONE batched transfer for every chip's samples,
                    # once per exchange materialization)
                    host_samples, host_valid = jax.device_get(
                        (dev_samples, dev_valid)
                    )
                    sample_words = [
                        np.concatenate(
                            [s[i][v] for s, v in zip(host_samples, host_valid)]
                        )
                        for i in range(len(all_words[0]))
                    ]
                    if sample_words[0].size:
                        bounds = compute_range_bounds(sample_words, nparts)
                        jb = [jnp.asarray(b) for b in bounds]
                        pids = [
                            pid_jit(w, jb) for w in all_words
                        ]
                    else:
                        pids = [
                            jnp.zeros(b.capacity, jnp.int32) for b in per_chip
                        ]
                else:
                    raise AssertionError(kind)
                out = mesh_exchange(mc, schema, per_chip, pids)
                state["out"] = out
                return out

        def make(p):
            def it():
                db = materialize()[p]
                yield db

            return it

        return PartitionSet([make(p) for p in range(nparts)])

    def _mesh_range_words(self, ctx, words_jit, per_chip):
        from ..plan.partitioning import align_word_groups

        group_lists = [words_jit(b) for b in per_chip]
        aligned, _targets = align_word_groups(
            group_lists, self.partitioning.order, jnp
        )
        return aligned

    def execute(self, ctx: ExecContext) -> PartitionSet:
        # exchange reuse (plan/reuse.py): a node shared by several consumers
        # materializes once per query — the ReuseExchange analogue
        if getattr(self, "_reuse_shared", False):
            cached = ctx.reuse_cache.get(id(self))
            if cached is None:
                cached = self._execute_impl(ctx)
                ctx.reuse_cache[id(self)] = cached
            return cached
        return self._execute_impl(ctx)

    def _execute_impl(self, ctx: ExecContext) -> PartitionSet:
        from ..mem.spill import with_oom_retry
        from ..plan.partitioning import SAMPLE_PER_BATCH, compute_range_bounds

        import threading

        nparts = self.num_partitions
        mc = ctx.mesh
        if mc is not None and nparts == mc.n:
            from ..parallel.mesh import mesh_supported_schema
            from ..plan.partitioning import SinglePartitioning

            if (
                mesh_supported_schema(self.output)
                and not isinstance(self.partitioning, SinglePartitioning)
                and self._pid_fns(nparts)[0] != "single"
            ):
                return self._execute_mesh(ctx, mc)
        exchange_child = self.children[0]
        pre_filter = None
        if (
            isinstance(exchange_child, TpuFilterExec)
            and not exchange_child._needs_task
            and not _expr_has_error_site(exchange_child.condition)
            # round-robin balances by ROW POSITION: fusing a filter would
            # assign pids over unfiltered positions and can degenerate to
            # total skew — hash/range pids are value-based and unaffected
            and self._scatter_fns(nparts)[0] in ("hash", "range")
        ):
            # fuse the filter into the bucketing kernel: its rows fall out
            # during the partition sort, skipping the filter's own
            # compaction sort + full-width gather (same fusion the
            # aggregate does with its pre_filter)
            pre_filter = exchange_child.condition
            exchange_child = exchange_child.children[0]
        kind, fn = self._scatter_fns(nparts, pre_filter)
        catalog = ctx.catalog
        child_parts = exchange_child.execute(ctx)
        from .. import config as cfg

        # Multi-process query (spark.rapids.shuffle.multiproc.*): this
        # executor maps only the child partitions its rank owns; peers map
        # the rest and serve them over the TCP transport (DCN path). The
        # topology comes from the CONTEXT, frozen at session init — the
        # multiproc keys are startup_only, and re-reading the conf here
        # would let a live set_conf disagree with the running transport
        # (the conf-key lint's scope rule).
        mp_size = ctx.mp_size
        mp_rank = ctx.mp_rank
        in_broadcast = getattr(ctx, "broadcast_depth", 0) > 0
        multiproc = (
            bool(ctx.mp_driver)
            and mp_size > 1
            and cfg.SHUFFLE_MANAGER_ENABLED.get(ctx.conf)
            and not in_broadcast
        )
        if multiproc:
            child_parts = PartitionSet(
                [
                    t if i % mp_size == mp_rank else (lambda: iter(()))
                    for i, t in enumerate(child_parts.parts)
                ]
            )
        state = {"buckets": None}
        mat_lock = threading.Lock()

        def materialize():
            with mat_lock:
                return _materialize_locked()

        def _materialize_locked():
            if state["buckets"] is not None:
                return state["buckets"]
            buckets = [[] for _ in range(nparts)]
            if kind == "range":
                from ..plan.partitioning import (
                    align_word_groups,
                    merge_sampled_word_groups,
                    pad_flat_words,
                )

                words_jit, range_slice = fn
                order = self.partitioning.order
                batches, group_lists = [], []
                for t in child_parts.parts:
                    for db in t():
                        batches.append(db)
                        group_lists.append(with_oom_retry(catalog, words_jit, db))
                # string columns may encode to different word counts per
                # batch (bucketed widths) — align before sampling/bucketing
                all_words, local_targets = align_word_groups(
                    group_lists, order, jnp
                )
                del group_lists
                # Sample on device, then fetch everything in ONE transfer —
                # per-batch np.asarray syncs are lethal over slow PJRT links.
                dev_samples, dev_valid = [], []
                for db, words in zip(batches, all_words):
                    s_idx = (
                        jnp.arange(SAMPLE_PER_BATCH, dtype=jnp.int32)
                        * jnp.maximum(db.num_rows, 1)
                    ) // SAMPLE_PER_BATCH
                    dev_samples.append(jnp.stack([w[s_idx] for w in words]))
                    # duplicates (n < SAMPLE_PER_BATCH) just weight the
                    # sample; only an empty batch must be excluded outright
                    dev_valid.append(
                        jnp.broadcast_to(db.num_rows > 0, (SAMPLE_PER_BATCH,))
                    )
                sample_words = None
                if batches:
                    # graft: ok(host-sync: ONE batched pull for all range
                    # samples, once per exchange — the per-batch np.asarray
                    # alternative is what the comment above rules out)
                    host_samples, host_valid = jax.device_get((dev_samples, dev_valid))
                    sample_words = [
                        np.concatenate(
                            [s[i][v] for s, v in zip(host_samples, host_valid)]
                        )
                        for i in range(len(all_words[0]))
                    ]
                bounds = None
                if multiproc:
                    # Every rank sees only its own child partitions, so
                    # per-rank bounds would send the same key range to
                    # different reduce partitions on different ranks —
                    # globally wrong ORDER BY results. Gather all ranks'
                    # samples through the driver service and replay one
                    # deterministic merge so every rank buckets with
                    # identical bounds (the bounds-on-the-Spark-driver
                    # analogue, GpuRangePartitioner.createRangeBounds).
                    payload = {
                        "targets": local_targets,
                        # graft: ok(host-sync: host numpy after the single
                        # batched device_get above — JSON payload for the
                        # driver's bounds sync, no device traffic)
                        "words": [w.tolist() for w in (sample_words or [])],
                    }
                    contribs = ctx.shuffle_manager.registry.range_bounds_sync(
                        key=f"{base_sid}:range",
                        rank=mp_rank,
                        size=mp_size,
                        payload=payload,
                    )
                    merged, gtargets = merge_sampled_word_groups(contribs, order)
                    if merged is not None:
                        bounds = compute_range_bounds(merged, nparts)
                        if gtargets != local_targets and batches:
                            # peers saw wider string keys: re-pad this
                            # rank's words to the agreed global widths so
                            # rows and bounds compare word-for-word
                            all_words = [
                                pad_flat_words(
                                    w, local_targets, gtargets, order, jnp
                                )
                                for w in all_words
                            ]
                elif sample_words is not None and sample_words[0].size:
                    bounds = compute_range_bounds(sample_words, nparts)
                jb = None if bounds is None else [jnp.asarray(b) for b in bounds]
                for db, words in zip(batches, all_words):
                    if jb is None:
                        buckets[0].append(db)
                        continue
                    for p, s in enumerate(
                        with_oom_retry(catalog, range_slice, db, words, jb)
                    ):
                        buckets[p].append(s)
            elif kind == "hash":
                # Drain every partition first (dispatches all upstream work
                # asynchronously), then ONE bulk shrink sync, then slice —
                # partitions overlap on device instead of serializing. The
                # cost is holding the drained inputs concurrently; slices
                # consume the list destructively so inputs free as we go.
                drained = bulk_shrink(
                    [db for t in child_parts.parts for db in t()]
                )
                while drained:
                    db = drained.pop(0)
                    for p, s in enumerate(with_oom_retry(catalog, fn, db)):
                        buckets[p].append(s)
                    del db
            elif kind == "single":
                # coalesce to one partition; shrink sparse batches (e.g.
                # ungrouped partial aggregates: 1 live row in a huge cap)
                drained = [db for t in child_parts.parts for db in t()]
                buckets[0].extend(bulk_shrink(drained))
            else:  # roundrobin
                for pi, t in enumerate(child_parts.parts):
                    # device-resident running offset: no host sync per batch
                    offset = jnp.asarray(pi % nparts, jnp.int32)
                    for db in t():
                        for p, s in enumerate(
                            with_oom_retry(catalog, fn, db, offset % nparts)
                        ):
                            buckets[p].append(s)
                        offset = offset + db.num_rows
            state["buckets"] = buckets
            return buckets

        from .. import config as cfg

        if cfg.SHUFFLE_MANAGER_ENABLED.get(ctx.conf) and not in_broadcast:
            # Accelerated path: park partition buckets in the spillable
            # shuffle catalog and read them back through the caching
            # reader (RapidsShuffleManager writer/reader protocol). A
            # broadcast-build subtree stays on the in-process path: its
            # results are per-executor by definition (no shared catalog /
            # registry traffic).
            #
            # The shuffle id is minted HERE, during the single-threaded
            # plan-execution walk — identical plans mint identical ids in
            # every rank. Minting lazily inside ensure_written would let
            # the partition THREAD POOL order decide which exchange gets
            # which id (nondeterministic across ranks → peers would pair
            # the wrong exchanges). Retries re-run the map stage under a
            # deterministic per-generation offset within the query's id
            # namespace.
            base_sid = ctx.next_shuffle_id()
            mgr_state = {"shuffle_id": None, "generation": 0, "attempt": 0}
            mgr_lock = threading.Lock()

            def ensure_written():
                with mgr_lock:
                    if mgr_state["shuffle_id"] is not None:
                        return mgr_state["shuffle_id"]
                    manager = ctx.shuffle_manager
                    sid = base_sid + mgr_state["generation"] * 10_000
                    writer = manager.get_writer(
                        sid, map_id=mp_rank if multiproc else 0,
                        num_partitions=nparts,
                        attempt=mgr_state["attempt"],
                    )
                    try:
                        for p, bucket in enumerate(materialize()):
                            for db in bucket:
                                # graft: ok(host-sync: shuffle-manager write
                                # filter — serializing an empty bucket batch
                                # costs a frame per peer; one scalar pull per
                                # bucket batch on the manager path only)
                                if db.row_count():
                                    writer.write(p, db)
                        writer.commit()
                    except BaseException:
                        # atomic per-(map, attempt) commit: a mid-write
                        # failure drops THIS attempt's partial blocks and
                        # advances the attempt id, so the task retry's
                        # re-write can never duplicate batches a consumer
                        # would read twice
                        writer.abort()
                        mgr_state["attempt"] += 1
                        raise
                    state["buckets"] = None  # catalog owns the batches now
                    mgr_state["shuffle_id"] = sid
                    return sid

            consumed: set = set()

            def make_managed(p):
                def it():
                    with mgr_lock:
                        if mgr_state.get("released"):
                            # task retry AFTER the map output was freed: the
                            # thunk must stay re-runnable (lineage recovery),
                            # so re-run the map stage under the next
                            # generation's shuffle id — materialize()
                            # re-executes the child pipeline since its
                            # buckets were handed to the (now unregistered)
                            # catalog. Without this, the retry would read an
                            # unknown shuffle id and silently commit ZERO
                            # rows for this partition.
                            mgr_state["shuffle_id"] = None
                            mgr_state["generation"] += 1
                            mgr_state["released"] = False
                            # full reset: stale entries would re-trip the
                            # release after ONE retried read, forcing every
                            # other retried partition to re-run the whole
                            # map stage again; the fresh generation frees
                            # only when it fully drains (query end is the
                            # backstop for partially-retried generations)
                            consumed.clear()
                    sid = ensure_written()
                    if multiproc and p % mp_size != mp_rank:
                        # a peer owns this reduce partition; this executor
                        # only had to contribute its map output (above)
                        return
                    from ..resilience import faults as _faults
                    from ..shuffle.client import ShuffleFetchError
                    from ..shuffle.manager import MapOutputLostError

                    if _faults.lose_map_output():
                        # chaos: the committed map output vanishes wholesale
                        # (peer death) — the recovery path below must rebuild
                        # it from lineage, not silently read zero rows
                        ctx.shuffle_manager.unregister_shuffle(sid)

                    def _lost(cause):
                        # Map-output recomputation: mark this generation
                        # released so the NEXT attempt of any reduce task
                        # re-runs the map stage under a fresh shuffle id,
                        # then raise the recoverable error the session's
                        # task-retry loop re-executes on. Guarded by the
                        # sid match: concurrent losers of one generation
                        # bump it exactly once.
                        if not cfg.RECOVERY_RECOMPUTE_ENABLED.get(ctx.conf):
                            raise cause
                        if mgr_state["generation"] >= (
                            cfg.RECOVERY_MAX_MAP_RECOMPUTES.get(ctx.conf)
                        ):
                            raise cause
                        with mgr_lock:
                            if (
                                mgr_state["shuffle_id"] == sid
                                and not mgr_state.get("released")
                            ):
                                mgr_state["released"] = True
                                from ..obs.metrics import GLOBAL as _obs

                                _obs.counter(
                                    "shuffle.recomputedPartitions"
                                ).add(1)
                        raise MapOutputLostError(
                            f"shuffle {sid} partition {p}: map output lost "
                            f"({cause}); recomputing from lineage under "
                            "generation "
                            f"{mgr_state['generation'] + 1}"
                        ) from cause

                    if not multiproc and not (
                        ctx.shuffle_manager.registry.outputs_for(sid)
                    ):
                        # single-process reads pass expected_maps=0, so an
                        # emptied registry would otherwise yield NOTHING —
                        # ensure_written always commits a MapStatus (even
                        # all-empty sizes), so absence means loss
                        _lost(MapOutputLostError(
                            f"shuffle {sid}: no map outputs registered"
                        ))
                    try:
                        yield from ctx.shuffle_manager.get_reader().read_partitions(
                            sid, p, p + 1,
                            expected_maps=mp_size if multiproc else 0,
                        )
                    except (ShuffleFetchError, TimeoutError) as e:
                        # blacklisted peer / exhausted fetch budget: the
                        # peer's output is unreachable — rebuild it
                        _lost(e)
                    # free catalog-held map output once every partition has
                    # been drained (ShuffleBufferCatalog unregisterShuffle)
                    with mgr_lock:
                        consumed.add(p)
                        done = (
                            len(consumed) == nparts
                            and not mgr_state.get("released")
                            and mgr_state["shuffle_id"] == sid
                            # a reused exchange is drained once per consumer;
                            # early release would force a map-stage re-run.
                            # Multi-process: peers fetch on their own clock —
                            # map output lives until the executor exits.
                            and not getattr(self, "_reuse_shared", False)
                            and not multiproc
                        )
                        if done:
                            mgr_state["released"] = True
                    if done:
                        ctx.shuffle_manager.unregister_shuffle(sid)

                return it

            return PartitionSet([make_managed(p) for p in range(nparts)])

        if cfg.ADAPTIVE_ENABLED.get(ctx.conf) and not self._aqe_disabled:
            # AQE partition coalescing + skew-join splitting
            # (GpuCustomShuffleReaderExec / CoalescedPartitionSpec +
            # OptimizeSkewedJoin analogues): measured output sizes group
            # adjacent small partitions into one reduce task, and — when
            # this exchange feeds a shuffled join — an oversized partition
            # is split across the freed slots while the peer's partition is
            # replicated. The partition COUNT stays static (PartitionSets
            # are fixed-arity); both join sides compute the SAME plan from
            # the combined measurements, so positional pairing holds.
            advisory = cfg.ADVISORY_PARTITION_SIZE.get(ctx.conf)
            skew_on = cfg.SKEW_JOIN_ENABLED.get(ctx.conf)
            skew_thresh = cfg.SKEW_JOIN_THRESHOLD.get(ctx.conf)
            skew_factor = cfg.SKEW_JOIN_FACTOR.get(ctx.conf)
            aqe_state = {"assign": None}

            def my_sizes():
                # LIVE-row bytes, not capacity bytes: bucket batches share
                # the input's (padded) capacity, which would make every
                # bucket look equally big and hide both small partitions
                # and skew. One pipelined device_get for all counts,
                # memoized — both sides of a linked join read each
                # exchange's sizes (tunnel RTTs are the budget).
                if aqe_state.get("sizes") is None:
                    buckets = materialize()
                    # graft: ok(host-sync: AQE needs measured sizes on host
                    # to plan coalescing — ONE pipelined device_get for all
                    # bucket counts, memoized per exchange)
                    counts = jax.device_get(
                        [[db.num_rows for db in b] for b in buckets]
                    )
                    rb = _row_bytes(self.output)
                    aqe_state["sizes"] = [int(sum(c)) * rb for c in counts]
                return aqe_state["sizes"]

            ctx.aqe_size_providers[id(self)] = my_sizes

            def assignment():
                if aqe_state["assign"] is not None:
                    return aqe_state["assign"]
                sizes = my_sizes()
                peer = self._aqe_peer
                if peer is None:
                    assign, _ = _aqe_join_plan(
                        sizes, [0] * nparts, nparts, advisory, (), 0, 0
                    )
                else:
                    peer_fn = ctx.aqe_size_providers.get(id(peer))
                    if peer_fn is None:
                        # peer never took the AQE path: identity grouping
                        # preserves positional pairing
                        assign = [[(p, 0, 1)] for p in range(nparts)]
                        aqe_state["assign"] = assign
                        self.aqe_groups = nparts
                        return assign
                    sides = (
                        _SPLITTABLE_SIDES.get(
                            getattr(self, "_aqe_join_type", "inner"), ()
                        )
                        if skew_on
                        else ()
                    )
                    mine, theirs = sizes, peer_fn()
                    if getattr(self, "_aqe_side", "left") == "left":
                        a, b = _aqe_join_plan(
                            mine, theirs, nparts, advisory, sides,
                            skew_thresh, skew_factor,
                        )
                        assign = a
                    else:
                        a, b = _aqe_join_plan(
                            theirs, mine, nparts, advisory, sides,
                            skew_thresh, skew_factor,
                        )
                        assign = b
                self.aqe_groups = sum(1 for a in assign if a)
                self.aqe_splits = sum(
                    1 for slot in assign for (_, j, k) in slot if k > 1 and j == 0
                )
                aqe_state["assign"] = assign
                return assign

            def make_aqe(p):
                def it():
                    buckets = materialize()
                    tok = ctx.cancel_token
                    for src, j, k in assignment()[p]:
                        if tok is not None:
                            tok.check()
                        if k == 1:
                            yield from buckets[src]
                        else:
                            for db in buckets[src]:
                                if tok is not None:
                                    tok.check()
                                part = _row_range_slice(db, j, k)
                                if part is not None:
                                    yield part

                return it

            return PartitionSet([make_aqe(p) for p in range(nparts)])

        def make(p):
            def it():
                tok = ctx.cancel_token
                for db in materialize()[p]:
                    if tok is not None:
                        tok.check()
                    yield db

            return it

        return PartitionSet([make(p) for p in range(nparts)])

    def node_string(self):
        return f"TpuShuffleExchange {self.partitioning} p={self.num_partitions}"


class TpuLimitExec(Exec):
    def __init__(self, n: int, child: Exec):
        super().__init__([child])
        self.n = n

    @property
    def output(self) -> Schema:
        return self.children[0].output

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        limit = self.n
        child_parts = self.children[0].execute(ctx)
        # LIMIT syncs a row count per batch (it must know when to stop);
        # prefetching the upstream stream hides the dispatch gap behind
        # those syncs, and the bounded window caps how far past the limit
        # the producer can run before the early-exit close() stops it.
        from .pipeline import pipe_metrics, pipeline_conf, pipelined_partition

        pconf = pipeline_conf(ctx)
        metrics = pipe_metrics(self, ctx) if pconf is not None else None

        def it():
            remaining = limit
            tok = ctx.cancel_token

            def consume(src):
                nonlocal remaining
                for db in src:
                    if tok is not None:
                        tok.check()
                    if remaining <= 0:
                        return
                    out = slice_head(db, jnp.asarray(remaining, jnp.int32))
                    # graft: ok(host-sync: LIMIT must learn the row count
                    # to know when to stop — the documented per-batch sync
                    # the pipelined prefetch window exists to hide)
                    n = out.row_count()
                    remaining -= n
                    if n:
                        yield out

            for t in child_parts.parts:
                yield from pipelined_partition(pconf, ctx, t(), consume, metrics)
                if remaining <= 0:
                    return

        return PartitionSet([it])


# ── batch coalescing (GpuCoalesceBatches.scala:92-455) ─────────────────────


class CoalesceGoal:
    """Batching contract lattice (CoalesceGoal: RequireSingleBatch >
    TargetSize) — how much input batching an operator needs."""

    __slots__ = ("target_bytes",)
    SINGLE = None  # sentinel set below

    def __init__(self, target_bytes: int):
        self.target_bytes = target_bytes

    def __repr__(self):
        if self.target_bytes < 0:
            return "RequireSingleBatch"
        return f"TargetSize({self.target_bytes})"

    def __eq__(self, o):
        return isinstance(o, CoalesceGoal) and o.target_bytes == self.target_bytes

    def __hash__(self):
        return hash(("goal", self.target_bytes))


CoalesceGoal.SINGLE = CoalesceGoal(-1)


class TpuCoalesceBatchesExec(Exec):
    """Concatenate undersized device batches up to the goal before handing
    them to the parent (GpuCoalesceBatches' Table.concatenate accumulation
    loop :133-455). Many-small-file scans otherwise push one tiny batch per
    file through every downstream kernel — each a device round trip."""

    def __init__(self, child: Exec, goal: CoalesceGoal):
        super().__init__([child])
        self.goal = goal

    @property
    def output(self) -> Schema:
        return self.children[0].output

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        goal = self.goal
        batches_m = self.metric("numOutputBatches", "ESSENTIAL")

        def fn(it):
            tok = ctx.cancel_token
            acc: list = []
            acc_bytes = 0

            def flush():
                nonlocal acc, acc_bytes
                if not acc:
                    return None
                out = acc[0] if len(acc) == 1 else concat_device(acc)
                acc, acc_bytes = [], 0
                batches_m.add(1)
                return out

            for db in it:
                if tok is not None:
                    tok.check()
                sz = db.size_bytes()
                if (
                    goal.target_bytes >= 0
                    and acc
                    and acc_bytes + sz > goal.target_bytes
                ):
                    out = flush()
                    if out is not None:
                        yield out
                acc.append(db)
                acc_bytes += sz
            out = flush()
            if out is not None:
                yield out

        return self.children[0].execute(ctx).map_partitions(fn)

    def node_string(self):
        return f"TpuCoalesceBatches {self.goal!r}"
