"""TPU physical operators — the GpuExec family.

Reference analogues: basicPhysicalOperators.scala (GpuProjectExec,
GpuFilterExec), aggregate.scala (GpuHashAggregateExec), GpuSortExec.scala,
GpuShuffleExchangeExec + GpuPartitioning, GpuTransitionOverrides' transitions.

Each operator compiles ONE fused XLA program per (expression tree, schema,
capacity) via jax.jit over DeviceBatch pytrees; the device semaphore gates
first touch of the device per partition-task (GpuSemaphore protocol).
"""
from __future__ import annotations

from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from ..columnar.device import (
    DeviceBatch,
    DeviceColumn,
    bucket_capacity,
    device_to_host,
    empty_batch,
    host_to_device,
)
from ..columnar.host import concat_batches
from ..expr import Expression, bind, output_name
from ..expr.aggregates import AggregateFunction
from ..expr.base import BoundReference, Ctx, Val
from ..expr.misc import contains_task_dependent
from . import task
from ..ops.aggregate import group_aggregate
from ..ops.concat import concat_device
from ..ops.gather import compact, gather_batch
from ..ops.hash import murmur3_rows, partition_ids
from ..ops.sortkeys import batch_radix_words, sort_permutation
from ..plan.logical import SortOrder
from ..plan.physical import Exec, ExecContext, PartitionSet
from ..types import Schema, StringType, StructField


def val_to_column(ctx: Ctx, val: Val, dtype) -> DeviceColumn:
    """Materialize an expression result into a full DeviceColumn."""
    if isinstance(dtype, StringType):
        data = val.data
        if data.ndim == 1:  # scalar string literal [w]
            data = jnp.broadcast_to(data[None, :], (ctx.n, data.shape[0]))
        lengths = jnp.broadcast_to(jnp.asarray(val.lengths), (ctx.n,))
        return DeviceColumn(dtype, data, val.full_valid(ctx), lengths)
    data = ctx.broadcast(val.data)
    if data.dtype != dtype.np_dtype:
        data = data.astype(dtype.np_dtype)
    return DeviceColumn(dtype, data, val.full_valid(ctx))


# ── transitions ─────────────────────────────────────────────────────────────


class HostToDeviceExec(Exec):
    """Host Arrow batches → device batches (HostColumnarToGpu analogue)."""

    def __init__(self, child: Exec):
        super().__init__([child])

    @property
    def output(self) -> Schema:
        return self.children[0].output

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        schema = self.output

        def fn(it):
            for rb in it:
                ctx.semaphore.acquire_if_necessary()
                if rb.num_rows == 0:
                    continue
                yield host_to_device(rb)

        return self.children[0].execute(ctx).map_partitions(fn)


class DeviceToHostExec(Exec):
    """Device batches → host Arrow (GpuColumnarToRow/GpuBringBackToHost)."""

    def __init__(self, child: Exec):
        super().__init__([child])

    @property
    def output(self) -> Schema:
        return self.children[0].output

    def execute(self, ctx: ExecContext) -> PartitionSet:
        def fn(it):
            for db in it:
                rb = device_to_host(db)
                ctx.semaphore.release_if_necessary()
                if rb.num_rows:
                    yield rb

        return self.children[0].execute(ctx).map_partitions(fn)


# ── compute execs ───────────────────────────────────────────────────────────


class TpuRangeExec(Exec):
    """Device-side sequence generation (GpuRangeExec,
    basicPhysicalOperators.scala) — ids are born on device, no H2D copy."""

    def __init__(self, cpu_range):
        super().__init__([])
        self._cpu = cpu_range
        self._schema = cpu_range.output
        self._fns = {}

    @property
    def output(self) -> Schema:
        return self._schema

    @property
    def is_device(self) -> bool:
        return True

    def _fn(self, cap: int):
        if cap not in self._fns:
            schema = self._schema
            step = self._cpu.step

            @jax.jit
            def gen(first, m):
                ids = first + step * jnp.arange(cap, dtype=jnp.int64)
                valid = jnp.arange(cap, dtype=jnp.int32) < m
                from ..types import LONG

                col = DeviceColumn(LONG, jnp.where(valid, ids, 0), valid)
                return DeviceBatch(schema, [col], m.astype(jnp.int32))

            self._fns[cap] = gen
        return self._fns[cap]

    def execute(self, ctx: ExecContext) -> PartitionSet:
        from .. import config as cfg

        batch_rows = cfg.BATCH_SIZE_ROWS.get(ctx.conf)
        start, step = self._cpu.start, self._cpu.step
        parts = []
        for lo, cnt in self._cpu.partition_bounds():
            def make(lo=lo, cnt=cnt):
                def it():
                    ctx.semaphore.acquire_if_necessary()
                    done = 0
                    while done < cnt:
                        m = min(batch_rows, cnt - done)
                        cap = bucket_capacity(max(m, 1))
                        first = start + (lo + done) * step
                        yield self._fn(cap)(
                            jnp.asarray(first, dtype=jnp.int64),
                            jnp.asarray(m, dtype=jnp.int32),
                        )
                        done += m

                return it()

            parts.append(make)
        return PartitionSet(parts)

    def node_string(self):
        c = self._cpu
        return f"TpuRange ({c.start}, {c.end}, step={c.step}, splits={c.num_partitions})"


class TpuProjectExec(Exec):
    def __init__(self, exprs: List[Expression], child: Exec):
        super().__init__([child])
        self.exprs = [bind(e, child.output) for e in exprs]
        self._schema = Schema(
            [
                StructField(output_name(e0), e.data_type, e.nullable)
                for e0, e in zip(exprs, self.exprs)
            ]
        )
        schema = self._schema
        self._needs_task = any(contains_task_dependent(e) for e in self.exprs)

        @jax.jit
        def _project(batch: DeviceBatch, tvals) -> DeviceBatch:
            c = Ctx.for_device(batch, task=tvals)
            cols = [
                val_to_column(c, e.eval(c), e.data_type) for e in self.exprs
            ]
            # keep padding rows inert
            live = batch.row_mask()
            cols = [
                DeviceColumn(col.dtype, col.data, col.validity & live, col.lengths)
                for col in cols
            ]
            return DeviceBatch(schema, cols, batch.num_rows)

        self._fn = _project

    @property
    def output(self) -> Schema:
        return self._schema

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        fn = self._fn
        needs_task = self._needs_task

        def run(it):
            return task.run_device(fn, it, needs_task)

        return self.children[0].execute(ctx).map_partitions(run)

    def node_string(self):
        return f"TpuProject [{', '.join(map(str, self.exprs))}]"


class TpuFilterExec(Exec):
    def __init__(self, condition: Expression, child: Exec):
        super().__init__([child])
        self.condition = bind(condition, child.output)

        self._needs_task = contains_task_dependent(self.condition)

        @jax.jit
        def _filter(batch: DeviceBatch, tvals) -> DeviceBatch:
            c = Ctx.for_device(batch, task=tvals)
            v = self.condition.eval(c)
            keep = c.broadcast_bool(v.data) & v.full_valid(c)
            return compact(batch, keep)

        self._fn = _filter

    @property
    def output(self) -> Schema:
        return self.children[0].output

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        fn = self._fn
        needs_task = self._needs_task

        def run(it):
            return task.run_device(fn, it, needs_task)

        return self.children[0].execute(ctx).map_partitions(run)

    def node_string(self):
        return f"TpuFilter {self.condition}"


class TpuUnionExec(Exec):
    def __init__(self, children: List[Exec]):
        super().__init__(children)

    @property
    def output(self) -> Schema:
        return self.children[0].output

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        parts = []
        for c in self.children:
            parts.extend(c.execute(ctx).parts)
        return PartitionSet(parts)


class TpuCoalescePartitionsExec(Exec):
    def __init__(self, child: Exec):
        super().__init__([child])

    @property
    def output(self) -> Schema:
        return self.children[0].output

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        child_parts = self.children[0].execute(ctx)

        def it():
            for t in child_parts.parts:
                yield from t()

        return PartitionSet([it])


class TpuHashAggregateExec(Exec):
    """Sort-based group-by on device; one phase (partial|final|complete).

    The reference's hot loop (aggregate.scala:406-468) is: per-batch update
    aggregate → concat partials → merge aggregate. Here both update and merge
    are the same fused kernel with different reduce ops.
    """

    def __init__(
        self,
        mode: str,
        grouping: List[Expression],
        agg_fns: List[AggregateFunction],
        result_exprs: Optional[List[Expression]],
        result_names: Optional[List[str]],
        child: Exec,
    ):
        super().__init__([child])
        self.mode = mode
        self.grouping = [bind(g, child.output) for g in grouping]
        self.agg_fns = agg_fns
        self.result_exprs = result_exprs
        self.result_names = result_names
        self._schema = self._compute_schema(child)
        self._agg_fn_cache: dict = {}

    def _compute_schema(self, child: Exec) -> Schema:
        fields = []
        for g in self.grouping:
            fields.append(StructField(output_name(g), g.data_type, g.nullable))
        if self.mode == "partial":
            for i, f in enumerate(self.agg_fns):
                for j, bt in enumerate(f.buffer_types):
                    fields.append(StructField(f"buf{i}_{j}", bt, True))
            return Schema(fields)
        assert self.result_exprs is not None
        return Schema(
            [
                StructField(name, e.data_type, e.nullable)
                for name, e in zip(self.result_names, self.result_exprs)
            ]
        )

    @property
    def output(self) -> Schema:
        return self._schema

    @property
    def is_device(self) -> bool:
        return True

    def _buffer_ordinal(self, f: AggregateFunction, j: int) -> int:
        base = len(self.grouping)
        for g in self.agg_fns:
            if g is f:
                return base + j
            base += len(g.buffer_types)
        raise KeyError

    def _make_kernel(self, child_schema: Schema):
        mode = self.mode
        out_schema = self._schema
        grouping = self.grouping
        agg_fns = self.agg_fns

        def _aggregate(batch: DeviceBatch) -> DeviceBatch:
            c = Ctx.for_device(batch)
            live = batch.row_mask()
            # materialize grouping keys + agg inputs as columns
            key_cols = [
                val_to_column(c, g.eval(c), g.data_type) for g in grouping
            ]
            key_cols = [
                DeviceColumn(k.dtype, k.data, k.validity & live, k.lengths)
                for k in key_cols
            ]
            in_cols: list[DeviceColumn] = []
            ops: list[str] = []
            for f in agg_fns:
                if mode in ("partial", "complete"):
                    exprs = [bind(e, child_schema) for e in f.update_exprs]
                    for e, op in zip(exprs, f.update_ops):
                        col = val_to_column(c, e.eval(c), e.data_type)
                        in_cols.append(
                            DeviceColumn(col.dtype, col.data, col.validity & live, col.lengths)
                        )
                        ops.append(op)
                else:
                    for j, op in enumerate(f.merge_ops):
                        in_cols.append(batch.columns[self._buffer_ordinal(f, j)])
                        ops.append(op)
            tmp_schema = Schema(
                [StructField(f"k{i}", k.dtype, True) for i, k in enumerate(key_cols)]
            )
            work = DeviceBatch(
                Schema(list(tmp_schema.fields)), key_cols, batch.num_rows
            )
            # group_aggregate works on a batch containing the key columns;
            # ungrouped reductions force one output group even when empty
            out_keys, out_aggs, num_groups = group_aggregate(
                work,
                list(range(len(key_cols))),
                in_cols,
                ops,
                min_groups=0 if grouping else 1,
            )
            if mode == "partial":
                cols = out_keys + out_aggs
                return DeviceBatch(out_schema, cols, num_groups)
            # final/complete: evaluate aggregates + result projection
            cap = batch.capacity
            gctx = Ctx(jnp, cap, True, [Val(k.data, k.validity, k.lengths) for k in out_keys], num_groups)
            agg_results: list[Val] = []
            i = 0
            for f in agg_fns:
                nbuf = len(f.buffer_types)
                bufs = [
                    Val(out_aggs[i + j].data, out_aggs[i + j].validity, out_aggs[i + j].lengths)
                    for j in range(nbuf)
                ]
                agg_results.append(f.evaluate(gctx, bufs))
                i += nbuf
            rctx = Ctx(
                jnp,
                cap,
                True,
                [Val(k.data, k.validity, k.lengths) for k in out_keys] + agg_results,
                num_groups,
            )
            glive = jnp.arange(cap, dtype=jnp.int32) < num_groups
            cols = []
            for e in self.result_exprs:
                col = val_to_column(rctx, e.eval(rctx), e.data_type)
                cols.append(
                    DeviceColumn(col.dtype, col.data, col.validity & glive, col.lengths)
                )
            return DeviceBatch(out_schema, cols, num_groups)

        return jax.jit(_aggregate)

    def execute(self, ctx: ExecContext) -> PartitionSet:
        child = self.children[0]
        child_schema = child.output
        kernel = self._make_kernel(child_schema)
        merge_jit = self._merge_jit()

        def run(it):
            if self.mode == "partial":
                # per-batch update aggregate, then concat + merge — the
                # reference's hot loop (aggregate.scala:406-468)
                partials = [kernel(db) for db in it]
                if not partials:
                    if self.grouping:
                        return
                    partials = [kernel(empty_batch(child_schema))]
                if len(partials) == 1:
                    yield partials[0]
                else:
                    yield merge_jit(concat_device(partials))
                return
            # final/complete: single merge+evaluate over the whole partition
            batches = list(it)
            if not batches:
                if self.grouping:
                    return
                batches = [empty_batch(child_schema)]
            merged = batches[0] if len(batches) == 1 else concat_device(batches)
            yield kernel(merged)

        return child.execute(ctx).map_partitions(run)

    def _merge_jit(self):
        """Merge-mode aggregation kernel over (concatenated) partial batches.
        The partial-output layout is keys ++ buffers, so key ordinals and
        _buffer_ordinal line up with self's layout."""

        @jax.jit
        def _m(batch: DeviceBatch) -> DeviceBatch:
            in_cols = []
            ops = []
            for f in self.agg_fns:
                for j, op in enumerate(f.merge_ops):
                    in_cols.append(batch.columns[self._buffer_ordinal(f, j)])
                    ops.append(op)
            out_keys, out_aggs, num_groups = group_aggregate(
                batch,
                list(range(len(self.grouping))),
                in_cols,
                ops,
                min_groups=0 if self.grouping else 1,
            )
            return DeviceBatch(self._schema, out_keys + out_aggs, num_groups)

        return _m

    def node_string(self):
        return (
            f"TpuHashAggregate({self.mode}) keys={[str(g) for g in self.grouping]} "
            f"aggs={[str(a) for a in self.agg_fns]}"
        )


class TpuSortExec(Exec):
    """Per-partition sort. Two modes (GpuSortExec.scala:36-42,212-510):

    * single-batch: coalesce the partition into one batch and sort it;
    * out-of-core: when the partition exceeds the configured threshold, sort
      each incoming batch into a *run*, park runs in the spill catalog
      (device→host→disk as memory demands), then merge runs pairwise — at
      most two runs are device-resident at any moment.
    """

    def __init__(self, order: List[SortOrder], child: Exec):
        super().__init__([child])
        self.order = [
            SortOrder(bind(o.child, child.output), o.ascending, o.nulls_first)
            for o in order
        ]

    @property
    def output(self) -> Schema:
        return self.children[0].output

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        from .. import config as cfg
        from ..mem.spill import SpillPriorities, with_oom_retry

        _sort = device_sort_fn(self.order)
        threshold = cfg.OUT_OF_CORE_SORT_THRESHOLD.get(ctx.conf)
        catalog = ctx.catalog

        def make_run(b):
            """Sort one input batch into a spillable run; drop the input ref."""
            catalog.ensure_headroom(2 * b.size_bytes())
            return catalog.register(
                with_oom_retry(catalog, _sort, b), SpillPriorities.WORKING
            )

        def run(it):
            # Stream the input: buffer small partitions for the single-batch
            # fast path; past the threshold, convert each incoming batch into
            # a sorted spillable run immediately so the unsorted input never
            # accumulates on device.
            pending, pending_bytes, runs = [], 0, None
            for b in it:
                if runs is None:
                    pending.append(b)
                    pending_bytes += b.size_bytes()
                    if pending_bytes > threshold and len(pending) > 1:
                        runs = [make_run(p) for p in pending]
                        pending = []
                else:
                    runs.append(make_run(b))
            if runs is None:
                if not pending:
                    return
                merged = concat_device(pending)
                del pending
                yield with_oom_retry(catalog, _sort, merged)
                return
            # Pairwise merge of sorted runs; a merge reuses the sort kernel
            # over the concatenation of exactly two runs, which get_batch()
            # pins so the retry-spill cannot evict what it is merging.
            while len(runs) > 1:
                nxt = []
                for i in range(0, len(runs) - 1, 2):
                    a, b = runs[i], runs[i + 1]

                    def merge_pair(a=a, b=b):
                        # pin the operands FIRST so the headroom pass (and
                        # any retry-spill) cannot evict what is being merged
                        ba, bb = a.get_batch(), b.get_batch()
                        catalog.ensure_headroom(2 * (a.size_bytes + b.size_bytes))
                        return _sort(concat_device([ba, bb]))

                    out = with_oom_retry(catalog, merge_pair)
                    a.close(), b.close()
                    nxt.append(catalog.register(out, SpillPriorities.WORKING))
                if len(runs) % 2:
                    nxt.append(runs[-1])
                runs = nxt
            with runs[0] as final:
                yield final.get_batch()

        return self.children[0].execute(ctx).map_partitions(run)

    def node_string(self):
        return f"TpuSort [{', '.join(map(str, self.order))}]"


def device_sort_fn(order: List[SortOrder]):
    """Jitted whole-batch sort kernel shared by TpuSortExec and TopN."""

    @jax.jit
    def _sort(batch: DeviceBatch) -> DeviceBatch:
        c = Ctx.for_device(batch)
        live = batch.row_mask()
        words = []
        for o in order:
            col = val_to_column(c, o.child.eval(c), o.child.data_type)
            col = DeviceColumn(col.dtype, col.data, col.validity & live, col.lengths)
            from ..ops.sortkeys import column_radix_words

            words.extend(
                column_radix_words(col, o.ascending, o.resolved_nulls_first())
            )
        perm = sort_permutation(words, live)
        return gather_batch(batch, perm, batch.num_rows)

    return _sort


class TpuTakeOrderedAndProjectExec(Exec):
    """TopN on device: per-partition sort + head(n), then merged final
    sort + head(n) (reference: GpuTakeOrderedAndProjectExec, limit.scala)."""

    def __init__(self, n: int, order: List[SortOrder], child: Exec):
        super().__init__([child])
        self.n = n
        self.order = [
            SortOrder(bind(o.child, child.output), o.ascending, o.nulls_first)
            for o in order
        ]

    @property
    def output(self) -> Schema:
        return self.children[0].output

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        n = self.n
        sort_fn = device_sort_fn(self.order)

        @jax.jit
        def _head(batch: DeviceBatch) -> DeviceBatch:
            take = jnp.minimum(batch.num_rows, n)
            live = jnp.arange(batch.capacity, dtype=jnp.int32) < take
            cols = [
                DeviceColumn(c.dtype, c.data, c.validity & live, c.lengths)
                for c in batch.columns
            ]
            return DeviceBatch(batch.schema, cols, take)

        def topn(batches):
            if not batches:
                return None
            merged = batches[0] if len(batches) == 1 else concat_device(batches)
            return _head(sort_fn(merged))

        child_parts = self.children[0].execute(ctx)

        def it():
            partials = []
            for t in child_parts.parts:
                out = topn(list(t()))
                if out is not None:
                    partials.append(out)
            final = topn(partials)
            if final is not None:
                yield final

        return PartitionSet([it])

    def node_string(self):
        return f"TpuTakeOrderedAndProject n={self.n} [{', '.join(map(str, self.order))}]"


class TpuExpandExec(Exec):
    """Projection-list fan-out per batch (GpuExpandExec analogue): each
    projection compiles into the same fused kernel; output batches share the
    input's row count."""

    def __init__(self, projections: List[List[Expression]], names: List[str], child: Exec):
        super().__init__([child])
        self.projections = [
            [bind(e, child.output) for e in proj] for proj in projections
        ]
        from ..types import NullType

        fields = []
        for i, name in enumerate(names):
            es = [proj[i] for proj in self.projections]
            dt = next(
                (e.data_type for e in es if not isinstance(e.data_type, NullType)),
                es[0].data_type,
            )
            fields.append(StructField(name, dt, any(e.nullable for e in es)))
        self._schema = Schema(fields)
        schema = self._schema
        projections = self.projections

        @jax.jit
        def _expand(batch: DeviceBatch) -> list[DeviceBatch]:
            c = Ctx.for_device(batch)
            live = batch.row_mask()
            out = []
            for proj in projections:
                cols = []
                for e, f in zip(proj, schema):
                    col = val_to_column(c, e.eval(c), f.data_type)
                    cols.append(
                        DeviceColumn(f.data_type, col.data, col.validity & live, col.lengths)
                    )
                out.append(DeviceBatch(schema, cols, batch.num_rows))
            return out

        self._fn = _expand

    @property
    def output(self) -> Schema:
        return self._schema

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        fn = self._fn

        def run(it):
            for db in it:
                yield from fn(db)

        return self.children[0].execute(ctx).map_partitions(run)

    def node_string(self):
        return f"TpuExpand x{len(self.projections)}"


class TpuShuffleExchangeExec(Exec):
    """Partitioned exchange with on-device bucketing and device-side slicing
    (GpuShuffleExchangeExec + the four GpuPartitioning impls;
    sliceInternalOnGpu analogue). Hash = murmur3 pmod; range = radix-word
    compare against host-sampled bounds; round-robin; single. In-process:
    device batches move between partitions without leaving HBM; the
    multi-process serializer path lives in shuffle/."""

    def __init__(self, partitioning, child: Exec):
        super().__init__([child])
        from .cpu import _bind_partitioning

        self.partitioning = _bind_partitioning(partitioning, child.output)

    @property
    def num_partitions(self) -> int:
        return self.partitioning.num_partitions

    @property
    def output(self) -> Schema:
        return self.children[0].output

    @property
    def is_device(self) -> bool:
        return True

    def _scatter_fns(self, nparts):
        """Build the jitted kernels for this exchange's partitioning; XLA's
        own compile cache dedupes retraces across execute() calls."""
        from ..plan.partitioning import (
            HashPartitioning,
            RangePartitioning,
            RoundRobinPartitioning,
            words_partition_ids,
        )

        part = self.partitioning

        if isinstance(part, HashPartitioning) and part.keys:
            keys = part.keys

            @jax.jit
            def hash_slice(batch: DeviceBatch) -> list[DeviceBatch]:
                c = Ctx.for_device(batch)
                cols = []
                for k in keys:
                    col = val_to_column(c, k.eval(c), k.data_type)
                    cols.append((k.data_type, col.data, col.validity, col.lengths))
                h = murmur3_rows(jnp, cols, batch.capacity)
                pids = partition_ids(jnp, h, nparts)
                return [
                    compact(batch, (pids == p) & batch.row_mask())
                    for p in range(nparts)
                ]

            return ("hash", hash_slice)

        if isinstance(part, RoundRobinPartitioning):

            @jax.jit
            def rr_slice(batch: DeviceBatch, start) -> list[DeviceBatch]:
                pids = (start + jnp.arange(batch.capacity, dtype=jnp.int32)) % nparts
                return [
                    compact(batch, (pids == p) & batch.row_mask())
                    for p in range(nparts)
                ]

            return ("roundrobin", rr_slice)

        if isinstance(part, RangePartitioning):
            order = part.order

            def batch_word_groups(batch: DeviceBatch):
                """Per-order-column radix word lists (aligned later)."""
                from ..ops.sortkeys import column_radix_words

                c = Ctx.for_device(batch)
                return [
                    column_radix_words(
                        val_to_column(c, o.child.eval(c), o.child.data_type),
                        o.ascending,
                        o.resolved_nulls_first(),
                    )
                    for o in order
                ]

            words_jit = jax.jit(batch_word_groups)

            @jax.jit
            def range_slice(batch: DeviceBatch, words, bounds) -> list[DeviceBatch]:
                pids = words_partition_ids(jnp, words, bounds)
                return [
                    compact(batch, (pids == p) & batch.row_mask())
                    for p in range(nparts)
                ]

            return ("range", (words_jit, range_slice))

        return ("single", None)

    def execute(self, ctx: ExecContext) -> PartitionSet:
        from ..mem.spill import with_oom_retry
        from ..plan.partitioning import SAMPLE_PER_BATCH, compute_range_bounds

        nparts = self.num_partitions
        kind, fn = self._scatter_fns(nparts)
        catalog = ctx.catalog
        child_parts = self.children[0].execute(ctx)
        state = {"buckets": None}

        def materialize():
            if state["buckets"] is not None:
                return state["buckets"]
            buckets = [[] for _ in range(nparts)]
            if kind == "range":
                from ..plan.partitioning import align_word_groups

                words_jit, range_slice = fn
                order = self.partitioning.order
                batches, group_lists = [], []
                for t in child_parts.parts:
                    for db in t():
                        if db.row_count() == 0:
                            continue
                        batches.append(db)
                        group_lists.append(with_oom_retry(catalog, words_jit, db))
                # string columns may encode to different word counts per
                # batch (bucketed widths) — align before sampling/bucketing
                all_words = align_word_groups(group_lists, order, jnp)
                del group_lists
                samples = []
                for db, words in zip(batches, all_words):
                    n = db.row_count()
                    idx = np.arange(0, n, max(1, n // SAMPLE_PER_BATCH))
                    samples.append([np.asarray(w[:n])[idx] for w in words])
                bounds = None
                if samples:
                    sample_words = [
                        np.concatenate([s[i] for s in samples])
                        for i in range(len(samples[0]))
                    ]
                    bounds = compute_range_bounds(sample_words, nparts)
                jb = None if bounds is None else [jnp.asarray(b) for b in bounds]
                for db, words in zip(batches, all_words):
                    if jb is None:
                        buckets[0].append(db)
                        continue
                    for p, s in enumerate(
                        with_oom_retry(catalog, range_slice, db, words, jb)
                    ):
                        buckets[p].append(s)
            else:
                for pi, t in enumerate(child_parts.parts):
                    offset = 0
                    for db in t():
                        if kind == "hash":
                            for p, s in enumerate(with_oom_retry(catalog, fn, db)):
                                buckets[p].append(s)
                        elif kind == "roundrobin":
                            start = jnp.asarray((pi + offset) % nparts, jnp.int32)
                            offset += db.row_count()
                            for p, s in enumerate(
                                with_oom_retry(catalog, fn, db, start)
                            ):
                                buckets[p].append(s)
                        else:
                            buckets[0].append(db)
            state["buckets"] = buckets
            return buckets

        from .. import config as cfg

        if cfg.SHUFFLE_MANAGER_ENABLED.get(ctx.conf):
            # Accelerated path: park partition buckets in the spillable
            # shuffle catalog and read them back through the caching
            # reader (RapidsShuffleManager writer/reader protocol).
            mgr_state = {"shuffle_id": None}

            def ensure_written():
                if mgr_state["shuffle_id"] is not None:
                    return mgr_state["shuffle_id"]
                manager = ctx.shuffle_manager
                sid = ctx.next_shuffle_id()
                writer = manager.get_writer(sid, map_id=0, num_partitions=nparts)
                for p, bucket in enumerate(materialize()):
                    for db in bucket:
                        if db.row_count():
                            writer.write(p, db)
                writer.commit()
                state["buckets"] = None  # catalog owns the batches now
                mgr_state["shuffle_id"] = sid
                return sid

            consumed: set = set()

            def make_managed(p):
                def it():
                    sid = ensure_written()
                    yield from ctx.shuffle_manager.get_reader().read_partitions(
                        sid, p, p + 1
                    )
                    # free catalog-held map output once every partition has
                    # been drained (ShuffleBufferCatalog unregisterShuffle)
                    consumed.add(p)
                    if len(consumed) == nparts:
                        ctx.shuffle_manager.unregister_shuffle(sid)

                return it

            return PartitionSet([make_managed(p) for p in range(nparts)])

        def make(p):
            def it():
                for db in materialize()[p]:
                    yield db

            return it

        return PartitionSet([make(p) for p in range(nparts)])

    def node_string(self):
        return f"TpuShuffleExchange {self.partitioning} p={self.num_partitions}"


class TpuLimitExec(Exec):
    def __init__(self, n: int, child: Exec):
        super().__init__([child])
        self.n = n

    @property
    def output(self) -> Schema:
        return self.children[0].output

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        limit = self.n
        child_parts = self.children[0].execute(ctx)

        @jax.jit
        def _head(batch: DeviceBatch, remaining) -> DeviceBatch:
            take = jnp.minimum(batch.num_rows, remaining)
            live = jnp.arange(batch.capacity, dtype=jnp.int32) < take
            cols = [
                DeviceColumn(c.dtype, c.data, c.validity & live, c.lengths)
                for c in batch.columns
            ]
            return DeviceBatch(batch.schema, cols, take)

        def it():
            remaining = limit
            for t in child_parts.parts:
                for db in t():
                    if remaining <= 0:
                        return
                    out = _head(db, jnp.asarray(remaining, jnp.int32))
                    n = out.row_count()
                    remaining -= n
                    if n:
                        yield out

        return PartitionSet([it])
