"""CPU window operator — oracle/fallback for the window family.

Reference: GpuWindowExec.scala + GpuWindowExpression.scala. Implemented as a
per-partition python loop over numpy segments — intentionally a *different*
algorithm from the device kernel (segmented scans) so differential tests
cross-check two independent implementations.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np
import pyarrow as pa

from ..columnar.host import arrow_from_np, concat_batches, np_from_arrow
from ..expr import Expression, bind
from ..expr.aggregates import Average, Count, Max, Min, Sum
from ..expr.base import Ctx, Literal
from ..expr.windows import (
    CURRENT_ROW,
    UNBOUNDED_FOLLOWING,
    UNBOUNDED_PRECEDING,
    CumeDist,
    DenseRank,
    Lag,
    Lead,
    NTile,
    PercentRank,
    Rank,
    RowNumber,
    WindowExpression,
)
from ..plan.logical import SortOrder
from ..plan.physical import Exec, ExecContext, PartitionSet
from ..types import DOUBLE, INT, LONG, Schema, StringType, StructField
from .cpu import _cpu_ctx, _val_to_np, cpu_sort_indices


class CpuWindowExec(Exec):
    """Appends one column per window expression; all share one spec."""

    def __init__(self, window_cols: list, child: Exec):
        super().__init__([child])
        self.window_cols = window_cols  # [(name, WindowExpression)]
        self.spec = window_cols[0][1].spec
        fields = list(child.output.fields)
        for name, we in window_cols:
            fields.append(StructField(name, we.data_type, we.nullable))
        self._schema = Schema(fields)

    @property
    def output(self) -> Schema:
        return self._schema

    def execute(self, ctx: ExecContext) -> PartitionSet:
        child = self.children[0]
        schema = child.output

        def fn(it):
            rb = concat_batches(schema, list(it))
            if rb.num_rows == 0:
                yield pa.RecordBatch.from_arrays(
                    [pa.nulls(0, f.data_type.to_arrow()) for f in self._schema],
                    schema=self._schema.to_arrow(),
                )
                return
            yield self._compute(rb, schema)

        return child.execute(ctx).map_partitions(fn)

    # ── the window computation over one coalesced partition ────────────
    def _compute(self, rb: pa.RecordBatch, schema: Schema) -> pa.RecordBatch:
        spec = self.spec
        n = rb.num_rows
        order = [
            SortOrder(bind(o.child, schema), o.ascending, o.nulls_first)
            for o in spec.order_by
        ]
        pkeys = [bind(p, schema) for p in spec.partition_by]
        sort_spec = [SortOrder(p, True, True) for p in pkeys] + order
        perm = (
            cpu_sort_indices(rb, schema, sort_spec)
            if sort_spec
            else np.arange(n, dtype=np.int64)
        )
        srb = rb.take(pa.array(perm))
        ctx = _cpu_ctx(srb, schema)

        def key_matrix(exprs):
            from ..ops.sortkeys import np_column_radix_words

            cols = []
            for e in exprs:
                d, v = _val_to_np(ctx, e.eval(ctx))
                cols.extend(np_column_radix_words(e.data_type, d, v))
            return cols

        pk_words = key_matrix(pkeys)
        ok_words = key_matrix([o.child for o in order])

        def starts_from(words):
            s = np.zeros(n, dtype=bool)
            s[0] = True
            for w in words:
                s[1:] |= w[1:] != w[:-1]
            return s

        seg_start = starts_from(pk_words) if pk_words else _first_only(n)
        peer_start = seg_start.copy()
        for w in ok_words:
            peer_start[1:] |= w[1:] != w[:-1]
        seg_bounds = np.flatnonzero(seg_start).tolist() + [n]

        out_cols = []
        for name, we in self.window_cols:
            data, valid = self._compute_one(we, ctx, schema, seg_bounds, peer_start, n)
            out_cols.append(
                arrow_from_np(data, valid, we.data_type)
                if not isinstance(we.data_type, StringType)
                else _np_str_to_arrow(data, valid)
            )
        arrays = [srb.column(i) for i in range(srb.num_columns)] + out_cols
        return pa.RecordBatch.from_arrays(arrays, schema=self._schema.to_arrow())

    def _compute_one(self, we, ctx, schema, seg_bounds, peer_start, n):
        fn = we.function
        frame = we.spec.resolved_frame()

        def _peer_first0(s, e):
            """0-based rank (index of each row's peer-group first row) —
            shared by Rank and PercentRank."""
            ranks = np.arange(e - s)
            return np.maximum.accumulate(np.where(peer_start[s:e], ranks, 0))

        if isinstance(fn, (RowNumber, Rank, DenseRank)):
            out = np.zeros(n, dtype=np.int32)
            for s, e in zip(seg_bounds[:-1], seg_bounds[1:]):
                if isinstance(fn, RowNumber):
                    out[s:e] = np.arange(1, e - s + 1)
                elif isinstance(fn, Rank):
                    out[s:e] = _peer_first0(s, e) + 1
                else:  # DenseRank
                    out[s:e] = np.cumsum(peer_start[s:e].astype(np.int32))
            return out, np.ones(n, dtype=bool)

        if isinstance(fn, (PercentRank, CumeDist, NTile)):
            is_frac = isinstance(fn, (PercentRank, CumeDist))
            out = np.zeros(n, dtype=np.float64 if is_frac else np.int32)
            for s, e in zip(seg_bounds[:-1], seg_bounds[1:]):
                m = e - s
                if isinstance(fn, PercentRank):
                    out[s:e] = _peer_first0(s, e) / (m - 1) if m > 1 else 0.0
                elif isinstance(fn, CumeDist):
                    # rows <= current peer group == each row's peer-group
                    # LAST index + 1 (next-group-start propagation)
                    ends = np.append(peer_start[s + 1 : e], True)
                    ends_idx = np.nonzero(ends)[0]
                    last = ends_idx[np.searchsorted(ends_idx, np.arange(m))]
                    out[s:e] = (last + 1) / m
                else:  # NTile
                    b = fn.buckets
                    base, rem = divmod(m, b)
                    rn0 = np.arange(m)
                    big = rem * (base + 1)
                    out[s:e] = np.where(
                        rn0 < big,
                        rn0 // max(base + 1, 1),
                        rem + (rn0 - big) // max(base, 1),
                    ) + 1
            return out, np.ones(n, dtype=bool)

        if isinstance(fn, (Lead, Lag)):
            x = bind(fn.child, schema)
            d, v = _val_to_np(ctx, x.eval(ctx))
            dflt = bind(fn.default, schema)
            dd, dv = _val_to_np(ctx, dflt.eval(ctx))
            k = fn.offset if isinstance(fn, Lead) else -fn.offset
            is_str = isinstance(we.data_type, StringType)
            out = np.empty(n, dtype=object if is_str else we.data_type.np_dtype)
            if not is_str:
                out[:] = 0
            out_set = np.broadcast_to(np.asarray(dd, dtype=out.dtype), (n,))
            out[:] = out_set
            ov = np.array(np.broadcast_to(np.asarray(dv).astype(bool), (n,)), copy=True)
            for s, e in zip(seg_bounds[:-1], seg_bounds[1:]):
                idx = np.arange(s, e)
                j = idx + k
                ok = (j >= s) & (j < e)
                out[idx[ok]] = np.asarray(d, dtype=out.dtype)[j[ok]]
                ov[idx[ok]] = v[j[ok]]
            return out, ov

        from ..expr.udf import GroupedAggUdf

        if isinstance(fn, GroupedAggUdf):
            # WindowInPandas: the GROUPED_AGG pandas UDF sees each row's
            # frame as pandas Series (reference GpuWindowInPandasExecBase).
            # Whole-partition frames collapse to one call per segment.
            from ..expr.udf import np_to_series, scalar_from_agg_result

            arg_series = []
            for a in fn.args:
                x = bind(a, schema)
                d_, v_ = _val_to_np(ctx, x.eval(ctx))
                d_ = np.array(np.broadcast_to(np.asarray(d_), (n,)), copy=True)
                m_ = np.array(
                    np.broadcast_to(np.asarray(v_).astype(bool), (n,)), copy=True
                )
                arg_series.append(np_to_series(x.data_type, d_, m_))
            out_dt = fn.return_type
            is_str = isinstance(out_dt, StringType)
            out = np.empty(n, dtype=object) if is_str else np.zeros(n, out_dt.np_dtype)
            ov = np.zeros(n, dtype=bool)
            order_info = None
            sentinels = (UNBOUNDED_PRECEDING, CURRENT_ROW, UNBOUNDED_FOLLOWING)
            if frame.frame_type == "range" and not (
                frame.lower in sentinels and frame.upper in sentinels
            ):
                o = we.spec.order_by[0]
                obound = bind(o.child, schema)
                od, ovv = _val_to_np(ctx, obound.eval(ctx))
                od = np.asarray(od)
                if not np.issubdtype(od.dtype, np.floating):
                    od = od.astype(np.int64)
                frame = frame.scaled_for_decimal(obound.data_type)
                order_info = (
                    od if o.ascending else -od,
                    np.asarray(ovv).astype(bool),
                )
            whole_partition = (
                frame.lower == UNBOUNDED_PRECEDING
                and frame.upper == UNBOUNDED_FOLLOWING
            )

            def call(lo, hi):
                args = [s_.iloc[lo : hi + 1].reset_index(drop=True) for s_ in arg_series]
                return scalar_from_agg_result(out_dt, fn.fn(*args))

            for s, e in zip(seg_bounds[:-1], seg_bounds[1:]):
                if whole_partition:
                    scalar, valid = call(s, e - 1)
                    out[s:e] = scalar
                    ov[s:e] = valid
                else:
                    for i in range(s, e):
                        lo, hi = _frame_bounds(frame, i, s, e, peer_start, order_info)
                        if lo > hi:
                            # empty frame: Spark still calls the UDF (a
                            # count-style UDF returns 0, not NULL)
                            scalar, valid = call(0, -1)
                        else:
                            scalar, valid = call(lo, hi)
                        out[i] = scalar
                        ov[i] = valid
            return out, ov

        # aggregate over frame
        inner = _agg_input(fn)
        x = bind(inner, schema)
        d, v = _val_to_np(ctx, x.eval(ctx))
        d = np.asarray(d)
        v = np.asarray(v).astype(bool)
        is_avg = isinstance(fn, Average)
        out_dt = we.data_type
        from ..types import StringType as _StrT

        if isinstance(out_dt, _StrT):
            out = np.empty(n, dtype=object)  # string min/max
        else:
            out = np.zeros(n, dtype=out_dt.np_dtype if not is_avg else np.float64)
        ov = np.zeros(n, dtype=bool)
        order_info = None
        sentinels = (UNBOUNDED_PRECEDING, CURRENT_ROW, UNBOUNDED_FOLLOWING)
        if frame.frame_type == "range" and not (
            frame.lower in sentinels and frame.upper in sentinels
        ):
            o = we.spec.order_by[0]
            obound = bind(o.child, schema)
            od, ovv = _val_to_np(ctx, obound.eval(ctx))
            od = np.asarray(od)
            if not np.issubdtype(od.dtype, np.floating):
                od = od.astype(np.int64)
            frame = frame.scaled_for_decimal(obound.data_type)
            order_info = (
                od if o.ascending else -od,
                np.asarray(ovv).astype(bool),
            )
        for s, e in zip(seg_bounds[:-1], seg_bounds[1:]):
            for i in range(s, e):
                lo, hi = _frame_bounds(frame, i, s, e, peer_start, order_info)
                if lo > hi:
                    vals = np.zeros(0, dtype=d.dtype)
                else:
                    sel = slice(lo, hi + 1)
                    vals = d[sel][v[sel]]
                if isinstance(fn, Count):
                    out[i] = len(vals)
                    ov[i] = True
                elif len(vals) == 0:
                    ov[i] = False
                elif isinstance(fn, Sum):
                    if np.issubdtype(d.dtype, np.integer):
                        out[i] = np.sum(vals.astype(np.int64), dtype=np.int64)
                    else:
                        out[i] = np.sum(vals.astype(np.float64))
                    ov[i] = True
                elif isinstance(fn, (Min, Max)):
                    if np.issubdtype(d.dtype, np.floating):
                        # Spark: NaN greatest
                        if isinstance(fn, Max):
                            out[i] = np.nan if np.isnan(vals).any() else vals.max()
                        else:
                            nn = vals[~np.isnan(vals)]
                            out[i] = nn.min() if len(nn) else np.nan
                    else:
                        out[i] = vals.min() if isinstance(fn, Min) else vals.max()
                    ov[i] = True
                elif is_avg:
                    out[i] = np.sum(vals.astype(np.float64)) / len(vals)
                    ov[i] = True
        return out, ov


def _first_only(n: int) -> np.ndarray:
    s = np.zeros(n, dtype=bool)
    if n:
        s[0] = True
    return s


def _agg_input(fn) -> Expression:
    if isinstance(fn, Sum):
        return fn.update_exprs[0]  # cast to result type (wrapping long sums)
    if isinstance(fn, (Count, Min, Max, Average)):
        return fn.child
    raise NotImplementedError(f"window aggregate {type(fn).__name__}")


def _frame_bounds(frame, i, s, e, peer_start, order_info=None):
    """Inclusive [lo, hi] row bounds of the frame for row i in segment [s, e).
    ``order_info`` = (sign-adjusted values, validity) of the single ORDER BY
    key, required for numeric RANGE bounds; NULL order rows frame over their
    peer group (Spark semantics — incomparable to numeric offsets)."""
    if frame.frame_type == "rows":
        lo = s if frame.lower == UNBOUNDED_PRECEDING else max(s, i + frame.lower)
        hi = e - 1 if frame.upper == UNBOUNDED_FOLLOWING else min(e - 1, i + frame.upper)
        return lo, min(hi, e - 1)

    def peer_lo():
        j = i
        while j > s and not peer_start[j]:
            j -= 1
        return j

    def peer_hi():
        j = i + 1
        while j < e and not peer_start[j]:
            j += 1
        return j - 1

    sentinels = (UNBOUNDED_PRECEDING, CURRENT_ROW, UNBOUNDED_FOLLOWING)
    if frame.lower in sentinels and frame.upper in sentinels:
        lo = s
        hi = e - 1
        if frame.lower == CURRENT_ROW:
            lo = peer_lo()
        if frame.upper == CURRENT_ROW:
            hi = peer_hi()
        return lo, hi
    # numeric RANGE: value-space scan (the device does binary searches —
    # deliberately different algorithm, same semantics)
    sval, ovalid = order_info
    if frame.lower == UNBOUNDED_PRECEDING:
        lo = s
    elif not ovalid[i]:
        lo = peer_lo()
    else:
        delta = 0 if frame.lower == CURRENT_ROW else frame.lower
        target = sval[i] + delta
        lo = e  # empty unless found
        for j in range(s, e):
            if ovalid[j] and sval[j] >= target:
                lo = j
                break
    if frame.upper == UNBOUNDED_FOLLOWING:
        hi = e - 1
    elif not ovalid[i]:
        hi = peer_hi()
    else:
        delta = 0 if frame.upper == CURRENT_ROW else frame.upper
        target = sval[i] + delta
        hi = s - 1
        for j in range(e - 1, s - 1, -1):
            if ovalid[j] and sval[j] <= target:
                hi = j
                break
    return lo, hi


def _np_str_to_arrow(data, valid):
    vals = [
        data[i] if valid[i] else None for i in range(len(valid))
    ]
    return pa.array(vals, type=pa.string())
