"""Dispatch-ahead partition pipelining — the host-stall killer.

JAX dispatch is asynchronous (enqueuing a kernel costs ~nothing; only
``device_get``/``block_until_ready``/scalar conversions wait), but the
engine's operator chains are *pull-based* generators: batch i+1's kernels
are not even dispatched until the consumer finishes with batch i. Every
blocking sink — the D2H pull at collect(), a LIMIT's per-batch row-count
sync — therefore idles the device for a full host round trip per batch
(BENCH_r05: ``host_overhead_frac`` 0.89-0.997 on nearly every TPC-H query).
The reference never pays this: cuDF streams batches through the plan with
no per-op host syncs (PAPER L0/L1).

``PipelinedIterator`` moves the upstream pull loop onto a producer thread
with a bounded in-flight window: device work for batches i+1..i+k is
dispatched while the consumer blocks on batch i. The window is bounded by
BOTH a batch count (``spark.rapids.tpu.pipeline.maxBatches``) and bytes
(``spark.rapids.tpu.pipeline.maxInflightBytes``), and the producer asks the
spill catalog for headroom before each pull — prefetch can never grow the
device working set unboundedly (the memory contract documented in
docs/pipelined-execution.md).

Semantics preserved:

* batches arrive in order, exactly once (no loss, no duplication);
* an upstream error surfaces on the CONSUMING thread, after every batch
  produced before it;
* closing the iterator (LIMIT early-exit, a downstream error) stops the
  producer at the next batch boundary and closes the upstream generator on
  the producer thread (generators must be closed by the thread driving
  them);
* the device-semaphore permit acquired by upstream operators on the
  producer thread is released when production ends (the ``release``
  callback), mirroring TpuCoalescePartitionsExec's worker protocol.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Iterator, Optional

from ..obs import ledger as obs_ledger
from ..obs import trace as obs_trace
from ..obs.metrics import GLOBAL as _GLOBAL_METRICS
from ..obs.metrics import MetricKind

#: per-batch upstream production time on producer threads (log2 buckets):
#: the distribution behind the pipeProducerTime total
_M_DISPATCH_HIST = _GLOBAL_METRICS.histogram("pipeline.dispatchHist")

# Producer threads can run first-touch XLA compiles (upstream kernel
# pulls) whose deep LLVM recursion overflows the default thread stack —
# spawn producers with the engine's shared big-stack helper (ONE process-
# wide lock for every stack_size window; utils/threads.py).
from ..utils.threads import start_big_stack_thread


class PipelinedIterator:
    """Bounded dispatch-ahead prefetcher over an iterator of batches.

    ``metrics`` (optional) is a dict of plan Metrics fed while running:
      * ``depth``     — max batches ever in flight (set_max)
      * ``stall``     — ns the consumer waited on an empty window
      * ``producer``  — ns the producer spent pulling upstream batches
      * ``wait_full`` — ns the producer waited on a full window
      * ``batches``   — batches that crossed the pipe
    """

    def __init__(
        self,
        source: Iterator,
        depth: int = 4,
        max_bytes: int = 0,
        catalog=None,
        release: Optional[Callable[[], None]] = None,
        metrics: Optional[dict] = None,
        cancel_token=None,
    ):
        self._source = source
        # sched/ cancellation: checked before each upstream pull so a
        # cancelled query's producer stops at its next batch boundary; the
        # raised error surfaces on the consuming thread like any upstream
        # failure, and the finally-block release still runs (semaphore/
        # permit holds cannot leak on a cancel)
        self._cancel_token = cancel_token
        self._depth = max(1, int(depth))
        self._max_bytes = max(0, int(max_bytes))
        self._catalog = catalog
        self._release = release
        self._metrics = metrics or {}
        self._cond = threading.Condition()
        self._buf: list = []  # [(item, size_bytes)]
        self._bytes = 0
        self._stop = False
        self._done = False
        self._error: Optional[BaseException] = None
        self._last_size = 0
        # span-context propagation (obs/trace.py): capture the consuming
        # thread's current span so upstream work pulled on the producer
        # thread attributes under the operator that spawned the pipeline —
        # not outside the query trace (the pre-obs attribution hole). The
        # phase ledger propagates the same way: producer-side pulls bill
        # the query's 'dispatch' phase.
        self._trace_ctx = obs_trace.capture_context()
        self._ledger = obs_ledger.current()
        self._thread = start_big_stack_thread(self._produce, "srt-pipeline")

    # ── producer side ───────────────────────────────────────────────────
    def _window_full(self) -> bool:
        depth = self._depth
        if depth > 1:
            # resilience opt-in: while the OOM retry machinery has fired
            # recently anywhere in the process, prefetching ahead only adds
            # allocation pressure to a device that just ran out — clamp the
            # dispatch window to one batch until the pressure signal ages
            # out (resilience/retry.py oom_pressure)
            from ..resilience import retry as _R

            if _R.oom_pressure():
                depth = 1
        if len(self._buf) >= depth:
            return True
        # the bytes bound never blocks an EMPTY window: one batch must
        # always be able to flow or an oversized batch would deadlock
        return bool(
            self._max_bytes
            and self._buf
            and self._bytes >= self._max_bytes
        )

    def _produce(self) -> None:
        obs_trace.attach_context(self._trace_ctx)
        obs_ledger.set_current(self._ledger)
        led = self._ledger
        if self._cancel_token is not None:
            # producer threads drive upstream pulls (and first-touch
            # compiles): give the watchdog a current token here too
            from ..resilience import watchdog as _wd

            _wd.set_current(self._cancel_token)
        m_prod = self._metrics.get("producer")
        m_full = self._metrics.get("wait_full")
        m_depth = self._metrics.get("depth")
        it = self._source
        try:
            while True:
                with self._cond:
                    t0 = time.perf_counter_ns()
                    while self._window_full() and not self._stop:
                        self._cond.wait(0.1)
                    if m_full is not None:
                        m_full.add(time.perf_counter_ns() - t0)
                    if self._stop:
                        return
                if self._catalog is not None and self._last_size:
                    # make room for roughly one more batch BEFORE dispatching
                    # it, so prefetch pressure spills parked buffers instead
                    # of OOMing the allocator mid-kernel
                    try:
                        self._catalog.ensure_headroom(self._last_size)
                    except Exception:
                        pass  # headroom is advisory; the pull may still fit
                if self._cancel_token is not None:
                    self._cancel_token.check()
                t0 = time.perf_counter_ns()
                try:
                    # the pull is the upstream chain's production: kernel
                    # enqueue + operator host work → ledger 'dispatch'
                    # (nested compile/h2d scopes subtract themselves out)
                    with obs_ledger.scope_or_null(led, "dispatch"):
                        item = next(it)
                except StopIteration:
                    return
                pull_ns = time.perf_counter_ns() - t0
                _M_DISPATCH_HIST.observe(pull_ns)
                if m_prod is not None:
                    m_prod.add(pull_ns)
                size = 0
                sb = getattr(item, "size_bytes", None)
                if callable(sb):
                    try:
                        size = int(sb())
                    except Exception:
                        size = 0
                self._last_size = size or self._last_size
                with self._cond:
                    if self._stop:
                        return
                    self._buf.append((item, size))
                    self._bytes += size
                    if m_depth is not None:
                        m_depth.set_max(len(self._buf))
                    self._cond.notify_all()
        except BaseException as e:  # noqa: BLE001 - re-raised on the consumer
            with self._cond:
                self._error = e
                self._cond.notify_all()
        finally:
            close = getattr(it, "close", None)
            if callable(close):
                try:
                    close()  # generators must be closed by their own driver
                except BaseException:  # noqa: BLE001
                    pass
            if self._release is not None:
                try:
                    self._release()
                except Exception:
                    pass
            with self._cond:
                self._done = True
                self._cond.notify_all()

    # ── consumer side ───────────────────────────────────────────────────
    def __iter__(self) -> "PipelinedIterator":
        return self

    def __next__(self):
        m_stall = self._metrics.get("stall")
        m_batches = self._metrics.get("batches")
        with self._cond:
            t0 = time.perf_counter_ns()
            while not self._buf and not self._done and self._error is None:
                self._cond.wait(0.1)
            if m_stall is not None:
                m_stall.add(time.perf_counter_ns() - t0)
            if self._buf:
                item, size = self._buf.pop(0)
                self._bytes -= size
                self._cond.notify_all()
                if m_batches is not None:
                    m_batches.add(1)
                return item
            if self._error is not None:
                err, self._error = self._error, None
                self._done = True
                raise err
            raise StopIteration

    def close(self, join_timeout: float = 0.5) -> None:
        """Stop the producer at its next batch boundary and drop any
        buffered (unconsumed) batches. Safe to call more than once.

        The join is best-effort: a producer parked inside a long device
        pull must not stall a LIMIT early-exit (the latency this layer
        exists to remove), so after a short grace the daemon thread is
        left to finish its in-flight batch alone — it re-checks ``_stop``
        under the lock before buffering, so nothing it produces leaks."""
        with self._cond:
            self._stop = True
            self._buf.clear()
            self._bytes = 0
            self._cond.notify_all()
        self._thread.join(timeout=join_timeout)

    def __enter__(self) -> "PipelinedIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def pipeline_conf(ctx) -> Optional[dict]:
    """Resolve the pipeline settings for one query context; None when the
    kill switch (``spark.rapids.tpu.pipeline.enabled=false``) is thrown."""
    from .. import config as cfg

    if not cfg.PIPELINE_ENABLED.get(ctx.conf):
        return None
    max_bytes = cfg.PIPELINE_MAX_INFLIGHT_BYTES.get(ctx.conf)
    if max_bytes <= 0:
        # auto: a quarter of the spillable device budget when one is known,
        # else 1 GiB — small next to HBM, large next to typical batches
        limit = getattr(ctx.catalog, "device_limit", 0) or 0
        max_bytes = limit // 4 if limit > 0 else (1 << 30)
    return {
        "depth": cfg.PIPELINE_MAX_BATCHES.get(ctx.conf),
        "max_bytes": max_bytes,
    }


def pipe_metrics(node, ctx=None) -> dict:
    """The five ``pipe*`` metrics of a pipelined sink (typed: the window
    depth is a high-watermark, the three waits are nanos timers). Call once
    per execute() — on the single-threaded plan-walk — and pass the dict
    into ``pipelined_partition`` so partition thunks share one metric set.
    With a ``ctx`` the MODERATE level gates collection: at ESSENTIAL the
    sink publishes nothing (the hot loop's no-obs-work contract)."""
    if ctx is not None and not node.metrics_on(ctx, "MODERATE"):
        return {}
    return {
        "depth": node.metric("pipeDispatchDepth", "MODERATE", MetricKind.WATERMARK),
        "stall": node.metric("pipeStallTime", "MODERATE", MetricKind.NANOS),
        "producer": node.metric("pipeProducerTime", "MODERATE", MetricKind.NANOS),
        "wait_full": node.metric("pipeWaitFullTime", "MODERATE", MetricKind.NANOS),
        "batches": node.metric("pipeBatches", "MODERATE", MetricKind.COUNTER),
    }


def pipelined_partition(conf, ctx, it, fn, metrics=None):
    """Run ``fn`` (a batch-stream transform, e.g. the D2H pull loop) over a
    dispatch-ahead view of partition iterator ``it``; falls back to the
    direct pull loop when ``conf`` is None (pipeline disabled). ``conf`` is
    a ``pipeline_conf(ctx)`` result and ``metrics`` a ``pipe_metrics(node)``
    dict — both resolved once per execute(), not per partition."""
    if conf is None:
        led = getattr(ctx, "ledger", None)
        if led is not None:
            # no producer thread to bill 'dispatch' — time the direct
            # upstream pulls here so the ledger decomposition holds in
            # the pipeline-disabled (strictly serial) configuration
            it = led.timed_iter("dispatch", it)
        yield from fn(it)
        return
    pipe = PipelinedIterator(
        it,
        depth=conf["depth"],
        max_bytes=conf["max_bytes"],
        catalog=ctx.catalog,
        release=ctx.semaphore.release_if_necessary,
        metrics=metrics,
        cancel_token=getattr(ctx, "cancel_token", None),
    )
    try:
        yield from fn(pipe)
    finally:
        pipe.close()
