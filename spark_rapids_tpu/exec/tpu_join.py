"""TPU join operators.

Reference: GpuShuffledHashJoinBase + GpuHashJoin.scala (build side coalesced
to a single batch, stream side batched — :165-362) and the per-version
GpuBroadcastHashJoinExec shims. The kernel is the sort-merge matcher in
ops/join.py; the execution contract matches the reference: build on the
RIGHT side, stream the LEFT, one device sync per stream batch to size the
output bucket.
"""
from __future__ import annotations

import dataclasses as dc
from typing import List, Optional

import jax
import jax.numpy as jnp

from ..columnar.device import DeviceBatch, DeviceColumn, bucket_capacity, empty_batch
from ..expr import Expression, bind
from ..expr.base import Ctx, Val
from ..ops.concat import concat_device
from ..ops.gather import compact, gather_column
from ..ops.join import gather_pairs, join_bounds, pad_string_column
from ..plan.physical import Exec, ExecContext, PartitionSet
from ..types import Schema, StringType, StructField
from .tpu import val_to_column


class TpuShuffledHashJoinExec(Exec):
    def __init__(
        self,
        join_type: str,
        left_keys: List[Expression],
        right_keys: List[Expression],
        residual: Optional[Expression],
        left: Exec,
        right: Exec,
        drop_right_keys: Optional[List[str]] = None,
    ):
        super().__init__([left, right])
        self.join_type = join_type
        self.left_keys = [bind(k, left.output) for k in left_keys]
        self.right_keys = [bind(k, right.output) for k in right_keys]
        self.residual = residual
        self.drop_right_keys = drop_right_keys or []
        self._schema = self._compute_schema()

    def _compute_schema(self) -> Schema:
        left, right = self.children
        lt = list(left.output.fields)
        rt = [f for f in right.output.fields if f.name not in self.drop_right_keys]
        if self.join_type in ("left_semi", "left_anti"):
            return Schema(lt)
        if self.join_type in ("left", "full"):
            rt = [dc.replace(f, nullable=True) for f in rt]
        if self.join_type in ("right", "full"):
            lt = [dc.replace(f, nullable=True) for f in lt]
        return Schema(lt + rt)

    @property
    def output(self) -> Schema:
        return self._schema

    @property
    def is_device(self) -> bool:
        return True

    def _right_ordinals(self) -> List[int]:
        right = self.children[1]
        return [
            i
            for i, f in enumerate(right.output.fields)
            if f.name not in self.drop_right_keys
        ]

    # ── kernels ─────────────────────────────────────────────────────────
    def _phase1(self):
        """counts per probe row (+ build order/lower for phase 2)."""
        left_keys, right_keys = self.left_keys, self.right_keys

        @jax.jit
        def fn(build: DeviceBatch, probe: DeviceBatch):
            bctx = Ctx.for_device(build)
            pctx = Ctx.for_device(probe)
            bcols = [val_to_column(bctx, k.eval(bctx), k.data_type) for k in right_keys]
            pcols = [val_to_column(pctx, k.eval(pctx), k.data_type) for k in left_keys]
            # unify string widths across sides per key position
            for i, (b, p) in enumerate(zip(bcols, pcols)):
                if isinstance(b.dtype, StringType):
                    w = max(b.data.shape[1], p.data.shape[1])
                    bcols[i] = pad_string_column(b, w)
                    pcols[i] = pad_string_column(p, w)
            build_order, lower, upper = join_bounds(
                bcols, build.row_mask(), pcols, probe.row_mask()
            )
            counts = upper - lower
            return build_order, lower, counts

        return fn

    def _phase2(self):
        """Gather matched pairs into a static-capacity output batch."""
        out_schema = self._schema
        left_exec, right_exec = self.children
        right_ords = self._right_ordinals()
        jt = self.join_type
        residual = self.residual
        if residual is not None:
            pair_schema = Schema(
                list(left_exec.output.fields) + list(right_exec.output.fields)
            )
            residual = bind(residual, pair_schema)

        @jax.jit
        def fn(
            build: DeviceBatch,
            probe: DeviceBatch,
            build_order,
            lower,
            counts,
            out_cap_arr,
        ):
            out_cap = out_cap_arr.shape[0]
            probe_idx, build_idx, pair_live, total = gather_pairs(
                build_order, lower, counts, probe.row_mask(), out_cap
            )
            lcols = [gather_column(c, probe_idx, pair_live) for c in probe.columns]
            rcols_all = [gather_column(c, build_idx, pair_live) for c in build.columns]
            live = pair_live
            if residual is not None:
                rctx = Ctx(
                    jnp,
                    out_cap,
                    True,
                    [Val(c.data, c.validity, c.lengths) for c in lcols + rcols_all],
                    total,
                )
                rv = residual.eval(rctx)
                keep = rctx.broadcast_bool(rv.data) & rv.full_valid(rctx) & pair_live
                live = keep
            # per-probe / per-build matched flags (for outer joins)
            npr = probe.capacity
            nb = build.capacity
            probe_matched = (
                jnp.zeros(npr, bool).at[jnp.where(live, probe_idx, npr)].set(True, mode="drop")
            )
            build_matched = (
                jnp.zeros(nb, bool).at[jnp.where(live, build_idx, nb)].set(True, mode="drop")
            )
            rcols = [rcols_all[i] for i in right_ords]
            if jt in ("left_semi", "left_anti"):
                want = probe_matched if jt == "left_semi" else (
                    ~probe_matched & probe.row_mask()
                )
                return compact(probe, want), probe_matched, build_matched
            cols = lcols + rcols
            out = DeviceBatch(
                out_schema,
                [
                    DeviceColumn(c.dtype, c.data, c.validity & live, c.lengths)
                    for c in cols
                ],
                live.sum().astype(jnp.int32),
            )
            out = compact(out, live)
            return out, probe_matched, build_matched

        return fn

    def _null_extend(self, batch: DeviceBatch, keep: jax.Array, side: str) -> DeviceBatch:
        """Rows of one side with the other side's columns as NULLs."""
        sub = compact(batch, keep)
        cap = sub.capacity
        left_exec, right_exec = self.children
        right_fields = [
            f for f in right_exec.output.fields if f.name not in self.drop_right_keys
        ]
        if side == "left":  # left rows + null right
            cols = list(sub.columns)
            for f in right_fields:
                cols.append(_null_column(f, cap))
        else:  # null left + right rows (sub has full right schema)
            cols = [_null_column(f, cap) for f in left_exec.output.fields]
            for i in self._right_ordinals():
                cols.append(sub.columns[i])
        return DeviceBatch(self._schema, cols, sub.num_rows)

    # ── execution ───────────────────────────────────────────────────────
    def execute(self, ctx: ExecContext) -> PartitionSet:
        left, right = self.children
        lparts = left.execute(ctx)
        rparts = right.execute(ctx)
        assert lparts.num_partitions == rparts.num_partitions, (
            f"{lparts.num_partitions} vs {rparts.num_partitions}"
        )
        phase1 = self._phase1()
        phase2 = self._phase2()
        jt = self.join_type

        def make(lt, rt):
            def it():
                bbatches = list(rt())
                build = (
                    concat_device(bbatches)
                    if bbatches
                    else empty_batch(right.output)
                )
                build_matched = jnp.zeros(build.capacity, dtype=bool)
                for probe in lt():
                    build_order, lower, counts = phase1(build, probe)
                    total = int(counts.sum())
                    out_cap = bucket_capacity(max(total, 1))
                    out, probe_matched, bmatch = phase2(
                        build,
                        probe,
                        build_order,
                        lower,
                        counts,
                        jnp.zeros(out_cap, jnp.int8),
                    )
                    build_matched = build_matched | bmatch
                    if jt in ("left", "full"):
                        unmatched = (~probe_matched) & probe.row_mask()
                        extra = self._null_extend(probe, unmatched, "left")
                        if extra.row_count():
                            yield extra
                    if out.row_count():
                        yield out
                if jt in ("right", "full"):
                    unmatched = (~build_matched) & build.row_mask()
                    extra = self._null_extend(build, unmatched, "right")
                    if extra.row_count():
                        yield extra

            return it

        return PartitionSet([make(lt, rt) for lt, rt in zip(lparts.parts, rparts.parts)])

    def node_string(self):
        return (
            f"TpuShuffledHashJoin {self.join_type} "
            f"[{', '.join(map(str, self.left_keys))}] [{', '.join(map(str, self.right_keys))}]"
        )


def _null_column(f: StructField, cap: int) -> DeviceColumn:
    from ..columnar.device import MIN_STR_WIDTH

    if isinstance(f.data_type, StringType):
        return DeviceColumn(
            f.data_type,
            jnp.zeros((cap, MIN_STR_WIDTH), jnp.uint8),
            jnp.zeros(cap, bool),
            jnp.zeros(cap, jnp.int32),
        )
    return DeviceColumn(
        f.data_type,
        jnp.zeros(cap, f.data_type.np_dtype),
        jnp.zeros(cap, bool),
    )
