"""TPU join operators.

Reference: GpuShuffledHashJoinBase + GpuHashJoin.scala (build side coalesced
to a single batch, stream side batched — :165-362) and the per-version
GpuBroadcastHashJoinExec shims. The kernel is the sort-merge matcher in
ops/join.py; the execution contract matches the reference: build on the
RIGHT side, stream the LEFT. Output buckets are sized by ONE batched device
sync per stream WINDOW (phase1 for up to _PROBE_WINDOW batches dispatches
before a single pull of their match totals — over a tunneled PJRT link a
per-batch sync is a ~100ms+ round trip each).
"""
from __future__ import annotations

import dataclasses as dc
import threading
from typing import List, Optional

import jax
import jax.numpy as jnp

from ..columnar.device import DeviceBatch, DeviceColumn, bucket_capacity, dc_replace, empty_batch
from ..expr import Expression, bind
from ..expr.base import Ctx, Val
from ..ops.concat import concat_device
from ..ops.gather import compact, gather_column
from ..ops.join import gather_pairs, join_bounds, join_output_schema, pad_string_column
from ..plan.physical import Exec, ExecContext, PartitionSet
from ..types import Schema, StringType, StructField
from .tpu import val_to_column
from .. import kernels as K


def _colocate_with(batch: DeviceBatch, anchor: DeviceBatch) -> DeviceBatch:
    """Move ``batch`` onto ``anchor``'s device when they differ (mesh mode
    can mix mesh-exchanged and host-exchanged join inputs); single-device
    mode is a metadata check only."""

    def dev(b):
        if not b.columns:
            return None
        x = b.columns[0].data
        devices = getattr(x, "devices", None)
        if devices is None:
            return None
        try:
            return next(iter(devices()))
        except Exception:
            return None

    da, db = dev(batch), dev(anchor)
    if da is None or db is None or da == db:
        return batch
    return jax.device_put(batch, db)


def _link_aqe_exchanges(left: Exec, right: Exec, join_type: str = "inner") -> None:
    """Positional partition pairing requires both join inputs to share one
    AQE coalesce assignment. Find the shuffle exchange feeding each side
    (descending through batch-coalesce wrappers); link the pair so each
    computes the grouping from combined sizes, or disable coalescing when
    only one side is exchange-fed (the other side's partitioning is fixed).
    The join type rides along so the skew-split pass knows which side may
    be split (the other side is replicated — only legal when replication
    cannot emit unmatched rows). Spark parity: AQE applies identical
    CoalescedPartitionSpecs to both shuffle reads of a join
    (ShufflePartitionsUtil) and OptimizeSkewedJoin splits a skewed side
    while replicating the other."""
    from .tpu import TpuCoalesceBatchesExec, TpuShuffleExchangeExec

    def find(node: Exec):
        while True:
            if isinstance(node, TpuShuffleExchangeExec):
                return node
            if isinstance(node, TpuCoalesceBatchesExec):
                node = node.children[0]
                continue
            return None

    lex, rex = find(left), find(right)
    if lex is not None and rex is not None:
        lex._aqe_peer, rex._aqe_peer = rex, lex
        lex._aqe_side, rex._aqe_side = "left", "right"
        lex._aqe_join_type = rex._aqe_join_type = join_type
    else:
        for ex in (lex, rex):
            if ex is not None:
                ex._aqe_disabled = True


# probe batches whose phase1 results may be held on device concurrently
# while their match totals ride one batched sync (memory bound per stream)
_PROBE_WINDOW = 8


def _stream_probe_join(node, get_build, probe_thunk, phase1, phase2, jt,
                       matched_acc=None, ctx=None):
    """One probe stream joined against one build batch — the shared loop
    under the shuffled, runtime-broadcast-switched, and broadcast joins.
    ``get_build(first_probe)`` supplies the build batch lazily (broadcast
    materializes it on the probe's device); ``matched_acc['m']`` (when
    given) accumulates build-row match bits for right/full null-extension.

    The PROBE side is splittable (each probe row matches against the whole
    build batch independently, and the match-bit accumulator ORs across
    halves like across batches), so with an ``ctx`` the phase1 launch rides
    the OOM retry/split state machine (resilience/retry.py)."""
    from itertools import islice

    from ..resilience import retry as R

    build = None
    it = iter(probe_thunk())
    while True:
        # WINDOWED phase1 dispatch: up to _PROBE_WINDOW probe batches
        # dispatch before ONE batched pull of their match totals — one
        # tunnel round trip per window instead of per batch (q5 r5 profile:
        # 30 sequential ~288ms sync waits were 8.6s of an 8.9s run). The
        # window bound keeps join memory O(window), not O(probe side), and
        # an early-exiting consumer (LIMIT) stops after the current window.
        window = []
        for probe in islice(it, _PROBE_WINDOW):
            if build is None:
                build = get_build(probe)
            # mesh mode: the two sides can land on different devices when
            # only one side's exchange took the mesh path — one jit needs
            # one device
            probe = _colocate_with(probe, build)
            if ctx is not None:
                window.extend(
                    R.run_with_retry(
                        ctx.catalog,
                        lambda b: (b, phase1(build, b)),
                        probe,
                        ctx.retry_policy,
                        op=node._breaker_op,
                        breaker=ctx.breaker,
                    )
                )
            else:
                window.append((probe, phase1(build, probe)))
        if not window:
            return
        # graft: ok(host-sync: output capacities must be chosen on host
        # (bucketed jit signatures) — ONE batched pull for the whole probe
        # window instead of a sync per batch)
        totals = jax.device_get([c.sum() for (_p, (_b, _l, c)) in window])
        tok = ctx.cancel_token if ctx is not None else None
        for i, total_dev in enumerate(totals):
            if tok is not None:
                tok.check()
            probe, (build_order, lower, counts) = window[i]
            window[i] = None  # release as consumed
            # graft: ok(host-sync: already on host — item of the single
            # windowed device_get above)
            total = int(total_dev)
            out_cap = bucket_capacity(max(total, 1))
            out, probe_matched, bmatch = phase2(
                build,
                probe,
                build_order,
                lower,
                counts,
                jnp.zeros(out_cap, jnp.int8),
            )
            if matched_acc is not None:
                matched_acc["m"] = matched_acc["m"] | bmatch
            # possibly-empty batches are yielded WITHOUT a row_count() host
            # sync: an empty capacity-masked batch costs downstream kernels
            # microseconds, a sync costs a tunnel round trip
            if jt in ("left", "full"):
                unmatched = (~probe_matched) & probe.row_mask()
                yield node._null_extend(probe, unmatched, "left")
            yield out


class TpuShuffledHashJoinExec(Exec):
    #: planner rule name the circuit breaker counts runtime failures under
    #: (plan/overrides.py consults breaker.check(rule.name))
    _breaker_op = "ShuffledHashJoinExec"

    def __init__(
        self,
        join_type: str,
        left_keys: List[Expression],
        right_keys: List[Expression],
        residual: Optional[Expression],
        left: Exec,
        right: Exec,
        drop_right_keys: Optional[List[str]] = None,
    ):
        super().__init__([left, right])
        self.join_type = join_type
        self.left_keys = [bind(k, left.output) for k in left_keys]
        self.right_keys = [bind(k, right.output) for k in right_keys]
        self.residual = residual
        self.drop_right_keys = drop_right_keys or []
        self._schema = self._compute_schema()

    def _compute_schema(self) -> Schema:
        left, right = self.children
        return join_output_schema(
            self.join_type, left.output.fields, right.output.fields, self.drop_right_keys
        )

    @property
    def output(self) -> Schema:
        return self._schema

    @property
    def is_device(self) -> bool:
        return True

    def _right_ordinals(self) -> List[int]:
        right = self.children[1]
        return [
            i
            for i, f in enumerate(right.output.fields)
            if f.name not in self.drop_right_keys
        ]

    # ── kernels ─────────────────────────────────────────────────────────
    def _phase1(self):
        """counts per probe row (+ build order/lower for phase 2)."""
        left_keys, right_keys = tuple(self.left_keys), tuple(self.right_keys)

        def make():
            return _make_phase1(left_keys, right_keys)

        return K.jit_kernel(("join_p1", left_keys, right_keys), make)
    def _phase2(self):
        """Gather matched pairs into a static-capacity output batch."""
        out_schema = self._schema
        left_exec, right_exec = self.children
        right_ords = tuple(self._right_ordinals())
        jt = self.join_type
        residual = self.residual
        if residual is not None:
            pair_schema = Schema(
                list(left_exec.output.fields) + list(right_exec.output.fields)
            )
            residual = bind(residual, pair_schema)

        key = ("join_p2", jt, residual, right_ords, out_schema)
        return K.jit_kernel(
            key, lambda: _make_phase2(out_schema, right_ords, jt, residual)
        )

    def _null_extend(self, batch: DeviceBatch, keep: jax.Array, side: str) -> DeviceBatch:
        """Rows of one side with the other side's columns as NULLs."""
        left_exec, right_exec = self.children
        right_fields = [
            f for f in right_exec.output.fields if f.name not in self.drop_right_keys
        ]
        return null_extend_batch(
            self._schema,
            batch,
            keep,
            side,
            left_exec.output.fields,
            right_fields,
            self._right_ordinals(),
        )

    # ── execution ───────────────────────────────────────────────────────
    def _try_broadcast_switch(self, ctx: ExecContext):
        """AQE runtime join-strategy switch (Spark's DynamicJoinSelection +
        local shuffle reader; GpuCustomShuffleReaderExec analogue): when
        the build side's MEASURED map-output size fits the broadcast
        threshold, join every probe partition against ONE concatenated
        build table and read the probe side's exchange LOCALLY — its
        all-to-all bucketing is skipped entirely. Returns
        ``(switched_partition_set | None, reusable_build_parts | None)`` —
        the second slot hands an already-executed build exchange back to
        the normal path so declining never materializes it twice."""
        from .. import config as cfg
        from .tpu import TpuShuffleExchangeExec

        if ctx.mesh is not None or not cfg.ADAPTIVE_ENABLED.get(ctx.conf):
            return None, None
        # broadcast-build-right is only sound when unmatched BUILD rows
        # never surface (they would duplicate per probe partition)
        if self.join_type not in ("inner", "left", "left_semi", "left_anti"):
            return None, None
        left, right = self.children
        if not isinstance(right, TpuShuffleExchangeExec):
            return None, None
        thresh = cfg.ADAPTIVE_BROADCAST_THRESHOLD.get(ctx.conf)
        if thresh < 0:
            thresh = cfg.AUTO_BROADCAST_THRESHOLD.get(ctx.conf)
        if thresh < 0:
            return None, None
        rparts = right.execute(ctx)
        size_fn = ctx.aqe_size_providers.get(id(right))
        if size_fn is None:  # exchange didn't take the AQE path
            return None, rparts
        total = sum(size_fn())
        # the measurement materialized the build side ON THIS thread; drop
        # the device-semaphore permit it acquired or the main thread holds
        # one task slot for the rest of the query
        ctx.semaphore.release_if_necessary()
        if total > thresh:
            # declined: hand the already-executed build partitions back so
            # the normal path doesn't materialize the exchange twice
            return None, rparts
        self.aqe_broadcast_switched = True
        # local shuffle read: bypass the probe exchange's bucketing (the
        # broadcast build holds every key, so co-partitioning is moot)
        probe_src = (
            left.children[0] if isinstance(left, TpuShuffleExchangeExec) else left
        )
        lparts = probe_src.execute(ctx)
        phase1 = self._phase1()
        phase2 = self._phase2()
        jt = self.join_type
        bstate: dict = {}
        block = threading.Lock()

        def build_once() -> DeviceBatch:
            with block:
                if "b" not in bstate:
                    batches = [db for p in rparts.parts for db in p()]
                    bstate["b"] = (
                        concat_device(batches)
                        if batches
                        else empty_batch(right.output)
                    )
                return bstate["b"]

        def make(lt):
            def it():
                yield from _stream_probe_join(
                    self, lambda _p: build_once(), lt, phase1, phase2, jt,
                    ctx=ctx,
                )

            return it

        return PartitionSet([make(lt) for lt in lparts.parts]), None

    def execute(self, ctx: ExecContext) -> PartitionSet:
        left, right = self.children
        # link BEFORE any side executes: the AQE coalesce/skew assignment
        # must see its peer even when the broadcast-switch probe below
        # executes the build exchange first (and then declines)
        _link_aqe_exchanges(left, right, self.join_type)
        switched, reuse_rparts = self._try_broadcast_switch(ctx)
        if switched is not None:
            return switched
        lparts = left.execute(ctx)
        rparts = reuse_rparts if reuse_rparts is not None else right.execute(ctx)
        assert lparts.num_partitions == rparts.num_partitions, (
            f"{lparts.num_partitions} vs {rparts.num_partitions}"
        )
        phase1 = self._phase1()
        phase2 = self._phase2()
        jt = self.join_type

        def make(lt, rt):
            def it():
                bbatches = list(rt())
                build = (
                    concat_device(bbatches)
                    if bbatches
                    else empty_batch(right.output)
                )
                acc = {"m": jnp.zeros(build.capacity, dtype=bool)}
                yield from _stream_probe_join(
                    self, lambda _p: build, lt, phase1, phase2, jt, acc,
                    ctx=ctx,
                )
                if jt in ("right", "full"):
                    unmatched = (~acc["m"]) & build.row_mask()
                    yield self._null_extend(build, unmatched, "right")

            return it

        return PartitionSet([make(lt, rt) for lt, rt in zip(lparts.parts, rparts.parts)])

    def node_string(self):
        return (
            f"TpuShuffledHashJoin {self.join_type} "
            f"[{', '.join(map(str, self.left_keys))}] [{', '.join(map(str, self.right_keys))}]"
        )


class TpuBroadcastExchangeExec(Exec):
    """Build side collected once to a single device batch shared by all join
    tasks (GpuBroadcastExchangeExecBase:238; in-process, the serialize/
    JVM-broadcast/deserialize round trip collapses to one cached batch)."""

    def __init__(self, child: Exec):
        super().__init__([child])
        self._cache = None
        import threading

        self._lock = threading.Lock()

    @property
    def output(self) -> Schema:
        return self.children[0].output

    @property
    def is_device(self) -> bool:
        return True

    def broadcast_batch(self, ctx: ExecContext) -> DeviceBatch:
        with self._lock:
            if self._cache is None:
                # exchanges under a broadcast build run WHOLE in every
                # process: the build table must be complete per executor
                # (multiproc rank-splitting or shared-registry map statuses
                # here would broadcast a partial table)
                ctx.broadcast_depth += 1
                try:
                    parts = self.children[0].execute(ctx)
                    batches = [b for t in parts.parts for b in t()]
                finally:
                    ctx.broadcast_depth -= 1
                self._cache = (
                    concat_device(batches) if batches else empty_batch(self.output)
                )
            return self._cache

    def broadcast_batch_like(self, ctx: ExecContext, peer: DeviceBatch) -> DeviceBatch:
        """Mesh mode: the build batch replicated onto the peer's device (the
        in-process analogue of the broadcast re-materializing per executor);
        per-device copies are cached for the node's lifetime."""
        build = self.broadcast_batch(ctx)
        if ctx.mesh is None:
            return build
        import jax

        dev = next(iter(peer.columns[0].data.devices()))
        with self._lock:
            cache = self.__dict__.setdefault("_dev_cache", {})
            if dev not in cache:
                cache[dev] = jax.device_put(build, dev)
            return cache[dev]

    def execute(self, ctx: ExecContext) -> PartitionSet:
        def it():
            yield self.broadcast_batch(ctx)

        return PartitionSet([it])

    def node_string(self):
        return "TpuBroadcastExchange"


class TpuBroadcastHashJoinExec(TpuShuffledHashJoinExec):
    """Hash join with a broadcast build (right) side: stream partitions stay
    put, each joins the one broadcast batch (GpuBroadcastHashJoinExec shims;
    build-side selection per the reference's
    shims/spark301/.../GpuBroadcastHashJoinExec.scala:63-75).

    right/full outer need BUILD-side null-extension: unmatched build rows
    must surface exactly ONCE globally even though every stream partition
    probes the same broadcast batch. Each partition accumulates its build
    match bits (host-side — per-device broadcast copies share row order);
    the LAST partition to finish ORs them and emits the unmatched tail. A
    partition abandoned early (its consumer stopped — e.g. a satisfied
    limit) skips the tail via GeneratorExit, which is sound: every consumer
    had stopped wanting rows."""

    _breaker_op = "BroadcastHashJoinExec"

    def execute(self, ctx: ExecContext) -> PartitionSet:
        left, right = self.children
        assert isinstance(right, TpuBroadcastExchangeExec)
        assert self.join_type in (
            "inner", "left", "left_semi", "left_anti", "right", "full",
        )
        lparts = left.execute(ctx)
        phase1 = self._phase1()
        phase2 = self._phase2()
        jt = self.join_type

        if jt not in ("right", "full"):
            def make(lt):
                def it():
                    yield from _stream_probe_join(
                        self,
                        lambda probe: right.broadcast_batch_like(ctx, probe),
                        lt,
                        phase1,
                        phase2,
                        jt,
                        ctx=ctx,
                    )

                return it

            return PartitionSet([make(lt) for lt in lparts.parts])

        state = {"remaining": len(lparts.parts), "mask": None, "emitted": False}
        lock = threading.Lock()

        def make_outer(lt):
            def it():
                acc = {"m": None}
                seen_build = {}

                def get_build(probe):
                    b = right.broadcast_batch_like(ctx, probe)
                    seen_build["b"] = b
                    if acc["m"] is None:
                        acc["m"] = jnp.zeros(b.capacity, dtype=bool)
                    return b

                done = False
                abandoned = False
                try:
                    yield from _stream_probe_join(
                        self, get_build, lt, phase1, phase2, jt, acc,
                        ctx=ctx,
                    )
                    done = True
                except GeneratorExit:
                    # consumer stopped wanting rows (e.g. satisfied limit):
                    # this partition is FINISHED for tail purposes
                    abandoned = True
                    raise
                finally:
                    with lock:
                        if acc["m"] is not None:
                            # merging a partial mask (failed/abandoned
                            # attempt) is safe: recorded matches are real,
                            # and a retry re-merges the complete mask.
                            # DEVICE-resident accumulation (the PR-1
                            # row-base pattern): the OR dispatches async —
                            # the old per-partition np.asarray pull paid a
                            # blocking host sync per finished partition.
                            # Masks from partitions placed on OTHER chips
                            # commit to the accumulator's device first
                            # (one bool[capacity] transfer per partition).
                            prev = state["mask"]
                            state["mask"] = (
                                acc["m"]
                                if prev is None
                                else prev | _colocated(prev, acc["m"])
                            )
                        # decrement once per FINISHED partition, never for a
                        # failed attempt — task retry (_run_task) re-runs the
                        # thunk and a per-attempt decrement would emit the
                        # tail early (duplicates) or mark it emitted with an
                        # incomplete mask (lost rows)
                        last = False
                        if done or abandoned:
                            state["remaining"] -= 1
                            last = (
                                state["remaining"] == 0
                                and not state["emitted"]
                            )
                            if last:
                                state["emitted"] = True
                    if last and done:
                        build = seen_build.get("b") or right.broadcast_batch(ctx)
                        mask = state["mask"]
                        rm = build.row_mask()
                        if mask is None:
                            mask = jnp.zeros(build.capacity, dtype=bool)
                        # the accumulated mask may live on another chip
                        # than this (last) partition's build replica
                        unmatched = (~_colocated(rm, mask)) & rm
                        yield self._null_extend(build, unmatched, "right")

            return it

        return PartitionSet([make_outer(lt) for lt in lparts.parts])

    def node_string(self):
        return (
            f"TpuBroadcastHashJoin {self.join_type} "
            f"[{', '.join(map(str, self.left_keys))}]"
        )


def _colocated(anchor, arr):
    """Commit ``arr`` to ``anchor``'s device when the two device arrays
    landed on different chips (placed partitions commit their batches —
    and so the per-partition match masks — to their own devices); an op
    over two differently-committed arrays raises in jax. No-op (and no
    transfer) when the devices already agree or placement is unsharded."""
    try:
        a_dev = anchor.devices()
        if arr.devices() != a_dev:
            (dev,) = a_dev
            arr = jax.device_put(arr, dev)
    except Exception:
        pass
    return arr


def _chunk_device_batch(db: DeviceBatch, rows: int):
    """Slice a device batch into static sub-batches of <= rows (shared by
    the nested-loop and cartesian pair loops)."""
    if db.capacity <= rows:
        yield db
        return
    # chunk over CAPACITY, not the live-row count: the count is a device
    # scalar and syncing it costs a tunnel round trip; padded capacity is at
    # most ~2x the live rows, and the clip below keeps tail chunks empty-valid
    n = db.capacity
    # graft: ok(cancel-beat: slices one already-resident batch; the
    # consuming join loop beats per chunk)
    for lo in range(0, max(n, 1), rows):
        idx = jnp.arange(rows, dtype=jnp.int32) + lo
        live = idx < db.num_rows
        cols = [gather_column(c, idx, live) for c in db.columns]
        yield DeviceBatch(
            db.schema,
            cols,
            jnp.clip(db.num_rows - lo, 0, rows).astype(jnp.int32),
        )


class TpuBroadcastNestedLoopJoinExec(Exec):
    """Cross / conditional (non-equi) join on device.

    Reference: GpuBroadcastNestedLoopJoinExec.scala (Table.crossJoin +
    condition filter) and GpuCartesianProductExec.scala (pairwise batch
    cross join). TPU design: the pair space [n x m] is enumerated as a
    static-capacity flat index batch (li = k // m, ri = k % m), both sides
    gathered, the condition evaluated on the pairs, and matches compacted —
    one fused kernel per (shapes) pair; the stream side is chunked so the
    pair capacity stays bounded."""

    MAX_PAIR_CAP = 1 << 20

    def __init__(
        self,
        join_type: str,
        condition: Optional[Expression],
        left: Exec,
        right: Exec,
    ):
        super().__init__([left, right])
        self.join_type = join_type
        self._schema = join_output_schema(
            join_type, left.output.fields, right.output.fields
        )
        self.condition = (
            bind(condition, Schema(list(left.output.fields) + list(right.output.fields)))
            if condition is not None
            else None
        )

    @property
    def output(self) -> Schema:
        return self._schema

    @property
    def is_device(self) -> bool:
        return True

    def _pair_kernel(self):
        out_schema = self._schema
        condition = self.condition
        jt = self.join_type
        key = ("join_pair", jt, condition, out_schema)
        return K.jit_kernel(key, lambda: _make_pair_kernel(out_schema, condition, jt))


    def _null_extend(self, batch: DeviceBatch, keep: jax.Array, side: str) -> DeviceBatch:
        left_exec, right_exec = self.children
        return null_extend_batch(
            self._schema, batch, keep, side,
            left_exec.output.fields, right_exec.output.fields,
        )

    @staticmethod
    def _stream_rows(build_capacity: int) -> int:
        """Power-of-two stream-side chunk rows for a build of this size."""
        lrows = max(
            1, TpuBroadcastNestedLoopJoinExec.MAX_PAIR_CAP // max(build_capacity, 1)
        )
        p = 1
        while p * 2 <= lrows:
            p *= 2
        return p

    def execute(self, ctx: ExecContext) -> PartitionSet:
        left, right = self.children
        lparts = left.execute(ctx)
        kernel = self._pair_kernel()
        jt = self.join_type
        chunk = _chunk_device_batch

        def make(lt):
            def it():
                rparts = right.execute(ctx)
                rbatches = [b for t in rparts.parts for b in t()]
                build = (
                    concat_device(rbatches) if rbatches else empty_batch(right.output)
                )
                m = build.capacity
                lrows = self._stream_rows(m)
                build_matched = jnp.zeros(m, dtype=bool)
                tok = ctx.cancel_token
                for stream in lt():
                    for lb in chunk(stream, lrows):
                        if tok is not None:
                            tok.check()
                        out, lmatch, rmatch = kernel(lb, build)
                        build_matched = build_matched | rmatch
                        if jt in ("left_semi", "left_anti"):
                            want = lmatch if jt == "left_semi" else (
                                ~lmatch & lb.row_mask()
                            )
                            yield compact(lb, want)
                            continue
                        if jt in ("left", "full"):
                            unmatched = (~lmatch) & lb.row_mask()
                            yield self._null_extend(lb, unmatched, "left")
                        if out is not None:
                            yield out
                if jt in ("right", "full"):
                    unmatched = (~build_matched) & build.row_mask()
                    yield self._null_extend(build, unmatched, "right")

            return it

        # stream side is coalesced to one partition by the planner
        return PartitionSet([make(lt) for lt in lparts.parts])

    def node_string(self):
        return f"TpuBroadcastNestedLoopJoin {self.join_type} {self.condition or ''}"


def null_extend_batch(
    out_schema: Schema,
    batch: DeviceBatch,
    keep: jax.Array,
    side: str,
    left_fields,
    right_fields,
    right_ordinals=None,
) -> DeviceBatch:
    """Rows of one join side with the other side's columns as NULLs — shared
    by the hash and nested-loop joins' outer-extension paths. Cached fused
    kernel (one compact + null-column splice per call, not eager ops)."""
    lf, rf = tuple(left_fields), tuple(right_fields)
    ro = None if right_ordinals is None else tuple(right_ordinals)
    fn = K.kernel(
        ("null_extend", out_schema, side, lf, rf, ro),
        lambda: K.GuardedJit(
            lambda b, k: _null_extend_impl(out_schema, b, k, side, lf, rf, ro)
        ),
    )
    return fn(batch, keep)


def _null_extend_impl(
    out_schema: Schema,
    batch: DeviceBatch,
    keep: jax.Array,
    side: str,
    left_fields,
    right_fields,
    right_ordinals=None,
) -> DeviceBatch:
    sub = compact(batch, keep)
    cap = sub.capacity
    if side == "left":  # left rows + null right
        cols = list(sub.columns) + [_null_column(f, cap) for f in right_fields]
    else:  # null left + right rows
        ords = (
            right_ordinals
            if right_ordinals is not None
            else range(len(batch.columns))
        )
        cols = [_null_column(f, cap) for f in left_fields] + [
            sub.columns[i] for i in ords
        ]
    return DeviceBatch(out_schema, cols, sub.num_rows)


def _null_column(f: StructField, cap: int) -> DeviceColumn:
    from ..columnar.device import MIN_STR_WIDTH

    if isinstance(f.data_type, StringType):
        return DeviceColumn(
            f.data_type,
            jnp.zeros((cap, MIN_STR_WIDTH), jnp.uint8),
            jnp.zeros(cap, bool),
            jnp.zeros(cap, jnp.int32),
        )
    return DeviceColumn(
        f.data_type,
        jnp.zeros(cap, f.data_type.np_dtype),
        jnp.zeros(cap, bool),
    )

def _make_phase1(left_keys: tuple, right_keys: tuple):
    def fn(build: DeviceBatch, probe: DeviceBatch):
        bctx = Ctx.for_device(build)
        pctx = Ctx.for_device(probe)
        bcols = [val_to_column(bctx, k.eval(bctx), k.data_type) for k in right_keys]
        pcols = [val_to_column(pctx, k.eval(pctx), k.data_type) for k in left_keys]
        # unify string widths across sides per key position
        for i, (b, p) in enumerate(zip(bcols, pcols)):
            if isinstance(b.dtype, StringType):
                w = max(b.data.shape[1], p.data.shape[1])
                bcols[i] = pad_string_column(b, w)
                pcols[i] = pad_string_column(p, w)
        build_order, lower, upper = join_bounds(
            bcols, build.row_mask(), pcols, probe.row_mask()
        )
        counts = upper - lower
        return build_order, lower, counts

    return fn


def _make_phase2(out_schema: Schema, right_ords: tuple, jt: str, residual):
    def fn(
            build: DeviceBatch,
            probe: DeviceBatch,
            build_order,
            lower,
            counts,
            out_cap_arr,
        ):
            out_cap = out_cap_arr.shape[0]
            probe_idx, build_idx, pair_live, total = gather_pairs(
                build_order, lower, counts, probe.row_mask(), out_cap
            )
            lcols = [gather_column(c, probe_idx, pair_live) for c in probe.columns]
            rcols_all = [gather_column(c, build_idx, pair_live) for c in build.columns]
            live = pair_live
            if residual is not None:
                rctx = Ctx(
                    jnp,
                    out_cap,
                    True,
                    [Val(c.data, c.validity, c.lengths) for c in lcols + rcols_all],
                    total,
                )
                rv = residual.eval(rctx)
                keep = rctx.broadcast_bool(rv.data) & rv.full_valid(rctx) & pair_live
                live = keep
            # per-probe / per-build matched flags (for outer joins)
            npr = probe.capacity
            nb = build.capacity
            probe_matched = (
                jnp.zeros(npr, bool).at[jnp.where(live, probe_idx, npr)].set(True, mode="drop")
            )
            build_matched = (
                jnp.zeros(nb, bool).at[jnp.where(live, build_idx, nb)].set(True, mode="drop")
            )
            rcols = [rcols_all[i] for i in right_ords]
            if jt in ("left_semi", "left_anti"):
                want = probe_matched if jt == "left_semi" else (
                    ~probe_matched & probe.row_mask()
                )
                return compact(probe, want), probe_matched, build_matched
            cols = lcols + rcols
            # num_rows = full capacity: live pairs are scattered across the
            # pair grid, so compact must see every slot (its keep mask is
            # intersected with row_mask)
            out = DeviceBatch(
                out_schema,
                [
                    dc_replace(c, validity=c.validity & live)
                    for c in cols
                ],
                jnp.asarray(out_cap, jnp.int32),
            )
            out = compact(out, live)
            return out, probe_matched, build_matched

    return fn


def _make_pair_kernel(out_schema: Schema, condition, jt: str):
    def fn(lb: DeviceBatch, rb: DeviceBatch):
            n, m = lb.capacity, rb.capacity
            cap = n * m
            li = jnp.arange(cap, dtype=jnp.int32) // m
            ri = jnp.arange(cap, dtype=jnp.int32) % m
            pair_live = (li < lb.num_rows) & (ri < rb.num_rows)
            lcols = [gather_column(c, li, pair_live) for c in lb.columns]
            rcols = [gather_column(c, ri, pair_live) for c in rb.columns]
            live = pair_live
            if condition is not None:
                cctx = Ctx(
                    jnp,
                    cap,
                    True,
                    [Val(c.data, c.validity, c.lengths) for c in lcols + rcols],
                    live.sum().astype(jnp.int32),
                )
                cv = condition.eval(cctx)
                live = cctx.broadcast_bool(cv.data) & cv.full_valid(cctx) & pair_live
            # matched flags per side row (outer/semi/anti bookkeeping)
            left_matched = (
                jnp.zeros(n, bool).at[jnp.where(live, li, n)].set(True, mode="drop")
            )
            right_matched = (
                jnp.zeros(m, bool).at[jnp.where(live, ri, m)].set(True, mode="drop")
            )
            if jt in ("left_semi", "left_anti"):
                return None, left_matched, right_matched
            # num_rows = cap: live pairs are scattered over the [n x m] grid
            # and compact intersects its keep mask with row_mask
            out = DeviceBatch(
                out_schema,
                [
                    dc_replace(c, validity=c.validity & live)
                    for c in lcols + rcols
                ],
                jnp.asarray(cap, jnp.int32),
            )
            return compact(out, live), left_matched, right_matched

    return fn


class TpuCartesianProductExec(TpuBroadcastNestedLoopJoinExec):
    """Pairwise-partition cross join — GpuCartesianProductExec.scala:349.

    Where the nested-loop join concatenates/broadcasts one side, this exec
    schedules n_left × n_right tasks, each crossing ONE (left, right)
    partition pair through the same fused pair kernel. Only cross/inner
    shapes plan here (outer variants need global matched-set bookkeeping and
    stay on the NLJ path — same split as the reference)."""

    def execute(self, ctx: ExecContext) -> PartitionSet:
        left, right = self.children
        lparts = left.execute(ctx)
        rparts = right.execute(ctx)
        kernel = self._pair_kernel()

        chunk = _chunk_device_batch

        def make(lt, rt):
            def it():
                rbatches = list(rt())
                build = (
                    concat_device(rbatches) if rbatches else empty_batch(right.output)
                )
                p = self._stream_rows(build.capacity)
                tok = ctx.cancel_token
                for stream in lt():
                    for lb in chunk(stream, p):
                        if tok is not None:
                            tok.check()
                        out, _lm, _rm = kernel(lb, build)
                        if out is not None:
                            yield out

            return it

        return PartitionSet(
            [make(lt, rt) for lt in lparts.parts for rt in rparts.parts]
        )

    def node_string(self):
        return f"TpuCartesianProduct {self.condition or ''}"
