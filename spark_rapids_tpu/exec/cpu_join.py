"""CPU join operators — oracle/fallback for the join family.

Reference: GpuHashJoin.scala (Table.innerJoin/leftJoin over key columns with
null-key filtering, :282), GpuShuffledHashJoinBase, GpuBroadcastNestedLoop
JoinExec. Spark join-key semantics: NULL keys never match (unlike grouping);
NaN keys DO match each other and -0.0 == 0.0 (Spark normalizes join keys).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np
import pyarrow as pa

from ..columnar.host import arrow_from_np, batch_from_columns, concat_batches, np_from_arrow
from ..expr import Expression, bind
from ..expr.base import Ctx
from ..plan.physical import Exec, ExecContext, PartitionSet
from ..types import Schema, StructField
from .cpu_kernels import normalized_float_bits
from .cpu import _cpu_ctx, _val_to_np


def _key_codes(keys: List[Expression], rb: pa.RecordBatch, schema: Schema):
    """Encode key columns into side-independent comparable values + per-row
    all-valid mask. Must NOT use per-side dictionaries (codes from one side
    would be meaningless on the other): strings stay strings, floats become
    normalized bit patterns (NaN canonical, -0.0 -> 0.0), others int64."""
    from ..types import DoubleType, FloatType, StringType

    c = _cpu_ctx(rb, schema)
    n = rb.num_rows
    words = []
    all_valid = np.ones(n, dtype=bool)
    for k in keys:
        d, v = _val_to_np(c, k.eval(c))
        all_valid &= v
        dt = k.data_type
        if isinstance(dt, StringType):
            words.append(d)  # object array of str
        elif isinstance(dt, (FloatType, DoubleType)):
            words.append(normalized_float_bits(d))
        else:
            words.append(d.astype(np.int64))
    if not words:
        return np.zeros((n, 0), dtype=object), all_valid
    return np.stack([w.astype(object) for w in words], axis=1), all_valid


def _take(rb: pa.RecordBatch, idx: np.ndarray) -> pa.RecordBatch:
    return rb.take(pa.array(idx, type=pa.int64()))


def _null_batch(schema: Schema, n: int) -> list[pa.Array]:
    return [pa.nulls(n, type=f.data_type.to_arrow()) for f in schema]


class CpuShuffledHashJoinExec(Exec):
    """Equi-join: both sides hash-partitioned by key; per-partition hash join."""

    def __init__(
        self,
        join_type: str,
        left_keys: List[Expression],
        right_keys: List[Expression],
        residual: Optional[Expression],
        left: Exec,
        right: Exec,
        drop_right_keys: Optional[List[str]] = None,
    ):
        super().__init__([left, right])
        self.join_type = join_type
        self.left_keys = [bind(k, left.output) for k in left_keys]
        self.right_keys = [bind(k, right.output) for k in right_keys]
        self.residual = residual
        self.drop_right_keys = drop_right_keys or []
        self._schema = self._compute_schema()

    def _compute_schema(self) -> Schema:
        from ..ops.join import join_output_schema

        left, right = self.children
        return join_output_schema(
            self.join_type, left.output.fields, right.output.fields, self.drop_right_keys
        )

    @property
    def output(self) -> Schema:
        return self._schema

    def execute(self, ctx: ExecContext) -> PartitionSet:
        left, right = self.children
        lparts = left.execute(ctx)
        rparts = right.execute(ctx)
        assert lparts.num_partitions == rparts.num_partitions
        lschema, rschema = left.output, right.output

        def make(lt, rt):
            def it():
                lrb = concat_batches(lschema, list(lt()))
                rrb = concat_batches(rschema, list(rt()))
                yield self._join_partition(lrb, rrb)

            return it

        return PartitionSet(
            [make(lt, rt) for lt, rt in zip(lparts.parts, rparts.parts)]
        )

    def _join_partition(
        self,
        lrb: pa.RecordBatch,
        rrb: pa.RecordBatch,
        build_matched_acc=None,
    ) -> pa.RecordBatch:
        """``build_matched_acc`` (np bool array over build rows): broadcast
        right/full mode — build match bits are ACCUMULATED instead of
        null-extending per partition (which would duplicate unmatched build
        rows across stream partitions); the caller emits the tail once."""
        left, right = self.children
        lcodes, lvalid = _key_codes(self.left_keys, lrb, left.output)
        rcodes, rvalid = _key_codes(self.right_keys, rrb, right.output)
        # build on right (stream=left), matching the reference's build-side
        table: dict = {}
        for j in range(rrb.num_rows):
            if not rvalid[j]:
                continue
            table.setdefault(tuple(rcodes[j]), []).append(j)
        li: list[int] = []
        ri: list[int] = []
        lmatched = np.zeros(lrb.num_rows, dtype=bool)
        rmatched = np.zeros(rrb.num_rows, dtype=bool)
        for i in range(lrb.num_rows):
            if lvalid[i]:
                js = table.get(tuple(lcodes[i]))
                if js:
                    for j in js:
                        li.append(i)
                        ri.append(j)
                    lmatched[i] = True
                    for j in js:
                        rmatched[j] = True
        li_a = np.asarray(li, dtype=np.int64)
        ri_a = np.asarray(ri, dtype=np.int64)
        # residual condition filters matched pairs (then outer rows re-added)
        if self.residual is not None and len(li_a):
            pairs = self._pairs_batch(lrb, rrb, li_a, ri_a, drop=False)
            rs = Schema(list(self.children[0].output.fields) + list(self.children[1].output.fields))
            c = _cpu_ctx(pairs, rs)
            cond = bind(self.residual, rs)
            d, v = _val_to_np(c, cond.eval(c))
            keep = d.astype(bool) & v
            # recompute matched flags post-residual
            lmatched = np.zeros(lrb.num_rows, dtype=bool)
            rmatched = np.zeros(rrb.num_rows, dtype=bool)
            lmatched[li_a[keep]] = True
            rmatched[ri_a[keep]] = True
            li_a, ri_a = li_a[keep], ri_a[keep]
        jt = self.join_type
        if jt == "inner":
            return self._pairs_batch(lrb, rrb, li_a, ri_a)
        if jt == "left_semi":
            return _take(lrb, np.nonzero(lmatched)[0])
        if jt == "left_anti":
            return _take(lrb, np.nonzero(~lmatched)[0])
        if jt in ("left", "full"):
            extra_l = np.nonzero(~lmatched)[0]
        else:
            extra_l = np.zeros(0, dtype=np.int64)
        if jt in ("right", "full") and build_matched_acc is None:
            extra_r = np.nonzero(~rmatched)[0]
        else:
            extra_r = np.zeros(0, dtype=np.int64)
        if build_matched_acc is not None:
            build_matched_acc |= rmatched
        return self._outer_batch(lrb, rrb, li_a, ri_a, extra_l, extra_r)

    def _right_cols(self, rrb: pa.RecordBatch):
        right = self.children[1]
        return [
            (i, f)
            for i, f in enumerate(right.output.fields)
            if f.name not in self.drop_right_keys
        ]

    def _pairs_batch(self, lrb, rrb, li, ri, drop=True) -> pa.RecordBatch:
        arrays = [lrb.column(i).take(pa.array(li)) for i in range(lrb.num_columns)]
        rcols = self._right_cols(rrb) if drop else [
            (i, f) for i, f in enumerate(self.children[1].output.fields)
        ]
        arrays += [rrb.column(i).take(pa.array(ri)) for i, _ in rcols]
        schema = self._schema if drop else Schema(
            list(self.children[0].output.fields) + list(self.children[1].output.fields)
        )
        names = schema.names
        return pa.RecordBatch.from_arrays(
            [a.combine_chunks() if isinstance(a, pa.ChunkedArray) else a for a in arrays],
            schema=schema.to_arrow(),
        )

    def _outer_batch(self, lrb, rrb, li, ri, extra_l, extra_r) -> pa.RecordBatch:
        parts = []
        matched = self._pairs_batch(lrb, rrb, li, ri)
        parts.append(matched)
        rcols = self._right_cols(rrb)
        if len(extra_l):
            arrays = [lrb.column(i).take(pa.array(extra_l)) for i in range(lrb.num_columns)]
            arrays += _null_batch(Schema([f for _, f in rcols]), len(extra_l))
            parts.append(pa.RecordBatch.from_arrays(arrays, schema=self._schema.to_arrow()))
        if len(extra_r):
            arrays = _null_batch(Schema(list(self.children[0].output.fields)), len(extra_r))
            arrays += [rrb.column(i).take(pa.array(extra_r)) for i, _ in rcols]
            parts.append(pa.RecordBatch.from_arrays(arrays, schema=self._schema.to_arrow()))
        return concat_batches(self._schema, parts)

    def node_string(self):
        return f"CpuShuffledHashJoin {self.join_type} [{', '.join(map(str, self.left_keys))}] [{', '.join(map(str, self.right_keys))}]"


class CpuBroadcastExchangeExec(Exec):
    """Collect the build side once into a single batch shared by every join
    task (GpuBroadcastExchangeExecBase; the JVM-broadcast step collapses to
    an in-process cached batch)."""

    def __init__(self, child: Exec):
        super().__init__([child])
        self._cache = None

    @property
    def output(self) -> Schema:
        return self.children[0].output

    def broadcast_batch(self, ctx: ExecContext) -> pa.RecordBatch:
        if self._cache is None:
            schema = self.children[0].output
            parts = self.children[0].execute(ctx)
            self._cache = concat_batches(
                schema, [b for t in parts.parts for b in t()]
            )
        return self._cache

    def execute(self, ctx: ExecContext) -> PartitionSet:
        def it():
            yield self.broadcast_batch(ctx)

        return PartitionSet([it])

    def node_string(self):
        return "CpuBroadcastExchange"


class CpuBroadcastHashJoinExec(CpuShuffledHashJoinExec):
    """Hash join against a broadcast build side: the stream (left) keeps its
    partitioning, every partition joins the same build batch
    (GpuBroadcastHashJoinExec shims)."""

    def execute(self, ctx: ExecContext) -> PartitionSet:
        left, right = self.children
        lparts = left.execute(ctx)
        lschema = left.output
        assert isinstance(right, CpuBroadcastExchangeExec)

        if self.join_type not in ("right", "full"):
            def make(lt):
                def it():
                    lrb = concat_batches(lschema, list(lt()))
                    yield self._join_partition(lrb, right.broadcast_batch(ctx))

                return it

            return PartitionSet([make(lt) for lt in lparts.parts])

        # right/full outer: accumulate build match bits across stream
        # partitions; the last finisher emits the unmatched-build tail once
        # (mirrors TpuBroadcastHashJoinExec — see its docstring)
        import threading

        state = {"remaining": len(lparts.parts), "mask": None, "emitted": False}
        lock = threading.Lock()

        def make_outer(lt):
            def it():
                rrb = right.broadcast_batch(ctx)
                local = np.zeros(rrb.num_rows, dtype=bool)
                done = False
                abandoned = False
                try:
                    lrb = concat_batches(lschema, list(lt()))
                    yield self._join_partition(
                        lrb, rrb, build_matched_acc=local
                    )
                    done = True
                except GeneratorExit:
                    abandoned = True
                    raise
                finally:
                    with lock:
                        state["mask"] = (
                            local
                            if state["mask"] is None
                            else state["mask"] | local
                        )
                        # once per FINISHED partition — a failed attempt gets
                        # retried and must not consume the countdown (see
                        # TpuBroadcastHashJoinExec)
                        last = False
                        if done or abandoned:
                            state["remaining"] -= 1
                            last = (
                                state["remaining"] == 0
                                and not state["emitted"]
                            )
                            if last:
                                state["emitted"] = True
                    if last and done:
                        extra_r = np.nonzero(~state["mask"])[0]
                        if len(extra_r):
                            empty_l = concat_batches(lschema, [])
                            yield self._outer_batch(
                                empty_l,
                                rrb,
                                np.zeros(0, dtype=np.int64),
                                np.zeros(0, dtype=np.int64),
                                np.zeros(0, dtype=np.int64),
                                extra_r,
                            )

            return it

        return PartitionSet([make_outer(lt) for lt in lparts.parts])

    def node_string(self):
        return (
            f"CpuBroadcastHashJoin {self.join_type} "
            f"[{', '.join(map(str, self.left_keys))}]"
        )


class CpuNestedLoopJoinExec(Exec):
    """Cross/conditional join (GpuBroadcastNestedLoopJoinExec analogue)."""

    def __init__(self, join_type: str, condition: Optional[Expression], left: Exec, right: Exec):
        super().__init__([left, right])
        self.join_type = join_type
        self.condition = condition
        from ..ops.join import join_output_schema

        self._schema = join_output_schema(
            join_type, left.output.fields, right.output.fields
        )

    @property
    def output(self) -> Schema:
        return self._schema

    def execute(self, ctx: ExecContext) -> PartitionSet:
        left, right = self.children
        lschema, rschema = left.output, right.output
        lparts = left.execute(ctx)
        rparts = right.execute(ctx)
        jt = self.join_type

        def it():
            lrb = concat_batches(lschema, [b for t in lparts.parts for b in t()])
            rrb = concat_batches(rschema, [b for t in rparts.parts for b in t()])
            nl, nr = lrb.num_rows, rrb.num_rows
            li = np.repeat(np.arange(nl, dtype=np.int64), nr)
            ri = np.tile(np.arange(nr, dtype=np.int64), nl)
            pair_schema = Schema(list(lschema.fields) + list(rschema.fields))
            arrays = [lrb.column(i).take(pa.array(li)) for i in range(lrb.num_columns)]
            arrays += [rrb.column(i).take(pa.array(ri)) for i in range(rrb.num_columns)]
            pairs = pa.RecordBatch.from_arrays(arrays, schema=pair_schema.to_arrow())
            if self.condition is not None:
                c = _cpu_ctx(pairs, pair_schema)
                cond = bind(self.condition, pair_schema)
                d, v = _val_to_np(c, cond.eval(c))
                keep = d.astype(bool) & v
            else:
                keep = np.ones(nl * nr, dtype=bool)
            matched_l = keep.reshape(nl, nr).any(axis=1) if nl and nr else np.zeros(nl, bool)
            matched_r = keep.reshape(nl, nr).any(axis=0) if nl and nr else np.zeros(nr, bool)
            if jt in ("left_semi", "left_anti"):
                mask = matched_l if jt == "left_semi" else ~matched_l
                yield lrb.filter(pa.array(mask))
                return
            matched = pairs.filter(pa.array(keep))
            blocks = [
                pa.RecordBatch.from_arrays(
                    [matched.column(i) for i in range(matched.num_columns)],
                    schema=self._schema.to_arrow(),
                )
            ]
            if jt in ("left", "full") and (~matched_l).any():
                lsub = lrb.filter(pa.array(~matched_l))
                blocks.append(
                    pa.RecordBatch.from_arrays(
                        [lsub.column(i) for i in range(lsub.num_columns)]
                        + [pa.nulls(lsub.num_rows, f.data_type.to_arrow()) for f in rschema],
                        schema=self._schema.to_arrow(),
                    )
                )
            if jt in ("right", "full") and (~matched_r).any():
                rsub = rrb.filter(pa.array(~matched_r))
                blocks.append(
                    pa.RecordBatch.from_arrays(
                        [pa.nulls(rsub.num_rows, f.data_type.to_arrow()) for f in lschema]
                        + [rsub.column(i) for i in range(rsub.num_columns)],
                        schema=self._schema.to_arrow(),
                    )
                )
            yield from blocks

        return PartitionSet([it])


def extract_equi_join_keys(condition, left_schema: Schema, right_schema: Schema):
    """Split a join condition into (left_keys, right_keys, residual)."""
    from ..expr.predicates import EqualTo, And
    from ..expr import UnresolvedAttribute

    if condition is None:
        return [], [], None
    conjuncts = []

    def flatten(e):
        if isinstance(e, And):
            flatten(e.l)
            flatten(e.r)
        else:
            conjuncts.append(e)

    flatten(condition)
    lk, rk, residual = [], [], []
    for e in conjuncts:
        if isinstance(e, EqualTo):
            sides = []
            for operand in (e.l, e.r):
                if isinstance(operand, UnresolvedAttribute):
                    in_l = operand.name in left_schema.names
                    in_r = operand.name in right_schema.names
                    if in_l and not in_r:
                        sides.append("l")
                        continue
                    if in_r and not in_l:
                        sides.append("r")
                        continue
                sides.append("?")
            if sides == ["l", "r"]:
                lk.append(e.l)
                rk.append(e.r)
                continue
            if sides == ["r", "l"]:
                lk.append(e.r)
                rk.append(e.l)
                continue
        residual.append(e)
    res = None
    for e in residual:
        from ..expr.predicates import And as AndE

        res = e if res is None else AndE(res, e)
    return lk, rk, res


class CpuCartesianProductExec(Exec):
    """Pairwise-partition cross join (GpuCartesianProductExec analogue,
    CPU engine): one task per (left, right) partition pair."""

    def __init__(self, condition: Optional[Expression], left: Exec, right: Exec):
        super().__init__([left, right])
        self.condition = condition
        from ..ops.join import join_output_schema

        self._schema = join_output_schema(
            "inner", left.output.fields, right.output.fields
        )

    @property
    def output(self) -> Schema:
        return self._schema

    def execute(self, ctx: ExecContext) -> PartitionSet:
        left, right = self.children
        lschema, rschema = left.output, right.output
        lparts = left.execute(ctx)
        rparts = right.execute(ctx)
        pair_schema = Schema(list(lschema.fields) + list(rschema.fields))
        cond = (
            bind(self.condition, pair_schema) if self.condition is not None else None
        )

        def make(lt, rt):
            def it():
                lrb = concat_batches(lschema, list(lt()))
                rrb = concat_batches(rschema, list(rt()))
                nl, nr = lrb.num_rows, rrb.num_rows
                if nl == 0 or nr == 0:
                    return
                li = np.repeat(np.arange(nl, dtype=np.int64), nr)
                ri = np.tile(np.arange(nr, dtype=np.int64), nl)
                arrays = [
                    lrb.column(i).take(pa.array(li)) for i in range(lrb.num_columns)
                ]
                arrays += [
                    rrb.column(i).take(pa.array(ri)) for i in range(rrb.num_columns)
                ]
                pairs = pa.RecordBatch.from_arrays(
                    arrays, schema=pair_schema.to_arrow()
                )
                if cond is not None:
                    c = _cpu_ctx(pairs, pair_schema)
                    d, v = _val_to_np(c, cond.eval(c))
                    pairs = pairs.filter(pa.array(d.astype(bool) & v))
                if pairs.num_rows:
                    yield pa.RecordBatch.from_arrays(
                        [pairs.column(i) for i in range(pairs.num_columns)],
                        schema=self._schema.to_arrow(),
                    )

            return it

        return PartitionSet(
            [make(lt, rt) for lt in lparts.parts for rt in rparts.parts]
        )

    def node_string(self):
        return f"CpuCartesianProduct {self.condition or ''}"
