"""Per-partition task context — the TaskContext / InputFileBlockHolder seam.

Reference: Spark's ``TaskContext.partitionId`` and ``InputFileBlockHolder``
(thread-locals set by the scheduler/scan), which the reference's
GpuSparkPartitionID / GpuMonotonicallyIncreasingID / GpuInputFileName read
(GpuSparkPartitionID.scala, GpuMonotonicallyIncreasingID.scala,
GpuInputFileBlock.scala). Here the engine runs partitions through
``PartitionSet`` thunks; each thunk installs a ``TaskInfo`` in a thread-local
for the duration of the partition's iteration.

Expressions cannot read the thread-local directly on the device path — they
run inside a traced ``jax.jit`` program. Instead the task-dependent values are
packaged as ``TaskVals`` (a small pytree of device scalars) and passed as a
traced input to the compiled kernel; ``Ctx.task`` exposes them to expression
``eval``. The host-side ``TaskInfo`` is the source of truth the operators
sample per batch.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Optional

import numpy as np

_LOCAL = threading.local()


class TaskInfo:
    """Mutable per-partition state (one per running partition iteration).

    ``attempt`` is the lineage re-execution counter (Spark's
    ``TaskContext.attemptNumber``): 0 on the first run, bumped by the
    session's task-retry loop for each recovery re-execution and by the
    speculation monitor for duplicate attempts.
    """

    def __init__(self, partition_id: int, attempt: int = 0):
        self.partition_id = partition_id
        self.attempt = attempt
        # running live-row count for monotonically_increasing_id
        self.row_base = 0

    def advance_rows(self, n: int) -> int:
        base = self.row_base
        self.row_base += int(n)
        return base


def current() -> Optional[TaskInfo]:
    return getattr(_LOCAL, "task", None)


def set_current(info: Optional[TaskInfo]) -> None:
    _LOCAL.task = info


def current_attempt() -> int:
    """The attempt number the session's retry/speculation layer set for
    this worker thread (0 outside any retry scope). Read by
    ``plan/physical._scoped_part`` when minting each layer's TaskInfo so
    every plan node of a re-executed partition observes the same attempt."""
    return getattr(_LOCAL, "attempt", 0)


def set_attempt(attempt: int) -> None:
    _LOCAL.attempt = int(attempt)


def get_or_create(partition_id: int = 0) -> TaskInfo:
    t = current()
    if t is None:
        t = TaskInfo(partition_id)
        _LOCAL.task = t
    return t


def set_input_file(path: str, start: int = 0, length: int = -1) -> None:
    """Record the file (and block) currently being scanned. A thread-local
    *separate* from TaskInfo, exactly like Spark's InputFileBlockHolder —
    every pipeline stage of the partition sees the same value regardless of
    which nested TaskInfo is active. Scans read whole files here, so the
    block is (0, file size); -1 length means unknown."""
    if length < 0:
        try:
            import os

            length = os.path.getsize(path)
        except OSError:
            length = -1
    _LOCAL.input_file = path
    _LOCAL.input_block = (start, length)


def input_file() -> str:
    return getattr(_LOCAL, "input_file", "")


def input_file_block() -> tuple:
    """(start, length) of the current block; (-1, -1) outside a scan
    (Spark's InputFileBlockHolder defaults)."""
    return getattr(_LOCAL, "input_block", (-1, -1))


def reset_input_file() -> None:
    _LOCAL.input_file = ""
    _LOCAL.input_block = (-1, -1)


@dataclasses.dataclass
class TaskVals:
    """Task-dependent scalars passed into compiled kernels as traced inputs.

    ``file_bytes``/``file_len`` carry the current input file name as padded
    utf-8 so ``input_file_name()`` stays a pure device expression.
    """

    part_id: object  # int32 scalar
    row_base: object  # int64 scalar
    file_bytes: object  # uint8[w]
    file_len: object  # int32 scalar
    block_start: object = None  # int64 scalar (-1 outside a scan)
    block_length: object = None  # int64 scalar (-1 outside a scan)

    def tree_flatten(self):
        return (
            self.part_id,
            self.row_base,
            self.file_bytes,
            self.file_len,
            self.block_start,
            self.block_length,
        ), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


try:  # register as pytree so TaskVals can cross the jit boundary
    import jax

    jax.tree_util.register_pytree_node_class(TaskVals)
except Exception:  # pragma: no cover - jax always present in this image
    pass


def _encode_file(path: str, xp) -> tuple:
    from ..columnar.device import pad_scalar_bytes

    buf, n = pad_scalar_bytes(path.encode("utf-8"))
    return xp.asarray(buf), xp.asarray(n, dtype=xp.int32)


def task_vals(xp, row_base: Optional[int] = None) -> TaskVals:
    """Sample the thread-local TaskInfo into backend arrays (xp is numpy or
    jax.numpy)."""
    t = current()
    pid = t.partition_id if t else 0
    base = row_base if row_base is not None else (t.row_base if t else 0)
    fname = input_file()
    fb, fl = _encode_file(fname, xp)
    bs, bl = input_file_block()
    return TaskVals(
        xp.asarray(pid, dtype=xp.int32),
        xp.asarray(base, dtype=xp.int64),
        fb,
        fl,
        xp.asarray(bs, dtype=xp.int64),
        xp.asarray(bl, dtype=xp.int64),
    )


DEFAULT_WIDTH = 8


def zero_vals(xp) -> TaskVals:
    return TaskVals(
        xp.asarray(0, dtype=xp.int32),
        xp.asarray(0, dtype=xp.int64),
        xp.zeros(DEFAULT_WIDTH, dtype=xp.uint8),
        xp.asarray(0, dtype=xp.int32),
        xp.asarray(-1, dtype=xp.int64),
        xp.asarray(-1, dtype=xp.int64),
    )


def abstract_zero_vals() -> TaskVals:
    """ShapeDtypeStruct pytree matching ``zero_vals`` — the TaskVals input
    the kernel pre-compilation pass lowers non-task-dependent project /
    filter kernels against (plan/planner.py precompile_plan)."""
    import jax
    import numpy as _np

    S = jax.ShapeDtypeStruct
    return TaskVals(
        S((), _np.int32),
        S((), _np.int64),
        S((DEFAULT_WIDTH,), _np.uint8),
        S((), _np.int32),
        S((), _np.int64),
        S((), _np.int64),
    )


def run_device(fn, it, needs_task, catalog=None, policy=None, op=None,
               breaker=None, token=None):
    """Drive a jitted kernel ``fn(batch, TaskVals)`` over device batches,
    sampling the thread-local task state only when the expression tree
    needs it (shared by TpuProjectExec/TpuFilterExec).

    The running row base (monotonically_increasing_id's per-partition
    offset) accumulates as a DEVICE scalar: ``row_base + num_rows`` is one
    async device add, where the old ``info.advance_rows(db.row_count())``
    paid a blocking host sync per batch — exactly the per-op stall the
    pipelined executor exists to remove. The host TaskInfo still provides
    the partition id and the initial base.

    With a ``catalog``/``policy``, each launch routes through the OOM retry
    state machine (resilience/retry.py): spill-retry, then split-in-half —
    project/filter are row-wise, so halves yield independently. Task-
    dependent kernels keep spill-retry only: splitting would need per-half
    row_base threading, and the task-dependent set (monotonically
    increasing ids, input-file metadata) is never the memory hog.

    ``token`` (sched/cancel.py CancelToken) is checked before every batch —
    the scheduler's cancellation/deadline contract: a cancelled query stops
    dispatching within one batch boundary and unwinds through the normal
    error path (permits, semaphore, spill holds all release)."""
    import jax.numpy as jnp

    from ..obs import ledger as _ledger
    from ..resilience import retry as R

    if token is not None:
        # watchdog current-token install: compiles/fetches beneath this
        # loop label their stall phase on it; each check() is a beat
        from ..resilience import watchdog as _wd

        _wd.set_current(token)
    # host-overhead ledger: each kernel launch bills its enqueue time to
    # the 'dispatch' phase (a first-touch compile nested inside subtracts
    # itself out — exclusive scopes). The ledger is resolved ONCE per
    # partition; un-ledgered paths keep a no-op scope.
    led = _ledger.current()

    def _dispatch_scope():
        return _ledger.scope_or_null(led, "dispatch")

    if not needs_task:
        zeros = zero_vals(jnp)
        if policy is None:
            for db in it:
                if token is not None:
                    token.check()
                with _dispatch_scope():
                    out = fn(db, zeros)
                yield out
            return
        for db in it:
            if token is not None:
                token.check()
            # NOT scoped: run_with_retry yields split halves lazily (the
            # OOM contract — halves must not be held concurrently), so its
            # time lands in the caller's phase instead
            yield from R.run_with_retry(
                catalog, lambda b: fn(b, zeros), db, policy, op=op,
                breaker=breaker,
            )
        return
    base = None  # device-resident running row count (no per-batch sync)
    for db in it:
        if token is not None:
            token.check()
        get_or_create()
        tv = task_vals(jnp, row_base=base)
        with _dispatch_scope():
            if policy is None:
                out = fn(db, tv)
            else:
                out = R.run_once(
                    catalog, lambda b: fn(b, tv), db, policy, op=op,
                    breaker=breaker,
                )
        base = tv.row_base + db.num_rows.astype(jnp.int64)
        yield out
