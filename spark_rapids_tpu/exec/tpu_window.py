"""TPU window operator — one fused XLA kernel per window spec group.

Reference: GpuWindowExec.scala + GpuWindowExpression.scala (cudf
``groupBy.aggregateWindows`` / ``aggregateWindowsOverRanges``). TPU-first
design: instead of cudf's per-function window kernels, the whole spec group
compiles into ONE program over the coalesced partition batch —

1. radix-encode partition + order keys, one variadic stable sort;
2. segment/peer boundaries by adjacent word difference;
3. every window function lowers onto *segmented scans*
   (``lax.associative_scan`` with a reset flag) and gathers:
   running/unbounded frames = inclusive scan (+ gather at segment/peer end),
   bounded sum/count/avg = prefix-sum differences at clamped indices,
   bounded min/max = sparse-table range queries (doubling RMQ),
   numeric RANGE bounds = per-row binary searches in value space,
   lead/lag = in-segment gather, ranks = index arithmetic on peer firsts.

Rows come out partition-sorted (Spark's window output order).
"""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

from ..columnar.device import DeviceBatch, DeviceColumn
from ..expr import Expression, bind
from ..expr.aggregates import Average, Count, Max, Min, Sum
from ..expr.base import Ctx, Val
from ..expr.windows import (
    CURRENT_ROW,
    UNBOUNDED_FOLLOWING,
    UNBOUNDED_PRECEDING,
    CumeDist,
    DenseRank,
    NTile,
    PercentRank,
    Lag,
    Lead,
    Rank,
    RowNumber,
)
from ..ops.concat import concat_device
from ..ops.gather import gather_batch
from ..ops.sortkeys import column_radix_words, sort_permutation
from ..plan.physical import Exec, ExecContext, PartitionSet
from ..types import Schema, StringType, StructField
from .tpu import val_to_column

def _segscan(vals, starts, op):
    """Inclusive segmented scan: op-accumulate left-to-right, reset where
    ``starts``. Standard (flag, value) associative combine."""

    def comb(a, b):
        af, av = a
        bf, bv = b
        return (af | bf, jnp.where(bf, bv, op(av, bv)))

    _, v = jax.lax.associative_scan(comb, (starts, vals))
    return v


def _seg_last_idx(idx, starts, cap):
    """Per-row index of its segment's last row (reverse segmented max)."""
    end_flags = jnp.concatenate([starts[1:], jnp.ones(1, dtype=bool)])
    rev = lambda x: x[::-1]
    return rev(_segscan(rev(idx), rev(end_flags), jnp.maximum))


class TpuWindowExec(Exec):
    def __init__(self, window_cols: list, child: Exec):
        super().__init__([child])
        self.window_cols = window_cols
        self.spec = window_cols[0][1].spec
        fields = list(child.output.fields)
        for name, we in window_cols:
            fields.append(StructField(name, we.data_type, we.nullable))
        self._schema = Schema(fields)

    @property
    def output(self) -> Schema:
        return self._schema

    @property
    def is_device(self) -> bool:
        return True

    def execute(self, ctx: ExecContext) -> PartitionSet:
        from ..mem.spill import with_oom_retry

        child = self.children[0]
        kernel = self._kernel(child.output)
        catalog = ctx.catalog

        def run(it):
            batches = list(it)
            if not batches:
                return
            merged = concat_device(batches)
            del batches
            yield with_oom_retry(catalog, kernel, merged)

        return child.execute(ctx).map_partitions(run)

    def _kernel(self, child_schema: Schema):
        spec = self.spec
        pkeys = tuple(bind(p, child_schema) for p in spec.partition_by)
        orders = tuple(
            (bind(o.child, child_schema), o.ascending, o.resolved_nulls_first())
            for o in spec.order_by
        )
        window_cols = tuple((name, we) for name, we in self.window_cols)
        out_schema = self._schema
        from .. import kernels as K

        key = ("window", pkeys, orders, window_cols, out_schema, child_schema)
        return K.jit_kernel(
            key,
            lambda: _make_window_kernel(
                pkeys, orders, window_cols, out_schema, child_schema
            ),
        )

    def node_string(self):
        names = ", ".join(str(we) for _, we in self.window_cols)
        return f"TpuWindow [{names}]"


def _make_window_kernel(pkeys, orders, window_cols, out_schema, child_schema):
    def fn(batch: DeviceBatch) -> DeviceBatch:
            cap = batch.capacity
            c = Ctx.for_device(batch)
            live0 = batch.row_mask()

            def words_of(exprs_dirs):
                words = []
                for e, asc, nf in exprs_dirs:
                    col = val_to_column(c, e.eval(c), e.data_type)
                    col = DeviceColumn(col.dtype, col.data, col.validity & live0, col.lengths)
                    words.extend(column_radix_words(col, asc, nf))
                return words

            pk_words = words_of([(p, True, True) for p in pkeys])
            ok_words = words_of(orders)
            perm = sort_permutation(pk_words + ok_words, live0)
            sorted_batch = gather_batch(batch, perm, batch.num_rows)
            live = sorted_batch.row_mask()
            idx = jnp.arange(cap, dtype=jnp.int32)

            def starts_from(words):
                s = idx == 0
                for w in words:
                    sw = w[perm]
                    prev = jnp.concatenate([sw[:1], sw[:-1]])
                    s = s | (sw != prev)
                return s & live

            first_live = (idx == 0) & live
            seg_start = starts_from(pk_words) if pkeys else first_live
            peer_start = seg_start
            for w in ok_words:
                sw = w[perm]
                prev = jnp.concatenate([sw[:1], sw[:-1]])
                peer_start = peer_start | ((sw != prev) & live)
            # padding is its own segment so the last live segment ends at
            # num_rows-1, not cap-1 (lead/default, suffix scans, seg_last)
            pad_start = idx == sorted_batch.num_rows
            seg_start = seg_start | pad_start
            peer_start = peer_start | pad_start

            seg_first = _segscan(idx, seg_start, jnp.minimum)
            seg_last = _seg_last_idx(idx, seg_start, cap)
            peer_first = _segscan(idx, peer_start, jnp.minimum)
            peer_last = _seg_last_idx(idx, peer_start, cap)

            sctx = Ctx.for_device(sorted_batch)
            new_cols: List[DeviceColumn] = []
            for name, we in window_cols:
                col = _compute_window_column(
                    we, sctx, child_schema, cap, live,
                    seg_start, seg_first, seg_last,
                    peer_start, peer_first, peer_last, idx,
                )
                new_cols.append(col)
            return DeviceBatch(
                out_schema, list(sorted_batch.columns) + new_cols, sorted_batch.num_rows
            )

    return fn


def _compute_window_column(
    we, ctx, schema, cap, live,
    seg_start, seg_first, seg_last,
    peer_start, peer_first, peer_last, idx,
) -> DeviceColumn:
    fn = we.function
    frame = we.spec.resolved_frame()

    if isinstance(fn, RowNumber):
        out = (idx - seg_first + 1).astype(jnp.int32)
        return DeviceColumn(we.data_type, out, live)
    if isinstance(fn, Rank):
        out = (peer_first - seg_first + 1).astype(jnp.int32)
        return DeviceColumn(we.data_type, out, live)
    if isinstance(fn, DenseRank):
        out = _segscan(peer_start.astype(jnp.int32), seg_start, jnp.add)
        return DeviceColumn(we.data_type, out.astype(jnp.int32), live)
    if isinstance(fn, (PercentRank, CumeDist, NTile)):
        n = (seg_last - seg_first + 1).astype(jnp.float64)
        if isinstance(fn, PercentRank):
            rank = (peer_first - seg_first).astype(jnp.float64)
            out = jnp.where(n > 1, rank / jnp.maximum(n - 1, 1.0), 0.0)
            return DeviceColumn(we.data_type, out, live)
        if isinstance(fn, CumeDist):
            le = (peer_last - seg_first + 1).astype(jnp.float64)
            return DeviceColumn(we.data_type, le / jnp.maximum(n, 1.0), live)
        # NTile: first (n % b) buckets take one extra row
        b = jnp.asarray(fn.buckets, jnp.int64)
        ni = (seg_last - seg_first + 1).astype(jnp.int64)
        rn0 = (idx - seg_first).astype(jnp.int64)  # 0-based row number
        base = ni // b
        rem = ni % b
        big_span = rem * (base + 1)
        in_big = rn0 < big_span
        bucket = jnp.where(
            in_big,
            rn0 // jnp.maximum(base + 1, 1),
            rem + (rn0 - big_span) // jnp.maximum(base, 1),
        )
        return DeviceColumn(
            we.data_type, (bucket + 1).astype(jnp.int32), live
        )

    if isinstance(fn, (Lead, Lag)):
        from ..types import NullType
        from ..ops.join import pad_string_column

        x = bind(fn.child, schema)
        col = val_to_column(ctx, x.eval(ctx), x.data_type)
        dflt = bind(fn.default, schema)
        if isinstance(dflt.data_type, NullType):
            # NULL default: a zeroed, all-invalid column of the input shape
            dcol = DeviceColumn(
                x.data_type,
                jnp.zeros_like(col.data),
                jnp.zeros(cap, bool),
                None if col.lengths is None else jnp.zeros(cap, jnp.int32),
            )
        else:
            dcol = val_to_column(ctx, dflt.eval(ctx), x.data_type)
            if col.data.ndim == 2:  # unify string widths
                w = max(col.data.shape[1], dcol.data.shape[1])
                col = pad_string_column(col, w)
                dcol = pad_string_column(dcol, w)
        k = fn.offset if isinstance(fn, Lead) else -fn.offset
        j = idx + k
        ok = (j >= seg_first) & (j <= seg_last) & live
        safe = jnp.clip(j, 0, cap - 1)
        data = jnp.where(
            ok[:, None] if col.data.ndim == 2 else ok,
            col.data[safe],
            dcol.data,
        )
        valid = jnp.where(ok, col.validity[safe], dcol.validity) & live
        lengths = None
        if col.lengths is not None:
            dlen = dcol.lengths if dcol.lengths is not None else jnp.zeros(cap, jnp.int32)
            lengths = jnp.where(ok, col.lengths[safe], dlen)
        return DeviceColumn(we.data_type, data, valid, lengths)

    # ── aggregates over a frame ─────────────────────────────────────────
    inner = _agg_input(fn)
    x = bind(inner, schema)
    col = val_to_column(ctx, x.eval(ctx), x.data_type)
    data = col.data
    valid = col.validity & live
    is_avg = isinstance(fn, Average)
    is_count = isinstance(fn, Count)

    # frame endpoints as row indices
    sentinels = (UNBOUNDED_PRECEDING, CURRENT_ROW, UNBOUNDED_FOLLOWING)
    if frame.frame_type == "rows":
        lo = seg_first if frame.lower == UNBOUNDED_PRECEDING else jnp.maximum(
            seg_first, idx + frame.lower
        )
        hi = seg_last if frame.upper == UNBOUNDED_FOLLOWING else jnp.minimum(
            seg_last, idx + frame.upper
        )
    elif frame.lower in sentinels and frame.upper in sentinels:
        # peer-bounded RANGE (multi-key orders allowed)
        lo = seg_first if frame.lower == UNBOUNDED_PRECEDING else peer_first
        hi = seg_last if frame.upper == UNBOUNDED_FOLLOWING else peer_last
    else:
        # numeric RANGE: value-space searches over the single order key
        o = we.spec.order_by[0]
        oe = bind(o.child, schema)
        ocol = val_to_column(ctx, oe.eval(ctx), oe.data_type)
        ovalid = ocol.validity & live
        ov = ocol.data
        if not jnp.issubdtype(ov.dtype, jnp.floating):
            ov = ov.astype(jnp.int64)
        frame = frame.scaled_for_decimal(oe.data_type)
        sval = ov if o.ascending else -ov
        # null rows sort to a contiguous block; sentinel keeps sval ascending
        if jnp.issubdtype(sval.dtype, jnp.floating):
            neg_s, pos_s = -jnp.inf, jnp.inf
        else:
            info = jnp.iinfo(sval.dtype)
            neg_s, pos_s = info.min, info.max
        # the nulls block's physical position in the sorted batch
        nulls_first = o.resolved_nulls_first()
        sval = jnp.where(ovalid, sval, neg_s if nulls_first else pos_s)
        lo, hi = _range_frame_bounds(
            frame, sval, ovalid, seg_first, seg_last, peer_first, peer_last, cap
        )
    nonempty = (lo <= hi) & live

    from ..types import StringType as _StrT

    if isinstance(fn, (Min, Max)) and isinstance(x.data_type, _StrT):
        # string min/max over any frame: lexicographic ARG-pick via the same
        # doubling RMQ, over the grouped-agg radix-word encoding (the
        # _seg_arglexmin machinery generalized to [lo, hi] range queries —
        # r2 verdict window gap; reference does cudf MIN/MAX string windows)
        from ..ops.aggregate import _string_base_words, _string_value_words

        vwords = _string_value_words(
            _string_base_words(col), valid, isinstance(fn, Min)
        )
        pick = _sparse_argpick_words(vwords, lo, hi, cap)
        pcnt = _segscan(valid.astype(jnp.int64), seg_start, jnp.add)
        hi_c = pcnt[jnp.clip(hi, 0, cap - 1)]
        lo_c = jnp.where(
            lo > seg_first, pcnt[jnp.clip(lo - 1, 0, cap - 1)],
            jnp.zeros_like(pcnt[0]),
        )
        ok = ((hi_c - lo_c) > 0) & nonempty
        safe = jnp.clip(pick, 0, cap - 1)
        data_o = jnp.where(ok[:, None], col.data[safe], 0).astype(jnp.uint8)
        len_o = jnp.where(ok, col.lengths[safe], 0).astype(jnp.int32)
        return DeviceColumn(we.data_type, data_o, ok, len_o)

    if isinstance(fn, (Min, Max)):
        op = jnp.minimum if isinstance(fn, Min) else jnp.maximum
        is_float = jnp.issubdtype(data.dtype, jnp.floating)
        if is_float:
            ident = jnp.array(jnp.inf if isinstance(fn, Min) else -jnp.inf, data.dtype)
            # Spark NaN-greatest: +inf sentinel, restored after the scan.
            # aux flag — max: "frame saw a NaN" (result becomes NaN);
            # min: "frame saw a non-NaN value" (else the min IS NaN) — this
            # distinguishes an all-NaN frame from a genuine +inf minimum.
            aux = (
                (valid & ~jnp.isnan(data))
                if isinstance(fn, Min)
                else (valid & jnp.isnan(data))
            )
            work = jnp.where(valid, jnp.where(jnp.isnan(data), jnp.inf, data), ident)
        else:
            info = jnp.iinfo(data.dtype)
            ident = jnp.array(info.max if isinstance(fn, Min) else info.min, data.dtype)
            aux = jnp.zeros(cap, bool)
            work = jnp.where(valid, data, ident)
        bounded = (
            frame.lower != UNBOUNDED_PRECEDING
            and frame.upper != UNBOUNDED_FOLLOWING
        )
        if bounded:
            out, any_valid, any_aux = _sparse_minmax(
                work, valid, aux, lo, hi, cap, op, ident
            )
        else:
            out, any_valid, any_aux = _scan_window(
                work, valid, aux, frame, seg_start, lo, hi, seg_last, cap, op
            )
        if is_float:
            if isinstance(fn, Max):
                out = jnp.where(any_aux, jnp.nan, out)
            else:
                out = jnp.where(any_valid & ~any_aux, jnp.nan, out)
        return DeviceColumn(we.data_type, out.astype(we.data_type.np_dtype), any_valid & nonempty)

    # sum / count / avg via segmented prefix sums + clamped index gathers
    sum_dt = jnp.float64 if (is_avg or jnp.issubdtype(data.dtype, jnp.floating)) else jnp.int64
    vals = jnp.where(valid, data.astype(sum_dt), jnp.zeros(cap, sum_dt))
    cnts = valid.astype(jnp.int64)
    psum = _segscan(vals, seg_start, jnp.add)
    pcnt = _segscan(cnts, seg_start, jnp.add)

    def window_total(pref):
        hi_v = pref[jnp.clip(hi, 0, cap - 1)]
        lo_prev = jnp.clip(lo - 1, 0, cap - 1)
        lo_v = jnp.where(lo > seg_first, pref[lo_prev], jnp.zeros_like(pref[0]))
        return hi_v - lo_v

    total = window_total(psum)
    count = window_total(pcnt)
    if is_count:
        return DeviceColumn(
            we.data_type,
            jnp.where(nonempty, count, 0).astype(jnp.int64),
            live,  # count is never null
        )
    if is_avg:
        out = total / jnp.maximum(count, 1).astype(jnp.float64)
        return DeviceColumn(we.data_type, out, (count > 0) & nonempty)
    # sum (wrapping long for integrals, double for floats — Sum.update cast)
    out = total.astype(we.data_type.np_dtype)
    return DeviceColumn(we.data_type, out, (count > 0) & nonempty)


def _scan_window(work, valid, had_nan, frame, seg_start, lo, hi, seg_last, cap, op):
    """min/max for frames with at least one unbounded end: gather the
    inclusive prefix scan at ``hi`` (lower unbounded) or the suffix scan at
    ``lo`` (upper unbounded). ``lo``/``hi`` are already segment-clamped; an
    empty frame's garbage gather is masked by the caller's nonempty flag."""
    rev = lambda x: x[::-1]
    end_flags = jnp.concatenate([seg_start[1:], jnp.ones(1, dtype=bool)])
    lower_unb = frame.lower == UNBOUNDED_PRECEDING
    upper_unb = frame.upper == UNBOUNDED_FOLLOWING
    if lower_unb and upper_unb:
        pre = _segscan(work, seg_start, op)
        pre_valid = _segscan(valid.astype(jnp.int32), seg_start, jnp.add) > 0
        pre_nan = _segscan(had_nan.astype(jnp.int32), seg_start, jnp.add) > 0
        last = jnp.clip(seg_last, 0, cap - 1)
        return pre[last], pre_valid[last], pre_nan[last]
    if lower_unb:
        pre = _segscan(work, seg_start, op)
        pre_valid = _segscan(valid.astype(jnp.int32), seg_start, jnp.add) > 0
        pre_nan = _segscan(had_nan.astype(jnp.int32), seg_start, jnp.add) > 0
        end = jnp.clip(hi, 0, cap - 1)
        return pre[end], pre_valid[end], pre_nan[end]
    # upper unbounded
    suf = rev(_segscan(rev(work), rev(end_flags), op))
    suf_valid = rev(_segscan(rev(valid.astype(jnp.int32)), rev(end_flags), jnp.add)) > 0
    suf_nan = rev(_segscan(rev(had_nan.astype(jnp.int32)), rev(end_flags), jnp.add)) > 0
    start = jnp.clip(lo, 0, cap - 1)
    return suf[start], suf_valid[start], suf_nan[start]


def _sparse_minmax(work, valid, aux, lo, hi, cap, op, ident):
    """Bounded min/max via a sparse-table range query (doubling RMQ):
    O(cap·log cap) build, two gathers per row — replaces the per-width
    frame unroll whose giant programs broke XLA tooling and capped the
    frame width (reference: aggregateWindows bounded frames; r1 verdict
    weak #8). Works for ANY [lo, hi] row bounds, so ROWS and numeric RANGE
    frames share it."""
    levels = max(1, int(cap).bit_length())
    T, V, A = [work], [valid], [aux]
    for k in range(1, levels):
        s = 1 << (k - 1)

        def sh(arr, fill):
            pad = jnp.full((s,), fill, dtype=arr.dtype)
            return jnp.concatenate([arr[s:], pad])

        T.append(op(T[-1], sh(T[-1], ident)))
        V.append(V[-1] | sh(V[-1], False))
        A.append(A[-1] | sh(A[-1], False))
    Ts, Vs, As = jnp.stack(T), jnp.stack(V), jnp.stack(A)
    L = jnp.maximum(hi - lo + 1, 1)
    m = jnp.zeros(lo.shape, jnp.int32)
    for k in range(1, levels):
        m = jnp.where(L >= (1 << k), k, m)
    pw = jnp.left_shift(jnp.int32(1), m)
    lo_c = jnp.clip(lo, 0, cap - 1)
    j2 = jnp.clip(hi - pw + 1, 0, cap - 1)
    out = op(Ts[m, lo_c], Ts[m, j2])
    return out, Vs[m, lo_c] | Vs[m, j2], As[m, lo_c] | As[m, j2]


def _sparse_argpick_words(words, lo, hi, cap):
    """Doubling RMQ over ROW INDICES with lexicographic word compare: the
    index of the lex-smallest word tuple in [lo, hi] (ties keep the earlier
    row). Serves string min AND max — the caller inverts the value words
    for max (_string_value_words)."""
    idx0 = jnp.arange(cap, dtype=jnp.int32)

    def lex_le(ia, ib):
        lt = jnp.zeros(ia.shape, dtype=bool)
        eq = jnp.ones(ia.shape, dtype=bool)
        for w in words:
            wa, wb = w[ia], w[ib]
            lt = lt | (eq & (wa < wb))
            eq = eq & (wa == wb)
        return lt | eq

    levels = max(1, int(cap).bit_length())
    T = [idx0]
    for k in range(1, levels):
        s = 1 << (k - 1)
        prev = T[-1]
        # tail cells fall back to their own (in-range) index
        shifted = jnp.concatenate([prev[s:], idx0[cap - s:]])
        T.append(jnp.where(lex_le(prev, shifted), prev, shifted))
    Ts = jnp.stack(T)
    L = jnp.maximum(hi - lo + 1, 1)
    m = jnp.zeros(lo.shape, jnp.int32)
    for k in range(1, levels):
        m = jnp.where(L >= (1 << k), k, m)
    pw = jnp.left_shift(jnp.int32(1), m)
    p1 = Ts[m, jnp.clip(lo, 0, cap - 1)]
    p2 = Ts[m, jnp.clip(hi - pw + 1, 0, cap - 1)]
    return jnp.where(lex_le(p1, p2), p1, p2)


def _bsearch_first(sval, lo_b, hi_b, target, cap, strict: bool):
    """Vectorized per-row binary search: first j in [lo_b, hi_b] with
    sval[j] >= target (or > when ``strict``), else hi_b + 1 (sval ascending
    within the segment)."""
    l = lo_b.astype(jnp.int32)
    r = hi_b.astype(jnp.int32) + 1
    for _ in range(int(cap).bit_length() + 1):
        m = (l + r) // 2
        mc = jnp.clip(m, 0, cap - 1)
        hit = (sval[mc] > target) if strict else (sval[mc] >= target)
        go_left = hit & (l < r)
        r = jnp.where(go_left, m, r)
        l = jnp.where(go_left | (l >= r), l, m + 1)
    return l


def _range_frame_bounds(
    frame, sval, ovalid, seg_first, seg_last, peer_first, peer_last, cap
):
    """Row bounds of a numeric RANGE frame: value-space binary searches
    within the segment (cudf aggregateWindowsOverRanges analogue). NULL
    order rows take their peer group as the frame (Spark: nulls are peers,
    incomparable to numeric offsets)."""
    lo_delta = 0 if frame.lower == CURRENT_ROW else frame.lower
    hi_delta = 0 if frame.upper == CURRENT_ROW else frame.upper
    v = sval
    if frame.lower == UNBOUNDED_PRECEDING:
        lo = seg_first
    else:
        lo = _bsearch_first(
            sval, seg_first, seg_last, v + lo_delta, cap, strict=False
        )
        lo = jnp.where(ovalid, lo, peer_first)
    if frame.upper == UNBOUNDED_FOLLOWING:
        hi = seg_last
    else:
        # last j with sval[j] <= target  ⇔  (first j with sval[j] > target) - 1
        first_gt = _bsearch_first(
            sval, seg_first, seg_last, v + hi_delta, cap, strict=True
        )
        hi = first_gt - 1
        hi = jnp.where(ovalid, hi, peer_last)
    return lo, hi


def _agg_input(fn) -> Expression:
    if isinstance(fn, Sum):
        return fn.update_exprs[0]
    if isinstance(fn, (Count, Min, Max, Average)):
        return fn.child
    raise NotImplementedError(f"window aggregate {type(fn).__name__}")
