"""CPU physical operators — the fallback engine.

In the reference, fallback means "leave the original Spark CPU exec in
place" (RapidsMeta.willNotWorkOnGpu). Standalone, this module IS that CPU
engine: numpy/arrow operators with Spark-exact semantics. It doubles as the
differential-test oracle, the role SparkQueryCompareTestSuite's CPU session
plays in the reference (tests/.../SparkQueryCompareTestSuite.scala:339).
"""
from __future__ import annotations

import itertools
from typing import Iterator, List, Optional

import numpy as np
import pyarrow as pa

from ..columnar.host import arrow_from_np, batch_from_columns, concat_batches, np_from_arrow
from ..expr import Expression, bind, output_name
from ..expr.aggregates import AggregateFunction
from ..expr.base import BoundReference, Ctx
from ..expr.misc import contains_task_dependent
from . import task
from ..ops.hash import murmur3_rows, partition_ids
from ..plan.logical import SortOrder
from ..plan.physical import Exec, ExecContext, PartitionSet
from ..types import BOOLEAN, DataType, NullType, Schema, StringType, StructField
from . import cpu_kernels as ck


def _cpu_ctx(rb: pa.RecordBatch, schema: Schema) -> Ctx:
    cols = [
        np_from_arrow(rb.column(i), f.data_type) for i, f in enumerate(schema)
    ]
    return Ctx.for_cpu(cols, rb.num_rows)


def _val_to_np(ctx: Ctx, val) -> tuple[np.ndarray, np.ndarray]:
    data = val.data
    if not isinstance(data, np.ndarray) or data.ndim == 0:
        data = np.broadcast_to(np.asarray(data), (ctx.n,)).copy()
    valid = val.valid
    if not isinstance(valid, np.ndarray) or np.ndim(valid) == 0:
        valid = np.broadcast_to(np.asarray(valid, dtype=bool), (ctx.n,)).copy()
    return data, valid.astype(bool)


class CpuScanExec(Exec):
    """In-memory arrow table scan (LocalRelation)."""

    def __init__(
        self,
        table: pa.Table,
        schema: Schema,
        num_partitions: int = 1,
        source: pa.Table = None,
    ):
        super().__init__([])
        self.table = table
        self._schema = schema
        self.num_partitions = num_partitions
        # identity anchor for the device-upload cache (see LocalRelation)
        self.source = source if source is not None else table

    @property
    def output(self) -> Schema:
        return self._schema

    def execute(self, ctx: ExecContext) -> PartitionSet:
        n = self.table.num_rows
        parts = []
        per = max(1, -(-n // self.num_partitions))
        for p in range(self.num_partitions):
            lo = min(p * per, n)
            hi = min(lo + per, n)

            def make(lo=lo, hi=hi):
                def it():
                    if hi > lo:
                        for rb in self.table.slice(lo, hi - lo).combine_chunks().to_batches():
                            yield rb
                return it()

            parts.append(make)
        return PartitionSet(parts)

    def node_string(self):
        return f"CpuScan{self._schema.names}"


class CpuRangeExec(Exec):
    """``spark.range()`` — sequence generation (Spark's RangeExec; the
    reference replaces it with GpuRangeExec, basicPhysicalOperators.scala).
    Spark splits the range into ``num_partitions`` contiguous slices."""

    def __init__(self, start: int, end: int, step: int, num_partitions: int):
        super().__init__([])
        self.start = start
        self.end = end
        self.step = step
        self.num_partitions = max(1, num_partitions)
        from ..types import LONG, StructField as SF

        self._schema = Schema([SF("id", LONG, False)])

    @property
    def output(self) -> Schema:
        return self._schema

    def total_rows(self) -> int:
        if self.step == 0:
            raise ValueError("range step cannot be 0")
        n = (self.end - self.start + self.step - (1 if self.step > 0 else -1)) // self.step
        return max(0, n)

    def partition_bounds(self) -> list[tuple[int, int]]:
        """[(first_row_index, row_count)] per partition — contiguous slices."""
        n = self.total_rows()
        per = -(-n // self.num_partitions) if n else 0
        out = []
        for p in range(self.num_partitions):
            lo = min(p * per, n)
            hi = min(lo + per, n)
            out.append((lo, hi - lo))
        return out

    def execute(self, ctx: ExecContext) -> PartitionSet:
        from .. import config as cfg

        batch_rows = cfg.BATCH_SIZE_ROWS.get(ctx.conf)
        start, step = self.start, self.step
        parts = []
        for lo, cnt in self.partition_bounds():
            def make(lo=lo, cnt=cnt):
                def it():
                    done = 0
                    while done < cnt:
                        m = min(batch_rows, cnt - done)
                        first = start + (lo + done) * step
                        ids = first + step * np.arange(m, dtype=np.int64)
                        yield pa.RecordBatch.from_arrays(
                            [pa.array(ids, type=pa.int64())], names=["id"]
                        )
                        done += m

                return it()

            parts.append(make)
        return PartitionSet(parts)

    def node_string(self):
        return f"CpuRange ({self.start}, {self.end}, step={self.step}, splits={self.num_partitions})"


class CpuProjectExec(Exec):
    def __init__(self, exprs: List[Expression], child: Exec):
        super().__init__([child])
        self.exprs = [bind(e, child.output) for e in exprs]
        self._schema = Schema(
            [
                StructField(output_name(e0), e.data_type, e.nullable)
                for e0, e in zip(exprs, self.exprs)
            ]
        )

    @property
    def output(self) -> Schema:
        return self._schema

    def execute(self, ctx: ExecContext) -> PartitionSet:
        child = self.children[0]
        schema_in = child.output
        schema_out = self._schema

        needs_task = any(contains_task_dependent(e) for e in self.exprs)

        def fn(it: Iterator[pa.RecordBatch]):
            for rb in it:
                c = _cpu_ctx(rb, schema_in)
                if needs_task:
                    info = task.get_or_create()
                    c.task = task.task_vals(np)
                cols = [_val_to_np(c, e.eval(c)) for e in self.exprs]
                if needs_task:
                    info.advance_rows(rb.num_rows)
                yield batch_from_columns(schema_out, cols)

        return child.execute(ctx).map_partitions(fn)

    def node_string(self):
        return f"CpuProject [{', '.join(map(str, self.exprs))}]"


class CpuFilterExec(Exec):
    def __init__(self, condition: Expression, child: Exec):
        super().__init__([child])
        self.condition = bind(condition, child.output)

    @property
    def output(self) -> Schema:
        return self.children[0].output

    def execute(self, ctx: ExecContext) -> PartitionSet:
        schema_in = self.children[0].output

        needs_task = contains_task_dependent(self.condition)

        def fn(it):
            for rb in it:
                c = _cpu_ctx(rb, schema_in)
                if needs_task:
                    info = task.get_or_create()
                    c.task = task.task_vals(np)
                v = self.condition.eval(c)
                data, valid = _val_to_np(c, v)
                keep = data.astype(bool) & valid
                if needs_task:
                    info.advance_rows(rb.num_rows)
                yield rb.filter(pa.array(keep))

        return self.children[0].execute(ctx).map_partitions(fn)

    def node_string(self):
        return f"CpuFilter {self.condition}"


class CpuGenerateExec(Exec):
    """explode/posexplode over arrays and maps (Spark GenerateExec; the
    reference replaces it with GpuGenerateExec.scala). Each input row fans
    out to one output row per element; null/empty collections yield no
    rows (non-outer semantics)."""

    def __init__(self, generator: Expression, out_names: List[str], child: Exec):
        super().__init__([child])
        from ..expr.complex import Explode

        self.generator: Explode = bind(generator, child.output)
        self.out_names = list(out_names)
        self._schema = self._compute_schema(child)

    def _compute_schema(self, child: Exec) -> Schema:
        from ..types import INT, MapType

        g = self.generator
        ct = g.child.data_type
        fields = list(child.output.fields)
        i = 0
        if g.position:
            fields.append(StructField(self.out_names[i], INT, False))
            i += 1
        if isinstance(ct, MapType):
            fields.append(StructField(self.out_names[i], ct.key_type, False))
            fields.append(StructField(self.out_names[i + 1], ct.value_type, True))
        else:
            fields.append(StructField(self.out_names[i], ct.element_type, True))
        return Schema(fields)

    @property
    def output(self) -> Schema:
        return self._schema

    def execute(self, ctx: ExecContext) -> PartitionSet:
        from ..types import MapType

        schema_in = self.children[0].output
        schema_out = self._schema
        g = self.generator
        is_map = isinstance(g.child.data_type, MapType)

        def fn(it):
            for rb in it:
                c = _cpu_ctx(rb, schema_in)
                v = g.child.eval(c)
                data = c.broadcast(v.data)
                valid = c.broadcast_bool(v.valid)
                take: List[int] = []
                pos: List[int] = []
                elems: List = []
                for i in range(rb.num_rows):
                    if not valid[i] or data[i] is None:
                        continue
                    for j, el in enumerate(data[i]):
                        take.append(i)
                        pos.append(j)
                        elems.append(el)
                base = rb.take(pa.array(take, type=pa.int64()))
                arrays = list(base.columns)
                if g.position:
                    arrays.append(pa.array(pos, type=pa.int32()))
                if is_map:
                    arrays.append(
                        pa.array([k for k, _ in elems],
                                 type=g.child.data_type.key_type.to_arrow())
                    )
                    arrays.append(
                        pa.array([x for _, x in elems],
                                 type=g.child.data_type.value_type.to_arrow())
                    )
                else:
                    arrays.append(
                        pa.array(elems, type=g.child.data_type.element_type.to_arrow())
                    )
                yield pa.RecordBatch.from_arrays(arrays, schema=schema_out.to_arrow())

        return self.children[0].execute(ctx).map_partitions(fn)

    def node_string(self):
        return f"CpuGenerate {self.generator}"


class CpuUnionExec(Exec):
    def __init__(self, children: List[Exec]):
        super().__init__(children)

    @property
    def output(self) -> Schema:
        return self.children[0].output

    def execute(self, ctx: ExecContext) -> PartitionSet:
        parts = []
        for c in self.children:
            parts.extend(c.execute(ctx).parts)
        return PartitionSet(parts)


class CpuCoalescePartitionsExec(Exec):
    """Merge all partitions into one (used before single-partition ops)."""

    def __init__(self, child: Exec):
        super().__init__([child])

    @property
    def output(self) -> Schema:
        return self.children[0].output

    def execute(self, ctx: ExecContext) -> PartitionSet:
        child_parts = self.children[0].execute(ctx)

        def it():
            for t in child_parts.parts:
                yield from t()

        return PartitionSet([it])


class CpuShuffleExchangeExec(Exec):
    """Partitioned exchange (CPU path) over the four partitionings:
    hash (murmur3 pmod n), range (sampled radix-word bounds), round-robin,
    single — GpuShuffleExchangeExec + the GpuPartitioning impls (§1 L6)."""

    def __init__(self, partitioning, child: Exec):
        super().__init__([child])
        self.partitioning = _bind_partitioning(partitioning, child.output)

    @property
    def num_partitions(self) -> int:
        return self.partitioning.num_partitions

    @property
    def output(self) -> Schema:
        return self.children[0].output

    def _np_word_groups(self, rb: pa.RecordBatch, schema: Schema):
        from ..ops.sortkeys import np_column_radix_words

        c = _cpu_ctx(rb, schema)
        groups = []
        for o in self.partitioning.order:
            d, v = _val_to_np(c, o.child.eval(c))
            groups.append(
                np_column_radix_words(
                    o.child.data_type, d, v, None, o.ascending, o.resolved_nulls_first()
                )
            )
        return groups

    def execute(self, ctx: ExecContext) -> PartitionSet:
        from ..plan.partitioning import (
            SAMPLE_PER_BATCH,
            HashPartitioning,
            RangePartitioning,
            RoundRobinPartitioning,
            compute_range_bounds,
            words_partition_ids,
        )

        schema = self.children[0].output
        inputs = self.children[0].execute(ctx)
        nparts = self.num_partitions
        part = self.partitioning
        buckets: list[list[pa.RecordBatch]] = [[] for _ in range(nparts)]

        def scatter(rb, pids):
            for p in range(nparts):
                mask = pids == p
                if mask.any():
                    buckets[p].append(rb.filter(pa.array(mask)))

        if isinstance(part, RangePartitioning):
            from ..plan.partitioning import align_word_groups

            batches, group_lists = [], []
            for thunk in inputs.parts:
                for rb in thunk():
                    if rb.num_rows == 0:
                        continue
                    batches.append(rb)
                    group_lists.append(self._np_word_groups(rb, schema))
            # align per-batch string word counts (see align_word_groups)
            all_words, _targets = align_word_groups(group_lists, part.order, np)
            samples = []
            for rb, words in zip(batches, all_words):
                idx = np.arange(0, rb.num_rows, max(1, rb.num_rows // SAMPLE_PER_BATCH))
                samples.append([w[idx] for w in words])
            bounds = None
            if samples:
                sample_words = [
                    np.concatenate([s[i] for s in samples]) for i in range(len(samples[0]))
                ]
                bounds = compute_range_bounds(sample_words, nparts)
            for rb, words in zip(batches, all_words):
                if bounds is None:
                    buckets[0].append(rb)
                else:
                    scatter(rb, words_partition_ids(np, words, bounds))
        else:
            for pi, thunk in enumerate(inputs.parts):
                offset = 0
                for rb in thunk():
                    if rb.num_rows == 0:
                        continue
                    if isinstance(part, HashPartitioning) and part.keys:
                        c = _cpu_ctx(rb, schema)
                        cols = []
                        for k in part.keys:
                            d, val = _val_to_np(c, k.eval(c))
                            cols.append((k.data_type, d, val, None))
                        h = murmur3_rows(np, cols, rb.num_rows)
                        scatter(rb, partition_ids(np, h, nparts))
                    elif isinstance(part, RoundRobinPartitioning):
                        # deterministic start per input partition (the
                        # reference seeds with the partition index)
                        pids = (pi + offset + np.arange(rb.num_rows)) % nparts
                        offset += rb.num_rows
                        scatter(rb, pids)
                    else:  # single partition
                        buckets[0].append(rb)

        def make(p):
            def it():
                yield from buckets[p]
            return it
        return PartitionSet([make(p) for p in range(nparts)])

    def node_string(self):
        return f"CpuShuffleExchange {self.partitioning} p={self.num_partitions}"


def _bind_partitioning(part, schema: Schema):
    """Bind a partitioning's expressions against the child schema."""
    import dataclasses as _dc

    from ..plan import partitioning as P

    if isinstance(part, P.HashPartitioning):
        return _dc.replace(part, keys=[bind(k, schema) for k in part.keys])
    if isinstance(part, P.RangePartitioning):
        return _dc.replace(
            part,
            order=[
                SortOrder(bind(o.child, schema), o.ascending, o.nulls_first)
                for o in part.order
            ],
        )
    return part


class CpuHashAggregateExec(Exec):
    """Group-by aggregate, one phase (mode: 'partial' | 'final' | 'complete').

    Mirrors the reference's update/merge split (aggregate.scala:345-520):
    partial consumes input rows producing (keys ++ buffers); final consumes
    buffers producing results.
    """

    def __init__(
        self,
        mode: str,
        grouping: List[Expression],
        agg_fns: List[AggregateFunction],
        result_exprs: Optional[List[Expression]],
        result_names: Optional[List[str]],
        child: Exec,
    ):
        super().__init__([child])
        self.mode = mode
        self.grouping = [bind(g, child.output) for g in grouping]
        self.agg_fns = agg_fns  # bound against the ORIGINAL input schema
        self.result_exprs = result_exprs
        self.result_names = result_names
        self._schema = self._compute_schema(child)

    def _compute_schema(self, child: Exec) -> Schema:
        fields = []
        for g0, g in zip(self.grouping, self.grouping):
            fields.append(StructField(output_name(g0), g.data_type, g.nullable))
        if self.mode == "partial":
            for i, f in enumerate(self.agg_fns):
                for j, bt in enumerate(f.buffer_types):
                    fields.append(StructField(f"buf{i}_{j}", bt, True))
            return Schema(fields)
        # final/complete: results after evaluate + result projection
        assert self.result_exprs is not None
        out = []
        for name, e in zip(self.result_names, self.result_exprs):
            out.append(StructField(name, e.data_type, e.nullable))
        return Schema(out)

    @property
    def output(self) -> Schema:
        return self._schema

    def execute(self, ctx: ExecContext) -> PartitionSet:
        child = self.children[0]
        schema_in = child.output

        def fn(it):
            batches = list(it)
            rb = concat_batches(schema_in, batches)
            yield self._aggregate(rb, schema_in)

        return child.execute(ctx).map_partitions(fn)

    # ── core ────────────────────────────────────────────────────────────
    def _aggregate(self, rb: pa.RecordBatch, schema_in: Schema) -> pa.RecordBatch:
        c = _cpu_ctx(rb, schema_in)
        n = rb.num_rows
        key_np = [_val_to_np(c, g.eval(c)) for g in self.grouping]
        encoded = []
        for (d, v), g in zip(key_np, self.grouping):
            encoded.extend(ck.encode_group_key(g.data_type, d, v))
        inv, first_idx = ck.group_inverse(encoded, n)
        if self.grouping:
            num_groups = len(first_idx)
        else:
            num_groups = 1
            inv = np.zeros(n, dtype=np.int64)
        # reduction with no rows: one group with empty-input semantics
        out_cols: list[tuple[np.ndarray, np.ndarray]] = []
        for (d, v) in key_np:
            out_cols.append((d[first_idx], v[first_idx]))
        buffer_vals = []
        for f in self.agg_fns:
            if self.mode in ("partial", "complete"):
                ins = [bind(e, schema_in) for e in f.update_exprs]
                ops = f.update_ops
            else:
                ins = None
                ops = f.merge_ops
            bufs = []
            for j, op in enumerate(ops):
                if ins is not None:
                    d, v = _val_to_np(c, ins[j].eval(c))
                    dt = ins[j].data_type
                else:
                    ord_ = self._buffer_ordinal(f, j)
                    d, v = _val_to_np(c, c.columns[ord_])
                    dt = schema_in[ord_].data_type
                gd, gv = ck.reduce_groups(op, dt, d, v, inv, num_groups)
                bufs.append((gd, gv, dt))
            buffer_vals.append(bufs)
        if self.mode == "partial":
            for bufs in buffer_vals:
                for gd, gv, dt in bufs:
                    out_cols.append((gd, gv))
            return batch_from_columns(self._schema, out_cols)
        # final/complete: evaluate agg fns then result projection
        from ..expr.base import Val

        gctx = Ctx.for_cpu([(d, v) for d, v in out_cols], num_groups)
        agg_results: list[Val] = []
        for f, bufs in zip(self.agg_fns, buffer_vals):
            vals = [Val(gd, gv) for gd, gv, _ in bufs]
            agg_results.append(f.evaluate(gctx, vals))
        res_ctx_cols = [Val(d, v) for d, v in out_cols[: len(self.grouping)]]
        res_ctx_cols.extend(agg_results)
        rctx = Ctx.for_cpu([], num_groups)
        rctx.columns = res_ctx_cols
        final = []
        for e in self.result_exprs:
            final.append(_val_to_np(rctx, e.eval(rctx)))
        return batch_from_columns(self._schema, final)

    def _buffer_ordinal(self, f: AggregateFunction, j: int) -> int:
        base = len(self.grouping)
        for g in self.agg_fns:
            if g is f:
                return base + j
            base += len(g.buffer_types)
        raise KeyError

    def node_string(self):
        return f"CpuHashAggregate({self.mode}) keys={[str(g) for g in self.grouping]} aggs={[str(a) for a in self.agg_fns]}"


def cpu_sort_indices(rb: pa.RecordBatch, schema: Schema, order: List[SortOrder]) -> np.ndarray:
    """Stable permutation realizing Spark's sort order over one batch."""
    c = _cpu_ctx(rb, schema)
    n = rb.num_rows
    # build numpy sort keys, last key first (lexsort semantics)
    keys = []
    for o in order:
        d, v = _val_to_np(c, o.child.eval(c))
        dt = o.child.data_type
        from ..types import FloatType, DoubleType, StringType

        if isinstance(dt, StringType):
            enc = np.array(
                [x.encode() if (x is not None and vv) else b"" for x, vv in zip(d, v)],
                dtype=object,
            )
            val_key = enc
        elif isinstance(dt, (FloatType, DoubleType)):
            # signed-int64 total order: NaN (canonical, positive bits)
            # lands above +inf, matching Spark's NaN-greatest ordering
            bits = ck.normalized_float_bits(d)
            val_key = np.where(bits < 0, ~bits ^ np.int64(-(2**63)), bits)
        else:
            val_key = d.astype(np.int64)
        if not o.ascending and val_key.dtype == object:
            # lexsort can't negate bytes; use DENSE ranks so equal
            # values share a rank (keeps ties stable under negation)
            order_idx = np.argsort(val_key, kind="stable")
            sv = val_key[order_idx]
            new_grp = np.ones(n, dtype=np.int64)
            new_grp[1:] = (sv[1:] != sv[:-1]).astype(np.int64)
            dense = np.cumsum(new_grp) - 1
            rank = np.empty(n, dtype=np.int64)
            rank[order_idx] = dense
            val_key = -rank
        elif not o.ascending:
            val_key = -1 - val_key  # avoid -MIN overflow? two's complement ok
        nf = o.resolved_nulls_first()
        null_key = np.where(v, 1, 0) if nf else np.where(v, 0, 1)
        # null flag is MORE significant than the value within a column
        keys.append(null_key)
        keys.append(val_key)
    return np.lexsort(keys[::-1])


class CpuSortExec(Exec):
    def __init__(self, order: List[SortOrder], child: Exec):
        super().__init__([child])
        self.order = [
            SortOrder(bind(o.child, child.output), o.ascending, o.nulls_first)
            for o in order
        ]

    @property
    def output(self) -> Schema:
        return self.children[0].output

    def execute(self, ctx: ExecContext) -> PartitionSet:
        schema = self.children[0].output

        def fn(it):
            rb = concat_batches(schema, list(it))
            if rb.num_rows == 0:
                yield rb
                return
            perm = cpu_sort_indices(rb, schema, self.order)
            yield rb.take(pa.array(perm))

        return self.children[0].execute(ctx).map_partitions(fn)


class CpuTakeOrderedAndProjectExec(Exec):
    """TopN: per-partition sort + slice(n), then merged final sort + slice(n)
    — the reference's GpuTakeOrderedAndProjectExec pattern (limit.scala)."""

    def __init__(self, n: int, order: List[SortOrder], child: Exec):
        super().__init__([child])
        self.n = n
        self.order = [
            SortOrder(bind(o.child, child.output), o.ascending, o.nulls_first)
            for o in order
        ]

    @property
    def output(self) -> Schema:
        return self.children[0].output

    def execute(self, ctx: ExecContext) -> PartitionSet:
        schema = self.children[0].output
        n = self.n

        def topn(it):
            rb = concat_batches(schema, list(it))
            if rb.num_rows == 0:
                return []
            perm = cpu_sort_indices(rb, schema, self.order)[:n]
            return [rb.take(pa.array(perm))]

        child_parts = self.children[0].execute(ctx)

        def it():
            partials: list[pa.RecordBatch] = []
            for t in child_parts.parts:
                partials.extend(topn(t()))
            yield from topn(iter(partials))

        return PartitionSet([it])

    def node_string(self):
        return f"CpuTakeOrderedAndProject n={self.n} [{', '.join(map(str, self.order))}]"


class CpuExpandExec(Exec):
    """Projection-list fan-out: each input row produces one output row per
    projection (reference: GpuExpandExec.scala) — the engine under
    rollup/cube/grouping sets."""

    def __init__(self, projections: List[List[Expression]], names: List[str], child: Exec):
        super().__init__([child])
        self.projections = [
            [bind(e, child.output) for e in proj] for proj in projections
        ]
        fields = []
        for i, name in enumerate(names):
            es = [proj[i] for proj in self.projections]
            dt = next(
                (e.data_type for e in es if not isinstance(e.data_type, NullType)),
                es[0].data_type,
            )
            fields.append(StructField(name, dt, any(e.nullable for e in es)))
        self._schema = Schema(fields)

    @property
    def output(self) -> Schema:
        return self._schema

    def execute(self, ctx: ExecContext) -> PartitionSet:
        schema_in = self.children[0].output
        schema_out = self._schema

        def fn(it):
            for rb in it:
                c = _cpu_ctx(rb, schema_in)
                for proj in self.projections:
                    cols = []
                    for e, f in zip(proj, schema_out):
                        d, v = _val_to_np(c, e.eval(c))
                        if not isinstance(f.data_type, StringType) and d.dtype != f.data_type.np_dtype:
                            d = d.astype(f.data_type.np_dtype)
                        cols.append((d, v))
                    yield batch_from_columns(schema_out, cols)

        return self.children[0].execute(ctx).map_partitions(fn)

    def node_string(self):
        return f"CpuExpand x{len(self.projections)}"


class CpuLimitExec(Exec):
    """CollectLimit: single partition, first n rows."""

    def __init__(self, n: int, child: Exec):
        super().__init__([child])
        self.n = n

    @property
    def output(self) -> Schema:
        return self.children[0].output

    def execute(self, ctx: ExecContext) -> PartitionSet:
        child_parts = self.children[0].execute(ctx)

        def it():
            remaining = self.n
            for t in child_parts.parts:
                for rb in t():
                    if remaining <= 0:
                        return
                    if rb.num_rows > remaining:
                        rb = rb.slice(0, remaining)
                    remaining -= rb.num_rows
                    yield rb

        return PartitionSet([it])
