"""Minimal ORC footer/metadata reader for stripe-granularity pruning.

pyarrow exposes per-stripe READS (``ORCFile.read_stripe``) but not the
stripe statistics, so this module parses the two protobuf sections the
pruning pass needs straight from the file tail — postscript → Footer
(stripe list + flat field names) and Metadata (per-stripe column
statistics). Reference: GpuOrcScan.scala:853 (stripe gating) +
OrcFilters.scala (predicate → stats SearchArgument); the ORC layout is the
public spec (orc_proto: PostScript/Footer/Metadata/ColumnStatistics).

Only what pruning needs is decoded: integer/double/string/date/decimal
min/max + hasNull, flat (non-nested) schemas, NONE/ZLIB/ZSTD compression.
Anything unexpected → ``None`` → the caller reads every stripe (pruning is
an optimization, never a correctness dependency).
"""
from __future__ import annotations

import struct
from typing import List, Optional

# ── protobuf wire decoding (just varint/len-delimited/fixed64) ─────────────


def _varint(buf: bytes, pos: int):
    out = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) — value is int for varint/
    fixed, bytes for length-delimited."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _varint(buf, pos)
        fno, wt = key >> 3, key & 7
        if wt == 0:
            v, pos = _varint(buf, pos)
        elif wt == 1:
            v = struct.unpack_from("<Q", buf, pos)[0]
            pos += 8
        elif wt == 2:
            ln, pos = _varint(buf, pos)
            v = buf[pos : pos + ln]
            pos += ln
        elif wt == 5:
            v = struct.unpack_from("<I", buf, pos)[0]
            pos += 4
        else:
            raise ValueError(f"wire type {wt}")
        yield fno, wt, v


# ── ORC section decompression ──────────────────────────────────────────────

_NONE, _ZLIB, _SNAPPY, _LZO, _LZ4, _ZSTD = range(6)


def _decompress(raw: bytes, codec: int) -> Optional[bytes]:
    if codec == _NONE:
        return raw
    out = []
    pos = 0
    while pos + 3 <= len(raw):
        hdr = raw[pos] | (raw[pos + 1] << 8) | (raw[pos + 2] << 16)
        pos += 3
        ln = hdr >> 1
        chunk = raw[pos : pos + ln]
        pos += ln
        if hdr & 1:  # original (stored) block
            out.append(chunk)
        elif codec == _ZLIB:
            import zlib

            out.append(zlib.decompress(chunk, wbits=-15))
        elif codec == _ZSTD:
            try:
                import zstandard

                out.append(zstandard.ZstdDecompressor().decompress(chunk))
            except Exception:
                return None
        else:
            return None
    return b"".join(out)


# ── sections ───────────────────────────────────────────────────────────────


class OrcStripeStats:
    """names: flat field names (schema column i ↔ stats column i+1);
    stripes: list of per-stripe dicts col_index → (kind, min, max,
    has_null)."""

    def __init__(self, names: List[str], stripes: List[dict]):
        self.names = names
        self.stripes = stripes


def _parse_column_stats(buf: bytes):
    kind = None
    mn = mx = None
    has_null = False
    for fno, wt, v in _fields(buf):
        if fno == 10 and wt == 0:
            has_null = bool(v)
        elif fno == 2 and wt == 2:  # IntegerStatistics
            kind = "int"
            for f2, w2, v2 in _fields(v):
                if f2 == 1:
                    mn = _zigzag(v2)
                elif f2 == 2:
                    mx = _zigzag(v2)
        elif fno == 3 and wt == 2:  # DoubleStatistics
            kind = "double"
            for f2, w2, v2 in _fields(v):
                if f2 == 1:
                    mn = struct.unpack("<d", struct.pack("<Q", v2))[0]
                elif f2 == 2:
                    mx = struct.unpack("<d", struct.pack("<Q", v2))[0]
        elif fno == 4 and wt == 2:  # StringStatistics
            kind = "string"
            for f2, w2, v2 in _fields(v):
                if f2 == 1:
                    mn = v2.decode("utf-8", "replace")
                elif f2 == 2:
                    mx = v2.decode("utf-8", "replace")
        elif fno == 6 and wt == 2:  # DecimalStatistics (string form)
            kind = "decimal"
            for f2, w2, v2 in _fields(v):
                if f2 == 1:
                    mn = v2.decode()
                elif f2 == 2:
                    mx = v2.decode()
        elif fno == 7 and wt == 2:  # DateStatistics (days, sint32)
            kind = "date"
            for f2, w2, v2 in _fields(v):
                if f2 == 1:
                    mn = _zigzag(v2)
                elif f2 == 2:
                    mx = _zigzag(v2)
    return kind, mn, mx, has_null


def read_stripe_stats(path: str) -> Optional[OrcStripeStats]:
    """Parse [metadata][footer][postscript][len] from the file tail; None
    when anything is unsupported (nested schema, exotic codec, parse
    error)."""
    try:
        with open(path, "rb") as fh:
            fh.seek(0, 2)
            size = fh.tell()
            tail_len = min(size, 16 * 1024 * 1024)
            fh.seek(size - tail_len)
            tail = fh.read(tail_len)
        ps_len = tail[-1]
        ps = tail[-1 - ps_len : -1]
        footer_len = meta_len = 0
        codec = _NONE
        for fno, wt, v in _fields(ps):
            if fno == 1:
                footer_len = v
            elif fno == 2:
                codec = v
            elif fno == 5:
                meta_len = v
        foot_raw = tail[-1 - ps_len - footer_len : -1 - ps_len]
        meta_raw = tail[
            -1 - ps_len - footer_len - meta_len : -1 - ps_len - footer_len
        ]
        footer = _decompress(foot_raw, codec)
        metadata = _decompress(meta_raw, codec)
        if footer is None or metadata is None:
            return None

        # Footer: field 4 = repeated Type (root first), field 3 = stripes
        names: List[str] = []
        types_seen = 0
        n_stripes = 0
        for fno, wt, v in _fields(footer):
            if fno == 4 and wt == 2:
                types_seen += 1
                if types_seen == 1:  # root struct: fieldNames live here
                    kind = None
                    for f2, w2, v2 in _fields(v):
                        if f2 == 1:
                            kind = v2
                        elif f2 == 3:
                            names.append(v2.decode())
                    if kind != 12:  # STRUCT
                        return None
                else:
                    # nested children would shift column ids; only flat
                    # schemas (root's children are leaves) are supported
                    for f2, w2, v2 in _fields(v):
                        if f2 == 2:
                            return None
            elif fno == 3 and wt == 2:
                n_stripes += 1
        if not names:
            return None

        stripes: List[dict] = []
        for fno, wt, v in _fields(metadata):
            if fno == 1 and wt == 2:  # StripeStatistics
                cols: dict = {}
                ci = 0
                for f2, w2, v2 in _fields(v):
                    if f2 == 1 and w2 == 2:  # repeated ColumnStatistics
                        cols[ci] = _parse_column_stats(v2)
                        ci += 1
                stripes.append(cols)
        if len(stripes) != n_stripes:
            return None
        return OrcStripeStats(names, stripes)
    except Exception:
        return None


def stripe_survives(stats: OrcStripeStats, stripe: int, predicates) -> bool:
    """Conjunct gate over one stripe's column stats — mirrors
    row_group_survives for parquet (floats never pruned: NaN-blind stats)."""
    from .files import _stat_allows

    cols = stats.stripes[stripe]
    for name, op, value in predicates:
        try:
            idx = stats.names.index(name) + 1  # root struct is column 0
        except ValueError:
            continue
        entry = cols.get(idx)
        if entry is None:
            continue
        kind, mn, mx, _has_null = entry
        if kind in (None, "double") or mn is None or mx is None:
            continue
        if kind == "decimal":
            import decimal

            try:
                mn, mx = decimal.Decimal(mn), decimal.Decimal(mx)
                value = decimal.Decimal(str(value))
            except Exception:
                continue
        if not _stat_allows(op, value, mn, mx):
            return False
    return True
