"""Bucketed table layout — the standalone analogue of Spark's bucketed
reads (reference: GpuFileSourceScanExec.scala:148-149 ``bucketedScan`` and
the bucket-pruning filter pushdown).

The writer routes each row to one of ``num_buckets`` files per task by
Spark's bucket id — ``pmod(murmur3(bucket cols, seed 42), n)``, the same
hash the exchange uses — and records the spec in a ``_bucket_spec.json``
sidecar next to the data (the Hive metastore's role in Spark). The scan
prunes whole bucket FILES when every bucket column is equality-constrained
by a pushed-down predicate: the matching rows can only live in the bucket
the literals hash to.
"""
from __future__ import annotations

import json
import os
import re
from typing import Optional

import numpy as np
import pyarrow as pa

SPEC_FILE = "_bucket_spec.json"
_BUCKET_RE = re.compile(r"_b(\d{5})\.[A-Za-z0-9.]+$")


def write_spec(root: str, num_buckets: int, cols: list[str]) -> None:
    with open(os.path.join(root, SPEC_FILE), "w") as f:
        json.dump({"num_buckets": int(num_buckets), "cols": list(cols)}, f)


def read_spec(root: str) -> Optional[dict]:
    p = os.path.join(root, SPEC_FILE)
    if not os.path.isfile(p):
        return None
    try:
        with open(p) as f:
            spec = json.load(f)
        if spec.get("num_buckets", 0) > 0 and spec.get("cols"):
            return spec
    except (OSError, ValueError):
        pass
    return None


def parse_bucket_id(filename: str) -> Optional[int]:
    m = _BUCKET_RE.search(filename)
    return int(m.group(1)) if m else None


def batch_bucket_ids(rb: pa.RecordBatch, schema, cols: list[str]) -> np.ndarray:
    """Per-row murmur3 fold over the bucket columns (int32, pre-pmod) —
    identical code path to the CPU engine's hash exchange so a bucketed
    write and a hash shuffle agree on placement."""
    from ..expr.base import UnresolvedAttribute, bind
    from ..exec.cpu import _cpu_ctx, _val_to_np
    from ..ops.hash import murmur3_rows

    ctx = _cpu_ctx(rb, schema)
    hashed = []
    for name in cols:
        e = bind(UnresolvedAttribute(name), schema)
        d, v = _val_to_np(ctx, e.eval(ctx))
        hashed.append((e.data_type, d, v, None))
    return murmur3_rows(np, hashed, rb.num_rows)


def bucket_ids(rb: pa.RecordBatch, schema, spec: dict) -> np.ndarray:
    from ..ops.hash import partition_ids

    h = batch_bucket_ids(rb, schema, spec["cols"])
    return partition_ids(np, h, spec["num_buckets"])


def target_bucket(spec: dict, predicates, schema) -> Optional[int]:
    """Bucket id the pushed-down equality literals hash to, or None when
    any bucket column lacks an ``=`` conjunct (no pruning possible)."""
    by_name = {}
    for name, op, value in predicates:
        if op == "=" and value is not None:
            by_name.setdefault(name, value)
    if not all(c in by_name for c in spec["cols"]):
        return None
    try:
        arrays = {}
        for c in spec["cols"]:
            f = schema[schema.index_of(c)]
            arrays[c] = pa.array([by_name[c]], type=f.data_type.to_arrow())
        rb = pa.record_batch(arrays)
    except (pa.ArrowInvalid, pa.ArrowTypeError, KeyError):
        return None
    sub_schema = type(schema)(
        [schema[schema.index_of(c)] for c in spec["cols"]]
    )
    return int(bucket_ids(rb, sub_schema, spec)[0])
