"""File scans — the L5 I/O layer.

Reference: GpuParquetScan.scala (1830 LoC: PERFILE/COALESCING/MULTITHREADED
reader strategies), GpuOrcScan.scala, GpuBatchScanExec.scala (CSV). On TPU
there is no device-side Parquet decode (cudf's Table.readParquet has no XLA
analogue), so the architecture keeps the reference's *host-side* half — file
listing, footer/schema handling, multi-file coalescing, background prefetch
threads — and feeds decoded Arrow batches to the H2D transition. pyarrow is
the decode engine (the host-buffer role of ParquetCopyBlocksRunner).

Reader strategies (spark.rapids.sql.format.parquet.reader.type analogue):
* PERFILE: one partition per file, streamed batch reads
* COALESCING: small files grouped into shared partitions by size until
  the reader byte target (MultiFileParquetPartitionReader,
  GpuParquetScan.scala:939 — there the stitch is row-group chunks into one
  host buffer; here it is files into one partition stream)
* MULTITHREADED: a background thread pool prefetches file batches (the cloud
  reader, GpuParquetScan.scala:1358)

Also here:
* Hive-style partition discovery + per-file constant-column splicing
  (ColumnarPartitionReaderWithPartitionValues analogue).
* Parquet row-group pruning from footer min/max statistics for pushed-down
  predicates (GpuParquetFileFilterHandler, GpuParquetScan.scala:253), plus
  whole-file pruning on partition values. The scan exec counts skipped row
  groups in ``pruned_row_groups`` so tests can prove pruning happened.
"""
from __future__ import annotations

import glob as _glob
import math
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, List, Optional, Tuple

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.orc as paorc
import pyarrow.parquet as papq

from .. import config as cfg
from ..config import TpuConf
from ..exec import task
from ..plan.physical import Exec, ExecContext, PartitionSet
from ..types import DOUBLE, LONG, STRING, Schema, StructField


_EXT = {"parquet": ".parquet", "orc": ".orc", "csv": ".csv"}


def expand_paths(paths, fmt: str) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for f in sorted(files):
                    if f.startswith(("_", ".")):
                        continue
                    out.append(os.path.join(root, f))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no {fmt} files found in {paths}")
    return out


# ── Hive-style partition discovery ─────────────────────────────────────────

# Spark's PartitioningUtils.charToEscape set (escapePathName/unescapePathName)
_ESCAPE_CHARS = set('"#%\'*/:=?\\\x7f{[]^') | {chr(c) for c in range(0x20)}


def escape_path_name(s: str) -> str:
    return "".join(
        f"%{ord(c):02X}" if c in _ESCAPE_CHARS else c for c in s
    )


def unescape_path_name(s: str) -> str:
    out = []
    i = 0
    while i < len(s):
        if s[i] == "%" and i + 3 <= len(s):
            try:
                out.append(chr(int(s[i + 1 : i + 3], 16)))
                i += 3
                continue
            except ValueError:
                pass
        out.append(s[i])
        i += 1
    return "".join(out)


def _partition_segments(path: str) -> List[Tuple[str, str]]:
    segs = []
    for part in path.split(os.sep)[:-1]:  # exclude the file name
        if "=" in part and not part.startswith("."):
            k, _, v = part.partition("=")
            if k:
                segs.append((unescape_path_name(k), unescape_path_name(v)))
    return segs


def discover_partitions(files: List[str]):
    """Infer Hive-layout partition columns from ``key=value`` directory
    segments. Returns (partition Schema, per-file value dicts); empty schema
    when the files carry no partition segments (Spark's
    PartitioningAwareFileIndex inference, narrowed to long/double/string)."""
    per_file = [dict(_partition_segments(f)) for f in files]
    keys: List[str] = []
    for d in per_file:
        for k in d:
            if k not in keys:
                keys.append(k)
    if not keys or any(set(d) != set(keys) for d in per_file):
        return Schema([]), [dict() for _ in files]

    def infer(vals):
        def is_long(s):
            try:
                int(s)
                return True
            except ValueError:
                return False

        def is_double(s):
            try:
                float(s)
                return True
            except ValueError:
                return False

        vals = [v for v in vals if v != _HIVE_NULL]
        if vals and all(is_long(v) for v in vals):
            return LONG
        if vals and all(is_double(v) for v in vals):
            return DOUBLE
        return STRING

    fields = []
    for k in keys:
        vals = [d[k] for d in per_file]
        nullable = any(d[k] == _HIVE_NULL for d in per_file)
        fields.append(StructField(k, infer(vals), nullable))
    return Schema(fields), per_file


_HIVE_NULL = "__HIVE_DEFAULT_PARTITION__"


def _typed_partition_value(raw: str, dt):
    if raw == _HIVE_NULL:
        return None
    if dt == LONG:
        return int(raw)
    if dt == DOUBLE:
        return float(raw)
    return raw


def splice_partition_values(
    rb: pa.RecordBatch, part_schema: Schema, values: dict
) -> pa.RecordBatch:
    """Append constant partition-value columns to a data batch
    (ColumnarPartitionReaderWithPartitionValues.scala analogue)."""
    if not len(part_schema.fields):
        return rb
    arrays = list(rb.columns)
    names = list(rb.schema.names)
    for f in part_schema:
        v = _typed_partition_value(values[f.name], f.data_type)
        arrays.append(
            pa.array([v] * rb.num_rows, type=f.data_type.to_arrow())
        )
        names.append(f.name)
    return pa.RecordBatch.from_arrays(arrays, names=names)


def infer_schema(files: List[str], fmt: str, options: dict) -> Schema:
    if fmt == "parquet":
        base = Schema.from_arrow(papq.read_schema(files[0]))
    elif fmt == "orc":
        base = Schema.from_arrow(paorc.ORCFile(files[0]).schema)
    elif fmt == "csv":
        table = _read_csv(files[0], options)
        base = Schema.from_arrow(table.schema)
    else:
        raise ValueError(fmt)
    part_schema, _ = discover_partitions(files)
    extra = [f for f in part_schema if f.name not in base.names]
    return Schema(list(base.fields) + extra)


def _read_csv(path: str, options: dict) -> pa.Table:
    header = str(options.get("header", "false")).lower() in ("true", "1")
    sep = options.get("sep", options.get("delimiter", ","))
    read_opts = pacsv.ReadOptions(autogenerate_column_names=not header)
    parse_opts = pacsv.ParseOptions(delimiter=sep)
    # Spark's CSV defaults: nullValue is the empty string (and ONLY it —
    # "NaN" must parse as a float NaN, not null), empty strings read as
    # null; the default routes through the version shim, users override
    # with the nullValue option
    null_opts = dict(
        null_values=[options.get("nullValue", "")], strings_can_be_null=True
    )
    conv = pacsv.ConvertOptions(**null_opts)
    if "schema" in options:
        schema: Schema = options["schema"]
        conv = pacsv.ConvertOptions(
            **null_opts,
            column_types=dict(
                zip(schema.names, (f.data_type.to_arrow() for f in schema))
            ),
        )
        if not header:
            read_opts = pacsv.ReadOptions(column_names=schema.names)
    return pacsv.read_csv(path, read_options=read_opts, parse_options=parse_opts, convert_options=conv)


# ── predicate pushdown: row-group pruning ──────────────────────────────────


def _stat_allows(op: str, value, mn, mx) -> bool:
    """Could any row in [mn, mx] satisfy ``col <op> value``? Conservative:
    True when stats are missing, and for NaN operands (the engine orders
    NaN greatest / NaN == NaN, which min/max stats cannot witness)."""
    if mn is None or mx is None:
        return True
    if isinstance(value, float) and math.isnan(value):
        return True
    try:
        if op == ">":
            return mx > value
        if op == ">=":
            return mx >= value
        if op == "<":
            return mn < value
        if op == "<=":
            return mn <= value
        if op == "=":
            return mn <= value <= mx
    except TypeError:
        return True
    return True


def row_group_survives(md, rg_index: int, predicates) -> bool:
    """Evaluate pushed-down conjuncts against one row group's footer stats
    (GpuParquetFileFilterHandler analogue over pyarrow metadata)."""
    rg = md.row_group(rg_index)
    cols = {rg.column(i).path_in_schema: rg.column(i) for i in range(rg.num_columns)}
    for name, op, value in predicates:
        c = cols.get(name)
        if c is None or c.statistics is None or not c.statistics.has_min_max:
            continue
        if c.physical_type in ("FLOAT", "DOUBLE"):
            # float min/max stats are NaN-blind (a NaN row can hide in any
            # group) and the engine treats NaN as the greatest value — never
            # prune float columns on stats
            continue
        st = c.statistics
        if not _stat_allows(op, value, st.min, st.max):
            return False
    return True


def partition_value_survives(values: dict, part_schema: Schema, predicates) -> bool:
    """Whole-file pruning on Hive partition values."""
    types = {f.name: f.data_type for f in part_schema}
    for name, op, value in predicates:
        if name not in values:
            continue
        v = _typed_partition_value(values[name], types[name])
        if not _stat_allows(op, value, v, v):
            return False
    return True


def _iter_file(
    path: str,
    fmt: str,
    schema: Schema,
    options: dict,
    batch_rows: int,
    part_schema: Optional[Schema] = None,
    part_values: Optional[dict] = None,
    predicates=(),
    pruned_counter=None,
) -> Iterator[pa.RecordBatch]:
    target = schema.to_arrow()
    part_schema = part_schema or Schema([])
    part_names = set(part_schema.names)

    def out(rb):
        return _conform(
            splice_partition_values(rb, part_schema, part_values or {}), target
        )

    if fmt == "parquet":
        pf = papq.ParquetFile(path)
        want = [
            n
            for n in schema.names
            if n in pf.schema_arrow.names and n not in part_names
        ]
        md = pf.metadata
        groups = list(range(md.num_row_groups))
        if predicates:
            survivors = [g for g in groups if row_group_survives(md, g, predicates)]
            if pruned_counter is not None and len(survivors) < len(groups):
                pruned_counter(len(groups) - len(survivors))
            groups = survivors
        # pruned schema ⇒ pruned decode (pushed-down column projection)
        for rb in pf.iter_batches(
            batch_size=batch_rows, columns=want, row_groups=groups
        ):
            yield out(rb)
        pf.close()
    elif fmt == "orc":
        f = paorc.ORCFile(path)
        want = [
            n for n in schema.names if n in f.schema.names and n not in part_names
        ]
        if predicates and f.nstripes > 1:
            # stripe-granularity read with statistics gating
            # (GpuOrcScan.scala:853 + OrcFilters.scala analogue; pyarrow
            # reads per stripe, our orc_meta parses the stats footer)
            from .orc_meta import read_stripe_stats, stripe_survives

            stats = read_stripe_stats(path)
            if stats is not None:
                keep = [
                    i
                    for i in range(f.nstripes)
                    if stripe_survives(stats, i, predicates)
                ]
                if pruned_counter is not None and len(keep) < f.nstripes:
                    pruned_counter(f.nstripes - len(keep))
                for i in keep:
                    rb_s = f.read_stripe(i, columns=want)
                    for off in range(0, rb_s.num_rows, batch_rows):
                        yield out(rb_s.slice(off, batch_rows))
                return
        table = f.read(columns=want)
        for rb in table.to_batches(max_chunksize=batch_rows):
            yield out(rb)
    elif fmt == "csv":
        for rb in _read_csv(path, options).to_batches(max_chunksize=batch_rows):
            yield out(rb)
    else:
        raise ValueError(fmt)


def _conform(rb: pa.RecordBatch, target: pa.Schema) -> pa.RecordBatch:
    if rb.schema == target:
        return rb
    cols = []
    for i, f in enumerate(target):
        arr = rb.column(rb.schema.get_field_index(f.name))
        if arr.type != f.type:
            arr = arr.cast(f.type)
        cols.append(arr)
    return pa.RecordBatch.from_arrays(cols, schema=target)


class CpuFileScanExec(Exec):
    """File source scan (GpuFileSourceScanExec/GpuBatchScanExec analogue)."""

    def __init__(
        self,
        files: List[str],
        fmt: str,
        schema: Schema,
        options: dict,
        conf: TpuConf,
    ):
        super().__init__([])
        self.files = files
        self.fmt = fmt
        self._schema = schema
        self.options = options
        self.batch_rows = cfg.MAX_READER_BATCH_SIZE_ROWS.get(conf)
        self.coalesce_bytes = cfg.MAX_READER_BATCH_SIZE_BYTES.get(conf)
        conf_key = (
            cfg.ORC_READER_TYPE if fmt == "orc" else cfg.PARQUET_READER_TYPE
        )
        rt = options.get("readerType", conf_key.get(conf)).upper()
        if rt == "AUTO":
            # reference default: COALESCING locally, MULTITHREADED when any
            # path lives on a cloud scheme (RapidsConf.scala:651)
            schemes = {
                s.strip().lower()
                for s in cfg.CLOUD_SCHEMES.get(conf).split(",")
                if s.strip()
            }
            # URI schemes are case-insensitive (RFC 3986)
            is_cloud = any(
                "://" in f and f.split("://", 1)[0].lower() in schemes
                for f in files
            )
            rt = "MULTITHREADED" if is_cloud else "COALESCING"
        self.reader_type = rt
        self.num_threads = cfg.MULTITHREADED_READ_NUM_THREADS.get(conf)
        # pushed-down conjuncts (name, op, literal) — set by the planner
        self.predicates: list = list(options.get("__predicates", ()))
        self.part_schema, self._part_values = discover_partitions(files)
        self.bucket_spec = options.get("__bucket_spec")
        self.pruned_row_groups = 0
        self.pruned_files = 0
        self.pruned_buckets = 0
        self._prune_lock = threading.Lock()

    @property
    def output(self) -> Schema:
        return self._schema

    def _count_pruned(self, n: int):
        with self._prune_lock:
            self.pruned_row_groups += n

    def _surviving_files(self):
        """(path, partition values) pairs after partition-value and bucket
        pruning (bucket pruning: GpuFileSourceScanExec.scala:148-149 — when
        every bucket column carries an equality conjunct, matching rows can
        only live in the literals' bucket file)."""
        target = None
        if self.bucket_spec and self.predicates:
            from .bucketing import parse_bucket_id, target_bucket

            target = target_bucket(
                self.bucket_spec, self.predicates, self._schema
            )
        out = []
        for path, vals in zip(self.files, self._part_values):
            if self.predicates and not partition_value_survives(
                vals, self.part_schema, self.predicates
            ):
                self.pruned_files += 1
                continue
            if target is not None:
                b = parse_bucket_id(os.path.basename(path))
                if b is not None and b != target:
                    self.pruned_files += 1
                    self.pruned_buckets += 1
                    continue
            out.append((path, vals))
        return out

    def _file_iter(self, path: str, vals: dict):
        task.set_input_file(path)  # InputFileBlockHolder analogue
        yield from _iter_file(
            path,
            self.fmt,
            self._schema,
            self.options,
            self.batch_rows,
            self.part_schema,
            vals,
            self.predicates if self.fmt in ("parquet", "orc") else (),
            self._count_pruned,
        )

    def execute(self, ctx: ExecContext) -> PartitionSet:
        pairs = self._surviving_files()
        if self.reader_type == "MULTITHREADED":
            return self._execute_multithreaded(pairs)
        if self.reader_type == "COALESCING":
            return self._execute_coalescing(pairs)
        parts = []
        for path, vals in pairs:
            def make(path=path, vals=vals):
                return self._file_iter(path, vals)

            parts.append(make)
        if not parts:
            parts = [lambda: iter(())]
        return PartitionSet(parts)

    def _execute_coalescing(self, pairs) -> PartitionSet:
        """Small files grouped by on-disk size into shared partitions until
        the reader byte target (MultiFileParquetPartitionReader's stitching,
        at file granularity)."""
        groups: List[List[tuple]] = []
        cur: List[tuple] = []
        cur_bytes = 0
        for path, vals in pairs:
            try:
                sz = os.path.getsize(path)
            except OSError:
                sz = self.coalesce_bytes
            if cur and cur_bytes + sz > self.coalesce_bytes:
                groups.append(cur)
                cur, cur_bytes = [], 0
            cur.append((path, vals))
            cur_bytes += sz
        if cur:
            groups.append(cur)

        def make(group):
            def it():
                for path, vals in group:
                    yield from self._file_iter(path, vals)

            return it()

        parts = [lambda g=g: make(g) for g in groups]
        if not parts:
            parts = [lambda: iter(())]
        return PartitionSet(parts)

    def _execute_multithreaded(self, pairs) -> PartitionSet:
        """Background prefetch pool (MultiFileCloudParquetPartitionReader)."""
        pool = ThreadPoolExecutor(max_workers=self.num_threads)

        def make(path, vals):
            def thunk():
                fut = pool.submit(lambda: list(self._file_iter(path, vals)))

                def it():
                    task.set_input_file(path)
                    for rb in fut.result():
                        yield rb

                return it()

            return thunk

        parts = [make(p, v) for p, v in pairs]
        if not parts:
            parts = [lambda: iter(())]
        return PartitionSet(parts)

    def node_string(self):
        pred = f" pushed={self.predicates}" if self.predicates else ""
        return f"CpuFileScan {self.fmt} [{len(self.files)} files]{pred}"
