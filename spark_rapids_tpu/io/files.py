"""File scans — the L5 I/O layer.

Reference: GpuParquetScan.scala (1830 LoC: PERFILE/COALESCING/MULTITHREADED
reader strategies), GpuOrcScan.scala, GpuBatchScanExec.scala (CSV). On TPU
there is no device-side Parquet decode (cudf's Table.readParquet has no XLA
analogue), so the architecture keeps the reference's *host-side* half — file
listing, footer/schema handling, multi-file coalescing, background prefetch
threads — and feeds decoded Arrow batches to the H2D transition. pyarrow is
the decode engine (the host-buffer role of ParquetCopyBlocksRunner).

Reader strategies (spark.rapids.sql.format.parquet.reader.type analogue):
* PERFILE: one partition per file, streamed batch reads
* COALESCING (multi-file): small files stitched into shared partitions
* MULTITHREADED: a background thread pool prefetches file batches (the cloud
  reader, GpuParquetScan.scala:1358)
"""
from __future__ import annotations

import glob as _glob
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, List, Optional

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.dataset as pads
import pyarrow.orc as paorc
import pyarrow.parquet as papq

from .. import config as cfg
from ..config import TpuConf
from ..exec import task
from ..plan.physical import Exec, ExecContext, PartitionSet
from ..types import Schema


_EXT = {"parquet": ".parquet", "orc": ".orc", "csv": ".csv"}


def expand_paths(paths, fmt: str) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for f in sorted(files):
                    if f.startswith(("_", ".")):
                        continue
                    out.append(os.path.join(root, f))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no {fmt} files found in {paths}")
    return out


def infer_schema(files: List[str], fmt: str, options: dict) -> Schema:
    if fmt == "parquet":
        return Schema.from_arrow(papq.read_schema(files[0]))
    if fmt == "orc":
        return Schema.from_arrow(paorc.ORCFile(files[0]).schema)
    if fmt == "csv":
        table = _read_csv(files[0], options)
        return Schema.from_arrow(table.schema)
    raise ValueError(fmt)


def _read_csv(path: str, options: dict) -> pa.Table:
    header = str(options.get("header", "false")).lower() in ("true", "1")
    sep = options.get("sep", options.get("delimiter", ","))
    read_opts = pacsv.ReadOptions(autogenerate_column_names=not header)
    parse_opts = pacsv.ParseOptions(delimiter=sep)
    conv = pacsv.ConvertOptions()
    if "schema" in options:
        schema: Schema = options["schema"]
        conv = pacsv.ConvertOptions(column_types=dict(zip(schema.names, (f.data_type.to_arrow() for f in schema))))
        if not header:
            read_opts = pacsv.ReadOptions(column_names=schema.names)
    return pacsv.read_csv(path, read_options=read_opts, parse_options=parse_opts, convert_options=conv)


def _iter_file(path: str, fmt: str, schema: Schema, options: dict, batch_rows: int) -> Iterator[pa.RecordBatch]:
    target = schema.to_arrow()
    if fmt == "parquet":
        pf = papq.ParquetFile(path)
        want = [n for n in schema.names if n in pf.schema_arrow.names]
        # pruned schema ⇒ pruned decode (pushed-down column projection)
        for rb in pf.iter_batches(batch_size=batch_rows, columns=want):
            yield _conform(rb, target)
        pf.close()
    elif fmt == "orc":
        f = paorc.ORCFile(path)
        want = [n for n in schema.names if n in f.schema.names]
        table = f.read(columns=want)
        for rb in table.to_batches(max_chunksize=batch_rows):
            yield _conform(rb, target)
    elif fmt == "csv":
        for rb in _read_csv(path, options).to_batches(max_chunksize=batch_rows):
            yield _conform(rb, target)
    else:
        raise ValueError(fmt)


def _conform(rb: pa.RecordBatch, target: pa.Schema) -> pa.RecordBatch:
    if rb.schema == target:
        return rb
    cols = []
    for i, f in enumerate(target):
        arr = rb.column(rb.schema.get_field_index(f.name))
        if arr.type != f.type:
            arr = arr.cast(f.type)
        cols.append(arr)
    return pa.RecordBatch.from_arrays(cols, schema=target)


class CpuFileScanExec(Exec):
    """File source scan (GpuFileSourceScanExec/GpuBatchScanExec analogue)."""

    def __init__(
        self,
        files: List[str],
        fmt: str,
        schema: Schema,
        options: dict,
        conf: TpuConf,
    ):
        super().__init__([])
        self.files = files
        self.fmt = fmt
        self._schema = schema
        self.options = options
        self.batch_rows = cfg.MAX_READER_BATCH_SIZE_ROWS.get(conf)
        self.reader_type = options.get("readerType", "PERFILE").upper()
        self.num_threads = cfg.MULTITHREADED_READ_NUM_THREADS.get(conf)

    @property
    def output(self) -> Schema:
        return self._schema

    def execute(self, ctx: ExecContext) -> PartitionSet:
        if self.reader_type == "MULTITHREADED":
            return self._execute_multithreaded()
        # PERFILE / COALESCING: one partition per file (COALESCING groups
        # small files; with pyarrow streaming the grouping is by partition)
        parts = []
        for path in self.files:
            def make(path=path):
                def it():
                    task.set_input_file(path)  # InputFileBlockHolder analogue
                    yield from _iter_file(
                        path, self.fmt, self._schema, self.options, self.batch_rows
                    )

                return it()

            parts.append(make)
        return PartitionSet(parts)

    def _execute_multithreaded(self) -> PartitionSet:
        """Background prefetch pool (MultiFileCloudParquetPartitionReader)."""
        pool = ThreadPoolExecutor(max_workers=self.num_threads)

        def make(path):
            def thunk():
                fut = pool.submit(
                    lambda: list(
                        _iter_file(path, self.fmt, self._schema, self.options, self.batch_rows)
                    )
                )
                def it():
                    task.set_input_file(path)
                    for rb in fut.result():
                        yield rb
                return it()

            return thunk

        return PartitionSet([make(p) for p in self.files])

    def node_string(self):
        return f"CpuFileScan {self.fmt} [{len(self.files)} files]"
