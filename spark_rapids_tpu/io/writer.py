"""File writers — reference: GpuParquetFileFormat.scala, GpuOrcFileFormat
.scala, GpuFileFormatWriter.scala (single-directory writer; dynamic-partition
writing follows with the writer rework)."""
from __future__ import annotations

import os
import uuid

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.orc as paorc
import pyarrow.parquet as papq


class DataFrameWriter:
    def __init__(self, df):
        self._df = df
        self._mode = "error"
        self._options: dict = {}

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = m
        return self

    def option(self, k, v) -> "DataFrameWriter":
        self._options[k] = v
        return self

    def _prep(self, path: str):
        if os.path.exists(path):
            if self._mode in ("error", "errorifexists"):
                raise FileExistsError(path)
            if self._mode == "overwrite":
                import shutil

                shutil.rmtree(path)
            elif self._mode == "ignore":
                return None
        os.makedirs(path, exist_ok=True)
        return os.path.join(path, f"part-00000-{uuid.uuid4().hex}")

    def parquet(self, path: str):
        f = self._prep(path)
        if f is None:
            return
        papq.write_table(self._df.to_arrow(), f + ".parquet")

    def orc(self, path: str):
        f = self._prep(path)
        if f is None:
            return
        paorc.write_table(self._df.to_arrow(), f + ".orc")

    def csv(self, path: str):
        f = self._prep(path)
        if f is None:
            return
        include_header = str(self._options.get("header", "false")).lower() in ("true", "1")
        pacsv.write_csv(
            self._df.to_arrow(),
            f + ".csv",
            pacsv.WriteOptions(include_header=include_header),
        )
