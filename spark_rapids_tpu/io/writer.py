"""File write path — the L5 write layer as PLAN NODES, not a driver-side
collect.

Reference: GpuDataWritingCommandExec.scala, GpuFileFormatWriter.scala (345),
GpuFileFormatDataWriter.scala (419: SingleDirectoryDataWriter and
DynamicPartitionDataWriter), GpuParquetFileFormat/GpuOrcFileFormat. The
reference encodes batches on device via cudf TableWriter; here the columnar
data is Arrow on the host side of the D2H transition and pyarrow encodes —
the same split as the scan layer (no device Parquet codec on TPU).

``CpuWriteFilesExec`` consumes each child partition *inside the partition
task* (concurrently across partitions, never funneled through the driver),
writing ``part-{pid}-{uuid}`` files; with ``partition_by`` each task splits
its batches by partition-value tuple and appends to per-directory writers
(``key=value/`` Hive layout — DynamicPartitionDataWriter). The exec's output
is one stats row per written file (filename, rows) — the write-stats tracker
(BasicColumnarWriteStatsTracker analogue)."""
from __future__ import annotations

import os
import threading
import uuid
from typing import List, Optional

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.orc as paorc
import pyarrow.parquet as papq

from ..plan.physical import Exec, ExecContext, PartitionSet
from ..types import LONG, STRING, Schema, StructField

STATS_SCHEMA = Schema(
    [StructField("filename", STRING, False), StructField("num_rows", LONG, False)]
)

_NAN = float("nan")  # canonical NaN for partition-combo dedup


# 1582-10-15, the Gregorian cutover, as days since epoch
_CUTOVER_DAYS = -141427


def _rebase_guard(rb: pa.RecordBatch) -> None:
    """spark.sql.parquet.datetimeRebaseModeInWrite=EXCEPTION (shim-routed
    default, Spark 3.1/3.2): refuse dates/timestamps before the Gregorian
    cutover — the engine writes proleptic values and performs no julian
    rebase (reference RebaseHelper.newRebaseExceptionInWrite)."""
    import pyarrow.compute as pc

    per_unit = {
        "s": 86_400,
        "ms": 86_400_000,
        "us": 86_400_000_000,
        "ns": 86_400_000_000_000,
    }
    for i, f in enumerate(rb.schema):
        if pa.types.is_date32(f.type):
            cut = _CUTOVER_DAYS
        elif pa.types.is_date64(f.type):
            cut = _CUTOVER_DAYS * 86_400_000  # date64 stores milliseconds
        elif pa.types.is_timestamp(f.type):
            cut = _CUTOVER_DAYS * per_unit[f.type.unit]
        else:
            continue
        col = rb.column(i)
        if col.null_count == len(col):
            continue
        # compare raw storage units (view strips date/datetime boxing;
        # date32 is int32-backed, the rest int64)
        width = pa.int32() if pa.types.is_date32(f.type) else pa.int64()
        lo = pc.min(col.view(width)).as_py()
        if lo is not None and lo < cut:
            raise ValueError(
                f"write of column {f.name!r} contains dates before "
                "1582-10-15, which would need julian rebase "
                "(spark.sql.parquet.datetimeRebaseModeInWrite="
                "EXCEPTION; use the 3.3 shim for CORRECTED writes)"
            )


class _FormatWriter:
    """One open output file, append-able batch by batch."""

    def __init__(self, fmt: str, path: str, schema: pa.Schema, options: dict):
        self.path = path
        self.fmt = fmt
        self.options = options
        self.rows = 0
        if fmt == "parquet":
            self._w = papq.ParquetWriter(path, schema)
        elif fmt == "orc":
            self._w = paorc.ORCWriter(path)
        elif fmt == "csv":
            include_header = str(options.get("header", "false")).lower() in (
                "true",
                "1",
            )
            self._w = pacsv.CSVWriter(
                path, schema, write_options=pacsv.WriteOptions(include_header=include_header)
            )
        else:
            raise ValueError(fmt)

    def write(self, rb: pa.RecordBatch):
        self.rows += rb.num_rows
        if self.fmt == "parquet" and self.options.get("__rebase") == "EXCEPTION":
            _rebase_guard(rb)
        if self.fmt == "orc":
            self._w.write(pa.Table.from_batches([rb]))
        else:
            self._w.write_batch(rb)

    def close(self):
        self._w.close()


def append_live_file(path: str, fmt: str, table: pa.Table, basename: str,
                     options: Optional[dict] = None) -> str:
    """The live-ingestion append primitive (live/ingest.py): land one
    Arrow table as a single ROOT-LEVEL data file named by the caller.
    The caller picks a basename that sorts after every existing one so
    a fresh directory listing replays files in append order — the
    invariant the pass-through/top-N maintenance classes rely on."""
    os.makedirs(path, exist_ok=True)
    full = os.path.join(path, basename)
    w = _FormatWriter(fmt, full, table.schema, dict(options or {}))
    for rb in table.combine_chunks().to_batches():
        if rb.num_rows:
            w.write(rb)
    w.close()
    return full


def _fmt_value(v) -> str:
    """Hive partition-directory encoding of one value (escaped like Spark's
    PartitioningUtils.escapePathName so read-back round-trips)."""
    from .files import escape_path_name

    if v is None:
        return "__HIVE_DEFAULT_PARTITION__"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v != v:
        return "NaN"  # java Double.toString
    return escape_path_name(str(v))


class CpuWriteFilesExec(Exec):
    """The write plan node (GpuDataWritingCommandExec analogue)."""

    def __init__(
        self,
        child: Exec,
        path: str,
        fmt: str,
        partition_by: List[str],
        options: dict,
    ):
        super().__init__([child])
        self.path = path
        self.fmt = fmt
        self.partition_by = list(partition_by)
        self.w_options = dict(options)
        child_schema = child.output
        missing = [c for c in self.partition_by if c not in child_schema.names]
        if missing:
            raise ValueError(f"partitionBy columns not in schema: {missing}")
        self._data_names = [
            n for n in child_schema.names if n not in self.partition_by
        ]
        self.bucket_spec = options.get("__bucket_spec")
        if self.bucket_spec:
            bad = [
                c for c in self.bucket_spec["cols"]
                if c not in child_schema.names or c in self.partition_by
            ]
            if bad:
                raise ValueError(
                    f"bucketBy columns must be non-partition data columns: {bad}"
                )
        self._child_schema = child_schema
        from ..types import Schema as _Schema

        self._data_schema = _Schema(
            [f for f in child_schema.fields if f.name in self._data_names]
        )

    @property
    def output(self) -> Schema:
        return STATS_SCHEMA

    def execute(self, ctx: ExecContext) -> PartitionSet:
        child_parts = self.children[0].execute(ctx)
        ext = {"parquet": ".parquet", "orc": ".orc", "csv": ".csv"}[self.fmt]

        def make(pid: int, thunk):
            def it():
                writers: dict = {}
                run_id = uuid.uuid4().hex[:12]

                def writer_for(
                    subdir: str, schema: pa.Schema, bucket: int = None
                ) -> _FormatWriter:
                    key = (subdir, bucket)
                    w = writers.get(key)
                    if w is None:
                        d = os.path.join(self.path, subdir) if subdir else self.path
                        os.makedirs(d, exist_ok=True)
                        suffix = "" if bucket is None else f"_b{bucket:05d}"
                        fname = f"part-{pid:05d}-{run_id}{suffix}{ext}"
                        w = _FormatWriter(
                            self.fmt, os.path.join(d, fname), schema, self.w_options
                        )
                        writers[key] = w
                    return w

                def write_bucketed(subdir: str, rb2: pa.RecordBatch, schema):
                    """Route rows to per-bucket files by the exchange's own
                    hash (io/bucketing.py — keeps bucket placement and
                    shuffle placement in agreement)."""
                    from .bucketing import bucket_ids

                    bids = bucket_ids(rb2, schema, self.bucket_spec)
                    tbl2 = pa.Table.from_batches([rb2])
                    for b in sorted(set(bids.tolist())):
                        sub2 = tbl2.filter(pa.array(bids == b))
                        for srb2 in sub2.combine_chunks().to_batches():
                            if srb2.num_rows:
                                writer_for(subdir, srb2.schema, b).write(srb2)

                for rb in thunk():
                    if rb.num_rows == 0:
                        continue
                    if not self.partition_by:
                        if self.bucket_spec:
                            write_bucketed("", rb, self._child_schema)
                        else:
                            writer_for("", rb.schema).write(rb)
                        continue
                    # dynamic partitioning: group rows by partition tuple
                    # (DynamicPartitionDataWriter's sorted-loop analogue)
                    tbl = pa.Table.from_batches([rb])
                    keys = [rb.column(rb.schema.get_field_index(c)) for c in self.partition_by]

                    def canon(v):
                        # one canonical NaN object so set-dedup of combos
                        # works (fresh as_py() NaNs are !=-distinct)
                        if isinstance(v, float) and v != v:
                            return _NAN
                        return v

                    combos = set(
                        tuple(canon(k[i].as_py()) for k in keys)
                        for i in range(rb.num_rows)
                    )
                    import pyarrow.compute as pc

                    data_tbl = tbl.select(self._data_names)
                    for combo in sorted(
                        combos, key=lambda c: tuple((x is None, str(x)) for x in c)
                    ):
                        mask = None
                        for cname, v in zip(self.partition_by, combo):
                            colarr = tbl.column(cname)
                            if v is None:
                                m = pc.is_null(colarr)
                            elif isinstance(v, float) and v != v:
                                # NaN != NaN under pc.equal — match explicitly
                                m = pc.is_nan(colarr)
                            else:
                                m = pc.equal(colarr, pa.scalar(v, type=colarr.type))
                            m = pc.fill_null(m, False)
                            mask = m if mask is None else pc.and_(mask, m)
                        sub = data_tbl.filter(mask)
                        subdir = os.path.join(
                            *[
                                f"{c}={_fmt_value(v)}"
                                for c, v in zip(self.partition_by, combo)
                            ]
                        )
                        for srb in sub.combine_chunks().to_batches():
                            if srb.num_rows:
                                if self.bucket_spec:
                                    write_bucketed(
                                        subdir, srb, self._data_schema
                                    )
                                else:
                                    writer_for(subdir, srb.schema).write(srb)
                for w in writers.values():
                    w.close()
                stats = pa.record_batch(
                    {
                        "filename": pa.array(
                            [w.path for w in writers.values()], type=pa.string()
                        ),
                        "num_rows": pa.array(
                            [w.rows for w in writers.values()], type=pa.int64()
                        ),
                    }
                )
                yield stats

            return it

        return PartitionSet(
            [make(i, t) for i, t in enumerate(child_parts.parts)]
        )

    def node_string(self):
        pb = f" partitionBy={self.partition_by}" if self.partition_by else ""
        return f"WriteFiles {self.fmt} {self.path}{pb}"


class DataFrameWriter:
    """df.write — executes a write PLAN (scan→…→WriteFilesExec), with the
    encode work running per-partition in executor tasks."""

    def __init__(self, df):
        self._df = df
        self._mode = "error"
        self._partition_by: List[str] = []
        self._bucket_spec = None
        self._options: dict = {}

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = m
        return self

    def option(self, k, v) -> "DataFrameWriter":
        self._options[k] = v
        return self

    def partition_by(self, *cols: str) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    partitionBy = partition_by

    def bucket_by(self, num_buckets: int, *cols: str) -> "DataFrameWriter":
        """Bucketed layout: rows route to ``num_buckets`` files per task by
        pmod(murmur3(cols), n); a ``_bucket_spec.json`` sidecar records the
        spec for the scan's bucket pruning (io/bucketing.py)."""
        if num_buckets <= 0 or not cols:
            raise ValueError("bucketBy needs num_buckets > 0 and columns")
        self._bucket_spec = {"num_buckets": int(num_buckets),
                             "cols": list(cols)}
        return self

    bucketBy = bucket_by

    def _reads_from(self, path: str) -> bool:
        """True when the DataFrame's plan scans ``path`` (or a file inside
        it) — Spark refuses 'Cannot overwrite a path that is also being
        read from' rather than deleting its own input."""
        from ..plan import logical as L

        target = os.path.realpath(path)

        def walk(p) -> bool:
            if isinstance(p, L.FileScan):
                for sp in p.paths:
                    rp = os.path.realpath(sp)
                    if rp == target or rp.startswith(target + os.sep):
                        return True
            return any(walk(c) for c in p.children())

        return walk(self._df._plan)

    def _check_append_bucket_spec(self, path: str) -> None:
        """Appending must agree with the existing bucket layout: a
        mismatched spec (or bucketBy over data written without one) would
        silently overwrite ``_bucket_spec.json`` and make bucket pruning
        skip files that DO hold matching rows — wrong results, not an
        error. Spark refuses the same way ('mismatched bucketing')."""
        from .bucketing import read_spec

        existing = read_spec(path)
        if self._bucket_spec:
            if existing is None:
                has_data = any(
                    not f.startswith("_") for f in os.listdir(path)
                )
                if has_data:
                    raise ValueError(
                        f"Cannot append bucketed data (bucketBy) to {path}: "
                        "existing data was written without a bucket spec — "
                        "bucket pruning over the mixed layout would return "
                        "wrong results"
                    )
                return
            if existing["num_buckets"] != self._bucket_spec["num_buckets"] or [
                c.lower() for c in existing["cols"]
            ] != [c.lower() for c in self._bucket_spec["cols"]]:
                raise ValueError(
                    f"Cannot append to {path}: bucket spec mismatch — "
                    f"existing num_buckets={existing['num_buckets']} "
                    f"cols={existing['cols']}, requested "
                    f"num_buckets={self._bucket_spec['num_buckets']} "
                    f"cols={self._bucket_spec['cols']}"
                )
        elif existing is not None:
            raise ValueError(
                f"Cannot append unbucketed data to bucketed table {path} "
                f"(num_buckets={existing['num_buckets']} "
                f"cols={existing['cols']}); use "
                f"bucketBy({existing['num_buckets']}, "
                f"{', '.join(map(repr, existing['cols']))})"
            )

    def _write(self, path: str, fmt: str):
        if os.path.exists(path):
            if self._mode in ("error", "errorifexists"):
                raise FileExistsError(path)
            if self._mode == "overwrite":
                import shutil

                if self._reads_from(path):
                    raise ValueError(
                        f"Cannot overwrite a path that is also being read"
                        f" from: {path}"
                    )
                shutil.rmtree(path)
            elif self._mode == "ignore":
                return
            elif self._mode == "append":
                self._check_append_bucket_spec(path)
        os.makedirs(path, exist_ok=True)
        session = self._df._session
        from ..cache import keys as _ckeys
        from ..plan import logical as L

        # bump the target table's data version BEFORE the write lands —
        # a reader racing this write must not cache under the old
        # version (the overwrite rmtree above already destroyed it) —
        # and again after commit so results computed mid-write are
        # rejected at cache admission. Closes the stale-read window the
        # old global-counter-on-temp-view-only scheme left open.
        table_key = _ckeys.table_key_for_path(path)
        _ckeys.bump_table_version(session, table_key)
        opts = dict(self._options)
        # shim-routed write semantics (SparkShims seam)
        opts.setdefault("__rebase", session.shim.parquet_rebase_write())
        if self._bucket_spec:
            opts["__bucket_spec"] = self._bucket_spec
        lp = L.WriteFiles(
            self._df._plan, path, fmt, list(self._partition_by), opts
        )
        stats = session._execute(lp)
        if self._bucket_spec:
            from .bucketing import write_spec

            write_spec(path, self._bucket_spec["num_buckets"],
                       self._bucket_spec["cols"])
        # driver commit marker (FileFormatWriter's _SUCCESS)
        open(os.path.join(path, "_SUCCESS"), "w").close()
        # post-commit bump: readers that fingerprinted during the write
        # see a different version at cache admission and skip the store
        _ckeys.bump_table_version(session, table_key)
        # a write into a registered LIVE root advances that table's epoch
        # as an opaque entry (live/ingest.py): versions stay consistent,
        # maintenance does a full refresh for this epoch
        live = getattr(session, "_live_runtime", None)
        if live is not None:
            live.tables.note_external_write(path)
        return stats

    def parquet(self, path: str):
        return self._write(path, "parquet")

    def orc(self, path: str):
        return self._write(path, "orc")

    def csv(self, path: str):
        return self._write(path, "csv")
