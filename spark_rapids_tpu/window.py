"""Window spec builder — the pyspark ``Window`` API surface.

Usage::

    from spark_rapids_tpu.window import Window
    w = Window.partition_by("k").order_by("ts").rows_between(-3, Window.currentRow)
    df.with_column("s", F.sum(F.col("v")).over(w))
"""
from __future__ import annotations

from typing import Union

from .expr.windows import (
    CURRENT_ROW,
    UNBOUNDED_FOLLOWING,
    UNBOUNDED_PRECEDING,
    WindowFrame,
    WindowOrder,
    WindowSpec,
)
from .expr import UnresolvedAttribute
from .functions import Column, _e


def _c2e(c):
    """Column-name semantics: strings are column references, not literals."""
    if isinstance(c, str):
        return UnresolvedAttribute(c)
    return _e(c)


def _to_orders(cols) -> tuple:
    orders = []
    for c in cols:
        if isinstance(c, WindowOrder):
            orders.append(c)
            continue
        if isinstance(c, Column) and getattr(c, "_sort_desc", False):
            orders.append(WindowOrder(_c2e(c), False, None))
            continue
        orders.append(WindowOrder(_c2e(c), True, None))
    return tuple(orders)


class WindowSpecBuilder:
    def __init__(self, spec: WindowSpec):
        self.spec = spec

    def partition_by(self, *cols) -> "WindowSpecBuilder":
        return WindowSpecBuilder(
            WindowSpec(tuple(_c2e(c) for c in cols), self.spec.order_by, self.spec.frame)
        )

    def order_by(self, *cols) -> "WindowSpecBuilder":
        return WindowSpecBuilder(
            WindowSpec(self.spec.partition_by, _to_orders(cols), self.spec.frame)
        )

    def rows_between(self, start: int, end: int) -> "WindowSpecBuilder":
        return WindowSpecBuilder(
            WindowSpec(
                self.spec.partition_by,
                self.spec.order_by,
                WindowFrame("rows", int(start), int(end)),
            )
        )

    def range_between(self, start: int, end: int) -> "WindowSpecBuilder":
        return WindowSpecBuilder(
            WindowSpec(
                self.spec.partition_by,
                self.spec.order_by,
                WindowFrame("range", int(start), int(end)),
            )
        )


class Window:
    unboundedPreceding = UNBOUNDED_PRECEDING
    unboundedFollowing = UNBOUNDED_FOLLOWING
    currentRow = CURRENT_ROW
    # snake_case aliases
    unbounded_preceding = UNBOUNDED_PRECEDING
    unbounded_following = UNBOUNDED_FOLLOWING
    current_row = CURRENT_ROW

    @staticmethod
    def partition_by(*cols) -> WindowSpecBuilder:
        return WindowSpecBuilder(WindowSpec()).partition_by(*cols)

    partitionBy = partition_by

    @staticmethod
    def order_by(*cols) -> WindowSpecBuilder:
        return WindowSpecBuilder(WindowSpec()).order_by(*cols)

    orderBy = order_by
