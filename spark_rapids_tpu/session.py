"""TpuSession + DataFrame — the SparkSession-shaped entry point.

The reference is a plugin into a running SparkSession (Plugin.scala injects
ColumnarOverrideRules); standalone, this module owns the whole query
lifecycle: DataFrame → logical plan → CPU physical plan → TpuOverrides
rewrite → execution. ``conf["spark.rapids.sql.enabled"]=False`` gives the
pure-CPU run — which is exactly how the differential test harness produces
its oracle (the reference's with_cpu_session/with_gpu_session idiom,
integration_tests asserts.py:313-377).
"""
from __future__ import annotations

from contextlib import contextmanager as _contextmanager
from typing import Any, Iterable, List, Optional, Sequence, Union

import pyarrow as pa

from . import config as cfg
from .config import TpuConf
from .expr import Alias, Expression, UnresolvedAttribute, output_name
from .functions import Column, _e, col
from .plan import logical as L
from .plan.overrides import TpuOverrides
from .plan.physical import Exec, ExecContext
from .plan.planner import plan_physical
from .types import Schema
from .columnar.host import concat_batches

# threading.stack_size is process-global: EVERY set→spawn→restore window in
# the engine (partition workers here, pipeline producers) shares this one
# lock — two independent locks could interleave and spawn a thread after
# the other window's restore (utils/threads.py)
from .utils.threads import BIG_STACK_BYTES, STACK_SIZE_LOCK as _STACK_SIZE_LOCK


def _token_checked(thunk, token, ledger=None):
    """Wrap a partition thunk so the query's cancel token is checked once
    per result batch — with CPU-only plans (no device loop to check) this
    IS the batch-boundary cancellation guarantee."""
    if token is None and ledger is None:
        return thunk

    def it():
        # install the token as this worker thread's watchdog current so
        # blocking regions beneath the pull (kernel compile, shuffle
        # fetch) can label their stall phase; every check() is a beat —
        # and the query's phase ledger rides the same install so those
        # regions attribute their time (obs/ledger.py current-ledger)
        from .obs import ledger as _ledger
        from .resilience import watchdog as _wd

        if token is not None:
            _wd.set_current(token)
        _ledger.set_current(ledger)
        try:
            for rb in thunk():
                if token is not None:
                    token.check()
                yield rb
        finally:
            _wd.set_current(None)
            _ledger.set_current(None)

    return it


class TpuSession:
    def __init__(self, conf: Optional[dict] = None):
        from . import kernels as K

        K.enable_persistent_cache()  # reuse XLA binaries across processes
        self.conf = TpuConf(conf or {})
        # version shim (ShimLoader analogue): semantics knobs route through
        # it; shim-driven defaults fill keys the user left unset
        from .shims import get_shim

        self.shim = get_shim(cfg.SPARK_VERSION.get(self.conf))
        if self.conf.get_raw(cfg.ANSI_ENABLED.key) is None and self.shim.ansi_default():
            self.conf = self.conf.set(cfg.ANSI_ENABLED.key, True)
        if (
            self.conf.get_raw(cfg.ADAPTIVE_ENABLED.key) is None
            and self.shim.adaptive_default()
        ):
            self.conf = self.conf.set(cfg.ADAPTIVE_ENABLED.key, True)
        if cfg.CPU_ONLY.get(self.conf):
            import jax

            jax.config.update("jax_platforms", "cpu")
        # native host data plane gate (spark.rapids.native.enabled)
        from . import native as _native

        _native.set_enabled(cfg.NATIVE_ENABLED.get(self.conf))
        from .ops import pallas_strings as _ps

        _ps.set_enabled(cfg.PALLAS_ENABLED.get(self.conf))
        self._mesh_ctx = None
        # startup_only: mesh mode is committed at construction (partition
        # arity, exchange lowering); per-query surfaces read this frozen
        # flag, never the conf (conf-key lint, scope rule)
        self._mesh_on = cfg.MESH_ENABLED.get(self.conf)
        if self._mesh_on:
            # mesh mode: one exchange partition per chip, so the planner's
            # shuffle arity matches the mesh unless the user pinned it
            if self.conf.get_raw(cfg.SHUFFLE_PARTITIONS.key) is None:
                self.conf = self.conf.set(
                    cfg.SHUFFLE_PARTITIONS.key, self.mesh_context().n
                )
        elif (
            cfg.SQL_ENABLED.get(self.conf)
            and self.conf.get_raw(cfg.SHUFFLE_PARTITIONS.key) is None
        ):
            # single-device default: ONE task (the reference's
            # concurrentGpuTasks model). Without mesh mode every partition
            # runs serialized on the default device — each extra partition
            # is another kernel pipeline + host sync, measured 2-4x slower
            # at partitions=2 vs 1 on the bench queries. Mesh mode above
            # sets one partition per chip instead.
            self.conf = self.conf.set(cfg.SHUFFLE_PARTITIONS.key, 1)
        self.read = DataFrameReader(self)
        self._temp_views: dict = {}  # lower-case name -> DataFrame
        self._last_plan: Optional[Exec] = None
        self._last_overrides: Optional[TpuOverrides] = None
        self._last_fused_stages = 0
        self._task_retries = 0
        self._query_seq = 0
        import threading as _threading

        self._retry_lock = _threading.Lock()
        # multi-tenant scheduler (sched/): admission control + cancellation
        # registry for concurrent collect()/toPandas() callers. Scheduler
        # CONFS are re-read at every admission (nothing frozen here).
        from .sched import QueryScheduler

        self._scheduler = QueryScheduler()
        # concurrency guards for the session-lifetime caches: the df.cache()
        # store (single-flight per cache key) and the device-upload LRU
        self._cache_lock = _threading.Lock()
        self._h2d_lock = _threading.Lock()
        # the caches those locks guard (previously lazy __dict__ entries;
        # eager init lets the guarded-by pass anchor its annotations)
        self._cache_store: dict = {}  # graft: guarded_by(_cache_lock)
        self._h2d_cache: dict = {}  # graft: guarded_by(_h2d_lock)
        # common-work sharing (cache/keys|results|subplan): per-table
        # monotonic write counters behind result/prepared invalidation
        # (every write path routes through cache/keys.bump_table_version,
        # which also bumps the global _catalog_version the prepared-plan
        # cache keys on), the bounded semantic result cache, and the
        # in-flight shared-subtree registry. See docs/result-cache.md.
        self._catalog_lock = _threading.Lock()
        self._catalog_version = 0  # graft: guarded_by(_catalog_lock)
        self._table_versions: dict = {}  # graft: guarded_by(_catalog_lock)
        self._view_sources: dict = {}  # graft: guarded_by(_catalog_lock)
        self._view_source_ids: dict = {}  # graft: guarded_by(_catalog_lock)
        from .cache.results import ResultCache
        from .cache.subplan import SubplanRegistry

        self._result_cache = ResultCache(self.conf)
        self._subplan_registry = SubplanRegistry()
        # live analytics runtime (live/): built lazily by the `live`
        # property, gated on spark.rapids.tpu.live.enabled
        self._live_runtime = None
        # resilience: session-lifetime CPU-fallback circuit breaker (runtime
        # kernel failures flip ops to CPU at the next planning pass) and the
        # deterministic fault-injection scenario (None unless
        # spark.rapids.tpu.faults.enabled — chaos testing only)
        from .resilience import CircuitBreaker

        self._breaker = CircuitBreaker.from_conf(self.conf)
        # survivability wiring: the watchdog feeds op-attributed stalls to
        # this session's breaker, and the first-touch compile budget is
        # process-global like the kernel cache it guards
        self._scheduler.breaker = self._breaker
        K.set_compile_deadline(cfg.COMPILE_DEADLINE_S.get(self.conf))
        # shape-bucket lattice: process-global like the kernel cache whose
        # entry count it bounds (columnar/device.py bucket_capacity reads it)
        K.set_shape_bucket_floor(
            cfg.SHAPE_BUCKETS_MIN_ROWS.get(self.conf)
            if cfg.SHAPE_BUCKETS_ENABLED.get(self.conf)
            else 1
        )
        # restart survivability: the process-global on-disk XLA executable
        # store (cache/xla_store.py) — GuardedJit consults it before
        # compiling, so a restarted server starts hot in seconds
        from .cache import xla_store as _xc

        _xc.configure(self.conf)
        # obs wiring: the dynamic-series cardinality cap is process-global
        # (the registry it guards is), and the live scrape endpoint starts
        # here for bare sessions (TpuServer.start also ensures it)
        from .obs import metrics as _obs_metrics
        from .obs.scrape import ensure_scrape

        _obs_metrics.set_slug_cap(cfg.METRICS_MAX_DYNAMIC_SLUGS.get(self.conf))
        ensure_scrape(self)
        self._fault_injector = self._build_fault_injector()
        mp_driver = cfg.MULTIPROC_DRIVER.get(self.conf)
        mp_rank = cfg.MULTIPROC_RANK.get(self.conf)
        mp_size = cfg.MULTIPROC_SIZE.get(self.conf)
        if mp_driver:
            # fail fast on inconsistent multi-process settings — a missing
            # piece silently double-counts (every rank runs the full query)
            if not cfg.SHUFFLE_MANAGER_ENABLED.get(self.conf):
                raise ValueError(
                    "spark.rapids.shuffle.multiproc.driver requires "
                    "spark.rapids.shuffle.manager.enabled=true"
                )
            if mp_size < 2 or not (0 <= mp_rank < mp_size):
                raise ValueError(
                    f"multiproc rank/size invalid: rank={mp_rank} "
                    f"size={mp_size}"
                )
        # The multiproc keys are startup_only: the transport, executor id,
        # and driver registration commit to this topology NOW, so every
        # per-query surface (ExecContext, the exchange's rank split) reads
        # the frozen tuple instead of re-reading the conf — a live
        # set_conf can no longer make the plan disagree with the running
        # transport (conf-key lint, scope rule). The thread-local override
        # lets subquery resolution run single-process WITHOUT mutating the
        # shared conf (the old saved/restored-conf dance raced concurrent
        # queries on other threads into multiproc-off planning).
        self._mp_topology = (
            (mp_driver, mp_rank, mp_size) if mp_driver else ("", 0, 1)
        )
        self._mp_off_tls = _threading.local()

    def _build_fault_injector(self):
        """One injector for the session's lifetime, so every-Nth fault
        counters accumulate across queries (None unless faults enabled)."""
        from .resilience import faults as _faults

        config = _faults.config_from_conf(self.conf)
        return None if config is None else _faults.FaultInjector(config)

    def sql(self, text: str, params=None) -> "DataFrame":
        """Run a SELECT statement over registered temp views (sql/ package —
        the standalone analogue of riding Spark's parser; reference QA
        battery: integration_tests/src/main/python/qa_nightly_sql.py).
        ``params`` binds the statement's ``?`` placeholders positionally —
        AST-level substitution (sql/parser.py::bind_parameters), so values
        are always literals, never spliced text."""
        from .sql import Compiler, bind_parameters, parse

        q = parse(text)
        if params is not None:
            q = bind_parameters(q, params)
        return Compiler(self).compile(q)

    def create_or_replace_temp_view(self, name: str, df: "DataFrame"):
        from .cache import keys as _ckeys

        self._temp_views[name.lower()] = df
        key = _ckeys.table_key_for_view(name)
        # map the view's backing tables so result-cache read sets resolve
        # physical scans (keyed by source identity) back to this view
        _ckeys.register_view_sources(
            self, key, _ckeys.view_backing_tables(df._plan)
        )
        # bumps this view's write counter AND the global catalog version
        # (the serve prepared-plan cache keys on the global), and evicts
        # dependent result-cache entries
        _ckeys.bump_table_version(self, key)

    def drop_temp_view(self, name: str) -> bool:
        """Unregister a temp view. A write path like any other: the
        view's version bumps so cached results and prepared plans built
        against it can never serve after the drop."""
        from .cache import keys as _ckeys

        df = self._temp_views.pop(name.lower(), None)
        if df is None:
            return False
        key = _ckeys.table_key_for_view(name)
        _ckeys.register_view_sources(self, key, ())
        _ckeys.bump_table_version(self, key)
        return True

    def table(self, name: str) -> "DataFrame":
        try:
            return self._temp_views[name.lower()]
        except KeyError:
            raise ValueError(f"unknown table {name!r}") from None

    def _next_query_seq(self) -> int:
        with self._retry_lock:
            self._query_seq += 1
            return self._query_seq

    # ── live analytics (live/) ──────────────────────────────────────────
    @property
    def live(self):
        """The session's :class:`live.LiveRuntime` — streaming append
        ingestion, incremental view maintenance, and subscription fan-out
        (ISSUE 20). Gated on ``spark.rapids.tpu.live.enabled`` (default
        off); built lazily on first touch."""
        if not cfg.LIVE_ENABLED.get(self.conf):
            raise RuntimeError(
                "live analytics is disabled: set "
                "spark.rapids.tpu.live.enabled=true before using "
                "session.live"
            )
        rt = self._live_runtime
        if rt is None:
            from .live import LiveRuntime

            # construct OUTSIDE the session lock: the runtime's __init__
            # acquires its own tier-17 live locks (listener registration),
            # which must never nest under a tier-78 session lock. A racing
            # loser is discarded before it spawns any thread or state.
            candidate = LiveRuntime(self)
            with self._retry_lock:
                if self._live_runtime is None:
                    self._live_runtime = candidate
                rt = self._live_runtime
        return rt

    # ── multi-tenant scheduling (sched/) ────────────────────────────────
    @property
    def scheduler(self):
        """The session's QueryScheduler (admission pool + active-query
        registry) — read-only introspection for services and tests."""
        return self._scheduler

    def active_queries(self) -> dict:
        """query_id → {pool, permits, granted, running, queue_wait_s} of
        every query currently queued or executing in this session — the
        live queue view the serve STATUS command and ops tooling render."""
        return self._scheduler.active_queries()

    def cancel(self, query_id: str, reason: str = "cancelled by user") -> bool:
        """Cancel one in-flight query (the ``cancelJobGroup`` analogue for
        a single query): it stops at its next batch boundary, releases its
        admission permits, and raises QueryCancelledError to its caller.
        True when a matching active query existed."""
        return self._scheduler.cancel(query_id, reason)

    def cancel_all(self, reason: str = "cancel_all") -> int:
        """Cancel every queued and running query; returns how many were
        flagged. The session stays fully usable afterwards."""
        return self._scheduler.cancel_all(reason)

    def multiproc_topology(self) -> tuple:
        """``(driver, rank, size)`` as frozen at session construction —
        the only sanctioned read of the startup_only multiproc keys on
        the query path. Returns the single-process tuple while the
        calling thread is inside a subquery-resolution scope (see
        ``_resolve_subqueries``: subqueries must run WHOLE on every
        rank, and the thread-local override gets that without mutating
        the shared conf under concurrent queries)."""
        if getattr(self._mp_off_tls, "depth", 0) > 0:
            return ("", 0, 1)
        return self._mp_topology

    @_contextmanager
    def _single_process_scope(self):
        """Thread-local multiproc-off scope for subquery resolution. A
        DEPTH counter, not a flag: a subquery nested inside another
        subquery must not re-enable multiproc for the still-executing
        outer one when the inner scope exits."""
        tls = self._mp_off_tls
        tls.depth = getattr(tls, "depth", 0) + 1
        try:
            yield
        finally:
            tls.depth -= 1

    def mesh_context(self):
        """Lazily build the session's MeshContext (mesh mode only)."""
        if self._mesh_ctx is None:
            import jax

            from .parallel.mesh import MeshContext

            n = cfg.MESH_SIZE.get(self.conf) or len(jax.devices())
            self._mesh_ctx = MeshContext(min(n, len(jax.devices())))
        return self._mesh_ctx

    # ── builders ────────────────────────────────────────────────────────
    def create_dataframe(
        self,
        data: Union[pa.Table, pa.RecordBatch, dict, list],
        schema: Optional[Schema] = None,
        num_partitions: int = 1,
    ) -> "DataFrame":
        if isinstance(data, pa.RecordBatch):
            table = pa.Table.from_batches([data])
        elif isinstance(data, pa.Table):
            table = data
        elif isinstance(data, dict):
            table = pa.table(data)
        else:
            raise TypeError(f"cannot create dataframe from {type(data)}")
        if schema is None:
            schema = Schema.from_arrow(table.schema)
        else:
            table = table.cast(schema.to_arrow())
        return DataFrame(self, L.LocalRelation(table, schema, num_partitions))

    createDataFrame = create_dataframe

    def range(self, start: int, end: Optional[int] = None, step: int = 1, num_partitions: int = 1):
        if end is None:
            start, end = 0, start
        return DataFrame(self, L.Range(start, end, step, num_partitions))

    def set_conf(self, key: str, value: Any):
        self.conf = self.conf.set(key, value)
        if key.startswith("spark.rapids.tpu.faults."):
            self._fault_injector = self._build_fault_injector()
        if key == cfg.COMPILE_DEADLINE_S.key:
            from . import kernels as K

            K.set_compile_deadline(cfg.COMPILE_DEADLINE_S.get(self.conf))
        if key.startswith("spark.rapids.tpu.compileCache."):
            from .cache import xla_store as _xc

            _xc.configure(self.conf)
        if key.startswith("spark.rapids.tpu.shapeBuckets."):
            from . import kernels as K

            K.set_shape_bucket_floor(
                cfg.SHAPE_BUCKETS_MIN_ROWS.get(self.conf)
                if cfg.SHAPE_BUCKETS_ENABLED.get(self.conf)
                else 1
            )

    # ── execution ───────────────────────────────────────────────────────
    def _resolve_subqueries(self, lp: L.LogicalPlan) -> L.LogicalPlan:
        """Execute every subquery plan through the full engine and inline
        the results (Spark executes subqueries before the main query;
        reference GpuScalarSubquery.scala / GpuInSet.scala):

            ScalarSubquery(plan) → Literal(value)
            InSubquery(c, plan)  → InSet(c, distinct values)
        """
        from .expr.base import Literal
        from .expr.subquery import InSet, InSubquery, ScalarSubquery

        def run_whole(plan):
            """Subqueries resolve to literals every executor needs — under a
            multi-process query each process computes the WHOLE subquery
            locally (rank-splitting it would inline a partial aggregate).
            The single-process override is THREAD-LOCAL (ExecContext reads
            multiproc_topology() at construction): the old save/restore of
            the shared conf let a concurrent query on another thread plan
            itself multiproc-off mid-subquery."""
            if self._mp_topology[0]:
                with self._single_process_scope():
                    return self._execute(plan)
            return self._execute(plan)

        def fix(e):
            if isinstance(e, ScalarSubquery):
                tbl = run_whole(e.plan)
                if tbl.num_columns != 1:
                    raise ValueError(
                        "scalar subquery must return one column, got "
                        f"{tbl.num_columns}"
                    )
                if tbl.num_rows > 1:
                    raise ValueError(
                        "scalar subquery returned more than one row"
                    )
                val = tbl.column(0)[0].as_py() if tbl.num_rows else None
                from .types import DateType, TimestampType

                if val is not None and isinstance(
                    e.data_type, (DateType, TimestampType)
                ):
                    # date/timestamp literals store their physical ints
                    # (Literal.eval special-cases only None/string/decimal)
                    val = InSet._encode_values([val], e.data_type)[0]
                return Literal(val, e.data_type)
            if isinstance(e, InSubquery):
                tbl = run_whole(e.plan)
                if tbl.num_columns != 1:
                    raise ValueError(
                        "IN-subquery must return one column, got "
                        f"{tbl.num_columns}"
                    )
                vals = tbl.column(0).to_pylist()
                seen: set = set()
                out = []
                has_null = False
                for x in vals:
                    if x is None:
                        has_null = True
                        continue
                    try:
                        new = x not in seen
                        if new:
                            seen.add(x)
                    except TypeError:
                        new = True
                    if new:
                        out.append(x)
                if has_null:
                    out.append(None)
                return InSet(e.c, tuple(out))
            return e

        return L.transform_expressions(lp, fix)

    def _translate_udfs(self, lp: L.LogicalPlan) -> L.LogicalPlan:
        """udf-compiler pass: rewrite translatable python UDFs into plain
        expression trees so they fuse on device (reference
        udf-compiler/CatalystExpressionBuilder.scala; subset documented in
        expr/udf_compiler.py). Untranslatable UDFs keep their CPU
        fallback."""
        from .expr.udf import PythonUdf
        from .expr.udf_compiler import try_translate

        def fix(e):
            if isinstance(e, PythonUdf):
                t = try_translate(e.fn, list(e.args), e.return_type)
                if t is not None:
                    return t
            return e

        return L.transform_expressions(lp, fix)

    def _resolve_cached(self, lp: L.LogicalPlan) -> L.LogicalPlan:
        """Materialize InMemoryRelation nodes: first touch executes the
        subtree and stores the result as PARQUET BYTES in memory (the
        ParquetCachedBatchSerializer analogue — compressed columnar cache,
        reference shims/spark311/ParquetCachedBatchSerializer.scala);
        later touches decode from the store."""
        import dataclasses as _dc

        if not isinstance(lp, L.LogicalPlan):
            return lp
        if isinstance(lp, L.InMemoryRelation):
            entry = self._cache_entry(lp)
            return L.LocalRelation(
                entry["table"], lp.schema, lp.num_partitions
            )
        kw = {}
        changed = False
        for f in _dc.fields(lp):
            v = getattr(lp, f.name)
            if isinstance(v, L.LogicalPlan):
                nv = self._resolve_cached(v)
            elif isinstance(v, list) and v and isinstance(v[0], L.LogicalPlan):
                nv = [self._resolve_cached(c) for c in v]
            else:
                nv = v
            kw[f.name] = nv
            if nv is not v:
                changed = True
        return _dc.replace(lp, **kw) if changed else lp

    def _cache_entry(self, lp: "L.InMemoryRelation") -> dict:
        """Materialize (or await) one InMemoryRelation's cache entry with
        SINGLE-FLIGHT semantics: the first toucher of a cold key executes
        the subtree; concurrent touchers of the same key block on its done
        event instead of re-executing the subtree or racing the dict (two
        threads double-executing an expensive cached aggregate is precisely
        what cache() exists to prevent). A failed materialization clears
        the key and raises only to the OWNER; waiters retry ownership
        themselves — the owner's failure may be its own cancellation or
        deadline, which must not poison an innocent tenant's query. The
        retry loop terminates: each pass either waits for a different
        owner or becomes the owner, and an owner always returns or
        raises."""
        import io
        import threading

        import pyarrow.parquet as papq

        while True:
            with self._cache_lock:
                store = self._cache_store
                entry = store.get(lp.cache_key)
                owner = entry is None
                if owner:
                    entry = {
                        "bytes": None,
                        "table": None,
                        "error": None,
                        "done": threading.Event(),
                        "lock": threading.Lock(),
                    }
                    store[lp.cache_key] = entry
            if owner:
                try:
                    table = self._execute(lp.child)
                    buf = io.BytesIO()
                    papq.write_table(table, buf, compression="zstd")
                    entry["bytes"] = buf.getvalue()
                except BaseException as e:
                    entry["error"] = e
                    with self._cache_lock:
                        if store.get(lp.cache_key) is entry:
                            del store[lp.cache_key]
                    raise
                finally:
                    entry["done"].set()
                break
            # this wait predates the waiter's own admission (no CancelToken
            # yet), so session.cancel_all() reaches it through the
            # scheduler's cancel epoch instead — shutdown must not leave a
            # thread parked on another query's materialization
            from .sched import QueryCancelledError

            epoch = self._scheduler.cancel_epoch
            while not entry["done"].wait(0.05):
                if self._scheduler.cancel_epoch != epoch:
                    raise QueryCancelledError(
                        "cancel_all while waiting on cache "
                        f"({lp.cache_key}) materialization"
                    )
            if entry["error"] is None:
                break  # materialized: decode below
        with entry["lock"]:
            if entry["table"] is None:
                entry["table"] = papq.read_table(io.BytesIO(entry["bytes"]))
                # the decoded table serves all later reads (and anchors the
                # device-upload cache); the compressed bytes are done
                entry["bytes"] = None
        return entry

    def uncache(self, key: int) -> None:
        with self._cache_lock:
            entry = self._cache_store.pop(key, None)
        if entry and entry.get("table") is not None:
            # also evict the device uploads anchored on the decoded table —
            # unpersist() must actually free HBM. Same lock as the H2D
            # LRU's insert/evict path: a concurrent query's upload must not
            # race this iteration.
            tid = id(entry["table"])
            with self._h2d_lock:
                h2d = self._h2d_cache
                for k in [k for k in h2d if len(k) > 1 and k[1] == tid]:
                    h2d.pop(k, None)

    def _execute(self, lp: L.LogicalPlan) -> pa.Table:
        from .resilience import faults as _faults

        # chaos harness scope: injection points fire only while THIS
        # session's queries execute (no-op when faults are not enabled)
        with _faults.scoped(self._fault_injector):
            final_plan, ctx = self._prepare_plan(lp)
            # semantic result cache (cache/results.py): an identical
            # completed query short-circuits HERE — before tracing,
            # ledgers, and scheduler admission; a hit must cost no
            # scheduler state at all
            rkey, rkeys = None, ()
            if cfg.RESULT_CACHE_ENABLED.get(self.conf):
                from .cache import results as _rcache

                rkey, rkeys = _rcache.key_for(self, final_plan)
                if rkey is not None:
                    hit = self._result_cache.get(rkey)
                    if hit is not None:
                        return _assemble_result(hit, final_plan.output)
            from .obs import ledger as obs_ledger
            from .obs import trace as obs_trace
            from .profiling import query_trace

            seq = ctx.query_seq
            # concurrent subplan dedup (cache/subplan.py): wrap shareable
            # subtrees for single-flight execution. Admission and
            # calibration keep keying off final_plan; only execution
            # runs the wrapped exec_plan.
            exec_plan, lease = self._subplan_registry.prepare(
                self, final_plan, self.conf, f"q{seq}"
            )
            led = getattr(ctx, "ledger", None)
            tracer = self._maybe_tracer(seq)
            if tracer is not None:
                # tracer pinned into the wrappers: a straggling producer
                # thread keeps recording into ITS query's buffer, never
                # into a later query's active tracer
                obs_trace.instrument_plan(exec_plan, tracer)
            if led is not None:
                led.wall_start()
            try:
                with obs_ledger.ledger_scope(led), obs_trace.query_scope(
                    tracer, f"query-{seq}", {"seq": seq}
                ):
                    # multi-tenant admission (sched/): estimate the HBM
                    # footprint, take a weighted permit share (queueing
                    # under the fair-share policy — the wait shows as a
                    # 'queued' span), install the cancel token, run. The
                    # context manager releases permits on every exit path.
                    with self._scheduler.admit(
                        f"q{seq}", final_plan, self.conf, tracer
                    ) as admission:
                        ctx.cancel_token = admission.token
                        if led is not None:
                            led.add("queue_wait", admission.queue_wait_ns)
                        with query_trace(cfg.PROFILE_PATH.get(self.conf)):
                            result = self._run_plan(exec_plan, ctx)
                        if rkey is not None:
                            # admission re-fingerprints: a write that
                            # raced this execution rejects the store
                            self._result_cache.admit(
                                self, rkey, rkeys, result.to_batches()
                            )
                        return result
            finally:
                if lease is not None:
                    lease.release()
                if led is not None:
                    led.wall_stop()
                    self._last_ledger = led
                self._harvest_calibration(final_plan)
                if tracer is not None:
                    self._export_trace(tracer, exec_plan, seq, ledger=led)
                self._leak_check(ctx)

    def _harvest_calibration(self, final_plan) -> None:
        """Feed the measured per-op cost table at query exit
        (spark.rapids.tpu.cbo.calibration.enabled): opTime ÷ rows per node
        into the EWMA, persisted so later sessions plan on measured costs
        (obs/calibration.py). Never fails a query."""
        if not cfg.CBO_CALIBRATION_ENABLED.get(self.conf):
            return
        from .obs import calibration as obs_cal

        try:
            cal = obs_cal.get(cfg.CBO_CALIBRATION_FILE.get(self.conf))
            if cal.observe_plan(final_plan):
                cal.save()
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "cost-calibration harvest failed", exc_info=True
            )

    def _maybe_tracer(self, seq: int):
        """The span tracer for this query when tracing is on AND this query
        is sampled, else None. Sampling is deterministic in the session's
        query sequence (Dapper-style cheap sampled spans;
        spark.rapids.tpu.trace.sample)."""
        trace_dir = cfg.TRACE_DIR.get(self.conf)
        if not (cfg.TRACE_ENABLED.get(self.conf) or trace_dir):
            return None
        sample = cfg.TRACE_SAMPLE.get(self.conf)
        # Weyl-sequence hash of the seq → [0, 1): deterministic, well
        # spread even for consecutive seqs
        u = ((seq * 2654435761) & 0xFFFFFFFF) / 2**32
        if u >= sample:
            return None
        from .obs.trace import Tracer

        return Tracer(capacity=cfg.TRACE_BUFFER_SPANS.get(self.conf))

    def _export_trace(self, tracer, plan, seq: int, ledger=None) -> None:
        """Per-query artifacts (spark.rapids.tpu.trace.dir): the Chrome-
        trace/Perfetto span dump plus the metrics JSON. Export failures
        never fail the query."""
        self._last_tracer = tracer
        trace_dir = cfg.TRACE_DIR.get(self.conf)
        if not trace_dir:
            return
        import os

        from .obs import export as obs_export

        try:
            tracer.export_chrome(
                os.path.join(trace_dir, f"query-{seq}.trace.json")
            )
            obs_export.write_query_artifact(
                os.path.join(trace_dir, f"query-{seq}.metrics.json"),
                plan=plan,
                session=self,
                tracer=tracer,
                ledger=ledger,
            )
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "trace export to %s failed", trace_dir, exc_info=True
            )

    def _leak_check(self, ctx) -> None:
        if ctx.catalog.debug:
            leaks = ctx.catalog.leak_report()
            if leaks:
                import logging

                logging.getLogger(__name__).warning(
                    "spillable-buffer LEAKS at query end (%d, %d bytes): %s",
                    len(leaks),
                    sum(l["size"] for l in leaks),
                    leaks[:10],
                )

    def _prepare_plan(self, lp: L.LogicalPlan):
        """Analysis + physical planning + overrides: everything _execute
        does before running the plan. Split out so ``DataFrame.to_jax`` can
        execute the same plan WITHOUT the final device→host transition.

        Creates the query's host-overhead ledger (obs/ledger.py, attached
        as ``ctx.ledger``) and bills this whole pass to its ``parse_plan``
        phase — nested compile-warm scopes subtract themselves out."""
        from .obs import ledger as obs_ledger

        led = (
            obs_ledger.PhaseLedger()
            if cfg.LEDGER_ENABLED.get(self.conf)
            else None
        )
        if led is not None:
            led.wall_start()
        try:
            with obs_ledger.ledger_scope(led), obs_ledger.phase("parse_plan"):
                final_plan, ctx = self._prepare_plan_inner(lp)
        finally:
            if led is not None:
                led.wall_stop()
        ctx.ledger = led
        return final_plan, ctx

    def _prepare_plan_inner(self, lp: L.LogicalPlan):
        from .plan.pruning import prune_columns

        lp = self._resolve_cached(lp)
        lp = self._resolve_subqueries(lp)
        if cfg.UDF_COMPILER_ENABLED.get(self.conf):
            lp = self._translate_udfs(lp)
        mt = cfg.SPLIT_MAX_TOKENS.get(self.conf)
        import dataclasses as _dc

        from .expr.strings_ext import StringSplit as _SS

        lp = L.transform_expressions(
            lp,
            lambda e: _dc.replace(e, max_tokens=mt)
            if isinstance(e, _SS) and e.max_tokens != mt
            else e,
        )
        if cfg.ANSI_ENABLED.get(self.conf):
            # Spark resolves ansiEnabled into Cast at analysis time; same
            # here — the rewrite happens before planning so both the CPU
            # oracle and the device plan see ANSI casts
            import dataclasses as _dc

            from .expr.cast import Cast

            lp = L.transform_expressions(
                lp,
                lambda e: _dc.replace(e, ansi=True)
                if isinstance(e, Cast) and not e.ansi
                else e,
            )
        lp = prune_columns(lp)
        cpu_plan = plan_physical(lp, self.conf)
        overrides = TpuOverrides(self.conf, breaker=self._breaker)
        final_plan = overrides.apply(cpu_plan)
        # whole-stage fusion BEFORE exchange reuse: fusing rewrites operator
        # chains consistently across the plan, so identical exchange
        # subtrees still canonicalize identically — while fusing after
        # reuse would rewrite through physically-shared nodes
        from .plan.fusion import fuse_stages

        final_plan, self._last_fused_stages = fuse_stages(
            final_plan, self.conf, breaker=self._breaker
        )
        if cfg.EXCHANGE_REUSE_ENABLED.get(self.conf):
            from .plan.reuse import reuse_exchanges

            final_plan, self._last_reused_exchanges = reuse_exchanges(final_plan)
        else:
            self._last_reused_exchanges = 0
        self._last_plan = final_plan
        self._last_overrides = overrides
        self._assert_test_mode(overrides, final_plan)
        ctx = ExecContext(self.conf, self)
        if cfg.PROFILE_OPTIME.get(self.conf) or cfg.CBO_CALIBRATION_ENABLED.get(
            self.conf
        ):
            # calibration needs per-op opTime attribution (block-until-ready
            # per batch — a measurement mode) to harvest measured ns/row
            from .profiling import instrument_plan

            instrument_plan(final_plan)
        self._last_precompile = {}
        from . import kernels as K

        if cfg.PRECOMPILE_ENABLED.get(self.conf) and (
            self.conf.get_raw(cfg.PRECOMPILE_ENABLED.key) is not None
            or K.precompile_worthwhile()
        ):
            # kernel pre-compilation pass (plan/planner.py): warm the
            # shape-predictable kernels before execution so XLA compiles
            # overlap across plan nodes instead of serializing at first
            # touch of each operator; best-effort by design
            from .plan.planner import precompile_plan

            try:
                self._last_precompile = precompile_plan(final_plan, self.conf)
            except Exception:
                pass
        return final_plan, ctx

    def _run_task(self, thunk, attempts: int, on_retry=None,
                  partition_id: int = 0, token=None, ledger=None,
                  tracer=None) -> List[pa.RecordBatch]:
        """One partition task with Spark's retry model (spark.task.maxFailures;
        SURVEY §5 failure detection): the lineage IS the recovery mechanism —
        a partition thunk is a pure closure over its upstream pipeline, so a
        failed attempt simply re-runs it. Results commit only on success (a
        partial stream from a failed attempt is discarded). Deterministic
        semantic errors surface immediately: retrying an ANSI overflow or an
        assertion can only fail again — and so can a cancelled or
        deadline-expired query (sched/ errors never retry).

        Each attempt runs under a lineage attempt scope
        (resilience/lineage.py): the attempt id becomes this worker
        thread's ``TaskInfo.attempt`` for every plan layer, shuffle writers
        commit atomically per (map, attempt), and re-executions are
        accounted on ``task.reattempts`` with their wall time attributed
        to the ledger's ``recovery`` phase."""
        from .expr.base import AnsiError
        from .resilience import CompileDeadlineError
        from .resilience import faults as _faults
        from .resilience import lineage as _lineage
        from .sched import SchedulerError

        desc = _lineage.TaskDescriptor(partition_id, query_id=getattr(
            token, "query_id", ""
        ))
        last: Optional[Exception] = None
        for attempt in range(max(1, attempts)):
            desc.attempt = attempt
            try:
                with _lineage.attempt_scope(attempt):
                    # chaos straggler point: the configured partition's
                    # FIRST attempt crawls (token-beating sleep) — what the
                    # speculation monitor must overtake
                    _faults.on_task_attempt(partition_id, attempt, token)
                    if attempt == 0:
                        return list(thunk())
                    with _lineage.recovery_scope(ledger):
                        return list(thunk())
            except (AssertionError, AnsiError, SchedulerError,
                    CompileDeadlineError):
                # a blown compile budget is never task-retried: the retry
                # would re-enter the same compile and burn the budget
                # again; the breaker is already forced open, so the
                # caller's NEXT run plans the op on CPU
                raise
            except Exception as e:  # noqa: BLE001 - Spark retries any task failure
                last = e
                if on_retry is not None:
                    on_retry()  # per-query accounting (_run_plan)
                else:
                    with self._retry_lock:
                        self._task_retries += 1
                if attempt + 1 < attempts:
                    import logging

                    _lineage.record_reattempt(desc, e, ledger=ledger,
                                              tracer=tracer)
                    logging.getLogger(__name__).warning(
                        "task failed (partition %d, attempt %d/%d), "
                        "retrying from lineage: %s",
                        partition_id,
                        attempt + 1,
                        attempts,
                        e,
                    )
        assert last is not None
        raise last

    def run_plan_stream(self, final_plan, ctx, on_retry=None):
        """Generator over a prepared plan's result record batches,
        partition by partition — the serving front-end's streaming
        currency (serve/server.py), and the serial collect() path.

        Retry semantics match collect(): a partition's task commits only
        when it SUCCEEDED (``_run_task`` discards the partial stream of a
        failed attempt before any of it is yielded), so the stream never
        duplicates rows; cancellation/deadline raise between batches via
        the context's cancel token. Empty batches are filtered — the wire
        never carries zero-row frames mid-stream (the END frame closes a
        result, not a sentinel batch)."""
        parts = final_plan.execute(ctx)
        attempts = cfg.TASK_MAX_FAILURES.get(self.conf)
        token = getattr(ctx, "cancel_token", None)
        ledger = getattr(ctx, "ledger", None)
        yield from self._stream_parts(parts, attempts, token, on_retry, ledger)

    def _stream_parts(self, parts, attempts, token, on_retry, ledger=None):
        for i, thunk in enumerate(parts.parts):
            for rb in self._run_task(
                _token_checked(thunk, token, ledger), attempts, on_retry,
                partition_id=i, token=token, ledger=ledger,
            ):
                if rb.num_rows:
                    yield rb

    def _run_plan(self, final_plan, ctx) -> pa.Table:
        parts = final_plan.execute(ctx)
        batches: List[pa.RecordBatch] = []
        attempts = cfg.TASK_MAX_FAILURES.get(self.conf)
        token = getattr(ctx, "cancel_token", None)
        ledger = getattr(ctx, "ledger", None)
        # per-QUERY retry count (concurrent queries must not clobber each
        # other mid-flight); the session attribute becomes the last
        # finished query's total, assigned once in the finally below
        query_retries = [0]

        def on_retry():
            with self._retry_lock:
                query_retries[0] += 1

        # concurrentGpuTasks is re-read HERE, per query — a long-lived
        # service retunes it live with set_conf (docs/configs.md scope)
        n_threads = min(len(parts.parts), cfg.CONCURRENT_TPU_TASKS.get(self.conf))
        if n_threads > 1:
            # Run partition tasks concurrently (the reference's executor task
            # slots + GpuSemaphore model): device dispatch and D2H waits of
            # different partitions overlap instead of serializing per
            # partition; jax releases the GIL while blocking on transfers.
            import threading
            from concurrent.futures import ThreadPoolExecutor

            # XLA compilation can run inside these workers (first touch of a
            # kernel); LLVM passes recurse deeply on large fused programs and
            # overflow the default worker stack — give executors a big one.
            # stack_size() is PROCESS-global: the set→spawn→restore window
            # serializes under a lock so a concurrently-admitted query
            # cannot restore the small stack while this one's workers are
            # still being spawned (workers all exist once every submit
            # returns — ThreadPoolExecutor spawns up to max_workers threads
            # on submission, and len(parts) >= n_threads here).
            # straggler speculation (sched/speculation.py): when enabled
            # and this query runs under a cancel token, partitions route
            # through the monitor — it launches duplicate attempts for
            # stragglers, first commit wins, the loser is cancelled with
            # reason 'speculation' through an attempt-scoped child token
            spec = None
            if cfg.SPECULATION_ENABLED.get(self.conf) and token is not None:
                from .sched.speculation import SpeculationMonitor

                spec = SpeculationMonitor.from_conf(
                    self.conf, ctx=ctx, token=token,
                    pool=getattr(self._scheduler, "pool", None),
                    n_partitions=len(parts.parts),
                )

            def _submit_task(i, t):
                if spec is None:
                    return lambda: self._run_task(
                        _token_checked(t, token, ledger), attempts, on_retry,
                        partition_id=i, token=token, ledger=ledger,
                    )

                def run_attempt(attempt_token):
                    return self._run_task(
                        _token_checked(t, attempt_token, ledger), attempts,
                        on_retry, partition_id=i, token=attempt_token,
                        ledger=ledger,
                    )

                return lambda: spec.run_partition(i, run_attempt)

            with _STACK_SIZE_LOCK:
                prev_stack = threading.stack_size(BIG_STACK_BYTES)
                try:
                    pool = ThreadPoolExecutor(max_workers=n_threads)
                    futures = [
                        pool.submit(_submit_task(i, t))
                        for i, t in enumerate(parts.parts)
                    ]
                finally:
                    threading.stack_size(prev_stack)
            try:
                results = [f.result() for f in futures]
            finally:
                pool.shutdown(wait=True)
                if spec is not None:
                    spec.close()
                self._task_retries = query_retries[0]
            batches = [rb for rbs in results for rb in rbs if rb.num_rows]
        else:
            try:
                batches.extend(
                    self._stream_parts(parts, attempts, token, on_retry, ledger)
                )
            finally:
                self._task_retries = query_retries[0]
        from .obs import ledger as obs_ledger

        schema = final_plan.output
        with obs_ledger.scope_or_null(ledger, "serialize"):
            if not batches:
                return pa.table(
                    {
                        f.name: pa.array([], type=f.data_type.to_arrow())
                        for f in schema
                    }
                )
            return pa.Table.from_batches(batches)

    def _assert_test_mode(self, overrides: TpuOverrides, plan: Exec):
        """TEST_CONF: fail when expected-on-device execs fell back
        (reference: GpuTransitionOverrides validation under TEST_CONF)."""
        if not cfg.TEST_CONF.get(self.conf):
            return
        allowed = (cfg.TEST_ALLOWED_NONTPU.get(self.conf) or "").split(",")
        allowed = {a.strip() for a in allowed if a.strip()}
        # WriteFiles encodes on the host side of D2H by design (no device
        # Parquet codec on TPU — io/writer.py docstring)
        allowed |= {
            "CpuScan",
            "CpuFileScan",
            "DeviceToHost",
            "HostToDevice",
            "WriteFiles",
        }
        bad = []
        for e in overrides.explain:
            if e.on_device:
                continue
            name = e.node.split(" ")[0].split("[")[0]
            if not any(name.startswith(a) for a in allowed):
                bad.append((e.node, e.reasons))
        if bad:
            msg = "; ".join(f"{n}: {r}" for n, r in bad)
            raise AssertionError(f"execs unexpectedly not on device: {msg}")


class DataFrameReader:
    def __init__(self, session: TpuSession):
        self._session = session
        self._options: dict = {}

    def option(self, k: str, v) -> "DataFrameReader":
        self._options[k] = v
        return self

    def _rewrite(self, paths) -> tuple:
        """spark.rapids.alluxio.pathsToReplace: 'src->dst' prefix rewrites
        applied before file listing (RapidsConf.scala:929 — route cloud
        reads through a cache mount)."""
        raw = cfg.ALLUXIO_PATHS_TO_REPLACE.get(self._session.conf)
        if not raw:
            return tuple(paths)
        rules = []
        for part in raw.split(","):
            if "->" in part:
                src, dst = part.split("->", 1)
                rules.append((src.strip(), dst.strip()))
        out = []
        for p in paths:
            for src, dst in rules:
                if p.startswith(src):
                    p = dst + p[len(src) :]
                    break
            out.append(p)
        return tuple(out)

    def _bucket_options(self, paths) -> dict:
        """Attach the _bucket_spec.json sidecar (one consistent spec across
        all roots) so the scan can bucket-prune (io/bucketing.py)."""
        import os

        from .io.bucketing import read_spec

        opts = dict(self._options)
        specs = [read_spec(p) for p in paths if os.path.isdir(p)]
        specs = [s for s in specs if s is not None]
        if specs and all(s == specs[0] for s in specs) and len(specs) == len(
            [p for p in paths if os.path.isdir(p)]
        ):
            opts["__bucket_spec"] = specs[0]
        return self._root_options(paths, opts)

    @staticmethod
    def _root_options(roots, opts: dict) -> dict:
        """Record the scan ROOTS (not just the expanded files) on the scan
        node: cache/keys.py needs them so an append that creates a NEW
        partition subdirectory under a scanned root — a directory that did
        not exist at registration time — still invalidates entries keyed
        by that root."""
        import os

        opts["__roots"] = tuple(os.path.realpath(r) for r in roots)
        return opts

    def parquet(self, *paths: str) -> "DataFrame":
        from .io.files import infer_schema, expand_paths

        roots = self._rewrite(paths)
        files = expand_paths(roots, "parquet")
        schema = infer_schema(files, "parquet", self._options)
        return DataFrame(
            self._session,
            L.FileScan(files, "parquet", schema, self._bucket_options(roots)),
        )

    def orc(self, *paths: str) -> "DataFrame":
        from .io.files import infer_schema, expand_paths

        roots = self._rewrite(paths)
        files = expand_paths(roots, "orc")
        schema = infer_schema(files, "orc", self._options)
        return DataFrame(
            self._session,
            L.FileScan(files, "orc", schema, self._bucket_options(roots)),
        )

    def csv(self, *paths: str, **kwargs) -> "DataFrame":
        from .io.files import infer_schema, expand_paths

        opts = dict(self._options)
        opts.update(kwargs)
        # shim-routed default (SparkShims seam): what string reads as NULL
        opts.setdefault("nullValue", self._session.shim.csv_null_value())
        roots = self._rewrite(paths)
        files = expand_paths(roots, "csv")
        schema = infer_schema(files, "csv", opts)
        return DataFrame(
            self._session,
            L.FileScan(files, "csv", schema, self._root_options(roots, opts)),
        )


def _to_exprs(cols: Sequence[Union[str, Column, Expression]]) -> List[Expression]:
    out = []
    for c in cols:
        if isinstance(c, str):
            out.append(UnresolvedAttribute(c))
        elif isinstance(c, Column):
            out.append(c.expr)
        else:
            out.append(c)
    return out


def _extract_windows(
    exprs: List[Expression], plan: L.LogicalPlan
) -> tuple[List[Expression], L.LogicalPlan]:
    """Pull WindowExpressions out of a projection into Window nodes below it
    (Spark's ExtractWindowExpressions): expressions sharing a
    (partition_by, order_by) spec land in one Window node; the projection
    references the appended columns."""
    from .expr.base import bind as _bind
    from .expr.base import map_child_exprs
    from .expr.windows import WindowExpression, WindowOrder, WindowSpec, contains_window

    if not any(contains_window(e) for e in exprs):
        return exprs, plan

    groups: dict = {}  # (partition_by, order_by) -> list[(name, wexpr)]
    counter = [0]
    child_schema = plan.schema

    def pull(e: Expression) -> Expression:
        if isinstance(e, WindowExpression):
            # resolve against the child schema now: the Window node's own
            # schema needs the function's type before planning
            spec = WindowSpec(
                tuple(_bind(p, child_schema) for p in e.spec.partition_by),
                tuple(
                    WindowOrder(_bind(o.child, child_schema), o.ascending, o.nulls_first)
                    for o in e.spec.order_by
                ),
                e.spec.frame,
            )
            e = WindowExpression(_bind(e.function, child_schema), spec)
            key = (spec.partition_by, spec.order_by)
            name = f"__w{counter[0]}"
            counter[0] += 1
            groups.setdefault(key, []).append((name, e))
            return UnresolvedAttribute(name)
        if not e.children():
            return e
        return map_child_exprs(e, pull)

    new_exprs = [pull(e) for e in exprs]
    for cols in groups.values():
        plan = L.Window(cols, plan)
    return new_exprs, plan


def _extract_generators(
    exprs: List[Expression], plan: L.LogicalPlan
) -> tuple[List[Expression], L.LogicalPlan]:
    """Pull a top-level explode/posexplode out of a projection into a
    Generate node below it (Spark's ExtractGenerator); the projection then
    references the generator's output columns by name."""
    from .expr.complex import Explode, contains_generator
    from .types import MapType

    if not any(contains_generator(e) for e in exprs):
        return exprs, plan
    new_exprs: List[Expression] = []
    generator = None
    internal: List[str] = []  # collision-proof Generate output names
    for e in exprs:
        alias = e.name if isinstance(e, Alias) else None
        target = e.child if isinstance(e, Alias) else e
        if isinstance(target, Explode):
            if generator is not None:
                raise ValueError("only one generator per select is supported")
            generator = target
            from .expr import bind as _bind

            ct = _bind(target.child, plan.schema).data_type
            public: List[str] = []
            if target.position:
                public.append("pos")
            if isinstance(ct, MapType):
                if alias is not None:
                    raise ValueError(
                        "explode of a map produces two columns (key, value); "
                        "select them by name instead of aliasing the explode"
                    )
                public.extend(["key", "value"])
            else:
                public.append(alias or "col")
            internal = [f"__gen{i}" for i in range(len(public))]
            new_exprs.extend(
                Alias(UnresolvedAttribute(g), p)
                for g, p in zip(internal, public)
            )
        elif contains_generator(e):
            raise ValueError("explode() must be a top-level select expression")
        else:
            new_exprs.append(e)
    return new_exprs, L.Generate(generator, internal, plan)


def _assemble_result(batches, schema) -> pa.Table:
    """Rebuild a collect() table from cached batches — the exact
    construction ``_run_plan`` uses, so cached and cold results are
    bit-identical (including the empty-result arrow schema)."""
    if not batches:
        return pa.table(
            {f.name: pa.array([], type=f.data_type.to_arrow()) for f in schema}
        )
    return pa.Table.from_batches(batches)


class DataFrame:
    def __init__(self, session: TpuSession, plan: L.LogicalPlan):
        self._session = session
        self._plan = plan

    @property
    def schema(self) -> Schema:
        return self._plan.schema

    @property
    def columns(self) -> List[str]:
        return self.schema.names

    # ── transformations ─────────────────────────────────────────────────
    def select(self, *cols) -> "DataFrame":
        exprs, plan = _extract_windows(_to_exprs(cols), self._plan)
        exprs, plan = _extract_generators(exprs, plan)
        return DataFrame(self._session, L.Project(exprs, plan))

    def cache(self) -> "DataFrame":
        """Materialize this DataFrame's result on first use and serve later
        uses from a parquet-compressed in-memory store (the
        ParquetCachedBatchSerializer analogue)."""
        import itertools

        counter = self._session.__dict__.setdefault(
            "_cache_ids", itertools.count(1)
        )
        key = next(counter)

        def parts_of(p) -> int:
            own = getattr(p, "num_partitions", 0)
            kids = [parts_of(c) for c in p.children()]
            return max([own] + kids + [1])

        return DataFrame(
            self._session,
            L.InMemoryRelation(self._plan, key, parts_of(self._plan)),
        )

    persist = cache

    def unpersist(self) -> "DataFrame":
        if isinstance(self._plan, L.InMemoryRelation):
            self._session.uncache(self._plan.cache_key)
            return DataFrame(self._session, self._plan.child)
        return self

    def map_in_pandas(self, fn, schema) -> "DataFrame":
        """``fn(iterator of pd.DataFrame) -> iterator of pd.DataFrame`` per
        partition (pyspark mapInPandas; reference GpuMapInPandasExec).
        ``schema`` declares the result columns."""
        schema = _to_schema(schema)
        return DataFrame(self._session, L.MapInPandas(fn, schema, self._plan))

    mapInPandas = map_in_pandas

    def with_column(self, name: str, c: Column) -> "DataFrame":
        exprs: List[Expression] = []
        replaced = False
        for f in self.schema:
            if f.name == name:
                exprs.append(Alias(c.expr, name))
                replaced = True
            else:
                exprs.append(UnresolvedAttribute(f.name))
        if not replaced:
            exprs.append(Alias(c.expr, name))
        exprs, plan = _extract_windows(exprs, self._plan)
        return DataFrame(self._session, L.Project(exprs, plan))

    withColumn = with_column

    def filter(self, condition: Union[Column, Expression]) -> "DataFrame":
        e = condition.expr if isinstance(condition, Column) else condition
        return DataFrame(self._session, L.Filter(e, self._plan))

    where = filter

    def group_by(self, *cols) -> "GroupedData":
        return GroupedData(self, _to_exprs(cols))

    groupBy = group_by

    def rollup(self, *cols) -> "GroupedData":
        """ROLLUP grouping sets: (all), (all-1), …, () — reference analogue:
        GpuExpandExec under the aggregate."""
        exprs = _to_exprs(cols)
        sets = [list(range(k)) for k in range(len(exprs), -1, -1)]
        return GroupedData(self, exprs, grouping_sets=sets)

    def cube(self, *cols) -> "GroupedData":
        """CUBE grouping sets: every subset of the grouping columns."""
        exprs = _to_exprs(cols)
        n = len(exprs)
        sets = [
            [i for i in range(n) if mask & (1 << i)] for mask in range(2**n - 1, -1, -1)
        ]
        return GroupedData(self, exprs, grouping_sets=sets)

    def agg(self, *aggs) -> "DataFrame":
        return GroupedData(self, []).agg(*aggs)

    def sort(self, *cols, ascending: Union[bool, List[bool]] = True) -> "DataFrame":
        orders = self._sort_orders(cols, ascending)
        return DataFrame(self._session, L.Sort(orders, True, self._plan))

    orderBy = sort
    order_by = sort

    def sort_within_partitions(self, *cols, ascending=True) -> "DataFrame":
        orders = self._sort_orders(cols, ascending)
        return DataFrame(self._session, L.Sort(orders, False, self._plan))

    def _sort_orders(self, cols, ascending) -> List[L.SortOrder]:
        exprs = _to_exprs(cols)
        if isinstance(ascending, bool):
            ascending = [ascending] * len(exprs)
        # Column.desc()/asc() markers override the ascending kwarg
        ascending = [
            False if (isinstance(c, Column) and getattr(c, "_sort_desc", False)) else a
            for c, a in zip(cols, ascending)
        ]
        return [L.SortOrder(e, a) for e, a in zip(exprs, ascending)]

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(self._session, L.Limit(n, self._plan))

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self._session, L.Union([self._plan, other._plan]))

    unionAll = union

    def repartition(self, n: int, *cols) -> "DataFrame":
        exprs = _to_exprs(cols) if cols else None
        return DataFrame(self._session, L.Repartition(n, exprs, self._plan))

    def join(
        self,
        other: "DataFrame",
        on: Union[str, List, None] = None,
        how: str = "inner",
    ) -> "DataFrame":
        how = {
            "inner": "inner",
            "left": "left",
            "left_outer": "left",
            "leftouter": "left",
            "right": "right",
            "right_outer": "right",
            "rightouter": "right",
            "outer": "full",
            "full": "full",
            "full_outer": "full",
            "cross": "cross",
            "semi": "left_semi",
            "left_semi": "left_semi",
            "leftsemi": "left_semi",
            "anti": "left_anti",
            "left_anti": "left_anti",
            "leftanti": "left_anti",
        }[how]
        lk: List[Expression] = []
        rk: List[Expression] = []
        using = False
        residual = None
        if on is None:
            pass
        elif isinstance(on, str):
            lk, rk, using = [UnresolvedAttribute(on)], [UnresolvedAttribute(on)], True
        elif isinstance(on, list) and on and isinstance(on[0], str):
            lk = [UnresolvedAttribute(n) for n in on]
            rk = [UnresolvedAttribute(n) for n in on]
            using = True
        elif isinstance(on, list) and on and isinstance(on[0], tuple):
            lk = [UnresolvedAttribute(l) for l, _ in on]
            rk = [UnresolvedAttribute(r) for _, r in on]
        elif isinstance(on, Column):
            # split a boolean condition into equi keys + residual predicate
            from .exec.cpu_join import extract_equi_join_keys

            lk, rk, residual = extract_equi_join_keys(
                on.expr, self.schema, other.schema
            )
        else:
            raise TypeError(
                "join on= must be a name, list of names, list of (l, r) pairs, "
                "or a Column condition"
            )
        return DataFrame(
            self._session,
            L.Join(self._plan, other._plan, how, lk, rk, residual, using),
        )

    def cross_join(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(
            self._session,
            L.Join(self._plan, other._plan, "cross", [], [], None, False),
        )

    def distinct(self) -> "DataFrame":
        """Spark plans Distinct as Aggregate(all columns) — same here, so it
        rides the two-phase device group-by."""
        cols = [UnresolvedAttribute(n) for n in self.schema.names]
        return DataFrame(self._session, L.Aggregate(cols, list(cols), self._plan))

    def drop(self, *cols: str) -> "DataFrame":
        """Project out the named columns (pyspark: unknown names ignored)."""
        gone = set(cols)
        keep = [n for n in self.schema.names if n not in gone]
        return self.select(*keep)

    def with_column_renamed(self, existing: str, new: str) -> "DataFrame":
        """Rename one column; no-op when absent (pyspark semantics)."""
        if existing not in self.schema.names:
            return self
        exprs = [
            Alias(UnresolvedAttribute(n), new) if n == existing else col(n)
            for n in self.schema.names
        ]
        return self.select(*exprs)

    withColumnRenamed = with_column_renamed

    def fillna(self, value, subset: Optional[List[str]] = None) -> "DataFrame":
        """Replace nulls with ``value`` in type-compatible columns
        (pyspark DataFrameNaFunctions.fill: numeric values fill numeric
        columns, strings fill strings, bools fill bools)."""
        from .expr.base import Literal
        from .expr.conditional import Coalesce
        from .types import (
            BooleanType,
            FractionalType,
            IntegralType,
            NumericType,
            StringType,
        )

        if isinstance(value, dict):
            # pyspark's per-column form: {'a': 0, 'b': 'x'}; subset is
            # documented as IGNORED for dict values
            per_col = dict(value)
            subset = None
        elif isinstance(value, (bool, int, float, str)):
            per_col = None
        else:
            raise TypeError(
                f"fillna value must be bool/int/float/str/dict, got {type(value)}"
            )

        def compatible(v, dt) -> bool:
            return (
                (isinstance(v, bool) and isinstance(dt, BooleanType))
                or (
                    isinstance(v, (int, float))
                    and not isinstance(v, bool)
                    and isinstance(dt, NumericType)
                )
                or (isinstance(v, str) and isinstance(dt, StringType))
            )

        names = set(subset) if subset is not None else None
        exprs: List[Expression] = []
        for f in self.schema:
            dt = f.data_type
            if per_col is not None:
                v = per_col.get(f.name)
                applies = v is not None and compatible(v, dt)
            else:
                v = value
                applies = (names is None or f.name in names) and compatible(v, dt)
            if applies:
                if isinstance(dt, FractionalType):
                    v = float(v)
                elif isinstance(dt, IntegralType) and not isinstance(v, bool):
                    v = int(v)
                exprs.append(
                    Alias(
                        Coalesce(
                            (UnresolvedAttribute(f.name), Literal(v, dt))
                        ),
                        f.name,
                    )
                )
            else:
                exprs.append(UnresolvedAttribute(f.name))
        return self.select(*exprs)

    def dropna(
        self,
        how: str = "any",
        thresh: Optional[int] = None,
        subset: Optional[List[str]] = None,
    ) -> "DataFrame":
        """Drop rows with nulls (pyspark DataFrameNaFunctions.drop):
        ``how='any'`` drops rows with any null among the subset,
        ``'all'`` only all-null rows; ``thresh`` keeps rows with at least
        that many non-nulls."""
        from .expr.base import Literal
        from .expr.conditional import If
        from .types import INT

        if how not in ("any", "all"):
            raise ValueError(f"how must be 'any' or 'all', got {how!r}")
        names = subset if subset is not None else list(self.schema.names)
        if not names:
            return self
        non_null_count: Optional[Expression] = None
        for n in names:
            one = If(
                _e(col(n).is_not_null()), Literal(1, INT), Literal(0, INT)
            )
            non_null_count = (
                one
                if non_null_count is None
                else _e(Column(non_null_count) + Column(one))
            )
        if thresh is None:
            thresh = len(names) if how == "any" else 1
        return self.filter(Column(non_null_count) >= thresh)

    def sample(self, *args, **kwargs) -> "DataFrame":
        """Bernoulli sample. Accepts pyspark's signatures:
        ``sample(fraction, seed=0)`` or
        ``sample(withReplacement, fraction, seed)`` (replacement must be
        falsy — with-replacement sampling is not implemented)."""
        from .functions import rand as rand_fn

        a = list(args)
        with_replacement = kwargs.pop("withReplacement", None)
        if a and isinstance(a[0], bool):
            with_replacement = a.pop(0)
        if with_replacement:
            raise NotImplementedError(
                "sample(withReplacement=True) is not supported"
            )
        fraction = kwargs.get("fraction", a[0] if a else None)
        if fraction is None:
            raise TypeError("sample() requires a fraction")
        seed = kwargs.get("seed", a[1] if len(a) > 1 else 0)
        return self.filter(rand_fn(int(seed)) < float(fraction))

    def head(self, n: Optional[int] = None):
        """pyspark: head() → first row or None; head(n) → list of rows
        (including head(1) → one-element list)."""
        if n is None:
            rows = self.limit(1).collect()
            return rows[0] if rows else None
        return self.limit(n).collect()

    def first(self):
        """pyspark: first() == head() — a single row, or None when empty."""
        return self.head()

    def take(self, n: int) -> List[tuple]:
        return self.limit(n).collect()

    def show(self, n: int = 20, truncate: bool = True) -> None:
        """Print the first ``n`` rows in pyspark's grid format."""
        rows = self.limit(n).collect()
        names = list(self.schema.names)
        def fmt(v):
            s = "null" if v is None else str(v)
            return s[:17] + "..." if truncate and len(s) > 20 else s
        table = [[fmt(v) for v in r] for r in rows]
        widths = [
            max(len(names[i]), *(len(r[i]) for r in table)) if table else len(names[i])
            for i in range(len(names))
        ]
        sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
        print(sep)
        print("|" + "|".join(f" {names[i]:<{widths[i]}} " for i in range(len(names))) + "|")
        print(sep)
        for r in table:
            print("|" + "|".join(f" {r[i]:<{widths[i]}} " for i in range(len(names))) + "|")
        print(sep)

    def _set_op(self, other: "DataFrame", keep_matched: bool) -> "DataFrame":
        """Null-safe INTERSECT/EXCEPT: tag each side, union, group by all
        columns (GROUP BY treats nulls as equal — exactly Spark's set-op
        null semantics, which a hash join's null-skipping keys would NOT
        give), then filter on side presence."""
        from .functions import lit, max as max_fn

        names = list(self.schema.names)
        left = self.with_column("__side_l", lit(1)).with_column("__side_r", lit(0))
        right = other.with_column("__side_l", lit(0)).with_column("__side_r", lit(1))
        grouped = (
            left.union(right)
            .group_by(*names)
            .agg(
                max_fn(col("__side_l")).alias("__hl"),
                max_fn(col("__side_r")).alias("__hr"),
            )
        )
        cond = (col("__hl") == 1) & (
            (col("__hr") == 1) if keep_matched else (col("__hr") == 0)
        )
        return grouped.filter(cond).select(*names)

    def intersect(self, other: "DataFrame") -> "DataFrame":
        """Distinct rows present in both frames (Spark INTERSECT,
        null-safe: a (null, 1) row on both sides IS returned)."""
        return self._set_op(other, keep_matched=True)

    def subtract(self, other: "DataFrame") -> "DataFrame":
        """Distinct rows of this frame absent from the other (Spark
        EXCEPT, null-safe)."""
        return self._set_op(other, keep_matched=False)

    def drop_duplicates(self, subset: Optional[List[str]] = None) -> "DataFrame":
        if subset is None:
            return self.distinct()
        from .functions import first as first_fn

        keys = [UnresolvedAttribute(n) for n in subset]
        keep = set(subset)
        # output preserves the original column order (pyspark semantics)
        aggs: List[Expression] = []
        for f in self.schema:
            if f.name in keep:
                aggs.append(UnresolvedAttribute(f.name))
            else:
                aggs.append(Alias(first_fn(col(f.name)).expr, f.name))
        return DataFrame(self._session, L.Aggregate(keys, aggs, self._plan))

    dropDuplicates = drop_duplicates

    def create_or_replace_temp_view(self, name: str) -> None:
        self._session.create_or_replace_temp_view(name, self)

    createOrReplaceTempView = create_or_replace_temp_view

    def to_jax(self):
        """Zero-copy device export: run the query and hand out the LIVE
        device-resident result as one :class:`DeviceBatch` — a jax pytree
        (per-column ``data``/``validity``/``lengths`` arrays) consumable by
        a jitted function with NO host round trip. The TPU-natural analogue
        of the reference's ML export path (ColumnarRdd.scala,
        InternalColumnarRddConverter.scala:1-579, docs/ml-integration.md),
        where cuDF tables are handed to XGBoost without leaving the GPU.

        The batch is padded to capacity: rows ``[0, num_rows)`` are live
        (``num_rows`` is a device scalar — ``row_count()`` syncs it);
        padding rows have ``validity == False``. Use ``batch.by_name(c)``
        for column access.
        """
        from .exec.tpu import DeviceToHostExec
        from .ops.concat import concat_device
        from .ops.gather import bulk_shrink

        final_plan, ctx = self._session._prepare_plan(self._plan)
        plan = final_plan
        if isinstance(plan, DeviceToHostExec):
            plan = plan.children[0]
        else:
            raise ValueError(
                "to_jax(): plan does not end on the device (fell back to "
                "CPU?) — use to_arrow() instead"
            )
        try:
            # device export rides the same admission control as collect():
            # its result stays resident in HBM, exactly what the permit
            # pool is budgeting
            with self._session._scheduler.admit(
                f"q{ctx.query_seq}", final_plan, self._session.conf
            ) as admission:
                ctx.cancel_token = admission.token
                parts = plan.execute(ctx)
                # same retry model as collect(): partition thunks re-run
                # from lineage on transient failures (spark.task.maxFailures)
                # — with the same per-QUERY retry accounting (a concurrent
                # collect's counter must not be clobbered mid-flight)
                attempts = cfg.TASK_MAX_FAILURES.get(self._session.conf)
                query_retries = [0]

                def on_retry():
                    with self._session._retry_lock:
                        query_retries[0] += 1

                try:
                    batches = [
                        db
                        for i, t in enumerate(parts.parts)
                        for db in self._session._run_task(
                            t, attempts, on_retry, partition_id=i,
                            token=admission.token,
                        )
                    ]
                finally:
                    self._session._task_retries = query_retries[0]
            batches = [b for b in bulk_shrink(batches) if b.capacity]
            if not batches:
                from .columnar.device import empty_batch

                return empty_batch(plan.output)
            if len(batches) == 1:
                return batches[0]
            return concat_device(batches)
        finally:
            self._session._leak_check(ctx)

    # ── actions ─────────────────────────────────────────────────────────
    def to_arrow(self) -> pa.Table:
        return self._session._execute(self._plan)

    def collect(self) -> List[tuple]:
        t = self.to_arrow()
        from . import native

        rows = native.rows_decode(t)  # C row assembly (srt_rows.cc)
        if rows is not None:
            return rows
        cols = [c.to_pylist() for c in t.columns]
        return [tuple(c[i] for c in cols) for i in range(t.num_rows)]

    def count(self) -> int:
        from .functions import count as count_fn

        t = self.agg(count_fn("*").alias("count")).to_arrow()
        return t.column(0)[0].as_py()

    def explain(self, mode: str = "plans") -> str:
        if mode == "metrics":
            # reference-style: per-op metrics inline on the physical plan
            # (the Spark-UI node annotations). Metrics live on the EXECUTED
            # plan instance, so this renders the session's last run —
            # collect() first (matching the UI, which is also post-run).
            from .obs.export import render_ledger, render_plan_metrics

            plan = self._session._last_plan
            if plan is None:
                s = "<no query executed yet — collect() first>"
            else:
                # every collected metric (ESSENTIAL always; MODERATE/DEBUG
                # when the level conf collected them), headed by the host-
                # overhead ledger: where the last query's wall clock went
                s = render_plan_metrics(plan)
                led = render_ledger(
                    getattr(self._session, "_last_ledger", None)
                )
                if led:
                    s = led + "\n" + s
            print(s)
            return s
        cpu_plan = plan_physical(self._plan, self._session.conf)
        overrides = TpuOverrides(self._session.conf)
        final_plan = overrides.apply(cpu_plan)
        s = final_plan.tree_string()
        print(s)
        return s

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    toPandas = to_pandas

    @property
    def write(self):
        from .io.writer import DataFrameWriter

        return DataFrameWriter(self)


def _to_schema(schema) -> Schema:
    """Accept a Schema, a pyspark-style DDL string (``"a long, b double"``),
    or a list of (name, DataType) pairs / StructFields."""
    from .types import StructField

    if isinstance(schema, Schema):
        return schema
    if isinstance(schema, str):
        from .types import parse_ddl_schema

        return parse_ddl_schema(schema)
    fields = []
    for f in schema:
        if isinstance(f, StructField):
            fields.append(f)
        else:
            name, dt = f
            fields.append(StructField(name, dt, True))
    return Schema(fields)


GROUPING_ID = "__grouping_id"


class GroupedData:
    def __init__(
        self,
        df: DataFrame,
        grouping: List[Expression],
        grouping_sets: Optional[List[List[int]]] = None,
        pivot: Optional[tuple] = None,
    ):
        self._df = df
        self._grouping = grouping
        self._grouping_sets = grouping_sets
        self._pivot = pivot

    def pivot(self, pivot_col: str, values: Optional[list] = None) -> "GroupedData":
        """Pivot on ``pivot_col`` — Catalyst's RewritePivot shape: each
        (value, aggregate) pair becomes ``agg(if(p <=> value, x, null))``
        (reference analogue: GpuPivotFirst); ``count`` yields null for
        absent (group, value) combinations like Spark's DataFrame pivot.
        When ``values`` is omitted they are collected eagerly from the data
        (sorted, like Spark's auto-detection)."""
        if self._grouping_sets is not None:
            raise ValueError("pivot is only supported after a groupBy")
        if values is None:
            key = UnresolvedAttribute(pivot_col)
            vals_df = DataFrame(
                self._df._session, L.Aggregate([key], [key], self._df._plan)
            )
            collected = [v for (v,) in vals_df.collect()]
            non_null = sorted(v for v in collected if v is not None)
            values = non_null + ([None] if None in collected else [])
        return GroupedData(self._df, self._grouping, pivot=(pivot_col, values))

    def _expand_pivot(self, agg_exprs: List[Expression]) -> List[Expression]:
        import dataclasses as _dc

        from .expr.aggregates import AggregateFunction
        from .expr.base import Literal, map_child_exprs, to_expr
        from .expr.conditional import If
        from .expr.predicates import EqualNullSafe
        from .types import NULL

        pcol, values = self._pivot

        def wrap(e: Expression, v) -> Expression:
            if isinstance(e, AggregateFunction):
                from .expr.aggregates import Count
                from .expr.predicates import GreaterThan

                cond = EqualNullSafe(UnresolvedAttribute(pcol), to_expr(v))
                guarded = If(cond, e.child, Literal(None, NULL))
                agg = _dc.replace(e, child=guarded)
                if isinstance(e, Count):
                    # Spark's DataFrame pivot (PivotFirst / GpuPivotFirst)
                    # yields NULL, not 0, when no input row matched the
                    # pivot value; gate the count on a matched-row count
                    matched = _dc.replace(
                        e, child=If(cond, to_expr(1), Literal(None, NULL))
                    )
                    return If(
                        GreaterThan(matched, to_expr(0)), agg, Literal(None, NULL)
                    )
                return agg
            if not e.children():
                return e
            return map_child_exprs(e, lambda c: wrap(c, v))

        out: List[Expression] = []
        multiple = len(agg_exprs) > 1
        for v in values:
            for a in agg_exprs:
                base = str(v) if v is not None else "null"
                name = f"{base}_{output_name(a)}" if multiple else base
                target = a.child if isinstance(a, Alias) else a
                out.append(Alias(wrap(target, v), name))
        return out

    def apply_in_pandas(self, fn, schema) -> DataFrame:
        """``fn(pd.DataFrame) -> pd.DataFrame`` once per key group (pyspark
        applyInPandas; reference GpuFlatMapGroupsInPandasExec). Grouping
        must be plain columns; ``schema`` declares the result columns."""
        if self._grouping_sets is not None or self._pivot is not None:
            raise ValueError("apply_in_pandas requires a plain groupBy")
        names = []
        for g in self._grouping:
            if not isinstance(g, UnresolvedAttribute):
                raise ValueError(
                    "apply_in_pandas grouping must be plain columns"
                )
            names.append(g.name)
        schema = _to_schema(schema)
        return DataFrame(
            self._df._session,
            L.FlatMapGroupsInPandas(names, fn, schema, self._df._plan),
        )

    applyInPandas = apply_in_pandas

    def cogroup(self, other: "GroupedData") -> "CoGroupedData":
        """Pair this grouped frame with another for
        ``cogroup(...).apply_in_pandas(fn, schema)`` (pyspark cogroup;
        reference GpuFlatMapCoGroupsInPandasExec)."""
        if not isinstance(other, GroupedData):
            raise TypeError("cogroup expects another groupBy()")
        return CoGroupedData(self, other)

    def _plain_key_names(self, what: str) -> List[str]:
        if self._grouping_sets is not None or self._pivot is not None:
            raise ValueError(f"{what} requires a plain groupBy")
        names = []
        for g in self._grouping:
            if not isinstance(g, UnresolvedAttribute):
                raise ValueError(f"{what} grouping must be plain columns")
            names.append(g.name)
        return names

    def _agg_in_pandas(self, agg_exprs: List[Expression]) -> DataFrame:
        """GROUPED_AGG pandas UDF route: pre-project key + argument columns,
        then AggregateInPandas evaluates one scalar per (group, udf)."""
        from .expr.udf import GroupedAggUdf
        from .types import StructField

        keys = self._plain_key_names("grouped-agg pandas UDFs")
        proj: List[Expression] = [UnresolvedAttribute(n) for n in keys]
        udfs = []
        out_fields = []
        child_schema = self._df.schema
        for n in keys:
            out_fields.append(StructField(n, child_schema[n].data_type, True))
        for i, a in enumerate(agg_exprs):
            target = a.child if isinstance(a, Alias) else a
            if not isinstance(target, GroupedAggUdf):
                raise ValueError(
                    "grouped-agg pandas UDFs cannot be mixed with other "
                    f"aggregates in one agg() (got {a})"
                )
            arg_names = []
            for j, arg in enumerate(target.args):
                nm = f"__pagg_arg{i}_{j}"
                proj.append(Alias(arg, nm))
                arg_names.append(nm)
            out_name = output_name(a)
            udfs.append((out_name, target.fn, target.return_type, arg_names))
            out_fields.append(StructField(out_name, target.return_type, True))
        projected = L.Project(proj, self._df._plan)
        return DataFrame(
            self._df._session,
            L.AggregateInPandas(keys, udfs, Schema(out_fields), projected),
        )

    def agg(self, *aggs) -> DataFrame:
        agg_exprs = []
        for a in aggs:
            e = a.expr if isinstance(a, Column) else a
            agg_exprs.append(e)
        from .expr.udf import GroupedAggUdf

        def _has_grouped_agg(e) -> bool:
            stack = [e]
            while stack:
                x = stack.pop()
                if isinstance(x, GroupedAggUdf):
                    return True
                stack.extend(x.children())
            return False

        if any(_has_grouped_agg(a) for a in agg_exprs):
            return self._agg_in_pandas(agg_exprs)
        if self._pivot is not None:
            agg_exprs = self._expand_pivot(agg_exprs)
        if self._grouping_sets is not None:
            return self._agg_grouping_sets(agg_exprs)
        # Spark: group-by output = grouping columns ++ aggregates
        all_out = list(self._grouping) + agg_exprs
        return DataFrame(
            self._df._session,
            L.Aggregate(self._grouping, all_out, self._df._plan),
        )

    def _agg_grouping_sets(self, agg_exprs: List[Expression]) -> DataFrame:
        """rollup/cube: Expand fans each row out once per grouping set with
        non-member keys nulled and a grouping-id tiebreaker column, then a
        plain aggregate groups on [keys…, grouping_id] (Spark's
        ResolveGroupingAnalytics → Expand plan; reference GpuExpandExec)."""
        from .expr import Literal
        from .types import INT

        child_schema = self._df.schema
        n_keys = len(self._grouping)
        names = list(child_schema.names)
        key_names = [f"__key{i}" for i in range(n_keys)]
        out_names = names + key_names + [GROUPING_ID]
        projections: List[List[Expression]] = []
        for s in self._grouping_sets:
            proj: List[Expression] = [UnresolvedAttribute(nm) for nm in names]
            for i, g in enumerate(self._grouping):
                if i in s:
                    proj.append(Alias(g, key_names[i]))
                else:
                    from .expr import bind as _bind

                    dt = _bind(g, child_schema).data_type
                    proj.append(Alias(Literal(None, dt), key_names[i]))
            gid = sum((1 << (n_keys - 1 - i)) for i in range(n_keys) if i not in s)
            proj.append(Alias(Literal(gid, INT), GROUPING_ID))
            projections.append(proj)
        expand = L.Expand(projections, out_names, self._df._plan)
        grouping = [UnresolvedAttribute(nm) for nm in key_names] + [
            UnresolvedAttribute(GROUPING_ID)
        ]
        # output: original grouping names, then aggregates (gid internal)
        out_keys = [
            Alias(UnresolvedAttribute(kn), output_name(g))
            for kn, g in zip(key_names, self._grouping)
        ]
        # aggregate inputs read the ORIGINAL columns (passed through Expand
        # unchanged), exactly like Spark's grouping-analytics plan
        return DataFrame(
            self._df._session,
            L.Aggregate(grouping, out_keys + agg_exprs, expand),
        )

    def count(self) -> DataFrame:
        from .functions import count as count_fn

        return self.agg(count_fn("*").alias("count"))

    def sum(self, *names: str) -> DataFrame:
        from .functions import sum as sum_fn

        return self.agg(*[sum_fn(col(n)).alias(f"sum({n})") for n in names])

    def avg(self, *names: str) -> DataFrame:
        from .functions import avg as avg_fn

        return self.agg(*[avg_fn(col(n)).alias(f"avg({n})") for n in names])

    def min(self, *names: str) -> DataFrame:
        from .functions import min as min_fn

        return self.agg(*[min_fn(col(n)).alias(f"min({n})") for n in names])

    def max(self, *names: str) -> DataFrame:
        from .functions import max as max_fn

        return self.agg(*[max_fn(col(n)).alias(f"max({n})") for n in names])


class CoGroupedData:
    """Two co-grouped frames awaiting ``apply_in_pandas`` (pyspark
    ``GroupedData.cogroup``; reference GpuFlatMapCoGroupsInPandasExec)."""

    def __init__(self, left: GroupedData, right: GroupedData):
        self._left = left
        self._right = right

    def apply_in_pandas(self, fn, schema) -> DataFrame:
        """``fn(left_pd, right_pd) -> pd.DataFrame`` once per key group
        present on either side; an absent side arrives as an empty frame
        with that side's columns."""
        lk = self._left._plain_key_names("cogroup apply_in_pandas")
        rk = self._right._plain_key_names("cogroup apply_in_pandas")
        if len(lk) != len(rk):
            raise ValueError(
                f"cogroup key counts differ: {lk} vs {rk}"
            )
        schema = _to_schema(schema)
        return DataFrame(
            self._left._df._session,
            L.FlatMapCoGroupsInPandas(
                lk, rk, fn, schema, self._left._df._plan, self._right._df._plan
            ),
        )

    applyInPandas = apply_in_pandas
