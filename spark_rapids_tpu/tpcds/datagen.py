"""TPC-DS table generator (dsdgen-shaped, vectorized numpy, deterministic).

All 24 tables of the TPC-DS schema at any scale factor. Cardinalities follow
the spec's SF scaling (facts scale linearly, dimensions with the spec's
sub-linear steps, date/time dims are fixed); value domains cover everything
the 99 queries filter on: d_year 1998-2002 with moy/dom/qoy/week_seq chains,
the ten item categories with class/brand/manufact hierarchies, the real
cd_gender x cd_marital_status x cd_education_status cross product,
hd_buy_potential bands, ca_state/ca_gmt_offset/ca_county geography,
promotion channel flags, and returns tables generated as samples of their
sales fact (so ticket/order-number join chains in q17/q25/q29/q64 are
non-vacuous). Monetary columns are float64 (the "useDoubleForDecimal"
columnar-benchmark configuration), matching the TPC-H generator.

Reference anchor: the reference has no in-tree TPC-DS generator; its
benchmark shape is integration_tests/.../mortgage/Benchmarks.scala. This is
the engine's own north-star rig (BASELINE.md).
"""
from __future__ import annotations

import os
from datetime import date, timedelta
from typing import Callable, Dict, List

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

EPOCH = date(1970, 1, 1)


def _days(y: int, m: int, d: int) -> int:
    return (date(y, m, d) - EPOCH).days


# date_dim covers 1997..2003 — every query predicate lands in 1998-2002
DATE_LO = _days(1997, 1, 1)
DATE_HI = _days(2003, 12, 31)
N_DATES = DATE_HI - DATE_LO + 1
# d_date_sk is dsdgen's julian-day-shaped dense surrogate
SK_BASE = 2450000

CATEGORIES = [
    "Books", "Children", "Electronics", "Home", "Jewelry",
    "Men", "Music", "Shoes", "Sports", "Women",
]
CLASSES_PER_CAT = 8
COLORS = [
    "white", "black", "red", "blue", "green", "yellow", "purple", "brown",
    "pink", "orange", "gray", "cream", "navy", "khaki", "salmon", "beige",
    "maroon", "olive", "turquoise", "azure", "chocolate", "coral", "ivory",
    "linen", "plum", "tan", "violet", "wheat", "snow", "misty", "powder",
    "honeydew", "floral", "deep", "light", "cornflower", "midnight", "cyan",
    "papaya", "frosted", "forest", "ghost", "pale", "peach", "metallic",
    "burnished", "spring", "sky", "steel", "seashell",
]
SIZES = ["small", "medium", "large", "extra large", "economy", "N/A", "petite"]
UNITS = [
    "Each", "Dozen", "Case", "Pallet", "Gross", "Box", "Bunch", "Carton",
    "Cup", "Dram", "Gram", "Lb", "Oz", "Ounce", "Pound", "Tbl", "Ton", "Tsp",
    "N/A", "Unknown",
]
GENDERS = ["M", "F"]
MARITAL = ["M", "S", "D", "W", "U"]
EDUCATION = [
    "Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
    "Advanced Degree", "Unknown",
]
CREDIT = ["Low Risk", "High Risk", "Good", "Unknown"]
BUY_POTENTIAL = [">10000", "501-1000", "Unknown", "0-500", "1001-5000",
                 "5001-10000"]
STATES = [
    "AL", "AR", "AZ", "CA", "CO", "CT", "FL", "GA", "IA", "IL", "IN", "KS",
    "KY", "LA", "MA", "MD", "MI", "MN", "MO", "MS", "NC", "ND", "NE", "NJ",
    "NM", "NY", "OH", "OK", "OR", "PA", "SC", "SD", "TN", "TX", "UT", "VA",
    "WA", "WI", "WV",
]
COUNTIES = [
    "Ziebach County", "Williamson County", "Walker County", "Salem County",
    "Raleigh County", "Oglethorpe County", "Mobile County", "Luce County",
    "Huron County", "Franklin Parish", "Fairfield County", "Dauphin County",
    "Bronx County", "Barrow County", "Arthur County",
]
CITIES = [
    "Midway", "Fairview", "Oak Grove", "Five Points", "Centerville",
    "Liberty", "Pleasant Hill", "Riverside", "Bethel", "Clinton",
    "Springfield", "Union", "Salem", "Greenfield", "Franklin", "Oakland",
    "Glendale", "Marion", "Shiloh", "Lebanon", "Antioch", "Hopewell",
    "Friendship", "Concord", "Harmony", "Pine Grove", "Greenwood",
    "Sulphur Springs", "Wildwood", "Lakeside", "Plainview", "Edgewood",
]
STREET_TYPES = ["Street", "Avenue", "Boulevard", "Circle", "Court", "Drive",
                "Lane", "Parkway", "Road", "Way"]
STREET_NAMES = ["Main", "Oak", "Park", "First", "Second", "Cedar", "Elm",
                "Maple", "Pine", "Washington", "Lake", "Hill", "Walnut",
                "Spring", "North", "Ridge", "River", "Sunset", "Railroad",
                "Church", "Willow", "Mill", "Forest", "Jackson", "Highland"]
COUNTRIES = [
    "United States", "Canada", "Mexico", "Germany", "France", "Japan",
    "United Kingdom", "Brazil", "India", "China", "Italy", "Spain",
    "Netherlands", "Australia", "Argentina", "Chile", "Peru", "Egypt",
    "Kenya", "Nigeria", "Norway", "Sweden", "Poland", "Portugal", "Greece",
    "Turkey", "Israel", "Jordan", "Thailand", "Vietnam",
]
# one shared low-cardinality zip pool across store/address/warehouse tables:
# zip-equality joins (q8/q19/q24) stay non-vacuous at tiny SF, and the zips
# the q8 template names literally all exist
ZIPS = [
    "24128", "57834", "13354", "15734", "78668", "76232", "62878", "82235",
    "78890", "60512", "26233", "51200", "63837", "40558", "81989", "88190",
    "35474", "10003", "10004", "10005", "10006", "10007", "10008", "10009",
] + [f"{z:05d}" for z in range(20000, 20176)]
MEALS = ["breakfast", "lunch", "dinner"]
SHIFTS = ["first", "second", "third"]
AM_PM = ["AM", "PM"]
SM_TYPES = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "LIBRARY", "TWO DAY"]
SM_CODES = ["AIR", "SURFACE", "SEA"]
SM_CARRIERS = [
    "UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS", "ZHOU", "ZOUROS",
    "MSC", "LATVIAN", "ALLIANCE", "ORIENTAL", "BARIAN", "BOXBUNDLES",
    "GREAT EASTERN", "DIAMOND", "RUPEKSA", "GERMA", "HARMSTORF", "PRIVATECARRIER",
]
REASONS = [
    "Package was damaged", "Stopped working", "Did not get it on time",
    "Not the product that was ordred", "Parts missing",
    "Does not work with a product that I have", "Gift exchange",
    "Did not like the color", "Did not like the model",
    "Did not like the make", "Did not like the warranty",
    "No service location in my area", "Found a better price in a store",
    "Found a better extended warranty in a store", "Not working any more",
    "unauthoized purchase", "duplicate purchase", "its is a fraudulent purchase",
    "it didn't fit my face", "reason 20", "reason 21", "reason 22",
    "reason 23", "reason 24", "reason 25", "reason 26", "reason 27",
    "reason 28", "reason 29", "reason 30", "reason 31", "reason 32",
    "reason 33", "reason 34", "reason 35",
]
FIRST_NAMES = [
    "James", "John", "Robert", "Michael", "William", "David", "Mary",
    "Patricia", "Linda", "Barbara", "Elizabeth", "Jennifer", "Maria",
    "Susan", "Margaret", "Dorothy", "Richard", "Charles", "Joseph",
    "Thomas", "Lisa", "Nancy", "Karen", "Betty", "Helen", "Daniel",
    "Matthew", "Anthony", "Mark", "Donald", "Paul", "Steven", "George",
    "Kenneth", "Sandra", "Donna", "Carol", "Ruth", "Sharon", "Michelle",
]
LAST_NAMES = [
    "Smith", "Johnson", "Williams", "Brown", "Jones", "Garcia", "Miller",
    "Davis", "Rodriguez", "Martinez", "Hernandez", "Lopez", "Gonzalez",
    "Wilson", "Anderson", "Thomas", "Taylor", "Moore", "Jackson", "Martin",
    "Lee", "Perez", "Thompson", "White", "Harris", "Sanchez", "Clark",
    "Ramirez", "Lewis", "Robinson", "Walker", "Young", "Allen", "King",
    "Wright", "Scott", "Torres", "Nguyen", "Hill", "Flores",
]

# SF-1 cardinalities (facts linear in SF; dims use dsdgen's sub-linear
# steps approximated as sqrt; date/time/demographics fixed)
_SF1 = {
    "store_sales": 2_880_000,
    "store_returns": 288_000,
    "catalog_sales": 1_440_000,
    "catalog_returns": 144_000,
    "web_sales": 720_000,
    "web_returns": 72_000,
    "inventory": 783_000,
    "customer": 100_000,
    "customer_address": 50_000,
    "item": 18_000,
    "promotion": 300,
    "store": 12,
    "warehouse": 5,
    "call_center": 6,
    "web_site": 30,
    "web_page": 60,
    "catalog_page": 11_718,
}

TABLES = [
    "date_dim", "time_dim", "item", "customer", "customer_address",
    "customer_demographics", "household_demographics", "income_band",
    "store", "warehouse", "call_center", "web_site", "web_page",
    "catalog_page", "promotion", "reason", "ship_mode",
    "store_sales", "store_returns", "catalog_sales", "catalog_returns",
    "web_sales", "web_returns", "inventory",
]


def _n(name: str, sf: float, linear: bool) -> int:
    base = _SF1[name]
    if linear:
        return max(10, int(base * sf))
    # dimensions scale ~ with sqrt(SF) like dsdgen's stepped scaling
    return max(10, int(base * (sf ** 0.5)))


def _money(rng, lo, hi, n):
    return np.round(rng.uniform(lo, hi, n), 2)


def _pick(rng, values: List[str], n: int) -> pa.Array:
    idx = rng.integers(0, len(values), n)
    return pa.array([values[i] for i in idx])


def _id_col(prefix: str, n: int) -> pa.Array:
    return pa.array([f"{prefix}{i:016d}" for i in range(1, n + 1)])


def _date32(days: np.ndarray) -> pa.Array:
    return pa.array(days.astype("int32"), type=pa.date32())


def _sk(days: np.ndarray) -> np.ndarray:
    return (days - DATE_LO + SK_BASE).astype(np.int64)


def _null_some(rng, arr: np.ndarray, frac: float) -> pa.Array:
    """Null out ~frac of an int64 fk column (dsdgen leaves fk gaps too)."""
    mask = rng.random(len(arr)) < frac
    return pa.array([None if m else int(v) for m, v in zip(mask, arr)],
                    type=pa.int64())


# ── dimensions ─────────────────────────────────────────────────────────────


def _gen_date_dim(sf, rng) -> pa.Table:
    days = np.arange(DATE_LO, DATE_HI + 1, dtype=np.int64)
    dates = [EPOCH + timedelta(days=int(d)) for d in days]
    years = np.array([d.year for d in dates], np.int64)
    moy = np.array([d.month for d in dates], np.int64)
    dom = np.array([d.day for d in dates], np.int64)
    dow = np.array([(d.weekday() + 1) % 7 for d in dates], np.int64)  # 0=Sunday
    qoy = (moy - 1) // 3 + 1
    week_seq = ((days - DATE_LO) // 7 + 5270).astype(np.int64)
    month_seq = ((years - 1970) * 12 + moy - 1).astype(np.int64)
    quarter_seq = ((years - 1970) * 4 + qoy - 1).astype(np.int64)
    day_names = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
                 "Friday", "Saturday"]
    first_dom = np.array([_days(d.year, d.month, 1) for d in dates], np.int64)
    return pa.table({
        "d_date_sk": _sk(days),
        "d_date_id": _id_col("AAAAAAAA", len(days)),
        "d_date": _date32(days),
        "d_month_seq": month_seq,
        "d_week_seq": week_seq,
        "d_quarter_seq": quarter_seq,
        "d_year": years,
        "d_dow": dow,
        "d_moy": moy,
        "d_dom": dom,
        "d_qoy": qoy,
        "d_fy_year": years,
        "d_fy_quarter_seq": quarter_seq,
        "d_fy_week_seq": week_seq,
        "d_day_name": pa.array([day_names[i] for i in dow]),
        "d_quarter_name": pa.array([f"{y}Q{q}" for y, q in zip(years, qoy)]),
        "d_holiday": pa.array(["N"] * len(days)),
        "d_weekend": pa.array(["Y" if i in (0, 6) else "N" for i in dow]),
        "d_following_holiday": pa.array(["N"] * len(days)),
        "d_first_dom": _sk(first_dom),
        "d_last_dom": _sk(first_dom + 27),
        "d_same_day_ly": _sk(np.maximum(days - 365, DATE_LO)),
        "d_same_day_lq": _sk(np.maximum(days - 91, DATE_LO)),
        "d_current_day": pa.array(["N"] * len(days)),
        "d_current_week": pa.array(["N"] * len(days)),
        "d_current_month": pa.array(["N"] * len(days)),
        "d_current_quarter": pa.array(["N"] * len(days)),
        "d_current_year": pa.array(["N"] * len(days)),
    })


def _gen_time_dim(sf, rng) -> pa.Table:
    # one row per minute of the day (queries bucket by hour/meal/shift)
    secs = np.arange(0, 86400, 60, dtype=np.int64)
    hours = secs // 3600
    minutes = (secs % 3600) // 60
    shift = np.where(hours < 8, 2, np.where(hours < 16, 0, 1))
    meal = np.where(
        (hours >= 6) & (hours < 9), 0,
        np.where((hours >= 11) & (hours < 14), 1,
                 np.where((hours >= 17) & (hours < 20), 2, -1)),
    )
    return pa.table({
        "t_time_sk": secs,
        "t_time_id": _id_col("AAAAAAAA", len(secs)),
        "t_time": secs,
        "t_hour": hours,
        "t_minute": minutes,
        "t_second": np.zeros(len(secs), np.int64),
        "t_am_pm": pa.array([AM_PM[0] if h < 12 else AM_PM[1] for h in hours]),
        "t_shift": pa.array([SHIFTS[i] for i in shift]),
        "t_sub_shift": pa.array([SHIFTS[i] for i in shift]),
        "t_meal_time": pa.array(
            [MEALS[i] if i >= 0 else None for i in meal]
        ),
    })


def _gen_item(sf, rng) -> pa.Table:
    n = _n("item", sf, linear=False)
    cat_idx = rng.integers(0, len(CATEGORIES), n)
    class_idx = rng.integers(0, CLASSES_PER_CAT, n)
    brand_id = (cat_idx + 1) * 1_000_000 + class_idx * 1000 + rng.integers(1, 10, n)
    manu_id = rng.integers(1, 1001, n)
    price = _money(rng, 0.5, 300.0, n)
    rec_start = np.full(n, _days(1997, 1, 1), np.int64)
    return pa.table({
        "i_item_sk": np.arange(1, n + 1, dtype=np.int64),
        "i_item_id": _id_col("AAAAAAAA", n),
        "i_rec_start_date": _date32(rec_start),
        "i_rec_end_date": pa.array([None] * n, type=pa.date32()),
        "i_item_desc": _pick(rng, [
            "carefully packed product", "bright popular gadget",
            "durable household staple", "imported seasonal special",
            "classic bestselling title", "quiet reliable tool",
            "colorful youth favorite", "premium branded accessory",
        ], n),
        "i_current_price": price,
        "i_wholesale_cost": np.round(price * rng.uniform(0.4, 0.8, n), 2),
        "i_brand_id": brand_id.astype(np.int64),
        "i_brand": pa.array([f"brandbrand#{b % 100000}" for b in brand_id]),
        "i_class_id": class_idx.astype(np.int64) + 1,
        "i_class": pa.array(
            [f"{CATEGORIES[c].lower()}class{k + 1}"
             for c, k in zip(cat_idx, class_idx)]
        ),
        "i_category_id": cat_idx.astype(np.int64) + 1,
        "i_category": pa.array([CATEGORIES[c] for c in cat_idx]),
        "i_manufact_id": manu_id.astype(np.int64),
        "i_manufact": pa.array([f"manufact#{m}" for m in manu_id]),
        "i_size": _pick(rng, SIZES, n),
        "i_formulation": _pick(rng, COLORS, n),
        "i_color": _pick(rng, COLORS, n),
        "i_units": _pick(rng, UNITS, n),
        "i_container": pa.array(["Unknown"] * n),
        "i_manager_id": rng.integers(1, 101, n).astype(np.int64),
        "i_product_name": pa.array([f"product{i}" for i in range(1, n + 1)]),
    })


def _gen_customer(sf, rng, n_cd, n_hd, n_addr) -> pa.Table:
    n = _n("customer", sf, linear=False)
    first_sales = rng.integers(DATE_LO, DATE_HI - 365, n)
    return pa.table({
        "c_customer_sk": np.arange(1, n + 1, dtype=np.int64),
        "c_customer_id": _id_col("AAAAAAAA", n),
        "c_current_cdemo_sk": _null_some(
            rng, rng.integers(1, n_cd + 1, n), 0.02
        ),
        "c_current_hdemo_sk": _null_some(
            rng, rng.integers(1, n_hd + 1, n), 0.02
        ),
        "c_current_addr_sk": rng.integers(1, n_addr + 1, n).astype(np.int64),
        "c_first_shipto_date_sk": _sk(first_sales + 30).astype(np.int64),
        "c_first_sales_date_sk": _sk(first_sales).astype(np.int64),
        "c_salutation": _pick(rng, ["Mr.", "Mrs.", "Ms.", "Dr.", "Miss", "Sir"], n),
        "c_first_name": _pick(rng, FIRST_NAMES, n),
        "c_last_name": _pick(rng, LAST_NAMES, n),
        "c_preferred_cust_flag": _pick(rng, ["Y", "N"], n),
        "c_birth_day": rng.integers(1, 29, n).astype(np.int64),
        "c_birth_month": rng.integers(1, 13, n).astype(np.int64),
        "c_birth_year": rng.integers(1930, 1993, n).astype(np.int64),
        "c_birth_country": _pick(rng, [c.upper() for c in COUNTRIES], n),
        "c_login": pa.array([None] * n, type=pa.string()),
        "c_email_address": pa.array(
            [f"user{i}@example.com" for i in range(1, n + 1)]
        ),
        "c_last_review_date_sk": _sk(
            rng.integers(DATE_LO, DATE_HI, n)
        ).astype(np.int64),
    })


def _gen_customer_address(sf, rng) -> pa.Table:
    n = _n("customer_address", sf, linear=False)
    return pa.table({
        "ca_address_sk": np.arange(1, n + 1, dtype=np.int64),
        "ca_address_id": _id_col("AAAAAAAA", n),
        "ca_street_number": pa.array(
            [str(x) for x in rng.integers(1, 1000, n)]
        ),
        "ca_street_name": _pick(rng, STREET_NAMES, n),
        "ca_street_type": _pick(rng, STREET_TYPES, n),
        "ca_suite_number": pa.array(
            [f"Suite {x}" for x in rng.integers(0, 500, n)]
        ),
        "ca_city": _pick(rng, CITIES, n),
        "ca_county": _pick(rng, COUNTIES, n),
        "ca_state": _pick(rng, STATES, n),
        "ca_zip": _pick(rng, ZIPS, n),
        "ca_country": pa.array(["United States"] * n),
        "ca_gmt_offset": rng.choice([-5.0, -6.0, -7.0, -8.0], n),
        "ca_location_type": _pick(
            rng, ["apartment", "condo", "single family"], n
        ),
    })


def _gen_customer_demographics(sf, rng) -> pa.Table:
    # full cross product of the three filtered dims x sampled tail dims —
    # every (gender, marital, education) combo a query names exists
    rows = []
    sk = 1
    for g in GENDERS:
        for m in MARITAL:
            for e in EDUCATION:
                for pe in range(500, 10001, 500):
                    rows.append((sk, g, m, e, pe))
                    sk += 1
    n = len(rows)
    arr = lambda i: [r[i] for r in rows]  # noqa: E731
    return pa.table({
        "cd_demo_sk": pa.array(arr(0), type=pa.int64()),
        "cd_gender": pa.array(arr(1)),
        "cd_marital_status": pa.array(arr(2)),
        "cd_education_status": pa.array(arr(3)),
        "cd_purchase_estimate": pa.array(arr(4), type=pa.int64()),
        "cd_credit_rating": pa.array(
            [CREDIT[i % len(CREDIT)] for i in range(n)]
        ),
        "cd_dep_count": pa.array([i % 7 for i in range(n)], type=pa.int64()),
        "cd_dep_employed_count": pa.array(
            [(i // 7) % 7 for i in range(n)], type=pa.int64()
        ),
        "cd_dep_college_count": pa.array(
            [(i // 49) % 7 for i in range(n)], type=pa.int64()
        ),
    })


def _gen_household_demographics(sf, rng) -> pa.Table:
    rows = []
    sk = 1
    for ib in range(1, 21):
        for bp in BUY_POTENTIAL:
            for dep in range(0, 10):
                for veh in range(-1, 5):
                    rows.append((sk, ib, bp, dep, veh))
                    sk += 1
    return pa.table({
        "hd_demo_sk": pa.array([r[0] for r in rows], type=pa.int64()),
        "hd_income_band_sk": pa.array([r[1] for r in rows], type=pa.int64()),
        "hd_buy_potential": pa.array([r[2] for r in rows]),
        "hd_dep_count": pa.array([r[3] for r in rows], type=pa.int64()),
        "hd_vehicle_count": pa.array([r[4] for r in rows], type=pa.int64()),
    })


def _gen_income_band(sf, rng) -> pa.Table:
    lo = np.arange(0, 200000, 10000, dtype=np.int64)
    return pa.table({
        "ib_income_band_sk": np.arange(1, 21, dtype=np.int64),
        "ib_lower_bound": lo,
        "ib_upper_bound": lo + 10000,
    })


def _gen_store(sf, rng) -> pa.Table:
    n = max(2, _n("store", sf, linear=False))
    return pa.table({
        "s_store_sk": np.arange(1, n + 1, dtype=np.int64),
        "s_store_id": _id_col("AAAAAAAA", n),
        "s_rec_start_date": _date32(np.full(n, _days(1997, 1, 1), np.int64)),
        "s_rec_end_date": pa.array([None] * n, type=pa.date32()),
        "s_closed_date_sk": pa.array([None] * n, type=pa.int64()),
        "s_store_name": _pick(rng, ["ought", "able", "pri", "ese", "anti",
                                    "cally", "ation", "eing", "bar"], n),
        "s_number_employees": rng.integers(200, 301, n).astype(np.int64),
        "s_floor_space": rng.integers(5_000_000, 10_000_001, n).astype(np.int64),
        "s_hours": _pick(rng, ["8AM-4PM", "8AM-12AM", "8AM-8AM"], n),
        "s_manager": _pick(rng, [f"{f} {l}" for f, l in
                                 zip(FIRST_NAMES[:20], LAST_NAMES[:20])], n),
        "s_market_id": rng.integers(1, 11, n).astype(np.int64),
        "s_geography_class": pa.array(["Unknown"] * n),
        "s_market_desc": pa.array(["store market description"] * n),
        "s_market_manager": _pick(rng, [f"{f} {l}" for f, l in
                                        zip(FIRST_NAMES[20:], LAST_NAMES[20:])], n),
        "s_division_id": np.ones(n, np.int64),
        "s_division_name": pa.array(["Unknown"] * n),
        "s_company_id": np.ones(n, np.int64),
        "s_company_name": pa.array(["Unknown"] * n),
        "s_street_number": pa.array([str(x) for x in rng.integers(1, 1000, n)]),
        "s_street_name": _pick(rng, STREET_NAMES, n),
        "s_street_type": _pick(rng, STREET_TYPES, n),
        "s_suite_number": pa.array([f"Suite {x}" for x in rng.integers(0, 500, n)]),
        "s_city": _pick(rng, CITIES[:8], n),
        "s_county": _pick(rng, COUNTIES[:6], n),
        "s_state": _pick(rng, STATES[:8], n),
        "s_zip": _pick(rng, ZIPS, n),
        "s_country": pa.array(["United States"] * n),
        "s_gmt_offset": rng.choice([-5.0, -6.0], n),
        "s_tax_precentage": np.round(rng.uniform(0.0, 0.11, n), 2),
    })


def _gen_warehouse(sf, rng) -> pa.Table:
    n = max(2, _n("warehouse", sf, linear=False))
    return pa.table({
        "w_warehouse_sk": np.arange(1, n + 1, dtype=np.int64),
        "w_warehouse_id": _id_col("AAAAAAAA", n),
        "w_warehouse_name": _pick(rng, [
            "Conventional childr", "Important issues liv", "Doors canno",
            "Bad cards must make.", "Rooms cook ",
        ], n),
        "w_warehouse_sq_ft": rng.integers(50_000, 1_000_001, n).astype(np.int64),
        "w_street_number": pa.array([str(x) for x in rng.integers(1, 1000, n)]),
        "w_street_name": _pick(rng, STREET_NAMES, n),
        "w_street_type": _pick(rng, STREET_TYPES, n),
        "w_suite_number": pa.array([f"Suite {x}" for x in rng.integers(0, 500, n)]),
        "w_city": _pick(rng, CITIES[:8], n),
        "w_county": _pick(rng, COUNTIES[:6], n),
        "w_state": _pick(rng, STATES[:8], n),
        "w_zip": _pick(rng, ZIPS, n),
        "w_country": pa.array(["United States"] * n),
        "w_gmt_offset": rng.choice([-5.0, -6.0], n),
    })


def _gen_call_center(sf, rng) -> pa.Table:
    n = max(2, _n("call_center", sf, linear=False))
    return pa.table({
        "cc_call_center_sk": np.arange(1, n + 1, dtype=np.int64),
        "cc_call_center_id": _id_col("AAAAAAAA", n),
        "cc_rec_start_date": _date32(np.full(n, _days(1997, 1, 1), np.int64)),
        "cc_rec_end_date": pa.array([None] * n, type=pa.date32()),
        "cc_closed_date_sk": pa.array([None] * n, type=pa.int64()),
        "cc_open_date_sk": _sk(np.full(n, DATE_LO, np.int64)).astype(np.int64),
        "cc_name": pa.array([f"call center {i}" for i in range(1, n + 1)]),
        "cc_class": _pick(rng, ["small", "medium", "large"], n),
        "cc_employees": rng.integers(1, 7, n).astype(np.int64),
        "cc_sq_ft": rng.integers(1000, 4000, n).astype(np.int64),
        "cc_hours": _pick(rng, ["8AM-4PM", "8AM-12AM", "8AM-8AM"], n),
        "cc_manager": _pick(rng, [f"{f} {l}" for f, l in
                                  zip(FIRST_NAMES[:20], LAST_NAMES[:20])], n),
        "cc_mkt_id": rng.integers(1, 7, n).astype(np.int64),
        "cc_mkt_class": pa.array(["Unknown"] * n),
        "cc_mkt_desc": pa.array(["call center market desc"] * n),
        "cc_market_manager": _pick(rng, [f"{f} {l}" for f, l in
                                         zip(FIRST_NAMES[20:], LAST_NAMES[20:])], n),
        "cc_division": np.ones(n, np.int64),
        "cc_division_name": pa.array(["Unknown"] * n),
        "cc_company": np.ones(n, np.int64),
        "cc_company_name": pa.array(["Unknown"] * n),
        "cc_street_number": pa.array([str(x) for x in rng.integers(1, 1000, n)]),
        "cc_street_name": _pick(rng, STREET_NAMES, n),
        "cc_street_type": _pick(rng, STREET_TYPES, n),
        "cc_suite_number": pa.array([f"Suite {x}" for x in rng.integers(0, 500, n)]),
        "cc_city": _pick(rng, CITIES[:8], n),
        "cc_county": _pick(rng, COUNTIES[:6], n),
        "cc_state": _pick(rng, STATES[:8], n),
        "cc_zip": _pick(rng, ZIPS, n),
        "cc_country": pa.array(["United States"] * n),
        "cc_gmt_offset": rng.choice([-5.0, -6.0], n),
        "cc_tax_percentage": np.round(rng.uniform(0.0, 0.12, n), 2),
    })


def _gen_web_site(sf, rng) -> pa.Table:
    n = max(2, _n("web_site", sf, linear=False))
    return pa.table({
        "web_site_sk": np.arange(1, n + 1, dtype=np.int64),
        "web_site_id": _id_col("AAAAAAAA", n),
        "web_rec_start_date": _date32(np.full(n, _days(1997, 1, 1), np.int64)),
        "web_rec_end_date": pa.array([None] * n, type=pa.date32()),
        "web_name": pa.array([f"site_{i}" for i in range(n)]),
        "web_open_date_sk": _sk(np.full(n, DATE_LO, np.int64)).astype(np.int64),
        "web_close_date_sk": pa.array([None] * n, type=pa.int64()),
        "web_class": pa.array(["Unknown"] * n),
        "web_manager": _pick(rng, [f"{f} {l}" for f, l in
                                   zip(FIRST_NAMES[:20], LAST_NAMES[:20])], n),
        "web_mkt_id": rng.integers(1, 7, n).astype(np.int64),
        "web_mkt_class": pa.array(["Unknown"] * n),
        "web_mkt_desc": pa.array(["web market desc"] * n),
        "web_market_manager": _pick(rng, [f"{f} {l}" for f, l in
                                          zip(FIRST_NAMES[20:], LAST_NAMES[20:])], n),
        "web_company_id": np.ones(n, np.int64),
        "web_company_name": _pick(rng, ["pri", "able", "ought", "bar", "ese"], n),
        "web_street_number": pa.array([str(x) for x in rng.integers(1, 1000, n)]),
        "web_street_name": _pick(rng, STREET_NAMES, n),
        "web_street_type": _pick(rng, STREET_TYPES, n),
        "web_suite_number": pa.array([f"Suite {x}" for x in rng.integers(0, 500, n)]),
        "web_city": _pick(rng, CITIES[:8], n),
        "web_county": _pick(rng, COUNTIES[:6], n),
        "web_state": _pick(rng, STATES[:8], n),
        "web_zip": _pick(rng, ZIPS, n),
        "web_country": pa.array(["United States"] * n),
        "web_gmt_offset": rng.choice([-5.0, -6.0], n),
        "web_tax_percentage": np.round(rng.uniform(0.0, 0.12, n), 2),
    })


def _gen_web_page(sf, rng) -> pa.Table:
    n = max(2, _n("web_page", sf, linear=False))
    return pa.table({
        "wp_web_page_sk": np.arange(1, n + 1, dtype=np.int64),
        "wp_web_page_id": _id_col("AAAAAAAA", n),
        "wp_rec_start_date": _date32(np.full(n, _days(1997, 1, 1), np.int64)),
        "wp_rec_end_date": pa.array([None] * n, type=pa.date32()),
        "wp_creation_date_sk": _sk(np.full(n, DATE_LO, np.int64)).astype(np.int64),
        "wp_access_date_sk": _sk(np.full(n, DATE_LO + 100, np.int64)).astype(np.int64),
        "wp_autogen_flag": _pick(rng, ["Y", "N"], n),
        "wp_customer_sk": pa.array([None] * n, type=pa.int64()),
        "wp_url": pa.array(["http://www.foo.com"] * n),
        "wp_type": _pick(rng, ["ad", "bio", "feedback", "general",
                               "order", "protected", "welcome"], n),
        "wp_char_count": rng.integers(100, 8000, n).astype(np.int64),
        "wp_link_count": rng.integers(2, 25, n).astype(np.int64),
        "wp_image_count": rng.integers(1, 7, n).astype(np.int64),
        "wp_max_ad_count": rng.integers(0, 4, n).astype(np.int64),
    })


def _gen_catalog_page(sf, rng) -> pa.Table:
    n = _n("catalog_page", sf, linear=False)
    return pa.table({
        "cp_catalog_page_sk": np.arange(1, n + 1, dtype=np.int64),
        "cp_catalog_page_id": _id_col("AAAAAAAA", n),
        "cp_start_date_sk": _sk(np.full(n, DATE_LO, np.int64)).astype(np.int64),
        "cp_end_date_sk": _sk(np.full(n, DATE_HI, np.int64)).astype(np.int64),
        "cp_department": pa.array(["DEPARTMENT"] * n),
        "cp_catalog_number": rng.integers(1, 110, n).astype(np.int64),
        "cp_catalog_page_number": rng.integers(1, 110, n).astype(np.int64),
        "cp_description": _pick(rng, [
            "catalog page one", "catalog page two", "catalog page three",
        ], n),
        "cp_type": _pick(rng, ["bi-annual", "quarterly", "monthly"], n),
    })


def _gen_promotion(sf, rng, n_items) -> pa.Table:
    n = _n("promotion", sf, linear=False)
    start = rng.integers(DATE_LO, DATE_HI - 60, n)
    yn = lambda: _pick(rng, ["N", "N", "N", "Y"], n)  # noqa: E731
    return pa.table({
        "p_promo_sk": np.arange(1, n + 1, dtype=np.int64),
        "p_promo_id": _id_col("AAAAAAAA", n),
        "p_start_date_sk": _sk(start).astype(np.int64),
        "p_end_date_sk": _sk(start + rng.integers(10, 60, n)).astype(np.int64),
        "p_item_sk": rng.integers(1, n_items + 1, n).astype(np.int64),
        "p_cost": np.round(rng.uniform(500.0, 2000.0, n), 2),
        "p_response_target": np.ones(n, np.int64),
        "p_promo_name": _pick(rng, ["anti", "ought", "able", "pri",
                                    "ese", "cally", "ation"], n),
        "p_channel_dmail": yn(),
        "p_channel_email": yn(),
        "p_channel_catalog": yn(),
        "p_channel_tv": yn(),
        "p_channel_radio": yn(),
        "p_channel_press": yn(),
        "p_channel_event": yn(),
        "p_channel_demo": yn(),
        "p_channel_details": pa.array(["promo details"] * n),
        "p_purpose": _pick(rng, ["Unknown"], n),
        "p_discount_active": pa.array(["N"] * n),
    })


def _gen_reason(sf, rng) -> pa.Table:
    n = len(REASONS)
    return pa.table({
        "r_reason_sk": np.arange(1, n + 1, dtype=np.int64),
        "r_reason_id": _id_col("AAAAAAAA", n),
        "r_reason_desc": pa.array(REASONS),
    })


def _gen_ship_mode(sf, rng) -> pa.Table:
    n = 20
    return pa.table({
        "sm_ship_mode_sk": np.arange(1, n + 1, dtype=np.int64),
        "sm_ship_mode_id": _id_col("AAAAAAAA", n),
        "sm_type": pa.array([SM_TYPES[i % len(SM_TYPES)] for i in range(n)]),
        "sm_code": pa.array([SM_CODES[i % len(SM_CODES)] for i in range(n)]),
        "sm_carrier": pa.array(SM_CARRIERS[:n]),
        "sm_contract": pa.array([f"contract{i}" for i in range(n)]),
    })


# ── facts ──────────────────────────────────────────────────────────────────


def _sales_money(rng, n, qty):
    """The spec's per-line money chain (wholesale→list→sales→ext columns)."""
    wholesale = _money(rng, 1.0, 100.0, n)
    list_price = np.round(wholesale * rng.uniform(1.0, 2.0, n), 2)
    sales_price = np.round(list_price * rng.uniform(0.0, 1.0, n), 2)
    ext_discount = np.round((list_price - sales_price) * qty, 2)
    ext_sales = np.round(sales_price * qty, 2)
    ext_wholesale = np.round(wholesale * qty, 2)
    ext_list = np.round(list_price * qty, 2)
    tax = np.round(ext_sales * rng.uniform(0.0, 0.09, n), 2)
    coupon = np.where(rng.random(n) < 0.1,
                      np.round(ext_sales * rng.uniform(0.0, 0.5, n), 2), 0.0)
    net_paid = np.round(ext_sales - coupon, 2)
    net_paid_tax = np.round(net_paid + tax, 2)
    net_profit = np.round(net_paid - ext_wholesale, 2)
    return dict(
        wholesale=wholesale, list=list_price, sales=sales_price,
        ext_discount=ext_discount, ext_sales=ext_sales,
        ext_wholesale=ext_wholesale, ext_list=ext_list, tax=tax,
        coupon=coupon, net_paid=net_paid, net_paid_tax=net_paid_tax,
        net_profit=net_profit,
    )


def _fact_dims(sf):
    return {
        "item": _n("item", sf, linear=False),
        "customer": _n("customer", sf, linear=False),
        "addr": _n("customer_address", sf, linear=False),
        "cd": 2 * 5 * 7 * 20,
        "hd": 20 * 6 * 10 * 6,
        "store": max(2, _n("store", sf, linear=False)),
        "warehouse": max(2, _n("warehouse", sf, linear=False)),
        "promo": _n("promotion", sf, linear=False),
        "web_site": max(2, _n("web_site", sf, linear=False)),
        "web_page": max(2, _n("web_page", sf, linear=False)),
        "call_center": max(2, _n("call_center", sf, linear=False)),
        "catalog_page": _n("catalog_page", sf, linear=False),
        "time": 1440,
    }


def _gen_store_sales(sf, rng) -> pa.Table:
    n = _n("store_sales", sf, linear=True)
    d = _fact_dims(sf)
    sold = rng.integers(_days(1998, 1, 1), _days(2002, 12, 31), n)
    qty = rng.integers(1, 101, n)
    m = _sales_money(rng, n, qty)
    return pa.table({
        "ss_sold_date_sk": _null_some(rng, _sk(sold), 0.02),
        "ss_sold_time_sk": (rng.integers(0, d["time"], n) * 60).astype(np.int64),
        "ss_item_sk": rng.integers(1, d["item"] + 1, n).astype(np.int64),
        "ss_customer_sk": _null_some(
            rng, rng.integers(1, d["customer"] + 1, n), 0.02
        ),
        "ss_cdemo_sk": _null_some(rng, rng.integers(1, d["cd"] + 1, n), 0.02),
        "ss_hdemo_sk": _null_some(rng, rng.integers(1, d["hd"] + 1, n), 0.02),
        "ss_addr_sk": _null_some(rng, rng.integers(1, d["addr"] + 1, n), 0.02),
        "ss_store_sk": _null_some(rng, rng.integers(1, d["store"] + 1, n), 0.02),
        "ss_promo_sk": _null_some(rng, rng.integers(1, d["promo"] + 1, n), 0.1),
        "ss_ticket_number": (np.arange(n, dtype=np.int64) // 4 + 1),
        "ss_quantity": qty.astype(np.int64),
        "ss_wholesale_cost": m["wholesale"],
        "ss_list_price": m["list"],
        "ss_sales_price": m["sales"],
        "ss_ext_discount_amt": m["ext_discount"],
        "ss_ext_sales_price": m["ext_sales"],
        "ss_ext_wholesale_cost": m["ext_wholesale"],
        "ss_ext_list_price": m["ext_list"],
        "ss_ext_tax": m["tax"],
        "ss_coupon_amt": m["coupon"],
        "ss_net_paid": m["net_paid"],
        "ss_net_paid_inc_tax": m["net_paid_tax"],
        "ss_net_profit": m["net_profit"],
    })


def _returns_from(sales: pa.Table, rng, frac: float, cols: Dict[str, str],
                  extra: Callable) -> pa.Table:
    """Sample ~frac of a sales fact into its returns fact, carrying the join
    identity columns (ticket/order number + item + customer) so the
    multi-channel sales⋈returns chains are non-vacuous."""
    n_src = sales.num_rows
    idx = np.flatnonzero(rng.random(n_src) < frac)
    sample = sales.take(pa.array(idx))
    return extra(sample, idx)


def _gen_store_returns(sf, rng, store_sales: pa.Table) -> pa.Table:
    def build(sample: pa.Table, idx) -> pa.Table:
        n = sample.num_rows
        sold = np.array(
            [v.as_py() or SK_BASE for v in sample["ss_sold_date_sk"]],
            np.int64,
        )
        ret_day = sold + rng.integers(1, 90, n)
        qty_sold = np.array([v.as_py() for v in sample["ss_quantity"]], np.int64)
        ret_qty = np.maximum(1, (qty_sold * rng.uniform(0.1, 1.0, n)).astype(np.int64))
        sales_price = np.array(
            [v.as_py() for v in sample["ss_sales_price"]], np.float64
        )
        amt = np.round(sales_price * ret_qty, 2)
        tax = np.round(amt * 0.05, 2)
        fee = _money(rng, 0.5, 100.0, n)
        ship = _money(rng, 0.0, 50.0, n)
        refunded = np.round(amt * rng.uniform(0.3, 1.0, n), 2)
        reversed_ = np.round((amt - refunded) * 0.5, 2)
        credit = np.round(amt - refunded - reversed_, 2)
        return pa.table({
            "sr_returned_date_sk": pa.array(
                np.minimum(ret_day, SK_BASE + N_DATES - 1), type=pa.int64()
            ),
            "sr_return_time_sk": (rng.integers(0, 1440, n) * 60).astype(np.int64),
            "sr_item_sk": sample["ss_item_sk"],
            "sr_customer_sk": sample["ss_customer_sk"],
            "sr_cdemo_sk": sample["ss_cdemo_sk"],
            "sr_hdemo_sk": sample["ss_hdemo_sk"],
            "sr_addr_sk": sample["ss_addr_sk"],
            "sr_store_sk": sample["ss_store_sk"],
            "sr_reason_sk": rng.integers(1, len(REASONS) + 1, n).astype(np.int64),
            "sr_ticket_number": sample["ss_ticket_number"],
            "sr_return_quantity": ret_qty,
            "sr_return_amt": amt,
            "sr_return_tax": tax,
            "sr_return_amt_inc_tax": np.round(amt + tax, 2),
            "sr_fee": fee,
            "sr_return_ship_cost": ship,
            "sr_refunded_cash": refunded,
            "sr_reversed_charge": reversed_,
            "sr_store_credit": credit,
            "sr_net_loss": np.round(amt * 0.1 + fee + ship, 2),
        })

    return _returns_from(store_sales, rng, 0.1, {}, build)


def _gen_catalog_sales(sf, rng) -> pa.Table:
    n = _n("catalog_sales", sf, linear=True)
    d = _fact_dims(sf)
    sold = rng.integers(_days(1998, 1, 1), _days(2002, 12, 31), n)
    ship = sold + rng.integers(1, 140, n)
    qty = rng.integers(1, 101, n)
    m = _sales_money(rng, n, qty)
    ship_cost = np.round(m["ext_sales"] * rng.uniform(0.0, 0.2, n), 2)
    bill_cust = rng.integers(1, d["customer"] + 1, n)
    # ~15% drop-ship to a different customer (q? bill<>ship filters)
    ship_cust = np.where(
        rng.random(n) < 0.15,
        rng.integers(1, d["customer"] + 1, n), bill_cust,
    )
    return pa.table({
        "cs_sold_date_sk": _null_some(rng, _sk(sold), 0.02),
        "cs_sold_time_sk": (rng.integers(0, d["time"], n) * 60).astype(np.int64),
        "cs_ship_date_sk": _sk(np.minimum(ship, DATE_HI)).astype(np.int64),
        "cs_bill_customer_sk": bill_cust.astype(np.int64),
        "cs_bill_cdemo_sk": rng.integers(1, d["cd"] + 1, n).astype(np.int64),
        "cs_bill_hdemo_sk": rng.integers(1, d["hd"] + 1, n).astype(np.int64),
        "cs_bill_addr_sk": rng.integers(1, d["addr"] + 1, n).astype(np.int64),
        "cs_ship_customer_sk": ship_cust.astype(np.int64),
        "cs_ship_cdemo_sk": rng.integers(1, d["cd"] + 1, n).astype(np.int64),
        "cs_ship_hdemo_sk": rng.integers(1, d["hd"] + 1, n).astype(np.int64),
        "cs_ship_addr_sk": rng.integers(1, d["addr"] + 1, n).astype(np.int64),
        "cs_call_center_sk": _null_some(
            rng, rng.integers(1, d["call_center"] + 1, n), 0.02
        ),
        "cs_catalog_page_sk": rng.integers(
            1, d["catalog_page"] + 1, n
        ).astype(np.int64),
        "cs_ship_mode_sk": rng.integers(1, 21, n).astype(np.int64),
        "cs_warehouse_sk": rng.integers(1, d["warehouse"] + 1, n).astype(np.int64),
        "cs_item_sk": rng.integers(1, d["item"] + 1, n).astype(np.int64),
        "cs_promo_sk": _null_some(rng, rng.integers(1, d["promo"] + 1, n), 0.1),
        "cs_order_number": (np.arange(n, dtype=np.int64) // 3 + 1),
        "cs_quantity": qty.astype(np.int64),
        "cs_wholesale_cost": m["wholesale"],
        "cs_list_price": m["list"],
        "cs_sales_price": m["sales"],
        "cs_ext_discount_amt": m["ext_discount"],
        "cs_ext_sales_price": m["ext_sales"],
        "cs_ext_wholesale_cost": m["ext_wholesale"],
        "cs_ext_list_price": m["ext_list"],
        "cs_ext_tax": m["tax"],
        "cs_coupon_amt": m["coupon"],
        "cs_ext_ship_cost": ship_cost,
        "cs_net_paid": m["net_paid"],
        "cs_net_paid_inc_tax": m["net_paid_tax"],
        "cs_net_paid_inc_ship": np.round(m["net_paid"] + ship_cost, 2),
        "cs_net_paid_inc_ship_tax": np.round(
            m["net_paid_tax"] + ship_cost, 2
        ),
        "cs_net_profit": m["net_profit"],
    })


def _gen_catalog_returns(sf, rng, catalog_sales: pa.Table) -> pa.Table:
    def build(sample: pa.Table, idx) -> pa.Table:
        n = sample.num_rows
        sold = np.array(
            [v.as_py() or SK_BASE for v in sample["cs_sold_date_sk"]],
            np.int64,
        )
        ret_day = np.minimum(sold + rng.integers(1, 90, n), SK_BASE + N_DATES - 1)
        qty_sold = np.array([v.as_py() for v in sample["cs_quantity"]], np.int64)
        ret_qty = np.maximum(1, (qty_sold * rng.uniform(0.1, 1.0, n)).astype(np.int64))
        sales_price = np.array(
            [v.as_py() for v in sample["cs_sales_price"]], np.float64
        )
        amt = np.round(sales_price * ret_qty, 2)
        tax = np.round(amt * 0.05, 2)
        fee = _money(rng, 0.5, 100.0, n)
        ship = _money(rng, 0.0, 50.0, n)
        refunded = np.round(amt * rng.uniform(0.3, 1.0, n), 2)
        reversed_ = np.round((amt - refunded) * 0.5, 2)
        return pa.table({
            "cr_returned_date_sk": pa.array(ret_day, type=pa.int64()),
            "cr_returned_time_sk": (rng.integers(0, 1440, n) * 60).astype(np.int64),
            "cr_item_sk": sample["cs_item_sk"],
            "cr_refunded_customer_sk": sample["cs_bill_customer_sk"],
            "cr_refunded_cdemo_sk": sample["cs_bill_cdemo_sk"],
            "cr_refunded_hdemo_sk": sample["cs_bill_hdemo_sk"],
            "cr_refunded_addr_sk": sample["cs_bill_addr_sk"],
            "cr_returning_customer_sk": sample["cs_ship_customer_sk"],
            "cr_returning_cdemo_sk": sample["cs_ship_cdemo_sk"],
            "cr_returning_hdemo_sk": sample["cs_ship_hdemo_sk"],
            "cr_returning_addr_sk": sample["cs_ship_addr_sk"],
            "cr_call_center_sk": sample["cs_call_center_sk"],
            "cr_catalog_page_sk": sample["cs_catalog_page_sk"],
            "cr_ship_mode_sk": sample["cs_ship_mode_sk"],
            "cr_warehouse_sk": sample["cs_warehouse_sk"],
            "cr_reason_sk": rng.integers(1, len(REASONS) + 1, n).astype(np.int64),
            "cr_order_number": sample["cs_order_number"],
            "cr_return_quantity": ret_qty,
            "cr_return_amount": amt,
            "cr_return_tax": tax,
            "cr_return_amt_inc_tax": np.round(amt + tax, 2),
            "cr_fee": fee,
            "cr_return_ship_cost": ship,
            "cr_refunded_cash": refunded,
            "cr_reversed_charge": reversed_,
            "cr_store_credit": np.round(amt - refunded - reversed_, 2),
            "cr_net_loss": np.round(amt * 0.1 + fee + ship, 2),
        })

    return _returns_from(catalog_sales, rng, 0.1, {}, build)


def _gen_web_sales(sf, rng) -> pa.Table:
    n = _n("web_sales", sf, linear=True)
    d = _fact_dims(sf)
    sold = rng.integers(_days(1998, 1, 1), _days(2002, 12, 31), n)
    ship = sold + rng.integers(1, 140, n)
    qty = rng.integers(1, 101, n)
    m = _sales_money(rng, n, qty)
    ship_cost = np.round(m["ext_sales"] * rng.uniform(0.0, 0.2, n), 2)
    bill_cust = rng.integers(1, d["customer"] + 1, n)
    ship_cust = np.where(
        rng.random(n) < 0.15,
        rng.integers(1, d["customer"] + 1, n), bill_cust,
    )
    return pa.table({
        "ws_sold_date_sk": _null_some(rng, _sk(sold), 0.02),
        "ws_sold_time_sk": (rng.integers(0, d["time"], n) * 60).astype(np.int64),
        "ws_ship_date_sk": _sk(np.minimum(ship, DATE_HI)).astype(np.int64),
        "ws_item_sk": rng.integers(1, d["item"] + 1, n).astype(np.int64),
        "ws_bill_customer_sk": bill_cust.astype(np.int64),
        "ws_bill_cdemo_sk": rng.integers(1, d["cd"] + 1, n).astype(np.int64),
        "ws_bill_hdemo_sk": rng.integers(1, d["hd"] + 1, n).astype(np.int64),
        "ws_bill_addr_sk": rng.integers(1, d["addr"] + 1, n).astype(np.int64),
        "ws_ship_customer_sk": ship_cust.astype(np.int64),
        "ws_ship_cdemo_sk": rng.integers(1, d["cd"] + 1, n).astype(np.int64),
        "ws_ship_hdemo_sk": rng.integers(1, d["hd"] + 1, n).astype(np.int64),
        "ws_ship_addr_sk": rng.integers(1, d["addr"] + 1, n).astype(np.int64),
        "ws_web_page_sk": rng.integers(1, d["web_page"] + 1, n).astype(np.int64),
        "ws_web_site_sk": rng.integers(1, d["web_site"] + 1, n).astype(np.int64),
        "ws_ship_mode_sk": rng.integers(1, 21, n).astype(np.int64),
        "ws_warehouse_sk": rng.integers(1, d["warehouse"] + 1, n).astype(np.int64),
        "ws_promo_sk": _null_some(rng, rng.integers(1, d["promo"] + 1, n), 0.1),
        "ws_order_number": (np.arange(n, dtype=np.int64) // 3 + 1),
        "ws_quantity": qty.astype(np.int64),
        "ws_wholesale_cost": m["wholesale"],
        "ws_list_price": m["list"],
        "ws_sales_price": m["sales"],
        "ws_ext_discount_amt": m["ext_discount"],
        "ws_ext_sales_price": m["ext_sales"],
        "ws_ext_wholesale_cost": m["ext_wholesale"],
        "ws_ext_list_price": m["ext_list"],
        "ws_ext_tax": m["tax"],
        "ws_coupon_amt": m["coupon"],
        "ws_ext_ship_cost": ship_cost,
        "ws_net_paid": m["net_paid"],
        "ws_net_paid_inc_tax": m["net_paid_tax"],
        "ws_net_paid_inc_ship": np.round(m["net_paid"] + ship_cost, 2),
        "ws_net_paid_inc_ship_tax": np.round(
            m["net_paid_tax"] + ship_cost, 2
        ),
        "ws_net_profit": m["net_profit"],
    })


def _gen_web_returns(sf, rng, web_sales: pa.Table) -> pa.Table:
    def build(sample: pa.Table, idx) -> pa.Table:
        n = sample.num_rows
        sold = np.array(
            [v.as_py() or SK_BASE for v in sample["ws_sold_date_sk"]],
            np.int64,
        )
        ret_day = np.minimum(sold + rng.integers(1, 90, n), SK_BASE + N_DATES - 1)
        qty_sold = np.array([v.as_py() for v in sample["ws_quantity"]], np.int64)
        ret_qty = np.maximum(1, (qty_sold * rng.uniform(0.1, 1.0, n)).astype(np.int64))
        sales_price = np.array(
            [v.as_py() for v in sample["ws_sales_price"]], np.float64
        )
        amt = np.round(sales_price * ret_qty, 2)
        tax = np.round(amt * 0.05, 2)
        fee = _money(rng, 0.5, 100.0, n)
        ship = _money(rng, 0.0, 50.0, n)
        refunded = np.round(amt * rng.uniform(0.3, 1.0, n), 2)
        reversed_ = np.round((amt - refunded) * 0.5, 2)
        return pa.table({
            "wr_returned_date_sk": pa.array(ret_day, type=pa.int64()),
            "wr_returned_time_sk": (rng.integers(0, 1440, n) * 60).astype(np.int64),
            "wr_item_sk": sample["ws_item_sk"],
            "wr_refunded_customer_sk": sample["ws_bill_customer_sk"],
            "wr_refunded_cdemo_sk": sample["ws_bill_cdemo_sk"],
            "wr_refunded_hdemo_sk": sample["ws_bill_hdemo_sk"],
            "wr_refunded_addr_sk": sample["ws_bill_addr_sk"],
            "wr_returning_customer_sk": sample["ws_ship_customer_sk"],
            "wr_returning_cdemo_sk": sample["ws_ship_cdemo_sk"],
            "wr_returning_hdemo_sk": sample["ws_ship_hdemo_sk"],
            "wr_returning_addr_sk": sample["ws_ship_addr_sk"],
            "wr_web_page_sk": sample["ws_web_page_sk"],
            "wr_reason_sk": rng.integers(1, len(REASONS) + 1, n).astype(np.int64),
            "wr_order_number": sample["ws_order_number"],
            "wr_return_quantity": ret_qty,
            "wr_return_amt": amt,
            "wr_return_tax": tax,
            "wr_return_amt_inc_tax": np.round(amt + tax, 2),
            "wr_fee": fee,
            "wr_return_ship_cost": ship,
            "wr_refunded_cash": refunded,
            "wr_reversed_charge": reversed_,
            "wr_account_credit": np.round(amt - refunded - reversed_, 2),
            "wr_net_loss": np.round(amt * 0.1 + fee + ship, 2),
        })

    return _returns_from(web_sales, rng, 0.1, {}, build)


def _gen_inventory(sf, rng) -> pa.Table:
    d = _fact_dims(sf)
    # weekly snapshots x (item, warehouse) sample, spec-shaped
    weeks = np.arange(_days(1998, 1, 2), _days(2002, 12, 31), 7, dtype=np.int64)
    target = _n("inventory", sf, linear=True)
    per_week = max(1, target // len(weeks))
    rows_d, rows_i, rows_w, rows_q = [], [], [], []
    for wday in weeks:
        items = rng.integers(1, d["item"] + 1, per_week)
        whs = rng.integers(1, d["warehouse"] + 1, per_week)
        qty = rng.integers(0, 1001, per_week)
        rows_d.append(np.full(per_week, wday, np.int64))
        rows_i.append(items)
        rows_w.append(whs)
        rows_q.append(qty)
    return pa.table({
        "inv_date_sk": _sk(np.concatenate(rows_d)).astype(np.int64),
        "inv_item_sk": np.concatenate(rows_i).astype(np.int64),
        "inv_warehouse_sk": np.concatenate(rows_w).astype(np.int64),
        "inv_quantity_on_hand": np.concatenate(rows_q).astype(np.int64),
    })


# ── public API ─────────────────────────────────────────────────────────────

_CACHE: Dict = {}


def gen_table(name: str, sf: float, seed: int = 20030101) -> pa.Table:
    """Generate one TPC-DS table at scale factor ``sf`` (deterministic)."""
    key = (name, sf, seed)
    if key in _CACHE:
        return _CACHE[key]
    rng = np.random.default_rng(
        np.random.SeedSequence([seed, TABLES.index(name), int(sf * 1e6)])
    )
    if name == "date_dim":
        t = _gen_date_dim(sf, rng)
    elif name == "time_dim":
        t = _gen_time_dim(sf, rng)
    elif name == "item":
        t = _gen_item(sf, rng)
    elif name == "customer":
        d = _fact_dims(sf)
        t = _gen_customer(sf, rng, d["cd"], d["hd"], d["addr"])
    elif name == "customer_address":
        t = _gen_customer_address(sf, rng)
    elif name == "customer_demographics":
        t = _gen_customer_demographics(sf, rng)
    elif name == "household_demographics":
        t = _gen_household_demographics(sf, rng)
    elif name == "income_band":
        t = _gen_income_band(sf, rng)
    elif name == "store":
        t = _gen_store(sf, rng)
    elif name == "warehouse":
        t = _gen_warehouse(sf, rng)
    elif name == "call_center":
        t = _gen_call_center(sf, rng)
    elif name == "web_site":
        t = _gen_web_site(sf, rng)
    elif name == "web_page":
        t = _gen_web_page(sf, rng)
    elif name == "catalog_page":
        t = _gen_catalog_page(sf, rng)
    elif name == "promotion":
        t = _gen_promotion(sf, rng, _fact_dims(sf)["item"])
    elif name == "reason":
        t = _gen_reason(sf, rng)
    elif name == "ship_mode":
        t = _gen_ship_mode(sf, rng)
    elif name == "store_sales":
        t = _gen_store_sales(sf, rng)
    elif name == "store_returns":
        t = _gen_store_returns(sf, rng, gen_table("store_sales", sf, seed))
    elif name == "catalog_sales":
        t = _gen_catalog_sales(sf, rng)
    elif name == "catalog_returns":
        t = _gen_catalog_returns(sf, rng, gen_table("catalog_sales", sf, seed))
    elif name == "web_sales":
        t = _gen_web_sales(sf, rng)
    elif name == "web_returns":
        t = _gen_web_returns(sf, rng, gen_table("web_sales", sf, seed))
    elif name == "inventory":
        t = _gen_inventory(sf, rng)
    else:
        raise KeyError(name)
    _CACHE[key] = t
    return t


def register_tables(session, sf: float, seed: int = 20030101,
                    num_partitions: int = 1) -> None:
    """Register all 24 tables as temp views on a session."""
    for name in TABLES:
        t = gen_table(name, sf, seed)
        n = num_partitions if t.num_rows > 5000 else 1
        session.create_dataframe(t, num_partitions=n).create_or_replace_temp_view(
            name
        )


def write_tables(root: str, sf: float, files_per_table: int = 4,
                 seed: int = 20030101) -> None:
    """Materialize the dataset as multi-file parquet directories."""
    for name in TABLES:
        t = gen_table(name, sf, seed)
        out = os.path.join(root, name)
        os.makedirs(out, exist_ok=True)
        nf = files_per_table if t.num_rows > 10_000 else 1
        step = (t.num_rows + nf - 1) // nf
        for i in range(nf):
            pq.write_table(
                t.slice(i * step, step), os.path.join(out, f"part-{i:03d}.parquet")
            )
