"""TPC-DS queries 26-50 as SQL text."""

Q = {}

Q[26] = """
select i_item_id, avg(cs_quantity) agg1, avg(cs_list_price) agg2,
       avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4
from catalog_sales, customer_demographics, date_dim, item, promotion
where cs_sold_date_sk = d_date_sk and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd_demo_sk and cs_promo_sk = p_promo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N') and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""

Q[27] = """
select i_item_id, s_state, grouping(s_state) g_state,
       avg(ss_quantity) agg1, avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College' and d_year = 2002
  and s_state in ('TX', 'OH', 'CA', 'FL', 'GA', 'AL')
group by rollup (i_item_id, s_state)
order by i_item_id nulls last, s_state nulls last
limit 100
"""

Q[28] = """
select *
from (select avg(ss_list_price) b1_lp, count(ss_list_price) b1_cnt,
             count(distinct ss_list_price) b1_cntd
      from store_sales
      where ss_quantity between 0 and 5
        and (ss_list_price between 8 and 8 + 10
             or ss_coupon_amt between 459 and 459 + 1000
             or ss_wholesale_cost between 57 and 57 + 20)) b1,
     (select avg(ss_list_price) b2_lp, count(ss_list_price) b2_cnt,
             count(distinct ss_list_price) b2_cntd
      from store_sales
      where ss_quantity between 6 and 10
        and (ss_list_price between 90 and 90 + 10
             or ss_coupon_amt between 2323 and 2323 + 1000
             or ss_wholesale_cost between 31 and 31 + 20)) b2,
     (select avg(ss_list_price) b3_lp, count(ss_list_price) b3_cnt,
             count(distinct ss_list_price) b3_cntd
      from store_sales
      where ss_quantity between 11 and 15
        and (ss_list_price between 142 and 142 + 10
             or ss_coupon_amt between 12214 and 12214 + 1000
             or ss_wholesale_cost between 79 and 79 + 20)) b3,
     (select avg(ss_list_price) b4_lp, count(ss_list_price) b4_cnt,
             count(distinct ss_list_price) b4_cntd
      from store_sales
      where ss_quantity between 16 and 20
        and (ss_list_price between 135 and 135 + 10
             or ss_coupon_amt between 6071 and 6071 + 1000
             or ss_wholesale_cost between 38 and 38 + 20)) b4,
     (select avg(ss_list_price) b5_lp, count(ss_list_price) b5_cnt,
             count(distinct ss_list_price) b5_cntd
      from store_sales
      where ss_quantity between 21 and 25
        and (ss_list_price between 122 and 122 + 10
             or ss_coupon_amt between 836 and 836 + 1000
             or ss_wholesale_cost between 17 and 17 + 20)) b5,
     (select avg(ss_list_price) b6_lp, count(ss_list_price) b6_cnt,
             count(distinct ss_list_price) b6_cntd
      from store_sales
      where ss_quantity between 26 and 30
        and (ss_list_price between 154 and 154 + 10
             or ss_coupon_amt between 7326 and 7326 + 1000
             or ss_wholesale_cost between 7 and 7 + 20)) b6
limit 100
"""

Q[29] = """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_quantity) as store_sales_quantity,
       sum(sr_return_quantity) as store_returns_quantity,
       sum(cs_quantity) as catalog_sales_quantity
from store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2,
     date_dim d3, store, item
where d1.d_moy = 9 and d1.d_year = 1999 and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_moy between 9 and 9 + 3 and d2.d_year = 1999
  and sr_customer_sk = cs_bill_customer_sk and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_year in (1999, 2000, 2001)
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
"""

Q[30] = """
with customer_total_return as (
  select wr_returning_customer_sk as ctr_customer_sk, ca_state as ctr_state,
         sum(wr_return_amt) as ctr_total_return
  from web_returns, date_dim, customer_address
  where wr_returned_date_sk = d_date_sk and d_year = 2002
    and wr_returning_addr_sk = ca_address_sk
  group by wr_returning_customer_sk, ca_state)
select c_customer_id, c_salutation, c_first_name, c_last_name,
       c_preferred_cust_flag, c_birth_day, c_birth_month, c_birth_year,
       c_birth_country, c_login, c_email_address, c_last_review_date_sk,
       ctr_total_return
from customer_total_return ctr1, customer_address, customer
where ctr1.ctr_total_return > (select avg(ctr_total_return) * 1.2
                               from customer_total_return ctr2
                               where ctr1.ctr_state = ctr2.ctr_state)
  and ca_address_sk = c_current_addr_sk and ca_state = 'GA'
  and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id, c_salutation, c_first_name, c_last_name,
         c_preferred_cust_flag, c_birth_day, c_birth_month, c_birth_year,
         c_birth_country, c_login, c_email_address, c_last_review_date_sk,
         ctr_total_return
limit 100
"""

Q[31] = """
with ss as (
  select ca_county, d_qoy, d_year, sum(ss_ext_sales_price) as store_sales
  from store_sales, date_dim, customer_address
  where ss_sold_date_sk = d_date_sk and ss_addr_sk = ca_address_sk
  group by ca_county, d_qoy, d_year),
 ws as (
  select ca_county, d_qoy, d_year, sum(ws_ext_sales_price) as web_sales
  from web_sales, date_dim, customer_address
  where ws_sold_date_sk = d_date_sk and ws_bill_addr_sk = ca_address_sk
  group by ca_county, d_qoy, d_year)
select ss1.ca_county, ss1.d_year,
       ws2.web_sales / ws1.web_sales web_q1_q2_increase,
       ss2.store_sales / ss1.store_sales store_q1_q2_increase,
       ws3.web_sales / ws2.web_sales web_q2_q3_increase,
       ss3.store_sales / ss2.store_sales store_q2_q3_increase
from ss ss1, ss ss2, ss ss3, ws ws1, ws ws2, ws ws3
where ss1.d_qoy = 1 and ss1.d_year = 2000
  and ss1.ca_county = ss2.ca_county and ss2.d_qoy = 2 and ss2.d_year = 2000
  and ss2.ca_county = ss3.ca_county and ss3.d_qoy = 3 and ss3.d_year = 2000
  and ss1.ca_county = ws1.ca_county and ws1.d_qoy = 1 and ws1.d_year = 2000
  and ws1.ca_county = ws2.ca_county and ws2.d_qoy = 2 and ws2.d_year = 2000
  and ws1.ca_county = ws3.ca_county and ws3.d_qoy = 3 and ws3.d_year = 2000
  and case when ws1.web_sales > 0 then ws2.web_sales / ws1.web_sales
           else null end
        > case when ss1.store_sales > 0 then ss2.store_sales / ss1.store_sales
               else null end
  and case when ws2.web_sales > 0 then ws3.web_sales / ws2.web_sales
           else null end
        > case when ss2.store_sales > 0 then ss3.store_sales / ss2.store_sales
               else null end
order by ss1.ca_county
"""

Q[32] = """
select sum(cs_ext_discount_amt) as excess_discount_amount
from catalog_sales, item, date_dim
where i_manufact_id = 29 and i_item_sk = cs_item_sk
  and d_date between date '1999-01-07' and date '1999-01-07' + interval '90' day
  and d_date_sk = cs_sold_date_sk
  and cs_ext_discount_amt > (
    select 1.3 * avg(cs_ext_discount_amt)
    from catalog_sales, date_dim
    where cs_item_sk = i_item_sk and d_date_sk = cs_sold_date_sk
      and d_date between date '1999-01-07'
                     and date '1999-01-07' + interval '90' day)
limit 100
"""

Q[33] = """
with ss as (
  select i_manufact_id, sum(ss_ext_sales_price) total_sales
  from store_sales, date_dim, customer_address, item
  where i_manufact_id in (select i_manufact_id from item
                          where i_category in ('Electronics'))
    and ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
    and d_year = 1998 and d_moy = 5 and ss_addr_sk = ca_address_sk
    and ca_gmt_offset = -5.0
  group by i_manufact_id),
 cs as (
  select i_manufact_id, sum(cs_ext_sales_price) total_sales
  from catalog_sales, date_dim, customer_address, item
  where i_manufact_id in (select i_manufact_id from item
                          where i_category in ('Electronics'))
    and cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
    and d_year = 1998 and d_moy = 5 and cs_bill_addr_sk = ca_address_sk
    and ca_gmt_offset = -5.0
  group by i_manufact_id),
 ws as (
  select i_manufact_id, sum(ws_ext_sales_price) total_sales
  from web_sales, date_dim, customer_address, item
  where i_manufact_id in (select i_manufact_id from item
                          where i_category in ('Electronics'))
    and ws_item_sk = i_item_sk and ws_sold_date_sk = d_date_sk
    and d_year = 1998 and d_moy = 5 and ws_bill_addr_sk = ca_address_sk
    and ca_gmt_offset = -5.0
  group by i_manufact_id)
select i_manufact_id, sum(total_sales) total_sales
from (select * from ss
      union all
      select * from cs
      union all
      select * from ws) tmp1
group by i_manufact_id
order by total_sales, i_manufact_id
limit 100
"""

Q[34] = """
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) cnt
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and (d_dom between 1 and 3 or d_dom between 25 and 28)
        and (hd_buy_potential = '>10000' or hd_buy_potential = 'Unknown')
        and hd_vehicle_count > 0
        and (case when hd_vehicle_count > 0
                  then cast(hd_dep_count as double) / hd_vehicle_count
                  else null end) > 1.2
        and d_year in (1999, 2000, 2001)
        and s_county in ('Ziebach County', 'Williamson County',
                         'Walker County', 'Salem County')
      group by ss_ticket_number, ss_customer_sk) dn,
     customer
where ss_customer_sk = c_customer_sk and cnt between 15 and 20
order by c_last_name, c_first_name, c_salutation,
         c_preferred_cust_flag desc, ss_ticket_number
"""

Q[35] = """
select ca_state, cd_gender, cd_marital_status, cd_dep_count, count(*) cnt1,
       min(cd_dep_count) mn1, max(cd_dep_count) mx1, avg(cd_dep_count) av1,
       cd_dep_employed_count, count(*) cnt2, min(cd_dep_employed_count) mn2,
       max(cd_dep_employed_count) mx2, avg(cd_dep_employed_count) av2,
       cd_dep_college_count, count(*) cnt3, min(cd_dep_college_count) mn3,
       max(cd_dep_college_count) mx3, avg(cd_dep_college_count) av3
from customer c, customer_address ca, customer_demographics
where c.c_current_addr_sk = ca.ca_address_sk
  and cd_demo_sk = c.c_current_cdemo_sk
  and exists (select * from store_sales, date_dim
              where c.c_customer_sk = ss_customer_sk
                and ss_sold_date_sk = d_date_sk and d_year = 2002
                and d_qoy < 4)
  and (exists (select * from web_sales, date_dim
               where c.c_customer_sk = ws_bill_customer_sk
                 and ws_sold_date_sk = d_date_sk and d_year = 2002
                 and d_qoy < 4)
    or exists (select * from catalog_sales, date_dim
               where c.c_customer_sk = cs_ship_customer_sk
                 and cs_sold_date_sk = d_date_sk and d_year = 2002
                 and d_qoy < 4))
group by ca_state, cd_gender, cd_marital_status, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
order by ca_state, cd_gender, cd_marital_status, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
limit 100
"""

Q[36] = """
select sum(ss_net_profit) / sum(ss_ext_sales_price) as gross_margin,
       i_category, i_class, grouping(i_category) + grouping(i_class)
         as lochierarchy,
       rank() over (partition by grouping(i_category) + grouping(i_class),
                    case when grouping(i_class) = 0 then i_category end
                    order by sum(ss_net_profit) / sum(ss_ext_sales_price) asc)
         as rank_within_parent
from store_sales, date_dim d1, item, store
where d1.d_year = 2001 and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
  and s_state in ('TX', 'OH', 'CA', 'FL', 'GA', 'AL')
group by rollup (i_category, i_class)
order by lochierarchy desc, case when lochierarchy = 0 then i_category end,
         rank_within_parent
limit 100
"""

Q[37] = """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, catalog_sales
where i_current_price between 68 and 68 + 30 and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '2000-02-01' and date '2000-02-01' + interval '60' day
  and i_manufact_id in (677, 940, 694, 808, 17, 128, 29)
  and inv_quantity_on_hand between 100 and 500 and cs_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
"""

Q[38] = """
select count(*)
from (select distinct c_last_name, c_first_name, d_date
      from store_sales, date_dim, customer
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_customer_sk = customer.c_customer_sk
        and d_month_seq between 360 and 360 + 11
      intersect
      select distinct c_last_name, c_first_name, d_date
      from catalog_sales, date_dim, customer
      where catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
        and catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
        and d_month_seq between 360 and 360 + 11
      intersect
      select distinct c_last_name, c_first_name, d_date
      from web_sales, date_dim, customer
      where web_sales.ws_sold_date_sk = date_dim.d_date_sk
        and web_sales.ws_bill_customer_sk = customer.c_customer_sk
        and d_month_seq between 360 and 360 + 11) hot_cust
limit 100
"""

Q[39] = """
with inv as (
  select w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy, stdev, mean,
         case when mean = 0 then null else stdev / mean end cov
  from (select w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy,
               stddev_samp(inv_quantity_on_hand) stdev,
               avg(inv_quantity_on_hand) mean
        from inventory, item, warehouse, date_dim
        where inv_item_sk = i_item_sk and inv_warehouse_sk = w_warehouse_sk
          and inv_date_sk = d_date_sk and d_year = 2001
        group by w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy) foo
  where case when mean = 0 then 0 else stdev / mean end > 1)
select inv1.w_warehouse_sk, inv1.i_item_sk, inv1.d_moy, inv1.mean, inv1.cov,
       inv2.w_warehouse_sk wsk2, inv2.i_item_sk isk2, inv2.d_moy moy2,
       inv2.mean mean2, inv2.cov cov2
from inv inv1, inv inv2
where inv1.i_item_sk = inv2.i_item_sk
  and inv1.w_warehouse_sk = inv2.w_warehouse_sk
  and inv1.d_moy = 1 and inv2.d_moy = 1 + 1
order by inv1.w_warehouse_sk, inv1.i_item_sk, inv1.d_moy, inv1.mean,
         inv1.cov, inv2.d_moy, inv2.mean, inv2.cov
"""

Q[40] = """
select w_state, i_item_id,
       sum(case when d_date < date '2000-03-11'
                then cs_sales_price - coalesce(cr_refunded_cash, 0)
                else 0 end) as sales_before,
       sum(case when d_date >= date '2000-03-11'
                then cs_sales_price - coalesce(cr_refunded_cash, 0)
                else 0 end) as sales_after
from catalog_sales
     left outer join catalog_returns
       on cs_order_number = cr_order_number and cs_item_sk = cr_item_sk,
     warehouse, item, date_dim
where i_current_price between 0.99 and 1.49 and i_item_sk = cs_item_sk
  and cs_warehouse_sk = w_warehouse_sk and cs_sold_date_sk = d_date_sk
  and d_date between date '2000-03-11' - interval '30' day
                 and date '2000-03-11' + interval '30' day
group by w_state, i_item_id
order by w_state, i_item_id
limit 100
"""

Q[41] = """
select distinct i_product_name
from item i1
where i_manufact_id between 738 and 738 + 40
  and (select count(*) as item_cnt
       from item
       where (i_manufact = i1.i_manufact
              and ((i_category = 'Women'
                    and (i_color = 'powder' or i_color = 'khaki')
                    and (i_units = 'Ounce' or i_units = 'Oz')
                    and (i_size = 'medium' or i_size = 'extra large'))
                or (i_category = 'Women'
                    and (i_color = 'brown' or i_color = 'honeydew')
                    and (i_units = 'Bunch' or i_units = 'Ton')
                    and (i_size = 'N/A' or i_size = 'small'))
                or (i_category = 'Men'
                    and (i_color = 'floral' or i_color = 'deep')
                    and (i_units = 'N/A' or i_units = 'Dozen')
                    and (i_size = 'petite' or i_size = 'large'))
                or (i_category = 'Men'
                    and (i_color = 'light' or i_color = 'cornflower')
                    and (i_units = 'Box' or i_units = 'Pound')
                    and (i_size = 'medium' or i_size = 'extra large'))))
          or (i_manufact = i1.i_manufact
              and ((i_category = 'Women'
                    and (i_color = 'midnight' or i_color = 'snow')
                    and (i_units = 'Pallet' or i_units = 'Gross')
                    and (i_size = 'medium' or i_size = 'extra large'))
                or (i_category = 'Women'
                    and (i_color = 'cyan' or i_color = 'papaya')
                    and (i_units = 'Cup' or i_units = 'Dram')
                    and (i_size = 'N/A' or i_size = 'small'))
                or (i_category = 'Men'
                    and (i_color = 'orange' or i_color = 'frosted')
                    and (i_units = 'Each' or i_units = 'Tbl')
                    and (i_size = 'petite' or i_size = 'large'))
                or (i_category = 'Men'
                    and (i_color = 'forest' or i_color = 'ghost')
                    and (i_units = 'Lb' or i_units = 'Gram')
                    and (i_size = 'medium' or i_size = 'extra large'))))
      ) > 0
order by i_product_name
limit 100
"""

Q[42] = """
select d_year, i_category_id, i_category, sum(ss_ext_sales_price) total
from date_dim dt, store_sales, item
where dt.d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 1 and dt.d_moy = 11 and dt.d_year = 2000
group by d_year, i_category_id, i_category
order by total desc, d_year, i_category_id, i_category
limit 100
"""

Q[43] = """
select s_store_name, s_store_id,
       sum(case when d_day_name = 'Sunday' then ss_sales_price
                else null end) sun_sales,
       sum(case when d_day_name = 'Monday' then ss_sales_price
                else null end) mon_sales,
       sum(case when d_day_name = 'Tuesday' then ss_sales_price
                else null end) tue_sales,
       sum(case when d_day_name = 'Wednesday' then ss_sales_price
                else null end) wed_sales,
       sum(case when d_day_name = 'Thursday' then ss_sales_price
                else null end) thu_sales,
       sum(case when d_day_name = 'Friday' then ss_sales_price
                else null end) fri_sales,
       sum(case when d_day_name = 'Saturday' then ss_sales_price
                else null end) sat_sales
from date_dim, store_sales, store
where d_date_sk = ss_sold_date_sk and s_store_sk = ss_store_sk
  and s_gmt_offset = -5.0 and d_year = 2000
group by s_store_name, s_store_id
order by s_store_name, s_store_id, sun_sales, mon_sales, tue_sales,
         wed_sales, thu_sales, fri_sales, sat_sales
limit 100
"""

Q[44] = """
select asceding.rnk, i1.i_product_name best_performing,
       i2.i_product_name worst_performing
from (select *
      from (select item_sk, rank() over (order by rank_col asc) rnk
            from (select ss_item_sk item_sk, avg(ss_net_profit) rank_col
                  from store_sales ss1
                  where ss_store_sk = 4
                  group by ss_item_sk
                  having avg(ss_net_profit)
                           > 0.9 * (select avg(ss_net_profit) rank_col
                                    from store_sales
                                    where ss_store_sk = 4
                                      and ss_addr_sk is null
                                    group by ss_store_sk)) v1) v11
      where rnk < 11) asceding,
     (select *
      from (select item_sk, rank() over (order by rank_col desc) rnk
            from (select ss_item_sk item_sk, avg(ss_net_profit) rank_col
                  from store_sales ss1
                  where ss_store_sk = 4
                  group by ss_item_sk
                  having avg(ss_net_profit)
                           > 0.9 * (select avg(ss_net_profit) rank_col
                                    from store_sales
                                    where ss_store_sk = 4
                                      and ss_addr_sk is null
                                    group by ss_store_sk)) v2) v21
      where rnk < 11) descending,
     item i1, item i2
where asceding.rnk = descending.rnk and i1.i_item_sk = asceding.item_sk
  and i2.i_item_sk = descending.item_sk
order by asceding.rnk
limit 100
"""

Q[45] = """
select ca_zip, ca_city, sum(ws_sales_price)
from web_sales, customer, customer_address, date_dim, item
where ws_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk and ws_item_sk = i_item_sk
  and (substr(ca_zip, 1, 5) in ('85669', '86197', '88274', '83405',
                                '86475', '85392', '85460', '80348', '81792')
       or i_item_id in (select i_item_id from item
                        where i_item_sk in (2, 3, 5, 7, 11, 13, 17, 19, 23,
                                            29)))
  and ws_sold_date_sk = d_date_sk and d_qoy = 2 and d_year = 2001
group by ca_zip, ca_city
order by ca_zip, ca_city
limit 100
"""

Q[46] = """
select c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       amt, profit
from (select ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      from store_sales, date_dim, store, household_demographics,
           customer_address
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk and ss_addr_sk = ca_address_sk
        and (hd_dep_count = 4 or hd_vehicle_count = 3)
        and d_dow in (6, 0) and d_year in (1999, 2000, 2001)
        and s_city in ('Fairview', 'Midway', 'Fairview', 'Fairview',
                       'Fairview')
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and customer.c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number
limit 100
"""

Q[47] = """
with v1 as (
  select i_category, i_brand, s_store_name, s_company_name, d_year, d_moy,
         sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price)) over (partition by i_category, i_brand,
                                        s_store_name, s_company_name, d_year)
           avg_monthly_sales,
         rank() over (partition by i_category, i_brand, s_store_name,
                      s_company_name
                      order by d_year, d_moy) rn
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and (d_year = 1999 or (d_year = 1998 and d_moy = 12)
         or (d_year = 2000 and d_moy = 1))
  group by i_category, i_brand, s_store_name, s_company_name, d_year, d_moy),
 v2 as (
  select v1.i_category, v1.i_brand, v1.s_store_name, v1.s_company_name,
         v1.d_year, v1.d_moy, v1.avg_monthly_sales, v1.sum_sales,
         v1_lag.sum_sales psum, v1_lead.sum_sales nsum
  from v1, v1 v1_lag, v1 v1_lead
  where v1.i_category = v1_lag.i_category
    and v1.i_category = v1_lead.i_category
    and v1.i_brand = v1_lag.i_brand and v1.i_brand = v1_lead.i_brand
    and v1.s_store_name = v1_lag.s_store_name
    and v1.s_store_name = v1_lead.s_store_name
    and v1.s_company_name = v1_lag.s_company_name
    and v1.s_company_name = v1_lead.s_company_name
    and v1.rn = v1_lag.rn + 1 and v1.rn = v1_lead.rn - 1)
select *
from v2
where d_year = 1999 and avg_monthly_sales > 0
  and case when avg_monthly_sales > 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else null end > 0.1
order by sum_sales - avg_monthly_sales, 3
limit 100
"""

Q[48] = """
select sum(ss_quantity)
from store_sales, store, customer_demographics, customer_address, date_dim
where s_store_sk = ss_store_sk and ss_sold_date_sk = d_date_sk
  and d_year = 2000
  and ((cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'M'
        and cd_education_status = '4 yr Degree'
        and ss_sales_price between 100.00 and 150.00)
    or (cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'D'
        and cd_education_status = '2 yr Degree'
        and ss_sales_price between 50.00 and 100.00)
    or (cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'S'
        and cd_education_status = 'College'
        and ss_sales_price between 150.00 and 200.00))
  and ((ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('CO', 'OH', 'TX') and ss_net_profit between 0 and 2000)
    or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('OR', 'MN', 'KY')
        and ss_net_profit between 150 and 3000)
    or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('VA', 'CA', 'MS')
        and ss_net_profit between 50 and 25000))
"""

Q[49] = """
select channel, item, return_ratio, return_rank, currency_rank
from (select 'web' as channel, web.item, web.return_ratio,
             web.return_rank, web.currency_rank
      from (select item, return_ratio, currency_ratio,
                   rank() over (order by return_ratio) as return_rank,
                   rank() over (order by currency_ratio) as currency_rank
            from (select ws.ws_item_sk as item,
                         cast(sum(coalesce(wr.wr_return_quantity, 0))
                              as double)
                           / cast(sum(coalesce(ws.ws_quantity, 0))
                                  as double) as return_ratio,
                         cast(sum(coalesce(wr.wr_return_amt, 0)) as double)
                           / cast(sum(coalesce(ws.ws_net_paid, 0))
                                  as double) as currency_ratio
                  from web_sales ws
                       left outer join web_returns wr
                         on ws.ws_order_number = wr.wr_order_number
                        and ws.ws_item_sk = wr.wr_item_sk,
                       date_dim
                  where wr.wr_return_amt > 100 and ws.ws_net_profit > 1
                    and ws.ws_net_paid > 0 and ws.ws_quantity > 0
                    and ws_sold_date_sk = d_date_sk and d_year = 2001
                    and d_moy = 12
                  group by ws.ws_item_sk) in_web) web
      where web.return_rank <= 10 or web.currency_rank <= 10
      union
      select 'catalog' as channel, catalog.item, catalog.return_ratio,
             catalog.return_rank, catalog.currency_rank
      from (select item, return_ratio, currency_ratio,
                   rank() over (order by return_ratio) as return_rank,
                   rank() over (order by currency_ratio) as currency_rank
            from (select cs.cs_item_sk as item,
                         cast(sum(coalesce(cr.cr_return_quantity, 0))
                              as double)
                           / cast(sum(coalesce(cs.cs_quantity, 0))
                                  as double) as return_ratio,
                         cast(sum(coalesce(cr.cr_return_amount, 0))
                              as double)
                           / cast(sum(coalesce(cs.cs_net_paid, 0))
                                  as double) as currency_ratio
                  from catalog_sales cs
                       left outer join catalog_returns cr
                         on cs.cs_order_number = cr.cr_order_number
                        and cs.cs_item_sk = cr.cr_item_sk,
                       date_dim
                  where cr.cr_return_amount > 100 and cs.cs_net_profit > 1
                    and cs.cs_net_paid > 0 and cs.cs_quantity > 0
                    and cs_sold_date_sk = d_date_sk and d_year = 2001
                    and d_moy = 12
                  group by cs.cs_item_sk) in_cat) catalog
      where catalog.return_rank <= 10 or catalog.currency_rank <= 10
      union
      select 'store' as channel, store.item, store.return_ratio,
             store.return_rank, store.currency_rank
      from (select item, return_ratio, currency_ratio,
                   rank() over (order by return_ratio) as return_rank,
                   rank() over (order by currency_ratio) as currency_rank
            from (select sts.ss_item_sk as item,
                         cast(sum(coalesce(sr.sr_return_quantity, 0))
                              as double)
                           / cast(sum(coalesce(sts.ss_quantity, 0))
                                  as double) as return_ratio,
                         cast(sum(coalesce(sr.sr_return_amt, 0)) as double)
                           / cast(sum(coalesce(sts.ss_net_paid, 0))
                                  as double) as currency_ratio
                  from store_sales sts
                       left outer join store_returns sr
                         on sts.ss_ticket_number = sr.sr_ticket_number
                        and sts.ss_item_sk = sr.sr_item_sk,
                       date_dim
                  where sr.sr_return_amt > 100 and sts.ss_net_profit > 1
                    and sts.ss_net_paid > 0 and sts.ss_quantity > 0
                    and ss_sold_date_sk = d_date_sk and d_year = 2001
                    and d_moy = 12
                  group by sts.ss_item_sk) in_store) store
      where store.return_rank <= 10 or store.currency_rank <= 10) x
order by 1, 4, 5, 2
limit 100
"""

Q[50] = """
select s_store_name, s_company_id, s_street_number, s_street_name,
       s_street_type, s_suite_number, s_city, s_county, s_state, s_zip,
       sum(case when sr_returned_date_sk - ss_sold_date_sk <= 30
                then 1 else 0 end) as days30,
       sum(case when sr_returned_date_sk - ss_sold_date_sk > 30
                 and sr_returned_date_sk - ss_sold_date_sk <= 60
                then 1 else 0 end) as days60,
       sum(case when sr_returned_date_sk - ss_sold_date_sk > 60
                 and sr_returned_date_sk - ss_sold_date_sk <= 90
                then 1 else 0 end) as days90,
       sum(case when sr_returned_date_sk - ss_sold_date_sk > 90
                 and sr_returned_date_sk - ss_sold_date_sk <= 120
                then 1 else 0 end) as days120,
       sum(case when sr_returned_date_sk - ss_sold_date_sk > 120
                then 1 else 0 end) as days_more_120
from store_sales, store_returns, store, date_dim d1, date_dim d2
where d2.d_year = 2001 and d2.d_moy = 8
  and ss_ticket_number = sr_ticket_number and ss_item_sk = sr_item_sk
  and ss_sold_date_sk = d1.d_date_sk and sr_returned_date_sk = d2.d_date_sk
  and ss_customer_sk = sr_customer_sk and ss_store_sk = s_store_sk
group by s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state, s_zip
order by s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state, s_zip
limit 100
"""
