"""TPC-DS queries 51-75 as SQL text."""

Q = {}

Q[51] = """
with web_v1 as (
  select ws_item_sk item_sk, d_date,
         sum(sum(ws_sales_price))
           over (partition by ws_item_sk order by d_date
                 rows between unbounded preceding and current row) cume_sales
  from web_sales, date_dim
  where ws_sold_date_sk = d_date_sk and d_month_seq between 360 and 360 + 11
    and ws_item_sk is not null
  group by ws_item_sk, d_date),
 store_v1 as (
  select ss_item_sk item_sk, d_date,
         sum(sum(ss_sales_price))
           over (partition by ss_item_sk order by d_date
                 rows between unbounded preceding and current row) cume_sales
  from store_sales, date_dim
  where ss_sold_date_sk = d_date_sk and d_month_seq between 360 and 360 + 11
    and ss_item_sk is not null
  group by ss_item_sk, d_date)
select *
from (select item_sk, d_date, web_sales, store_sales,
             max(web_sales) over (partition by item_sk order by d_date
                                  rows between unbounded preceding
                                           and current row) web_cumulative,
             max(store_sales) over (partition by item_sk order by d_date
                                    rows between unbounded preceding
                                             and current row) store_cumulative
      from (select case when web.item_sk is not null then web.item_sk
                        else store.item_sk end item_sk,
                   case when web.d_date is not null then web.d_date
                        else store.d_date end d_date,
                   web.cume_sales web_sales, store.cume_sales store_sales
            from web_v1 web full outer join store_v1 store
              on web.item_sk = store.item_sk and web.d_date = store.d_date
           ) x) y
where web_cumulative > store_cumulative
order by item_sk, d_date
limit 100
"""

Q[52] = """
select d_year, i_brand_id brand_id, i_brand brand, sum(ss_ext_sales_price) ext_price
from date_dim dt, store_sales, item
where dt.d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 1 and dt.d_moy = 11 and dt.d_year = 2000
group by d_year, i_brand, i_brand_id
order by d_year, ext_price desc, brand_id
limit 100
"""

Q[53] = """
select *
from (select i_manufact_id, sum(ss_sales_price) sum_sales,
             avg(sum(ss_sales_price))
               over (partition by i_manufact_id) avg_quarterly_sales
      from item, store_sales, date_dim, store
      where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and d_month_seq in (360, 361, 362, 363, 364, 365, 366, 367, 368,
                            369, 370, 371)
        and ((i_category in ('Books', 'Children', 'Electronics')
              and i_class in ('booksclass1', 'childrenclass2',
                              'electronicsclass3'))
          or (i_category in ('Women', 'Music', 'Men')
              and i_class in ('womenclass1', 'musicclass2', 'menclass4')))
      group by i_manufact_id, d_qoy) tmp1
where case when avg_quarterly_sales > 0
           then abs(sum_sales - avg_quarterly_sales) / avg_quarterly_sales
           else null end > 0.1
order by avg_quarterly_sales, sum_sales, i_manufact_id
limit 100
"""

Q[54] = """
with my_customers as (
  select distinct c_customer_sk, c_current_addr_sk
  from (select cs_sold_date_sk sold_date_sk, cs_bill_customer_sk customer_sk,
               cs_item_sk item_sk
        from catalog_sales
        union all
        select ws_sold_date_sk sold_date_sk, ws_bill_customer_sk customer_sk,
               ws_item_sk item_sk
        from web_sales) cs_or_ws_sales,
       item, date_dim, customer
  where sold_date_sk = d_date_sk and item_sk = i_item_sk
    and i_category = 'Women' and i_class like '%class%'
    and c_customer_sk = cs_or_ws_sales.customer_sk
    and d_moy = 12 and d_year = 1998),
 my_revenue as (
  select c_customer_sk, sum(ss_ext_sales_price) as revenue
  from my_customers, store_sales, customer_address, store, date_dim
  where c_current_addr_sk = ca_address_sk
    and ca_county = s_county and ca_state = s_state
    and ss_sold_date_sk = d_date_sk and c_customer_sk = ss_customer_sk
    and d_month_seq between (select distinct d_month_seq + 1 from date_dim
                             where d_year = 1998 and d_moy = 12)
                        and (select distinct d_month_seq + 3 from date_dim
                             where d_year = 1998 and d_moy = 12)
  group by c_customer_sk),
 segments as (
  select cast((revenue / 50) as int) as segment from my_revenue)
select segment, count(*) as num_customers, segment * 50 as segment_base
from segments
group by segment
order by segment, num_customers
limit 100
"""

Q[55] = """
select i_brand_id brand_id, i_brand brand, sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 28 and d_moy = 11 and d_year = 1999
group by i_brand, i_brand_id
order by ext_price desc, brand_id
limit 100
"""

Q[56] = """
with ss as (
  select i_item_id, sum(ss_ext_sales_price) total_sales
  from store_sales, date_dim, customer_address, item
  where i_item_id in (select i_item_id from item
                      where i_color in ('slate', 'blanched', 'burnished',
                                        'red', 'blue', 'green'))
    and ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
    and d_year = 2001 and d_moy = 2 and ss_addr_sk = ca_address_sk
    and ca_gmt_offset = -5.0
  group by i_item_id),
 cs as (
  select i_item_id, sum(cs_ext_sales_price) total_sales
  from catalog_sales, date_dim, customer_address, item
  where i_item_id in (select i_item_id from item
                      where i_color in ('slate', 'blanched', 'burnished',
                                        'red', 'blue', 'green'))
    and cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
    and d_year = 2001 and d_moy = 2 and cs_bill_addr_sk = ca_address_sk
    and ca_gmt_offset = -5.0
  group by i_item_id),
 ws as (
  select i_item_id, sum(ws_ext_sales_price) total_sales
  from web_sales, date_dim, customer_address, item
  where i_item_id in (select i_item_id from item
                      where i_color in ('slate', 'blanched', 'burnished',
                                        'red', 'blue', 'green'))
    and ws_item_sk = i_item_sk and ws_sold_date_sk = d_date_sk
    and d_year = 2001 and d_moy = 2 and ws_bill_addr_sk = ca_address_sk
    and ca_gmt_offset = -5.0
  group by i_item_id)
select i_item_id, sum(total_sales) total_sales
from (select * from ss
      union all
      select * from cs
      union all
      select * from ws) tmp1
group by i_item_id
order by total_sales, i_item_id
limit 100
"""

Q[57] = """
with v1 as (
  select i_category, i_brand, cc_name, d_year, d_moy,
         sum(cs_sales_price) sum_sales,
         avg(sum(cs_sales_price))
           over (partition by i_category, i_brand, cc_name, d_year)
           avg_monthly_sales,
         rank() over (partition by i_category, i_brand, cc_name
                      order by d_year, d_moy) rn
  from item, catalog_sales, date_dim, call_center
  where cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
    and cc_call_center_sk = cs_call_center_sk
    and (d_year = 1999 or (d_year = 1998 and d_moy = 12)
         or (d_year = 2000 and d_moy = 1))
  group by i_category, i_brand, cc_name, d_year, d_moy),
 v2 as (
  select v1.i_category, v1.i_brand, v1.cc_name, v1.d_year, v1.d_moy,
         v1.avg_monthly_sales, v1.sum_sales, v1_lag.sum_sales psum,
         v1_lead.sum_sales nsum
  from v1, v1 v1_lag, v1 v1_lead
  where v1.i_category = v1_lag.i_category
    and v1.i_category = v1_lead.i_category
    and v1.i_brand = v1_lag.i_brand and v1.i_brand = v1_lead.i_brand
    and v1.cc_name = v1_lag.cc_name and v1.cc_name = v1_lead.cc_name
    and v1.rn = v1_lag.rn + 1 and v1.rn = v1_lead.rn - 1)
select *
from v2
where d_year = 1999 and avg_monthly_sales > 0
  and case when avg_monthly_sales > 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else null end > 0.1
order by sum_sales - avg_monthly_sales, 3
limit 100
"""

Q[58] = """
with ss_items as (
  select i_item_id item_id, sum(ss_ext_sales_price) ss_item_rev
  from store_sales, item, date_dim
  where ss_item_sk = i_item_sk
    and d_date in (select d_date from date_dim
                   where d_week_seq = (select d_week_seq from date_dim
                                       where d_date = date '2000-01-03'))
    and ss_sold_date_sk = d_date_sk
  group by i_item_id),
 cs_items as (
  select i_item_id item_id, sum(cs_ext_sales_price) cs_item_rev
  from catalog_sales, item, date_dim
  where cs_item_sk = i_item_sk
    and d_date in (select d_date from date_dim
                   where d_week_seq = (select d_week_seq from date_dim
                                       where d_date = date '2000-01-03'))
    and cs_sold_date_sk = d_date_sk
  group by i_item_id),
 ws_items as (
  select i_item_id item_id, sum(ws_ext_sales_price) ws_item_rev
  from web_sales, item, date_dim
  where ws_item_sk = i_item_sk
    and d_date in (select d_date from date_dim
                   where d_week_seq = (select d_week_seq from date_dim
                                       where d_date = date '2000-01-03'))
    and ws_sold_date_sk = d_date_sk
  group by i_item_id)
select ss_items.item_id, ss_item_rev,
       ss_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100
         ss_dev,
       cs_item_rev,
       cs_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100
         cs_dev,
       ws_item_rev,
       ws_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100
         ws_dev,
       (ss_item_rev + cs_item_rev + ws_item_rev) / 3 average
from ss_items, cs_items, ws_items
where ss_items.item_id = cs_items.item_id
  and ss_items.item_id = ws_items.item_id
  and ss_item_rev between 0.9 * cs_item_rev and 1.1 * cs_item_rev
  and ss_item_rev between 0.9 * ws_item_rev and 1.1 * ws_item_rev
  and cs_item_rev between 0.9 * ss_item_rev and 1.1 * ss_item_rev
  and cs_item_rev between 0.9 * ws_item_rev and 1.1 * ws_item_rev
  and ws_item_rev between 0.9 * ss_item_rev and 1.1 * ss_item_rev
  and ws_item_rev between 0.9 * cs_item_rev and 1.1 * cs_item_rev
order by item_id, ss_item_rev
limit 100
"""

Q[59] = """
with wss as (
  select d_week_seq, ss_store_sk,
         sum(case when d_day_name = 'Sunday' then ss_sales_price
                  else null end) sun_sales,
         sum(case when d_day_name = 'Monday' then ss_sales_price
                  else null end) mon_sales,
         sum(case when d_day_name = 'Tuesday' then ss_sales_price
                  else null end) tue_sales,
         sum(case when d_day_name = 'Wednesday' then ss_sales_price
                  else null end) wed_sales,
         sum(case when d_day_name = 'Thursday' then ss_sales_price
                  else null end) thu_sales,
         sum(case when d_day_name = 'Friday' then ss_sales_price
                  else null end) fri_sales,
         sum(case when d_day_name = 'Saturday' then ss_sales_price
                  else null end) sat_sales
  from store_sales, date_dim
  where d_date_sk = ss_sold_date_sk
  group by d_week_seq, ss_store_sk)
select s_store_name1, s_store_id1, d_week_seq1,
       sun_sales1 / sun_sales2, mon_sales1 / mon_sales2,
       tue_sales1 / tue_sales2, wed_sales1 / wed_sales2,
       thu_sales1 / thu_sales2, fri_sales1 / fri_sales2,
       sat_sales1 / sat_sales2
from (select s_store_name s_store_name1, wss.d_week_seq d_week_seq1,
             s_store_id s_store_id1, sun_sales sun_sales1,
             mon_sales mon_sales1, tue_sales tue_sales1,
             wed_sales wed_sales1, thu_sales thu_sales1,
             fri_sales fri_sales1, sat_sales sat_sales1
      from wss, store, date_dim d
      where d.d_week_seq = wss.d_week_seq and ss_store_sk = s_store_sk
        and d_month_seq between 360 and 360 + 11) y,
     (select s_store_name s_store_name2, wss.d_week_seq d_week_seq2,
             s_store_id s_store_id2, sun_sales sun_sales2,
             mon_sales mon_sales2, tue_sales tue_sales2,
             wed_sales wed_sales2, thu_sales thu_sales2,
             fri_sales fri_sales2, sat_sales sat_sales2
      from wss, store, date_dim d
      where d.d_week_seq = wss.d_week_seq and ss_store_sk = s_store_sk
        and d_month_seq between 360 + 12 and 360 + 23) x
where s_store_id1 = s_store_id2 and d_week_seq1 = d_week_seq2 - 52
order by s_store_name1, s_store_id1, d_week_seq1
limit 100
"""

Q[60] = """
with ss as (
  select i_item_id, sum(ss_ext_sales_price) total_sales
  from store_sales, date_dim, customer_address, item
  where i_item_id in (select i_item_id from item where i_category = 'Music')
    and ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
    and d_year = 1998 and d_moy = 9 and ss_addr_sk = ca_address_sk
    and ca_gmt_offset = -5.0
  group by i_item_id),
 cs as (
  select i_item_id, sum(cs_ext_sales_price) total_sales
  from catalog_sales, date_dim, customer_address, item
  where i_item_id in (select i_item_id from item where i_category = 'Music')
    and cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
    and d_year = 1998 and d_moy = 9 and cs_bill_addr_sk = ca_address_sk
    and ca_gmt_offset = -5.0
  group by i_item_id),
 ws as (
  select i_item_id, sum(ws_ext_sales_price) total_sales
  from web_sales, date_dim, customer_address, item
  where i_item_id in (select i_item_id from item where i_category = 'Music')
    and ws_item_sk = i_item_sk and ws_sold_date_sk = d_date_sk
    and d_year = 1998 and d_moy = 9 and ws_bill_addr_sk = ca_address_sk
    and ca_gmt_offset = -5.0
  group by i_item_id)
select i_item_id, sum(total_sales) total_sales
from (select * from ss
      union all
      select * from cs
      union all
      select * from ws) tmp1
group by i_item_id
order by i_item_id, total_sales
limit 100
"""

Q[61] = """
select promotions, total,
       cast(promotions as double) / cast(total as double) * 100
from (select sum(ss_ext_sales_price) promotions
      from store_sales, store, promotion, date_dim, customer,
           customer_address, item
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_promo_sk = p_promo_sk and ss_customer_sk = c_customer_sk
        and ca_address_sk = c_current_addr_sk and ss_item_sk = i_item_sk
        and ca_gmt_offset = -5.0 and i_category = 'Jewelry'
        and (p_channel_dmail = 'Y' or p_channel_email = 'Y'
             or p_channel_tv = 'Y')
        and s_gmt_offset = -5.0 and d_year = 1998 and d_moy = 11) promotional_sales,
     (select sum(ss_ext_sales_price) total
      from store_sales, store, date_dim, customer, customer_address, item
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_customer_sk = c_customer_sk
        and ca_address_sk = c_current_addr_sk and ss_item_sk = i_item_sk
        and ca_gmt_offset = -5.0 and i_category = 'Jewelry'
        and s_gmt_offset = -5.0 and d_year = 1998 and d_moy = 11) all_sales
order by promotions, total
limit 100
"""

Q[62] = """
select substr(w_warehouse_name, 1, 20), sm_type, web_name,
       sum(case when ws_ship_date_sk - ws_sold_date_sk <= 30
                then 1 else 0 end) as days30,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 30
                 and ws_ship_date_sk - ws_sold_date_sk <= 60
                then 1 else 0 end) as days60,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 60
                 and ws_ship_date_sk - ws_sold_date_sk <= 90
                then 1 else 0 end) as days90,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 90
                 and ws_ship_date_sk - ws_sold_date_sk <= 120
                then 1 else 0 end) as days120,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 120
                then 1 else 0 end) as days_more_120
from web_sales, warehouse, ship_mode, web_site, date_dim
where d_month_seq between 360 and 360 + 11
  and ws_ship_date_sk = d_date_sk and ws_warehouse_sk = w_warehouse_sk
  and ws_ship_mode_sk = sm_ship_mode_sk and ws_web_site_sk = web_site_sk
group by substr(w_warehouse_name, 1, 20), sm_type, web_name
order by substr(w_warehouse_name, 1, 20), sm_type, web_name
limit 100
"""

Q[63] = """
select *
from (select i_manager_id, sum(ss_sales_price) sum_sales,
             avg(sum(ss_sales_price))
               over (partition by i_manager_id) avg_monthly_sales
      from item, store_sales, date_dim, store
      where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and d_month_seq in (360, 361, 362, 363, 364, 365, 366, 367, 368,
                            369, 370, 371)
        and ((i_category in ('Books', 'Children', 'Electronics')
              and i_class in ('booksclass1', 'childrenclass2',
                              'electronicsclass3'))
          or (i_category in ('Women', 'Music', 'Men')
              and i_class in ('womenclass1', 'musicclass2', 'menclass4')))
      group by i_manager_id, d_moy) tmp1
where case when avg_monthly_sales > 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else null end > 0.1
order by i_manager_id, avg_monthly_sales, sum_sales
limit 100
"""

Q[64] = """
with cs_ui as (
  select cs_item_sk,
         sum(cs_ext_list_price) as sale,
         sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit) as refund
  from catalog_sales, catalog_returns
  where cs_item_sk = cr_item_sk and cs_order_number = cr_order_number
  group by cs_item_sk
  having sum(cs_ext_list_price)
           > 2 * sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit)),
 cross_sales as (
  select i_product_name product_name, i_item_sk item_sk,
         s_store_name store_name, s_zip store_zip,
         ad1.ca_street_number b_street_number,
         ad1.ca_street_name b_street_name, ad1.ca_city b_city,
         ad1.ca_zip b_zip, ad2.ca_street_number c_street_number,
         ad2.ca_street_name c_street_name, ad2.ca_city c_city,
         ad2.ca_zip c_zip, d1.d_year as syear, d2.d_year as fsyear,
         d3.d_year s2year, count(*) cnt,
         sum(ss_wholesale_cost) s1, sum(ss_list_price) s2,
         sum(ss_coupon_amt) s3
  from store_sales, store_returns, cs_ui, date_dim d1, date_dim d2,
       date_dim d3, store, customer, customer_demographics cd1,
       customer_demographics cd2, promotion, household_demographics hd1,
       household_demographics hd2, customer_address ad1,
       customer_address ad2, income_band ib1, income_band ib2, item
  where ss_store_sk = s_store_sk and ss_sold_date_sk = d1.d_date_sk
    and ss_customer_sk = c_customer_sk and ss_cdemo_sk = cd1.cd_demo_sk
    and ss_hdemo_sk = hd1.hd_demo_sk and ss_addr_sk = ad1.ca_address_sk
    and ss_item_sk = i_item_sk and ss_item_sk = sr_item_sk
    and ss_ticket_number = sr_ticket_number
    and ss_item_sk = cs_ui.cs_item_sk
    and c_current_cdemo_sk = cd2.cd_demo_sk
    and c_current_hdemo_sk = hd2.hd_demo_sk
    and c_current_addr_sk = ad2.ca_address_sk
    and c_first_sales_date_sk = d2.d_date_sk
    and c_first_shipto_date_sk = d3.d_date_sk
    and ss_promo_sk = p_promo_sk
    and hd1.hd_income_band_sk = ib1.ib_income_band_sk
    and hd2.hd_income_band_sk = ib2.ib_income_band_sk
    and cd1.cd_marital_status <> cd2.cd_marital_status
    and i_color in ('purple', 'burlywood', 'indian', 'spring',
                    'floral', 'medium')
    and i_current_price between 64 and 64 + 10
    and i_current_price between 64 + 1 and 64 + 15
  group by i_product_name, i_item_sk, s_store_name, s_zip,
           ad1.ca_street_number, ad1.ca_street_name, ad1.ca_city, ad1.ca_zip,
           ad2.ca_street_number, ad2.ca_street_name, ad2.ca_city, ad2.ca_zip,
           d1.d_year, d2.d_year, d3.d_year)
select cs1.product_name, cs1.store_name, cs1.store_zip,
       cs1.b_street_number, cs1.b_street_name, cs1.b_city, cs1.b_zip,
       cs1.c_street_number, cs1.c_street_name, cs1.c_city, cs1.c_zip,
       cs1.syear, cs1.cnt, cs1.s1 as s11, cs1.s2 as s21, cs1.s3 as s31,
       cs2.s1 as s12, cs2.s2 as s22, cs2.s3 as s32, cs2.syear as syear2,
       cs2.cnt as cnt2
from cross_sales cs1, cross_sales cs2
where cs1.item_sk = cs2.item_sk and cs1.syear = 1999
  and cs2.syear = 1999 + 1 and cs2.cnt <= cs1.cnt
  and cs1.store_name = cs2.store_name and cs1.store_zip = cs2.store_zip
order by cs1.product_name, cs1.store_name, cnt2, s12, s22, s32
"""

Q[65] = """
select s_store_name, i_item_desc, sc.revenue, i_current_price,
       i_wholesale_cost, i_brand
from store, item,
     (select ss_store_sk, avg(revenue) as ave
      from (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
            from store_sales, date_dim
            where ss_sold_date_sk = d_date_sk
              and d_month_seq between 360 and 360 + 11
            group by ss_store_sk, ss_item_sk) sa
      group by ss_store_sk) sb,
     (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
      from store_sales, date_dim
      where ss_sold_date_sk = d_date_sk
        and d_month_seq between 360 and 360 + 11
      group by ss_store_sk, ss_item_sk) sc
where sb.ss_store_sk = sc.ss_store_sk and sc.revenue <= 0.1 * sb.ave
  and s_store_sk = sc.ss_store_sk and i_item_sk = sc.ss_item_sk
order by s_store_name, i_item_desc, sc.revenue
limit 100
"""

Q[66] = """
select w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
       w_country, ship_carriers, year_,
       sum(jan_sales) as jan_sales, sum(feb_sales) as feb_sales,
       sum(mar_sales) as mar_sales, sum(apr_sales) as apr_sales,
       sum(may_sales) as may_sales, sum(jun_sales) as jun_sales,
       sum(jul_sales) as jul_sales, sum(aug_sales) as aug_sales,
       sum(sep_sales) as sep_sales, sum(oct_sales) as oct_sales,
       sum(nov_sales) as nov_sales, sum(dec_sales) as dec_sales,
       sum(jan_net) as jan_net, sum(feb_net) as feb_net,
       sum(mar_net) as mar_net, sum(apr_net) as apr_net,
       sum(may_net) as may_net, sum(jun_net) as jun_net,
       sum(jul_net) as jul_net, sum(aug_net) as aug_net,
       sum(sep_net) as sep_net, sum(oct_net) as oct_net,
       sum(nov_net) as nov_net, sum(dec_net) as dec_net
from (select w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
             w_state, w_country,
             'DHL' || ',' || 'BARIAN' as ship_carriers, d_year as year_,
             sum(case when d_moy = 1 then ws_ext_sales_price * ws_quantity
                      else 0 end) as jan_sales,
             sum(case when d_moy = 2 then ws_ext_sales_price * ws_quantity
                      else 0 end) as feb_sales,
             sum(case when d_moy = 3 then ws_ext_sales_price * ws_quantity
                      else 0 end) as mar_sales,
             sum(case when d_moy = 4 then ws_ext_sales_price * ws_quantity
                      else 0 end) as apr_sales,
             sum(case when d_moy = 5 then ws_ext_sales_price * ws_quantity
                      else 0 end) as may_sales,
             sum(case when d_moy = 6 then ws_ext_sales_price * ws_quantity
                      else 0 end) as jun_sales,
             sum(case when d_moy = 7 then ws_ext_sales_price * ws_quantity
                      else 0 end) as jul_sales,
             sum(case when d_moy = 8 then ws_ext_sales_price * ws_quantity
                      else 0 end) as aug_sales,
             sum(case when d_moy = 9 then ws_ext_sales_price * ws_quantity
                      else 0 end) as sep_sales,
             sum(case when d_moy = 10 then ws_ext_sales_price * ws_quantity
                      else 0 end) as oct_sales,
             sum(case when d_moy = 11 then ws_ext_sales_price * ws_quantity
                      else 0 end) as nov_sales,
             sum(case when d_moy = 12 then ws_ext_sales_price * ws_quantity
                      else 0 end) as dec_sales,
             sum(case when d_moy = 1 then ws_net_paid * ws_quantity
                      else 0 end) as jan_net,
             sum(case when d_moy = 2 then ws_net_paid * ws_quantity
                      else 0 end) as feb_net,
             sum(case when d_moy = 3 then ws_net_paid * ws_quantity
                      else 0 end) as mar_net,
             sum(case when d_moy = 4 then ws_net_paid * ws_quantity
                      else 0 end) as apr_net,
             sum(case when d_moy = 5 then ws_net_paid * ws_quantity
                      else 0 end) as may_net,
             sum(case when d_moy = 6 then ws_net_paid * ws_quantity
                      else 0 end) as jun_net,
             sum(case when d_moy = 7 then ws_net_paid * ws_quantity
                      else 0 end) as jul_net,
             sum(case when d_moy = 8 then ws_net_paid * ws_quantity
                      else 0 end) as aug_net,
             sum(case when d_moy = 9 then ws_net_paid * ws_quantity
                      else 0 end) as sep_net,
             sum(case when d_moy = 10 then ws_net_paid * ws_quantity
                      else 0 end) as oct_net,
             sum(case when d_moy = 11 then ws_net_paid * ws_quantity
                      else 0 end) as nov_net,
             sum(case when d_moy = 12 then ws_net_paid * ws_quantity
                      else 0 end) as dec_net
      from web_sales, warehouse, date_dim, time_dim, ship_mode
      where ws_warehouse_sk = w_warehouse_sk and ws_sold_date_sk = d_date_sk
        and ws_sold_time_sk = t_time_sk and ws_ship_mode_sk = sm_ship_mode_sk
        and d_year = 2001 and t_time between 30838 and 30838 + 28800
        and sm_carrier in ('DHL', 'BARIAN')
      group by w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
               w_state, w_country, d_year
      union all
      select w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
             w_state, w_country,
             'DHL' || ',' || 'BARIAN' as ship_carriers, d_year as year_,
             sum(case when d_moy = 1 then cs_sales_price * cs_quantity
                      else 0 end) as jan_sales,
             sum(case when d_moy = 2 then cs_sales_price * cs_quantity
                      else 0 end) as feb_sales,
             sum(case when d_moy = 3 then cs_sales_price * cs_quantity
                      else 0 end) as mar_sales,
             sum(case when d_moy = 4 then cs_sales_price * cs_quantity
                      else 0 end) as apr_sales,
             sum(case when d_moy = 5 then cs_sales_price * cs_quantity
                      else 0 end) as may_sales,
             sum(case when d_moy = 6 then cs_sales_price * cs_quantity
                      else 0 end) as jun_sales,
             sum(case when d_moy = 7 then cs_sales_price * cs_quantity
                      else 0 end) as jul_sales,
             sum(case when d_moy = 8 then cs_sales_price * cs_quantity
                      else 0 end) as aug_sales,
             sum(case when d_moy = 9 then cs_sales_price * cs_quantity
                      else 0 end) as sep_sales,
             sum(case when d_moy = 10 then cs_sales_price * cs_quantity
                      else 0 end) as oct_sales,
             sum(case when d_moy = 11 then cs_sales_price * cs_quantity
                      else 0 end) as nov_sales,
             sum(case when d_moy = 12 then cs_sales_price * cs_quantity
                      else 0 end) as dec_sales,
             sum(case when d_moy = 1 then cs_net_paid_inc_tax * cs_quantity
                      else 0 end) as jan_net,
             sum(case when d_moy = 2 then cs_net_paid_inc_tax * cs_quantity
                      else 0 end) as feb_net,
             sum(case when d_moy = 3 then cs_net_paid_inc_tax * cs_quantity
                      else 0 end) as mar_net,
             sum(case when d_moy = 4 then cs_net_paid_inc_tax * cs_quantity
                      else 0 end) as apr_net,
             sum(case when d_moy = 5 then cs_net_paid_inc_tax * cs_quantity
                      else 0 end) as may_net,
             sum(case when d_moy = 6 then cs_net_paid_inc_tax * cs_quantity
                      else 0 end) as jun_net,
             sum(case when d_moy = 7 then cs_net_paid_inc_tax * cs_quantity
                      else 0 end) as jul_net,
             sum(case when d_moy = 8 then cs_net_paid_inc_tax * cs_quantity
                      else 0 end) as aug_net,
             sum(case when d_moy = 9 then cs_net_paid_inc_tax * cs_quantity
                      else 0 end) as sep_net,
             sum(case when d_moy = 10 then cs_net_paid_inc_tax * cs_quantity
                      else 0 end) as oct_net,
             sum(case when d_moy = 11 then cs_net_paid_inc_tax * cs_quantity
                      else 0 end) as nov_net,
             sum(case when d_moy = 12 then cs_net_paid_inc_tax * cs_quantity
                      else 0 end) as dec_net
      from catalog_sales, warehouse, date_dim, time_dim, ship_mode
      where cs_warehouse_sk = w_warehouse_sk and cs_sold_date_sk = d_date_sk
        and cs_sold_time_sk = t_time_sk and cs_ship_mode_sk = sm_ship_mode_sk
        and d_year = 2001 and t_time between 30838 and 30838 + 28800
        and sm_carrier in ('DHL', 'BARIAN')
      group by w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
               w_state, w_country, d_year) x
group by w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state,
         w_country, ship_carriers, year_
order by w_warehouse_name
limit 100
"""

Q[67] = """
select *
from (select i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
             d_moy, s_store_id, sumsales,
             rank() over (partition by i_category
                          order by sumsales desc) rk
      from (select i_category, i_class, i_brand, i_product_name, d_year,
                   d_qoy, d_moy, s_store_id,
                   sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales
            from store_sales, date_dim, store, item
            where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
              and ss_store_sk = s_store_sk
              and d_month_seq between 360 and 360 + 11
            group by rollup (i_category, i_class, i_brand, i_product_name,
                             d_year, d_qoy, d_moy, s_store_id)) dw1) dw2
where rk <= 100
order by i_category nulls last, i_class nulls last, i_brand nulls last,
         i_product_name nulls last, d_year nulls last, d_qoy nulls last,
         d_moy nulls last, s_store_id nulls last, sumsales, rk
limit 100
"""

Q[68] = """
select c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       extended_price, extended_tax, list_price
from (select ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_ext_sales_price) extended_price,
             sum(ss_ext_list_price) list_price,
             sum(ss_ext_tax) extended_tax
      from store_sales, date_dim, store, household_demographics,
           customer_address
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk and ss_addr_sk = ca_address_sk
        and d_dom between 1 and 2 and d_year in (1999, 2000, 2001)
        and (hd_dep_count = 4 or hd_vehicle_count = 3)
        and s_city in ('Fairview', 'Midway')
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and customer.c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, ss_ticket_number
limit 100
"""

Q[69] = """
select cd_gender, cd_marital_status, cd_education_status, count(*) cnt1,
       cd_purchase_estimate, count(*) cnt2, cd_credit_rating, count(*) cnt3
from customer c, customer_address ca, customer_demographics
where c.c_current_addr_sk = ca.ca_address_sk
  and ca_state in ('KY', 'GA', 'NM')
  and cd_demo_sk = c.c_current_cdemo_sk
  and exists (select * from store_sales, date_dim
              where c.c_customer_sk = ss_customer_sk
                and ss_sold_date_sk = d_date_sk and d_year = 2001
                and d_moy between 4 and 4 + 2)
  and not exists (select * from web_sales, date_dim
                  where c.c_customer_sk = ws_bill_customer_sk
                    and ws_sold_date_sk = d_date_sk and d_year = 2001
                    and d_moy between 4 and 4 + 2)
  and not exists (select * from catalog_sales, date_dim
                  where c.c_customer_sk = cs_ship_customer_sk
                    and cs_sold_date_sk = d_date_sk and d_year = 2001
                    and d_moy between 4 and 4 + 2)
group by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
order by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
limit 100
"""

Q[70] = """
select sum(ss_net_profit) as total_sum, s_state, s_county,
       grouping(s_state) + grouping(s_county) as lochierarchy,
       rank() over (partition by grouping(s_state) + grouping(s_county),
                    case when grouping(s_county) = 0 then s_state end
                    order by sum(ss_net_profit) desc) as rank_within_parent
from store_sales, date_dim d1, store
where d1.d_month_seq between 360 and 360 + 11
  and d1.d_date_sk = ss_sold_date_sk and s_store_sk = ss_store_sk
  and s_state in (select s_state
                  from (select s_state as s_state,
                               rank() over (partition by s_state
                                            order by sum(ss_net_profit) desc)
                                 ranking
                        from store_sales, store, date_dim
                        where d_month_seq between 360 and 360 + 11
                          and d_date_sk = ss_sold_date_sk
                          and s_store_sk = ss_store_sk
                        group by s_state) tmp1
                  where ranking <= 5)
group by rollup (s_state, s_county)
order by lochierarchy desc, case when lochierarchy = 0 then s_state end,
         rank_within_parent
limit 100
"""

Q[71] = """
select i_brand_id brand_id, i_brand brand, t_hour, t_minute,
       sum(ext_price) ext_price
from item,
     (select ws_ext_sales_price as ext_price,
             ws_sold_date_sk as sold_date_sk, ws_item_sk as sold_item_sk,
             ws_sold_time_sk as time_sk
      from web_sales, date_dim
      where d_date_sk = ws_sold_date_sk and d_moy = 11 and d_year = 1999
      union all
      select cs_ext_sales_price as ext_price,
             cs_sold_date_sk as sold_date_sk, cs_item_sk as sold_item_sk,
             cs_sold_time_sk as time_sk
      from catalog_sales, date_dim
      where d_date_sk = cs_sold_date_sk and d_moy = 11 and d_year = 1999
      union all
      select ss_ext_sales_price as ext_price,
             ss_sold_date_sk as sold_date_sk, ss_item_sk as sold_item_sk,
             ss_sold_time_sk as time_sk
      from store_sales, date_dim
      where d_date_sk = ss_sold_date_sk and d_moy = 11 and d_year = 1999
     ) tmp, time_dim
where sold_item_sk = i_item_sk and i_manager_id = 1
  and time_sk = t_time_sk
  and (t_meal_time = 'breakfast' or t_meal_time = 'dinner')
group by i_brand, i_brand_id, t_hour, t_minute
order by ext_price desc, brand_id
"""

Q[72] = """
select i_item_desc, w_warehouse_name, d1.d_week_seq,
       sum(case when p_promo_sk is null then 1 else 0 end) no_promo,
       sum(case when p_promo_sk is not null then 1 else 0 end) promo,
       count(*) total_cnt
from catalog_sales
     join inventory on (cs_item_sk = inv_item_sk)
     join warehouse on (w_warehouse_sk = inv_warehouse_sk)
     join item on (i_item_sk = cs_item_sk)
     join customer_demographics on (cs_bill_cdemo_sk = cd_demo_sk)
     join household_demographics on (cs_bill_hdemo_sk = hd_demo_sk)
     join date_dim d1 on (cs_sold_date_sk = d1.d_date_sk)
     join date_dim d2 on (inv_date_sk = d2.d_date_sk)
     join date_dim d3 on (cs_ship_date_sk = d3.d_date_sk)
     left outer join promotion on (cs_promo_sk = p_promo_sk)
     left outer join catalog_returns on (cr_item_sk = cs_item_sk
                                         and cr_order_number = cs_order_number)
where d1.d_week_seq = d2.d_week_seq and inv_quantity_on_hand < cs_quantity
  and d3.d_date > d1.d_date + interval '5' day
  and hd_buy_potential = '>10000' and d1.d_year = 1999
  and cd_marital_status = 'D'
group by i_item_desc, w_warehouse_name, d1.d_week_seq
order by total_cnt desc, i_item_desc, w_warehouse_name, d1.d_week_seq
limit 100
"""

Q[73] = """
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) cnt
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk and d_dom between 1 and 2
        and (hd_buy_potential = '>10000' or hd_buy_potential = 'Unknown')
        and hd_vehicle_count > 0
        and case when hd_vehicle_count > 0
                 then cast(hd_dep_count as double) / hd_vehicle_count
                 else null end > 1
        and d_year in (1999, 2000, 2001)
        and s_county in ('Ziebach County', 'Williamson County',
                         'Walker County', 'Salem County')
      group by ss_ticket_number, ss_customer_sk) dj,
     customer
where ss_customer_sk = c_customer_sk and cnt between 1 and 5
order by cnt desc, c_last_name asc
"""

Q[74] = """
with year_total as (
  select c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name, d_year as year_,
         sum(ss_net_paid) year_total, 's' sale_type
  from customer, store_sales, date_dim
  where c_customer_sk = ss_customer_sk and ss_sold_date_sk = d_date_sk
    and d_year in (2001, 2001 + 1)
  group by c_customer_id, c_first_name, c_last_name, d_year
  union all
  select c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name, d_year as year_,
         sum(ws_net_paid) year_total, 'w' sale_type
  from customer, web_sales, date_dim
  where c_customer_sk = ws_bill_customer_sk and ws_sold_date_sk = d_date_sk
    and d_year in (2001, 2001 + 1)
  group by c_customer_id, c_first_name, c_last_name, d_year)
select t_s_secyear.customer_id, t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name
from year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
where t_s_secyear.customer_id = t_s_firstyear.customer_id
  and t_s_firstyear.customer_id = t_w_secyear.customer_id
  and t_s_firstyear.customer_id = t_w_firstyear.customer_id
  and t_s_firstyear.sale_type = 's' and t_w_firstyear.sale_type = 'w'
  and t_s_secyear.sale_type = 's' and t_w_secyear.sale_type = 'w'
  and t_s_firstyear.year_ = 2001 and t_s_secyear.year_ = 2001 + 1
  and t_w_firstyear.year_ = 2001 and t_w_secyear.year_ = 2001 + 1
  and t_s_firstyear.year_total > 0 and t_w_firstyear.year_total > 0
  and case when t_w_firstyear.year_total > 0
           then t_w_secyear.year_total / t_w_firstyear.year_total
           else null end
        > case when t_s_firstyear.year_total > 0
               then t_s_secyear.year_total / t_s_firstyear.year_total
               else null end
order by 1, 1, 1
limit 100
"""

Q[75] = """
with all_sales as (
  select d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
         sum(sales_cnt) as sales_cnt, sum(sales_amt) as sales_amt
  from (select d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
               cs_quantity - coalesce(cr_return_quantity, 0) as sales_cnt,
               cs_ext_sales_price - coalesce(cr_return_amount, 0.0)
                 as sales_amt
        from catalog_sales
             join item on i_item_sk = cs_item_sk
             join date_dim on d_date_sk = cs_sold_date_sk
             left join catalog_returns on (cs_order_number = cr_order_number
                                           and cs_item_sk = cr_item_sk)
        where i_category = 'Books'
        union
        select d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
               ss_quantity - coalesce(sr_return_quantity, 0) as sales_cnt,
               ss_ext_sales_price - coalesce(sr_return_amt, 0.0) as sales_amt
        from store_sales
             join item on i_item_sk = ss_item_sk
             join date_dim on d_date_sk = ss_sold_date_sk
             left join store_returns on (ss_ticket_number = sr_ticket_number
                                         and ss_item_sk = sr_item_sk)
        where i_category = 'Books'
        union
        select d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,
               ws_quantity - coalesce(wr_return_quantity, 0) as sales_cnt,
               ws_ext_sales_price - coalesce(wr_return_amt, 0.0) as sales_amt
        from web_sales
             join item on i_item_sk = ws_item_sk
             join date_dim on d_date_sk = ws_sold_date_sk
             left join web_returns on (ws_order_number = wr_order_number
                                       and ws_item_sk = wr_item_sk)
        where i_category = 'Books') sales_detail
  group by d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id)
select prev_yr.d_year as prev_year, curr_yr.d_year as year_,
       curr_yr.i_brand_id, curr_yr.i_class_id, curr_yr.i_category_id,
       curr_yr.i_manufact_id, prev_yr.sales_cnt as prev_yr_cnt,
       curr_yr.sales_cnt as curr_yr_cnt,
       curr_yr.sales_cnt - prev_yr.sales_cnt as sales_cnt_diff,
       curr_yr.sales_amt - prev_yr.sales_amt as sales_amt_diff
from all_sales curr_yr, all_sales prev_yr
where curr_yr.i_brand_id = prev_yr.i_brand_id
  and curr_yr.i_class_id = prev_yr.i_class_id
  and curr_yr.i_category_id = prev_yr.i_category_id
  and curr_yr.i_manufact_id = prev_yr.i_manufact_id
  and curr_yr.d_year = 2002 and prev_yr.d_year = 2002 - 1
  and cast(curr_yr.sales_cnt as double) / cast(prev_yr.sales_cnt as double)
        < 0.9
order by sales_cnt_diff, sales_amt_diff
limit 100
"""
