"""TPC-DS queries 76-99 as SQL text."""

Q = {}

Q[76] = """
select channel, col_name, d_year, d_qoy, i_category, count(*) sales_cnt,
       sum(ext_sales_price) sales_amt
from (select 'store' as channel, 'ss_store_sk' col_name, d_year, d_qoy,
             i_category, ss_ext_sales_price ext_sales_price
      from store_sales, item, date_dim
      where ss_store_sk is null and ss_sold_date_sk = d_date_sk
        and ss_item_sk = i_item_sk
      union all
      select 'web' as channel, 'ws_promo_sk' col_name, d_year, d_qoy,
             i_category, ws_ext_sales_price ext_sales_price
      from web_sales, item, date_dim
      where ws_promo_sk is null and ws_sold_date_sk = d_date_sk
        and ws_item_sk = i_item_sk
      union all
      select 'catalog' as channel, 'cs_promo_sk' col_name, d_year, d_qoy,
             i_category, cs_ext_sales_price ext_sales_price
      from catalog_sales, item, date_dim
      where cs_promo_sk is null and cs_sold_date_sk = d_date_sk
        and cs_item_sk = i_item_sk) foo
group by channel, col_name, d_year, d_qoy, i_category
order by channel, col_name, d_year, d_qoy, i_category
limit 100
"""

Q[77] = """
with ss as (
  select s_store_sk, sum(ss_ext_sales_price) as sales,
         sum(ss_net_profit) as profit
  from store_sales, date_dim, store
  where ss_sold_date_sk = d_date_sk
    and d_date between date '2000-08-23'
                   and date '2000-08-23' + interval '30' day
    and ss_store_sk = s_store_sk
  group by s_store_sk),
 sr as (
  select s_store_sk, sum(sr_return_amt) as returns_,
         sum(sr_net_loss) as profit_loss
  from store_returns, date_dim, store
  where sr_returned_date_sk = d_date_sk
    and d_date between date '2000-08-23'
                   and date '2000-08-23' + interval '30' day
    and sr_store_sk = s_store_sk
  group by s_store_sk),
 cs as (
  select cs_call_center_sk, sum(cs_ext_sales_price) as sales,
         sum(cs_net_profit) as profit
  from catalog_sales, date_dim
  where cs_sold_date_sk = d_date_sk
    and d_date between date '2000-08-23'
                   and date '2000-08-23' + interval '30' day
  group by cs_call_center_sk),
 cr as (
  select cr_call_center_sk, sum(cr_return_amount) as returns_,
         sum(cr_net_loss) as profit_loss
  from catalog_returns, date_dim
  where cr_returned_date_sk = d_date_sk
    and d_date between date '2000-08-23'
                   and date '2000-08-23' + interval '30' day
  group by cr_call_center_sk),
 ws as (
  select wp_web_page_sk, sum(ws_ext_sales_price) as sales,
         sum(ws_net_profit) as profit
  from web_sales, date_dim, web_page
  where ws_sold_date_sk = d_date_sk
    and d_date between date '2000-08-23'
                   and date '2000-08-23' + interval '30' day
    and ws_web_page_sk = wp_web_page_sk
  group by wp_web_page_sk),
 wr as (
  select wp_web_page_sk, sum(wr_return_amt) as returns_,
         sum(wr_net_loss) as profit_loss
  from web_returns, date_dim, web_page
  where wr_returned_date_sk = d_date_sk
    and d_date between date '2000-08-23'
                   and date '2000-08-23' + interval '30' day
    and wr_web_page_sk = wp_web_page_sk
  group by wp_web_page_sk)
select channel, id, sum(sales) as sales, sum(returns_) as returns_,
       sum(profit) as profit
from (select 'store channel' as channel, ss.s_store_sk as id, sales,
             coalesce(returns_, 0) returns_,
             (profit - coalesce(profit_loss, 0)) as profit
      from ss left join sr on ss.s_store_sk = sr.s_store_sk
      union all
      select 'catalog channel' as channel, cs_call_center_sk as id, sales,
             returns_, (profit - profit_loss) as profit
      from cs, cr
      union all
      select 'web channel' as channel, ws.wp_web_page_sk as id, sales,
             coalesce(returns_, 0) returns_,
             (profit - coalesce(profit_loss, 0)) as profit
      from ws left join wr on ws.wp_web_page_sk = wr.wp_web_page_sk
     ) x
group by rollup (channel, id)
order by channel nulls last, id nulls last, sales
limit 100
"""

Q[78] = """
with ws as (
  select d_year as ws_sold_year, ws_item_sk,
         ws_bill_customer_sk ws_customer_sk, sum(ws_quantity) ws_qty,
         sum(ws_wholesale_cost) ws_wc, sum(ws_sales_price) ws_sp
  from web_sales
       left join web_returns on wr_order_number = ws_order_number
                            and ws_item_sk = wr_item_sk,
       date_dim
  where wr_order_number is null and ws_sold_date_sk = d_date_sk
  group by d_year, ws_item_sk, ws_bill_customer_sk),
 cs as (
  select d_year as cs_sold_year, cs_item_sk,
         cs_bill_customer_sk cs_customer_sk, sum(cs_quantity) cs_qty,
         sum(cs_wholesale_cost) cs_wc, sum(cs_sales_price) cs_sp
  from catalog_sales
       left join catalog_returns on cr_order_number = cs_order_number
                                and cs_item_sk = cr_item_sk,
       date_dim
  where cr_order_number is null and cs_sold_date_sk = d_date_sk
  group by d_year, cs_item_sk, cs_bill_customer_sk),
 ss as (
  select d_year as ss_sold_year, ss_item_sk,
         ss_customer_sk, sum(ss_quantity) ss_qty,
         sum(ss_wholesale_cost) ss_wc, sum(ss_sales_price) ss_sp
  from store_sales
       left join store_returns on sr_ticket_number = ss_ticket_number
                              and ss_item_sk = sr_item_sk,
       date_dim
  where sr_ticket_number is null and ss_sold_date_sk = d_date_sk
  group by d_year, ss_item_sk, ss_customer_sk)
select ss_sold_year, ss_item_sk, ss_customer_sk,
       round(cast(ss_qty as double)
             / (coalesce(ws_qty, 0) + coalesce(cs_qty, 0) + 1), 2) ratio,
       ss_qty store_qty, ss_wc store_wholesale_cost,
       ss_sp store_sales_price,
       coalesce(ws_qty, 0) + coalesce(cs_qty, 0) other_chan_qty,
       coalesce(ws_wc, 0) + coalesce(cs_wc, 0) other_chan_wholesale_cost,
       coalesce(ws_sp, 0) + coalesce(cs_sp, 0) other_chan_sales_price
from ss
     left join ws on (ws_sold_year = ss_sold_year
                      and ws_item_sk = ss_item_sk
                      and ws_customer_sk = ss_customer_sk)
     left join cs on (cs_sold_year = ss_sold_year
                      and cs_item_sk = ss_item_sk
                      and cs_customer_sk = ss_customer_sk)
where (coalesce(ws_qty, 0) > 0 or coalesce(cs_qty, 0) > 0)
  and ss_sold_year = 2000
order by ss_sold_year, ss_item_sk, ss_customer_sk, store_qty desc,
         store_wholesale_cost desc, store_sales_price desc, other_chan_qty,
         other_chan_wholesale_cost, other_chan_sales_price, ratio
limit 100
"""

Q[79] = """
select c_last_name, c_first_name, substr(s_city, 1, 30), ss_ticket_number,
       amt, profit
from (select ss_ticket_number, ss_customer_sk, store.s_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and (hd_dep_count = 6 or hd_vehicle_count > 2)
        and d_dow = 1 and d_year in (1999, 2000, 2001)
        and s_number_employees between 200 and 295
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk,
               store.s_city) ms,
     customer
where ss_customer_sk = c_customer_sk
order by c_last_name, c_first_name, substr(s_city, 1, 30), profit
limit 100
"""

Q[80] = """
with ssr as (
  select s_store_id as store_id, sum(ss_ext_sales_price) as sales,
         sum(coalesce(sr_return_amt, 0)) as returns_,
         sum(ss_net_profit - coalesce(sr_net_loss, 0)) as profit
  from store_sales
       left outer join store_returns
         on (ss_item_sk = sr_item_sk and ss_ticket_number = sr_ticket_number),
       date_dim, store, item, promotion
  where ss_sold_date_sk = d_date_sk
    and d_date between date '2000-08-23'
                   and date '2000-08-23' + interval '30' day
    and ss_store_sk = s_store_sk and ss_item_sk = i_item_sk
    and i_current_price > 50 and ss_promo_sk = p_promo_sk
    and p_channel_tv = 'N'
  group by s_store_id),
 csr as (
  select cp_catalog_page_id as catalog_page_id,
         sum(cs_ext_sales_price) as sales,
         sum(coalesce(cr_return_amount, 0)) as returns_,
         sum(cs_net_profit - coalesce(cr_net_loss, 0)) as profit
  from catalog_sales
       left outer join catalog_returns
         on (cs_item_sk = cr_item_sk and cs_order_number = cr_order_number),
       date_dim, catalog_page, item, promotion
  where cs_sold_date_sk = d_date_sk
    and d_date between date '2000-08-23'
                   and date '2000-08-23' + interval '30' day
    and cs_catalog_page_sk = cp_catalog_page_sk and cs_item_sk = i_item_sk
    and i_current_price > 50 and cs_promo_sk = p_promo_sk
    and p_channel_tv = 'N'
  group by cp_catalog_page_id),
 wsr as (
  select web_site_id, sum(ws_ext_sales_price) as sales,
         sum(coalesce(wr_return_amt, 0)) as returns_,
         sum(ws_net_profit - coalesce(wr_net_loss, 0)) as profit
  from web_sales
       left outer join web_returns
         on (ws_item_sk = wr_item_sk and ws_order_number = wr_order_number),
       date_dim, web_site, item, promotion
  where ws_sold_date_sk = d_date_sk
    and d_date between date '2000-08-23'
                   and date '2000-08-23' + interval '30' day
    and ws_web_site_sk = web_site_sk and ws_item_sk = i_item_sk
    and i_current_price > 50 and ws_promo_sk = p_promo_sk
    and p_channel_tv = 'N'
  group by web_site_id)
select channel, id, sum(sales) as sales, sum(returns_) as returns_,
       sum(profit) as profit
from (select 'store channel' as channel, 'store' || store_id as id,
             sales, returns_, profit
      from ssr
      union all
      select 'catalog channel' as channel,
             'catalog_page' || catalog_page_id as id, sales, returns_,
             profit
      from csr
      union all
      select 'web channel' as channel, 'web_site' || web_site_id as id,
             sales, returns_, profit
      from wsr) x
group by rollup (channel, id)
order by channel nulls last, id nulls last, sales
limit 100
"""

Q[81] = """
with customer_total_return as (
  select cr_returning_customer_sk as ctr_customer_sk, ca_state as ctr_state,
         sum(cr_return_amt_inc_tax) as ctr_total_return
  from catalog_returns, date_dim, customer_address
  where cr_returned_date_sk = d_date_sk and d_year = 2000
    and cr_returning_addr_sk = ca_address_sk
  group by cr_returning_customer_sk, ca_state)
select c_customer_id, c_salutation, c_first_name, c_last_name,
       ca_street_number, ca_street_name, ca_street_type, ca_suite_number,
       ca_city, ca_county, ca_state, ca_zip, ca_country, ca_gmt_offset,
       ca_location_type, ctr_total_return
from customer_total_return ctr1, customer_address, customer
where ctr1.ctr_total_return > (select avg(ctr_total_return) * 1.2
                               from customer_total_return ctr2
                               where ctr1.ctr_state = ctr2.ctr_state)
  and ca_address_sk = c_current_addr_sk and ca_state = 'GA'
  and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id, c_salutation, c_first_name, c_last_name,
         ca_street_number, ca_street_name, ca_street_type, ca_suite_number,
         ca_city, ca_county, ca_state, ca_zip, ca_country, ca_gmt_offset,
         ca_location_type, ctr_total_return
limit 100
"""

Q[82] = """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, store_sales
where i_current_price between 62 and 62 + 30 and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '2000-05-25' and date '2000-05-25' + interval '60' day
  and i_manufact_id in (129, 270, 821, 423)
  and inv_quantity_on_hand between 100 and 500 and ss_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
"""

Q[83] = """
with sr_items as (
  select i_item_id item_id, sum(sr_return_quantity) sr_item_qty
  from store_returns, item, date_dim
  where sr_item_sk = i_item_sk
    and d_date in (select d_date from date_dim
                   where d_week_seq in (select d_week_seq from date_dim
                                        where d_date in (date '2000-06-30',
                                                         date '2000-09-27',
                                                         date '2000-11-17')))
    and sr_returned_date_sk = d_date_sk
  group by i_item_id),
 cr_items as (
  select i_item_id item_id, sum(cr_return_quantity) cr_item_qty
  from catalog_returns, item, date_dim
  where cr_item_sk = i_item_sk
    and d_date in (select d_date from date_dim
                   where d_week_seq in (select d_week_seq from date_dim
                                        where d_date in (date '2000-06-30',
                                                         date '2000-09-27',
                                                         date '2000-11-17')))
    and cr_returned_date_sk = d_date_sk
  group by i_item_id),
 wr_items as (
  select i_item_id item_id, sum(wr_return_quantity) wr_item_qty
  from web_returns, item, date_dim
  where wr_item_sk = i_item_sk
    and d_date in (select d_date from date_dim
                   where d_week_seq in (select d_week_seq from date_dim
                                        where d_date in (date '2000-06-30',
                                                         date '2000-09-27',
                                                         date '2000-11-17')))
    and wr_returned_date_sk = d_date_sk
  group by i_item_id)
select sr_items.item_id,
       sr_item_qty,
       cast(sr_item_qty as double)
         / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 * 100 sr_dev,
       cr_item_qty,
       cast(cr_item_qty as double)
         / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 * 100 cr_dev,
       wr_item_qty,
       cast(wr_item_qty as double)
         / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 * 100 wr_dev,
       (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 average
from sr_items, cr_items, wr_items
where sr_items.item_id = cr_items.item_id
  and sr_items.item_id = wr_items.item_id
order by sr_items.item_id, sr_item_qty
limit 100
"""

Q[84] = """
select c_customer_id as customer_id,
       coalesce(c_last_name, '') || ', ' || coalesce(c_first_name, '')
         as customername
from customer, customer_address, customer_demographics,
     household_demographics, income_band, store_returns
where ca_city = 'Fairview' and c_current_addr_sk = ca_address_sk
  and ib_lower_bound >= 30000 and ib_upper_bound <= 30000 + 50000
  and ib_income_band_sk = hd_income_band_sk
  and cd_demo_sk = c_current_cdemo_sk
  and hd_demo_sk = c_current_hdemo_sk
  and sr_cdemo_sk = cd_demo_sk
order by c_customer_id
limit 100
"""

Q[85] = """
select substr(r_reason_desc, 1, 20), avg(ws_quantity), avg(wr_refunded_cash),
       avg(wr_fee)
from web_sales, web_returns, web_page, customer_demographics cd1,
     customer_demographics cd2, customer_address, date_dim, reason
where ws_web_page_sk = wp_web_page_sk and ws_item_sk = wr_item_sk
  and ws_order_number = wr_order_number
  and ws_sold_date_sk = d_date_sk and d_year = 2000
  and cd1.cd_demo_sk = wr_refunded_cdemo_sk
  and cd2.cd_demo_sk = wr_returning_cdemo_sk
  and ca_address_sk = wr_refunded_addr_sk and r_reason_sk = wr_reason_sk
  and ((cd1.cd_marital_status = 'M'
        and cd1.cd_marital_status = cd2.cd_marital_status
        and cd1.cd_education_status = 'Advanced Degree'
        and cd1.cd_education_status = cd2.cd_education_status
        and ws_sales_price between 100.00 and 150.00)
    or (cd1.cd_marital_status = 'S'
        and cd1.cd_marital_status = cd2.cd_marital_status
        and cd1.cd_education_status = 'College'
        and cd1.cd_education_status = cd2.cd_education_status
        and ws_sales_price between 50.00 and 100.00)
    or (cd1.cd_marital_status = 'W'
        and cd1.cd_marital_status = cd2.cd_marital_status
        and cd1.cd_education_status = '2 yr Degree'
        and cd1.cd_education_status = cd2.cd_education_status
        and ws_sales_price between 150.00 and 200.00))
  and ((ca_country = 'United States' and ca_state in ('IN', 'OH', 'NJ')
        and ws_net_profit between 100 and 200)
    or (ca_country = 'United States' and ca_state in ('WI', 'CT', 'KY')
        and ws_net_profit between 150 and 300)
    or (ca_country = 'United States' and ca_state in ('LA', 'IA', 'AR')
        and ws_net_profit between 50 and 250))
group by r_reason_desc
order by substr(r_reason_desc, 1, 20), avg(ws_quantity),
         avg(wr_refunded_cash), avg(wr_fee)
limit 100
"""

Q[86] = """
select sum(ws_net_paid) as total_sum, i_category, i_class,
       grouping(i_category) + grouping(i_class) as lochierarchy,
       rank() over (partition by grouping(i_category) + grouping(i_class),
                    case when grouping(i_class) = 0 then i_category end
                    order by sum(ws_net_paid) desc) as rank_within_parent
from web_sales, date_dim d1, item
where d1.d_month_seq between 360 and 360 + 11
  and d1.d_date_sk = ws_sold_date_sk and i_item_sk = ws_item_sk
group by rollup (i_category, i_class)
order by lochierarchy desc, case when lochierarchy = 0 then i_category end,
         rank_within_parent
limit 100
"""

Q[87] = """
select count(*)
from ((select distinct c_last_name, c_first_name, d_date
       from store_sales, date_dim, customer
       where store_sales.ss_sold_date_sk = date_dim.d_date_sk
         and store_sales.ss_customer_sk = customer.c_customer_sk
         and d_month_seq between 360 and 360 + 11)
      except
      (select distinct c_last_name, c_first_name, d_date
       from catalog_sales, date_dim, customer
       where catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
         and catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
         and d_month_seq between 360 and 360 + 11)
      except
      (select distinct c_last_name, c_first_name, d_date
       from web_sales, date_dim, customer
       where web_sales.ws_sold_date_sk = date_dim.d_date_sk
         and web_sales.ws_bill_customer_sk = customer.c_customer_sk
         and d_month_seq between 360 and 360 + 11)) cool_cust
"""

Q[88] = """
select *
from (select count(*) h8_30_to_9
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk and time_dim.t_hour = 8
        and time_dim.t_minute >= 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 4 + 2)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 2 + 2)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 0 + 2))
        and store.s_store_name = 'ese') s1,
     (select count(*) h9_to_9_30
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk and time_dim.t_hour = 9
        and time_dim.t_minute < 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 4 + 2)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 2 + 2)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 0 + 2))
        and store.s_store_name = 'ese') s2,
     (select count(*) h9_30_to_10
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk and time_dim.t_hour = 9
        and time_dim.t_minute >= 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 4 + 2)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 2 + 2)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 0 + 2))
        and store.s_store_name = 'ese') s3,
     (select count(*) h10_to_10_30
      from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = time_dim.t_time_sk
        and ss_hdemo_sk = household_demographics.hd_demo_sk
        and ss_store_sk = s_store_sk and time_dim.t_hour = 10
        and time_dim.t_minute < 30
        and ((household_demographics.hd_dep_count = 4
              and household_demographics.hd_vehicle_count <= 4 + 2)
          or (household_demographics.hd_dep_count = 2
              and household_demographics.hd_vehicle_count <= 2 + 2)
          or (household_demographics.hd_dep_count = 0
              and household_demographics.hd_vehicle_count <= 0 + 2))
        and store.s_store_name = 'ese') s4
"""

Q[89] = """
select *
from (select i_category, i_class, i_brand, s_store_name, s_company_name,
             d_moy, sum(ss_sales_price) sum_sales,
             avg(sum(ss_sales_price))
               over (partition by i_category, i_brand, s_store_name,
                     s_company_name) avg_monthly_sales
      from item, store_sales, date_dim, store
      where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk and d_year in (1999)
        and ((i_category in ('Books', 'Electronics', 'Sports')
              and i_class in ('booksclass1', 'electronicsclass2',
                              'sportsclass3'))
          or (i_category in ('Men', 'Jewelry', 'Women')
              and i_class in ('menclass1', 'jewelryclass2', 'womenclass3')))
      group by i_category, i_class, i_brand, s_store_name, s_company_name,
               d_moy) tmp1
where case when avg_monthly_sales <> 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else null end > 0.1
order by sum_sales - avg_monthly_sales, s_store_name
limit 100
"""

Q[90] = """
select cast(amc as double) / cast(pmc as double) am_pm_ratio
from (select count(*) amc
      from web_sales, household_demographics, time_dim, web_page
      where ws_sold_time_sk = time_dim.t_time_sk
        and ws_ship_hdemo_sk = household_demographics.hd_demo_sk
        and ws_web_page_sk = web_page.wp_web_page_sk
        and time_dim.t_hour between 8 and 8 + 1
        and household_demographics.hd_dep_count = 6
        and web_page.wp_char_count between 5000 and 5200) at_,
     (select count(*) pmc
      from web_sales, household_demographics, time_dim, web_page
      where ws_sold_time_sk = time_dim.t_time_sk
        and ws_ship_hdemo_sk = household_demographics.hd_demo_sk
        and ws_web_page_sk = web_page.wp_web_page_sk
        and time_dim.t_hour between 19 and 19 + 1
        and household_demographics.hd_dep_count = 6
        and web_page.wp_char_count between 5000 and 5200) pt
order by am_pm_ratio
limit 100
"""

Q[91] = """
select cc_call_center_id call_center, cc_name call_center_name,
       cc_manager manager, sum(cr_net_loss) returns_loss
from call_center, catalog_returns, date_dim, customer,
     customer_address, customer_demographics, household_demographics
where cr_call_center_sk = cc_call_center_sk
  and cr_returned_date_sk = d_date_sk
  and cr_returning_customer_sk = c_customer_sk
  and cd_demo_sk = c_current_cdemo_sk and hd_demo_sk = c_current_hdemo_sk
  and ca_address_sk = c_current_addr_sk and d_year = 1998 and d_moy = 11
  and ((cd_marital_status = 'M' and cd_education_status = 'Unknown')
    or (cd_marital_status = 'W' and cd_education_status = 'Advanced Degree'))
  and hd_buy_potential like 'Unknown%' and ca_gmt_offset = -7.0
group by cc_call_center_id, cc_name, cc_manager, cd_marital_status,
         cd_education_status
order by returns_loss desc
"""

Q[92] = """
select sum(ws_ext_discount_amt) as excess_discount_amount
from web_sales, item, date_dim
where i_manufact_id = 350 and i_item_sk = ws_item_sk
  and d_date between date '2000-01-27' and date '2000-01-27' + interval '90' day
  and d_date_sk = ws_sold_date_sk
  and ws_ext_discount_amt > (
    select 1.3 * avg(ws_ext_discount_amt)
    from web_sales, date_dim
    where ws_item_sk = i_item_sk and d_date_sk = ws_sold_date_sk
      and d_date between date '2000-01-27'
                     and date '2000-01-27' + interval '90' day)
order by sum(ws_ext_discount_amt)
limit 100
"""

Q[93] = """
select ss_customer_sk, sum(act_sales) sumsales
from (select ss_item_sk, ss_ticket_number, ss_customer_sk,
             case when sr_return_quantity is not null
                  then (ss_quantity - sr_return_quantity) * ss_sales_price
                  else ss_quantity * ss_sales_price end act_sales
      from store_sales
           left outer join store_returns
             on (sr_item_sk = ss_item_sk
                 and sr_ticket_number = ss_ticket_number),
           reason
      where sr_reason_sk = r_reason_sk
        and r_reason_desc = 'Package was damaged') t
group by ss_customer_sk
order by sumsales, ss_customer_sk
limit 100
"""

Q[94] = """
select count(distinct ws_order_number) as order_count,
       sum(ws_ext_ship_cost) as total_shipping_cost,
       sum(ws_net_profit) as total_net_profit
from web_sales ws1, date_dim, customer_address, web_site
where d_date between date '1999-02-01' and date '1999-02-01' + interval '60' day
  and ws1.ws_ship_date_sk = d_date_sk
  and ws1.ws_ship_addr_sk = ca_address_sk and ca_state = 'IL'
  and ws1.ws_web_site_sk = web_site_sk and web_company_name = 'pri'
  and exists (select * from web_sales ws2
              where ws1.ws_order_number = ws2.ws_order_number
                and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
  and not exists (select * from web_returns wr1
                  where ws1.ws_order_number = wr1.wr_order_number)
order by count(distinct ws_order_number)
limit 100
"""

Q[95] = """
with ws_wh as (
  select ws1.ws_order_number, ws1.ws_warehouse_sk wh1,
         ws2.ws_warehouse_sk wh2
  from web_sales ws1, web_sales ws2
  where ws1.ws_order_number = ws2.ws_order_number
    and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
select count(distinct ws_order_number) as order_count,
       sum(ws_ext_ship_cost) as total_shipping_cost,
       sum(ws_net_profit) as total_net_profit
from web_sales ws1, date_dim, customer_address, web_site
where d_date between date '1999-02-01' and date '1999-02-01' + interval '60' day
  and ws1.ws_ship_date_sk = d_date_sk
  and ws1.ws_ship_addr_sk = ca_address_sk and ca_state = 'IL'
  and ws1.ws_web_site_sk = web_site_sk and web_company_name = 'pri'
  and ws1.ws_order_number in (select ws_order_number from ws_wh)
  and ws1.ws_order_number in (select wr_order_number
                              from web_returns, ws_wh
                              where wr_order_number = ws_wh.ws_order_number)
order by count(distinct ws_order_number)
limit 100
"""

Q[96] = """
select count(*) cnt
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = time_dim.t_time_sk
  and ss_hdemo_sk = household_demographics.hd_demo_sk
  and ss_store_sk = s_store_sk and time_dim.t_hour = 20
  and time_dim.t_minute >= 30
  and household_demographics.hd_dep_count = 7
  and store.s_store_name = 'ese'
order by cnt
limit 100
"""

Q[97] = """
with ssci as (
  select ss_customer_sk customer_sk, ss_item_sk item_sk
  from store_sales, date_dim
  where ss_sold_date_sk = d_date_sk and d_month_seq between 360 and 360 + 11
  group by ss_customer_sk, ss_item_sk),
 csci as (
  select cs_bill_customer_sk customer_sk, cs_item_sk item_sk
  from catalog_sales, date_dim
  where cs_sold_date_sk = d_date_sk and d_month_seq between 360 and 360 + 11
  group by cs_bill_customer_sk, cs_item_sk)
select sum(case when ssci.customer_sk is not null
                 and csci.customer_sk is null then 1 else 0 end)
         store_only,
       sum(case when ssci.customer_sk is null
                 and csci.customer_sk is not null then 1 else 0 end)
         catalog_only,
       sum(case when ssci.customer_sk is not null
                 and csci.customer_sk is not null then 1 else 0 end)
         store_and_catalog
from ssci full outer join csci on (ssci.customer_sk = csci.customer_sk
                                   and ssci.item_sk = csci.item_sk)
limit 100
"""

Q[98] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ss_ext_sales_price) as itemrevenue,
       sum(ss_ext_sales_price) * 100
         / sum(sum(ss_ext_sales_price)) over (partition by i_class)
         as revenueratio
from store_sales, item, date_dim
where ss_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ss_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and date '1999-02-22' + interval '30' day
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
"""

Q[99] = """
select substr(w_warehouse_name, 1, 20), sm_type, cc_name,
       sum(case when cs_ship_date_sk - cs_sold_date_sk <= 30
                then 1 else 0 end) as days30,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 30
                 and cs_ship_date_sk - cs_sold_date_sk <= 60
                then 1 else 0 end) as days60,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 60
                 and cs_ship_date_sk - cs_sold_date_sk <= 90
                then 1 else 0 end) as days90,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 90
                 and cs_ship_date_sk - cs_sold_date_sk <= 120
                then 1 else 0 end) as days120,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 120
                then 1 else 0 end) as days_more_120
from catalog_sales, warehouse, ship_mode, call_center, date_dim
where d_month_seq between 360 and 360 + 11
  and cs_ship_date_sk = d_date_sk and cs_warehouse_sk = w_warehouse_sk
  and cs_ship_mode_sk = sm_ship_mode_sk and cs_call_center_sk = cc_call_center_sk
group by substr(w_warehouse_name, 1, 20), sm_type, cc_name
order by substr(w_warehouse_name, 1, 20), sm_type, cc_name
limit 100
"""
