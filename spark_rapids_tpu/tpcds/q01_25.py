"""TPC-DS queries 1-25 as SQL text (see queries_sql.py for the battery
notes: spec query shapes — CTE reuse, decorrelated subqueries, rollups,
windows — with parameters landing in the generator's value domains)."""

Q = {}

Q[1] = """
with customer_total_return as (
  select sr_customer_sk as ctr_customer_sk, sr_store_sk as ctr_store_sk,
         sum(sr_return_amt) as ctr_total_return
  from store_returns, date_dim
  where sr_returned_date_sk = d_date_sk and d_year = 2000
  group by sr_customer_sk, sr_store_sk)
select c_customer_id
from customer_total_return ctr1, store, customer
where ctr1.ctr_total_return > (select avg(ctr_total_return) * 1.2
                               from customer_total_return ctr2
                               where ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  and s_store_sk = ctr1.ctr_store_sk and s_state = 'AL'
  and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id
limit 100
"""

Q[2] = """
with wscs as (
  select sold_date_sk, sales_price
  from (select ws_sold_date_sk sold_date_sk, ws_ext_sales_price sales_price
        from web_sales
        union all
        select cs_sold_date_sk sold_date_sk, cs_ext_sales_price sales_price
        from catalog_sales) x),
 wswscs as (
  select d_week_seq,
         sum(case when d_day_name = 'Sunday' then sales_price else null end)
           sun_sales,
         sum(case when d_day_name = 'Monday' then sales_price else null end)
           mon_sales,
         sum(case when d_day_name = 'Tuesday' then sales_price else null end)
           tue_sales,
         sum(case when d_day_name = 'Wednesday' then sales_price else null end)
           wed_sales,
         sum(case when d_day_name = 'Thursday' then sales_price else null end)
           thu_sales,
         sum(case when d_day_name = 'Friday' then sales_price else null end)
           fri_sales,
         sum(case when d_day_name = 'Saturday' then sales_price else null end)
           sat_sales
  from wscs, date_dim
  where d_date_sk = sold_date_sk
  group by d_week_seq)
select d_week_seq1, round(sun_sales1 / sun_sales2, 2),
       round(mon_sales1 / mon_sales2, 2), round(tue_sales1 / tue_sales2, 2),
       round(wed_sales1 / wed_sales2, 2), round(thu_sales1 / thu_sales2, 2),
       round(fri_sales1 / fri_sales2, 2), round(sat_sales1 / sat_sales2, 2)
from (select wswscs.d_week_seq d_week_seq1, sun_sales sun_sales1,
             mon_sales mon_sales1, tue_sales tue_sales1,
             wed_sales wed_sales1, thu_sales thu_sales1,
             fri_sales fri_sales1, sat_sales sat_sales1
      from wswscs, date_dim
      where date_dim.d_week_seq = wswscs.d_week_seq and d_year = 2000) y,
     (select wswscs.d_week_seq d_week_seq2, sun_sales sun_sales2,
             mon_sales mon_sales2, tue_sales tue_sales2,
             wed_sales wed_sales2, thu_sales thu_sales2,
             fri_sales fri_sales2, sat_sales sat_sales2
      from wswscs, date_dim
      where date_dim.d_week_seq = wswscs.d_week_seq and d_year = 2001) z
where d_week_seq1 = d_week_seq2 - 53
order by d_week_seq1
"""

Q[3] = """
select d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) sum_agg
from date_dim dt, store_sales, item
where dt.d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manufact_id = 128 and dt.d_moy = 11
group by d_year, i_brand_id, i_brand
order by d_year, sum_agg desc, brand_id
limit 100
"""

Q[4] = """
with year_total as (
  select c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name, d_year dyear,
         sum(((ss_ext_list_price - ss_ext_wholesale_cost
               - ss_ext_discount_amt) + ss_ext_sales_price) / 2) year_total,
         's' sale_type
  from customer, store_sales, date_dim
  where c_customer_sk = ss_customer_sk and ss_sold_date_sk = d_date_sk
  group by c_customer_id, c_first_name, c_last_name, d_year
  union all
  select c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name, d_year dyear,
         sum(((cs_ext_list_price - cs_ext_wholesale_cost
               - cs_ext_discount_amt) + cs_ext_sales_price) / 2) year_total,
         'c' sale_type
  from customer, catalog_sales, date_dim
  where c_customer_sk = cs_bill_customer_sk and cs_sold_date_sk = d_date_sk
  group by c_customer_id, c_first_name, c_last_name, d_year
  union all
  select c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name, d_year dyear,
         sum(((ws_ext_list_price - ws_ext_wholesale_cost
               - ws_ext_discount_amt) + ws_ext_sales_price) / 2) year_total,
         'w' sale_type
  from customer, web_sales, date_dim
  where c_customer_sk = ws_bill_customer_sk and ws_sold_date_sk = d_date_sk
  group by c_customer_id, c_first_name, c_last_name, d_year)
select t_s_secyear.customer_id, t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name
from year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_c_firstyear, year_total t_c_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
where t_s_secyear.customer_id = t_s_firstyear.customer_id
  and t_s_firstyear.customer_id = t_c_secyear.customer_id
  and t_s_firstyear.customer_id = t_c_firstyear.customer_id
  and t_s_firstyear.customer_id = t_w_firstyear.customer_id
  and t_s_firstyear.customer_id = t_w_secyear.customer_id
  and t_s_firstyear.sale_type = 's' and t_c_firstyear.sale_type = 'c'
  and t_w_firstyear.sale_type = 'w' and t_s_secyear.sale_type = 's'
  and t_c_secyear.sale_type = 'c' and t_w_secyear.sale_type = 'w'
  and t_s_firstyear.dyear = 2001 and t_s_secyear.dyear = 2002
  and t_c_firstyear.dyear = 2001 and t_c_secyear.dyear = 2002
  and t_w_firstyear.dyear = 2001 and t_w_secyear.dyear = 2002
  and t_s_firstyear.year_total > 0 and t_c_firstyear.year_total > 0
  and t_w_firstyear.year_total > 0
  and t_c_secyear.year_total / t_c_firstyear.year_total
        > t_s_secyear.year_total / t_s_firstyear.year_total
  and t_c_secyear.year_total / t_c_firstyear.year_total
        > t_w_secyear.year_total / t_w_firstyear.year_total
order by t_s_secyear.customer_id, t_s_secyear.customer_first_name,
         t_s_secyear.customer_last_name
limit 100
"""

Q[5] = """
with ssr as (
  select s_store_id, sum(sales_price) as sales, sum(profit) as profit,
         sum(return_amt) as returns_, sum(net_loss) as profit_loss
  from (select ss_store_sk as store_sk, ss_sold_date_sk as date_sk,
               ss_ext_sales_price as sales_price, ss_net_profit as profit,
               cast(0.0 as double) as return_amt, cast(0.0 as double) as net_loss
        from store_sales
        union all
        select sr_store_sk as store_sk, sr_returned_date_sk as date_sk,
               cast(0.0 as double) as sales_price, cast(0.0 as double) as profit,
               sr_return_amt as return_amt, sr_net_loss as net_loss
        from store_returns) salesreturns,
       date_dim, store
  where date_sk = d_date_sk
    and d_date between date '2000-08-23' and date '2000-08-23' + interval '14' day
    and store_sk = s_store_sk
  group by s_store_id),
 csr as (
  select cp_catalog_page_id, sum(sales_price) as sales, sum(profit) as profit,
         sum(return_amt) as returns_, sum(net_loss) as profit_loss
  from (select cs_catalog_page_sk as page_sk, cs_sold_date_sk as date_sk,
               cs_ext_sales_price as sales_price, cs_net_profit as profit,
               cast(0.0 as double) as return_amt, cast(0.0 as double) as net_loss
        from catalog_sales
        union all
        select cr_catalog_page_sk as page_sk, cr_returned_date_sk as date_sk,
               cast(0.0 as double) as sales_price, cast(0.0 as double) as profit,
               cr_return_amount as return_amt, cr_net_loss as net_loss
        from catalog_returns) salesreturns,
       date_dim, catalog_page
  where date_sk = d_date_sk
    and d_date between date '2000-08-23' and date '2000-08-23' + interval '14' day
    and page_sk = cp_catalog_page_sk
  group by cp_catalog_page_id),
 wsr as (
  select web_site_id, sum(sales_price) as sales, sum(profit) as profit,
         sum(return_amt) as returns_, sum(net_loss) as profit_loss
  from (select ws_web_site_sk as wsr_web_site_sk, ws_sold_date_sk as date_sk,
               ws_ext_sales_price as sales_price, ws_net_profit as profit,
               cast(0.0 as double) as return_amt, cast(0.0 as double) as net_loss
        from web_sales
        union all
        select ws_web_site_sk as wsr_web_site_sk,
               wr_returned_date_sk as date_sk,
               cast(0.0 as double) as sales_price, cast(0.0 as double) as profit,
               wr_return_amt as return_amt, wr_net_loss as net_loss
        from web_returns left outer join web_sales
          on wr_item_sk = ws_item_sk and wr_order_number = ws_order_number
       ) salesreturns,
       date_dim, web_site
  where date_sk = d_date_sk
    and d_date between date '2000-08-23' and date '2000-08-23' + interval '14' day
    and wsr_web_site_sk = web_site_sk
  group by web_site_id)
select channel, id, sum(sales) as sales, sum(returns_) as returns_,
       sum(profit) as profit
from (select 'store channel' as channel, 'store' || s_store_id as id,
             sales, returns_, profit - profit_loss as profit
      from ssr
      union all
      select 'catalog channel' as channel,
             'catalog_page' || cp_catalog_page_id as id,
             sales, returns_, profit - profit_loss as profit
      from csr
      union all
      select 'web channel' as channel, 'web_site' || web_site_id as id,
             sales, returns_, profit - profit_loss as profit
      from wsr) x
group by rollup (channel, id)
order by channel nulls last, id nulls last, sales
limit 100
"""

Q[6] = """
select a.ca_state state, count(*) cnt
from customer_address a, customer c, store_sales s, date_dim d, item i
where a.ca_address_sk = c.c_current_addr_sk
  and c.c_customer_sk = s.ss_customer_sk and s.ss_sold_date_sk = d.d_date_sk
  and s.ss_item_sk = i.i_item_sk
  and d.d_month_seq = (select distinct d_month_seq from date_dim
                       where d_year = 2001 and d_moy = 1)
  and i.i_current_price > 1.2 * (select avg(j.i_current_price) from item j
                                 where j.i_category = i.i_category)
group by a.ca_state
having count(*) >= 10
order by cnt, state
limit 100
"""

Q[7] = """
select i_item_id, avg(ss_quantity) agg1, avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk and ss_promo_sk = p_promo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N') and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""

Q[8] = """
select s_store_name, sum(ss_net_profit)
from store_sales, date_dim, store,
     (select ca_zip
      from (select substr(ca_zip, 1, 5) ca_zip
            from customer_address
            where substr(ca_zip, 1, 5) in ('24128', '57834', '13354',
              '15734', '78668', '76232', '62878', '82235', '78890', '60512',
              '26233', '51200', '63837', '40558', '81989', '88190', '35474',
              '10003', '10004', '10005', '10006', '10007', '10008', '10009')
            intersect
            select substr(ca_zip, 1, 5) ca_zip
            from customer_address ca, customer c
            where ca.ca_address_sk = c.c_current_addr_sk
              and c.c_preferred_cust_flag = 'Y'
            ) v1) v2
where ss_store_sk = s_store_sk and ss_sold_date_sk = d_date_sk
  and d_qoy = 2 and d_year = 1998
  and substr(s_zip, 1, 2) = substr(v2.ca_zip, 1, 2)
group by s_store_name
order by s_store_name
limit 100
"""

Q[9] = """
select case when (select count(*) from store_sales
                  where ss_quantity between 1 and 20) > 5000
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 1 and 20)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 1 and 20) end bucket1,
       case when (select count(*) from store_sales
                  where ss_quantity between 21 and 40) > 5000
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 21 and 40)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 21 and 40) end bucket2,
       case when (select count(*) from store_sales
                  where ss_quantity between 41 and 60) > 5000
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 41 and 60)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 41 and 60) end bucket3,
       case when (select count(*) from store_sales
                  where ss_quantity between 61 and 80) > 5000
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 61 and 80)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 61 and 80) end bucket4,
       case when (select count(*) from store_sales
                  where ss_quantity between 81 and 100) > 5000
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 81 and 100)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 81 and 100) end bucket5
from reason
where r_reason_sk = 1
"""

Q[10] = """
select cd_gender, cd_marital_status, cd_education_status, count(*) cnt1,
       cd_purchase_estimate, count(*) cnt2, cd_credit_rating, count(*) cnt3,
       cd_dep_count, count(*) cnt4, cd_dep_employed_count, count(*) cnt5,
       cd_dep_college_count, count(*) cnt6
from customer c, customer_address ca, customer_demographics
where c.c_current_addr_sk = ca.ca_address_sk
  and ca_county in ('Ziebach County', 'Williamson County', 'Walker County',
                    'Salem County', 'Raleigh County')
  and cd_demo_sk = c.c_current_cdemo_sk
  and exists (select * from store_sales, date_dim
              where c.c_customer_sk = ss_customer_sk
                and ss_sold_date_sk = d_date_sk and d_year = 2002
                and d_moy between 1 and 4)
  and (exists (select * from web_sales, date_dim
               where c.c_customer_sk = ws_bill_customer_sk
                 and ws_sold_date_sk = d_date_sk and d_year = 2002
                 and d_moy between 1 and 4)
    or exists (select * from catalog_sales, date_dim
               where c.c_customer_sk = cs_ship_customer_sk
                 and cs_sold_date_sk = d_date_sk and d_year = 2002
                 and d_moy between 1 and 4))
group by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
order by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
limit 100
"""

Q[11] = """
with year_total as (
  select c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name, d_year dyear,
         sum(ss_ext_list_price - ss_ext_discount_amt) year_total,
         's' sale_type
  from customer, store_sales, date_dim
  where c_customer_sk = ss_customer_sk and ss_sold_date_sk = d_date_sk
  group by c_customer_id, c_first_name, c_last_name, d_year
  union all
  select c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name, d_year dyear,
         sum(ws_ext_list_price - ws_ext_discount_amt) year_total,
         'w' sale_type
  from customer, web_sales, date_dim
  where c_customer_sk = ws_bill_customer_sk and ws_sold_date_sk = d_date_sk
  group by c_customer_id, c_first_name, c_last_name, d_year)
select t_s_secyear.customer_id, t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name
from year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
where t_s_secyear.customer_id = t_s_firstyear.customer_id
  and t_s_firstyear.customer_id = t_w_secyear.customer_id
  and t_s_firstyear.customer_id = t_w_firstyear.customer_id
  and t_s_firstyear.sale_type = 's' and t_w_firstyear.sale_type = 'w'
  and t_s_secyear.sale_type = 's' and t_w_secyear.sale_type = 'w'
  and t_s_firstyear.dyear = 2001 and t_s_secyear.dyear = 2002
  and t_w_firstyear.dyear = 2001 and t_w_secyear.dyear = 2002
  and t_s_firstyear.year_total > 0 and t_w_firstyear.year_total > 0
  and t_w_secyear.year_total / t_w_firstyear.year_total
        > t_s_secyear.year_total / t_s_firstyear.year_total
order by t_s_secyear.customer_id, t_s_secyear.customer_first_name,
         t_s_secyear.customer_last_name
limit 100
"""

Q[12] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ws_ext_sales_price) as itemrevenue,
       sum(ws_ext_sales_price) * 100
         / sum(sum(ws_ext_sales_price)) over (partition by i_class)
         as revenueratio
from web_sales, item, date_dim
where ws_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ws_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and date '1999-02-22' + interval '30' day
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""

Q[13] = """
select avg(ss_quantity) q, avg(ss_ext_sales_price) e,
       avg(ss_ext_wholesale_cost) w, sum(ss_ext_wholesale_cost) sw
from store_sales, store, customer_demographics, household_demographics,
     customer_address, date_dim
where s_store_sk = ss_store_sk and ss_sold_date_sk = d_date_sk
  and d_year = 2001
  and ((ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'M' and cd_education_status = 'Advanced Degree'
        and ss_sales_price between 100.00 and 150.00 and hd_dep_count = 3)
    or (ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'S' and cd_education_status = 'College'
        and ss_sales_price between 50.00 and 100.00 and hd_dep_count = 1)
    or (ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'W' and cd_education_status = '2 yr Degree'
        and ss_sales_price between 150.00 and 200.00 and hd_dep_count = 1))
  and ((ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('TX', 'OH', 'TX')
        and ss_net_profit between 100 and 200)
    or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('OR', 'NM', 'KY')
        and ss_net_profit between 150 and 300)
    or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('VA', 'TX', 'MS')
        and ss_net_profit between 50 and 250))
"""

Q[14] = """
with cross_items as (
  select i_item_sk ss_item_sk
  from item,
       (select iss.i_brand_id brand_id, iss.i_class_id class_id,
               iss.i_category_id category_id
        from store_sales, item iss, date_dim d1
        where ss_item_sk = iss.i_item_sk and ss_sold_date_sk = d1.d_date_sk
          and d1.d_year between 1999 and 2001
        intersect
        select ics.i_brand_id, ics.i_class_id, ics.i_category_id
        from catalog_sales, item ics, date_dim d2
        where cs_item_sk = ics.i_item_sk and cs_sold_date_sk = d2.d_date_sk
          and d2.d_year between 1999 and 2001
        intersect
        select iws.i_brand_id, iws.i_class_id, iws.i_category_id
        from web_sales, item iws, date_dim d3
        where ws_item_sk = iws.i_item_sk and ws_sold_date_sk = d3.d_date_sk
          and d3.d_year between 1999 and 2001) x
  where i_brand_id = brand_id and i_class_id = class_id
    and i_category_id = category_id),
 avg_sales as (
  select avg(quantity * list_price) average_sales
  from (select ss_quantity quantity, ss_list_price list_price
        from store_sales, date_dim
        where ss_sold_date_sk = d_date_sk and d_year between 1999 and 2001
        union all
        select cs_quantity quantity, cs_list_price list_price
        from catalog_sales, date_dim
        where cs_sold_date_sk = d_date_sk and d_year between 1999 and 2001
        union all
        select ws_quantity quantity, ws_list_price list_price
        from web_sales, date_dim
        where ws_sold_date_sk = d_date_sk and d_year between 1999 and 2001) x)
select channel, i_brand_id, i_class_id, i_category_id, sum(sales),
       sum(number_sales)
from (select 'store' channel, i_brand_id, i_class_id, i_category_id,
             sum(ss_quantity * ss_list_price) sales,
             count(*) number_sales
      from store_sales, item, date_dim
      where ss_item_sk in (select ss_item_sk from cross_items)
        and ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
        and d_year = 2001 and d_moy = 11
      group by i_brand_id, i_class_id, i_category_id
      having sum(ss_quantity * ss_list_price)
               > (select average_sales from avg_sales)
      union all
      select 'catalog' channel, i_brand_id, i_class_id, i_category_id,
             sum(cs_quantity * cs_list_price) sales, count(*) number_sales
      from catalog_sales, item, date_dim
      where cs_item_sk in (select ss_item_sk from cross_items)
        and cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
        and d_year = 2001 and d_moy = 11
      group by i_brand_id, i_class_id, i_category_id
      having sum(cs_quantity * cs_list_price)
               > (select average_sales from avg_sales)
      union all
      select 'web' channel, i_brand_id, i_class_id, i_category_id,
             sum(ws_quantity * ws_list_price) sales, count(*) number_sales
      from web_sales, item, date_dim
      where ws_item_sk in (select ss_item_sk from cross_items)
        and ws_item_sk = i_item_sk and ws_sold_date_sk = d_date_sk
        and d_year = 2001 and d_moy = 11
      group by i_brand_id, i_class_id, i_category_id
      having sum(ws_quantity * ws_list_price)
               > (select average_sales from avg_sales)) y
group by rollup (channel, i_brand_id, i_class_id, i_category_id)
order by channel nulls last, i_brand_id nulls last, i_class_id nulls last,
         i_category_id nulls last
limit 100
"""

Q[15] = """
select ca_zip, sum(cs_sales_price)
from catalog_sales, customer, customer_address, date_dim
where cs_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and (substr(ca_zip, 1, 5) in ('85669', '86197', '88274', '83405', '86475',
                                '85392', '85460', '80348', '81792')
       or ca_state in ('CA', 'WA', 'GA') or cs_sales_price > 500)
  and cs_sold_date_sk = d_date_sk and d_qoy = 2 and d_year = 2001
group by ca_zip
order by ca_zip
limit 100
"""

Q[16] = """
select count(distinct cs_order_number) as order_count,
       sum(cs_ext_ship_cost) as total_shipping_cost,
       sum(cs_net_profit) as total_net_profit
from catalog_sales cs1, date_dim, customer_address, call_center
where d_date between date '2002-02-01' and date '2002-02-01' + interval '60' day
  and cs1.cs_ship_date_sk = d_date_sk
  and cs1.cs_ship_addr_sk = ca_address_sk and ca_state = 'GA'
  and cs1.cs_call_center_sk = cc_call_center_sk
  and cc_county in ('Ziebach County', 'Williamson County', 'Walker County',
                    'Salem County', 'Raleigh County')
  and exists (select * from catalog_sales cs2
              where cs1.cs_order_number = cs2.cs_order_number
                and cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
  and not exists (select * from catalog_returns cr1
                  where cs1.cs_order_number = cr1.cr_order_number)
limit 100
"""

Q[17] = """
select i_item_id, i_item_desc, s_state, count(ss_quantity) as store_sales_quantitycount,
       avg(ss_quantity) as store_sales_quantityave,
       stddev_samp(ss_quantity) as store_sales_quantitystdev,
       stddev_samp(ss_quantity) / avg(ss_quantity) as store_sales_quantitycov,
       count(sr_return_quantity) as store_returns_quantitycount,
       avg(sr_return_quantity) as store_returns_quantityave,
       stddev_samp(sr_return_quantity) as store_returns_quantitystdev,
       stddev_samp(sr_return_quantity) / avg(sr_return_quantity)
         as store_returns_quantitycov,
       count(cs_quantity) as catalog_sales_quantitycount,
       avg(cs_quantity) as catalog_sales_quantityave,
       stddev_samp(cs_quantity) as catalog_sales_quantitystdev,
       stddev_samp(cs_quantity) / avg(cs_quantity) as catalog_sales_quantitycov
from store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2,
     date_dim d3, store, item
where d1.d_quarter_name = '2001Q1' and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_quarter_name in ('2001Q1', '2001Q2', '2001Q3')
  and sr_customer_sk = cs_bill_customer_sk and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_quarter_name in ('2001Q1', '2001Q2', '2001Q3')
group by i_item_id, i_item_desc, s_state
order by i_item_id, i_item_desc, s_state
limit 100
"""

Q[18] = """
select i_item_id, ca_country, ca_state, ca_county,
       avg(cast(cs_quantity as double)) agg1,
       avg(cast(cs_list_price as double)) agg2,
       avg(cast(cs_coupon_amt as double)) agg3,
       avg(cast(cs_sales_price as double)) agg4,
       avg(cast(cs_net_profit as double)) agg5,
       avg(cast(c_birth_year as double)) agg6,
       avg(cast(cd1.cd_dep_count as double)) agg7
from catalog_sales, customer_demographics cd1, customer_demographics cd2,
     customer, customer_address, date_dim, item
where cs_sold_date_sk = d_date_sk and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd1.cd_demo_sk
  and cs_bill_customer_sk = c_customer_sk
  and cd1.cd_gender = 'F' and cd1.cd_education_status = 'Unknown'
  and c_current_cdemo_sk = cd2.cd_demo_sk
  and c_current_addr_sk = ca_address_sk
  and c_birth_month in (1, 6, 8, 9, 12, 2) and d_year = 1998
  and ca_state in ('MS', 'IN', 'ND', 'OK', 'NM', 'VA', 'MS')
group by rollup (i_item_id, ca_country, ca_state, ca_county)
order by ca_country nulls last, ca_state nulls last, ca_county nulls last,
         i_item_id nulls last
limit 100
"""

Q[19] = """
select i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 8 and d_moy = 11 and d_year = 1998
  and ss_customer_sk = c_customer_sk and c_current_addr_sk = ca_address_sk
  and substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5) and ss_store_sk = s_store_sk
group by i_brand, i_brand_id, i_manufact_id, i_manufact
order by ext_price desc, i_brand, i_brand_id, i_manufact_id, i_manufact
limit 100
"""

Q[20] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(cs_ext_sales_price) as itemrevenue,
       sum(cs_ext_sales_price) * 100
         / sum(sum(cs_ext_sales_price)) over (partition by i_class)
         as revenueratio
from catalog_sales, item, date_dim
where cs_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and cs_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and date '1999-02-22' + interval '30' day
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""

Q[21] = """
select w_warehouse_name, i_item_id,
       sum(case when d_date < date '2000-03-11' then inv_quantity_on_hand
                else 0 end) as inv_before,
       sum(case when d_date >= date '2000-03-11' then inv_quantity_on_hand
                else 0 end) as inv_after
from inventory, warehouse, item, date_dim
where i_current_price between 0.99 and 1.49 and i_item_sk = inv_item_sk
  and inv_warehouse_sk = w_warehouse_sk and inv_date_sk = d_date_sk
  and d_date between date '2000-03-11' - interval '30' day
                 and date '2000-03-11' + interval '30' day
group by w_warehouse_name, i_item_id
having (case when sum(case when d_date < date '2000-03-11'
                           then inv_quantity_on_hand else 0 end) > 0
             then cast(sum(case when d_date >= date '2000-03-11'
                                then inv_quantity_on_hand else 0 end)
                       as double)
                  / sum(case when d_date < date '2000-03-11'
                             then inv_quantity_on_hand else 0 end)
             else null end) between 0.666667 and 1.5
order by w_warehouse_name, i_item_id
limit 100
"""

Q[22] = """
select i_product_name, i_brand, i_class, i_category,
       avg(inv_quantity_on_hand) qoh
from inventory, date_dim, item
where inv_date_sk = d_date_sk and inv_item_sk = i_item_sk
  and d_month_seq between 350 and 350 + 11
group by rollup (i_product_name, i_brand, i_class, i_category)
order by qoh, i_product_name nulls last, i_brand nulls last,
         i_class nulls last, i_category nulls last
limit 100
"""

Q[23] = """
with frequent_ss_items as (
  select substr(i_item_desc, 1, 30) itemdesc, i_item_sk item_sk,
         d_date solddate, count(*) cnt
  from store_sales, date_dim, item
  where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
    and d_year in (2000, 2001, 2002, 2003)
  group by substr(i_item_desc, 1, 30), i_item_sk, d_date
  having count(*) > 4),
 max_store_sales as (
  select max(csales) tpcds_cmax
  from (select c_customer_sk, sum(ss_quantity * ss_sales_price) csales
        from store_sales, customer, date_dim
        where ss_customer_sk = c_customer_sk and ss_sold_date_sk = d_date_sk
          and d_year in (2000, 2001, 2002, 2003)
        group by c_customer_sk) x),
 best_ss_customer as (
  select c_customer_sk, sum(ss_quantity * ss_sales_price) ssales
  from store_sales, customer
  where ss_customer_sk = c_customer_sk
  group by c_customer_sk
  having sum(ss_quantity * ss_sales_price)
           > 0.5 * (select tpcds_cmax from max_store_sales))
select sum(sales)
from (select cs_quantity * cs_list_price sales
      from catalog_sales, date_dim
      where d_year = 2000 and d_moy = 2 and cs_sold_date_sk = d_date_sk
        and cs_item_sk in (select item_sk from frequent_ss_items)
        and cs_bill_customer_sk in (select c_customer_sk
                                    from best_ss_customer)
      union all
      select ws_quantity * ws_list_price sales
      from web_sales, date_dim
      where d_year = 2000 and d_moy = 2 and ws_sold_date_sk = d_date_sk
        and ws_item_sk in (select item_sk from frequent_ss_items)
        and ws_bill_customer_sk in (select c_customer_sk
                                    from best_ss_customer)) y
limit 100
"""

Q[24] = """
with ssales as (
  select c_last_name, c_first_name, s_store_name, ca_state, s_state,
         i_color, i_current_price, i_manager_id, i_units, i_size,
         sum(ss_net_paid) netpaid
  from store_sales, store_returns, store, item, customer, customer_address
  where ss_ticket_number = sr_ticket_number and ss_item_sk = sr_item_sk
    and ss_customer_sk = c_customer_sk and ss_item_sk = i_item_sk
    and ss_store_sk = s_store_sk and c_current_addr_sk = ca_address_sk
    and c_birth_country <> upper(ca_country) and s_zip = ca_zip
    and s_market_id = 8
  group by c_last_name, c_first_name, s_store_name, ca_state, s_state,
           i_color, i_current_price, i_manager_id, i_units, i_size)
select c_last_name, c_first_name, s_store_name, sum(netpaid) paid
from ssales
where i_color = 'red'
group by c_last_name, c_first_name, s_store_name
having sum(netpaid) > (select 0.05 * avg(netpaid) from ssales)
order by c_last_name, c_first_name, s_store_name
"""

Q[25] = """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) as store_sales_profit,
       sum(sr_net_loss) as store_returns_loss,
       sum(cs_net_profit) as catalog_sales_profit
from store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2,
     date_dim d3, store, item
where d1.d_moy = 4 and d1.d_year = 2001 and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_moy between 4 and 10 and d2.d_year = 2001
  and sr_customer_sk = cs_bill_customer_sk and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_moy between 4 and 10 and d3.d_year = 2001
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
"""
