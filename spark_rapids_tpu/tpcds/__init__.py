"""TPC-DS rig: scalable generator + the 99-query battery as SQL text.

The north-star workload (BASELINE.json: TPC-DS SF1000, 99 queries; SURVEY §7
step 10). The reference repo's only in-tree rig is the mortgage ETL battery
(integration_tests/.../mortgage/Benchmarks.scala); this module exceeds that
shape: dsdgen-shaped deterministic generator, every query from (sql-parsed)
text, differential tests, bench integration (``bench.py --suite tpcds``).
"""
from .datagen import TABLES, gen_table, register_tables, write_tables
from .queries_sql import ALL as QUERY_IDS
from .queries_sql import tpcds_sql

__all__ = [
    "TABLES",
    "gen_table",
    "register_tables",
    "write_tables",
    "QUERY_IDS",
    "tpcds_sql",
]
