"""The TPC-DS 99-query battery as SQL text, run through the sql/ front-end.

Query texts follow the spec templates' shapes — CTE reuse (q1/q30/q47/q57/
q64/q95), correlated scalar aggregates (q1/q6/q32/q41/q92), EXISTS chains
(q10/q16/q35/q69/q94), OR-of-EXISTS (q10/q35), rollups with grouping()
ranks (q18/q27/q36/q67/q70/q86), window ratios (q12/q20/q51/q98), channel
unions (q2/q5/q14/q33/q56/q60/q66/q71/q75/q76/q80), intersect/except
(q8/q14/q38/q87), full outer joins (q51/q97), and day-bucket pivots
(q50/q62/q88/q99) — with validation-style parameters chosen inside the
generator's value domains so results are non-vacuous at small SF.

The differential anchor is engine-vs-engine (tests/test_tpcds.py): both the
device plan and the CPU oracle consume the same parsed plan, exactly like
the reference consumes Spark's parse of its qa battery.
"""
from __future__ import annotations

from .q01_25 import Q as _Q1
from .q26_50 import Q as _Q2
from .q51_75 import Q as _Q3
from .q76_99 import Q as _Q4

_ALL = {}
for part in (_Q1, _Q2, _Q3, _Q4):
    _ALL.update(part)

ALL = sorted(_ALL)
assert ALL == list(range(1, 100)), f"missing queries: {set(range(1,100)) - set(ALL)}"


def tpcds_sql(n: int) -> str:
    """SQL text of TPC-DS query ``n`` (1-99)."""
    return _ALL[n]
