"""Typed configuration registry — the ``RapidsConf`` analogue.

Mirrors the reference's config system (sql-plugin RapidsConf.scala: ``ConfEntry``
builder DSL ~:60-120, ~120 ``spark.rapids.*`` keys, and the markdown doc
generator at :1052-1149). Key names keep the ``spark.rapids.`` namespace so a
spark-rapids user finds the same switches; device-specific keys live under
``spark.rapids.tpu.*``.

Every operator/expression replacement rule additionally gets an auto-derived
kill switch (``spark.rapids.sql.exec.*`` / ``spark.rapids.sql.expression.*``),
registered by the planner — the reference's ``DataFromReplacementRule.confKey``
pattern (RapidsMeta.scala:35-43).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Generic, Optional, TypeVar

T = TypeVar("T")

_REGISTRY: dict[str, "ConfEntry"] = {}
_REGISTRY_LOCK = threading.Lock()


class ConfEntry(Generic[T]):
    def __init__(
        self,
        key: str,
        default: T,
        doc: str,
        conv: Callable[[str], T],
        internal: bool = False,
        startup_only: bool = False,
    ):
        self.key = key
        self.default = default
        self.doc = doc
        self.conv = conv
        self.internal = internal
        self.startup_only = startup_only

    def get(self, conf: "TpuConf") -> T:
        return conf.get(self.key, self.default, self.conv)


class _EntryBuilder:
    def __init__(self, key: str):
        self._key = key
        self._doc = ""
        self._internal = False
        self._startup = False

    def doc(self, text: str) -> "_EntryBuilder":
        self._doc = text
        return self

    def internal(self) -> "_EntryBuilder":
        self._internal = True
        return self

    def startup_only(self) -> "_EntryBuilder":
        self._startup = True
        return self

    def _register(self, default, conv) -> ConfEntry:
        entry = ConfEntry(
            self._key, default, self._doc, conv, self._internal, self._startup
        )
        with _REGISTRY_LOCK:
            if self._key in _REGISTRY:
                raise ValueError(f"duplicate conf key {self._key}")
            _REGISTRY[self._key] = entry
        return entry

    def boolean_conf(self, default: bool) -> ConfEntry[bool]:
        return self._register(default, lambda s: s.strip().lower() in ("true", "1"))

    def int_conf(self, default: int) -> ConfEntry[int]:
        return self._register(default, int)

    def bytes_conf(self, default: int) -> ConfEntry[int]:
        return self._register(default, _parse_bytes)

    def double_conf(self, default: float) -> ConfEntry[float]:
        return self._register(default, float)

    def string_conf(self, default: Optional[str]) -> ConfEntry[Optional[str]]:
        return self._register(default, lambda s: s)


def conf(key: str) -> _EntryBuilder:
    return _EntryBuilder(key)


def _parse_bytes(s: str) -> int:
    s = s.strip().lower()
    mult = 1
    for suffix, m in (("k", 1 << 10), ("m", 1 << 20), ("g", 1 << 30), ("t", 1 << 40)):
        if s.endswith(suffix + "b"):
            s, mult = s[:-2], m
            break
        if s.endswith(suffix):
            s, mult = s[:-1], m
            break
    return int(float(s) * mult)


# ── Core keys (subset growing toward the reference's ~120) ──────────────────

SQL_ENABLED = conf("spark.rapids.sql.enabled").doc(
    "Enable (true) or disable (false) TPU acceleration of SQL operators."
).boolean_conf(True)

PALLAS_ENABLED = conf("spark.rapids.sql.pallas.enabled").doc(
    "Use hand-written Pallas TPU kernels for hot string ops (substring "
    "search over the padded byte planes) instead of the pure-XLA lowering. "
    "Results are bit-identical; this only changes the kernel strategy."
).startup_only().boolean_conf(True)

TASK_MAX_FAILURES = conf("spark.task.maxFailures").doc(
    "Task-retry budget (Spark's key): a failed partition task re-runs from "
    "its lineage up to this many total attempts before the query fails. "
    "Deterministic semantic errors (ANSI arithmetic/cast errors, "
    "assertions) are never retried."
).int_conf(4)

NATIVE_ENABLED = conf("spark.rapids.native.enabled").doc(
    "Use the native (C++) host data plane — Spark-exact murmur3 hashing, "
    "the best-fit staging-arena sub-allocator, and contiguous spill frames "
    "(built from native/srt_host.cc; auto-compiled with g++ on first use). "
    "Pure-python/numpy fallbacks run when disabled or when no toolchain is "
    "available."
).startup_only().boolean_conf(True)

EXPLAIN = conf("spark.rapids.sql.explain").doc(
    "Explain why parts of a query were or were not placed on the TPU: "
    "NONE, NOT_ON_GPU (only log un-replaced nodes), ALL."
).string_conf("NONE")

INCOMPATIBLE_OPS = conf("spark.rapids.sql.incompatibleOps.enabled").doc(
    "Enable operators that produce results that differ from Spark in corner "
    "cases (e.g. float aggregation ordering)."
).boolean_conf(False)

BATCH_SIZE_BYTES = conf("spark.rapids.sql.batchSizeBytes").doc(
    "Target size of a columnar batch the operators work on "
    "(reference: RapidsConf.scala:402)."
).bytes_conf(1 << 30)

BATCH_SIZE_ROWS = conf("spark.rapids.sql.batchSizeRows").doc(
    "Target row count of a device batch; capacities are bucketed to powers of "
    "two above this to bound XLA recompilation."
).int_conf(1 << 20)

MAX_READER_BATCH_SIZE_ROWS = conf("spark.rapids.sql.reader.batchSizeRows").doc(
    "Soft cap on rows per batch produced by file readers "
    "(reference: RapidsConf.scala READER_BATCH_SIZE_ROWS)."
).int_conf(1 << 20)

MAX_READER_BATCH_SIZE_BYTES = conf("spark.rapids.sql.reader.batchSizeBytes").doc(
    "Soft cap on bytes per batch produced by file readers."
).bytes_conf(1 << 30)

CONCURRENT_TPU_TASKS = conf("spark.rapids.sql.concurrentGpuTasks").doc(
    "Number of concurrent tasks that may hold the device at once — admission "
    "control via the device semaphore (reference: GpuSemaphore.scala), and "
    "the size of the session's partition-task thread pool. Re-read at every "
    "query, so a long-lived service can retune it live; query-level "
    "admission across tenants is the scheduler's permit pool "
    "(spark.rapids.tpu.scheduler.*)."
).int_conf(4)

HAS_NANS = conf("spark.rapids.sql.hasNans").doc(
    "Assume floating point values may contain NaNs (gates some operators, "
    "matching the reference)."
).boolean_conf(True)

VARIABLE_FLOAT_AGG = conf("spark.rapids.sql.variableFloatAgg.enabled").doc(
    "Allow float/double aggregations whose result can vary with evaluation "
    "order (sum/avg over float)."
).boolean_conf(True)

CAST_FLOAT_TO_STRING = conf("spark.rapids.sql.castFloatToString.enabled").doc(
    "Enable float→string casts, which may differ from Spark in formatting."
).boolean_conf(False)

CAST_STRING_TO_FLOAT = conf("spark.rapids.sql.castStringToFloat.enabled").doc(
    "Enable string→float casts, which may differ from Spark in corner cases."
).boolean_conf(False)

CAST_STRING_TO_TIMESTAMP = conf(
    "spark.rapids.sql.castStringToTimestamp.enabled"
).doc(
    "Enable string→timestamp casts on device; the device grammar is the "
    "UTC-only subset of Spark's (no zone offsets), matching the reference's "
    "gated support (GpuCast.scala castStringToTimestamp)."
).boolean_conf(False)

EXCHANGE_REUSE_ENABLED = conf("spark.sql.exchange.reuse").doc(
    "Deduplicate identical exchange subtrees so repeated subplans "
    "(self-joins of an aggregate, CTE fan-out) materialize once "
    "(Spark's ReuseExchange; reference GpuExec.doCanonicalize — "
    "GpuExec.scala:251-276)."
).boolean_conf(True)

PYTHON_PREFETCH_BATCHES = conf("spark.rapids.sql.python.prefetchBatches").doc(
    "Bounded producer/consumer queue depth between the engine's batch "
    "pipeline and streaming python UDF execs (mapInPandas): upstream "
    "production overlaps python compute on a producer thread (the "
    "reference's BatchQueue, GpuArrowEvalPythonExec.scala:188). 0 disables."
).int_conf(2)

GET_JSON_OBJECT_DEVICE = conf("spark.rapids.sql.getJsonObject.enabled").doc(
    "Run get_json_object on device via the span-extraction kernel. Like the "
    "reference's cudf get_json_object (GpuOverrides.scala:2519) it returns "
    "nested results as written (no re-serialization) and does not unescape "
    "string values — exact on compact escape-free JSON; off by default "
    "because CPU Spark normalizes through Jackson (docs/compatibility.md)."
).boolean_conf(False)

ADAPTIVE_ENABLED = conf("spark.sql.adaptive.enabled").doc(
    "Adaptive query execution (Spark's key, honored here): exchanges "
    "coalesce small output partitions at runtime from measured sizes "
    "(the GpuCustomShuffleReaderExec analogue)."
).boolean_conf(False)

ADVISORY_PARTITION_SIZE = conf(
    "spark.sql.adaptive.advisoryPartitionSizeInBytes"
).doc(
    "Target post-shuffle partition size for adaptive coalescing."
).bytes_conf(64 << 20)

SKEW_JOIN_ENABLED = conf("spark.sql.adaptive.skewJoin.enabled").doc(
    "Runtime skew-join handling (Spark's key, honored here): an oversized "
    "join-side partition is split across the slots freed by coalescing "
    "while the other side's partition is replicated "
    "(OptimizeSkewedJoin analogue)."
).boolean_conf(True)

SKEW_JOIN_THRESHOLD = conf(
    "spark.sql.adaptive.skewJoin.skewedPartitionThresholdInBytes"
).doc(
    "A partition larger than this (and skewedPartitionFactor x the median) "
    "is considered skewed."
).bytes_conf(256 << 20)

SKEW_JOIN_FACTOR = conf(
    "spark.sql.adaptive.skewJoin.skewedPartitionFactor"
).doc(
    "Skew multiplier over the median partition size."
).int_conf(5)

SPARK_VERSION = conf("spark.rapids.tpu.sparkVersion").doc(
    "Spark version whose semantics to emulate; selects the shim provider "
    "(reference: ShimLoader + per-version shims/ modules). Shim-dependent "
    "defaults (ANSI, adaptive execution) apply when their keys are unset."
).startup_only().string_conf("3.1")

CBO_ENABLED = conf("spark.rapids.sql.optimizer.enabled").doc(
    "Cost-based un-conversion: device islands whose estimated compute is "
    "too small to pay for their H2D/D2H transitions revert to the CPU "
    "engine (reference: CostBasedOptimizer.scala, default off there too)."
).boolean_conf(False)

ANSI_ENABLED = conf("spark.sql.ansi.enabled").doc(
    "Spark's ANSI mode (honored here): casts raise on overflow or malformed "
    "input instead of returning NULL, and integral narrowing range-checks "
    "instead of wrapping."
).boolean_conf(False)

STRING_MAX_BYTES = conf("spark.rapids.tpu.string.maxBytes").doc(
    "Maximum per-value string width the fixed-width device representation "
    "pads to before the column falls back to the CPU."
).int_conf(256)

POOL_SIZE_FRACTION = conf("spark.rapids.memory.gpu.allocFraction").doc(
    "Fraction of device memory the HBM pool may use "
    "(reference: RapidsConf.scala RMM_ALLOC_FRACTION)."
).double_conf(0.9)

MEMORY_DEBUG = conf("spark.rapids.memory.tpu.debug").doc(
    "Debug-allocator mode (the reference's spark.rapids.memory.gpu.debug + "
    "ai.rapids.refcount.debug): the spill catalog records the registration "
    "site of every spillable buffer, logs tier transitions, and reports any "
    "buffer still registered at query end as a LEAK with its origin."
).boolean_conf(False)

HOST_SPILL_STORAGE_SIZE = conf("spark.rapids.memory.host.spillStorageSize").doc(
    "Amount of host memory to use for spilled device buffers before "
    "overflowing to disk."
).bytes_conf(1 << 31)

SPILL_DIR = conf("spark.rapids.memory.spillDir").doc(
    "Directory for the disk spill tier."
).string_conf(None)

SHUFFLE_PARTITIONS = conf("spark.sql.shuffle.partitions").doc(
    "Default number of partitions for exchanges (Spark's key, honored here)."
).int_conf(8)

MESH_ENABLED = conf("spark.rapids.sql.mesh.enabled").doc(
    "Execute planner-built queries SPMD over a jax.sharding.Mesh: shuffle "
    "exchanges lower to one fused all_to_all over ICI (the accelerated-"
    "shuffle data plane wired into query execution, the UCX analogue — "
    "RapidsShuffleInternalManagerBase.scala) and each partition's kernels "
    "run on its own chip. Requires shuffle partitions == mesh size (the "
    "session aligns the default automatically)."
).startup_only().boolean_conf(False)

MESH_SIZE = conf("spark.rapids.sql.mesh.size").doc(
    "Number of devices in the execution mesh; 0 uses every visible device."
).startup_only().int_conf(0)

SPLIT_MAX_TOKENS = conf("spark.rapids.sql.split.maxTokens").doc(
    "Static token-plane width for device split(): a row splitting into "
    "more tokens fails loudly (never truncates) — raise this or disable "
    "spark.rapids.sql.expression.StringSplit for such data."
).int_conf(16)

UDF_COMPILER_ENABLED = conf("spark.rapids.sql.udfCompiler.enabled").doc(
    "Translate simple python UDFs (arithmetic/comparison/conditional/math/"
    "string-method subset) into expression trees that fuse on device — the "
    "udf-compiler analogue. Off by default like the reference: a translated "
    "UDF null-propagates where the raw python function would raise on None."
).boolean_conf(False)

PROFILE_PATH = conf("spark.rapids.sql.profile.path").doc(
    "When set, each collect() is wrapped in a jax.profiler trace dumped to "
    "this directory (TensorBoard XPlane capture with per-operator "
    "TraceAnnotation ranges) — the Nsight+NVTX analogue "
    "(NvtxWithMetrics.scala)."
).string_conf("")

PROFILE_OPTIME = conf("spark.rapids.sql.profile.opTime.enabled").doc(
    "Per-operator device-time attribution: every exec's output batches are "
    "block_until_ready'd under a timer feeding its opTime metric. "
    "Serializes the pipeline (CUDA_LAUNCH_BLOCKING-style) — debug only."
).boolean_conf(False)

TEST_CONF = conf("spark.rapids.sql.test.enabled").doc(
    "Test mode: fail if any operator that was expected on device fell back "
    "(reference: RapidsConf TEST_CONF)."
).internal().boolean_conf(False)

TEST_ALLOWED_NONTPU = conf("spark.rapids.sql.test.allowedNonGpu").doc(
    "Comma-separated exec names allowed to stay on CPU in test mode."
).internal().string_conf(None)

METRICS_LEVEL = conf("spark.rapids.sql.metrics.level").doc(
    "ESSENTIAL, MODERATE or DEBUG — how many metrics operators publish "
    "(reference: RapidsConf.scala:456)."
).string_conf("MODERATE")

METRICS_LEVEL_TPU = conf("spark.rapids.tpu.metrics.level").doc(
    "TPU-engine override of spark.rapids.sql.metrics.level for the obs/ "
    "subsystem: ESSENTIAL (counters only — no per-batch timer reads), "
    "MODERATE (plus transfer/pipeline timings) or DEBUG (plus opTime "
    "device-time attribution). Unset inherits the sql key."
).string_conf(None)

TRACE_ENABLED = conf("spark.rapids.tpu.trace.enabled").doc(
    "Hierarchical query tracing (obs/trace.py): each sampled query records "
    "query → operator → batch spans — including work executed on pipeline "
    "producer threads via span-context propagation — into a ring buffer "
    "exportable as Chrome-trace/Perfetto JSON. Implied by "
    "spark.rapids.tpu.trace.dir; see docs/observability.md."
).boolean_conf(False)

TRACE_SAMPLE = conf("spark.rapids.tpu.trace.sample").doc(
    "Fraction of queries traced when tracing is enabled (Dapper-style "
    "sampling): 1.0 traces every query, 0.01 one in a hundred. The "
    "per-query decision is deterministic in the session's query sequence "
    "number, so a rerun traces the same queries."
).double_conf(1.0)

TRACE_DIR = conf("spark.rapids.tpu.trace.dir").doc(
    "When set, every traced query writes query-<n>.trace.json (Chrome-"
    "trace/Perfetto: load at ui.perfetto.dev) and query-<n>.metrics.json "
    "(the per-query metrics artifact) into this directory. Setting it "
    "implies spark.rapids.tpu.trace.enabled."
).string_conf(None)

TRACE_BUFFER_SPANS = conf("spark.rapids.tpu.trace.bufferSpans").doc(
    "Span ring-buffer capacity per traced query; the oldest spans are "
    "overwritten beyond it (exporters report the drop count, and the "
    "process-wide trace.droppedSpans counter records every overwrite)."
).int_conf(65536)

TRACE_PROPAGATE = conf("spark.rapids.tpu.trace.propagate").doc(
    "Cross-process span-context propagation: serve protocol frames and "
    "multiproc shuffle requests carry a compact (trace id, parent span id, "
    "sampled) context so client spans, server query trees, and remote "
    "shuffle-worker fetch spans merge into one Perfetto trace "
    "(obs/trace.py SpanContext; the Dapper propagation model)."
).boolean_conf(True)

METRICS_HTTP_PORT = conf("spark.rapids.tpu.metrics.httpPort").doc(
    "Live scrape endpoint (obs/scrape.py): a stdlib HTTP listener serving "
    "/metrics (Prometheus text exposition of the process registry, "
    "histograms included) and /healthz (liveness + serve readiness). "
    "0 disables (default), a positive port binds there, -1 binds an "
    "ephemeral port. Started by TpuServer.start() and by bare sessions at "
    "construction."
).int_conf(0)

METRICS_MAX_DYNAMIC_SLUGS = conf("spark.rapids.tpu.metrics.maxDynamicSlugs").doc(
    "Cardinality cap for dynamically-named metric series (cancel-reason, "
    "tenant, stall-site, pool families): at most this many distinct slugs "
    "per prefix; overflow folds into one 'other' bucket and counts in "
    "metrics.slugOverflow. Guards the Prometheus export against unbounded "
    "series from wire-supplied names."
).int_conf(64)

LEDGER_ENABLED = conf("spark.rapids.tpu.ledger.enabled").doc(
    "Host-overhead ledger (obs/ledger.py): decompose each query's wall "
    "clock into exhaustive non-overlapping phases (parse/plan, compile, "
    "h2d, dispatch, device wait, d2h, serialize, queue wait, glue "
    "residual), exported via df.explain('metrics'), the per-query JSON "
    "artifact, and the bench diag ranked breakdown."
).boolean_conf(True)

CBO_CALIBRATION_ENABLED = conf("spark.rapids.tpu.cbo.calibration.enabled").doc(
    "Harvest measured per-op device/host ns-per-row into the persisted "
    "calibration table at every query exit (obs/calibration.py). Implies "
    "per-batch opTime attribution (profiling.instrument_plan) while on — "
    "a measurement mode, not a hot-path default."
).boolean_conf(False)

CBO_CALIBRATION_FILE = conf("spark.rapids.tpu.cbo.calibrationFile").doc(
    "Path of the persisted JSON calibration table (EWMA per-op-signature "
    "measured costs), shared across sessions and processes. Default: "
    "~/.cache/spark_rapids_tpu/cbo_calibration.json."
).string_conf(None)

CBO_MEASURED_WEIGHTS = conf("spark.rapids.tpu.cbo.measuredWeights").doc(
    "Drive the cost-based optimizer's island un-conversion from the "
    "MEASURED calibration table instead of the hardcoded per-op weights "
    "(plan/overrides.py). With this off — or the calibration file absent "
    "or empty — planning is bit-identical to the hardcoded table; the "
    "chosen weight source and numbers appear in the explain output."
).boolean_conf(False)

CPU_ONLY = conf("spark.rapids.tpu.cpuOnly").doc(
    "Force the JAX CPU backend (testing; the virtual-device mesh path)."
).internal().boolean_conf(False)

CLOUD_SCHEMES = conf("spark.rapids.cloudSchemes").doc(
    "Comma-separated URI schemes treated as cloud storage: the AUTO reader "
    "type picks MULTITHREADED for them (background prefetch hides object-"
    "store latency) and COALESCING otherwise (reference: "
    "RapidsConf.scala:651)."
).string_conf("dbfs,s3,s3a,s3n,wasbs,gs,abfs,abfss")

ALLUXIO_PATHS_TO_REPLACE = conf("spark.rapids.alluxio.pathsToReplace").doc(
    "Comma-separated 'src->dst' prefix rewrites applied to read paths "
    "before file listing — route cloud reads through an Alluxio-style "
    "cache mount (reference: RapidsConf.scala:929)."
).string_conf(None)

PARQUET_READER_TYPE = conf("spark.rapids.sql.format.parquet.reader.type").doc(
    "File reader strategy: AUTO (COALESCING for local paths, MULTITHREADED "
    "when any path scheme is in spark.rapids.cloudSchemes — the reference's "
    "default), PERFILE (one task per file), COALESCING (small files "
    "stitched into shared partitions), or MULTITHREADED (cloud-style "
    "thread-pool reads). The per-read option 'readerType' overrides this "
    "per DataFrame (reference: RapidsConf.scala:624-671)."
).string_conf("AUTO")

ORC_READER_TYPE = conf("spark.rapids.sql.format.orc.reader.type").doc(
    "ORC file reader strategy; same values as the parquet key."
).string_conf("AUTO")

MULTITHREADED_READ_NUM_THREADS = conf(
    "spark.rapids.sql.multiThreadedRead.numThreads"
).doc(
    "Thread pool size for the multithreaded (cloud) file reader "
    "(reference: RapidsConf.scala:624-671)."
).int_conf(20)

DECIMAL_ENABLED = conf("spark.rapids.sql.decimalType.enabled").doc(
    "Enable decimal (64-bit) processing on device."
).boolean_conf(True)

DEVICE_POOL_LIMIT = conf("spark.rapids.tpu.memory.deviceLimitBytes").doc(
    "Spillable-buffer budget on device; 0 means unlimited. When registered "
    "spillable bytes would exceed this, the catalog proactively spills "
    "(reference: RMM pool size via spark.rapids.memory.gpu.allocFraction)."
).bytes_conf(0)

ADAPTIVE_BROADCAST_THRESHOLD = conf(
    "spark.sql.adaptive.autoBroadcastJoinThreshold"
).doc(
    "AQE runtime join-strategy switch: a shuffled hash join whose MEASURED "
    "build side is at most this many bytes re-plans as a broadcast join at "
    "execution time (the probe side's exchange is read locally, skipping "
    "its all-to-all). -1 falls back to spark.sql.autoBroadcastJoinThreshold."
).bytes_conf(-1)

AUTO_BROADCAST_THRESHOLD = conf("spark.sql.autoBroadcastJoinThreshold").doc(
    "Maximum estimated build-side size for which a join is planned as a "
    "broadcast hash join (Spark's key, honored here; -1 disables)."
).bytes_conf(10 << 20)

PIPELINE_ENABLED = conf("spark.rapids.tpu.pipeline.enabled").doc(
    "Dispatch-ahead partition pipelining: blocking plan sinks (the D2H "
    "pull at collect(), LIMIT's per-batch row-count sync) consume their "
    "upstream batch stream through a bounded prefetch window driven by a "
    "producer thread, so device work for batches i+1..k dispatches while "
    "the sink blocks on batch i (kills the per-batch host-stall tax the "
    "round-5 bench measured as host_overhead_frac 0.89-0.997). Kill "
    "switch for the pipelined path; see docs/pipelined-execution.md."
).boolean_conf(True)

PIPELINE_MAX_BATCHES = conf("spark.rapids.tpu.pipeline.maxBatches").doc(
    "Maximum batches in flight per pipelined partition stream (the "
    "dispatch-ahead window depth). Bounds device-buffer growth together "
    "with spark.rapids.tpu.pipeline.maxInflightBytes."
).int_conf(4)

PIPELINE_MAX_INFLIGHT_BYTES = conf(
    "spark.rapids.tpu.pipeline.maxInflightBytes"
).doc(
    "Byte bound on the batches buffered ahead by a pipelined partition "
    "stream; the producer also requests spill-catalog headroom before "
    "each prefetch. 0 (default) sizes automatically: a quarter of the "
    "spillable device budget when known, else 1 GiB."
).bytes_conf(0)

PRECOMPILE_ENABLED = conf("spark.rapids.tpu.precompile.enabled").doc(
    "Kernel pre-compilation pass: after planning, walk the exec tree, "
    "derive the batch geometry of shape-predictable scan-side chains, and "
    "compile their kernels ahead of execution on a small compile pool "
    "(concurrent on TPU, serialized on XLA:CPU), warm-starting the "
    "persistent XLA cache — compile latency overlaps across plan nodes "
    "instead of serializing at first touch of each operator."
).boolean_conf(True)

PRECOMPILE_PARALLELISM = conf("spark.rapids.tpu.precompile.parallelism").doc(
    "Compile-pool width for the kernel pre-compilation pass; 0 picks "
    "automatically (1 on the CPU backend, up to 4 elsewhere)."
).int_conf(0)

FUSION_ENABLED = conf("spark.rapids.tpu.fusion.enabled").doc(
    "Whole-stage fusion (plan/fusion.py): maximal chains of adjacent "
    "device project/filter operators collapse into a single StageExec "
    "whose body is ONE jitted XLA program — one kernel launch (and one "
    "downstream D2H sync) per stage instead of one per operator. "
    "Bit-identical to per-op execution by construction; chains break at "
    "task-dependent expressions (row_base semantics) and at kernels with "
    "ANSI error sites (their per-op error channel must keep its batch "
    "attribution). Kill switch for the fused path."
).boolean_conf(True)

FUSION_MAX_OPS = conf("spark.rapids.tpu.fusion.maxOps").doc(
    "Maximum operators fused into one StageExec program; longer chains "
    "split into consecutive stages. Bounds single-program XLA trace and "
    "compile time."
).int_conf(16)

SHAPE_BUCKETS_ENABLED = conf("spark.rapids.tpu.shapeBuckets.enabled").doc(
    "Pow-2 shape-bucket lattice (kernels.shape_bucket_floor): batch "
    "capacities round up to at least shapeBuckets.minRows, so one cached "
    "XLA executable serves every batch geometry inside the bucket — "
    "first-touch compiles amortize across batch sizes and the persistent "
    "xla_store entry count collapses for warm restarts. Padding rows are "
    "masked inert (the existing capacity > num_rows invariant); results "
    "are bit-identical. Off restores exact pow-2-of-row-count capacities."
).boolean_conf(True)

SHAPE_BUCKETS_MIN_ROWS = conf("spark.rapids.tpu.shapeBuckets.minRows").doc(
    "Floor of the shape-bucket lattice: the smallest batch capacity the "
    "engine compiles for (rounded up to a power of two). Larger floors "
    "mean fewer distinct compiled shapes at the cost of more masked "
    "padding per small batch."
).int_conf(1024)

ROUTING_ENABLED = conf("spark.rapids.tpu.routing.enabled").doc(
    "Calibrated engine routing (plan/overrides.py): with a measured cost "
    "table present (obs/calibration.py), predict each device island's "
    "device time (ns/row x estimated rows + per-launch and transfer "
    "overheads) against its CPU-engine time and route sub-threshold "
    "islands — the tiny-input, full-dispatch-tax shape — back to the CPU "
    "engine, with the prediction and its numbers in the explain reason. "
    "Off (default), or with no calibration data, planning is unchanged."
).boolean_conf(False)

ROUTING_LAUNCH_OVERHEAD_NS = conf("spark.rapids.tpu.routing.launchOverheadNs").doc(
    "Fixed per-kernel-launch host overhead the routing predictor charges "
    "each device operator (dispatch + enqueue tax measured by the "
    "attribution ledger's dispatch phase)."
).int_conf(1_500_000)

ROUTING_TRANSFER_OVERHEAD_NS = conf("spark.rapids.tpu.routing.transferOverheadNs").doc(
    "Fixed per-island transfer overhead the routing predictor charges a "
    "device island (H2D upload + D2H result round trip on the PJRT link)."
).int_conf(4_000_000)

UPLOAD_CACHE_MAX_BYTES = conf("spark.rapids.tpu.uploadCache.maxBytes").doc(
    "Byte budget for the session's device-upload (H2D) cache of in-memory "
    "relations — the LRU bound standing between many-table sessions and "
    "pinned-HBM OOM. 0 (default) sizes automatically from device memory "
    "stats (a quarter of the device's byte limit) with a 4 GiB fallback "
    "when no stats are available."
).bytes_conf(0)

OUT_OF_CORE_SORT_THRESHOLD = conf("spark.rapids.tpu.sort.outOfCoreThresholdBytes").doc(
    "Partition size above which TpuSortExec switches from single-batch sort "
    "to spillable sorted-run merge (reference: GpuSortExec.scala:212 "
    "out-of-core mode gated by targetSize)."
).bytes_conf(1 << 30)


SHUFFLE_COMPRESSION_CODEC = conf("spark.rapids.shuffle.compression.codec").doc(
    "Codec for shuffle buffers on the inter-host (DCN) path: none, copy, "
    "lz4, zstd (reference: TableCompressionCodec + nvcomp LZ4)."
).string_conf("lz4")

SHUFFLE_MAX_RECEIVE_INFLIGHT = conf(
    "spark.rapids.shuffle.transport.maxReceiveInflightBytes"
).doc(
    "Bytes a reduce task may have requested but not yet received "
    "(reference: RapidsConf.scala:850)."
).bytes_conf(1 << 30)

SHUFFLE_BOUNCE_BUFFER_SIZE = conf("spark.rapids.shuffle.bounceBufferSize").doc(
    "Size of each host staging (bounce) buffer used to window large shuffle "
    "payloads into frames (reference: BounceBufferManager)."
).bytes_conf(4 << 20)

SHUFFLE_BOUNCE_BUFFER_COUNT = conf("spark.rapids.shuffle.bounceBufferCount").doc(
    "Number of bounce buffers in the staging pool."
).int_conf(8)

SHUFFLE_FETCH_TIMEOUT_S = conf("spark.rapids.shuffle.fetchTimeoutSeconds").doc(
    "Seconds a reduce task waits for shuffle data before raising a fetch "
    "failure (reference: shuffleFetchTimeoutSeconds)."
).int_conf(120)

SHUFFLE_MANAGER_ENABLED = conf("spark.rapids.shuffle.manager.enabled").doc(
    "Route exchanges through the accelerated shuffle manager (device-"
    "resident spillable map output + transport fetches) instead of the "
    "in-process default path (reference: RapidsShuffleManager)."
).boolean_conf(False)

MULTIPROC_DRIVER = conf("spark.rapids.shuffle.multiproc.driver").doc(
    "host:port of the cross-process driver service (heartbeat registry + "
    "map-output tracker — shuffle/driver_service.py). When set, this "
    "session is ONE executor of a multi-process query: exchanges run only "
    "the map/reduce partitions this rank owns and fetch peer map output "
    "over the TCP transport (the DCN path; reference: "
    "RapidsShuffleHeartbeatManager + UCX executor-to-executor traffic)."
).startup_only().string_conf("")

MULTIPROC_RANK = conf("spark.rapids.shuffle.multiproc.rank").doc(
    "This executor's rank in the multi-process query (0-based)."
).startup_only().int_conf(0)

MULTIPROC_SIZE = conf("spark.rapids.shuffle.multiproc.size").doc(
    "Total executors cooperating on the multi-process query."
).startup_only().int_conf(1)

SHUFFLE_HANDSHAKE_TIMEOUT_S = conf("spark.rapids.tpu.shuffle.handshakeTimeout").doc(
    "Seconds the TCP transport waits for a dialing peer's HELLO frame "
    "before dropping the connection (the WorkerAddress-exchange deadline)."
).double_conf(10.0)

HEARTBEAT_MAX_AGE_S = conf("spark.rapids.tpu.shuffle.heartbeatMaxAgeSeconds").doc(
    "An executor whose last heartbeat is older than this is considered "
    "dead and evicted from the peer registry (ShuffleHeartbeatManager."
    "evict_stale); 0 disables age-based eviction."
).double_conf(0.0)


# ── resilience: OOM split-and-retry, fetch retry, circuit breaker ──────────

RETRY_OOM_MAX_RETRIES = conf("spark.rapids.tpu.retry.oom.maxRetries").doc(
    "Spill-and-retry attempts per kernel launch on a device OOM "
    "(RESOURCE_EXHAUSTED) before the retry state machine starts splitting "
    "the input batch (reference: DeviceMemoryEventHandler.scala:42-69 "
    "spill-retry loop)."
).int_conf(2)

RETRY_OOM_SPLIT_ENABLED = conf("spark.rapids.tpu.retry.oom.splitEnabled").doc(
    "After the spill-retry budget is exhausted, recursively halve the "
    "input batch of splittable operators (project/filter, partial "
    "aggregate update, join probe) and retry each half — the "
    "split-and-retry escalation for work that genuinely does not fit."
).boolean_conf(True)

RETRY_OOM_MIN_SPLIT_ROWS = conf("spark.rapids.tpu.retry.oom.minSplitRows").doc(
    "Floor on the batch capacity the OOM retry state machine will split "
    "down to; a batch at or below this capacity that still OOMs fails "
    "the task."
).int_conf(1024)

RETRY_FETCH_MAX_RETRIES = conf("spark.rapids.tpu.retry.fetch.maxRetries").doc(
    "Per-peer shuffle fetch retries (metadata request or transfer wave) "
    "before the fetch surfaces as a ShuffleFetchError; each retry "
    "re-requests only the blocks not yet received."
).int_conf(3)

RETRY_FETCH_BACKOFF_MS = conf("spark.rapids.tpu.retry.fetch.backoffMs").doc(
    "Base backoff between shuffle fetch retries; attempt k sleeps "
    "backoffMs * 2^(k-1) with deterministic seeded jitter, capped by "
    "spark.rapids.tpu.retry.fetch.maxBackoffMs."
).double_conf(50.0)

RETRY_FETCH_MAX_BACKOFF_MS = conf("spark.rapids.tpu.retry.fetch.maxBackoffMs").doc(
    "Upper bound on the exponential shuffle-fetch backoff."
).double_conf(2000.0)

RETRY_FETCH_BLACKLIST_AFTER = conf("spark.rapids.tpu.retry.fetch.blacklistAfter").doc(
    "Consecutive exhausted fetch-retry budgets against one peer before "
    "that peer is blacklisted (evicted from the executor's peer table; "
    "later fetches to it fail fast). 0 disables blacklisting."
).int_conf(3)

CIRCUIT_BREAKER_ENABLED = conf("spark.rapids.tpu.retry.circuitBreaker.enabled").doc(
    "When a device kernel for an op signature fails repeatedly with "
    "non-OOM XLA errors, mark that op CPU-fallback for the session and "
    "log the reason in the explain output (the per-node fallback contract "
    "extended to runtime failures)."
).boolean_conf(True)

CIRCUIT_BREAKER_THRESHOLD = conf("spark.rapids.tpu.retry.circuitBreaker.threshold").doc(
    "Device-kernel failures for one op signature that trip its circuit "
    "breaker."
).int_conf(3)


# ── multi-tenant query scheduler (sched/) ──────────────────────────────────

SCHEDULER_ENABLED = conf("spark.rapids.tpu.scheduler.enabled").doc(
    "Gate every query action (collect/toPandas/to_jax) through the "
    "session's multi-tenant scheduler: HBM-aware admission control over a "
    "weighted permit pool, fair-share pools, bounded queueing with typed "
    "QueryQueueFull backpressure. Disabling skips permit gating; "
    "cancellation and deadlines keep working. See docs/scheduler.md."
).boolean_conf(True)

SCHEDULER_PERMITS = conf("spark.rapids.tpu.scheduler.permits").doc(
    "Device capacity units of the admission pool. Each query takes "
    "ceil(estimatedPeakBytes / bytesPerPermit) permits (clamped to the "
    "pool size), so several small queries or one scan-heavy join hold the "
    "device at a time — the query-granular generalization of "
    "spark.rapids.sql.concurrentGpuTasks. Re-read per query."
).int_conf(8)

SCHEDULER_MAX_QUEUED = conf("spark.rapids.tpu.scheduler.maxQueued").doc(
    "Maximum queries waiting for admission across all pools; an admission "
    "past this bound is rejected with the typed QueryQueueFull error — the "
    "backpressure signal a service in front of the engine sheds load on. "
    "Re-read per query."
).int_conf(32)

SCHEDULER_POOL = conf("spark.rapids.tpu.scheduler.pool").doc(
    "Fair-share pool this session's queries are admitted under (Spark FAIR "
    "scheduler pools analogue). Set per-session or flip between queries "
    "with set_conf — the value is read at each query's admission."
).string_conf("default")

SCHEDULER_POOLS = conf("spark.rapids.tpu.scheduler.pools").doc(
    "Pool weight spec 'name:weight,name:weight' (e.g. 'etl:1,interactive:"
    "3'). Under saturation a pool is admitted permit-capacity proportional "
    "to its weight (stride scheduling); FIFO within each pool. Unlisted "
    "pools get weight 1. Re-read per query."
).string_conf(None)

SCHEDULER_QUERY_TIMEOUT_S = conf("spark.rapids.tpu.scheduler.queryTimeout").doc(
    "Per-query deadline in seconds, measured from admission request "
    "(queue wait included). Expiry raises the typed QueryTimeoutError at "
    "the next batch boundary — queued or mid-execution. 0 disables."
).double_conf(0.0)

SCHEDULER_BYTES_PER_PERMIT = conf("spark.rapids.tpu.scheduler.bytesPerPermit").doc(
    "Estimated-footprint bytes one admission permit stands for; a query "
    "needs ceil(estimate / this) permits. Tune so permits × bytesPerPermit "
    "≈ the HBM budget you want admission to protect."
).bytes_conf(256 << 20)

SCHEDULER_DEFAULT_QUERY_BYTES = conf(
    "spark.rapids.tpu.scheduler.defaultQueryBytes"
).doc(
    "Footprint assumed for a query whose plan yields no measurable "
    "estimate (no scans with stats — sched/estimate.py returns 0)."
).bytes_conf(256 << 20)


# ── service survivability: watchdog, shedding, compile deadlines ───────────

WATCHDOG_ENABLED = conf("spark.rapids.tpu.watchdog.enabled").doc(
    "Master switch for the progress watchdog thread (resilience/watchdog."
    "py): scans running queries for missing progress beats and runs the "
    "periodic stale-peer sweep. The thread only exists while stallTimeout "
    "or evictStalePeriod is non-zero."
).boolean_conf(True)

WATCHDOG_STALL_TIMEOUT_S = conf("spark.rapids.tpu.watchdog.stallTimeout").doc(
    "Seconds a RUNNING query may go without a progress beat (batch "
    "boundary, H2D upload, pipeline pull, shuffle fetch, compile "
    "start/end) before the watchdog cancels it with reason "
    "'stall:<site>', feeds the circuit breaker, and releases its permits "
    "through the normal admission exit. Must exceed the longest legit "
    "beat gap — in particular first-touch XLA compiles (set "
    "spark.rapids.tpu.compile.deadlineSeconds below this so a hung "
    "compile is cut first). 0 disables stall detection."
).double_conf(0.0)

WATCHDOG_BEAT_INTERVAL_S = conf("spark.rapids.tpu.watchdog.beatInterval").doc(
    "Watchdog scan period in seconds; a stalled query is cancelled within "
    "stallTimeout + one beat interval. 0 picks stallTimeout/4 clamped to "
    "[0.05, 5]."
).double_conf(0.0)

WATCHDOG_EVICT_STALE_PERIOD_S = conf(
    "spark.rapids.tpu.watchdog.evictStalePeriod"
).doc(
    "Seconds between the watchdog's periodic shuffle-registry "
    "evict_stale sweeps (±20% jitter so many sessions never sweep in "
    "lockstep); dead peers older than spark.rapids.tpu.shuffle."
    "heartbeatMaxAgeSeconds (or 3x this period when that is unset) are "
    "evicted without waiting for an explicit heartbeat. 0 disables the "
    "periodic sweep (eviction then happens only on heartbeat calls)."
).double_conf(0.0)

SCHEDULER_SHED_EXPIRED = conf("spark.rapids.tpu.scheduler.shedExpired").doc(
    "Deadline-aware load shedding: reject a query at admission when its "
    "estimated queue wait plus estimated run time (calibrated from "
    "completed-query timings) already exceeds its deadline — the typed "
    "QueryOverloadedError carries a retry-after hint instead of wasting "
    "device time on a query that cannot finish. Queued queries whose "
    "deadlines expire while waiting are shed by the deadline check "
    "either way."
).boolean_conf(True)

COMPILE_DEADLINE_S = conf("spark.rapids.tpu.compile.deadlineSeconds").doc(
    "Budget in seconds for one first-touch XLA kernel compile "
    "(kernels.GuardedJit). On timeout the compile is abandoned to a "
    "daemon thread and the typed CompileDeadlineError force-opens the "
    "op's circuit breaker — the NEXT planning pass runs that op on CPU "
    "instead of blocking the tenant behind a 6-90s compile wall. "
    "Process-global (the kernel cache is process-global); the last "
    "session to set it wins. 0 disables."
).double_conf(0.0)


# ── persistent XLA executable cache (cache/xla_store.py) ───────────────────

COMPILE_CACHE_ENABLED = conf("spark.rapids.tpu.compileCache.enabled").doc(
    "Crash-safe on-disk XLA executable store (cache/xla_store.py): "
    "kernels.GuardedJit serializes compiled executables keyed by kernel "
    "structural identity + batch geometry + jax/jaxlib/XLA version + "
    "backend fingerprint, and consults the store before compiling — a "
    "restarted server deserializes yesterday's binaries in milliseconds "
    "instead of re-paying 6-90s first-touch compiles per query shape. "
    "Corrupt, truncated, or version-skewed entries degrade to a fresh "
    "compile (quarantine + cache.xla.corrupt), never to a failure. "
    "Process-global; reconfigured on set_conf."
).boolean_conf(True)

COMPILE_CACHE_DIR = conf("spark.rapids.tpu.compileCache.dir").doc(
    "Directory for the executable store. Empty (default) auto-selects "
    "~/.cache/spark_rapids_tpu/xc-<backend> (or "
    "$SPARK_RAPIDS_TPU_COMPILE_CACHE/xc-<backend>). Point every server "
    "of a fleet at ONE shared directory: a per-entry file lock makes the "
    "fleet compile each shape once (docs/operations.md restart runbook)."
).string_conf(None)

COMPILE_CACHE_MAX_BYTES = conf("spark.rapids.tpu.compileCache.maxBytes").doc(
    "Disk budget for the executable store; oldest-use entries (mtime LRU "
    "— loads touch their entry) are evicted past it. 0 = unbounded."
).bytes_conf(2 << 30)

COMPILE_CACHE_LOCK_TIMEOUT_S = conf(
    "spark.rapids.tpu.compileCache.lockTimeout"
).doc(
    "Seconds to wait on another process's per-entry compile lock before "
    "giving up the single-flight dedup and compiling anyway "
    "(cache.xla.lockTimeouts). The flock dies with its holder, so a "
    "CRASHED peer never blocks past its own death; this bounds a WEDGED "
    "one. Size it above your slowest expected compile."
).double_conf(120.0)


# ── network serving front-end (serve/) ─────────────────────────────────────

SERVE_HOST = conf("spark.rapids.tpu.serve.host").doc(
    "Interface the Arrow-IPC SQL endpoint binds (serve/server.py). The "
    "default stays loopback-only; bind 0.0.0.0 explicitly to expose the "
    "service."
).string_conf("127.0.0.1")

SERVE_PORT = conf("spark.rapids.tpu.serve.port").doc(
    "TCP port for the serving endpoint; 0 picks an ephemeral port "
    "(reported by TpuServer.start(), the test/bench mode)."
).int_conf(8045)

SERVE_TENANTS = conf("spark.rapids.tpu.serve.tenants").doc(
    "Auth spec 'token:tenant:pool,…' mapping each HELLO auth token to a "
    "tenant name and the fair-share scheduler pool its queries are "
    "admitted under (spark.rapids.tpu.scheduler.pools weights apply). "
    "Empty = open access: every client is tenant 'anonymous' in pool "
    "'default'. When set, a HELLO with an unknown token is rejected."
).string_conf(None)

SERVE_MAX_CONNECTIONS = conf("spark.rapids.tpu.serve.maxConnections").doc(
    "Concurrent client connections the server accepts; further connects "
    "are refused at HELLO with a typed error (admission-queue backpressure "
    "for queries is the scheduler's maxQueued, this bounds sockets/threads)."
).int_conf(64)

SERVE_STREAM_BATCH_ROWS = conf("spark.rapids.tpu.serve.streamBatchRows").doc(
    "Maximum rows per streamed result BATCH frame: engine result batches "
    "are re-chunked to this bound so clients see incremental frames (and "
    "mid-stream CANCEL has boundaries to act on) even when a partition "
    "produced one huge batch."
).int_conf(65536)

SERVE_MAX_CONNECTIONS_PER_TENANT = conf(
    "spark.rapids.tpu.serve.maxConnectionsPerTenant"
).doc(
    "Concurrent connections one tenant may hold; further connects from "
    "that tenant are refused at HELLO with a typed error so one tenant "
    "cannot wedge the accept loop for everyone (the global bound is "
    "spark.rapids.tpu.serve.maxConnections). 0 = unlimited."
).int_conf(0)

SERVE_MAX_INFLIGHT_PER_TENANT = conf(
    "spark.rapids.tpu.serve.maxInflightPerTenant"
).doc(
    "Concurrent in-flight (fetching) queries one tenant may run; a FETCH "
    "past the bound answers a typed OVERLOADED error with a retry-after "
    "hint while the connection stays alive. 0 = unlimited."
).int_conf(0)

SERVE_DRAIN_TIMEOUT_S = conf("spark.rapids.tpu.serve.drainTimeout").doc(
    "Seconds server.drain() (and the SIGTERM handler) waits for in-flight "
    "streams to finish before cancelling them with reason 'shutdown'. "
    "Every stream still ends with a typed END or ERROR frame; new "
    "commands during the drain answer a typed ServerDraining error."
).double_conf(30.0)

SERVE_SEND_TIMEOUT_S = conf("spark.rapids.tpu.serve.sendTimeout").doc(
    "Socket send timeout per result frame: a client that stops draining "
    "its socket (slow-loris reads) is treated as disconnected after this "
    "many seconds — its query cancels and the worker thread frees — "
    "instead of pinning a permit on a zero-window send forever. 0 "
    "disables."
).double_conf(60.0)

SERVE_HELLO_TIMEOUT_S = conf("spark.rapids.tpu.serve.helloTimeout").doc(
    "Seconds a fresh connection gets to complete its HELLO before being "
    "dropped (slow-loris connects hold a handler thread, never the "
    "accept loop)."
).double_conf(10.0)

SERVE_WARMUP_STATEMENTS = conf("spark.rapids.tpu.serve.warmupStatements").doc(
    "Semicolon-separated SQL statements the server plans+precompiles in "
    "the background after start(); STATUS reports ready=false until the "
    "warm pool is primed, so a rolling restart can wait for readiness "
    "before shifting traffic. Empty = ready immediately."
).string_conf(None)

SERVE_READY_TIMEOUT_S = conf("spark.rapids.tpu.serve.readyTimeout").doc(
    "Readiness budget the server ADVERTISES to clients (HELLO_OK and "
    "STATUS carry it): Connection.wait_ready() with no explicit timeout "
    "polls this long before giving up. Size it above the server's worst "
    "cold warmup (one q8-class XLA compile is ~90s); warm restarts "
    "against a populated compile cache finish in seconds regardless. "
    "STATUS reports per-warmup-statement progress so a caller can "
    "distinguish 'still compiling' from 'hung'."
).double_conf(600.0)

SERVE_PREPARED_CACHE_ENTRIES = conf(
    "spark.rapids.tpu.serve.preparedCacheEntries"
).doc(
    "Bound of the prepared-plan cache (serve/prepared.py): compiled "
    "physical plans keyed by canonicalized statement + bound parameters + "
    "batch geometry, LRU-evicted past this many entries. A hit skips "
    "parse/plan/compile entirely — the repeated-dashboard fast path."
).int_conf(128)


# ── deterministic fault injection (resilience/faults.py) ───────────────────

FAULTS_ENABLED = conf("spark.rapids.tpu.faults.enabled").doc(
    "Master switch for the deterministic fault-injection harness; all "
    "spark.rapids.tpu.faults.* points are inert unless enabled. Drives "
    "the chaos test suite — never enable in production."
).boolean_conf(False)

FAULTS_SEED = conf("spark.rapids.tpu.faults.seed").doc(
    "Seed for the injection jitter RNG, so a chaos run replays "
    "identically."
).int_conf(0)

FAULTS_DEVICE_OOM_EVERY_N = conf("spark.rapids.tpu.faults.deviceOomEveryN").doc(
    "Raise a synthetic RESOURCE_EXHAUSTED on every Nth compiled-kernel "
    "launch under an OOM-recovery scope (kernels.GuardedJit inside "
    "with_oom_retry / the retry state machine) — each injection "
    "deterministically exercises the spill/split recovery; 0 disables."
).int_conf(0)

FAULTS_OOM_ABOVE_BYTES = conf("spark.rapids.tpu.faults.oomAboveBytes").doc(
    "Raise a synthetic RESOURCE_EXHAUSTED whenever a splittable operator "
    "launches a batch larger than this many bytes — the deterministic "
    "driver for demonstrating recursive split-and-retry; 0 disables."
).bytes_conf(0)

FAULTS_KERNEL_ERROR_EVERY_N = conf("spark.rapids.tpu.faults.kernelErrorEveryN").doc(
    "Raise a synthetic non-OOM XLA error on every Nth splittable-operator "
    "launch (drives the circuit breaker); 0 disables."
).int_conf(0)

FAULTS_COMPILE_FAIL_EVERY_N = conf("spark.rapids.tpu.faults.compileFailEveryN").doc(
    "Fail every Nth first-touch kernel compile with a transient error "
    "(exercises the compile retry path); 0 disables."
).int_conf(0)

FAULTS_SPILL_WRITE_ERROR_EVERY_N = conf(
    "spark.rapids.tpu.faults.spill.writeErrorEveryN"
).doc(
    "Fail every Nth disk-tier spill write with an IO error (the buffer "
    "stays at the host tier); 0 disables."
).int_conf(0)

FAULTS_SPILL_READ_ERROR_EVERY_N = conf(
    "spark.rapids.tpu.faults.spill.readErrorEveryN"
).doc(
    "Fail every Nth disk-tier re-materialization read with an IO error "
    "(surfaces as a catalog SpillError naming the buffer); 0 disables."
).int_conf(0)

FAULTS_TCP_DROP_EVERY_N = conf("spark.rapids.tpu.faults.transport.dropEveryN").doc(
    "Silently drop every Nth outgoing shuffle DATA frame on the TCP "
    "transport (the fetch times out and retries); 0 disables."
).int_conf(0)

FAULTS_TCP_DELAY_EVERY_N = conf("spark.rapids.tpu.faults.transport.delayEveryN").doc(
    "Delay every Nth outgoing shuffle DATA frame by "
    "spark.rapids.tpu.faults.transport.delayMs; 0 disables."
).int_conf(0)

FAULTS_TCP_DELAY_MS = conf("spark.rapids.tpu.faults.transport.delayMs").doc(
    "Injected per-frame delay for the transport delay point."
).double_conf(50.0)

FAULTS_TCP_CORRUPT_EVERY_N = conf(
    "spark.rapids.tpu.faults.transport.corruptEveryN"
).doc(
    "Flip one payload byte in every Nth outgoing shuffle DATA frame "
    "AFTER its checksum is stamped (the receiver's CRC check drops the "
    "frame and the fetch retry recovers); 0 disables."
).int_conf(0)

FAULTS_KERNEL_STALL_EVERY_N = conf(
    "spark.rapids.tpu.faults.kernelStallEveryN"
).doc(
    "Stall every Nth compiled-kernel launch for kernelStallMs before "
    "running it (a wedged-device simulation — no error is raised; the "
    "progress watchdog is what must notice); 0 disables."
).int_conf(0)

FAULTS_KERNEL_STALL_MS = conf("spark.rapids.tpu.faults.kernelStallMs").doc(
    "Injected stall duration for the kernel-stall point."
).double_conf(500.0)

FAULTS_COMPILE_DELAY_EVERY_N = conf(
    "spark.rapids.tpu.faults.compileDelayEveryN"
).doc(
    "Delay every Nth first-touch kernel compile by compileDelayMs "
    "(inside the compile-deadline scope, so "
    "spark.rapids.tpu.compile.deadlineSeconds can cut it); 0 disables."
).int_conf(0)

FAULTS_COMPILE_DELAY_MS = conf("spark.rapids.tpu.faults.compileDelayMs").doc(
    "Injected delay for the compile-delay point."
).double_conf(500.0)

FAULTS_CACHE_TRUNCATE_EVERY_N = conf(
    "spark.rapids.tpu.faults.compileCache.truncateEveryN"
).doc(
    "Truncate every Nth compile-cache entry to half its size right after "
    "it is published (a torn write that survived the rename) — the load "
    "path must quarantine it and rebuild; 0 disables."
).int_conf(0)

FAULTS_CACHE_CORRUPT_EVERY_N = conf(
    "spark.rapids.tpu.faults.compileCache.corruptEveryN"
).doc(
    "Flip one payload byte in every Nth published compile-cache entry "
    "AFTER its CRC is stamped — the payload CRC on load must catch it "
    "(quarantine + cache.xla.corrupt, fresh compile); 0 disables."
).int_conf(0)

FAULTS_CACHE_STALE_VERSION_EVERY_N = conf(
    "spark.rapids.tpu.faults.compileCache.staleVersionEveryN"
).doc(
    "Write every Nth compile-cache entry with a perturbed engine schema "
    "revision in its header — the version fence must turn it into a "
    "SILENT miss (no load attempt, no quarantine); 0 disables."
).int_conf(0)

FAULTS_CACHE_CRASH_BEFORE_RENAME_EVERY_N = conf(
    "spark.rapids.tpu.faults.compileCache.crashBeforeRenameEveryN"
).doc(
    "Abandon every Nth compile-cache publish between its temp-file fsync "
    "and the rename (a crash at the worst moment of the atomic-write "
    "protocol) — the orphan must never serve a load and a later boot "
    "sweeps it; 0 disables."
).int_conf(0)

FAULTS_CACHE_LOCK_HOLDER_EVERY_N = conf(
    "spark.rapids.tpu.faults.compileCache.lockHolderEveryN"
).doc(
    "On every Nth compile-cache single-flight acquisition, a simulated "
    "wedged peer grabs the entry's flock first and holds it for "
    "lockHolderHoldMs — past compileCache.lockTimeout the caller must "
    "compile without the dedup instead of hanging; 0 disables."
).int_conf(0)

FAULTS_CACHE_LOCK_HOLDER_HOLD_MS = conf(
    "spark.rapids.tpu.faults.compileCache.lockHolderHoldMs"
).doc(
    "How long the simulated wedged lock holder keeps the entry flock."
).double_conf(500.0)

FAULTS_MAP_OUTPUT_LOSS_EVERY_N = conf(
    "spark.rapids.tpu.faults.shuffle.mapOutputLossEveryN"
).doc(
    "On every Nth managed shuffle-read, drop the shuffle's registered map "
    "outputs AND its catalog-held blocks before the read — the lost-"
    "executor simulation. The lineage recovery layer must rebuild the map "
    "stage from its partition thunks instead of failing the query "
    "(spark.rapids.tpu.recovery.recomputeMapOutputs); 0 disables."
).int_conf(0)

FAULTS_STALL_PARTITION = conf("spark.rapids.tpu.faults.stallPartition").doc(
    "Stall the FIRST attempt of this partition id for stallPartitionSeconds "
    "at task start — the deterministic straggler the speculation layer must "
    "overtake (re-attempts and speculative duplicates never stall, so the "
    "duplicate wins and the stalled loser is cancelled); -1 disables."
).int_conf(-1)

FAULTS_STALL_PARTITION_S = conf(
    "spark.rapids.tpu.faults.stallPartitionSeconds"
).doc(
    "Injected stall duration for the straggler point. The sleep beats the "
    "attempt's cancel token, so a cancelled loser exits within ~20ms."
).double_conf(2.0)


# ── lineage-based partition recovery (resilience/lineage.py) ───────────────

RECOVERY_RECOMPUTE_ENABLED = conf(
    "spark.rapids.tpu.recovery.recomputeMapOutputs"
).doc(
    "Rebuild lost shuffle map outputs from lineage instead of failing the "
    "query: when a managed shuffle read hits an exhausted fetch budget, a "
    "blacklisted peer, or finds its committed map outputs gone (lost "
    "executor), the exchange marks the shuffle released and the partition "
    "task's re-attempt re-runs the map stage under the next generation's "
    "shuffle id. Counted in shuffle.recomputedPartitions."
).boolean_conf(True)

RECOVERY_MAX_MAP_RECOMPUTES = conf(
    "spark.rapids.tpu.recovery.maxMapRecomputes"
).doc(
    "How many map-stage regenerations one exchange may perform per query "
    "before a shuffle-read failure is allowed to propagate (a persistently "
    "failing peer must not recompute forever; spark.task.maxFailures "
    "bounds the per-partition attempts on top)."
).int_conf(3)


# ── straggler speculation (sched/speculation.py) ───────────────────────────

SPECULATION_ENABLED = conf("spark.rapids.tpu.speculation.enabled").doc(
    "Launch a speculative duplicate attempt for partitions that run far "
    "past the measured baseline (spark.speculation analogue). The monitor "
    "watches per-partition runtimes once speculation.quantile of the "
    "query's partitions completed; first commit wins, the loser is "
    "cancelled through its attempt token, and the duplicate's device "
    "share is accounted as one extra scheduler permit (skipped when none "
    "is free). Applies to multi-partition parallel collect()s."
).boolean_conf(False)

SPECULATION_QUANTILE = conf("spark.rapids.tpu.speculation.quantile").doc(
    "Fraction of the query's partitions that must have completed before "
    "stragglers are considered (the baseline sample; "
    "spark.speculation.quantile)."
).double_conf(0.75)

SPECULATION_MULTIPLIER = conf("spark.rapids.tpu.speculation.multiplier").doc(
    "A running partition is speculatable once its elapsed time exceeds "
    "this multiple of the completed partitions' median runtime "
    "(spark.speculation.multiplier)."
).double_conf(1.5)

SPECULATION_MIN_RUNTIME_S = conf(
    "spark.rapids.tpu.speculation.minRuntime"
).doc(
    "Floor (seconds) under the speculation threshold: partitions faster "
    "than this are never speculated regardless of the multiplier — "
    "duplicating sub-100ms tasks only burns permits."
).double_conf(0.25)

SPECULATION_INTERVAL_S = conf("spark.rapids.tpu.speculation.interval").doc(
    "How often (seconds) the speculation monitor scans running partitions "
    "against the baseline (spark.speculation.interval)."
).double_conf(0.05)


# ── serve-fleet failover (serve/client.py dedup bookkeeping) ───────────────

SERVE_FAILOVER_DEDUP_WINDOW = conf(
    "spark.rapids.tpu.serve.failover.dedupWindow"
).doc(
    "How many client-generated dedup keys the server remembers (LRU). A "
    "failover replay arriving with a key this server has already executed "
    "counts serve.dedupReplays and is annotated in the query log — the "
    "at-most-once bookkeeping behind mid-stream client failover."
).int_conf(1024)


# ── common-work sharing (cache/results.py, cache/subplan.py) ───────────────

RESULT_CACHE_ENABLED = conf("spark.rapids.tpu.resultCache.enabled").doc(
    "Serve repeated queries from the bounded semantic result cache: a "
    "completed query's Arrow batches are stored under (plan canonical "
    "key, bound params, conf fingerprint, per-table data version) and an "
    "identical later query streams them back WITHOUT touching scheduler "
    "admission. Invalidation is table-granular — any write path (temp-"
    "view replacement, DataFrameWriter append/overwrite, view drop) "
    "bumps the written table's version and evicts its dependents. Off by "
    "default (kill switch): results are bit-identical by construction, "
    "but a cache hit skips execution-side effects some harnesses assert "
    "on (kernel first-call counters, retry metrics)."
).boolean_conf(False)

RESULT_CACHE_MAX_BYTES = conf("spark.rapids.tpu.resultCache.maxBytes").doc(
    "In-memory budget of the result cache; the same figure again bounds "
    "its disk tier (LRU entries demote to Arrow IPC files in the spill "
    "directory before being dropped). Memory-resident bytes are reserved "
    "against the host spill budget (mem/spill.py), so cached results "
    "compete with spilled device buffers instead of hiding from the "
    "memory ledger."
).bytes_conf(256 * 1024 * 1024)

RESULT_CACHE_MAX_ENTRIES = conf(
    "spark.rapids.tpu.resultCache.maxEntries"
).doc(
    "Entry-count bound of the result cache across both tiers (LRU). "
    "Bounds key-map growth for fleets cycling many distinct small "
    "queries under the byte budget."
).int_conf(256)

SUBPLAN_DEDUP_ENABLED = conf("spark.rapids.tpu.subplanDedup.enabled").doc(
    "Single-flight execution of common subtrees across CONCURRENT "
    "in-flight queries: at admission each plan is scanned for subtrees "
    "sharing a canonical key with another in-flight query's, and the "
    "subtree is computed once — the first executor owns it, the rest "
    "consume its materialized batches. Owner failure or cancellation "
    "wakes waiters into independent execution (never cascades). Off by "
    "default (kill switch); entries are concurrent-only and never "
    "outlive the queries pinning them."
).boolean_conf(False)

SUBPLAN_DEDUP_MIN_COST_NS = conf(
    "spark.rapids.tpu.subplanDedup.minCostNs"
).doc(
    "Estimated device cost (nanoseconds, from the calibration table via "
    "sched/estimate.py::estimate_plan_cost_ns) below which a subtree is "
    "not worth sharing — waiter coordination overhead beats recompute "
    "for point lookups."
).int_conf(1_000_000)


# ── live analytics (live/ingest.py, live/maintain.py, serve SUBSCRIBE) ─────

LIVE_ENABLED = conf("spark.rapids.tpu.live.enabled").doc(
    "Master kill switch for the live-analytics subsystem: streaming "
    "append ingestion with a per-table delta log, incremental view "
    "maintenance (pass-through / aggregate / top-N classes, full "
    "re-execution fallback with an explain reason otherwise), and the "
    "serve-side SUBSCRIBE/UPDATE delta-streaming protocol. Off by "
    "default: SUBSCRIBE frames are rejected and session.live raises "
    "until it is set."
).boolean_conf(False)

LIVE_POOL = conf("spark.rapids.tpu.live.pool").doc(
    "Scheduler pool refresh re-executions are admitted under (created at "
    "weight 1 if absent from spark.rapids.tpu.scheduler.pools). A "
    "dedicated pool keeps a dashboard fleet's refresh storm from "
    "starving ad-hoc interactive queries — size it explicitly in the "
    "pools spec when refreshes dominate."
).string_conf("live")

LIVE_DELTA_LOG_MAX_ENTRIES = conf(
    "spark.rapids.tpu.live.deltaLog.maxEntries"
).doc(
    "Per-table bound on retained delta-log entries. A consumer whose "
    "last-seen version has been truncated past detects the gap and "
    "falls back to a full re-execution for that refresh (correct, just "
    "not incremental), so small bounds trade memory for fallbacks."
).int_conf(256)

LIVE_STATE_MAX_BYTES = conf("spark.rapids.tpu.live.state.maxBytes").doc(
    "Host-memory budget for maintained query state (aggregate partials, "
    "top-N candidate sets, accumulated pass-through output), reserved "
    "against the spill catalog's host budget. On reserve failure state "
    "demotes to Arrow IPC files in the spill directory through the "
    "fault-injected spill IO points and is promoted back on next use."
).bytes_conf(128 * 1024 * 1024)

LIVE_SUBSCRIBER_MAX_PENDING = conf(
    "spark.rapids.tpu.live.subscriber.maxPending"
).doc(
    "Per-subscription bound on queued-but-unsent UPDATE epochs for a "
    "slow consumer. On overflow the pending deltas collapse into one "
    "full snapshot at the latest version — the subscriber sees every "
    "version's effect, not every version."
).int_conf(8)


class TpuConf:
    """An immutable-ish view over a key→string dict, with typed access.

    Mirrors ``RapidsConf``'s construction from the Spark conf; here it is
    constructed from a plain dict plus ``SPARK_RAPIDS_*``-style environment
    overrides.
    """

    def __init__(self, settings: Optional[dict[str, Any]] = None):
        self._settings: dict[str, str] = {}
        for k, v in (settings or {}).items():
            self._settings[k] = str(v) if not isinstance(v, bool) else str(v).lower()

    def get(self, key: str, default: T, conv: Callable[[str], T]) -> T:
        raw = self._settings.get(key)
        if raw is None:
            raw = os.environ.get("SRT_CONF_" + key.replace(".", "_").upper())
        if raw is None:
            return default
        return conv(raw)

    def get_raw(self, key: str) -> Optional[str]:
        return self._settings.get(key)

    def set(self, key: str, value: Any) -> "TpuConf":
        new = dict(self._settings)
        new[key] = str(value) if not isinstance(value, bool) else str(value).lower()
        return TpuConf(new)

    def is_enabled(self, entry: ConfEntry[bool]) -> bool:
        return entry.get(self)

    # Rule kill switches (auto-derived keys): default True unless set.
    def rule_enabled(self, conf_key: str, default: bool = True) -> bool:
        raw = self._settings.get(conf_key)
        if raw is None:
            return default
        return raw.strip().lower() in ("true", "1")

    def items(self):
        return self._settings.items()


def registry() -> dict[str, ConfEntry]:
    return dict(_REGISTRY)


def startup_only_keys() -> set:
    """Keys frozen when the session is constructed (topology, backend,
    shims). THE single source of truth for conf scope: docs_gen renders
    configs.md's Scope column from it, and graft-lint's conf-key pass
    flags any re-read of one of these outside the session-init surface
    (docs/static-analysis.md)."""
    return {k for k, e in _REGISTRY.items() if e.startup_only}


def generate_docs() -> str:
    """Markdown doc table — the analogue of RapidsConf.scala's doc generator
    (:1052-1149), so configuration docs cannot drift from the code."""
    lines = [
        "# Configuration",
        "",
        "Name | Description | Default",
        "-----|-------------|--------",
    ]
    for key in sorted(_REGISTRY):
        e = _REGISTRY[key]
        if e.internal:
            continue
        lines.append(f"{e.key} | {e.doc} | {e.default}")
    return "\n".join(lines) + "\n"
