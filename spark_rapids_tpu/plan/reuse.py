"""Exchange/subplan reuse via plan canonicalization.

Reference: GpuExec.doCanonicalize + Spark's ReuseExchange rule
(GpuExec.scala:251-276): TPC-DS-style plans repeat whole subtrees
(self-joins of an aggregate, CTE fan-out); without reuse every consumer
re-executes the exchange's entire input pipeline.

Design: a post-override pass walks the physical plan bottom-up, builds a
*structural key* for every exchange subtree (class + parameters + child
keys; expressions compare by their frozen-dataclass equality), and replaces
later duplicates with the FIRST instance — physically sharing the Exec
node. The shared exchange memoizes its ``execute()`` PartitionSet per
ExecContext (see TpuShuffleExchangeExec/TpuBroadcastExchangeExec), so the
partition buckets materialize once regardless of consumer count.

False negatives are safe (duplicate work, correct results), false
positives are not — any parameter this walk cannot prove comparable makes
the subtree non-reusable.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import pyarrow as pa

from ..expr.base import Expression
from ..types import Schema
from .physical import Exec


class _NotCanonical(Exception):
    pass


# Underscore attributes are derived/private state (compiled kernels, locks,
# caches, schemas recomputed from public params) — never part of identity.
_SKIP_ATTRS = {"metrics"}


def _val_key(v):
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return v
    if isinstance(v, Expression):
        return v  # frozen dataclasses: semantic __eq__
    if isinstance(v, (list, tuple)):
        return tuple(_val_key(x) for x in v)
    if isinstance(v, Schema):
        return tuple((f.name, f.data_type, f.nullable) for f in v)
    if isinstance(v, (pa.Table, pa.RecordBatch)):
        return ("table", id(v))  # identity: same in-memory source only
    if isinstance(v, type):
        return v
    if isinstance(v, dict):  # option maps (CpuFileScanExec.options)
        try:
            return ("dict", tuple((k, _val_key(x)) for k, x in sorted(v.items())))
        except TypeError:  # unsortable keys
            raise _NotCanonical("dict") from None
    # dataclass-ish parameter objects (SortOrder, partitionings): compare
    # by type + public attribute dict, recursively
    d = getattr(v, "__dict__", None)
    if d is not None:
        return (
            type(v),
            tuple((k, _val_key(x)) for k, x in sorted(d.items())
                  if not k.startswith("_")),
        )
    slots = getattr(type(v), "__slots__", None)
    if slots is not None:  # slotted value objects (CoalesceGoal)
        return (
            type(v),
            tuple((k, _val_key(getattr(v, k))) for k in slots
                  if not k.startswith("_")),
        )
    raise _NotCanonical(type(v).__name__)


def canonical_key(node: Exec):
    """Structural identity of an Exec subtree; raises _NotCanonical when any
    parameter resists comparison."""
    from ..exec.cpu import CpuScanExec

    if isinstance(node, CpuScanExec):
        # column pruning hands each consumer its own pruned pa.Table slice;
        # identity lives in the un-pruned source + the projected columns
        return (
            CpuScanExec,
            ("src", id(node.source)),
            tuple(node.table.column_names),
            node.num_partitions,
        )
    params = tuple(
        (k, _val_key(v))
        for k, v in sorted(vars(node).items())
        if k not in _SKIP_ATTRS and not k.startswith("_")
    )
    return (type(node), params, tuple(canonical_key(c) for c in node.children))


def _keys_equal(a, b) -> bool:
    try:
        return bool(a == b)
    except Exception:  # noqa: BLE001 - array-valued literal etc.
        return False


def reuse_exchanges(plan: Exec) -> Tuple[Exec, int]:
    """Replace duplicate exchange subtrees with the first instance. Returns
    (new plan, number of reused nodes). Spark's spark.sql.exchange.reuse."""
    from ..exec.tpu import TpuShuffleExchangeExec
    from ..exec.tpu_join import TpuBroadcastExchangeExec

    rebuilt: dict = {}  # id(old node) -> new node (ancestors of a dedupe)
    reused = 0
    # One `seen` scope per broadcast-build boundary: a shuffle exchange
    # executes differently inside a broadcast build (whole, in-process)
    # than outside (managed / rank-split), and its memoized PartitionSet
    # captures that decision — sharing one node across the boundary would
    # leak a rank-split set into a broadcast (partial build table) or an
    # unsplit set into a regular consumer (duplicated rows).
    scopes: List[List[Tuple[object, Exec]]] = [[]]

    def walk(node: Exec) -> Exec:
        nonlocal reused
        old = node
        is_bcast = isinstance(node, TpuBroadcastExchangeExec)
        if is_bcast:
            scopes.append([])
        new_children = [walk(c) for c in node.children]
        if is_bcast:
            scopes.pop()
        if any(nc is not oc for nc, oc in zip(new_children, node.children)):
            node = node.with_new_children(new_children)
            rebuilt[id(old)] = node
        if isinstance(node, (TpuShuffleExchangeExec, TpuBroadcastExchangeExec)):
            try:
                k = canonical_key(node)
            except _NotCanonical:
                return node
            seen = scopes[-1]
            for k2, hit in seen:
                if _keys_equal(k, k2):
                    hit._reuse_shared = True
                    # AQE grouping is pairwise between a join's two feeding
                    # exchanges; a node shared by several consumers cannot
                    # follow one join's assignment without desyncing the
                    # other, so the shared node reverts to identity
                    # partitions (its peers fall back the same way).
                    hit._aqe_disabled = True
                    reused += 1
                    return hit
            seen.append((k, node))
        return node

    out = walk(plan)
    if rebuilt:
        # AQE peer links are identity-based (ctx.aqe_size_providers keyed on
        # id); a rebuilt exchange must point at its peer's REBUILT instance
        # or the join's two sides would compute different groupings.
        def relink(node: Exec, visited: set):
            if id(node) in visited:
                return
            visited.add(id(node))
            peer = getattr(node, "_aqe_peer", None)
            if peer is not None and id(peer) in rebuilt:
                node._aqe_peer = rebuilt[id(peer)]
            for c in node.children:
                relink(c, visited)

        relink(out, set())
    return out, reused
