"""Output partitionings for exchanges — the four ``GpuPartitioning`` impls.

Reference: GpuHashPartitioning.scala (:49-76 murmur3 pmod bucketing on
device), GpuRangePartitioning.scala + GpuRangePartitioner.scala +
SamplingUtils.scala (sample rows → CPU-computed bounds → device bucketing),
GpuRoundRobinPartitioning.scala, GpuSinglePartitioning.scala.

TPU-first range design: rows and sampled bound rows are both encoded to the
framework's order-preserving uint64 *radix words* (ops/sortkeys.py); a row's
partition id is the count of bounds lexicographically below it — one fused
compare kernel on device, no per-type comparators. Bounds are picked on the
host from an evenly-strided sample of encoded words (the reservoir-sample
analogue; bounds only shape balance, never results).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from ..expr import Expression
from .logical import SortOrder


@dataclasses.dataclass
class Partitioning:
    num_partitions: int

    def exprs(self) -> List[Expression]:
        return []


@dataclasses.dataclass
class SinglePartitioning(Partitioning):
    num_partitions: int = 1


@dataclasses.dataclass
class HashPartitioning(Partitioning):
    keys: List[Expression] = dataclasses.field(default_factory=list)

    def exprs(self) -> List[Expression]:
        return list(self.keys)


@dataclasses.dataclass
class RoundRobinPartitioning(Partitioning):
    pass


@dataclasses.dataclass
class RangePartitioning(Partitioning):
    order: List[SortOrder] = dataclasses.field(default_factory=list)

    def exprs(self) -> List[Expression]:
        return [o.child for o in self.order]


SAMPLE_PER_BATCH = 128  # rows sampled per input batch for range bounds


def align_word_groups(per_batch_groups, orders, xp):
    """Align per-batch radix-word group lists to a common word count.

    String columns encode to a *variable* number of char words (width is
    bucketed per batch), so two batches of the same column can produce word
    lists of different lengths. A narrower batch's missing char words are
    exactly the zero words the wider padding would have produced (all-ones
    under descending, where value words are complemented), so alignment pads
    with that constant *before* the trailing length word.

    ``per_batch_groups``: list over batches of per-order-column word lists.
    Returns ``(aligned, targets)``: a list over batches of flat, aligned
    word lists, plus the per-column word counts everything was padded to
    (cross-rank gathers re-pad against these — keep the two in lockstep).
    """
    ncols = len(orders)
    if not per_batch_groups:
        return [], [0] * ncols
    targets = [
        max(len(g[ci]) for g in per_batch_groups) for ci in range(ncols)
    ]
    out = []
    for groups in per_batch_groups:
        flat = [w for ci in range(ncols) for w in groups[ci]]
        locals_ = [len(groups[ci]) for ci in range(ncols)]
        out.append(pad_flat_words(flat, locals_, targets, orders, xp))
    return out, targets


def pad_flat_words(flat_words, local_targets, global_targets, orders, xp):
    """Re-pad a flat aligned word list from per-column ``local_targets`` word
    counts up to ``global_targets`` (the cross-rank maxima). Same padding rule
    as :func:`align_word_groups`: a narrower string column's missing char
    words are inserted *before* its trailing length word, as zeros (all-ones
    under descending order, where value words are complemented)."""
    pos, out = 0, []
    for ci, o in enumerate(orders):
        g = list(flat_words[pos : pos + local_targets[ci]])
        pos += local_targets[ci]
        missing = global_targets[ci] - local_targets[ci]
        if missing:
            zero = xp.zeros_like(g[0])
            pad = zero if o.ascending else ~zero
            g = g[:-1] + [pad] * missing + [g[-1]]
        out.extend(g)
    return out


def merge_sampled_word_groups(contribs, orders):
    """Merge per-rank sampled radix-word contributions into one flat sample.

    Multi-process range exchanges must agree on ONE set of range bounds —
    per-rank bounds would route the same key range to different reduce
    partitions on different ranks (globally wrong sort). Each rank samples
    its own rows, publishes ``{"targets": [words-per-column], "words":
    [[int,...] per flat word]}`` through the driver service, and every rank
    runs this same deterministic merge over the gathered contributions
    (GpuRangePartitioner computes bounds once on the Spark driver; here the
    merge is replicated instead, driver service only gathers).

    Returns ``(sample_words, global_targets)`` — flat uint64 arrays ready
    for :func:`compute_range_bounds` — or ``(None, None)`` when no rank
    contributed rows.
    """
    # a rank with no input batches posts targets=[0,...], words=[] — it
    # contributes nothing and must not reach pad_flat_words (g[0] on [])
    live = [c for c in contribs if c and c.get("targets") and c.get("words")]
    if not live:
        return None, None
    ncols = len(orders)
    gtargets = [max(c["targets"][ci] for c in live) for ci in range(ncols)]
    merged: List[List[np.ndarray]] = [[] for _ in range(sum(gtargets))]
    for c in live:
        flat = [np.asarray(w, dtype=np.uint64) for w in c["words"]]
        padded = pad_flat_words(flat, c["targets"], gtargets, orders, np)
        for i, w in enumerate(padded):
            merged[i].append(w)
    sample_words = [np.concatenate(ws) for ws in merged]
    if sample_words[0].size == 0:
        return None, None
    return sample_words, gtargets


def compute_range_bounds(
    sample_words: List[np.ndarray], num_partitions: int
) -> Optional[List[np.ndarray]]:
    """Sampled radix words → P-1 bound rows (as word vectors), picked at even
    quantiles of the lexicographically-sorted sample (GpuRangePartitioner
    createRangeBounds analogue). Returns None when the sample is empty."""
    if not sample_words or sample_words[0].size == 0:
        return None
    k = sample_words[0].shape[0]
    order = np.lexsort(tuple(reversed(sample_words)))
    idx = np.minimum((np.arange(1, num_partitions) * k) // num_partitions, k - 1)
    return [w[order][idx] for w in sample_words]


def words_partition_ids(xp, words, bounds, int32_dtype=None):
    """pid[i] = #bounds lexicographically < row i's words (row == bound goes
    left). ``words``: per-row word arrays [cap]; ``bounds``: same-length list
    of [P-1] arrays. Works for numpy and jax.numpy."""
    i32 = int32_dtype or xp.int32
    cap = words[0].shape[0]
    nb = bounds[0].shape[0]
    if nb == 0:
        return xp.zeros(cap, dtype=i32)
    gt = xp.zeros((cap, nb), dtype=bool)
    eq = xp.ones((cap, nb), dtype=bool)
    for w, bw in zip(words, bounds):
        wv = w[:, None]
        bv = bw[None, :]
        gt = gt | (eq & (wv > bv))
        eq = eq & (wv == bv)
    return gt.sum(axis=1).astype(i32)
